"""Continuous scheduler over the paged engine.

Policy (Orca-style continuous batching with chunked prefill):

- **FIFO admission, batched**: each ``step()`` admits up to
  ``admit_per_step`` queued requests — strictly in submit order, stopping
  at the first that cannot get a slot or a block chain (no head-of-line
  skipping: deterministic, starvation-free). All admitted-and-unfinished
  prompts advance by ONE chunk per step through a single compiled chunk
  program (``PagedEngine.run_chunks``), so a long prompt never stalls the
  decode lanes — it interleaves, chunk by chunk, with everyone else's
  decode ticks.
- **decode**: every fully-prefilled slot with budget advances one token
  per step; EOS (when configured) retires a slot early. Retirement frees
  the block chain immediately — the freed blocks are the next
  admission's allocation (LIFO).
- **OOM queues**: a request that cannot be served *now* (no free slot, or
  the pool cannot supply its chain) simply stays queued. ``submit``
  never raises for capacity reasons — only for requests that could never
  fit (``> max_seq_len``).

Metrics are exact host-side counters, no device sync beyond the token
fetch the caller already pays: slot occupancy, block-pool occupancy,
padding-waste fraction (allocated-but-unwritten block capacity),
admission latency (steps and wall seconds from submit to admission),
queue depth, and tokens/s — plus, from round 7 (ISSUE 4), the latency
percentiles a continuous batcher exists to control: TTFT (submit →
first materialized token), per-output-token latency (inter-token gap),
and queue wait (submit → admit), all exact host-side series from
timestamps the scheduler already holds (``telemetry.LatencySeries``).
Pass ``metrics_log`` (a ``MetricsLogger``) to stream one ``kind=
"request"`` JSONL record per retirement — the raw material
``scripts/telemetry_report.py`` computes percentiles from — and
``tracer`` (a ``telemetry.SpanTracer``) for admission / prefill_chunk /
decode_tick spans.

Fleet integration (round 10; ``fleet/``, ANALYSIS.md "Serving fleet"):
one Scheduler is one *replica*. ``replica_id`` stamps every JSONL
record; ``device`` commits the replica's engine to its own sub-mesh
slice of ``jax.devices()``; ``begin_drain``/``drain_graceful`` stop
admission, finish in-flight requests, and hand the untouched queue back
for re-routing (zero leaked pool blocks — the scale-down primitive);
``prefill_only`` replicas park prefill-complete requests in ``ready``
instead of arming decode, and ``peek_ready``/``complete_handoff`` +
``adopt`` move a request's KV blocks into a decode replica's pool
(``PagedEngine.export_chain``/``import_chain``) — the disaggregated
prefill/decode split.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np

from pytorch_distributed_tpu.compilecache.aot import attribute_compile
from pytorch_distributed_tpu.telemetry import (
    NULL_RECORDER,
    NULL_TRACER,
    AnomalySentinel,
    GoodputLedger,
    LatencySeries,
    ProgramTimes,
)


@dataclasses.dataclass
class Request:
    rid: int
    tokens: np.ndarray  # [L] int32 prompt
    max_new_tokens: int
    submit_step: int
    submit_time: float
    slot: int = -1  # -1 while queued
    prefill_done: int = 0  # tokens prefilled so far (chunk multiple)
    produced: int = 0
    admit_step: int = -1
    admit_time: float = float("nan")
    first_token_time: float = float("nan")
    # step-domain TTFT anchor: the scheduler tick that materialized the
    # first token. Wall latencies measure THIS machine; tick latencies
    # measure the schedule — the fleet benches evaluate SLOs in ticks so
    # the router A/B is invariant to how fast the simulating host turns
    # the crank (fleet replicas tick in lockstep, so cross-replica step
    # differences are well-defined even across a prefill→decode handoff)
    first_token_step: int = -1
    last_token_time: float = float("nan")
    # inter-token gaps AFTER the first token (the decode-tick latency
    # this request's stream observed; the first token's latency is TTFT)
    token_gaps: List[float] = dataclasses.field(default_factory=list)
    # True when a compile stall landed inside this request's lifetime: a
    # prefill chunk of its batch hit a not-yet-hot bucket program, or its
    # first decode tick compiled the decode program. Cold requests' TTFT
    # pollutes p99 with XLA compile time — the per-request JSONL carries
    # the flag so percentiles can be reported warm-only vs all (and the
    # warmup runtime exists to make every request warm).
    cold: bool = False
    # fleet routing provenance (fleet/router.py): the session the router
    # used for affinity, and whether this request was spilled off its
    # affinity replica by the SLO gate — both land in the JSONL record
    session: Optional[int] = None
    spilled: bool = False

    @property
    def length(self) -> int:
        return int(len(self.tokens))


class Scheduler:
    """Continuous paged-KV scheduler: ``submit`` enqueues, ``step``
    advances the whole system one tick, ``drain`` runs to empty.

    ``step()`` returns ``[(rid, token)]`` for the tokens produced this
    tick — request ids, not slots (slots recycle; rids don't).
    """

    def __init__(self, config, params, n_slots: int, *,
                 n_blocks: Optional[int] = None, block_len: int = 16,
                 prefill_chunk: int = 64, admit_per_step: int = 4,
                 temperature: float = 0.0, top_k: Optional[int] = None,
                 seed: int = 0, eos_id: Optional[int] = None, mesh=None,
                 tracer=None, metrics_log=None, replica_id: int = 0,
                 prefill_only: bool = False, device=None,
                 handoff: bool = False, flightrec=None,
                 anomaly_threshold: float = 8.0,
                 gather_impl: Optional[str] = None,
                 kv_dtype: Optional[str] = None):
        from pytorch_distributed_tpu.serving.engine import PagedEngine

        if eos_id is not None and not 0 <= eos_id < config.vocab_size:
            raise ValueError(
                f"eos_id {eos_id} outside [0, vocab_size={config.vocab_size})"
            )
        if admit_per_step < 1:
            raise ValueError(
                f"admit_per_step must be >= 1, got {admit_per_step}"
            )
        self.engine = PagedEngine(
            config, params, n_slots, n_blocks=n_blocks, block_len=block_len,
            prefill_chunk=prefill_chunk, temperature=temperature,
            top_k=top_k, mesh=mesh, device=device,
            handoff=(handoff or prefill_only),
            gather_impl=gather_impl, kv_dtype=kv_dtype,
        )
        # the engine may have replaced gather_impl= into the config —
        # read back its copy so scheduler and programs agree
        self.config = self.engine.config
        self.n_slots = n_slots
        self.admit_per_step = admit_per_step
        self.eos_id = eos_id
        self.replica_id = replica_id
        self.prefill_only = prefill_only
        self.draining = False
        # prefill_only: requests whose prefill finished and are waiting
        # for the fleet router to hand their KV blocks to a decode
        # replica (rid -> the slot HERE holding them; slot + blocks stay
        # held until complete_handoff. The slot is recorded on this side
        # because adoption re-points req.slot at the decode replica's
        # slot — trusting it afterwards would free someone else's slot)
        self.ready: Dict[int, int] = {}
        self._handoffs = 0
        self._adopted = 0
        self._rng = jax.random.key(seed)
        self._next_rid = 0
        self._step_count = 0
        self.queue: deque = deque()
        self.resident: Dict[int, Request] = {}  # slot -> request
        self.positions = np.zeros(n_slots, np.int32)
        self.remaining = np.zeros(n_slots, np.int32)
        # ---- exact host-side metric counters ----
        self._tokens_out = 0
        self._completed = 0
        self._admitted = 0
        self._adm_latency_steps = 0
        self._adm_latency_s = 0.0
        self._occupancy_sum = 0.0  # mean-able over steps
        self._start_time: Optional[float] = None
        # ---- latency series (telemetry/latency.py; exact, host-side) ----
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self.metrics_log = metrics_log
        self.ttft = LatencySeries("ttft")
        # warm-only TTFT: requests whose lifetime saw no compile stall —
        # the honest SLO series (cold first-bucket requests excluded)
        self.ttft_warm = LatencySeries("ttft_warm")
        self.token_lat = LatencySeries("token_lat")
        self.queue_wait = LatencySeries("queue_wait")
        # wall cost of THIS replica's own step() on ticks that delivered
        # tokens — the replica-attributed token latency. In the fleet's
        # one-loop simulation the gap between two tokens includes every
        # OTHER replica's step too; this series is what the stream pays
        # on ITS replica (chunk-program interference included for mixed
        # replicas, excluded for pure-decode ones) — the disaggregation
        # A/B's honest metric (ANALYSIS.md "Serving fleet").
        self.tick_lat = LatencySeries("tick")
        self._cold_requests = 0
        # wall-time ledger: serving attributes its compile stalls (lazy
        # first-bucket compiles AND warmup compile time) so cold-vs-warm
        # starts compare on one number — goodput compile fraction
        self.goodput = GoodputLedger()
        self.goodput.start()
        # ---- attribution & forensics (ISSUE 8) ----
        # per-program measured wall for the cost-card join: the chunk
        # program of each tick's bucket, and the decode tick (whose
        # tokens materialize inside engine.decode, so its wall is honest
        # device+sync time, not bare dispatch)
        self.prog_times = ProgramTimes()
        self.flightrec = flightrec if flightrec is not None else NULL_RECORDER
        # anomaly sentinel over tick time / TTFT / queue depth; a recent
        # hit surfaces as metrics()["anomaly_recent"], which the fleet
        # SLOGate reads as a hot signal (spill around this replica)
        self.sentinel = (
            AnomalySentinel(
                threshold=anomaly_threshold, metrics_log=metrics_log,
                flightrec=self.flightrec, source=f"replica{replica_id}",
            )
            if anomaly_threshold and anomaly_threshold > 0 else None
        )
        self._last_anomaly_step = None
        #: ticks an anomaly stays "recent" for the SLO gate's hot signal
        self.anomaly_recent_ticks = 64
        if self.sentinel is not None:
            # scale floors: a detector over a near-constant series would
            # otherwise flag routine jitter (MAD ≈ 0 → any blip is ∞σ).
            # Time series floor at 10 ms — a stall must clear
            # threshold × 10 ms above baseline; queue depth floors at one
            # whole request.
            self.sentinel.detector("tick_time").abs_floor = 0.01
            self.sentinel.detector("ttft").abs_floor = 0.01
            self.sentinel.detector("queue_depth").abs_floor = 1.0

    # ---- API ----

    def warmup(self, background: bool = True):
        """Compile every program this scheduler can ever run, BEFORE
        traffic (compilecache/: ANALYSIS.md "Cold start & compile cache").

        The decode tick and the smallest prefill bucket compile (and
        execute inert) in the foreground — serving can start the moment
        this returns, with the serve-critical path hot; the remaining
        buckets AOT-compile on a background thread into the persistent
        compilation cache. ``background=False`` compiles everything in
        the foreground with inert execution: zero cold requests, the
        strongest guarantee, at full upfront cost.

        Warmup compile time lands in the ledger's ``compile`` category
        and each program emits a ``kind="warmup"`` manifest record to
        ``metrics_log`` — so a cold start (fresh cache) and a warm start
        (populated cache) compare on the goodput compile fraction.
        Returns the ``WarmupRunner`` (``.wait()`` joins the background
        thread; ``.summary()`` aggregates the manifest).
        """
        from pytorch_distributed_tpu.compilecache import (
            WarmupRunner,
            serving_registry,
        )

        runner = WarmupRunner(
            serving_registry(self.engine),
            tracer=self.tracer,
            ledger=self.goodput,
            manifest=self.metrics_log,
        )
        return runner.run(background=background)

    def submit(self, prompt: np.ndarray, max_new_tokens: int, *,
               session: Optional[int] = None, spilled: bool = False,
               rid: Optional[int] = None) -> int:
        """Enqueue one request; returns its request id. Never raises for
        capacity — only for requests no configuration could serve, and
        for submission into a draining replica (the router must not
        route here once ``begin_drain`` ran).

        ``session``/``spilled`` are fleet routing provenance stamped into
        the per-request JSONL; ``rid`` lets the fleet router allocate
        request ids from ONE fleet-wide space so a request keeps its id
        across replicas and the prefill→decode handoff."""
        if self.draining:
            raise RuntimeError(
                f"replica {self.replica_id} is draining; route elsewhere"
            )
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        l = len(prompt)
        if l < 1:
            raise ValueError("prompt must contain at least one token")
        c = self.engine.chunk
        padded = -(-l // c) * c
        if padded > self.config.max_seq_len:
            raise ValueError(
                f"prompt ({l}) padded to {padded} exceeds max_seq_len "
                f"{self.config.max_seq_len}"
            )
        if l + max_new_tokens > self.config.max_seq_len:
            raise ValueError(
                f"prompt ({l}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds max_seq_len {self.config.max_seq_len}"
            )
        if rid is None:
            rid = self._next_rid
            self._next_rid += 1
        else:
            self._next_rid = max(self._next_rid, rid + 1)
        self.queue.append(Request(
            rid=rid, tokens=prompt, max_new_tokens=max_new_tokens,
            submit_step=self._step_count, submit_time=time.perf_counter(),
            session=session, spilled=spilled,
        ))
        return rid

    def _free_slots(self) -> List[int]:
        return [s for s in range(self.n_slots) if s not in self.resident]

    def _admit(self) -> None:
        """Admit up to ``admit_per_step`` queue-head requests that can be
        served now. Strict FIFO: the first request that cannot get a slot
        or a chain stops admission for this step."""
        if self.draining:
            return
        free = self._free_slots()
        admitted = 0
        now = time.perf_counter()
        while self.queue and free and admitted < self.admit_per_step:
            req = self.queue[0]
            slot = free[0]
            if not self.engine.admit(slot, req.length, req.max_new_tokens):
                break  # pool OOM: queue (blocks free as others retire)
            self.queue.popleft()
            free.pop(0)
            req.slot = slot
            req.admit_step = self._step_count
            req.admit_time = now
            self.resident[slot] = req
            self.positions[slot] = 0
            self.remaining[slot] = 0  # decode-armed after the last chunk
            self._admitted += 1
            self._adm_latency_steps += self._step_count - req.submit_step
            self._adm_latency_s += now - req.submit_time
            self.queue_wait.observe(now - req.submit_time)
            self.flightrec.record(
                "admit", rid=req.rid, slot=slot, replica=self.replica_id
            )
            admitted += 1

    def _chunk_jobs(self):
        from pytorch_distributed_tpu.serving.engine import ChunkJob

        c = self.engine.chunk
        jobs = []
        for slot, req in sorted(self.resident.items()):
            if req.prefill_done >= req.length:
                continue
            start = req.prefill_done
            seg = req.tokens[start:start + c]
            tokens = np.zeros((c,), np.int32)
            tokens[:len(seg)] = seg
            is_last = start + c >= req.length
            jobs.append(ChunkJob(
                slot=slot, tokens=tokens, start=start, is_last=is_last,
                last_idx=(req.length - 1 - start) if is_last else 0,
            ))
        return jobs

    def step(self) -> List[Tuple[int, int]]:
        """One tick: admissions → one prefill chunk per unfinished prompt
        (ONE compiled program) → one decode token per ready lane →
        retirements. Returns ``[(rid, token)]``."""
        if self._start_time is None:
            self._start_time = time.perf_counter()
        t_step0 = time.perf_counter()
        with self.tracer.span("admission", queued=len(self.queue)):
            self._admit()
        jobs = self._chunk_jobs()
        if jobs:
            # cold bucket: this batch's (k_pad, wp) program has never
            # executed — the call below stalls for its compile (or a
            # persistent-cache load after an AOT-only warmup). Mark every
            # request riding the batch and book the stall as compile time.
            bucket = self.engine.bucket_for(jobs)
            cold_bucket = not self.engine.has_chunk_program(*bucket)
            if cold_bucket:
                for j in jobs:
                    self.resident[j.slot].cold = True
            t_chunk = time.perf_counter()
            with self.tracer.span("prefill_chunk", jobs=len(jobs)), \
                    attribute_compile(self.goodput if cold_bucket
                                      else None):
                self.engine.run_chunks(jobs)
            if not cold_bucket:
                # cost-card join: warm dispatch wall attributed to THIS
                # bucket's program (cold calls excluded — their wall is
                # compile, already booked to the ledger above)
                self.prog_times.observe(
                    self.engine.chunk_program_name(*bucket),
                    time.perf_counter() - t_chunk,
                )
            for j in jobs:
                req = self.resident[j.slot]
                req.prefill_done += self.engine.chunk
                if req.prefill_done >= req.length:
                    # prefill complete: arm the decode lane at the
                    # prompt's true frontier — or, on a prefill-only
                    # replica, park the request (blocks + slot held) in
                    # ``ready`` for the router's decode handoff
                    self.positions[j.slot] = req.length
                    if self.prefill_only:
                        self.ready[req.rid] = j.slot
                    else:
                        self.remaining[j.slot] = req.max_new_tokens
        active = self.remaining > 0
        self._occupancy_sum += len(self.resident) / self.n_slots
        self._step_count += 1
        if not active.any():
            self._observe_tick(t_step0)
            return []
        self._rng, sub = jax.random.split(self._rng)
        cold_decode = not self.engine.has_decode_program
        if cold_decode:
            # every active lane's token this tick arrives through the
            # decode program's first compile — those requests are cold
            for slot in np.nonzero(active)[0]:
                self.resident[int(slot)].cold = True
        t_dec = time.perf_counter()
        with self.tracer.span("decode_tick", lanes=int(active.sum())), \
                attribute_compile(self.goodput if cold_decode else None):
            tokens, self.positions = self.engine.decode(
                self.positions, active, sub
            )
        # engine.decode returns MATERIALIZED numpy tokens, so this
        # timestamp is token-delivery time, not dispatch time
        now = time.perf_counter()
        if not cold_decode:
            # cost-card join: tokens materialized above, so this wall is
            # dispatch + device + sync — the honest decode-tick cost
            self.prog_times.observe(self.engine.DECODE_PROGRAM, now - t_dec)
        out: List[Tuple[int, int]] = []
        for slot in np.nonzero(active)[0]:
            slot = int(slot)
            req = self.resident[slot]
            token = int(tokens[slot])
            out.append((req.rid, token))
            if req.produced == 0:
                req.first_token_time = now
                req.first_token_step = self._step_count
                self.ttft.observe(now - req.submit_time)
                if self.sentinel is not None and not req.cold:
                    # warm TTFT only: a cold request's compile stall is a
                    # known cause, already attributed — not an anomaly
                    self._note_anomaly(self.sentinel.observe(
                        "ttft", now - req.submit_time, rid=req.rid,
                        tick=self._step_count,
                    ))
                if not req.cold:
                    self.ttft_warm.observe(now - req.submit_time)
            else:
                gap = now - req.last_token_time
                req.token_gaps.append(gap)
                self.token_lat.observe(gap)
            req.last_token_time = now
            req.produced += 1
            self._tokens_out += 1
            if (self.eos_id is not None and token == self.eos_id) or \
                    req.produced >= req.max_new_tokens:
                self.remaining[slot] = 0
                del self.resident[slot]
                self.engine.release(slot)
                self._completed += 1
                if req.cold:
                    self._cold_requests += 1
                self.flightrec.record(
                    "retire", rid=req.rid, tokens=req.produced,
                    replica=self.replica_id,
                )
                self._log_request(req)
            else:
                self.remaining[slot] -= 1
        if out:
            self.tick_lat.observe(now - t_step0)
        self._observe_tick(t_step0)
        return out

    def _note_anomaly(self, hit: Optional[dict]) -> None:
        if hit is not None:
            self._last_anomaly_step = self._step_count

    def _observe_tick(self, t_step0: float) -> None:
        """Per-tick sentinel feed: tick wall and queue depth (every tick,
        both return paths of ``step``)."""
        if self.sentinel is None:
            return
        self._note_anomaly(self.sentinel.observe(
            "tick_time", time.perf_counter() - t_step0,
            tick=self._step_count,
        ))
        self._note_anomaly(self.sentinel.observe(
            "queue_depth", float(len(self.queue)), tick=self._step_count,
        ))

    def _log_request(self, req: Request) -> None:
        """One ``kind="request"`` JSONL record per retirement — the raw
        per-request latencies ``telemetry_report.py`` aggregates."""
        if self.metrics_log is None:
            return
        self.metrics_log.log(
            kind="request",
            rid=req.rid,
            replica_id=self.replica_id,
            rejected=False,
            session=req.session,
            spilled=req.spilled,
            prompt_len=req.length,
            new_tokens=req.produced,
            cold=req.cold,
            queue_wait_s=round(req.admit_time - req.submit_time, 6),
            ttft_s=round(req.first_token_time - req.submit_time, 6),
            queue_wait_steps=req.admit_step - req.submit_step,
            ttft_steps=req.first_token_step - req.submit_step,
            token_gaps_s=[round(g, 6) for g in req.token_gaps],
        )

    def drain(self, max_steps: int = 100_000) -> Dict[int, List[int]]:
        """Step until queue and lanes are empty; returns
        ``{rid: [tokens]}``."""
        produced: Dict[int, List[int]] = {}
        for _ in range(max_steps):
            if not self.queue and not self.resident:
                return produced
            for rid, tok in self.step():
                produced.setdefault(rid, []).append(tok)
        raise RuntimeError(
            f"drain did not converge within {max_steps} steps "
            f"(queue={len(self.queue)}, resident={len(self.resident)})"
        )

    # ---- graceful drain (fleet scale-down / replica removal) ----

    def begin_drain(self) -> None:
        """Stop admitting: ``submit`` raises, ``step`` skips admission.
        In-flight requests keep decoding to completion; the queue is
        frozen for ``drain_graceful`` to hand back to the router."""
        self.draining = True

    def drain_graceful(
        self, max_steps: int = 100_000
    ) -> Tuple[Dict[int, List[int]], List[Request]]:
        """Drain for scale-down: stop admitting, run every in-flight
        request to retirement, and return ``(produced, requeued)`` —
        the tokens the in-flight requests streamed, plus the queued
        (never-admitted) requests the router must re-route. After this
        returns, every pool block is back on the free list
        (``engine.allocator.in_use == 0``): retirement freed the
        in-flight chains and queued requests never held any.

        On a ``prefill_only`` replica the in-flight requests end parked
        in ``ready`` (their blocks intentionally held for handoff) — the
        router completes the handoffs, after which the pool is empty
        too."""
        self.begin_drain()
        requeued = list(self.queue)
        self.queue.clear()
        produced: Dict[int, List[int]] = {}
        for _ in range(max_steps):
            if not self.resident or (
                self.prefill_only
                and all(r.rid in self.ready
                        for r in self.resident.values())
            ):
                return produced, requeued
            for rid, tok in self.step():
                produced.setdefault(rid, []).append(tok)
        raise RuntimeError(
            f"drain_graceful did not converge within {max_steps} steps "
            f"(resident={len(self.resident)})"
        )

    # ---- prefill→decode handoff (fleet disaggregation) ----

    def ready_rids(self) -> List[int]:
        """Prefill-complete requests awaiting handoff, in rid order."""
        return sorted(self.ready)

    def peek_ready(self, rid: int):
        """``(request, KVExport)`` for a ready request, WITHOUT releasing
        it — the router calls ``adopt`` on the decode replica first and
        only then ``complete_handoff``, so a full decode pool leaves the
        request parked here, intact, for the next tick."""
        slot = self.ready[rid]
        return self.resident[slot], self.engine.export_chain(slot)

    def complete_handoff(self, rid: int) -> None:
        """The decode replica adopted the blocks: free this replica's
        copy (slot + chain) and account the handoff."""
        slot = self.ready.pop(rid)
        del self.resident[slot]
        self.engine.release(slot)
        self.remaining[slot] = 0
        self._handoffs += 1

    def adopt(self, req: Request, export) -> bool:
        """Adopt a prefill-complete request whose KV was exported from a
        prefill replica: allocate a slot + chain, import the blocks
        (``PagedEngine.import_chain`` — the cross-mesh ``device_put``),
        and arm the decode lane at the prompt frontier. Returns False
        (nothing changed, export still valid) when no slot or chain is
        available — the router retries next tick.

        The request keeps its fleet rid, submit timestamps, and
        admission timestamps from the prefill replica, so TTFT measured
        here is end-to-end (submit → queue → prefill → handoff → first
        decoded token)."""
        if self.prefill_only:
            raise RuntimeError("a prefill_only replica cannot adopt")
        if self.draining:
            return False
        free = self._free_slots()
        if not free:
            return False
        slot = free[0]
        if not self.engine.import_chain(slot, export):
            return False
        req.slot = slot
        req.prefill_done = req.length
        if req.admit_step < 0:  # adopted without a prior admission
            req.admit_step = self._step_count
            req.admit_time = time.perf_counter()
            self.queue_wait.observe(req.admit_time - req.submit_time)
        self.resident[slot] = req
        self.positions[slot] = req.length
        self.remaining[slot] = req.max_new_tokens
        self._admitted += 1
        self._adopted += 1
        return True

    # ---- cost cards (telemetry/costmodel.py) ----

    def log_cost_cards(self) -> list:
        """One ``kind="program_cost"`` JSONL record per registry program:
        the compiler's FLOP/byte statics joined with this scheduler's
        measured per-program tick wall (warm calls only — compile stalls
        are ledger ``compile`` time, not program cost). Building the
        statics AOT-compiles each not-yet-compiled bucket (a disk hit
        under the persistent cache), so call it once per run, after
        traffic — never inside the serve loop. Returns the records."""
        from pytorch_distributed_tpu.compilecache import serving_registry
        from pytorch_distributed_tpu.telemetry import log_cost_cards

        return log_cost_cards(
            serving_registry(self.engine), self.prog_times, self.metrics_log
        )

    # ---- metrics ----

    @property
    def anomaly_recent(self) -> bool:
        """True while an anomaly lies within the last
        ``anomaly_recent_ticks`` ticks — the SLO gate's hot signal."""
        return (
            self._last_anomaly_step is not None
            and self._step_count - self._last_anomaly_step
            <= self.anomaly_recent_ticks
        )

    def metrics(self) -> dict:
        """Exact host-side accounting; all counters, no device sync."""
        alloc_blocks = self.engine.allocator.in_use
        alloc_tokens = alloc_blocks * self.engine.block_len
        used_tokens = int(sum(
            # tokens actually written and live for the request: its
            # prefill frontier plus produced decode tokens
            min(r.prefill_done, r.length) + r.produced
            for r in self.resident.values()
        ))
        elapsed = (
            time.perf_counter() - self._start_time
            if self._start_time is not None else 0.0
        )
        return {
            "replica_id": self.replica_id,
            "draining": self.draining,
            "handoffs": self._handoffs,
            "adopted": self._adopted,
            "ready": len(self.ready),
            # the ledger's utilization view: share of this replica's wall
            # NOT lost to classified overheads (compile) — the
            # fleet autoscaler folds it in next to occupancy_mean
            "goodput_frac": self.goodput.report()["goodput_frac"],
            "steps": self._step_count,
            "queue_depth": len(self.queue),
            "occupancy": len(self.resident) / self.n_slots,
            "occupancy_mean": (
                self._occupancy_sum / self._step_count
                if self._step_count else 0.0
            ),
            "pool_blocks_in_use": alloc_blocks,
            "pool_frac_in_use": (
                alloc_blocks / (self.engine.allocator.n_blocks - 1)
            ),
            "padding_waste_frac": (
                1.0 - used_tokens / alloc_tokens if alloc_tokens else 0.0
            ),
            "admitted": self._admitted,
            "completed": self._completed,
            "tokens_out": self._tokens_out,
            "tokens_per_s": self._tokens_out / elapsed if elapsed else 0.0,
            "admission_latency_steps_mean": (
                self._adm_latency_steps / self._admitted
                if self._admitted else 0.0
            ),
            "admission_latency_s_mean": (
                self._adm_latency_s / self._admitted
                if self._admitted else 0.0
            ),
            # cold-start honesty: how many retired requests ate a compile
            # stall, and the compile seconds the ledger attributed —
            # warm-only TTFT is the SLO series, plain ttft includes cold
            "cold_requests": self._cold_requests,
            "compile_s": self.goodput.seconds("compile"),
            # anomaly sentinel (telemetry/anomaly.py): total hits and the
            # recency flag the fleet SLOGate treats as hot
            "anomaly_count": (
                self.sentinel.anomalies if self.sentinel is not None else 0
            ),
            "anomaly_recent": self.anomaly_recent,
            # latency percentiles — the SLO surface (exact, host-side)
            **self.ttft.summary("ttft"),
            **self.ttft_warm.summary("ttft_warm"),
            **self.token_lat.summary("token_lat"),
            **self.queue_wait.summary("queue_wait"),
            **self.tick_lat.summary("tick"),
        }
