"""Continuous scheduler over the paged engine.

Policy (Orca-style continuous batching with chunked prefill):

- **FIFO admission, batched**: each ``step()`` admits up to
  ``admit_per_step`` queued requests — strictly in submit order, stopping
  at the first that cannot get a slot or a block chain (no head-of-line
  skipping: deterministic, starvation-free). All admitted-and-unfinished
  prompts advance by ONE chunk per step through a single compiled chunk
  program (``PagedEngine.run_chunks``), so a long prompt never stalls the
  decode lanes — it interleaves, chunk by chunk, with everyone else's
  decode ticks.
- **decode**: every fully-prefilled slot with budget advances one token
  per step; EOS (when configured) retires a slot early. Retirement frees
  the block chain immediately — the freed blocks are the next
  admission's allocation (LIFO).
- **OOM queues**: a request that cannot be served *now* (no free slot, or
  the pool cannot supply its chain) simply stays queued. ``submit``
  never raises for capacity reasons — only for requests that could never
  fit (``> max_seq_len``).

Metrics are exact host-side counters, no device sync beyond the token
fetch the caller already pays: slot occupancy, block-pool occupancy,
padding-waste fraction (allocated-but-unwritten block capacity),
admission latency (steps and wall seconds from submit to admission),
queue depth, and tokens/s — plus, from round 7 (ISSUE 4), the latency
percentiles a continuous batcher exists to control: TTFT (submit →
first materialized token), per-output-token latency (inter-token gap),
and queue wait (submit → admit), all exact host-side series from
timestamps the scheduler already holds (``telemetry.LatencySeries``).
Pass ``metrics_log`` (a ``MetricsLogger``) to stream one ``kind=
"request"`` JSONL record per retirement — the raw material
``scripts/telemetry_report.py`` computes percentiles from — and
``tracer`` (a ``telemetry.SpanTracer``) for admission / prefill_chunk /
decode_tick spans.

KV pressure tier (round 13; ANALYSIS.md "KV pressure & preemption"):
``offload=True`` arms the second tier — ``preempt(rid)`` parks a
decode-armed request (LRU-idle victims first via ``preempt_lru``),
choosing per request between swapping its chain to a host-RAM
``HostBlockStore`` (compiled gather → async d2h, finalized next tick)
and recomputing from the prompt (chain dropped now; the streamed tokens
re-prefill as prompt at restore) by a MEASURED cost comparison
(``telemetry.costmodel.swap_vs_recompute``: chain bytes through the
probed link vs resume chunks times the chunk program's measured wall).
``_restore_parked`` restores FIFO before each tick's admissions — a
preempted request resumes before its next decode, token-identical
either way. ``preempt_on_oom`` lets admission preempt one victim per
stuck queue head; the fleet ``SLOGate``'s preempt rung drives the same
entry point to turn sheds into preemptions.

Fleet integration (round 10; ``fleet/``, ANALYSIS.md "Serving fleet"):
one Scheduler is one *replica*. ``replica_id`` stamps every JSONL
record; ``device`` commits the replica's engine to its own sub-mesh
slice of ``jax.devices()``; ``begin_drain``/``drain_graceful`` stop
admission, finish in-flight requests, and hand the untouched queue back
for re-routing (zero leaked pool blocks — the scale-down primitive);
``prefill_only`` replicas park prefill-complete requests in ``ready``
instead of arming decode, and ``peek_ready``/``complete_handoff`` +
``adopt`` move a request's KV blocks into a decode replica's pool
(``PagedEngine.export_chain``/``import_chain``) — the disaggregated
prefill/decode split.

Async host runtime (round 16; ANALYSIS.md "Async host runtime"):
``step()`` is now a thin wrapper over a **dispatch/collect split** —
``dispatch_tick()`` runs admissions, the chunk program, and a
NON-BLOCKING decode launch (``PagedEngine.decode_launch``: JAX async
dispatch returns before device completion), parking a ``TickHandle``;
``collect_tick()`` materializes the parked tick's tokens and does all
per-token host work (TTFT, retirement, JSONL). The fleet router's
``async_host=True`` loop drives the halves LAGGED — collect tick N−1,
then dispatch tick N back-to-back on every replica — so one replica's
host work overlaps the others' in-flight device work. Per replica the
order collect(N−1) → dispatch(N) is exactly the synchronous schedule,
which is why token streams are bit-identical between modes. Any entry
point that mutates decode-armed state from OUTSIDE the tick cycle
(``preempt``/``preempt_lru``/``begin_drain``) collects the pending
tick first, so an in-flight decode can never race a chain release.
``host_pool`` (a ``serving.host_worker.HostWorkerPool``) moves
per-request JSONL emission and the gate-metrics percentile math onto
worker threads; ``gate_metrics()`` is the router's routing view —
worker-refreshed percentile snapshot overlaid with LIVE cheap counters
(queue depth, occupancy, preemptible), so depth-bound SLO decisions
stay deterministic while the O(n log n) percentile work leaves the
critical path.

Lifecycle tracing (round 14; ANALYSIS.md "Request-lifecycle tracing"):
pass ``reqtrace`` (a ``telemetry.ReqTracer``) and every request becomes
one causal span tree — queued → prefill (per-chunk events naming the
bucket program) → decode windows → retire, with preempt/park/restore as
a sub-tree carrying the swap decision's predicted costs next to the
measured swap walls, ``handoff_wait`` bridging into the fleet router's
handoff span, and KV chain transitions (alloc/free/swap states)
annotated through the ``BlockAllocator.on_transition`` adapter.
``scripts/explain_request.py`` reconstructs any rid's story from the
resulting ``kind="span"`` JSONL.

Prefix sharing (round 17; ANALYSIS.md "Prefix sharing & copy-on-write"):
``prefix_cache=True`` arms the radix index over the block pool —
admission consults ``PagedEngine.admit_shared`` so a prompt whose
leading full blocks are already resident allocates only the suffix and
chunk-prefills only the uncovered tail (admission cost O(new tokens),
the PagedAttention sharing story), with the full-cover boundary block
copy-on-write duplicated so the final token's re-prefill regenerates
the logits row without touching shared state. Chains insert their full
prompt blocks as prefill crosses block boundaries; retirement decrefs,
and the index's LRU eviction of refcount-1 blocks is the engine's
first pool-pressure valve — it fires BEFORE ``preempt_on_oom`` parks a
live chain. Greedy streams stay token-identical to the no-sharing
engine (tests/test_prefix.py), and every hit lands a ``kind="prefix"``
JSONL record.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Callable, Dict, List, NamedTuple, Optional, Tuple

import jax
import numpy as np

from pytorch_distributed_tpu.compilecache.aot import attribute_compile
from pytorch_distributed_tpu.resilience.faults import fault_point
from pytorch_distributed_tpu.telemetry import (
    NULL_LEDGER,
    NULL_RECORDER,
    NULL_REQTRACER,
    NULL_TRACER,
    AnomalySentinel,
    GoodputLedger,
    LatencySeries,
    ProgramTimes,
    percentiles,
)


@dataclasses.dataclass
class Request:
    rid: int
    tokens: np.ndarray  # [L] int32 prompt
    max_new_tokens: int
    submit_step: int
    submit_time: float
    slot: int = -1  # -1 while queued
    prefill_done: int = 0  # tokens prefilled so far (chunk multiple)
    produced: int = 0
    admit_step: int = -1
    admit_time: float = float("nan")
    first_token_time: float = float("nan")
    # step-domain TTFT anchor: the scheduler tick that materialized the
    # first token. Wall latencies measure THIS machine; tick latencies
    # measure the schedule — the fleet benches evaluate SLOs in ticks so
    # the router A/B is invariant to how fast the simulating host turns
    # the crank (fleet replicas tick in lockstep, so cross-replica step
    # differences are well-defined even across a prefill→decode handoff)
    first_token_step: int = -1
    last_token_time: float = float("nan")
    # inter-token gaps AFTER the first token (the decode-tick latency
    # this request's stream observed; the first token's latency is TTFT)
    token_gaps: List[float] = dataclasses.field(default_factory=list)
    # True when a compile stall landed inside this request's lifetime: a
    # prefill chunk of its batch hit a not-yet-hot bucket program, or its
    # first decode tick compiled the decode program. Cold requests' TTFT
    # pollutes p99 with XLA compile time — the per-request JSONL carries
    # the flag so percentiles can be reported warm-only vs all (and the
    # warmup runtime exists to make every request warm).
    cold: bool = False
    # fleet routing provenance (fleet/router.py): the session the router
    # used for affinity, and whether this request was spilled off its
    # affinity replica by the SLO gate — both land in the JSONL record
    session: Optional[int] = None
    spilled: bool = False
    # ---- per-request deadline (round 19; ROADMAP item 5 rung) ----
    # absolute ``time.perf_counter()`` instant after which the request
    # expires through the cancel path with ``outcome="deadline"``. The
    # deadline is absolute (not remaining seconds) so it survives
    # re-dispatch to another replica unchanged — a request does not get
    # a fresh budget by losing its replica. ``inf`` == no deadline.
    deadline: float = float("inf")
    # replica hops: every replica that has owned this request, in order
    # (the re-dispatch chain ``scripts/explain_request.py`` renders)
    redispatches: int = 0
    # ---- pressure tier (round 13; offload schedulers only) ----
    # the submitted prompt's length — ``tokens`` grows on a recompute
    # restore (generated tokens re-prefill as prompt), so the JSONL's
    # prompt_len reports THIS, not len(tokens)
    orig_len: int = -1
    # tokens this request has streamed, kept only under offload: the
    # recompute path re-prefills them as prompt so the stream resumes
    # bit-exact from where it was preempted
    generated: Optional[List[int]] = None
    # preempt/restore accounting + the anti-thrash protection window
    # (a just-restored request cannot be re-victimized before this tick)
    preempts: int = 0
    protect_until: int = -1
    # ---- request-lifecycle trace spans (round 14; telemetry/reqtrace).
    # Span ids of this request's currently-open lifecycle spans (0 ==
    # none). They live on the Request because the request OBJECT crosses
    # replica boundaries on the disaggregated handoff — the span ids
    # travel with it, so the decode replica closes what the prefill
    # replica opened and the trace stays one tree.
    span_queue: int = 0
    span_prefill: int = 0
    span_ready: int = 0
    span_decode: int = 0
    span_preempt: int = 0
    span_parked: int = 0
    span_swap: int = 0

    @property
    def length(self) -> int:
        return int(len(self.tokens))


class TickHandle(NamedTuple):
    """One dispatched-but-uncollected scheduler tick (round 16).

    ``tokens`` is the decode program's token output — a DEVICE array on
    the async path (materialized at collect), an np array on the sync
    path (materialized inside the ledger window), or None when the tick
    had no active decode lane. ``lanes`` are the slots that were active
    at dispatch, in slot order — collect processes exactly these, and
    the no-external-mutation protocol (preempt/drain collect first)
    guarantees each is still resident at collect time."""

    tokens: object
    positions: object
    launch: object  # engine launch token (None for sync / no-decode)
    lanes: Tuple[int, ...]
    t_step0: float
    t_dec: float
    cold_decode: bool
    sync: bool


class Scheduler:
    """Continuous paged-KV scheduler: ``submit`` enqueues, ``step``
    advances the whole system one tick, ``drain`` runs to empty.

    ``step()`` returns ``[(rid, token)]`` for the tokens produced this
    tick — request ids, not slots (slots recycle; rids don't).
    """

    def __init__(self, config, params, n_slots: int, *,
                 n_blocks: Optional[int] = None, block_len: int = 16,
                 prefill_chunk: int = 64, admit_per_step: int = 4,
                 temperature: float = 0.0, top_k: Optional[int] = None,
                 seed: int = 0, eos_id: Optional[int] = None, mesh=None,
                 tracer=None, metrics_log=None, replica_id: int = 0,
                 prefill_only: bool = False, device=None,
                 handoff: bool = False, flightrec=None,
                 anomaly_threshold: float = 8.0,
                 gather_impl: Optional[str] = None,
                 kv_dtype: Optional[str] = None,
                 offload: bool = False, preempt_on_oom: bool = False,
                 swap_policy: str = "auto", protect_ticks: int = 2,
                 host_store=None,
                 host_store_max_bytes: Optional[int] = None,
                 reqtrace=None, ledger=None, host_pool=None,
                 prefix_cache: bool = False, blocksan=None,
                 split_s: Optional[int] = None,
                 autotune_dir: Optional[str] = None):
        from pytorch_distributed_tpu.serving.engine import PagedEngine
        from pytorch_distributed_tpu.serving.kv_pool import HostBlockStore

        if swap_policy not in ("auto", "swap", "recompute"):
            raise ValueError(
                f"swap_policy {swap_policy!r} must be auto|swap|recompute"
            )
        if preempt_on_oom and not offload:
            raise ValueError("preempt_on_oom needs offload=True")

        if eos_id is not None and not 0 <= eos_id < config.vocab_size:
            raise ValueError(
                f"eos_id {eos_id} outside [0, vocab_size={config.vocab_size})"
            )
        if admit_per_step < 1:
            raise ValueError(
                f"admit_per_step must be >= 1, got {admit_per_step}"
            )
        self.engine = PagedEngine(
            config, params, n_slots, n_blocks=n_blocks, block_len=block_len,
            prefill_chunk=prefill_chunk, temperature=temperature,
            top_k=top_k, mesh=mesh, device=device,
            handoff=(handoff or prefill_only), swap=offload,
            gather_impl=gather_impl, kv_dtype=kv_dtype,
            prefix_cache=prefix_cache, split_s=split_s,
            autotune_dir=autotune_dir,
        )
        # ---- prefix-sharing tier (round 17): radix reuse + COW ----
        self.prefix_cache = prefix_cache
        self._prefix_covered_tokens = 0
        # prompt tokens actually chunk-prefilled at admission (prefix
        # hits subtract their covered prefix) — the A/B's headline
        self._admitted_prefill_tokens = 0
        # ---- pressure tier (round 13): host offload + preemption ----
        self.offload = offload
        self.preempt_on_oom = preempt_on_oom
        self.swap_policy = swap_policy
        self.protect_ticks = protect_ticks
        self.host_store = (
            host_store if host_store is not None
            else HostBlockStore(max_bytes=host_store_max_bytes)
        )
        # rid -> (request, restore path): preempted requests awaiting
        # restore, FIFO (dict preserves insertion order)
        self.parked: Dict[int, Tuple[Request, str]] = {}
        # swap-outs whose d2h window is open: finalized at the top of
        # the next step() (and by begin_drain) — the real cross-tick
        # swapping-out state
        self._swapping: List[tuple] = []
        # slots whose chain is mid-swap-out: not reusable until finish
        self._swap_slots: set = set()
        self._preempts = 0
        self._restores = 0
        self._swap_outs = 0
        self._swap_ins = 0
        self._swap_aborts = 0
        self._swap_bytes = 0
        self._decision_swap = 0
        self._decision_recompute = 0
        self._oom_preempted_for: Optional[int] = None
        self.swap_lat = LatencySeries("swap")
        # the engine may have replaced gather_impl= into the config —
        # read back its copy so scheduler and programs agree
        self.config = self.engine.config
        self.n_slots = n_slots
        self.admit_per_step = admit_per_step
        self.eos_id = eos_id
        self.replica_id = replica_id
        self.prefill_only = prefill_only
        self.draining = False
        # prefill_only: requests whose prefill finished and are waiting
        # for the fleet router to hand their KV blocks to a decode
        # replica (rid -> the slot HERE holding them; slot + blocks stay
        # held until complete_handoff. The slot is recorded on this side
        # because adoption re-points req.slot at the decode replica's
        # slot — trusting it afterwards would free someone else's slot)
        self.ready: Dict[int, int] = {}
        self._handoffs = 0
        self._adopted = 0
        self._rng = jax.random.key(seed)
        self._next_rid = 0
        self._step_count = 0
        self.queue: deque = deque()
        self.resident: Dict[int, Request] = {}  # slot -> request
        self.positions = np.zeros(n_slots, np.int32)
        self.remaining = np.zeros(n_slots, np.int32)
        # ---- exact host-side metric counters ----
        self._tokens_out = 0
        self._completed = 0
        self._admitted = 0
        self._adm_latency_steps = 0
        self._adm_latency_s = 0.0
        self._occupancy_sum = 0.0  # mean-able over steps
        self._start_time: Optional[float] = None
        # ---- latency series (telemetry/latency.py; exact, host-side) ----
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self.metrics_log = metrics_log
        self.ttft = LatencySeries("ttft")
        # warm-only TTFT: requests whose lifetime saw no compile stall —
        # the honest SLO series (cold first-bucket requests excluded)
        self.ttft_warm = LatencySeries("ttft_warm")
        self.token_lat = LatencySeries("token_lat")
        self.queue_wait = LatencySeries("queue_wait")
        # wall cost of THIS replica's own step() on ticks that delivered
        # tokens — the replica-attributed token latency. In the fleet's
        # one-loop simulation the gap between two tokens includes every
        # OTHER replica's step too; this series is what the stream pays
        # on ITS replica (chunk-program interference included for mixed
        # replicas, excluded for pure-decode ones) — the disaggregation
        # A/B's honest metric (ANALYSIS.md "Serving fleet").
        self.tick_lat = LatencySeries("tick")
        self._cold_requests = 0
        # wall-time ledger: serving attributes its compile stalls (lazy
        # first-bucket compiles AND warmup compile time) so cold-vs-warm
        # starts compare on one number — goodput compile fraction
        self.goodput = GoodputLedger()
        self.goodput.start()
        # ---- attribution & forensics (ISSUE 8) ----
        # per-program measured wall for the cost-card join: the chunk
        # program of each tick's bucket, and the decode tick (whose
        # tokens materialize inside engine.decode, so its wall is honest
        # device+sync time, not bare dispatch)
        self.prog_times = ProgramTimes()
        self.flightrec = flightrec if flightrec is not None else NULL_RECORDER
        # ---- request-lifecycle tracing (round 14; telemetry/reqtrace) ----
        # rid-keyed span trees across every owner; the kv-transition
        # adapter below annotates block alloc/free/swap-state changes
        # with chain identity by mapping the allocator's owner slot back
        # to the resident rid
        self.reqtrace = reqtrace if reqtrace is not None else NULL_REQTRACER
        self._slot2rid: Dict[int, int] = {}
        if self.reqtrace.enabled:
            self.engine.set_kv_trace(self._kv_transition)
        # ---- block-lifecycle sanitizer (analysis.blocksan; round 18) ----
        # PDT_BLOCKSAN=1 installs a shadow ledger on the allocator; a
        # fleet router passes ONE sanitizer shared across replicas so
        # handoff pins and violations aggregate. Off (the default) this
        # is None end to end — the allocator hot path pays a single
        # attribute test per op.
        if blocksan is None:
            from pytorch_distributed_tpu.analysis.blocksan import (
                maybe_sanitizer,
            )
            blocksan = maybe_sanitizer(metrics_log=metrics_log,
                                       replica_id=replica_id)
        self.blocksan = blocksan
        self._san = (
            blocksan.attach(self.engine.allocator,
                            name=f"replica{replica_id}",
                            resolve_rid=self._slot2rid.get)
            if blocksan is not None else None
        )
        self._cancelled = 0
        self._deadline_misses = 0
        # host–device overlap ledger (round 15; telemetry/overlap.py):
        # the engine reports every compiled launch through it, and the
        # host marks below (admission, JSONL emit, swap decision) are
        # the attribution targets its bubble classifier resolves to
        self.ledger = ledger if ledger is not None else NULL_LEDGER
        self.engine.ledger = self.ledger
        self.engine.ledger_replica = replica_id
        # ---- async host runtime (round 16) ----
        # the dispatched-but-uncollected tick (main-thread-only state:
        # only dispatch_tick/collect_tick and the early-collect hooks
        # in preempt/begin_drain touch it)
        self._pending_tick: Optional[TickHandle] = None
        # tokens collected outside the router's collect phase (an early
        # collect forced by preempt/drain) — delivered at the next
        # collect_tick so no token is ever dropped or double-delivered
        self._collected: List[Tuple[int, int]] = []
        # optional worker pool (serving.host_worker.HostWorkerPool):
        # per-request JSONL emission and the gate-metrics percentile
        # math run there; everything a worker touches is either
        # self-locked (logger/tracer/ledger), copied at enqueue, or the
        # snapshot below under its dedicated lock
        self.host_pool = host_pool
        self._gate_cache: Optional[dict] = None
        self._gate_lock = threading.Lock()
        #: ticks between gate-snapshot refreshes. Refreshing every
        #: collect measurably drags the loop (one task + two list
        #: copies per tick); the gate's percentile rungs tolerate
        #: staleness by design — the depth-bound rungs ride the LIVE
        #: overlays in gate_metrics and never go stale at all.
        self.gate_refresh_ticks = 32
        self._gate_refreshed_step = -(10**9)
        # batched sentinel feed (async mode): per-tick observations
        # buffer here (main thread) and ship to a worker as ONE task
        # per batch — a task per tick measurably dragged the loop
        # (queue hop + GIL churn ~2x/tick)
        self._tick_obs: List[Tuple[float, float, int]] = []
        self.tick_obs_batch = 32
        # anomaly sentinel over tick time / TTFT / queue depth; a recent
        # hit surfaces as metrics()["anomaly_recent"], which the fleet
        # SLOGate reads as a hot signal (spill around this replica)
        self.sentinel = (
            AnomalySentinel(
                threshold=anomaly_threshold, metrics_log=metrics_log,
                flightrec=self.flightrec, source=f"replica{replica_id}",
            )
            if anomaly_threshold and anomaly_threshold > 0 else None
        )
        self._last_anomaly_step = None
        #: ticks an anomaly stays "recent" for the SLO gate's hot signal
        self.anomaly_recent_ticks = 64
        if self.sentinel is not None:
            # scale floors: a detector over a near-constant series would
            # otherwise flag routine jitter (MAD ≈ 0 → any blip is ∞σ).
            # Time series floor at 10 ms — a stall must clear
            # threshold × 10 ms above baseline; queue depth floors at one
            # whole request.
            self.sentinel.detector("tick_time").abs_floor = 0.01
            self.sentinel.detector("ttft").abs_floor = 0.01
            self.sentinel.detector("queue_depth").abs_floor = 1.0
        # round 21 (scale observatory): optional retire hook,
        # ``on_retire(rid, outcome)``, fired on the main thread when a
        # request leaves the scheduler for good (complete / cancel /
        # deadline). The fleet router uses it to drop per-rid
        # bookkeeping in streaming-retention mode.
        self.on_retire: Optional[Callable[[int, str], None]] = None

    # ---- API ----

    def warmup(self, background: bool = True):
        """Compile every program this scheduler can ever run, BEFORE
        traffic (compilecache/: ANALYSIS.md "Cold start & compile cache").

        The decode tick and the smallest prefill bucket compile (and
        execute inert) in the foreground — serving can start the moment
        this returns, with the serve-critical path hot; the remaining
        buckets AOT-compile on a background thread into the persistent
        compilation cache. ``background=False`` compiles everything in
        the foreground with inert execution: zero cold requests, the
        strongest guarantee, at full upfront cost.

        Warmup compile time lands in the ledger's ``compile`` category
        and each program emits a ``kind="warmup"`` manifest record to
        ``metrics_log`` — so a cold start (fresh cache) and a warm start
        (populated cache) compare on the goodput compile fraction.
        Returns the ``WarmupRunner`` (``.wait()`` joins the background
        thread; ``.summary()`` aggregates the manifest).
        """
        from pytorch_distributed_tpu.compilecache import (
            WarmupRunner,
            serving_registry,
        )

        runner = WarmupRunner(
            serving_registry(self.engine),
            tracer=self.tracer,
            ledger=self.goodput,
            manifest=self.metrics_log,
        )
        return runner.run(background=background)

    def _kv_transition(self, event: str, owner: int, info: dict) -> None:
        """``BlockAllocator.on_transition`` adapter: chain transitions
        (alloc/free/swap states) become ``kv_*`` events in the owning
        request's lifecycle trace. ``owner`` is a slot id; the adapter
        resolves it through ``_slot2rid`` (written just before each
        allocating call, cleared when the chain frees) — transitions on
        slots no request owns (warmup probes, teardown resets) are
        silently unattributable and dropped."""
        rid = self._slot2rid.get(owner)
        if rid is None:
            return
        self.reqtrace.event(
            rid, f"kv_{event}", replica=self.replica_id, slot=owner, **info
        )
        if event == "free":
            self._slot2rid.pop(owner, None)

    def submit(self, prompt: np.ndarray, max_new_tokens: int, *,
               session: Optional[int] = None, spilled: bool = False,
               rid: Optional[int] = None,
               deadline_s: Optional[float] = None,
               deadline: Optional[float] = None) -> int:
        """Enqueue one request; returns its request id. Never raises for
        capacity — only for requests no configuration could serve, and
        for submission into a draining replica (the router must not
        route here once ``begin_drain`` ran).

        ``session``/``spilled`` are fleet routing provenance stamped into
        the per-request JSONL; ``rid`` lets the fleet router allocate
        request ids from ONE fleet-wide space so a request keeps its id
        across replicas and the prefill→decode handoff.

        ``deadline_s`` (seconds from now) or ``deadline`` (an absolute
        ``time.perf_counter()`` instant — what the router passes on
        re-dispatch so the clock never resets) arms per-request
        expiry: the deadline sweep at the top of every ``dispatch_tick``
        expires the request through the cancel path with
        ``outcome="deadline"`` whatever state it is in."""
        if self.draining:
            raise RuntimeError(
                f"replica {self.replica_id} is draining; route elsewhere"
            )
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        l = len(prompt)
        if l < 1:
            raise ValueError("prompt must contain at least one token")
        c = self.engine.chunk
        padded = -(-l // c) * c
        if padded > self.config.max_seq_len:
            raise ValueError(
                f"prompt ({l}) padded to {padded} exceeds max_seq_len "
                f"{self.config.max_seq_len}"
            )
        if l + max_new_tokens > self.config.max_seq_len:
            raise ValueError(
                f"prompt ({l}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds max_seq_len {self.config.max_seq_len}"
            )
        if rid is None:
            rid = self._next_rid
            self._next_rid += 1
        else:
            self._next_rid = max(self._next_rid, rid + 1)
        now = time.perf_counter()
        if deadline is None:
            deadline = (now + deadline_s if deadline_s is not None
                        else float("inf"))
        req = Request(
            rid=rid, tokens=prompt, max_new_tokens=max_new_tokens,
            submit_step=self._step_count, submit_time=now,
            session=session, spilled=spilled, orig_len=l,
            generated=[] if self.offload else None,
            deadline=deadline,
        )
        if self.reqtrace.enabled:
            # standalone schedulers open the root here; under a fleet the
            # gate decision already did (open_root is idempotent) and
            # this just hangs the queue-wait span under it
            root = self.reqtrace.open_root(rid, prompt_len=l,
                                           session=session)
            req.span_queue = self.reqtrace.begin(
                rid, "queued", parent=root, replica=self.replica_id,
                max_new=max_new_tokens,
            )
        self.queue.append(req)
        return rid

    def _free_slots(self) -> List[int]:
        # a slot whose chain is mid-swap-out is NOT free: its table row
        # and allocator chain are still live until the swap finalizes
        return [s for s in range(self.n_slots)
                if s not in self.resident and s not in self._swap_slots]

    def _admit(self) -> None:
        """Admit up to ``admit_per_step`` queue-head requests that can be
        served now. Strict FIFO: the first request that cannot get a slot
        or a chain stops admission for this step."""
        if self.draining:
            return
        free = self._free_slots()
        admitted = 0
        now = time.perf_counter()
        while self.queue and free and admitted < self.admit_per_step:
            req = self.queue[0]
            slot = free[0]
            # kv-trace attribution BEFORE the allocating call: the alloc
            # transition fires inside engine.admit and must resolve to
            # this rid (popped right back on the OOM path)
            self._slot2rid[slot] = req.rid
            if self.prefix_cache:
                # shared-prefix admission: the longest indexed full-block
                # match rides shared blocks and only the uncovered tail
                # prefills (None = pool OOM, the same queue signal)
                hit = self.engine.admit_shared(
                    slot, req.tokens, req.max_new_tokens
                )
                admitted_ok = hit is not None
            else:
                hit = None
                admitted_ok = self.engine.admit(
                    slot, req.length, req.max_new_tokens
                )
            if not admitted_ok:
                self._slot2rid.pop(slot, None)
                # pool OOM: queue (blocks free as others retire). Under
                # pressure mode, first preempt one LRU victim — its
                # blocks free now (recompute) or next tick (swap), so
                # capacity turns over instead of waiting on a retire.
                # ONE preemption per stuck queue head: restores outrank
                # admissions (strict arrival order — a parked request is
                # older than the queue head), so preempting every tick
                # would only carousel chains through the host store;
                # one boost per head keeps the pressure valve open
                # without the thrash.
                if (self.preempt_on_oom
                        and not self.parked and not self._swapping
                        and self._oom_preempted_for != req.rid):
                    if self.preempt_lru(reason="admission-oom") is not None:
                        self._oom_preempted_for = req.rid
                break
            self.queue.popleft()
            free.pop(0)
            req.slot = slot
            req.admit_step = self._step_count
            req.admit_time = now
            # prefix hit: prefill resumes AT the covered frontier — only
            # the uncovered tail runs through the chunk programs
            req.prefill_done = hit.covered if hit is not None else 0
            self.resident[slot] = req
            self.positions[slot] = 0
            self.remaining[slot] = 0  # decode-armed after the last chunk
            self._admitted += 1
            self._adm_latency_steps += self._step_count - req.submit_step
            self._adm_latency_s += now - req.submit_time
            self.queue_wait.observe(now - req.submit_time)
            self._admitted_prefill_tokens += req.length - req.prefill_done
            if hit is not None:
                self._prefix_covered_tokens += hit.covered
                self._log_prefix(req, hit)
            self.flightrec.record(
                "admit", rid=req.rid, slot=slot, replica=self.replica_id
            )
            if self.reqtrace.enabled:
                self.reqtrace.end(
                    req.span_queue, slot=slot,
                    queue_wait_s=round(now - req.submit_time, 6),
                )
                req.span_queue = 0
                req.span_prefill = self.reqtrace.begin(
                    req.rid, "prefill", replica=self.replica_id,
                    slot=slot,
                    chunks=-(-(req.length - req.prefill_done)
                             // self.engine.chunk),
                    prefix_covered=req.prefill_done or None,
                )
            admitted += 1

    # ---- pressure tier: preempt, park, restore (round 13) ----------------

    def _victims(self) -> List[Tuple[float, int, int]]:
        """Eligible preemption victims, LRU-idle first: decode-armed
        resident requests (mid-prefill chains and handoff-parked
        ``ready`` requests are not preemptible), outside their post-
        restore protection window, not already mid-swap. Sorted by last
        token wall time (admit time for lanes yet to produce) so the
        stream that has gone longest without a token — the idlest
        conversation — pays first."""
        if not self.offload:
            return []
        import math as _math

        out = []
        for slot, req in self.resident.items():
            if req.prefill_done < req.length or slot in self._swap_slots:
                continue
            if req.rid in self.ready:
                continue  # held for fleet handoff, not ours to park
            if self._step_count < req.protect_until:
                continue
            last = req.last_token_time
            if _math.isnan(last):
                last = req.admit_time
            out.append((last, req.rid, slot))
        out.sort()
        return out

    def _swap_decision(self, req: Request, slot: int):
        """The per-request swap-vs-recompute verdict: the chain's bytes
        through the measured link vs the resume-prefill's chunks times
        the chunk program's measured wall (``telemetry.costmodel``),
        then the hard constraints — a resume sequence the table cannot
        hold forces swap, a host store without room forces recompute.
        Returns None when neither path is viable (the request is simply
        not preemptible right now)."""
        import dataclasses as _dc

        from pytorch_distributed_tpu.telemetry.costmodel import (
            swap_vs_recompute,
        )

        chain_len = len(self.engine.allocator.chain(slot))
        bytes_to_move = self.engine.chain_bytes(chain_len)
        seq_len = req.length + len(req.generated or ())
        c = self.engine.chunk
        chunks = -(-seq_len // c)
        # the chunk program a recompute would run: the measured mean
        # wall of any hot chunk bucket (the cost-card join side —
        # buckets differ by padding, not asymptotics; None when nothing
        # has measured yet and the decision falls to its default)
        chunk_wall = None
        for prog, (n, s) in self.prog_times.items():
            if prog.startswith("chunk_prefill[") and n > 0:
                chunk_wall = s / n
                break
        decision = swap_vs_recompute(
            bytes_to_move, chunks=chunks, chunk_wall_s=chunk_wall,
        )
        if self.swap_policy != "auto":
            decision = _dc.replace(decision, choice=self.swap_policy,
                                   reason=f"forced-{self.swap_policy}")
        # hard constraints override the cost verdict
        padded = -(-seq_len // c) * c
        need = self.engine.blocks_for(seq_len,
                                      req.max_new_tokens - req.produced)
        can_recompute = (
            padded <= self.config.max_seq_len
            and need <= min(self.engine.table_width,
                            self.engine.allocator.n_blocks - 1)
        )
        store_ok = self.host_store.has_room(bytes_to_move)
        if decision.choice == "recompute" and not can_recompute:
            decision = _dc.replace(decision, choice="swap",
                                   reason="recompute-overflows-table")
        elif decision.choice == "swap" and not store_ok:
            if not can_recompute:
                return None
            decision = _dc.replace(decision, choice="recompute",
                                   reason="host-store-full")
        return decision

    def preempt_lru(self, reason: str = "pressure") -> Optional[int]:
        """Preempt the least-recently-served eligible victim; returns
        its rid (None when nothing is preemptible — the caller's cue
        that shedding really is the last resort)."""
        # async host loop: an in-flight tick may be decoding the victim
        # — collect it first so the victim's produced/generated state is
        # current and its chain release cannot race the launched program
        self._collect_pending_tick()
        for _, rid, _slot in self._victims():
            if self.preempt(rid, reason=reason) is not None:
                return rid
        return None

    def preempt(self, rid: int, reason: str = "pressure"):
        """Park request ``rid``: its decision picks swap (chain leaves
        for the host store through the compiled gather + d2h) or
        recompute (chain dropped now, the stream's tokens re-prefill as
        prompt at restore). Either way the lane stops decoding THIS tick
        and the request is restored — before its next decode — by
        ``_restore_parked`` once capacity allows. Returns the
        ``SwapDecision`` (None when the request is not preemptible)."""
        # same in-flight hazard as preempt_lru (direct callers exist)
        self._collect_pending_tick()
        slot = next(
            (s for s, r in self.resident.items() if r.rid == rid), None
        )
        if slot is None:
            raise ValueError(f"rid {rid} is not resident")
        req = self.resident[slot]
        if req.prefill_done < req.length:
            raise ValueError(f"rid {rid} is mid-prefill: not preemptible")
        with self.ledger.host("swap-decision", self.replica_id):
            decision = self._swap_decision(req, slot)
        if decision is None:
            return None
        if self.reqtrace.enabled:
            # the preempt sub-tree: the open decode window ends here
            # (outcome=preempted) and everything until the restore —
            # swap_out, parked, swap_in — nests under this span, with
            # the decision's predicted costs attached for the
            # predicted-vs-measured join
            self.reqtrace.end(req.span_decode, outcome="preempted")
            req.span_decode = 0
            req.span_preempt = self.reqtrace.begin(
                rid, "preempt", replica=self.replica_id, reason=reason,
                decision=decision.choice,
                decision_reason=decision.reason,
                predicted_swap_s=decision.swap_s,
                predicted_recompute_s=decision.recompute_s,
                bytes=decision.bytes_to_move, chunks=decision.chunks,
            )
        if decision.choice == "recompute":
            del self.resident[slot]
            self.remaining[slot] = 0
            self.engine.release(slot)
            self.parked[rid] = (req, "recompute")
            self._decision_recompute += 1
            if self.reqtrace.enabled:
                req.span_parked = self.reqtrace.begin(
                    rid, "parked", parent=req.span_preempt,
                    replica=self.replica_id, path="recompute",
                )
        else:
            if self.reqtrace.enabled:
                req.span_swap = self.reqtrace.begin(
                    rid, "swap_out", parent=req.span_preempt,
                    replica=self.replica_id,
                )
            pending = self.engine.swap_out_begin(slot)  # jaxlint: disable=lifecycle-span-imbalance -- cross-tick window protocol: the span closes in _finalize_swaps at the top of the next step() (and in begin_drain), never in this function; _swap_slots tracks the open window meanwhile
            del self.resident[slot]
            self.remaining[slot] = 0
            self._swap_slots.add(slot)
            self._swapping.append(
                (rid, req, pending, time.perf_counter(), decision)
            )
            self._decision_swap += 1
        req.preempts += 1
        self._preempts += 1
        self.flightrec.record(
            "preempt", rid=rid, slot=slot, reason=reason,
            decision=decision.choice, replica=self.replica_id,
        )
        if self.metrics_log is not None:
            self.metrics_log.log(
                kind="preempt", rid=rid, replica_id=self.replica_id,
                reason=reason, decision=decision.choice,
                decision_reason=decision.reason,
                predicted_swap_s=decision.swap_s,
                predicted_recompute_s=decision.recompute_s,
                bytes=decision.bytes_to_move, chunks=decision.chunks,
                produced=req.produced, queue_depth=len(self.queue),
            )
        return decision

    def _finalize_swaps(self) -> None:
        """Close every open swap-out window: materialize the d2h copy,
        commit the host chain, free the device chain. A failure at
        either hazard site (``kv.swap_out_d2h``, ``kv.host_write``)
        REVERTS the preemption — the chain never left, so the lane is
        re-armed and the stream continues bit-exact."""
        if not self._swapping:
            return
        pending, self._swapping = self._swapping, []
        for rid, req, pend, t0, decision in pending:
            slot = pend.slot
            try:
                chain = self.engine.swap_out_finish(
                    pend, self.host_store, rid
                )
            except OSError as e:
                # revert: chain untouched on device; re-arm the lane
                self.resident[slot] = req
                self.remaining[slot] = req.max_new_tokens - req.produced
                self._swap_slots.discard(slot)
                self._swap_aborts += 1
                if self.reqtrace.enabled:
                    self.reqtrace.end(req.span_swap, ok=False,
                                      error=str(e))
                    req.span_swap = 0
                    self.reqtrace.end(req.span_preempt, outcome="aborted")
                    req.span_preempt = 0
                    # reverted == decoding again: a fresh decode window
                    req.span_decode = self.reqtrace.begin(
                        rid, "decode", replica=self.replica_id, lane=slot,
                        resumed="swap-abort",
                    )
                self.flightrec.record(
                    "swap_abort", rid=rid, direction="out", error=str(e),
                    replica=self.replica_id,
                )
                if self.metrics_log is not None:
                    self.metrics_log.log(
                        kind="swap", rid=rid, replica_id=self.replica_id,
                        direction="out", ok=False, error=str(e),
                    )
                continue
            wall = time.perf_counter() - t0
            self._swap_slots.discard(slot)
            self.parked[rid] = (req, "swap")
            self._swap_outs += 1
            self._swap_bytes += chain.nbytes
            self.swap_lat.observe(wall)
            if self.reqtrace.enabled:
                # predicted next to measured: the decision audit trail
                self.reqtrace.end(
                    req.span_swap, ok=True, bytes=chain.nbytes,
                    wall_s=round(wall, 6),
                    predicted_s=decision.swap_s,
                )
                req.span_swap = 0
                req.span_parked = self.reqtrace.begin(
                    rid, "parked", parent=req.span_preempt,
                    replica=self.replica_id, path="swap",
                )
            self.flightrec.record(
                "swap", rid=rid, direction="out", bytes=chain.nbytes,
                replica=self.replica_id,
            )
            if self.metrics_log is not None:
                self.metrics_log.log(
                    kind="swap", rid=rid, replica_id=self.replica_id,
                    direction="out", ok=True, bytes=chain.nbytes,
                    wall_s=round(wall, 6),
                    predicted_s=decision.swap_s,
                )

    def _restore_parked(self) -> None:
        """Restore parked requests FIFO, before this tick's admissions
        (a preempted request outranks a queued one — it already earned
        its admission). Swap path: fresh chain + h2d + donated scatter,
        lane re-armed at its exact frontier. Recompute path: the
        stream's tokens join the prompt and the request re-prefills —
        the final chunk's logits row reproduces the exact next-token
        distribution, so greedy streams resume token-identical either
        way. A restore that cannot proceed (no slot, no chain, injected
        h2d fault) leaves the request parked and retries next tick."""
        for rid in list(self.parked):
            req, path = self.parked[rid]
            free = self._free_slots()
            if not free:
                break
            slot = free[0]
            t0 = time.perf_counter()
            if path == "swap":
                chain = self.host_store.get(rid)
                self._slot2rid[slot] = rid
                try:
                    if not self.engine.swap_in_chain(slot, chain):
                        self._slot2rid.pop(slot, None)
                        break  # no chain free: retry when blocks return
                except OSError as e:
                    self._slot2rid.pop(slot, None)
                    self._swap_aborts += 1
                    if self.reqtrace.enabled:
                        self.reqtrace.event(
                            rid, "swap_abort", parent=req.span_preempt,
                            replica=self.replica_id, direction="in",
                            error=str(e),
                        )
                    self.flightrec.record(
                        "swap_abort", rid=rid, direction="in",
                        error=str(e), replica=self.replica_id,
                    )
                    if self.metrics_log is not None:
                        self.metrics_log.log(
                            kind="swap", rid=rid,
                            replica_id=self.replica_id,
                            direction="in", ok=False, error=str(e),
                        )
                    break  # host copy intact; retry next tick
                self.host_store.pop(rid)
                wall = time.perf_counter() - t0
                self._swap_ins += 1
                self._swap_bytes += chain.nbytes
                self.swap_lat.observe(wall)
                if self.metrics_log is not None:
                    self.metrics_log.log(
                        kind="swap", rid=rid, replica_id=self.replica_id,
                        direction="in", ok=True, bytes=chain.nbytes,
                        wall_s=round(wall, 6),
                    )
                del self.parked[rid]
                req.slot = slot
                self.resident[slot] = req
                self.positions[slot] = req.length + req.produced
                self.remaining[slot] = req.max_new_tokens - req.produced
                if self.reqtrace.enabled:
                    span_in = self.reqtrace.begin(
                        rid, "swap_in", parent=req.span_preempt,
                        replica=self.replica_id, t=t0,
                    )
                    self.reqtrace.end(span_in, ok=True,
                                      bytes=chain.nbytes,
                                      wall_s=round(wall, 6))
                    req.span_decode = self.reqtrace.begin(
                        rid, "decode", replica=self.replica_id,
                        lane=slot, resumed="swap",
                    )
            else:  # recompute: generated tokens re-prefill as prompt
                seq = req.tokens
                if req.generated:
                    seq = np.concatenate([
                        req.tokens,
                        np.asarray(req.generated, np.int32),
                    ])
                self._slot2rid[slot] = rid
                if self.prefix_cache:
                    # the restore's re-prefill consults the index too: a
                    # request whose own prompt blocks are still retained
                    # re-prefills only its generated tail — recompute
                    # preemption gets cheaper with the cache on
                    hit = self.engine.admit_shared(
                        slot, seq, req.max_new_tokens - req.produced
                    )
                    restored_ok = hit is not None
                else:
                    hit = None
                    restored_ok = self.engine.admit(
                        slot, len(seq), req.max_new_tokens - req.produced
                    )
                if not restored_ok:
                    self._slot2rid.pop(slot, None)
                    break  # pool OOM: retry when blocks return
                del self.parked[rid]
                req.tokens = seq
                req.generated = []  # consumed into the prompt
                req.prefill_done = hit.covered if hit is not None else 0
                if hit is not None:
                    self._prefix_covered_tokens += hit.covered
                    self._log_prefix(req, hit)
                self._admitted_prefill_tokens += (
                    req.length - req.prefill_done
                )
                req.slot = slot
                self.resident[slot] = req
                self.positions[slot] = 0
                self.remaining[slot] = 0  # armed by its final chunk
                if self.reqtrace.enabled:
                    req.span_prefill = self.reqtrace.begin(
                        rid, "prefill", replica=self.replica_id,
                        slot=slot, resumed="recompute",
                        chunks=-(-(len(seq) - req.prefill_done)
                                 // self.engine.chunk),
                        prefix_covered=req.prefill_done or None,
                    )
            req.protect_until = self._step_count + self.protect_ticks
            self._restores += 1
            if self.reqtrace.enabled:
                self.reqtrace.end(req.span_parked)
                req.span_parked = 0
                self.reqtrace.event(
                    rid, "restore", parent=req.span_preempt,
                    replica=self.replica_id, slot=slot, path=path,
                )
                self.reqtrace.end(req.span_preempt)
                req.span_preempt = 0
            self.flightrec.record(
                "restore", rid=rid, slot=slot, path=path,
                replica=self.replica_id,
            )

    def _chunk_jobs(self):
        from pytorch_distributed_tpu.serving.engine import ChunkJob

        c = self.engine.chunk
        jobs = []
        for slot, req in sorted(self.resident.items()):
            if req.prefill_done >= req.length:
                continue
            start = req.prefill_done
            seg = req.tokens[start:start + c]
            tokens = np.zeros((c,), np.int32)
            tokens[:len(seg)] = seg
            is_last = start + c >= req.length
            jobs.append(ChunkJob(
                slot=slot, tokens=tokens, start=start, is_last=is_last,
                last_idx=(req.length - 1 - start) if is_last else 0,
            ))
        return jobs

    def dispatch_tick(self, sync: bool = False) -> None:
        """The non-blocking half of one tick: restores/admissions → one
        prefill chunk per unfinished prompt (ONE compiled program) →
        the decode program LAUNCHED (not materialized). Parks a
        ``TickHandle`` for ``collect_tick``. ``sync=True`` (the
        synchronous loop, via ``step``) materializes the tokens inside
        the launch window instead — the historical exact-completion
        ledger anchor."""
        if self._pending_tick is not None:
            raise RuntimeError(
                "collect_tick() must drain the pending tick before "
                "another dispatch (one tick in flight per replica)"
            )
        # replica-death site: before ANY tick work, so a fault here
        # leaves the resident set exactly as the last collect left it —
        # the state the router's harvest/re-dispatch path must recover
        fault_point("serve.dispatch")
        if self._start_time is None:
            self._start_time = time.perf_counter()
        t_step0 = time.perf_counter()
        self._expire_deadlines()
        if self.offload:
            # pressure tier: close last tick's swap-out windows (their
            # blocks return to the pool), then restore parked requests
            # BEFORE admitting new ones — a preempted request resumes
            # ahead of the queue, before its next decode tick
            self._finalize_swaps()
            self._restore_parked()
        with self.tracer.span("admission", queued=len(self.queue)), \
                self.ledger.host("admission/gate", self.replica_id):
            self._admit()
        jobs = self._chunk_jobs()
        if jobs:
            # cold bucket: this batch's (k_pad, wp) program has never
            # executed — the call below stalls for its compile (or a
            # persistent-cache load after an AOT-only warmup). Mark every
            # request riding the batch and book the stall as compile time.
            bucket = self.engine.bucket_for(jobs)
            cold_bucket = not self.engine.has_chunk_program(*bucket)
            if cold_bucket:
                for j in jobs:
                    self.resident[j.slot].cold = True
            t_chunk = time.perf_counter()
            with self.tracer.span("prefill_chunk", jobs=len(jobs)), \
                    attribute_compile(self.goodput if cold_bucket
                                      else None):
                self.engine.run_chunks(jobs)
            if not cold_bucket:
                # cost-card join: warm dispatch wall attributed to THIS
                # bucket's program (cold calls excluded — their wall is
                # compile, already booked to the ledger above)
                self.prog_times.observe(
                    self.engine.chunk_program_name(*bucket),
                    time.perf_counter() - t_chunk,
                )
            for j in jobs:
                req = self.resident[j.slot]
                if self.reqtrace.enabled:
                    self.reqtrace.event(
                        req.rid, "prefill_chunk",
                        parent=req.span_prefill,
                        replica=self.replica_id, start=j.start,
                        program=self.engine.chunk_program_name(*bucket),
                        cold=cold_bucket or None,
                    )
                req.prefill_done += self.engine.chunk
                if self.prefix_cache:
                    # insert on block-boundary fill: every full PROMPT
                    # block the chunk just completed becomes index-
                    # reachable NOW, so a same-prefix request later in
                    # this very burst hits before this one retires.
                    # Decode-written blocks stay un-indexed — only
                    # prefill-computed KV is proven token-stable
                    # (ANALYSIS.md "Prefix sharing & copy-on-write")
                    self.engine.prefix_insert(
                        j.slot, req.tokens,
                        upto=min(req.prefill_done, req.length),
                    )
                if req.prefill_done >= req.length:
                    # prefill complete: arm the decode lane at the
                    # prompt's true frontier — or, on a prefill-only
                    # replica, park the request (blocks + slot held) in
                    # ``ready`` for the router's decode handoff
                    self.positions[j.slot] = req.length
                    if self.reqtrace.enabled:
                        self.reqtrace.end(req.span_prefill)
                        req.span_prefill = 0
                    if self.prefill_only:
                        self.ready[req.rid] = j.slot
                        if self._san is not None:
                            # the chain is promised to a decode replica:
                            # freeing it before complete_handoff is a
                            # pinned-block violation only the sanitizer
                            # can see (the allocator has no pin notion)
                            self._san.pin(j.slot, "handoff")
                        if self.reqtrace.enabled:
                            req.span_ready = self.reqtrace.begin(
                                req.rid, "handoff_wait",
                                replica=self.replica_id,
                            )
                    else:
                        # produced > 0 only after a recompute restore:
                        # the re-prefilled stream resumes what is left
                        # of its original decode budget
                        self.remaining[j.slot] = (
                            req.max_new_tokens - req.produced
                        )
                        if self.reqtrace.enabled:
                            req.span_decode = self.reqtrace.begin(
                                req.rid, "decode",
                                replica=self.replica_id, lane=j.slot,
                            )
        active = self.remaining > 0
        self._occupancy_sum += len(self.resident) / self.n_slots
        self._step_count += 1
        if not active.any():
            self._pending_tick = TickHandle(
                None, None, None, (), t_step0, t_step0, False, sync,
            )
            return
        if self.engine.temperature == 0.0:
            # greedy: _sample is a pure argmax and never reads the key
            # — the per-tick threefry split was ~14% of the serve
            # loop's host wall (round-16 profile) spent preparing an
            # unused input. The key still rides along (same program
            # signature, zero recompiles); sampled runs split as ever.
            sub = self._rng
        else:
            with self.ledger.host("sampling-prep", self.replica_id):
                # sampling-param prep: the per-tick key split (host-side
                # dispatch of a tiny program) — marked so its share of
                # any bubble is attributable
                self._rng, sub = jax.random.split(self._rng)
        cold_decode = not self.engine.has_decode_program
        if cold_decode:
            # every active lane's token this tick arrives through the
            # decode program's first compile — those requests are cold
            for slot in np.nonzero(active)[0]:
                self.resident[int(slot)].cold = True
        t_dec = time.perf_counter()
        with self.tracer.span("decode_tick", lanes=int(active.sum())), \
                attribute_compile(self.goodput if cold_decode else None):
            if sync:
                tokens, positions = self.engine.decode(
                    self.positions, active, sub
                )
                launch = None
            else:
                tokens, positions, launch = self.engine.decode_launch(
                    self.positions, active, sub
                )
        lanes = tuple(int(s) for s in np.nonzero(active)[0])
        self._pending_tick = TickHandle(
            tokens, positions, launch, lanes, t_step0, t_dec,
            cold_decode, sync,
        )

    def collect_tick(self) -> List[Tuple[int, int]]:
        """The blocking half: materialize the pending tick's tokens and
        run all per-token host work (TTFT/latency series, retirement,
        JSONL). Returns ``[(rid, token)]`` — including anything an
        early collect (preempt/drain) stashed since the last call.
        No-op without a pending tick."""
        # replica-death site: the tick's device tokens are lost with the
        # replica (the router-facing collect only — the early collects
        # inside preempt/cancel/drain are the same process surviving)
        fault_point("serve.collect")
        self._collect_pending_tick()
        out, self._collected = self._collected, []
        return out

    @property
    def has_uncollected(self) -> bool:
        """True while a token-bearing tick is in flight or collected
        tokens await delivery — the router's drain loop must keep
        stepping (``idle`` alone reads host state, which a pending tick
        is about to change)."""
        h = self._pending_tick
        return bool(self._collected) or (
            h is not None and h.tokens is not None
        )

    def _collect_pending_tick(self) -> None:
        h = self._pending_tick
        if h is None:
            return
        self._pending_tick = None
        if h.tokens is None:
            self._observe_tick(h.t_step0)
            return
        if h.sync:
            tokens, positions = h.tokens, np.array(h.positions)
        else:
            tokens, positions = self.engine.decode_collect(
                h.tokens, h.positions, h.launch
            )
        # write back ONLY the lanes this tick decoded: rows the host
        # armed since the launch (an adopted handoff chain, a restored
        # swap) must not be clobbered by the device's frozen copies
        lanes = np.asarray(h.lanes, np.int64)
        self.positions[lanes] = positions[lanes]
        # tokens materialized above, so this timestamp is
        # token-delivery time, not dispatch time
        now = time.perf_counter()
        if not h.cold_decode:
            # cost-card join: dispatch + device + sync — the honest
            # decode-tick cost (on the async path the sync lands here,
            # at collect, where the stream actually pays it)
            self.prog_times.observe(self.engine.DECODE_PROGRAM,
                                    now - h.t_dec)
        out: List[Tuple[int, int]] = []
        # collect-side host work under its own mark: the one-loop async
        # A/B needs "processing replica i's tokens" visible as a cause
        # when it serializes another replica's gap. Entered manually so
        # the 50-line loop below keeps its indentation; the finally at
        # the end of this method closes it on every path.
        collect_mark = self.ledger.host("tick-collect", self.replica_id)
        collect_mark.__enter__()
        try:
            self._process_collected(h, tokens, now, out)
        finally:
            collect_mark.__exit__(None, None, None)
        self._collected.extend(out)
        if (self.host_pool is not None and out
                and self._step_count - self._gate_refreshed_step
                >= self.gate_refresh_ticks):
            self._gate_refreshed_step = self._step_count
            self._queue_gate_refresh()

    def _process_collected(self, h: TickHandle, tokens, now: float,
                           out: List[Tuple[int, int]]) -> None:
        """Per-token host work for one collected tick: latency series,
        stream bookkeeping, retirement (slot + chain release), JSONL."""
        for slot in h.lanes:
            req = self.resident[slot]
            token = int(tokens[slot])
            out.append((req.rid, token))
            if req.produced == 0:
                req.first_token_time = now
                req.first_token_step = self._step_count
                self.ttft.observe(now - req.submit_time)
                if self.sentinel is not None and not req.cold:
                    # warm TTFT only: a cold request's compile stall is a
                    # known cause, already attributed — not an anomaly
                    self._note_anomaly(self.sentinel.observe(
                        "ttft", now - req.submit_time, rid=req.rid,
                        tick=self._step_count,
                    ))
                if not req.cold:
                    self.ttft_warm.observe(now - req.submit_time)
            else:
                gap = now - req.last_token_time
                req.token_gaps.append(gap)
                self.token_lat.observe(gap)
            req.last_token_time = now
            req.produced += 1
            if req.generated is not None:
                # offload mode keeps the stream so a recompute restore
                # can re-prefill it as prompt
                req.generated.append(token)
            self._tokens_out += 1
            if (self.eos_id is not None and token == self.eos_id) or \
                    req.produced >= req.max_new_tokens:
                self.remaining[slot] = 0
                del self.resident[slot]
                self.engine.release(slot)
                if self._san is not None:
                    self._san.check_retire(slot, rid=req.rid,
                                           site="retire")
                self._completed += 1
                if req.cold:
                    self._cold_requests += 1
                self.flightrec.record(
                    "retire", rid=req.rid, tokens=req.produced,
                    replica=self.replica_id,
                )
                if self.reqtrace.enabled:
                    self.reqtrace.end(req.span_decode,
                                      tokens=req.produced)
                    req.span_decode = 0
                    self.reqtrace.end(
                        self.reqtrace.root(req.rid),
                        outcome="complete", new_tokens=req.produced,
                        preempts=req.preempts or None,
                    )
                self._log_request(req)
                if self.on_retire is not None:
                    self.on_retire(req.rid, "complete")
            else:
                self.remaining[slot] -= 1
        if out:
            self.tick_lat.observe(now - h.t_step0)
        if self._san is not None:
            # use-after-free sweep: every id the decode program can read
            # next tick must be ledger-live (the trash row aside)
            from pytorch_distributed_tpu.serving.kv_pool import TRASH_BLOCK
            self._san.check_tables(self.engine.tables,
                                   trash_block=TRASH_BLOCK)
        self._observe_tick(h.t_step0)

    def step(self) -> List[Tuple[int, int]]:
        """One synchronous tick: dispatch + same-tick collect (the
        historical contract — admissions → one prefill chunk per
        unfinished prompt → one decode token per ready lane →
        retirements). Returns ``[(rid, token)]``. Any tick left pending
        by an async driver is collected first, so mode mixing never
        drops a token."""
        out = self.collect_tick()
        self.dispatch_tick(sync=True)
        return out + self.collect_tick()

    def _note_anomaly(self, hit: Optional[dict]) -> None:
        if hit is not None:
            self._last_anomaly_step = self._step_count

    def _observe_tick(self, t_step0: float) -> None:
        """Per-tick sentinel feed: tick wall and queue depth (every tick,
        both return paths of ``step``). With a host pool the median/MAD
        math (a measured ~15% of the serve loop's host wall) runs on a
        worker — the sentinel is internally locked, the fed values are
        captured here, and a hit latches ``_last_anomaly_step`` to the
        captured tick (a single int store; monotone-enough for the
        64-tick ``anomaly_recent`` window it feeds)."""
        if self.sentinel is None:
            return
        wall = time.perf_counter() - t_step0
        depth = float(len(self.queue))
        tick = self._step_count
        if self.host_pool is not None:
            self._tick_obs.append((wall, depth, tick))
            if len(self._tick_obs) >= self.tick_obs_batch:
                self.flush_host_work()
            return
        self._note_anomaly(self.sentinel.observe(
            "tick_time", wall, tick=tick,
        ))
        self._note_anomaly(self.sentinel.observe(
            "queue_depth", depth, tick=tick,
        ))

    def flush_host_work(self) -> None:
        """Ship the buffered per-tick sentinel observations to a worker
        as ONE task (in-order within the batch; a hit latches
        ``_last_anomaly_step`` to its tick — single int store, benign).
        The router calls this before its pool barrier so the tail of a
        drain is observed too. No-op without a pool or a buffer."""
        if self.host_pool is None or not self._tick_obs:
            return
        batch, self._tick_obs = self._tick_obs, []

        def work():
            with self.ledger.host("metrics-refresh", self.replica_id):
                last_hit = None
                for wall, depth, tick in batch:
                    h1 = self.sentinel.observe("tick_time", wall,
                                               tick=tick)
                    h2 = self.sentinel.observe("queue_depth", depth,
                                               tick=tick)
                    if h1 is not None or h2 is not None:
                        last_hit = tick
                if last_hit is not None:
                    self._last_anomaly_step = last_hit

        self.host_pool.submit(work)

    def _log_prefix(self, req: Request, hit) -> None:
        """One ``kind="prefix"`` JSONL record per shared-prefix
        admission (schema-registered; ``telemetry_report.py`` renders
        the hit-rate/covered-fraction section from these): what the
        index covered, how many blocks rode shared, and whether the
        boundary block was copy-on-write duplicated."""
        if self.metrics_log is None:
            return
        self.metrics_log.log(
            kind="prefix", rid=req.rid, replica_id=self.replica_id,
            prompt_len=req.length, covered=hit.covered,
            shared_blocks=hit.shared, cow=hit.cow,
            evicted=hit.evicted, session=req.session,
        )

    def _log_request(self, req: Request) -> None:
        """One ``kind="request"`` JSONL record per retirement — the raw
        per-request latencies ``telemetry_report.py`` aggregates. With a
        ``host_pool`` the serialization+write runs on a worker thread:
        a retired ``Request`` is never mutated again (it left
        ``resident`` in the same collect that enqueues this), so the
        closure captures an effectively-frozen object; the logger and
        ledger are self-locked."""
        if self.metrics_log is None:
            return
        if self.host_pool is not None:
            self.host_pool.submit(lambda: self._emit_request_record(req))
            return
        with self.ledger.host("jsonl-emit", self.replica_id):
            self._log_request_record(req)

    def _emit_request_record(self, req: Request) -> None:
        # worker-side: the ledger stamps the worker thread's name on
        # the mark, so classify_bubbles sees offloaded JSONL work as
        # "jsonl-emit@pdt-host-N", not idle-no-work
        with self.ledger.host("jsonl-emit", self.replica_id):
            self._log_request_record(req)

    def _log_request_record(self, req: Request) -> None:
        self.metrics_log.log(
            kind="request",
            rid=req.rid,
            replica_id=self.replica_id,
            rejected=False,
            session=req.session,
            spilled=req.spilled,
            prompt_len=req.orig_len if req.orig_len >= 0 else req.length,
            new_tokens=req.produced,
            preempts=req.preempts,
            cold=req.cold,
            queue_wait_s=round(req.admit_time - req.submit_time, 6),
            ttft_s=round(req.first_token_time - req.submit_time, 6),
            queue_wait_steps=req.admit_step - req.submit_step,
            ttft_steps=req.first_token_step - req.submit_step,
            token_gaps_s=[round(g, 6) for g in req.token_gaps],
        )

    @property
    def idle(self) -> bool:
        """Nothing queued, resident, parked, or mid-swap — the drain
        loops' (and the fleet router's) termination condition; a parked
        request is in-flight work, not absence of it."""
        return (not self.queue and not self.resident
                and not self.parked and not self._swapping)

    def stuck_rids(self) -> Dict[str, List[int]]:
        """Every in-flight rid by lifecycle state — the drain loops'
        non-convergence diagnostic (an empty dict == idle). A stuck
        drain that only reported counts forced a debugger session; the
        chaos matrix asserts on THIS surface instead."""
        out: Dict[str, List[int]] = {}
        if self.queue:
            out["queued"] = [r.rid for r in self.queue]
        prefill, decoding = [], []
        for req in self.resident.values():
            if req.rid in self.ready:
                continue
            (prefill if req.prefill_done < req.length
             else decoding).append(req.rid)
        if prefill:
            out["prefill"] = sorted(prefill)
        if decoding:
            out["decoding"] = sorted(decoding)
        if self.parked:
            out["parked"] = sorted(self.parked)
        if self._swapping:
            out["swapping"] = sorted(e[0] for e in self._swapping)
        if self.ready:
            out["handoff-ready"] = sorted(self.ready)
        return out

    def drain(self, max_steps: int = 100_000) -> Dict[int, List[int]]:
        """Step until queue and lanes are empty; returns
        ``{rid: [tokens]}``."""
        produced: Dict[int, List[int]] = {}
        for _ in range(max_steps):
            if self.idle:
                return produced
            for rid, tok in self.step():
                produced.setdefault(rid, []).append(tok)
        raise RuntimeError(
            f"drain did not converge within {max_steps} steps; "
            f"stuck rids by state: {self.stuck_rids()}"
        )

    # ---- graceful drain (fleet scale-down / replica removal) ----

    def begin_drain(self) -> None:
        """Stop admitting: ``submit`` raises, ``step`` skips admission.
        In-flight requests keep decoding to completion; the queue is
        frozen for ``drain_graceful`` to hand back to the router.

        Waits for in-flight swap-outs first (the drain-while-swapping
        race): a chain mid-d2h must either commit to the host store or
        revert to resident before any teardown path may free blocks —
        the allocator would refuse to free a ``swapping-out`` chain
        anyway (loudly), so closing the windows here keeps drains both
        safe AND quiet. Under the async loop a dispatched tick is
        collected first — its tokens stash for the next collect, so the
        drain starts from settled host state without dropping any."""
        self._collect_pending_tick()
        if self.offload:
            self._finalize_swaps()
        self.draining = True

    def drain_graceful(
        self, max_steps: int = 100_000
    ) -> Tuple[Dict[int, List[int]], List[Request]]:
        """Drain for scale-down: stop admitting, run every in-flight
        request to retirement, and return ``(produced, requeued)`` —
        the tokens the in-flight requests streamed, plus the queued
        (never-admitted) requests the router must re-route. After this
        returns, every pool block is back on the free list
        (``engine.allocator.in_use == 0``): retirement freed the
        in-flight chains and queued requests never held any.

        On a ``prefill_only`` replica the in-flight requests end parked
        in ``ready`` (their blocks intentionally held for handoff) — the
        router completes the handoffs, after which the pool is empty
        too."""
        self.begin_drain()
        requeued = list(self.queue)
        self.queue.clear()
        produced: Dict[int, List[int]] = {}
        for _ in range(max_steps):
            # parked/mid-swap requests are in-flight (they were already
            # admitted once): the drain restores and finishes them too
            if (not self.resident and not self.parked
                    and not self._swapping) or (
                self.prefill_only
                and not self.parked and not self._swapping
                and all(r.rid in self.ready
                        for r in self.resident.values())
            ):
                if self._san is not None and not self.ready:
                    # the documented post-condition, proven: ledger ≡
                    # allocator, no chains/windows/pins outstanding.
                    # (With chains still pinned in ``ready`` the router
                    # quiesces after completing the handoffs instead.)
                    self._san.verify_quiesce()
                return produced, requeued
            for rid, tok in self.step():
                produced.setdefault(rid, []).append(tok)
        raise RuntimeError(
            f"drain_graceful did not converge within {max_steps} "
            f"steps; stuck rids by state: {self.stuck_rids()}"
        )

    # ---- client cancellation (ROADMAP item 5's first rung) ----

    def cancel(self, rid: int, reason: str = "client-cancel",
               outcome: str = "cancelled") -> bool:
        """Abort request ``rid`` wherever it lives — queued, resident
        (mid-prefill or decoding), parked (either restore path), mid
        swap-out, or handoff-ready — freeing every resource it holds:
        device chain, host-store chain, slot, handoff pin. Closes the
        request's span tree with ``outcome`` (``"cancelled"`` for a
        client cancel; the deadline sweep passes ``"deadline"``).
        Returns True when the rid was found (False: already retired or
        unknown — a benign race, cancellation is idempotent).

        The blocksan cancellation-storm trace rides this path: after a
        storm over every lifecycle state, the ledger must equal the
        allocator with zero leaked blocks."""
        # an in-flight tick may be decoding the victim: collect first so
        # the chain release cannot race the launched program
        self._collect_pending_tick()
        for i, req in enumerate(self.queue):
            if req.rid == rid:
                del self.queue[i]
                self._finish_cancel(req, slot=None, reason=reason,
                                    outcome=outcome)
                return True
        if any(entry[0] == rid for entry in self._swapping):
            # close the open d2h window first: the chain either commits
            # to the host store (cancel the parked copy below) or
            # reverts to resident (release the chain below) — never
            # freed mid-window
            self._finalize_swaps()
        if rid in self.parked:
            req, path = self.parked.pop(rid)
            if path == "swap":
                self.host_store.pop(rid)
            self._finish_cancel(req, slot=None, reason=reason,
                                outcome=outcome)
            return True
        slot = next(
            (s for s, r in self.resident.items() if r.rid == rid), None
        )
        if slot is None:
            return False
        req = self.resident.pop(slot)
        self.ready.pop(rid, None)
        if self._san is not None:
            self._san.unpin(slot)
        self.remaining[slot] = 0
        self.engine.release(slot)
        self._slot2rid.pop(slot, None)
        if self._san is not None:
            self._san.check_retire(slot, rid=rid, site="cancel")
        self._finish_cancel(req, slot=slot, reason=reason,
                            outcome=outcome)
        return True

    def _expire_deadlines(self) -> None:
        """Per-tick deadline sweep (top of every ``dispatch_tick``):
        every live request whose absolute deadline has passed — queued,
        mid-prefill, decoding, parked (either path), mid swap-out, or
        handoff-ready — expires through the cancel machinery with
        ``outcome="deadline"``. Runs before restores/admissions so an
        expired parked request never burns a restore, and an expired
        queue head never burns a slot."""
        now = time.perf_counter()
        expired = [
            req.rid
            for bucket in (
                self.queue, self.resident.values(),
                (r for r, _ in self.parked.values()),
                (entry[1] for entry in self._swapping),
            )
            for req in bucket
            if req.deadline <= now
        ]
        for rid in expired:
            self.cancel(rid, reason="deadline-exceeded",
                        outcome="deadline")

    def _finish_cancel(self, req: Request, slot: Optional[int],
                       reason: str, outcome: str = "cancelled") -> None:
        """Shared cancellation tail: counters, flight record, span-tree
        closure (every open span ends, then the root, all with
        ``outcome`` — ``"cancelled"`` or ``"deadline"``)."""
        if outcome == "deadline":
            self._deadline_misses += 1
        else:
            self._cancelled += 1
        self.flightrec.record(
            "cancel", rid=req.rid, reason=reason, outcome=outcome,
            slot=slot if slot is not None else -1,
            tokens=req.produced, replica=self.replica_id,
        )
        if self.reqtrace.enabled:
            for name in ("span_decode", "span_prefill", "span_ready",
                         "span_swap", "span_parked", "span_preempt",
                         "span_queue"):
                sid = getattr(req, name)
                if sid:
                    self.reqtrace.end(sid, outcome=outcome)
                    setattr(req, name, 0)
            self.reqtrace.end(
                self.reqtrace.root(req.rid), outcome=outcome,
                new_tokens=req.produced, reason=reason,
            )
        if self.on_retire is not None:
            self.on_retire(req.rid, outcome)

    # ---- replica death: harvest + abandon (fleet failure plane) ----

    def harvest_requests(self) -> List[Request]:
        """Every in-flight ``Request`` this replica owns — queued,
        resident (mid-prefill, decoding, handoff-ready), parked, mid
        swap-out — in rid order. The router's failure plane calls this
        when the health plane declares the replica dead, BEFORE
        ``abandon`` tears it down: the records carry everything a
        re-dispatch needs (original prompt length, deadline, session,
        produced count, open span ids)."""
        reqs: Dict[int, Request] = {}
        for req in self.queue:
            reqs[req.rid] = req
        for req in self.resident.values():
            reqs[req.rid] = req
        for rid, (req, _path) in self.parked.items():
            reqs[rid] = req
        for entry in self._swapping:
            reqs[entry[0]] = entry[1]
        return [reqs[rid] for rid in sorted(reqs)]

    def abandon(self) -> None:
        """Tear down a replica the health plane declared dead: no tick
        of this scheduler ever runs again. The in-process analogue of
        the OS reclaiming a crashed worker — every device chain, open
        swap window, host-store chain, handoff pin, and queue entry is
        disposed of through the allocator's public API, and (under
        blocksan) the shadow ledger must agree the teardown leaked
        nothing (``verify_quiesce``). Tokens a dead replica produced
        but never delivered are LOST by design — the router's replay
        regenerates them; blocks are never lost.

        Each harvested request's open lifecycle spans end here with
        ``outcome="replica-lost"``; the ROOT stays open — the router
        decides its final outcome (re-dispatch → ``complete``, attempt
        cap → ``failed``, expired meanwhile → ``deadline``)."""
        if self.reqtrace.enabled:
            for req in self.harvest_requests():
                for name in ("span_decode", "span_prefill",
                             "span_ready", "span_swap", "span_parked",
                             "span_preempt", "span_queue"):
                    sid = getattr(req, name)
                    if sid:
                        self.reqtrace.end(sid, outcome="replica-lost")
                        setattr(req, name, 0)
                self.reqtrace.event(
                    req.rid, "replica_death", replica=self.replica_id,
                    produced=req.produced,
                )
        # a launched-but-uncollected tick is never collected: a dead
        # replica's device results are untrusted
        self._pending_tick = None
        self._collected.clear()
        self._tick_obs.clear()
        self.draining = True  # any straggler submit raises, loudly
        # open swap-out windows: close the allocator's swap state
        # WITHOUT committing (the d2h arrays are dropped), then the
        # chain frees like any other
        for entry in self._swapping:
            slot = entry[2].slot
            self.engine.allocator.clear_state(slot)
            self._swap_slots.discard(slot)
            self.engine.release(slot)
            self._slot2rid.pop(slot, None)
        self._swapping.clear()
        for rid, (req, path) in self.parked.items():
            if path == "swap":
                self.host_store.pop(rid)
        self.parked.clear()
        for slot in list(self.resident):
            req = self.resident.pop(slot)
            self.ready.pop(req.rid, None)
            if self._san is not None:
                self._san.unpin(slot)
            self.remaining[slot] = 0
            self.engine.release(slot)
            self._slot2rid.pop(slot, None)
            if self._san is not None:
                self._san.check_retire(slot, rid=req.rid,
                                       site="abandon")
        self.queue.clear()
        self.positions[:] = 0
        self.remaining[:] = 0
        self.flightrec.record("abandon", replica=self.replica_id)
        if self._san is not None:
            # the teardown gate: ledger ≡ allocator, no chain, window,
            # or pin outstanding — a dead replica may lose tokens,
            # never blocks
            self._san.verify_quiesce()

    # ---- prefill→decode handoff (fleet disaggregation) ----

    def ready_rids(self) -> List[int]:
        """Prefill-complete requests awaiting handoff, in rid order."""
        return sorted(self.ready)

    def peek_ready(self, rid: int):
        """``(request, KVExport)`` for a ready request, WITHOUT releasing
        it — the router calls ``adopt`` on the decode replica first and
        only then ``complete_handoff``, so a full decode pool leaves the
        request parked here, intact, for the next tick."""
        slot = self.ready[rid]
        return self.resident[slot], self.engine.export_chain(slot)

    def complete_handoff(self, rid: int) -> None:
        """The decode replica adopted the blocks: free this replica's
        copy (slot + chain) and account the handoff."""
        slot = self.ready.pop(rid)
        req = self.resident.pop(slot)
        if self.reqtrace.enabled:
            self.reqtrace.end(req.span_ready)
            req.span_ready = 0
        if self._san is not None:
            self._san.unpin(slot)  # adoption committed: free is legal now
        self.engine.release(slot)
        if self._san is not None:
            self._san.check_retire(slot, rid=rid, site="handoff-complete")
        self.remaining[slot] = 0
        self._handoffs += 1

    def adopt(self, req: Request, export) -> bool:
        """Adopt a prefill-complete request whose KV was exported from a
        prefill replica: allocate a slot + chain, import the blocks
        (``PagedEngine.import_chain`` — the cross-mesh ``device_put``),
        and arm the decode lane at the prompt frontier. Returns False
        (nothing changed, export still valid) when no slot or chain is
        available — the router retries next tick.

        The request keeps its fleet rid, submit timestamps, and
        admission timestamps from the prefill replica, so TTFT measured
        here is end-to-end (submit → queue → prefill → handoff → first
        decoded token)."""
        if self.prefill_only:
            raise RuntimeError("a prefill_only replica cannot adopt")
        if self.draining:
            return False
        free = self._free_slots()
        if not free:
            return False
        slot = free[0]
        self._slot2rid[slot] = req.rid
        if not self.engine.import_chain(slot, export):
            self._slot2rid.pop(slot, None)
            return False
        req.slot = slot
        req.prefill_done = req.length
        if req.admit_step < 0:  # adopted without a prior admission
            req.admit_step = self._step_count
            req.admit_time = time.perf_counter()
            self.queue_wait.observe(req.admit_time - req.submit_time)
        self.resident[slot] = req
        self.positions[slot] = req.length
        self.remaining[slot] = req.max_new_tokens
        self._admitted += 1
        self._adopted += 1
        if self.reqtrace.enabled:
            # the adopted decode window opens HERE, on this replica —
            # the router links the handoff span to it, so the trace
            # shows the request's timeline switching replicas
            req.span_decode = self.reqtrace.begin(
                req.rid, "decode", replica=self.replica_id, lane=slot,
                adopted=True,
            )
        return True

    # ---- cost cards (telemetry/costmodel.py) ----

    def log_cost_cards(self) -> list:
        """One ``kind="program_cost"`` JSONL record per registry program:
        the compiler's FLOP/byte statics joined with this scheduler's
        measured per-program tick wall (warm calls only — compile stalls
        are ledger ``compile`` time, not program cost). Building the
        statics AOT-compiles each not-yet-compiled bucket (a disk hit
        under the persistent cache), so call it once per run, after
        traffic — never inside the serve loop. Returns the records."""
        from pytorch_distributed_tpu.compilecache import serving_registry
        from pytorch_distributed_tpu.telemetry import log_cost_cards

        return log_cost_cards(
            serving_registry(self.engine), self.prog_times,
            self.metrics_log, annotate=self.engine.tuned_provenance(),
        )

    # ---- metrics ----

    def _queue_gate_refresh(self) -> None:
        """Refresh the gate-metrics snapshot OFF the critical path: the
        latency-series value lists are copied here on the main thread
        (cheap pointer copies); the worker does the O(n log n)
        percentile math and swaps the snapshot in under its lock. A
        stale refresh overwriting a newer one loses at most one tick of
        percentile drift — the live overlays in ``gate_metrics`` carry
        everything the depth-bound SLO rungs actually branch on."""
        vals = {
            "ttft": list(self.ttft.values),
            "queue_wait": list(self.queue_wait.values),
        }
        goodput_frac = self.goodput.report()["goodput_frac"]

        def work():
            with self.ledger.host("metrics-refresh", self.replica_id):
                snap = {"goodput_frac": goodput_frac}
                for name, v in vals.items():
                    for q, val in percentiles(v, qs=(95,)).items():
                        snap[f"{name}_{q}_s"] = val
                with self._gate_lock:
                    self._gate_cache = snap

        self.host_pool.submit(work)

    def gate_metrics(self) -> dict:
        """The SLO gate's routing view of this replica. Synchronous
        loop: the full (exact, O(n log n)) ``metrics()``. Async loop:
        the worker-refreshed percentile snapshot overlaid with LIVE
        cheap counters — queue depth, occupancy, draining, preemptible,
        anomaly — so every depth-bound decision the gate makes is
        byte-identical to what the synchronous loop would decide, and
        only the wall-clock percentile rungs see (≤ one tick of)
        staleness."""
        if self.host_pool is None:
            return self.metrics()
        with self._gate_lock:
            snap = dict(self._gate_cache) if self._gate_cache else {}
        snap.update(
            replica_id=self.replica_id,
            queue_depth=len(self.queue),
            occupancy=len(self.resident) / self.n_slots,
            occupancy_mean=(
                self._occupancy_sum / self._step_count
                if self._step_count else 0.0
            ),
            draining=self.draining,
            offload=self.offload,
            preemptible=len(self._victims()),
            anomaly_recent=self.anomaly_recent,
            prefix_cache=self.prefix_cache,
        )
        snap.setdefault("goodput_frac", 1.0)
        return snap

    @property
    def anomaly_recent(self) -> bool:
        """True while an anomaly lies within the last
        ``anomaly_recent_ticks`` ticks — the SLO gate's hot signal."""
        return (
            self._last_anomaly_step is not None
            and self._step_count - self._last_anomaly_step
            <= self.anomaly_recent_ticks
        )

    def live_requests(self) -> int:
        """In-flight requests this replica owns right now — queued,
        resident (prefill/decode/handoff-ready), parked, mid-swap-out.
        The census sweep's O(live) audit axis (round 21)."""
        return (len(self.queue) + len(self.resident) + len(self.parked)
                + len(self._swapping))

    def census_decls(self):
        """Bound declarations for every long-lived container on this
        scheduler (round 21 scale observatory; telemetry/census.py).
        The meta-test in tests/test_scale_obs.py fails if a container
        attr exists without a declaration — new per-request state must
        say how it is bounded."""
        from pytorch_distributed_tpu.telemetry.census import Decl

        return [
            Decl("queue", "live",
                 why="admission backlog; bounded by the SLO gate's "
                     "shed/backpressure ladder in a fleet, by the "
                     "caller's submit rate standalone"),
            Decl("resident", "fixed", cap=lambda s: s.n_slots,
                 why="slot-keyed; admission only fills free slots"),
            Decl("parked", "live",
                 why="preempted requests awaiting restore — a subset of "
                     "live requests; host_store byte budget bounds it "
                     "again from below"),
            Decl("_swapping", "fixed", cap=lambda s: s.n_slots,
                 why="open d2h windows; each holds a distinct slot"),
            Decl("_swap_slots", "fixed", cap=lambda s: s.n_slots,
                 why="slots mid-swap-out; subset of all slots"),
            Decl("ready", "fixed", cap=lambda s: s.n_slots,
                 why="handoff-ready rids each pin a slot HERE until "
                     "complete_handoff frees it"),
            Decl("_slot2rid", "fixed", cap=lambda s: s.n_slots,
                 why="slot-keyed reverse map; entries overwritten on "
                     "slot reuse, popped on free (audit candidate from "
                     "ISSUE 19 — proven slot-bounded, not rid-bounded)"),
            Decl("_collected", "fixed", cap=lambda s: 4 * s.n_slots,
                 why="early-collected tokens awaiting the next "
                     "collect_tick; at most a couple of ticks' worth "
                     "(≤ n_slots tokens each) can stash between drains"),
            Decl("_tick_obs", "fixed", cap=lambda s: 2 * s.tick_obs_batch,
                 why="sentinel feed batch, flushed every tick_obs_batch "
                     "observations"),
            Decl("_gate_cache", "fixed", cap=64,
                 why="one snapshot dict of gate percentile keys, "
                     "replaced wholesale each refresh"),
            # dotted reaches: bounded children whose containers would
            # otherwise escape the sweep
            Decl("ttft.values", "fixed", cap=lambda s: 2 * s.ttft.window,
                 why="LatencySeries percentile window (round 21 cap)"),
            Decl("ttft_warm.values", "fixed",
                 cap=lambda s: 2 * s.ttft_warm.window,
                 why="LatencySeries percentile window"),
            Decl("token_lat.values", "fixed",
                 cap=lambda s: 2 * s.token_lat.window,
                 why="LatencySeries percentile window"),
            Decl("queue_wait.values", "fixed",
                 cap=lambda s: 2 * s.queue_wait.window,
                 why="LatencySeries percentile window"),
            Decl("tick_lat.values", "fixed",
                 cap=lambda s: 2 * s.tick_lat.window,
                 why="LatencySeries percentile window"),
            Decl("swap_lat.values", "fixed",
                 cap=lambda s: 2 * s.swap_lat.window,
                 why="LatencySeries percentile window"),
            Decl("prog_times._acc", "fixed", cap=256,
                 why="per-program aggregates (closed program set)"),
            Decl("host_store._chains", "live",
                 why="one host copy per parked request"),
        ]

    def metrics(self) -> dict:
        """Exact host-side accounting; all counters, no device sync."""
        alloc_blocks = self.engine.allocator.in_use
        alloc_tokens = alloc_blocks * self.engine.block_len
        used_tokens = int(sum(
            # tokens actually written and live for the request: its
            # prefill frontier plus produced decode tokens
            min(r.prefill_done, r.length) + r.produced
            for r in self.resident.values()
        ))
        elapsed = (
            time.perf_counter() - self._start_time
            if self._start_time is not None else 0.0
        )
        return {
            "replica_id": self.replica_id,
            "draining": self.draining,
            "handoffs": self._handoffs,
            "adopted": self._adopted,
            "ready": len(self.ready),
            # the ledger's utilization view: share of this replica's wall
            # NOT lost to classified overheads (compile) — the
            # fleet autoscaler folds it in next to occupancy_mean
            "goodput_frac": self.goodput.report()["goodput_frac"],
            "steps": self._step_count,
            "queue_depth": len(self.queue),
            "occupancy": len(self.resident) / self.n_slots,
            "occupancy_mean": (
                self._occupancy_sum / self._step_count
                if self._step_count else 0.0
            ),
            "pool_blocks_in_use": alloc_blocks,
            "pool_frac_in_use": (
                alloc_blocks / (self.engine.allocator.n_blocks - 1)
            ),
            "padding_waste_frac": (
                1.0 - used_tokens / alloc_tokens if alloc_tokens else 0.0
            ),
            "admitted": self._admitted,
            "completed": self._completed,
            "cancelled": self._cancelled,
            "deadline_misses": self._deadline_misses,
            **(self.blocksan.summary()
               if self.blocksan is not None else {}),
            "tokens_out": self._tokens_out,
            "tokens_per_s": self._tokens_out / elapsed if elapsed else 0.0,
            "admission_latency_steps_mean": (
                self._adm_latency_steps / self._admitted
                if self._admitted else 0.0
            ),
            "admission_latency_s_mean": (
                self._adm_latency_s / self._admitted
                if self._admitted else 0.0
            ),
            # cold-start honesty: how many retired requests ate a compile
            # stall, and the compile seconds the ledger attributed —
            # warm-only TTFT is the SLO series, plain ttft includes cold
            "cold_requests": self._cold_requests,
            "compile_s": self.goodput.seconds("compile"),
            # pressure tier (round 13): what the SLO gate's preempt rung
            # reads (offload capability + eligible victims right now)
            # and the swap machinery's exact counters
            "offload": self.offload,
            "preemptible": len(self._victims()),
            "parked": len(self.parked),
            "preempts": self._preempts,
            "restores": self._restores,
            "swap_outs": self._swap_outs,
            "swap_ins": self._swap_ins,
            "swap_aborts": self._swap_aborts,
            "swap_bytes": self._swap_bytes,
            "decision_swap": self._decision_swap,
            "decision_recompute": self._decision_recompute,
            "host_store_bytes": (
                self.host_store.bytes_used if self.offload else 0
            ),
            # prefix-sharing tier (round 17): index hit rate, sharing
            # census, COW count, and the admitted-prefill-token sum the
            # --prefix A/B divides by requests (exact, host-side)
            **self.engine.prefix_metrics(),
            "prefix_covered_tokens": self._prefix_covered_tokens,
            "admitted_prefill_tokens": self._admitted_prefill_tokens,
            **self.swap_lat.summary("swap"),
            # anomaly sentinel (telemetry/anomaly.py): total hits and the
            # recency flag the fleet SLOGate treats as hot
            "anomaly_count": (
                self.sentinel.anomalies if self.sentinel is not None else 0
            ),
            "anomaly_recent": self.anomaly_recent,
            # latency percentiles — the SLO surface (exact, host-side)
            **self.ttft.summary("ttft"),
            **self.ttft_warm.summary("ttft_warm"),
            **self.token_lat.summary("token_lat"),
            **self.queue_wait.summary("queue_wait"),
            **self.tick_lat.summary("tick"),
        }
