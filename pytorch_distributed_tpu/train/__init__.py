from pytorch_distributed_tpu.train.state import TrainState
from pytorch_distributed_tpu.train.step import make_eval_step, make_train_step
from pytorch_distributed_tpu.train.trainer import Trainer, TrainerConfig

__all__ = [
    "TrainState",
    "make_train_step",
    "make_eval_step",
    "Trainer",
    "TrainerConfig",
]
