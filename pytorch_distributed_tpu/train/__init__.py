from pytorch_distributed_tpu.train.state import TrainState
from pytorch_distributed_tpu.train.step import make_eval_step, make_train_step
from pytorch_distributed_tpu.train.lm import (
    create_lm_state,
    make_lm_train_step,
    shift_labels,
)
from pytorch_distributed_tpu.train.trainer import Trainer, TrainerConfig

__all__ = [
    "TrainState",
    "make_train_step",
    "make_eval_step",
    "create_lm_state",
    "make_lm_train_step",
    "shift_labels",
    "Trainer",
    "TrainerConfig",
]
