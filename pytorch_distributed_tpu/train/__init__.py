from pytorch_distributed_tpu.train.state import TrainState
from pytorch_distributed_tpu.train.step import make_eval_step, make_train_step
from pytorch_distributed_tpu.train.lm import (
    create_lm_state,
    make_lm_eval_step,
    make_lm_train_step,
    shard_lm_state,
    shift_labels,
)
from pytorch_distributed_tpu.train.lm_trainer import (
    LMTrainer,
    LMTrainerConfig,
    lm_collate,
    shard_lm_batch,
)
from pytorch_distributed_tpu.train.pp import (
    create_pp_lm_state,
    make_pp_lm_train_step,
    shard_pp_state,
)
from pytorch_distributed_tpu.train.trainer import Trainer, TrainerConfig

__all__ = [
    "TrainState",
    "make_train_step",
    "make_eval_step",
    "create_lm_state",
    "make_lm_eval_step",
    "make_lm_train_step",
    "shard_lm_state",
    "shift_labels",
    "LMTrainer",
    "LMTrainerConfig",
    "lm_collate",
    "shard_lm_batch",
    "create_pp_lm_state",
    "make_pp_lm_train_step",
    "shard_pp_state",
    "Trainer",
    "TrainerConfig",
]
