"""Shared trainer machinery: the suspend/checkpoint/resume contract.

One home for the logic both trainers (image ``Trainer``, ``LMTrainer``)
must agree on — the reference's §3.5 fault-tolerance path plus this
framework's multi-host hardening. Keeping it in one place is load-bearing:
these are collective-ordering-sensitive code paths where two diverging
copies would deadlock pods.

Subclass contract:
  - ``self.config`` has ``suspend_sync_every``; ``self.watcher`` is a
    SuspendWatcher; ``self.ckpt`` a Checkpointer; ``self.mesh`` the mesh;
    ``self.state`` the TrainState; ``self.state_specs`` a spec tree or None.
  - ``_extra_payload()`` → dict of host-side scalars to checkpoint
    (best_acc / best_ppl, ...); ``_restore_extra(dict)`` applies them.
"""

from __future__ import annotations

import os
import time

import jax
import numpy as np

from pytorch_distributed_tpu.parallel import collectives, mesh as mesh_lib
from pytorch_distributed_tpu.resilience import faults
from pytorch_distributed_tpu.resilience.stepguard import (
    RollbackRequested,
    StepGuard,
)
from pytorch_distributed_tpu.resilience.watchdog import Watchdog
from pytorch_distributed_tpu.telemetry import (
    NULL_LEDGER,
    NULL_RECORDER,
    NULL_TRACER,
    AnomalySentinel,
    GoodputLedger,
    ProgramTimes,
    SpanTracer,
)
from pytorch_distributed_tpu.utils.logging import rank0_print


class SuspendableTrainer:
    """Mixin implementing suspend agreement, payloads, and resume."""

    # resilience attributes; _init_resilience overrides them per config
    guard = None
    watchdog = None
    rollbacks = 0
    # telemetry attributes; _init_resilience overrides them per config
    goodput = None
    tracer = NULL_TRACER
    _ring = None
    _dispatched = 0
    # attribution & forensics (ISSUE 8); _init_resilience overrides
    sentinel = None
    flightrec = NULL_RECORDER
    exporter = None
    prog_times = None
    _last_step_t = None
    # host–device overlap ledger (round 15; telemetry/overlap.py):
    # _bind_observability arms it when config.overlap is set
    ledger = NULL_LEDGER

    # ---- resilience plumbing (resilience/: stepguard, watchdog, faults).
    # Both trainers call _init_resilience from __init__ and bracket each
    # train step with _pre_step/_post_step; fit() catches
    # RollbackRequested and re-enters via _rollback. ----

    def _init_resilience(self) -> None:
        """Build the step guard and watchdog the config asks for. The
        guard exists whenever the compiled step emits ``step_good``
        (``nan_guard=True``); ``max_bad_steps=0`` means skip-only, no
        rollback. The goodput ledger and span tracer (telemetry/) are
        built here too — the watchdog feeds the ledger its stall time —
        plus (ISSUE 8) the anomaly sentinel, flight recorder, and
        per-program time accumulator; the metrics JSONL is created after
        this runs, so the trainers bind it via ``_bind_observability``."""
        from pytorch_distributed_tpu.telemetry import FlightRecorder

        cfg = self.config
        self.goodput = GoodputLedger()
        self.tracer = (
            SpanTracer() if getattr(cfg, "trace_dir", None) else NULL_TRACER
        )
        self._ring = None  # built lazily from the first metrics dict
        self._dispatched = 0  # run-level step-dispatch count (compile attr)
        self.prog_times = ProgramTimes()
        self._last_step_t = None
        threshold = getattr(cfg, "anomaly_threshold", 8.0)
        self.sentinel = (
            AnomalySentinel(
                threshold=threshold,
                window=getattr(cfg, "anomaly_window", 64),
            )
            if threshold and threshold > 0 else None
        )
        if self.sentinel is not None:
            # 10 ms scale floor: near-constant tiny-step series would
            # otherwise flag scheduler jitter (MAD ≈ 0 → any blip is ∞σ);
            # a stall must clear threshold × 10 ms above the baseline
            self.sentinel.detector("step_time").abs_floor = 0.01
            self.sentinel.detector("data_wait").abs_floor = 0.01
        rank0 = jax.process_index() == 0
        if getattr(cfg, "flightrec", True):
            self.flightrec = FlightRecorder(
                capacity=256,
                # durable per-event mirror (size-capped, rank 0): what a
                # SIGKILL'd run leaves behind for the relaunch to read
                mirror_path=os.path.join(cfg.save_dir, "flightrec.jsonl")
                if rank0 else None,
            )
            if rank0:
                self.flightrec.install_excepthook(
                    os.path.join(cfg.save_dir, "flightrec_dump.json")
                )
            if self.sentinel is not None:
                self.sentinel.flightrec = self.flightrec
        else:
            self.flightrec = NULL_RECORDER
        if getattr(cfg, "nan_guard", False):
            self.guard = StepGuard(
                max_bad_steps=getattr(cfg, "max_bad_steps", 0)
            )
        timeout = getattr(cfg, "watchdog_timeout_s", 0.0)
        if timeout and timeout > 0:
            self.watchdog = Watchdog(
                timeout,
                watcher=self.watcher,
                dump_path=os.path.join(cfg.save_dir, "watchdog_stall.log")
                if rank0
                else None,
                ledger=self.goodput,
                flightrec=self.flightrec,
                flightrec_path=os.path.join(
                    cfg.save_dir, "flightrec_stall.json"
                ) if rank0 else None,
            ).start()

    def _bind_observability(self) -> None:
        """Called by the trainers once ``self.metrics_log`` exists:
        attach the sentinel's JSONL stream, arm the overlap dispatch
        ledger (``config.overlap``; round 15) over the same JSONL, and
        start the live Prometheus exporter when the config asks for one
        (``metrics_port``)."""
        if self.sentinel is not None:
            self.sentinel.metrics_log = getattr(self, "metrics_log", None)
        if getattr(self.config, "overlap", False):
            from pytorch_distributed_tpu.telemetry import DispatchLedger

            self.ledger = DispatchLedger(
                getattr(self, "metrics_log", None)
            )
        port = getattr(self.config, "metrics_port", None)
        if port is not None and jax.process_index() == 0:
            from pytorch_distributed_tpu.telemetry import MetricsExporter

            self.exporter = MetricsExporter(
                self._live_metrics, port=port
            ).start()

    def _live_metrics(self) -> dict:
        """The exporter's scrape callback: run-level host counters only
        (no device sync on the scrape path)."""
        out = dict(self.goodput.report()) if self.goodput else {}
        out["steps_dispatched"] = self._dispatched
        out["rollbacks"] = self.rollbacks
        if self.sentinel is not None:
            out["anomalies"] = self.sentinel.anomalies
        if self.watchdog is not None:
            out["watchdog_stalls"] = self.watchdog.stalls
        return out

    # ---- compile-cache plumbing (compilecache/: registry, AOT, warmup;
    # ANALYSIS.md "Cold start & compile cache"). Both trainers call
    # _init_compilecache FIRST in __init__ (so even flax init and
    # placement programs land in the persistent cache) and fit() calls
    # _run_warmup after resume. ----

    def _init_compilecache(self) -> None:
        """Point jax's persistent compilation cache at the configured
        directory (config.compile_cache_dir, env PDT_COMPILE_CACHE_DIR
        fallback) — a relaunched/resumed run with the same fingerprint
        then loads its executables from disk instead of recompiling."""
        from pytorch_distributed_tpu.utils.env import (
            resolve_compile_cache_dir,
        )

        cache_dir = resolve_compile_cache_dir(
            getattr(self.config, "compile_cache_dir", None)
        )
        if cache_dir:
            from pytorch_distributed_tpu.compilecache import (
                enable_persistent_cache,
            )

            enable_persistent_cache(cache_dir)

    def _registry_entries(self):
        """Subclass hook: ``[(name, jit_fn, avals_list_thunk,
        expect_entries)]`` — every compiled step program this trainer
        runs, with a lazy thunk producing the list of abstract argument
        tuples (live state + ShapeDtypeStructs carrying the REAL batch
        shardings) the program compiles for."""
        return []

    def program_registry(self):
        """The trainer's AOT program registry: train step + eval step(s),
        fingerprinted by (env, mesh, trainer config, model config). Warm
        thunks AOT-compile via ``lower(...).compile()`` — trainer steps
        must never EXECUTE during warmup (a dummy step would corrupt
        params/opt state), so the win is the persistent cache: the real
        first dispatch becomes a disk load."""
        from pytorch_distributed_tpu.compilecache import (
            ProgramRegistry,
            ProgramSpec,
            jit_cache_size,
            run_fingerprint,
        )

        reg = ProgramRegistry(run_fingerprint(
            mesh=self.mesh,
            extra=(self.config, getattr(self, "model_config", None)),
        ))
        for name, fn, avals_thunk, expect in self._registry_entries():
            def warm(execute, fn=fn, thunk=avals_thunk):
                for avals in thunk():
                    fn.lower(*avals).compile()

            def aot(fn=fn, thunk=avals_thunk):
                # cost-card statics from the steady-state (first) aval
                # variant; a multi-shape eval step's card covers shape 0
                avals = thunk()
                return fn.lower(*avals[0]).compile() if avals else None

            reg.add(ProgramSpec(
                name=name, warm=warm, priority=0, expect_entries=expect,
                cache_probe=lambda fn=fn: jit_cache_size(fn),
                aot=aot,
            ))
        return reg

    def compiled_program_names(self) -> list:
        """One element per live jit-cache entry of each step program —
        the observed side of the registry coverage guard."""
        from pytorch_distributed_tpu.compilecache import jit_cache_size

        names = []
        for name, fn, _thunk, _expect in self._registry_entries():
            n = jit_cache_size(fn)
            names.extend([name] * (n or 0))
        return names

    def assert_registry_covers(self) -> None:
        """Fail (CoverageError) if a step program compiled more variants
        than the registry predicted — the trainers' half of the
        acceptance guard (the serving half audits PagedEngine)."""
        self.program_registry().assert_covers(self.compiled_program_names())

    def _run_warmup(self) -> None:
        """``config.warmup``: AOT-compile every registry entry before the
        first step, attributing the wall time to the goodput ledger's
        ``compile`` category and appending ``kind="warmup"`` manifest
        records to the metrics JSONL."""
        if not getattr(self.config, "warmup", False):
            return
        from pytorch_distributed_tpu.compilecache import WarmupRunner

        runner = WarmupRunner(
            self.program_registry(),
            tracer=self.tracer,
            ledger=self.goodput,
            manifest=getattr(self, "metrics_log", None),
        )
        runner.run(background=False)  # AOT thunks are traffic-safe anyway
        s = runner.summary()
        rank0_print(
            f"warmup: {s['programs']} programs in {s['total_s']:.2f}s "
            f"({s['cache_hits']} cache hits, {s['fresh']} fresh; "
            f"fingerprint {s['fingerprint']})"
        )

    # ---- telemetry plumbing (telemetry/: device ring, spans, goodput).
    # The trainers push each log event's device metric scalars through
    # _telemetry_append instead of blocking on float(); records drain
    # lagged, one transfer per flush_every log events. ----

    def _telemetry_append(self, metrics: dict, **meta) -> list:
        """Push one log event into the device ring (no host sync);
        returns any records the push drained."""
        if self._ring is None:
            from pytorch_distributed_tpu.telemetry import DeviceMetricsRing

            self._ring = DeviceMetricsRing(
                list(metrics),
                capacity=max(getattr(self.config, "flush_every", 32), 1),
                sharding=mesh_lib.replicated_sharding(self.mesh),
            )
        return self._ring.append(metrics, **meta)

    def _telemetry_flush(self) -> list:
        """Drain everything buffered (epoch end); may sync on the last
        pushed step — the same point the epoch-timing record syncs."""
        return self._ring.flush() if self._ring is not None else []

    def _drain_train_records(self, records) -> dict:
        """Emit drained ring records (subclass formats them); returns the
        last record's metrics. Base default: nothing to emit."""
        return {}

    def _log_goodput(self) -> None:
        """Emit the run-level goodput record (fit end / pre-suspend),
        finalizing the overlap ledger first — its end-of-run fence +
        bubble classification must land in the same JSONL (idempotent,
        so the suspend path and fit end can both call this)."""
        self.ledger.finalize()
        if self.goodput is not None and getattr(self, "metrics_log", None):
            self.metrics_log.log(kind="goodput", **self.goodput.report())

    def _log_cost_cards(self) -> None:
        """Emit one ``kind="program_cost"`` record per registry program
        (telemetry.costmodel), joining the compiler's FLOP/byte statics
        with the run's measured per-step wall. Gated behind
        ``config.cost_cards`` because the statics cost one extra
        ``lower(...).compile()`` per program (a disk hit when the
        persistent compile cache is on) — paid once at fit END, off the
        training critical path, and never on the pre-suspend fast path."""
        if not getattr(self.config, "cost_cards", False):
            return
        if jax.process_index() != 0:
            return
        from pytorch_distributed_tpu.telemetry import log_cost_cards

        log_cost_cards(
            self.program_registry(), self.prog_times,
            getattr(self, "metrics_log", None),
        )

    def _save_traces(self) -> None:
        """Write the span tracer's Chrome trace (rank 0, fit end)."""
        trace_dir = getattr(self.config, "trace_dir", None)
        if (
            trace_dir
            and self.tracer.enabled
            and jax.process_index() == 0
        ):
            self.tracer.save(os.path.join(trace_dir, "spans.trace.json"))

    def _pre_step(self, host_batch):
        """Once per train step, before device dispatch: apply any
        ``train.step`` fault directive — ``nan`` poisons the host batch
        (provoking NaN grads through the real compiled step), ``suspend``
        latches the watcher; ``kill``/``hang``/``raise`` execute inside
        fault_point itself."""
        spec = faults.fault_point("train.step")
        if spec is not None:
            if spec.kind == "nan":
                host_batch = faults.poison_batch(host_batch)
            elif spec.kind == "suspend":
                self.watcher.request_suspend()
        return host_batch

    def _post_step(self, metrics: dict) -> None:
        """After each step's dispatch: heartbeat the watchdog (beating
        here, not in _pre_step, keeps the first step's multi-second XLA
        compile outside the armed deadline window) and feed the guard its
        lagged ``step_good`` flag. The guard raises RollbackRequested
        (caught in fit) after K consecutive bad steps — deterministically
        on every rank, since the flag is a replicated psum'd metric.

        Forensics (ISSUE 8): the step lands one flight-recorder event
        (the ring's heartbeat — a post-mortem dump shows exactly which
        step the run died after) and its wall gap feeds the anomaly
        sentinel's ``step_time`` series. The gap is post_step→post_step,
        so a hang anywhere in the loop (data fetch, injected fault,
        dispatch) shows up; the first gap of a run (compile) is absorbed
        by the detector's warmup window."""
        if self.watchdog is not None:
            self.watchdog.beat()
        if self.guard is not None:
            self.guard.observe(metrics.get("step_good"))
        now = time.perf_counter()
        self.flightrec.record("step", n=self._dispatched)
        if self._last_step_t is not None and self.sentinel is not None:
            self.sentinel.observe(
                "step_time", now - self._last_step_t,
                step=self._dispatched,
            )
        self._last_step_t = now

    def _observe_data_wait(self, seconds: float) -> None:
        """Per-step data-wait observation for the sentinel (the trainers
        call this from their ``data_wait`` bracket)."""
        if self.sentinel is not None:
            self.sentinel.observe(
                "data_wait", seconds, step=self._dispatched
            )

    def _epoch_end_guard(self) -> None:
        if self.guard is not None:
            self.guard.flush()

    def _rollback(self, err: RollbackRequested) -> None:
        """Restore the newest restorable checkpoint after the guard gave
        up on skipping. Every rank raises at the same step (replicated
        metric) and reaches this together, so the collective-ordered
        resume path is safe. No checkpoint at all is fatal: training from
        a state the guard condemned would just NaN again."""
        self.rollbacks += 1
        rank0_print(f"stepguard: {err}; restoring last good checkpoint")
        # forensics: the condemned run's last events, dumped before the
        # replay overwrites the ring's recent history
        self.flightrec.record("rollback", n=self.rollbacks, reason=str(err))
        if jax.process_index() == 0:
            self.flightrec.dump(
                os.path.join(self.config.save_dir, "flightrec_dump.json"),
                "rollback",
            )
        # surface the condemned run's buffered log events before the
        # replay re-logs the same steps (keeps the JSONL ordered)
        self._drain_train_records(self._telemetry_flush())
        with self.goodput.timed("rollback"), \
                self.tracer.span("rollback_replay"):
            self.ckpt.wait()  # commit/join any in-flight save first
            if not self.try_resume():
                raise RuntimeError(
                    "stepguard requested rollback but no restorable "
                    "checkpoint exists — enable save_every_n_steps (or "
                    "suspend saves) so a rollback target is available"
                ) from err
        self.guard.reset()

    # ---- checkpoint payloads (collective: call on ALL ranks) ----

    def _extra_payload(self) -> dict:
        return {}

    def _restore_extra(self, restored: dict) -> None:
        pass

    def _payload(self, epoch: int, step: int) -> dict:
        """LEGACY single-file payload: every array gathered to host.

        ``gather_global`` is a collective for cross-process-sharded states,
        so this MUST run on every process together; only the disk write is
        rank-0-gated (``restnet_ddp.py:36,145``). The default save path is
        now ``_payload_live`` + ``save_latest_sharded`` (no gather); this
        remains for the single-file interchange format."""
        from pytorch_distributed_tpu.utils.checkpoint import gather_global

        payload = {"state": gather_global(self.state), "epoch": epoch,
                   "step": step}
        payload.update(self._extra_payload())
        return payload

    def _payload_live(self, epoch: int, step: int) -> dict:
        """Payload with the state's live (device, possibly cross-process
        sharded) arrays — for ``save_sharded``, which writes each process's
        blocks from its own shards. NO gather, no full-state host copy."""
        payload = {"state": self.state, "epoch": epoch, "step": step}
        payload.update(self._extra_payload())
        return payload

    def _state_shardings(self):
        if self.state_specs is not None:
            return mesh_lib.specs_to_shardings(self.mesh, self.state_specs)
        return jax.tree.map(
            lambda _: mesh_lib.replicated_sharding(self.mesh), self.state
        )

    def try_resume(self) -> bool:
        """Restore the NEWEST restorable checkpoint: ``latest.ckpt``
        (suspend save) or a ``step-*.ckpt`` interval save, whichever
        carries the highest ``state/step`` (``restnet_ddp.py:127-132``
        restores only latest — interval saves are a durability policy the
        reference lacks, so a crash after them must not fall back to an
        older suspend artifact).

        ELASTIC (reshard/; ROADMAP item 4): target shardings come from
        THIS run's mesh and spec tree, never from the writer's layout, so
        a checkpoint written on mesh (4,2) restores onto (2,2) or (8,1)
        with optimizer state, data cursor and global step intact — each
        process assembles exactly the block slices its devices need.
        ``config.elastic_resume=False`` refuses topology-mismatched
        candidates instead (they fall through like corrupt ones). A
        cross-topology resume changes ``run_fingerprint`` (the mesh is
        part of it), so the writer's compile-cache artifacts are misses
        by construction; ``fit()`` runs ``_run_warmup`` AFTER this
        method, which re-AOT-compiles the registry for the new mesh
        before step 1 — no mid-run compiles after an elastic resume.

        Fallback restore: candidates are pre-validated (manifest + shard
        completeness + save token) and scanned newest-first; a candidate
        that still fails at load time — e.g. a token mismatch surfacing
        mid-read — is logged and the scan falls through to the next
        *complete* checkpoint instead of refusing to start. Validation
        reads the same shared-fs files on every rank, so all ranks pick
        the same candidate. Legacy single files restore via the full-
        host-numpy path, placed slice-wise — mesh-agnostic by
        construction."""
        from pytorch_distributed_tpu.reshard import (
            ReshardRefused,
            load_elastic,
            mesh_desc,
            payload_shardings,
        )

        self.ckpt.wait()
        allow = getattr(self.config, "elastic_resume", True)
        for path in self.ckpt.restorable_paths():
            try:
                template = self._payload_live(0, 0)
                shardings = payload_shardings(
                    self.mesh, template, self.state_specs
                )
                restored, info = load_elastic(
                    path, template, shardings,
                    mesh=self.mesh, allow_reshard=allow,
                )
                # no-op for placed sharded leaves; places the legacy
                # path's host arrays (slice-wise put already done there,
                # this is belt-and-braces for sharding-less entries)
                state = jax.device_put(
                    restored["state"], shardings["state"]
                )
            except ReshardRefused as e:
                rank0_print(f"resume: skipping {path}: {e}")
                continue
            except (OSError, ValueError, KeyError, RuntimeError) as e:
                rank0_print(
                    f"resume: {path} failed to load ({e}); falling back "
                    "to the next complete checkpoint"
                )
                continue
            self.state = state
            self.start_epoch = int(restored["epoch"])
            self.start_step = int(restored["step"])
            self._restore_extra(restored)
            if info.resharded:
                rank0_print(
                    f"elastic resume: {info.describe()} — "
                    "run_fingerprint changed with the mesh; warmup "
                    "re-AOT-compiles the program registry for this "
                    "topology before step 1"
                )
            rank0_print(
                f"resumed from {path}: "
                f"epoch {self.start_epoch} step {self.start_step}"
            )
            return True
        return False

    def _maybe_save_step(self, epoch: int, step: int) -> None:
        """Interval checkpoint hook: every ``save_every_n_steps`` train
        steps, a non-blocking sharded save of the live state to
        ``step-<global_step>.ckpt`` with keep-last-``keep_last_ckpts``
        retention. The save's internal ``wait()`` commits the previous
        in-flight save — every rank calls this at the same step, so the
        collective ordering matches the suspend/best paths."""
        every = getattr(self.config, "save_every_n_steps", 0)
        if every <= 0 or (step + 1) % every:  # negative = off, like 0
            return
        self.flightrec.record("ckpt_save", epoch=epoch, step=step)
        with self.goodput.timed("checkpoint"), \
                self.tracer.span("ckpt_save", step=step):
            gstep = int(np.asarray(jax.device_get(self.state.step)))
            self.ckpt.save_step_sharded(
                self._payload_live(epoch, step + 1), gstep,
                keep_last=getattr(self.config, "keep_last_ckpts", 3),
                block=False,
            )

    # ---- the suspend agreement (ref restnet_ddp.py:36-47) ----

    def _maybe_suspend(self, epoch: int, step: int) -> None:
        """Poll → agree → checkpoint → yield.

        Multi-host with ``suspend_sync_every=N``: a locally-latched signal
        is ONLY acted on at agreement steps (step % N == 0), where every
        host all-reduces its flag — acting immediately on a local signal
        would send one host into the collective payload gather while the
        others run the next train step (mismatched collectives, permanent
        hang). The watcher latches, so deferring loses nothing.
        ``suspend_sync_every=0`` keeps the reference's primary-only
        semantics (unsafe by design, documented).
        """
        suspended = self.watcher.receive_suspend_command()
        sync = self.config.suspend_sync_every
        if sync and jax.process_count() > 1:
            if step % sync != 0:
                return  # defer to the next agreement step
            suspended = bool(
                collectives.all_reduce(np.float32(suspended), "max")
            )
        if not suspended:
            return
        # forensics first: the pre-suspend ring is the record of WHY the
        # run yielded (watchdog latch vs scheduler signal)
        self.flightrec.record("suspend", epoch=epoch, step=step)
        if jax.process_index() == 0:
            self.flightrec.dump(
                os.path.join(self.config.save_dir, "flightrec_dump.json"),
                "suspend",
            )
        # the run is about to yield: surface the ring's buffered log
        # events so the JSONL tail isn't lost with the process
        self._drain_train_records(self._telemetry_flush())
        # Sharded save: EVERY process writes its own blocks (no gather, no
        # full-state host copy on any rank); rank 0 adds the manifest; the
        # save's internal barrier guarantees all files landed before yield.
        with self.goodput.timed("checkpoint"), \
                self.tracer.span("ckpt_save", step=step, suspend=True):
            self.ckpt.save_latest_sharded(
                self._payload_live(epoch, step + 1)
            )
            rank0_print(
                f"suspend: saved {self.ckpt.latest_path} at epoch {epoch} "
                f"step {step}"
            )
            self.ckpt.wait()
        # the run may not come back: record what this attempt's wall
        # time went to before yielding
        self._log_goodput()
        self._save_traces()
        self.watcher.go_suspend()
