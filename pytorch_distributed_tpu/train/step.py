"""The compiled SPMD train/eval step — where DDP's whole machinery collapses.

In the reference, one training step is Python orchestrating five subsystems
(hot loop ``restnet_ddp.py:21-33``, SURVEY.md §3.2): H2D copy → DDP forward
→ loss → backward with the C++ Reducer firing bucketed NCCL all-reduces
overlapped with grad computation → optimizer step. Here the *entire* body —
forward, loss, backward, cross-replica gradient combine, optimizer update,
BN stats, metric reduction — is one XLA program built with ``shard_map``
over the mesh's data axis and compiled once by ``jit``:

- the gradient ``pmean`` is visible to XLA's latency-hiding scheduler, which
  overlaps it with the remaining backward (what DDP's bucketing
  hand-implements in C++, D7);
- BatchNorm normalizes with *per-replica* batch statistics, exactly DDP's
  unsynced-BN training dynamics (SURVEY.md §7 hard part (c)); the running
  stats are pmean'd across replicas each step so the state stays replicated
  and deterministic (the reference instead checkpoints rank 0's arbitrary
  local copy, ``restnet_ddp.py:38``);
- mixed precision is the state's scaler + the model's compute dtype: bf16
  needs no scaler (NoOpLossScaler compiles away); with DynamicLossScaler the
  GradScaler skip-on-nonfinite contract (``resnet_ddp_apex.py:30-33``) runs
  entirely on device — no per-step host sync, unlike torch's scaler;
- one code path serves all four reference recipes: a 1-device mesh is
  ``resnet_single_gpu``, an 8-device local mesh is ``resnet_dp`` (without
  the per-step scatter/replicate cost of D5), a multi-host mesh is
  ``restnet_ddp`` — the difference is the Mesh, not the code.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from pytorch_distributed_tpu.ops.losses import cross_entropy_loss
from pytorch_distributed_tpu.ops.metrics import ClassificationMetrics
from pytorch_distributed_tpu.ops.precision import NoOpLossScaler, all_finite
from pytorch_distributed_tpu.ops.optim import clip_grads_by_global_norm
from pytorch_distributed_tpu.parallel.mesh import DATA_AXIS, shard_map
from pytorch_distributed_tpu.resilience.stepguard import finite_ok, guard_state
from pytorch_distributed_tpu.train.state import TrainState


def prepare_image(image):
    """Device-side normalization for uint8 batches (the raw fast path).

    The raw input pipeline (``data.raw``) ships uint8 pixels — 4x fewer
    host→device bytes — and this applies exactly the host ``Normalize``
    math (``data/transforms.py``: /255, -mean, /std, fp32) inside the
    compiled step, where it fuses into the stem conv. Float batches are
    already normalized on host and pass through untouched.
    """
    if image.dtype != jnp.uint8:
        return image
    from pytorch_distributed_tpu.data.transforms import IMAGENET_MEAN, IMAGENET_STD

    return (image.astype(jnp.float32) / 255.0 - IMAGENET_MEAN) / IMAGENET_STD


def make_train_step(
    mesh: Mesh,
    axis: str = DATA_AXIS,
    label_smoothing: float = 0.0,
    state_specs: Optional[TrainState] = None,
    grad_clip_norm: float = 0.0,
    nan_guard: bool = False,
) -> Callable[[TrainState, dict], Tuple[TrainState, dict]]:
    """Build the compiled training step for a mesh.

    Returns ``step(state, batch) -> (state, metrics)`` where ``batch`` is a
    global array dict sharded batch-dim over ``axis`` (see
    ``parallel.shard_batch``) and metrics are replicated scalars
    {loss, correct1, correct5, count, grads_finite}.

    ``state_specs`` (from ``parallel.fsdp.shard_fsdp_state``) switches on
    the FSDP/ZeRO-3 path: parameters and optimizer state live sharded over
    ``axis``; the step all_gathers params before the forward and
    psum_scatters gradients back to their owners — same math as replicated
    DP (all_gather∘psum_scatter ≡ pmean), ~axis-size less state memory.

    ``nan_guard`` adds the resilience finite gate (resilience.stepguard):
    a step whose global loss or combined gradients are non-finite keeps
    the pre-step params/opt/BN state (``lax.cond`` select on device — no
    host sync) while ``step`` still advances, and the replicated
    ``step_good`` metric reports the verdict for the host rollback policy.
    """
    fsdp = state_specs is not None
    if fsdp:
        from pytorch_distributed_tpu.parallel.fsdp import (
            gather_params,
            scatter_grads,
        )

    def _local_step(state: TrainState, batch: dict):
        def loss_fn(params):
            variables = {"params": params}
            if state.batch_stats:
                variables["batch_stats"] = state.batch_stats
            outputs, mutated = state.apply_fn(
                variables, prepare_image(batch["image"]), train=True,
                mutable=["batch_stats"],
            )
            loss = cross_entropy_loss(
                outputs, batch["label"], label_smoothing=label_smoothing
            )
            return state.scaler.scale_loss(loss), (loss, outputs, mutated)

        full_params = (
            gather_params(state.params, state_specs.params, axis)
            if fsdp
            else state.params
        )
        grads, (loss, logits, mutated) = jax.grad(loss_fn, has_aux=True)(full_params)
        grads = state.scaler.unscale_grads(grads)
        # The DP gradient combine: per-replica mean-loss grads averaged over
        # the axis ≙ DDP's allreduce-and-divide (restnet_ddp.py:29 via D7).
        # FSDP: the same mean, delivered shard-wise (reduce-scatter).
        if fsdp:
            grads = scatter_grads(grads, state_specs.params, axis)
        else:
            grads = jax.lax.pmean(grads, axis_name=axis)

        if grad_clip_norm:
            # torch ordering (clip_grad_norm_ after scaler.unscale_): the
            # threshold must see TRUE gradient magnitudes, so this sits
            # after unscale_grads and after the cross-replica combine.
            # Non-finite grads survive clipping as NaN (inf * 0) and the
            # scaler's finite gate below still skips the step.
            grads, _ = clip_grads_by_global_norm(
                grads, grad_clip_norm,
                state_specs.params if fsdp else None,
            )

        new_batch_stats = mutated.get("batch_stats", state.batch_stats)
        if new_batch_stats:
            new_batch_stats = jax.lax.pmean(new_batch_stats, axis_name=axis)

        if isinstance(state.scaler, NoOpLossScaler):
            # bf16/fp32 path: no scaler, no finite gate, no extra compute.
            updates, new_opt_state = state.tx.update(
                grads, state.opt_state, state.params
            )
            new_params = jax.tree.map(jnp.add, state.params, updates)
            new_scaler = state.scaler
            finite = jnp.asarray(True)
        else:
            # GradScaler contract (resnet_ddp_apex.py:30-33): on non-finite
            # grads skip the whole update (params, momentum, schedule count)
            # and back off the scale — computed on device, no host sync.
            # The flag must be GLOBAL: under FSDP each device only sees its
            # gradient shards, so a local inf would make devices disagree on
            # skipping and silently diverge params/opt/scaler state.
            finite = (
                jax.lax.pmin(all_finite(grads).astype(jnp.int32), axis) > 0
            )
            updates, new_opt_state = state.tx.update(
                grads, state.opt_state, state.params
            )
            new_params = jax.tree.map(
                lambda p, u: jnp.where(finite, p + u, p), state.params, updates
            )
            new_opt_state = jax.tree.map(
                lambda new, old: jnp.where(finite, new, old)
                if jnp.issubdtype(jnp.asarray(new).dtype, jnp.inexact)
                or jnp.issubdtype(jnp.asarray(new).dtype, jnp.integer)
                else new,
                new_opt_state,
                state.opt_state,
            )
            new_scaler = state.scaler.update(finite)

        new_state = state.replace(
            step=state.step + 1,
            params=new_params,
            batch_stats=new_batch_stats,
            opt_state=new_opt_state,
            scaler=new_scaler,
        )

        batch_metrics = ClassificationMetrics.from_step(
            cross_entropy_loss(logits, batch["label"], reduction="sum"),
            logits,
            batch["label"],
        )
        batch_metrics = jax.lax.psum(batch_metrics, axis_name=axis)
        metrics = {
            "loss": batch_metrics.loss_sum / jnp.maximum(batch_metrics.count, 1.0),
            "correct1": batch_metrics.correct1,
            "correct5": batch_metrics.correct5,
            "count": batch_metrics.count,
            "grads_finite": finite.astype(jnp.float32),
        }
        if nan_guard:
            # The resilience finite gate. pmin over the axis: under FSDP
            # each device checks only its gradient shards, and devices
            # disagreeing on `good` would silently diverge params — the
            # same global-agreement argument as the fp16 scaler gate.
            good = (
                jax.lax.pmin(
                    finite_ok(metrics["loss"], grads).astype(jnp.int32),
                    axis,
                )
                > 0
            )
            # step always advances (a skip is a consumed batch); the fp16
            # scaler still backs off on the skipped step
            keep = (
                ("step",)
                if isinstance(state.scaler, NoOpLossScaler)
                else ("step", "scaler")
            )
            new_state = guard_state(good, new_state, state, keep=keep)
            metrics["step_good"] = good.astype(jnp.float32)
        return new_state, metrics

    state_spec = state_specs if fsdp else P()
    metrics_spec = P()
    sharded = shard_map(
        _local_step,
        mesh=mesh,
        in_specs=(state_spec, P(axis)),
        out_specs=(state_spec, metrics_spec),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,))


def make_eval_step(
    mesh: Mesh, axis: str = DATA_AXIS, state_specs: Optional[TrainState] = None
) -> Callable[[TrainState, dict, ClassificationMetrics], ClassificationMetrics]:
    """Build the compiled validation step (ref ``validate``,
    ``restnet_ddp.py:50-61``).

    ``eval_step(state, batch, metrics) -> metrics``: forward with running BN
    stats, top-1/5 counts psum'd over the axis, accumulated into the
    device-resident ``metrics`` pytree — no host sync per batch. Every
    replica (and host) ends with the global sums, a strict superset of the
    reference's reduce-to-rank-0 (``restnet_ddp.py:63-64``).
    """

    fsdp = state_specs is not None
    if fsdp:
        from pytorch_distributed_tpu.parallel.fsdp import gather_params

    def _local_eval(state: TrainState, batch: dict, metrics: ClassificationMetrics):
        params = (
            gather_params(state.params, state_specs.params, axis)
            if fsdp
            else state.params
        )
        variables = {"params": params}
        if state.batch_stats:
            variables["batch_stats"] = state.batch_stats
        logits = state.apply_fn(variables, prepare_image(batch["image"]), train=False)
        batch_metrics = ClassificationMetrics.from_step(
            cross_entropy_loss(logits, batch["label"], reduction="sum"),
            logits,
            batch["label"],
        )
        return metrics.merge(jax.lax.psum(batch_metrics, axis_name=axis))

    sharded = shard_map(
        _local_eval,
        mesh=mesh,
        in_specs=(state_specs if fsdp else P(), P(axis), P()),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(2,))
