"""Pipeline-parallel transformer training (GPipe over the model axis).

Round 1 left ``parallel.pipeline.gpipe`` moving activations for a toy
stage function (VERDICT missing #5); this trains the real ``TransformerLM``
block stack through it:

- the layer stack splits into S uniform stages of ``layers_per_stage``
  real ``models.transformer.Block``s; stage parameters are STACKED on a
  leading [S, ...] dim and placement-sharded P(model) — each device holds
  only its stage's slice (the PP memory win), same spec discipline as
  TP/EP/FSDP;
- embedding and head params are replicated; every stage computes the
  embedding (cheap, keeps gpipe's uniform-activation contract) but only
  stage 0's copy feeds the pipeline, and only the last stage's logits are
  real — a LOCAL zero mask kills the garbage branches' gradients (no psum
  inside the differentiated function: it would transpose to another psum
  and scale gradients by the stage count), and the loss is psum'd outside;
- gradients: stage params are stage-LOCAL over the model axis (no
  reduction); embedding/head grads have exactly one nonzero contributor on
  the model axis, so a ``psum`` over it recovers the full gradient; then
  the usual ``pmean`` over data. One compiled step, microbatching via
  ``lax.scan`` inside — no Python per-microbatch dispatch;
- parity: ``make_pp_reference_step`` runs the SAME stacked parameters
  sequentially (no mesh) — tests/test_pp_lm.py asserts loss and parameter
  trajectories match the pipelined run.

Composability (round-3): dropout threads per-(step, stage, microbatch,
data-shard) rngs through the gpipe scan, reproducing the sequential
reference's masks bit-for-bit (and therefore resume parity); TP lives
INSIDE stages when the mesh carries a separate ``stage`` axis (stage
params stack-shard on ``stage`` AND Megatron-shard on ``model`` via
``TRANSFORMER_TP_RULES``); MoE blocks run inside stages with their
load-balancing aux losses accumulated only over REAL pipeline ticks
(garbage warm-up/drain contributions masked, gradients included).

Round-4 closes the last composability cell — EP-under-PP: experts shard
over the data axis inside each stage, the all_to_all exchange runs inside
every gpipe tick (all data ranks at a stage execute ticks in lockstep, so
the collective is matched; garbage-tick exchanges carry garbage and are
masked like every other warm-up/drain product), and the data-axis grad
combine is spec-aware so expert grads — already complete after the
transposed all_to_all — are not double-summed.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pytorch_distributed_tpu.models.transformer import Block, TransformerConfig
from pytorch_distributed_tpu.ops.fused_ce import fused_linear_cross_entropy
from pytorch_distributed_tpu.ops.losses import cross_entropy_loss
from pytorch_distributed_tpu.ops.optim import (
    clip_grads_by_global_norm,
    spec_axes,
)
from pytorch_distributed_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    shard_map,
)
from pytorch_distributed_tpu.parallel.pipeline import gpipe
from pytorch_distributed_tpu.train.state import TrainState


class PPEmbed(nn.Module):
    config: TransformerConfig

    @nn.compact
    def __call__(self, tokens):
        cfg = self.config
        x = nn.Embed(cfg.vocab_size, cfg.embed_dim, dtype=cfg.dtype, name="wte")(tokens)
        if cfg.pos_embedding == "rope":
            # rotation happens inside each stage's Attention (positions
            # are arange(l) — PP batches are never seq-sharded)
            return x
        pos = jnp.arange(tokens.shape[1])
        return x + nn.Embed(
            cfg.max_seq_len, cfg.embed_dim, dtype=cfg.dtype, name="wpe"
        )(pos)


class PPStage(nn.Module):
    """One pipeline stage: ``layers_per_stage`` real transformer Blocks.

    ``use_moe`` follows the global ``moe_every`` pattern; stage stacking
    requires the pattern to repeat identically per stage
    (``layers_per_stage % moe_every == 0`` — checked at state creation),
    so the within-stage layer index determines it.
    """

    config: TransformerConfig
    layers_per_stage: int
    deterministic: bool = True

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        # resolved absolute positions for rope (PP batches are never
        # seq-sharded, so positions are simply arange)
        pos = jnp.arange(x.shape[1])
        for j in range(self.layers_per_stage):
            use_moe = bool(cfg.n_experts) and (
                j % cfg.moe_every == cfg.moe_every - 1
            )
            x = Block(
                cfg, use_moe=use_moe, deterministic=self.deterministic,
                name=f"layer{j}",
            )(x, 0, pos)
        return x


class PPHead(nn.Module):
    config: TransformerConfig

    @nn.compact
    def __call__(self, x, return_hidden: bool = False):
        cfg = self.config
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_f")(x)
        head = nn.Dense(
            cfg.vocab_size, use_bias=False, dtype=cfg.dtype, name="lm_head"
        )
        if return_hidden:
            # fused-CE path: the caller streams the lm_head matmul into
            # the blockwise CE with params["head"]["lm_head"]["kernel"]
            # (ops/fused_ce.py) — same contract as TransformerLM.
            return x
        return head(x).astype(jnp.float32)


def create_pp_lm_state(
    config: TransformerConfig,
    n_stages: int,
    tx,
    rng: jax.Array,
    init_len: Optional[int] = None,
) -> TrainState:
    """TrainState whose params are {"embed", "stages", "head"} with stage
    params STACKED [S, ...]. Global-shaped like every sharded state here:
    placement (``shard_pp_state``) does the splitting.
    """
    if config.num_layers % n_stages:
        raise ValueError(
            f"num_layers {config.num_layers} not divisible by n_stages {n_stages}"
        )
    if config.vocab_parallel:
        raise ValueError(
            "vocab_parallel does not compose with the PP trainer: PPEmbed/"
            "PPHead params are stage-replicated and their grads psum over "
            "the stage axis (train/pp.py grad combine) — a vocab-sharded "
            "embedding there would need its own placement + combine rules. "
            "Use the (data, seq, model) LM trainer for vocab parallelism."
        )
    lps = config.num_layers // n_stages
    if config.n_experts and lps % config.moe_every:
        raise ValueError(
            f"stage stacking needs an identical MoE pattern per stage: "
            f"layers_per_stage {lps} must be divisible by moe_every "
            f"{config.moe_every}"
        )
    length = init_len or min(config.max_seq_len, 128)
    tokens = jnp.zeros((1, length), jnp.int32)

    # Init twin with TP and EP collectives off: parameter shapes are
    # GLOBAL (the convention throughout — placement shards), and init
    # needs no mesh axis in scope. Same trick as train.lm.create_lm_state.
    import dataclasses

    init_cfg = dataclasses.replace(
        config, model_axis=None, tp_size=1, expert_axis=None, ep_size=1
    )

    embed = PPEmbed(init_cfg)
    e_vars = embed.init(rng, tokens)
    x = embed.apply(e_vars, tokens)

    stage = PPStage(init_cfg, lps)
    stage_vars = [
        stage.init(jax.random.fold_in(rng, s), x)["params"]
        for s in range(n_stages)
    ]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *stage_vars)

    head = PPHead(config)
    h_vars = head.init(jax.random.fold_in(rng, n_stages), x)

    from pytorch_distributed_tpu.ops.precision import NoOpLossScaler

    params = {
        "embed": e_vars["params"],
        "stages": stacked,
        "head": h_vars["params"],
    }
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        batch_stats={},
        opt_state=tx.init(params),
        scaler=NoOpLossScaler.create(),
        apply_fn=None,
        tx=tx,
    )


def pp_state_specs(
    state: TrainState, axis: str = MODEL_AXIS, config=None
) -> TrainState:
    """Spec tree: stage stacks sharded P(axis) on dim 0, rest replicated.

    With a TP-enabled ``config`` (model_axis set, != ``axis``), stage
    leaves COMPOSE both placements: the stacked dim shards on the stage
    axis and the Megatron dims on the model axis per
    ``TRANSFORMER_TP_RULES`` (shifted right by the stack dim)."""
    from pytorch_distributed_tpu.parallel.tensor import (
        opt_state_specs,
        path_str,
    )
    from pytorch_distributed_tpu.train.lm import TRANSFORMER_TP_RULES

    use_tp = (
        config is not None
        and getattr(config, "model_axis", None) is not None
        and config.tp_size > 1
    )
    use_ep = (
        config is not None
        and getattr(config, "n_experts", 0)
        and getattr(config, "expert_axis", None) is not None
        and config.ep_size > 1
    )
    if use_tp and config.model_axis == axis:
        raise ValueError(
            f"TP-within-PP needs distinct axes: stage axis {axis!r} vs "
            f"config.model_axis {config.model_axis!r}"
        )

    # Combined rule set, all shifted right by the stage-stack dim below:
    # TP rules (canonical MODEL_AXIS remapped to the config's axis) plus
    # the conditional MoE placements (expert dim over the data axis for
    # EP, expert hidden dim over the model axis for TP — train/lm.py's
    # _moe_rules builds them from the config's own axis names).
    rules: tuple = ()
    if use_tp:
        rules += tuple(
            (pat, tuple(
                config.model_axis if part == MODEL_AXIS else part
                for part in spec
            ))
            for pat, spec in TRANSFORMER_TP_RULES
        )
    if config is not None and getattr(config, "n_experts", 0) and (
        use_tp or use_ep
    ):
        from pytorch_distributed_tpu.train.lm import _moe_rules

        rules += tuple((pat, tuple(spec)) for pat, spec in _moe_rules(config))

    def _stage_spec(path, leaf):
        import re

        tail = (None,) * (leaf.ndim - 1)
        p = path_str(path)
        for pat, spec in rules:
            if re.search(pat, p):
                tail = tuple(spec)
                break
        return P(*((axis,) + tail))

    param_specs = {
        "embed": jax.tree.map(lambda _: P(), state.params["embed"]),
        "stages": jax.tree_util.tree_map_with_path(
            _stage_spec, state.params["stages"]
        ),
        "head": jax.tree.map(lambda _: P(), state.params["head"]),
    }
    return state.replace(
        step=P(),
        params=param_specs,
        batch_stats={},
        opt_state=opt_state_specs(state.params, param_specs, state.tx),
        scaler=jax.tree.map(lambda _: P(), state.scaler),
    )


def shard_pp_state(mesh: Mesh, state: TrainState, axis: str = MODEL_AXIS,
                   config=None):
    from pytorch_distributed_tpu.parallel.mesh import specs_to_shardings

    n_stages = jax.tree.leaves(state.params["stages"])[0].shape[0]
    if n_stages != mesh.shape[axis]:
        raise ValueError(
            f"state has {n_stages} stages but mesh's {axis!r} axis is "
            f"{mesh.shape[axis]} — they must match"
        )
    specs = pp_state_specs(state, axis, config=config)
    return jax.device_put(state, specs_to_shardings(mesh, specs)), specs


def pp_dropout_key(base_key, stage_idx, mb_idx):
    """The ONE dropout-key derivation both the pipelined and the sequential
    reference steps use: fold (stage, microbatch) into the step's base key.
    Shared so bit-parity (incl. across suspend/resume) is by construction."""
    return jax.random.fold_in(jax.random.fold_in(base_key, stage_idx), mb_idx)


def _pp_loss(config, lps, params, batch, n_microbatches, axis,
             dropout_key=None, fused_ce: bool = True,
             fused_ce_block_n: int = 512):
    """Stage-local CE sum over this shard's pipeline output (real only on
    the last stage; the caller masks) plus this stage's REAL-tick MoE aux
    losses."""
    tokens = batch["tokens"]
    b, l = tokens.shape
    if b % n_microbatches:
        raise ValueError(
            f"local batch {b} not divisible by n_microbatches {n_microbatches}"
        )
    x = PPEmbed(config).apply({"params": params["embed"]}, tokens)
    mb = x.reshape(n_microbatches, b // n_microbatches, l, x.shape[-1])

    stage = PPStage(config, lps, deterministic=dropout_key is None)
    # shard_map delivers this stage's [1, ...] slice of the stack
    my_stage = jax.tree.map(lambda s: s[0], params["stages"])
    stage_idx = jax.lax.axis_index(axis)

    def stage_fn(sp, act, mb_idx):
        rngs = None
        if dropout_key is not None:
            rngs = {"dropout": pp_dropout_key(dropout_key, stage_idx, mb_idx)}
        out, mutated = stage.apply(
            {"params": sp}, act, rngs=rngs, mutable=["aux_loss", "moe_stats"]
        )
        aux = jnp.zeros((), jnp.float32)
        for leaf in jax.tree.leaves(mutated.get("aux_loss", {})):
            aux = aux + leaf
        return out, aux

    outs, aux = gpipe(stage_fn, my_stage, mb, axis=axis, has_aux=True)
    outs = outs.reshape(b, l, x.shape[-1])
    return _head_loss_sum(config, params["head"], outs, batch,
                          fused_ce, fused_ce_block_n), aux


def _head_loss_sum(config, head_params, outs, batch, fused_ce,
                   fused_ce_block_n: int = 512):
    """ln_f + lm_head + weighted CE sum — fused (blockwise, no
    materialized logits) or via the full-logits reference path."""
    if fused_ce:
        hidden = PPHead(config).apply(
            {"params": head_params}, outs, return_hidden=True
        )
        return fused_linear_cross_entropy(
            hidden,
            head_params["lm_head"]["kernel"],
            batch["labels"],
            batch["weights"],
            block_n=fused_ce_block_n,
            compute_dtype=config.dtype,
        )
    logits = PPHead(config).apply({"params": head_params}, outs)
    per_tok = cross_entropy_loss(
        logits.reshape(-1, logits.shape[-1]),
        batch["labels"].reshape(-1),
        reduction="none",
    )
    return jnp.sum(per_tok * batch["weights"].reshape(-1))


def make_pp_lm_train_step(
    mesh: Mesh,
    config: TransformerConfig,
    state_specs: TrainState,
    n_microbatches: int = 8,
    data_axis: str = DATA_AXIS,
    axis: str = MODEL_AXIS,
    dropout_seed: int = 0,
    grad_clip_norm: float = 0.0,
    fused_ce: bool = True,
    fused_ce_block_n: int = 512,
) -> Callable[[TrainState, dict], Tuple[TrainState, dict]]:
    """Compiled PP train step over a (data, stage[, model]) mesh.

    ``n_microbatches`` defaults to 8 from measurement (scripts/bench_pp.py,
    4 stages, 8-device mesh): the step-time curve tracks the GPipe tick
    model (M+S-1 ticks; bubble (S-1)/(M+S-1)) and flattens at M=8 —
    91.7 ms vs 91.3 at M=16 vs 121.7 at the old default of 4 — because
    per-tick overhead eats the shrinking bubble win beyond that. Metrics
    include the analytic ``pp_bubble_frac`` for the configured M/S so the
    JSONL log records the schedule's efficiency.

    ``batch``: {"tokens", "labels", "weights"} [B, L] sharded P(data) —
    every stage in a data-replica group sees the same tokens. With a
    TP-enabled config (``model_axis`` set, distinct from ``axis``), the
    Megatron collectives run INSIDE each stage over the model axis while
    activations travel the stage ring — pass a mesh carrying both axes
    and specs from ``pp_state_specs(state, axis, config=config)``.
    Dropout (``config.dropout > 0``) derives per-(step, data-shard, stage,
    microbatch) keys via ``pp_dropout_key`` — identical to the sequential
    reference, so trajectories (and resume) stay bit-par.
    """
    n_stages = mesh.shape[axis]
    if config.num_layers % n_stages:
        raise ValueError(
            f"num_layers {config.num_layers} not divisible by "
            f"{axis!r}={n_stages}"
        )
    if config.model_axis is not None:
        if config.model_axis == axis:
            raise ValueError(
                f"TP-within-PP needs distinct mesh axes (stage {axis!r} vs "
                f"model {config.model_axis!r}); a shared axis would psum "
                "activations across pipeline stages and train on garbage"
            )
        if config.model_axis not in mesh.shape:
            raise ValueError(
                f"config.model_axis {config.model_axis!r} not in mesh axes "
                f"{tuple(mesh.shape)}"
            )
        if mesh.shape[config.model_axis] != config.tp_size:
            raise ValueError(
                f"mesh {config.model_axis!r} size "
                f"{mesh.shape[config.model_axis]} != tp_size {config.tp_size}"
            )
    if config.n_experts and config.expert_axis is not None:
        # EP-under-PP: the all_to_all expert exchange runs over the data
        # axis inside every pipeline tick (all data ranks at a stage run
        # ticks in lockstep, so the collective is matched).
        if config.expert_axis != data_axis:
            raise ValueError(
                f"expert_axis must be the PP data axis {data_axis!r} "
                f"(experts shard over it), got {config.expert_axis!r}"
            )
        if config.ep_size > 1 and mesh.shape[data_axis] != config.ep_size:
            raise ValueError(
                f"ep_size {config.ep_size} must equal the mesh's data axis "
                f"size {mesh.shape[data_axis]}"
            )
        if config.n_experts % max(config.ep_size, 1):
            raise ValueError(
                f"n_experts {config.n_experts} not divisible by ep_size "
                f"{config.ep_size}"
            )
        if config.ep_size > 1:
            # Catch the easy mistake early: shard_pp_state called WITHOUT
            # config= builds replicated expert specs, and the mismatch
            # would otherwise surface as an opaque flax shape error at
            # trace time deep inside MoEMLP.
            from pytorch_distributed_tpu.parallel.tensor import path_str

            moe_specs = [
                (path_str(p), s)
                for p, s in jax.tree_util.tree_flatten_with_path(
                    state_specs.params["stages"]
                )[0]
                if "moe/w_" in path_str(p)
            ]
            if moe_specs and not all(
                config.expert_axis in spec_axes(s) for _, s in moe_specs
            ):
                raise ValueError(
                    "config runs expert parallelism but state_specs' MoE "
                    f"leaves are not sharded over {config.expert_axis!r}; "
                    "build the specs with shard_pp_state(mesh, state, "
                    "config=config) so the EP placement rules apply"
                )
    lps = config.num_layers // n_stages
    use_dropout = config.dropout > 0.0

    def _local_step(state: TrainState, batch: dict):
        global_count = jax.lax.psum(jnp.sum(batch["weights"]), data_axis)
        n_stages_rt = jax.lax.psum(1, axis)
        my_stage = jax.lax.axis_index(axis)
        n_data = jax.lax.psum(1, data_axis)
        dropout_key = None
        if use_dropout:
            # per-(step, data shard); stage/microbatch folded inside the
            # pipeline (pp_dropout_key). Model-axis replicas share keys.
            dropout_key = jax.random.fold_in(
                jax.random.fold_in(
                    jax.random.key(dropout_seed), state.step
                ),
                jax.lax.axis_index(data_axis),
            )

        def loss_fn(params):
            local_sum, aux = _pp_loss(
                config, lps, params, batch, n_microbatches, axis,
                dropout_key=dropout_key, fused_ce=fused_ce,
                fused_ce_block_n=fused_ce_block_n,
            )
            # Mask LOCALLY — no psum inside the differentiated function (a
            # param-dependent psum transposes to another psum and scales
            # gradients by the axis size; same rule as train/lm.py). Only
            # the last stage's pipeline output is real; the zero mask on
            # other stages kills their garbage branches' gradients, while
            # every stage still receives its true gradient through the
            # transposed ppermute ring from the last stage's loss. MoE aux
            # losses are REAL on every stage (their garbage ticks already
            # masked inside gpipe) and enter as this shard's share of the
            # data-mean of the stage-summed, microbatch-averaged total.
            mask = (my_stage == n_stages_rt - 1).astype(jnp.float32)
            return (
                mask * local_sum / jnp.maximum(global_count, 1.0)
                + aux / (n_microbatches * n_data)
            )

        # Each (data, stage) shard's loss_fn is its SHARE of the global
        # mean (nonzero only on last stages), so loss and gradients combine
        # by psum — the same identity train/lm.py uses.
        local_loss, grads = jax.value_and_grad(loss_fn)(state.params)
        loss = jax.lax.psum(local_loss, (data_axis, axis))

        # embedding/head: exactly one nonzero contributor on the model axis
        # (stage 0 / stage S-1) → psum reassembles; stages stay local.
        grads = {
            "embed": jax.lax.psum(grads["embed"], axis),
            "stages": grads["stages"],
            "head": jax.lax.psum(grads["head"], axis),
        }
        # Data-axis combine, spec-aware: an EP leaf (experts sharded over
        # the data axis) already owns its complete gradient — the bwd
        # all_to_all returned every rank's contribution to ITS experts —
        # so psum only leaves whose spec does NOT shard over data.
        grads = jax.tree.map(
            lambda g, spec: g if data_axis in spec_axes(spec)
            else jax.lax.psum(g, data_axis),
            grads, state_specs.params,
        )

        if grad_clip_norm:
            # Stage-stacked leaves are local to their stage (specs name
            # the stage axis; TP-within-PP leaves also name the model
            # axis) — sharded_global_norm psums their square-sums over
            # exactly those axes, so every stage clips by the same global
            # norm the sequential model would compute.
            grads, _ = clip_grads_by_global_norm(
                grads, grad_clip_norm, state_specs.params
            )

        updates, new_opt_state = state.tx.update(grads, state.opt_state, state.params)
        new_params = jax.tree.map(jnp.add, state.params, updates)
        new_state = state.replace(
            step=state.step + 1, params=new_params, opt_state=new_opt_state
        )
        return new_state, {
            "loss": loss,
            "tokens": global_count,
            "pp_bubble_frac": jnp.float32(
                (n_stages - 1) / (n_microbatches + n_stages - 1)
            ),
        }

    sharded = shard_map(
        _local_step,
        mesh=mesh,
        in_specs=(state_specs, P(data_axis)),
        out_specs=(state_specs, P()),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,))


def make_pp_lm_eval_step(
    mesh: Mesh,
    config: TransformerConfig,
    state_specs: TrainState,
    n_microbatches: int = 8,
    data_axis: str = DATA_AXIS,
    axis: str = MODEL_AXIS,
    fused_ce: bool = True,
    fused_ce_block_n: int = 512,
) -> Callable[[TrainState, dict, dict], dict]:
    """Validation under the pipeline: the same gpipe schedule forward-only
    (dropout off), loss summed on the last stage and psum'd global —
    ``eval_step(state, batch, acc) -> acc`` with the LM eval accumulator
    contract (``train.lm.empty_lm_metrics``)."""
    n_stages = mesh.shape[axis]
    if config.num_layers % n_stages:
        raise ValueError(
            f"num_layers {config.num_layers} not divisible by "
            f"{axis!r}={n_stages}"
        )
    lps = config.num_layers // n_stages

    def _local_eval(state: TrainState, batch: dict, acc: dict):
        local_sum, _ = _pp_loss(
            config, lps, state.params, batch, n_microbatches, axis,
            dropout_key=None, fused_ce=fused_ce,
            fused_ce_block_n=fused_ce_block_n,
        )
        my_stage = jax.lax.axis_index(axis)
        n_stages_rt = jax.lax.psum(1, axis)
        mask = (my_stage == n_stages_rt - 1).astype(jnp.float32)
        # the masked psum over (data, stage) picks exactly the last
        # stages' real sums; token counts are stage-replicated, so they
        # reduce over data only
        loss_sum = jax.lax.psum(mask * local_sum, (data_axis, axis))
        tokens = jax.lax.psum(jnp.sum(batch["weights"]), data_axis)
        return {
            "loss_sum": acc["loss_sum"] + loss_sum,
            "tokens": acc["tokens"] + tokens,
        }

    sharded = shard_map(
        _local_eval,
        mesh=mesh,
        in_specs=(state_specs, P(data_axis), P()),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(2,))


def make_pp_reference_step(
    config: TransformerConfig,
    n_stages: int,
    tx,
    n_microbatches: int = 1,
    dropout_seed: int = 0,
    fused_ce: bool = True,
    fused_ce_block_n: int = 512,
) -> Callable[[TrainState, dict], Tuple[TrainState, dict]]:
    """Sequential single-device step over the SAME stacked params — the
    golden reference the pipelined step must match bit-for-bit (up to fp
    reassociation). Microbatched like the pipeline (``n_microbatches``):
    dropout keys come from the shared ``pp_dropout_key`` derivation and
    MoE routing/aux see the same per-microbatch token groups, so the
    comparison is exact, not just statistical."""
    if config.num_layers % n_stages:
        raise ValueError("num_layers % n_stages != 0")
    lps = config.num_layers // n_stages
    use_dropout = config.dropout > 0.0

    @jax.jit
    def step(state: TrainState, batch: dict):
        count = jnp.sum(batch["weights"])
        base_key = None
        if use_dropout:
            base_key = jax.random.fold_in(
                jax.random.fold_in(jax.random.key(dropout_seed), state.step),
                0,  # data shard 0 — the single-device reference
            )

        def loss_fn(params):
            x = PPEmbed(config).apply({"params": params["embed"]}, batch["tokens"])
            b, l, e = x.shape
            mb = x.reshape(n_microbatches, b // n_microbatches, l, e)
            stage = PPStage(config, lps, deterministic=not use_dropout)
            aux_total = jnp.zeros((), jnp.float32)
            outs = []
            for m in range(n_microbatches):
                act = mb[m]
                for s in range(n_stages):
                    sp = jax.tree.map(lambda leaf: leaf[s], params["stages"])
                    rngs = None
                    if use_dropout:
                        rngs = {"dropout": pp_dropout_key(base_key, s, m)}
                    act, mutated = stage.apply(
                        {"params": sp}, act, rngs=rngs,
                        mutable=["aux_loss", "moe_stats"],
                    )
                    for leaf in jax.tree.leaves(mutated.get("aux_loss", {})):
                        aux_total = aux_total + leaf
                outs.append(act)
            x = jnp.concatenate(outs, axis=0)
            loss_sum = _head_loss_sum(
                config, params["head"], x, batch, fused_ce,
                fused_ce_block_n,
            )
            ce = loss_sum / jnp.maximum(count, 1.0)
            return ce + aux_total / n_microbatches

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        updates, new_opt_state = state.tx.update(grads, state.opt_state, state.params)
        new_params = jax.tree.map(jnp.add, state.params, updates)
        return (
            state.replace(step=state.step + 1, params=new_params,
                          opt_state=new_opt_state),
            {"loss": loss, "tokens": count},
        )

    return step
