"""Pipeline-parallel transformer training (GPipe over the model axis).

Round 1 left ``parallel.pipeline.gpipe`` moving activations for a toy
stage function (VERDICT missing #5); this trains the real ``TransformerLM``
block stack through it:

- the layer stack splits into S uniform stages of ``layers_per_stage``
  real ``models.transformer.Block``s; stage parameters are STACKED on a
  leading [S, ...] dim and placement-sharded P(model) — each device holds
  only its stage's slice (the PP memory win), same spec discipline as
  TP/EP/FSDP;
- embedding and head params are replicated; every stage computes the
  embedding (cheap, keeps gpipe's uniform-activation contract) but only
  stage 0's copy feeds the pipeline, and only the last stage's logits are
  real — a LOCAL zero mask kills the garbage branches' gradients (no psum
  inside the differentiated function: it would transpose to another psum
  and scale gradients by the stage count), and the loss is psum'd outside;
- gradients: stage params are stage-LOCAL over the model axis (no
  reduction); embedding/head grads have exactly one nonzero contributor on
  the model axis, so a ``psum`` over it recovers the full gradient; then
  the usual ``pmean`` over data. One compiled step, microbatching via
  ``lax.scan`` inside — no Python per-microbatch dispatch;
- parity: ``make_pp_reference_step`` runs the SAME stacked parameters
  sequentially (no mesh) — tests/test_pp_lm.py asserts loss and parameter
  trajectories match the pipelined run.

Dropout is rejected for now (rng plumbing through the gpipe scan is a
follow-up); use the (data, seq) path in ``train.lm`` for dropout training.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pytorch_distributed_tpu.models.transformer import Block, TransformerConfig
from pytorch_distributed_tpu.ops.losses import cross_entropy_loss
from pytorch_distributed_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    shard_map,
)
from pytorch_distributed_tpu.parallel.pipeline import gpipe
from pytorch_distributed_tpu.train.state import TrainState


class PPEmbed(nn.Module):
    config: TransformerConfig

    @nn.compact
    def __call__(self, tokens):
        cfg = self.config
        x = nn.Embed(cfg.vocab_size, cfg.embed_dim, dtype=cfg.dtype, name="wte")(tokens)
        pos = jnp.arange(tokens.shape[1])
        return x + nn.Embed(
            cfg.max_seq_len, cfg.embed_dim, dtype=cfg.dtype, name="wpe"
        )(pos)


class PPStage(nn.Module):
    """One pipeline stage: ``layers_per_stage`` real transformer Blocks."""

    config: TransformerConfig
    layers_per_stage: int

    @nn.compact
    def __call__(self, x):
        for j in range(self.layers_per_stage):
            x = Block(self.config, name=f"layer{j}")(x, 0)
        return x


class PPHead(nn.Module):
    config: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_f")(x)
        logits = nn.Dense(
            cfg.vocab_size, use_bias=False, dtype=cfg.dtype, name="lm_head"
        )(x)
        return logits.astype(jnp.float32)


def create_pp_lm_state(
    config: TransformerConfig,
    n_stages: int,
    tx,
    rng: jax.Array,
    init_len: Optional[int] = None,
) -> TrainState:
    """TrainState whose params are {"embed", "stages", "head"} with stage
    params STACKED [S, ...]. Global-shaped like every sharded state here:
    placement (``shard_pp_state``) does the splitting.
    """
    if config.num_layers % n_stages:
        raise ValueError(
            f"num_layers {config.num_layers} not divisible by n_stages {n_stages}"
        )
    if config.dropout:
        raise NotImplementedError(
            "pipeline-parallel training does not thread dropout rngs yet; "
            "set dropout=0.0 or use the (data, seq) LM path"
        )
    if config.model_axis is not None or config.tp_size > 1:
        raise ValueError(
            "PP repurposes the 'model' mesh axis as the STAGE axis; a "
            "TP-enabled config (model_axis/tp_size) would psum activations "
            "across pipeline stages and train on garbage. Unset model_axis "
            "for PP (TP-within-PP needs a fourth mesh axis — not built yet)."
        )
    if config.n_experts:
        raise NotImplementedError(
            "MoE blocks inside pipeline stages are untested under PP; use "
            "the (data, seq) LM path for expert parallelism"
        )
    lps = config.num_layers // n_stages
    length = init_len or min(config.max_seq_len, 128)
    tokens = jnp.zeros((1, length), jnp.int32)

    embed = PPEmbed(config)
    e_vars = embed.init(rng, tokens)
    x = embed.apply(e_vars, tokens)

    stage = PPStage(config, lps)
    stage_vars = [
        stage.init(jax.random.fold_in(rng, s), x)["params"]
        for s in range(n_stages)
    ]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *stage_vars)

    head = PPHead(config)
    h_vars = head.init(jax.random.fold_in(rng, n_stages), x)

    from pytorch_distributed_tpu.ops.precision import NoOpLossScaler

    params = {
        "embed": e_vars["params"],
        "stages": stacked,
        "head": h_vars["params"],
    }
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        batch_stats={},
        opt_state=tx.init(params),
        scaler=NoOpLossScaler.create(),
        apply_fn=None,
        tx=tx,
    )


def pp_state_specs(state: TrainState, axis: str = MODEL_AXIS) -> TrainState:
    """Spec tree: stage stacks sharded P(axis) on dim 0, rest replicated."""
    from pytorch_distributed_tpu.parallel.tensor import opt_state_specs

    param_specs = {
        "embed": jax.tree.map(lambda _: P(), state.params["embed"]),
        "stages": jax.tree.map(
            lambda leaf: P(*((axis,) + (None,) * (leaf.ndim - 1))),
            state.params["stages"],
        ),
        "head": jax.tree.map(lambda _: P(), state.params["head"]),
    }
    return state.replace(
        step=P(),
        params=param_specs,
        batch_stats={},
        opt_state=opt_state_specs(state.params, param_specs, state.tx),
        scaler=jax.tree.map(lambda _: P(), state.scaler),
    )


def shard_pp_state(mesh: Mesh, state: TrainState, axis: str = MODEL_AXIS):
    from pytorch_distributed_tpu.parallel.mesh import specs_to_shardings

    n_stages = jax.tree.leaves(state.params["stages"])[0].shape[0]
    if n_stages != mesh.shape[axis]:
        raise ValueError(
            f"state has {n_stages} stages but mesh's {axis!r} axis is "
            f"{mesh.shape[axis]} — they must match"
        )
    specs = pp_state_specs(state, axis)
    return jax.device_put(state, specs_to_shardings(mesh, specs)), specs


def _pp_loss(config, lps, params, batch, n_microbatches, axis):
    """Stage-local CE sum over this shard's pipeline output (real only on
    the last stage; the caller masks)."""
    tokens = batch["tokens"]
    b, l = tokens.shape
    if b % n_microbatches:
        raise ValueError(
            f"local batch {b} not divisible by n_microbatches {n_microbatches}"
        )
    x = PPEmbed(config).apply({"params": params["embed"]}, tokens)
    mb = x.reshape(n_microbatches, b // n_microbatches, l, x.shape[-1])

    stage = PPStage(config, lps)
    # shard_map delivers this stage's [1, ...] slice of the stack
    my_stage = jax.tree.map(lambda s: s[0], params["stages"])

    def stage_fn(sp, act):
        return stage.apply({"params": sp}, act)

    outs = gpipe(stage_fn, my_stage, mb, axis=axis)
    outs = outs.reshape(b, l, x.shape[-1])
    logits = PPHead(config).apply({"params": params["head"]}, outs)
    per_tok = cross_entropy_loss(
        logits.reshape(-1, logits.shape[-1]),
        batch["labels"].reshape(-1),
        reduction="none",
    )
    w = batch["weights"].reshape(-1)
    return jnp.sum(per_tok * w)


def make_pp_lm_train_step(
    mesh: Mesh,
    config: TransformerConfig,
    state_specs: TrainState,
    n_microbatches: int = 4,
    data_axis: str = DATA_AXIS,
    axis: str = MODEL_AXIS,
) -> Callable[[TrainState, dict], Tuple[TrainState, dict]]:
    """Compiled PP train step over a (data, model) mesh.

    ``batch``: {"tokens", "labels", "weights"} [B, L] sharded P(data) —
    every stage in a data-replica group sees the same tokens.
    """
    n_stages = mesh.shape[axis]
    if config.num_layers % n_stages:
        raise ValueError(
            f"num_layers {config.num_layers} not divisible by "
            f"{axis!r}={n_stages}"
        )
    lps = config.num_layers // n_stages

    def _local_step(state: TrainState, batch: dict):
        global_count = jax.lax.psum(jnp.sum(batch["weights"]), data_axis)
        n_stages_rt = jax.lax.psum(1, axis)
        my_stage = jax.lax.axis_index(axis)

        def loss_fn(params):
            local_sum = _pp_loss(
                config, lps, params, batch, n_microbatches, axis
            )
            # Mask LOCALLY — no psum inside the differentiated function (a
            # param-dependent psum transposes to another psum and scales
            # gradients by the axis size; same rule as train/lm.py). Only
            # the last stage's pipeline output is real; the zero mask on
            # other stages kills their garbage branches' gradients, while
            # every stage still receives its true gradient through the
            # transposed ppermute ring from the last stage's loss.
            mask = (my_stage == n_stages_rt - 1).astype(jnp.float32)
            return mask * local_sum / jnp.maximum(global_count, 1.0)

        # Each (data, stage) shard's loss_fn is its SHARE of the global
        # mean (nonzero only on last stages), so loss and gradients combine
        # by psum — the same identity train/lm.py uses.
        local_loss, grads = jax.value_and_grad(loss_fn)(state.params)
        loss = jax.lax.psum(local_loss, (data_axis, axis))

        # embedding/head: exactly one nonzero contributor on the model axis
        # (stage 0 / stage S-1) → psum reassembles; stages stay local.
        grads = {
            "embed": jax.lax.psum(grads["embed"], axis),
            "stages": grads["stages"],
            "head": jax.lax.psum(grads["head"], axis),
        }
        grads = jax.lax.psum(grads, data_axis)

        updates, new_opt_state = state.tx.update(grads, state.opt_state, state.params)
        new_params = jax.tree.map(jnp.add, state.params, updates)
        new_state = state.replace(
            step=state.step + 1, params=new_params, opt_state=new_opt_state
        )
        return new_state, {"loss": loss, "tokens": global_count}

    sharded = shard_map(
        _local_step,
        mesh=mesh,
        in_specs=(state_specs, P(data_axis)),
        out_specs=(state_specs, P()),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,))


def make_pp_reference_step(
    config: TransformerConfig,
    n_stages: int,
    tx,
) -> Callable[[TrainState, dict], Tuple[TrainState, dict]]:
    """Sequential single-device step over the SAME stacked params — the
    golden reference the pipelined step must match bit-for-bit (up to fp
    reassociation)."""
    if config.num_layers % n_stages:
        raise ValueError("num_layers % n_stages != 0")
    lps = config.num_layers // n_stages

    @jax.jit
    def step(state: TrainState, batch: dict):
        count = jnp.sum(batch["weights"])

        def loss_fn(params):
            x = PPEmbed(config).apply({"params": params["embed"]}, batch["tokens"])
            stage = PPStage(config, lps)
            for s in range(n_stages):
                sp = jax.tree.map(lambda leaf: leaf[s], params["stages"])
                x = stage.apply({"params": sp}, x)
            logits = PPHead(config).apply({"params": params["head"]}, x)
            per_tok = cross_entropy_loss(
                logits.reshape(-1, logits.shape[-1]),
                batch["labels"].reshape(-1),
                reduction="none",
            )
            return jnp.sum(per_tok * batch["weights"].reshape(-1)) / jnp.maximum(
                count, 1.0
            )

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        updates, new_opt_state = state.tx.update(grads, state.opt_state, state.params)
        new_params = jax.tree.map(jnp.add, state.params, updates)
        return (
            state.replace(step=state.step + 1, params=new_params,
                          opt_state=new_opt_state),
            {"loss": loss, "tokens": count},
        )

    return step
