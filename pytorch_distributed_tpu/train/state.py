"""Training state: one immutable pytree holding everything a step mutates.

The canonical checkpoint layout shared by every recipe (SURVEY.md §5:
the reference keeps ``latest.pt`` interchangeable across all four scripts by
always saving the unwrapped ``model.module.state_dict()``,
``restnet_ddp.py:37-44``). Here the equivalent invariant is: TrainState has
the same tree structure in every parallelism mode — only the sharding
differs — so a checkpoint from a single-chip run restores onto a pod and
vice versa.

Contents mirror the reference's checkpoint dict:
  params/batch_stats ≙ ``model.state_dict()``; opt_state ≙ ``optimizer``
  (and, because LR schedules are pure functions of the step count inside
  opt_state, also ≙ ``scheduler``); step ≙ ``step``; scaler ≙ the AMP
  GradScaler state (``resnet_ddp_apex.py:44``). ``epoch``/``best_acc`` are
  host-side loop state, stored next to this pytree by the checkpointer.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.struct
import jax
import jax.numpy as jnp
import optax

from pytorch_distributed_tpu.ops.precision import NoOpLossScaler


@flax.struct.dataclass
class TrainState:
    """Immutable step state; ``apply_fn``/``tx`` are static (not checkpointed)."""

    step: jax.Array
    params: Any
    batch_stats: Any
    opt_state: Any
    scaler: Any
    apply_fn: Callable = flax.struct.field(pytree_node=False)
    tx: optax.GradientTransformation = flax.struct.field(pytree_node=False)

    @classmethod
    def create(
        cls,
        model,
        tx: optax.GradientTransformation,
        rng: jax.Array,
        input_shape,
        scaler: Optional[Any] = None,
        input_dtype=jnp.float32,
    ) -> "TrainState":
        """Initialize from a flax module (≙ constructing model+optimizer,
        ``restnet_ddp.py:98,122``). ``input_dtype=jnp.int32`` for token
        models."""
        variables = model.init(rng, jnp.zeros(input_shape, input_dtype), train=False)
        params = variables["params"]
        batch_stats = variables.get("batch_stats", {})
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            batch_stats=batch_stats,
            opt_state=tx.init(params),
            scaler=scaler if scaler is not None else NoOpLossScaler.create(),
            apply_fn=model.apply,
            tx=tx,
        )

    def param_count(self) -> int:
        return sum(int(jnp.size(p)) for p in jax.tree.leaves(self.params))
