"""Compiled LM training step over a (data, seq) mesh.

The image trainer's step (``train/step.py``) parallelizes over ``data``
only; language-model training adds the ``seq`` axis: the token sequence is
split across devices, attention goes global through the ring
(``parallel.sequence``), and gradients are combined over BOTH axes — every
device holds a full replica of the parameters, sharded activations only.
This is the long-context training configuration the reference cannot
express (SURVEY.md §2c: SP/CP absent).

Layout:
  tokens/labels  [B, L] → P(data, seq)    (labels are next-token targets,
                                           shifted on the host so the
                                           shard-boundary token's target
                                           lives with its logits)
  params/opt     replicated               (pure DP+SP; TP is the mesh's
                                           third axis, unused here)
  grad combine   psum over (data, seq) of each device's share of the
                 global-mean loss gradient
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from pytorch_distributed_tpu.ops.fused_ce import fused_linear_cross_entropy
from pytorch_distributed_tpu.ops.losses import cross_entropy_loss
from pytorch_distributed_tpu.ops.optim import (
    clip_grads_by_global_norm,
    spec_axes,
)
from pytorch_distributed_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    SEQ_AXIS,
    shard_map,
)
from pytorch_distributed_tpu.resilience.stepguard import finite_ok, guard_state
from pytorch_distributed_tpu.train.state import TrainState


def shift_labels(tokens, pad_id: int = 0):
    """Host-side next-token targets: labels[t] = tokens[t+1]; the final
    position predicts ``pad_id`` and is masked by ``weights``."""
    import numpy as np

    labels = np.concatenate(
        [tokens[:, 1:], np.full((tokens.shape[0], 1), pad_id, tokens.dtype)], axis=1
    )
    weights = np.ones_like(tokens, np.float32)
    weights[:, -1] = 0.0
    return labels, weights


def create_lm_state(
    config,
    tx,
    rng: jax.Array,
    init_len: Optional[int] = None,
) -> TrainState:
    """TrainState for a TransformerLM.

    Parameters are initialized through a dense-attention twin of the config
    (identical parameter tree; ring attention needs a mesh axis context that
    does not exist at init time), then the state's ``apply_fn`` is the real
    configured model.
    """
    import dataclasses

    from pytorch_distributed_tpu.models.transformer import TransformerLM

    # Init twin: dense attention (ring needs a mesh axis context that does
    # not exist at init) and no TP collectives. Parameter shapes are global
    # either way, so the produced tree serves every parallel layout.
    dense_cfg = dataclasses.replace(
        config, attention="dense", model_axis=None, tp_size=1,
        expert_axis=None, ep_size=1, ring_layout="contiguous",
    )
    init_model = TransformerLM(dense_cfg)
    state = TrainState.create(
        init_model,
        tx,
        rng,
        (1, init_len or min(config.max_seq_len, 128)),
        input_dtype=jnp.int32,
    )
    return state.replace(apply_fn=TransformerLM(config).apply)


# Megatron-style placement for TransformerLM parameters (paths from the flax
# module tree). Column-parallel layers shard their output dim, row-parallel
# their input dim; layernorms and wpe stay replicated. wte and lm_head stay
# replicated by DEFAULT; ``config.vocab_parallel`` shards their vocab dim
# (``_vocab_rules`` — conditional, like the MoE placements).
TRANSFORMER_TP_RULES = (
    (r"attn/qkv/kernel", P(None, None, MODEL_AXIS, None)),  # [E,3,H,D] → H
    (r"attn/qkv/bias", P(None, MODEL_AXIS, None)),  # [3,H,D] → H
    # GQA's split projections (models/transformer.py num_kv_heads)
    (r"attn/q/kernel", P(None, MODEL_AXIS, None)),  # [E,H,D] → H
    (r"attn/q/bias", P(MODEL_AXIS, None)),  # [H,D]
    (r"attn/kv/kernel", P(None, None, MODEL_AXIS, None)),  # [E,2,Hkv,D]
    (r"attn/kv/bias", P(None, MODEL_AXIS, None)),  # [2,Hkv,D]
    (r"attn/proj/kernel", P(MODEL_AXIS, None, None)),  # [H,D,E] → H
    (r"mlp_up/kernel", P(None, MODEL_AXIS)),  # [E,4E] → 4E
    (r"mlp_up/bias", P(MODEL_AXIS,)),  # [4E]
    (r"mlp_down/kernel", P(MODEL_AXIS, None)),  # [4E,E] → 4E
)

# MoE expert weights shard on TWO independent axes (models/moe.py): the
# expert dim over the DATA axis (GShard expert parallelism, when
# ep_size == data-axis size) and the expert HIDDEN dim over the MODEL axis
# (Megatron split inside each expert, when tp_size > 1). Rules are built
# per-config in lm_state_specs since both placements are conditional.


def _moe_rules(config):
    ep = (
        config.expert_axis
        if config.expert_axis is not None and config.ep_size > 1
        else None
    )
    tp = (
        config.model_axis
        if config.model_axis is not None and config.tp_size > 1
        else None
    )
    return (
        (r"moe/w_up", P(ep, None, tp)),  # [E, D, F]
        (r"moe/w_down", P(ep, tp, None)),  # [E, F, D]
    )


def _vocab_rules(config):
    """Vocab-parallel placements (config.vocab_parallel): wte shards its
    vocab rows, lm_head its vocab columns, both over the model axis."""
    tp = (
        config.model_axis
        if config.model_axis is not None and config.tp_size > 1
        else None
    )
    return (
        (r"wte/embedding", P(tp, None)),  # [V, E] → V
        (r"lm_head/kernel", P(None, tp)),  # [E, V] → V
    )


def _uses_vocab_parallel(config) -> bool:
    """Delegates to ``TransformerConfig.uses_vocab_parallel`` — the ONE
    predicate the model's head/embedding branch also consults, so the
    placement rules here and the collective branch in
    ``models/transformer.py`` cannot diverge (ADVICE r5 #3). The inline
    fallback covers duck-typed test configs without the method."""
    if config is None:
        return False
    fn = getattr(config, "uses_vocab_parallel", None)
    if fn is not None:
        return bool(fn())
    return (
        getattr(config, "vocab_parallel", False)
        and config.model_axis is not None
        and config.tp_size > 1
    )


def _has_moe_params(params) -> bool:
    from pytorch_distributed_tpu.parallel.tensor import path_str

    return any(
        "moe/w_" in path_str(p)
        for p, _ in jax.tree_util.tree_flatten_with_path(params)[0]
    )


def lm_state_specs(state: TrainState, rules=None, config=None) -> TrainState:
    """PartitionSpec pytree shaped like ``state``: params by the TP (and,
    when the config runs expert-parallel, EP) rules, optimizer state
    following its embedded parameter copies, everything else replicated.

    ``config`` (the TransformerConfig) is required when the params contain
    MoE experts — whether they shard over the data axis depends on its
    ``ep_size``, which the parameter tree alone cannot reveal.
    """
    from pytorch_distributed_tpu.parallel.tensor import (
        match_partition_rules,
        opt_state_specs,
    )

    if rules is None:
        rules = TRANSFORMER_TP_RULES
        if _has_moe_params(state.params):
            if config is None:
                raise ValueError(
                    "state contains MoE expert weights; pass the "
                    "TransformerConfig so their placement (ep_size/"
                    "expert_axis/tp_size) is known"
                )
            rules = rules + _moe_rules(config)
        if _uses_vocab_parallel(config):
            rules = rules + _vocab_rules(config)
    param_specs = match_partition_rules(rules, state.params)
    return state.replace(
        step=P(),
        params=param_specs,
        batch_stats=jax.tree.map(lambda _: P(), state.batch_stats),
        opt_state=opt_state_specs(state.params, param_specs, state.tx),
        scaler=jax.tree.map(lambda _: P(), state.scaler),
    )


def shard_lm_state(
    mesh: Mesh, state: TrainState, config=None, fsdp: bool = False
) -> Tuple[TrainState, TrainState]:
    """Place a (host or replicated) state onto the mesh per the TP/EP rules.

    Returns (placed_state, spec_state). For tp=1 meshes the specs shard
    nothing (every spec axis has size 1) and this is plain replication.
    ``config`` is required for MoE models (see ``lm_state_specs``) and is
    validated against the mesh: expert parallelism must span exactly the
    data axis, and a seq-sharded mesh requires ring attention.

    ``fsdp=True`` additionally ZeRO-shards the leaves the TP/EP rules
    leave REPLICATED over the data axis (storage only — the train step
    all_gathers them before the forward and reduce-scatters their grads;
    ``parallel.fsdp``). TP/EP placements are untouched, so FSDP composes
    with every other axis.
    """
    if config is not None:
        check_seq_parallel_attention(mesh, config)
    if config is not None and config.ep_size > 1:
        if config.expert_axis != DATA_AXIS:
            raise ValueError(
                f"expert_axis must be {DATA_AXIS!r} (the EP placement rule "
                f"shards experts over it), got {config.expert_axis!r}"
            )
        if config.ep_size != mesh.shape[DATA_AXIS]:
            raise ValueError(
                f"ep_size {config.ep_size} must equal the mesh's data axis "
                f"size {mesh.shape[DATA_AXIS]} (experts shard over the full "
                "data axis)"
            )
    from pytorch_distributed_tpu.parallel.mesh import specs_to_shardings

    specs = lm_state_specs(state, config=config)
    if fsdp:
        specs = _overlay_fsdp_specs(specs, state, mesh, config)
    return jax.device_put(state, specs_to_shardings(mesh, specs)), specs


def _lm_placement_rules(tree, config):
    """The TP(+EP) rule set for a params-shaped tree (paths only); MoE
    trees require the config so EP's data-axis expert shards are
    distinguishable from FSDP storage shards."""
    rules = TRANSFORMER_TP_RULES
    if _has_moe_params(tree):
        if config is None:
            raise ValueError(
                "FSDP over a MoE state needs the TransformerConfig — "
                "without it EP's data-axis expert shards are "
                "indistinguishable from FSDP storage shards"
            )
        rules = rules + _moe_rules(config)
    if _uses_vocab_parallel(config):
        rules = rules + _vocab_rules(config)
    return rules


def _rule_claimed(name: str, rules, mesh: Mesh) -> bool:
    """True if a TP/EP rule EFFECTIVELY claims this path: a matched rule
    whose every named mesh axis has size 1 shards nothing (tp=1 meshes —
    the Megatron specs are vacuous there, so the block matrices, most of
    the model, correctly fall through to ZeRO). The ONE shared claim
    test for the overlay and the step."""
    import re

    for pattern, spec in rules:
        if re.search(pattern, name):
            return any(mesh.shape.get(a, 1) > 1 for a in spec_axes(spec))
    return False


def lm_fsdp_membership(params, mesh: Mesh, config=None,
                       data_axis: str = DATA_AXIS):
    """Boolean params-shaped tree: which leaves the FSDP overlay shards —
    big enough for ``fsdp_dim`` and not effectively rule-claimed.
    ``params`` must carry GLOBAL shapes (use outside shard_map; local
    tracer shapes would misapply the min-shard threshold)."""
    from pytorch_distributed_tpu.parallel.fsdp import fsdp_dim
    from pytorch_distributed_tpu.parallel.tensor import path_str

    rules = _lm_placement_rules(params, config)
    data_size = mesh.shape[data_axis]

    def member(path, leaf):
        shape = getattr(leaf, "shape", ())
        if fsdp_dim(shape, data_size) is None:
            return False  # tiny / indivisible: replicate
        return not _rule_claimed(path_str(path), rules, mesh)

    return jax.tree_util.tree_map_with_path(member, params)


def _fsdp_gather_tree(specs_params, mesh: Mesh, config=None,
                      data_axis: str = DATA_AXIS):
    """Step-side gather mask, derived from the overlay's OUTPUT (the
    storage spec tree) so it cannot diverge from the storage decision:
    a leaf is gathered iff its storage spec names the data axis and no
    rule effectively claims it (EP expert shards also name data — the
    shared ``_rule_claimed`` excludes them)."""
    from pytorch_distributed_tpu.parallel.tensor import path_str

    rules = _lm_placement_rules(specs_params, config)

    def is_gather(path, storage):
        if data_axis not in spec_axes(storage):
            return False
        return not _rule_claimed(path_str(path), rules, mesh)

    return jax.tree_util.tree_map_with_path(is_gather, specs_params)


def _overlay_fsdp_specs(specs: TrainState, state: TrainState, mesh: Mesh,
                        config=None) -> TrainState:
    """ZeRO overlay: every ``lm_fsdp_membership`` leaf gets the FSDP
    data-axis placement (largest divisible dim); opt-state follows.
    Rule-claimed leaves keep their compute placement."""
    from pytorch_distributed_tpu.parallel.fsdp import fsdp_param_specs
    from pytorch_distributed_tpu.parallel.tensor import opt_state_specs

    fsdp_specs = fsdp_param_specs(state.params, mesh, DATA_AXIS)
    members = lm_fsdp_membership(state.params, mesh, config)
    param_specs = jax.tree.map(
        lambda tp_spec, fs_spec, m: fs_spec if m else tp_spec,
        specs.params, fsdp_specs, members,
    )
    return specs.replace(
        params=param_specs,
        opt_state=opt_state_specs(state.params, param_specs, state.tx),
    )


def _shard_positions(config, lq: int, seq_axis: str):
    """This shard's ABSOLUTE token positions: ``(positions, offset)``.

    Contiguous layout: ``positions=None`` and the scalar shard offset (the
    convention every attention path accepts). Zigzag: a [lq] position
    VECTOR following the chunk-pair map (shard r holds chunks
    (r, 2s-1-r) of the 2s-chunk decomposition) and offset 0 — wpe must
    embed the true absolute positions even though the shard's tokens are
    not contiguous."""
    if (
        config is not None
        and getattr(config, "ring_layout", "contiguous") == "zigzag"
    ):
        c = lq // 2
        r = jax.lax.axis_index(seq_axis)
        s = jax.lax.psum(1, seq_axis)
        positions = jnp.concatenate([
            r * c + jnp.arange(c), (2 * s - 1 - r) * c + jnp.arange(c)
        ])
        return positions, 0
    return None, jax.lax.axis_index(seq_axis) * lq


def check_seq_parallel_attention(mesh: Mesh, config, seq_axis: str = SEQ_AXIS):
    """Refuse silently-wrong sequence parallelism.

    Under a seq-sharded shard_map, dense/blockwise/flash attention computes
    shard-LOCAL attention — each shard only attends to its own tokens — and
    trains on wrong math without any error. Only the ring variants go
    global. Raise up front instead of producing a subtly broken model.
    """
    if (
        seq_axis in mesh.shape
        and mesh.shape[seq_axis] > 1
        and getattr(config, "attention", None) not in ("ring", "ring_flash")
    ):
        raise ValueError(
            f"mesh shards the sequence axis {seq_axis!r} "
            f"(size {mesh.shape[seq_axis]}) but config.attention="
            f"{getattr(config, 'attention', None)!r}: non-ring attention is "
            "shard-local under sequence parallelism and computes the wrong "
            "function. Use attention='ring'/'ring_flash' (or a seq-axis "
            "size of 1)."
        )


def _lm_loss_sum(apply_out, params, batch, config, use_fused, block_n):
    """Weighted CE sum for one step's model output — the ONE loss tail
    both the train and eval steps use. ``apply_out`` is post-ln_f hidden
    states (fused path) or full logits (``use_fused=False``; under
    vocab_parallel the model already all_gathered them)."""
    if use_fused:
        return fused_linear_cross_entropy(
            apply_out,
            params["lm_head"]["kernel"],
            batch["labels"],
            batch["weights"],
            block_n=block_n,
            compute_dtype=config.dtype,
            # vocab-parallel head: the kernel leaf here is the LOCAL
            # [E, V/tp] shard; the fused CE combines the streamed softmax
            # stats across shards and psums dx the row-parallel way
            vocab_axis=(
                config.model_axis if _uses_vocab_parallel(config) else None
            ),
        )
    per_tok = cross_entropy_loss(
        apply_out.reshape(-1, apply_out.shape[-1]),
        batch["labels"].reshape(-1),
        reduction="none",
    )
    return jnp.sum(per_tok * batch["weights"].reshape(-1))


def make_lm_train_step(
    mesh: Mesh,
    data_axis: str = DATA_AXIS,
    seq_axis: str = SEQ_AXIS,
    state_specs: Optional[TrainState] = None,
    config=None,
    dropout_seed: int = 0,
    grad_clip_norm: float = 0.0,
    fsdp: bool = False,
    fused_ce: bool = True,
    fused_ce_block_n: int = 512,
    nan_guard: bool = False,
) -> Callable[[TrainState, dict], Tuple[TrainState, dict]]:
    """Build ``step(state, batch) -> (state, metrics)``.

    ``batch``: {"tokens": [B, L] i32, "labels": [B, L] i32,
    "weights": [B, L] f32} as global arrays sharded P(data, seq).
    ``state_specs``: TrainState-shaped PartitionSpec tree (from
    ``lm_state_specs``) when parameters are tensor-parallel; default fully
    replicated. Gradients are psum'd over (data, seq) only — the model-axis
    collectives live inside the model via tp_copy/tp_reduce, which leave
    sharded-param grads local and replicated-param grads already complete.
    ``config`` (the TransformerConfig), when given, is validated against the
    mesh: a seq-sharded mesh requires ring attention
    (``check_seq_parallel_attention``); it also enables dropout rng
    plumbing when ``config.dropout > 0``.

    Dropout rng: derived per step from (``dropout_seed``, ``state.step``,
    this shard's data/seq coordinates) — a resumed run reproduces the exact
    masks of an uninterrupted one, and model-axis replicas (which hold
    replicated activations at every dropout site) share one mask.

    ``fused_ce`` (default, requires ``config``): the loss tail runs
    ``ops.fused_ce.fused_linear_cross_entropy`` — the lm_head matmul is
    streamed blockwise into the logsumexp, so the fp32 ``[B, L, V]``
    logits tensor never exists in HBM (the r4 memory wall at bs8/L4096).
    Numerically it accumulates logits in fp32 where the unfused path
    materialized bf16 — equal-or-better. ``fused_ce=False`` or
    ``config=None`` keeps the materialized-logits path.

    ``nan_guard`` adds the resilience finite gate (resilience.stepguard):
    a non-finite global loss or gradient keeps the pre-step params and
    optimizer state via an on-device ``lax.cond`` select (``step`` still
    advances) and emits the replicated ``step_good`` metric. The verdict
    is ``pmin``'d over EVERY mesh axis: TP/EP-sharded gradient leaves
    legitimately differ across their axes, and a NaN visible to only one
    shard must flip the decision for all of them.
    """
    if config is not None:
        check_seq_parallel_attention(mesh, config, seq_axis)
    use_dropout = config is not None and getattr(config, "dropout", 0.0) > 0.0
    use_fused = fused_ce and config is not None
    axes = (data_axis, seq_axis)
    if fsdp and state_specs is None:
        raise ValueError(
            "fsdp=True needs state_specs (from shard_lm_state(..., "
            "fsdp=True)) — the gather/scatter dims live in the spec tree"
        )
    gather_tree = (
        _fsdp_gather_tree(state_specs.params, mesh, config, data_axis)
        if fsdp else None
    )

    def _local_step(state: TrainState, batch: dict):
        lq = batch["tokens"].shape[1]
        positions, offset = _shard_positions(config, lq, seq_axis)
        # Token count is param-independent, so its psum can live outside the
        # differentiated function. No param-dependent psum may sit inside
        # loss_fn: under shard_map a psum transposes to another psum, which
        # would scale the gradient by the axis size.
        global_count = jax.lax.psum(jnp.sum(batch["weights"]), axes)

        n_shards = jax.lax.psum(1, axes)

        if use_dropout:
            # Same key on every model-axis replica; unique per (step,
            # data, seq) shard.
            key = jax.random.fold_in(
                jax.random.key(dropout_seed), state.step
            )
            shard = jax.lax.axis_index(data_axis) * jax.lax.psum(
                1, seq_axis
            ) + jax.lax.axis_index(seq_axis)
            rngs = {"dropout": jax.random.fold_in(key, shard)}
        else:
            rngs = None

        if gather_tree is not None:
            # ZeRO unshard: all_gather only the FSDP-owned storage shards
            # (TP/EP leaves stay compute-sharded); XLA overlaps the
            # gathers with the forward ops that consume them.
            from pytorch_distributed_tpu.parallel.fsdp import gather_params

            model_params = gather_params(
                state.params, state_specs.params, data_axis,
                mask=gather_tree,
            )
        else:
            model_params = state.params

        def loss_fn(params):
            hidden_or_logits, mutated = state.apply_fn(
                {"params": params},
                batch["tokens"],
                position_offset=offset,
                positions=positions,
                mutable=["aux_loss", "moe_stats"],
                rngs=rngs,
                return_hidden=use_fused,
            )
            loss_sum = _lm_loss_sum(
                hidden_or_logits, params, batch, config, use_fused,
                fused_ce_block_n,
            )
            # This device's share of the global mean loss; sowed auxiliary
            # losses (MoE load balancing, pre-weighted) enter as their
            # across-shards mean.
            local = loss_sum / jnp.maximum(global_count, 1.0)
            for leaf in jax.tree.leaves(mutated.get("aux_loss", {})):
                local = local + leaf / n_shards
            return local, mutated

        # local_loss_i = s_i / C  ⇒  psum(grad local_loss_i) = grad of the
        # global mean loss w.r.t. the replicated params.
        (local_loss, mutated), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(model_params)
        loss = jax.lax.psum(local_loss, axes)
        if state_specs is None:
            grads = jax.lax.psum(grads, axes)
        else:
            # A parameter sharded over some axis (TP over model, EP over
            # data) owns its gradient there; psum only over the axes its
            # spec does NOT shard. FSDP leaves (storage shards, gathered
            # above) take the ZeRO reduce-scatter instead: psum_scatter
            # over data returns exactly the shard this device owns, SUM
            # semantics matching the share-of-global-mean loss convention,
            # then a plain psum over the seq axis completes the combine.
            from pytorch_distributed_tpu.parallel.fsdp import _sharded_dim

            def _reduce(g, spec, is_fsdp=False):
                if is_fsdp:
                    d = _sharded_dim(spec, data_axis)
                    g = jax.lax.psum_scatter(
                        g, data_axis, scatter_dimension=d, tiled=True
                    )
                    return jax.lax.psum(g, seq_axis)
                named = spec_axes(spec)
                ax = tuple(a for a in axes if a not in named)
                return jax.lax.psum(g, ax) if ax else g

            if gather_tree is not None:
                grads = jax.tree.map(
                    _reduce, grads, state_specs.params, gather_tree
                )
            else:
                grads = jax.tree.map(_reduce, grads, state_specs.params)
        count = global_count

        grad_norm = None
        if grad_clip_norm:
            # After the reduction above each leaf's grad is complete for
            # its own shard and replicated elsewhere — exactly the
            # precondition sharded_global_norm expects (it psums square-
            # sums over the axes each spec shards).
            grads, grad_norm = clip_grads_by_global_norm(
                grads, grad_clip_norm,
                state_specs.params if state_specs is not None else None,
            )

        updates, new_opt_state = state.tx.update(grads, state.opt_state, state.params)
        new_params = jax.tree.map(jnp.add, state.params, updates)
        new_state = state.replace(
            step=state.step + 1,
            params=new_params,
            opt_state=new_opt_state,
        )
        metrics = {"loss": loss, "tokens": count}
        if nan_guard:
            # pmin over every mesh axis: TP/EP gradient shards differ per
            # axis, and one shard's NaN must veto the update everywhere —
            # otherwise devices diverge on the select and the state splits
            good = (
                jax.lax.pmin(
                    finite_ok(loss, grads).astype(jnp.int32),
                    tuple(mesh.axis_names),
                )
                > 0
            )
            new_state = guard_state(good, new_state, state)
            metrics["step_good"] = good.astype(jnp.float32)
        if grad_norm is not None:
            metrics["grad_norm"] = grad_norm  # PRE-clip norm observable
        moe_stats = jax.tree.leaves(mutated.get("moe_stats", {}))
        if moe_stats:
            # mean over MoE layers, then over shards: the observable for
            # silent capacity drops (VERDICT r1 weak #6)
            local_frac = sum(moe_stats) / len(moe_stats)
            metrics["moe_dropped_frac"] = jax.lax.pmean(local_frac, axes)
        return new_state, metrics

    state_spec = state_specs if state_specs is not None else P()
    sharded = shard_map(
        _local_step,
        mesh=mesh,
        in_specs=(state_spec, P(data_axis, seq_axis)),
        out_specs=(state_spec, P()),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,))


def make_lm_eval_step(
    mesh: Mesh,
    data_axis: str = DATA_AXIS,
    seq_axis: str = SEQ_AXIS,
    state_specs: Optional[TrainState] = None,
    config=None,
    fsdp: bool = False,
    fused_ce: bool = True,
    fused_ce_block_n: int = 512,
) -> Callable[[TrainState, dict, dict], dict]:
    """Compiled evaluation step: ``eval_step(state, batch, acc) -> acc``.

    ``acc`` is a device-resident ``{"loss_sum", "tokens"}`` accumulator
    (start it at zeros); perplexity = exp(loss_sum / tokens) on the host
    after the epoch. Forward runs with ``train=False`` (dropout off); the
    per-token loss sum and token count are psum'd over (data, seq) so every
    shard (and host) carries the global totals — the reference's
    reduce-to-0 superset, same as the image eval step.

    MoE configs evaluate with RELAXED capacity (4× the train
    capacity_factor, clamped to n_experts): under tight train-time
    capacity, the routing a token gets depends on which other rows share
    its batch — zero-weight padding rows could displace real tokens'
    routes and make reported perplexity vary with the val-set padding.
    True dropless eval (capacity_factor = n_experts ⇒ capacity = k·T)
    would make the one-hot [T, E, C] dispatch tensors quadratic in local
    token count — terabytes at recipe defaults — so the bound is a modest
    multiple instead: at 4× the expected per-expert load, displacement of
    a real token requires an 4×-overloaded expert, which top-k routing on
    a trained router essentially never produces; routing is
    near-deterministic while dispatch stays O(T·E·C) with C ≪ T.
    """
    if config is not None:
        check_seq_parallel_attention(mesh, config, seq_axis)
    axes = (data_axis, seq_axis)
    use_fused = fused_ce and config is not None
    eval_apply = None
    if config is not None and getattr(config, "n_experts", 0):
        import dataclasses

        from pytorch_distributed_tpu.models.transformer import TransformerLM

        eval_cf = min(4.0 * config.capacity_factor, float(config.n_experts))
        eval_cfg = dataclasses.replace(config, capacity_factor=eval_cf)
        eval_apply = TransformerLM(eval_cfg).apply

    if fsdp and state_specs is None:
        raise ValueError(
            "fsdp=True needs state_specs (from shard_lm_state(..., "
            "fsdp=True))"
        )
    eval_gather_tree = (
        _fsdp_gather_tree(state_specs.params, mesh, config, data_axis)
        if fsdp else None
    )

    def _local_eval(state: TrainState, batch: dict, acc: dict):
        lq = batch["tokens"].shape[1]
        positions, offset = _shard_positions(config, lq, seq_axis)
        apply_fn = eval_apply if eval_apply is not None else state.apply_fn
        if eval_gather_tree is not None:
            from pytorch_distributed_tpu.parallel.fsdp import gather_params

            model_params = gather_params(
                state.params, state_specs.params, data_axis,
                mask=eval_gather_tree,
            )
        else:
            model_params = state.params
        out = apply_fn(
            {"params": model_params},
            batch["tokens"],
            position_offset=offset,
            positions=positions,
            train=False,
            return_hidden=use_fused,
        )
        loss_sum = _lm_loss_sum(
            out, model_params, batch, config, use_fused, fused_ce_block_n
        )
        return {
            "loss_sum": acc["loss_sum"] + jax.lax.psum(loss_sum, axes),
            "tokens": acc["tokens"]
            + jax.lax.psum(jnp.sum(batch["weights"]), axes),
        }

    state_spec = state_specs if state_specs is not None else P()
    sharded = shard_map(
        _local_eval,
        mesh=mesh,
        in_specs=(state_spec, P(data_axis, seq_axis), P()),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(2,))


def empty_lm_metrics() -> dict:
    return {"loss_sum": jnp.zeros((), jnp.float32),
            "tokens": jnp.zeros((), jnp.float32)}
