"""LMTrainer: the full training loop for language models.

Round-1 built the compiled LM step (``train/lm.py``) but no loop around it
(VERDICT missing #8): no epochs, no eval, no checkpoint/suspend for LMs.
This is the LM counterpart of ``train.Trainer`` — same reference-derived
contracts (epoch loop + ``set_epoch`` reshuffle, seekable mid-epoch step
resume, suspend→checkpoint→yield with the multi-host any-reduce agreement,
latest/best artifacts, JSONL metrics; ``restnet_ddp.py:19-47,127-150``) —
over a (data, seq, model) mesh with TP/EP/SP-sharded or replicated state:

- state placement and gradient reduction follow ``shard_lm_state``'s spec
  tree; checkpoints store the canonical GLOBAL layout via
  ``checkpoint.gather_global`` (all-ranks collective, rank-0 write), so a
  dp×sp×tp checkpoint restores onto any other mesh shape;
- validation reports token perplexity (``make_lm_eval_step``: global
  psum'd loss-sum/token-count, dropout off);
- best.ckpt tracks LOWEST validation perplexity (the LM analog of the
  reference's best-accuracy tracking, ``restnet_ddp.py:145-150``);
- dropout is deterministic under resume: masks derive from
  (seed, state.step, shard coords), never from wall clock.

Batch layout: the loader yields host-local ``{"tokens","labels","weights"}``
[B_local, L]; ``shard_lm_batch`` places them P(data, seq) as global arrays.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Optional

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from pytorch_distributed_tpu.compilecache.aot import attribute_compile
from pytorch_distributed_tpu.ops.optim import build_optimizer
from pytorch_distributed_tpu.ops.schedules import warmup_cosine
from pytorch_distributed_tpu.parallel import mesh as mesh_lib
from pytorch_distributed_tpu.train.base import SuspendableTrainer
from pytorch_distributed_tpu.train.lm import (
    create_lm_state,
    empty_lm_metrics,
    make_lm_eval_step,
    make_lm_train_step,
    shard_lm_state,
    shift_labels,
)
from pytorch_distributed_tpu.utils.checkpoint import Checkpointer
from pytorch_distributed_tpu.utils.logging import rank0_print
from pytorch_distributed_tpu.utils.profiling import MetricsLogger
from pytorch_distributed_tpu.utils.suspend import NullSuspendWatcher, SuspendWatcher


def lm_collate(samples) -> dict:
    """[L]-token samples → {"tokens", "labels", "weights"} [B, L]."""
    tokens = np.stack(samples).astype(np.int32)
    labels, weights = shift_labels(tokens)
    return {"tokens": tokens, "labels": labels, "weights": weights}


def shard_lm_batch(mesh, batch, data_axis=mesh_lib.DATA_AXIS,
                   seq_axis=mesh_lib.SEQ_AXIS, layout="contiguous"):
    """Host-local [B, L] arrays → global arrays sharded P(data, seq) —
    or P(data) alone on meshes without a seq axis (the PP×TP
    (data, stage, model) convention).

    ``layout="zigzag"``: every per-token array is host-permuted with
    ``parallel.sequence.zigzag_shard`` first, so the contiguous placement
    delivers chunk pair (r, 2s-1-r) to seq-shard r — tokens, labels, and
    weights permute identically and stay aligned; the LM steps feed wpe
    the matching position vector (train/lm.py ``_shard_positions``)."""
    if seq_axis in mesh.shape:
        sharding = NamedSharding(mesh, P(data_axis, seq_axis))
        s = mesh.shape[seq_axis]
    else:
        # PP×TP meshes carry (data, stage, model) — no seq axis; batches
        # shard over data only
        sharding = NamedSharding(mesh, P(data_axis))
        s = 1
    if layout == "zigzag" and s > 1:
        from pytorch_distributed_tpu.parallel.sequence import zigzag_shard

        batch = jax.tree.map(
            lambda x: zigzag_shard(np.asarray(x), s, axis=1), batch
        )
    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(
            sharding, np.asarray(x)
        ),
        batch,
    )


@dataclasses.dataclass
class LMTrainerConfig:
    epochs: int = 1
    batch_size: int = 8  # sequences per data-replica step
    lr: float = 3e-4
    weight_decay: float = 0.1
    warmup_steps: int = 0
    min_lr_ratio: float = 0.1
    optimizer: str = "adamw"
    save_dir: str = "output_lm"
    log_every: int = 100
    num_workers: int = 0
    prefetch: int = 2
    seed: int = 0
    suspend_sync_every: int = 1  # see TrainerConfig.suspend_sync_every
    # Global-norm gradient clipping (0 = off). Correct under ANY sharding:
    # the norm psums each leaf's square-sum over the axes its spec shards
    # (ops.optim.sharded_global_norm) — the loss-spike control the
    # reference's SGD ResNet never needed but an LM does.
    grad_clip_norm: float = 0.0
    # FSDP/ZeRO for the LM: leaves the TP/EP rules leave replicated shard
    # over the data axis at rest; the step all_gathers them before the
    # forward and reduce-scatters their grads (train/lm.py round 4 —
    # composes with TP, EP, SP, clipping, and the sharded checkpointer).
    fsdp: bool = False
    # Pipeline parallelism: > 0 trains through the GPipe executor
    # (train/pp.py). Stages ride the mesh's model axis on the standard
    # (data, seq, model) mesh, or a dedicated "stage" axis on a
    # (data, stage, model) mesh — the latter composes TP-within-PP
    # (model_axis/tp_size set, Megatron collectives inside each stage).
    # The batch shards over data only (seq axis must be 1); FSDP is
    # rejected. pp_microbatches follows BENCH_PP.md's measured default.
    pipeline_stages: int = 0
    pp_microbatches: int = 8
    # Step-interval durability (0 = off; see TrainerConfig) — non-blocking
    # sharded step-<global_step>.ckpt saves with keep-last-K retention.
    save_every_n_steps: int = 0
    keep_last_ckpts: int = 3
    # Resilience guards — see TrainerConfig: compiled finite gate
    # (skip-on-NaN, no host sync), rollback after max_bad_steps
    # consecutive bad steps, per-step deadline watchdog. nan_guard does
    # not compose with pipeline_stages (the GPipe executor owns its own
    # update path).
    nan_guard: bool = False
    max_bad_steps: int = 0
    watchdog_timeout_s: float = 0.0
    # Telemetry — see TrainerConfig: metrics_out overrides the JSONL
    # path (rank-0 gated in MetricsLogger); flush_every sizes the
    # on-device metrics ring (sync-free log path, drained lagged one
    # transfer per window; 0 = legacy blocking float() per log
    # interval); trace_dir writes the host span Chrome trace.
    metrics_out: Optional[str] = None
    trace_dir: Optional[str] = None
    flush_every: int = 32
    # Compile cache (compilecache/, ANALYSIS.md "Cold start & compile
    # cache"): compile_cache_dir points jax's persistent compilation
    # cache at a directory (env fallback PDT_COMPILE_CACHE_DIR) so a
    # relaunched or preemption-resumed run loads its step executables
    # from disk; warmup AOT-compiles the program registry (train + eval
    # step) before the first step, with the wall time attributed to the
    # goodput ledger's compile category and kind="warmup" manifest
    # records in the metrics JSONL.
    compile_cache_dir: Optional[str] = None
    warmup: bool = False
    # Elastic resume — see TrainerConfig: a run killed on mesh (4,2)
    # resumes on (2,2) or (8,1) (TP/FSDP state re-partitioned from the
    # rule tables, optimizer moments included); False = same-topology
    # restores only.
    elastic_resume: bool = True
    # Attribution & forensics — see TrainerConfig: anomaly sentinel over
    # step-time/data-wait (robust z, 0 = off), flight-recorder ring +
    # mirror + trigger dumps, fit-end per-program cost cards, live
    # Prometheus /metrics port.
    anomaly_threshold: float = 8.0
    anomaly_window: int = 64
    flightrec: bool = True
    cost_cards: bool = False
    metrics_port: Optional[int] = None
    # Host–device overlap profiling — see TrainerConfig.overlap: the
    # dispatch ledger (kind="overlap" JSONL) over train/eval launches,
    # lagged-fenced on the step's metrics outputs.
    overlap: bool = False


class LMTrainer(SuspendableTrainer):
    """Drives (TransformerConfig, token datasets) over a mesh."""

    def __init__(
        self,
        model_config,
        train_dataset,
        val_dataset,
        config: LMTrainerConfig,
        mesh: Optional[jax.sharding.Mesh] = None,
        suspend_watcher: Optional[SuspendWatcher] = None,
    ):
        from pytorch_distributed_tpu.data import DataLoader, DistributedSampler

        self.config = config
        self.model_config = model_config
        self._init_compilecache()  # before any compile: init programs too
        self.mesh = mesh if mesh is not None else mesh_lib.make_mesh()
        self.watcher = suspend_watcher or NullSuspendWatcher()
        self.ckpt = Checkpointer(config.save_dir)

        n_local = mesh_lib.local_replica_count(self.mesh)
        local_batch = config.batch_size * n_local
        self.train_sampler = DistributedSampler(
            len(train_dataset), num_replicas=jax.process_count(),
            rank=jax.process_index(), shuffle=True, seed=config.seed,
        )
        self.val_sampler = DistributedSampler(
            len(val_dataset), num_replicas=jax.process_count(),
            rank=jax.process_index(), shuffle=False, seed=config.seed,
        )
        self.train_loader = DataLoader(
            train_dataset, batch_size=local_batch, sampler=self.train_sampler,
            num_workers=config.num_workers, drop_last=True,
            prefetch=config.prefetch, seed=config.seed, collate_fn=lm_collate,
        )
        self.val_loader = DataLoader(
            val_dataset, batch_size=local_batch, sampler=self.val_sampler,
            num_workers=config.num_workers, drop_last=False,
            prefetch=config.prefetch, seed=config.seed, collate_fn=lm_collate,
        )
        self._local_batch = local_batch

        steps_per_epoch = len(self.train_loader)
        schedule = warmup_cosine(
            config.lr,
            total_steps=max(steps_per_epoch * config.epochs, 1),
            warmup_steps=config.warmup_steps,
            final_lr=config.lr * config.min_lr_ratio,
        )
        tx = build_optimizer(
            config.optimizer, schedule, weight_decay=config.weight_decay
        )
        if config.pipeline_stages > 0 and config.nan_guard:
            raise ValueError(
                "nan_guard does not compose with pipeline_stages: the "
                "GPipe executor owns its own update path (train/pp.py)"
            )
        if config.pipeline_stages > 0:
            from pytorch_distributed_tpu.train.pp import (
                create_pp_lm_state,
                make_pp_lm_eval_step,
                make_pp_lm_train_step,
                shard_pp_state,
            )

            s = config.pipeline_stages
            # Two mesh conventions:
            # - plain PP: the standard (data, seq, model) mesh with the
            #   MODEL axis carrying the stages (model_config.model_axis
            #   must be None);
            # - TP-within-PP: a (data, stage, model) mesh — a dedicated
            #   "stage" axis for the pipeline ring, the model axis for
            #   the Megatron collectives (model_config.model_axis set).
            if "stage" in self.mesh.shape:
                stage_axis = "stage"
                if model_config.model_axis is not None and (
                    self.mesh.shape.get(model_config.model_axis, 1)
                    != model_config.tp_size
                ):
                    raise ValueError(
                        f"mesh {model_config.model_axis!r} size "
                        f"{self.mesh.shape.get(model_config.model_axis)} "
                        f"!= tp_size {model_config.tp_size}"
                    )
                if (model_config.model_axis is None
                        and self.mesh.shape.get(mesh_lib.MODEL_AXIS, 1) > 1):
                    raise ValueError(
                        "the mesh carries a model axis of size "
                        f"{self.mesh.shape[mesh_lib.MODEL_AXIS]} but the model config "
                        "has no model_axis — every chip on it would do "
                        "duplicate work; set model_axis/tp_size or size "
                        "the axis to 1"
                    )
            else:
                stage_axis = mesh_lib.MODEL_AXIS
                if model_config.model_axis is not None:
                    raise ValueError(
                        "TP-within-PP needs a dedicated stage axis — "
                        "build the mesh with axis_names=('data', 'stage', "
                        "'model') (stage size = pipeline_stages, model "
                        "size = tp_size); on the standard mesh the "
                        "trainer runs stages on the model axis"
                    )
            if self.mesh.shape.get(stage_axis, 1) != s:
                raise ValueError(
                    f"pipeline_stages={s} needs the mesh's {stage_axis!r} "
                    f"axis to carry the stages "
                    f"(got {self.mesh.shape.get(stage_axis)}); build the "
                    "mesh with that axis sized to pipeline_stages"
                )
            if self.mesh.shape.get(mesh_lib.SEQ_AXIS, 1) > 1:
                raise ValueError(
                    "the PP trainer shards batches over data only; use "
                    "seq_parallel=1 (ring attention cannot run inside a "
                    "pipeline stage)"
                )
            if config.fsdp:
                raise ValueError(
                    "fsdp does not compose with pipeline_stages in the "
                    "trainer (stage stacks already shard the model axis)"
                )
            state = create_pp_lm_state(
                model_config, s, tx, jax.random.key(config.seed)
            )
            self.state, self.state_specs = shard_pp_state(
                self.mesh, state, axis=stage_axis, config=model_config
            )
            # microbatches divide the PER-DATA-SHARD batch, which is
            # config.batch_size by definition; clamp for small runs
            if config.pp_microbatches < 1:
                raise ValueError(
                    f"pp_microbatches must be >= 1, got "
                    f"{config.pp_microbatches}"
                )
            mb = min(config.pp_microbatches, config.batch_size)
            while config.batch_size % mb:
                mb -= 1
            if mb != config.pp_microbatches:
                rank0_print(
                    f"pp_microbatches {config.pp_microbatches} -> {mb} "
                    f"(must divide the per-shard batch {config.batch_size})"
                )
            self.train_step = make_pp_lm_train_step(
                self.mesh, model_config, self.state_specs,
                n_microbatches=mb,
                axis=stage_axis,
                dropout_seed=config.seed,
                grad_clip_norm=config.grad_clip_norm,
            )
            self.eval_step = make_pp_lm_eval_step(
                self.mesh, model_config, self.state_specs,
                n_microbatches=mb,
                axis=stage_axis,
            )
        else:
            state = create_lm_state(
                model_config, tx, jax.random.key(config.seed)
            )
            self.state, self.state_specs = shard_lm_state(
                self.mesh, state, model_config, fsdp=config.fsdp
            )
            self.train_step = make_lm_train_step(
                self.mesh, state_specs=self.state_specs, config=model_config,
                dropout_seed=config.seed,
                grad_clip_norm=config.grad_clip_norm,
                fsdp=config.fsdp,
                nan_guard=config.nan_guard,
            )
            self.eval_step = make_lm_eval_step(
                self.mesh, state_specs=self.state_specs, config=model_config,
                fsdp=config.fsdp,
            )
        # pre-fault the checkpoint snapshot arena while the first step
        # compiles — the first non-blocking best-save then stalls only for
        # its memcpy (see utils.checkpoint._Arena)
        self.ckpt.warm_for({"state": self.state})

        self.best_ppl = float("inf")
        self.start_epoch = 0
        self.start_step = 0
        self._init_resilience()  # stepguard + watchdog + telemetry
        self.ckpt.tracer = self.tracer  # ckpt snapshot/commit spans
        # rank-0 gating lives inside MetricsLogger now
        self.metrics_log = MetricsLogger(
            config.metrics_out
            or os.path.join(config.save_dir, "metrics.jsonl")
        )
        self._bind_observability()  # sentinel JSONL + live exporter

    # ---- program registry (compilecache/): the programs this trainer
    # compiles, with the batch avals the loader will actually produce ----

    def _registry_entries(self):
        sample = self.train_loader.collate_fn([self.train_loader.dataset[0]])
        gb = self._local_batch * jax.process_count()
        if mesh_lib.SEQ_AXIS in self.mesh.shape:
            spec = P(mesh_lib.DATA_AXIS, mesh_lib.SEQ_AXIS)
        else:  # PP (data, stage, model) meshes shard over data only
            spec = P(mesh_lib.DATA_AXIS)
        sharding = NamedSharding(self.mesh, spec)

        def batch_aval():
            return {
                k: jax.ShapeDtypeStruct(
                    (gb,) + np.asarray(v).shape[1:], np.asarray(v).dtype,
                    sharding=sharding,
                )
                for k, v in sample.items()
            }

        def train_avals():
            return [(self.state, batch_aval())]

        def eval_avals():
            # validate() zero-pads partial batches back to the full local
            # batch, so the eval step holds exactly ONE shape
            acc = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(
                    x.shape, x.dtype,
                    sharding=mesh_lib.replicated_sharding(self.mesh),
                ),
                empty_lm_metrics(),
            )
            return [(self.state, batch_aval(), acc)]

        # train budget 2: steady-state entry + the donation/layout retrace
        # the first dispatch settles through — the same pair no_recompile's
        # warmup_steps=2 window forgives (analysis/guards.py)
        return [
            ("lm_train_step", self.train_step, train_avals, 2),
            ("lm_eval_step", self.eval_step, eval_avals, 1),
        ]

    # ---- checkpoint contract: shared machinery in train/base.py ----

    def _extra_payload(self) -> dict:
        return {"best_ppl": self.best_ppl}

    def _restore_extra(self, restored: dict) -> None:
        self.best_ppl = float(restored["best_ppl"])

    # ---- loops ----

    def _emit_train_record(self, rec: dict) -> None:
        """Print + JSONL one train log event — same arithmetic as the
        legacy blocking path, so the two series are bit-identical."""
        vals = {k: v for k, v in rec.items() if k not in ("epoch", "step")}
        rank0_print(
            f"epoch {rec['epoch']} step {rec['step']}: "
            f"loss {rec['loss']:.4f}"
        )
        self.metrics_log.log(
            kind="train", epoch=rec["epoch"], step=rec["step"], **vals
        )

    def _drain_train_records(self, records) -> dict:
        last: dict = {}
        for rec in records:
            self._emit_train_record(rec)
            last = {
                k: v for k, v in rec.items() if k not in ("epoch", "step")
            }
        return last

    def train_epoch(self, epoch: int, start_step: int = 0) -> dict:
        cfg = self.config
        last: dict = {}
        t0 = time.perf_counter()
        steps_done = 0
        it = enumerate(
            self.train_loader.iter_batches(start_step), start=start_step
        )
        while True:
            t_wait = time.perf_counter()
            with self.goodput.timed("data_wait"), \
                    self.tracer.span("data_wait"):
                pair = next(it, None)
            self._observe_data_wait(time.perf_counter() - t_wait)
            if pair is None:
                break
            step, host_batch = pair
            host_batch = self._pre_step(host_batch)
            batch = shard_lm_batch(
                self.mesh, host_batch,
                layout=self.model_config.ring_layout,
            )
            # the run's first dispatch traces + compiles the step: split
            # its wall into compile (XLA backend / cache load) and trace
            # (Python lowering) so a warm start's ledger shows the cache
            # win; later recompiles are a guarded hazard, not steady state
            first = self._dispatched == 0
            with self.tracer.span("step_dispatch", step=step), \
                    attribute_compile(self.goodput if first else None), \
                    self.ledger.launch(0, "lm_train_step") as launch:
                self.state, metrics = self.train_step(self.state, batch)
                # fresh (non-donated) outputs: the lagged fence target
                launch.handle = metrics
            self._dispatched += 1
            self._post_step(metrics)
            steps_done += 1
            if cfg.log_every and step % cfg.log_every == 0:
                if cfg.flush_every > 0:
                    # sync-free: push the replicated scalars into the
                    # device ring; records drain lagged, one transfer
                    # per flush_every log events
                    last = self._drain_train_records(
                        self._telemetry_append(
                            metrics, epoch=epoch, step=step
                        )
                    ) or last
                else:
                    # legacy blocking path (flush_every=0): float()
                    # syncs the dispatch pipeline at every log interval
                    last = {k: float(v) for k, v in metrics.items()}
                    self._emit_train_record(
                        dict(last, epoch=epoch, step=step)
                    )
            self._maybe_save_step(epoch, step)
            self._maybe_suspend(epoch, step)
        self._epoch_end_guard()  # drain the guard's lag window
        last = self._drain_train_records(self._telemetry_flush()) or last
        tokens_per_step = last.get("tokens")
        if steps_done:
            float(self.state.step)  # drain async dispatch before the clock
            elapsed = time.perf_counter() - t0
            # cost-card join: epoch wall attributed to the step program
            self.prog_times.observe_total(
                "lm_train_step", elapsed, steps_done
            )
            record = {
                "kind": "epoch_timing", "epoch": epoch, "steps": steps_done,
                "mean_ms": 1e3 * elapsed / steps_done,
            }
            if tokens_per_step:
                record["tokens_per_s"] = tokens_per_step * steps_done / elapsed
            self.metrics_log.log(**record)
        return last

    def validate(self) -> dict:
        acc = jax.device_put(
            empty_lm_metrics(), mesh_lib.replicated_sharding(self.mesh)
        )
        wrap_pad = self.val_sampler.local_padding_mask()
        for b, host_batch in enumerate(self.val_loader.iter_batches(0)):
            n = host_batch["tokens"].shape[0]
            # Zero the weight of wrap-padded duplicates (uneven
            # process splits repeat indices, torch-style) so the psum'd
            # loss_sum/tokens count each real sequence exactly once —
            # unbiased perplexity, unlike torch's duplicate counting.
            rows = wrap_pad[b * self._local_batch : b * self._local_batch + n]
            if rows.any():
                host_batch = dict(host_batch)
                host_batch["weights"] = (
                    host_batch["weights"] * ~rows[:, None]
                ).astype(np.float32)
            pad = self._local_batch - n
            if pad:
                # zero-weight padding rows keep the compiled batch shape
                # (one program, no recompiles) and contribute no loss/tokens
                host_batch = {
                    k: np.concatenate(
                        [v, np.zeros((pad,) + v.shape[1:], v.dtype)]
                    )
                    for k, v in host_batch.items()
                }
            # no fence handle: the accumulator is donated into the next
            # eval call, so completion rides the t1 lower bound
            with self.ledger.launch(0, "lm_eval_step"):
                acc = self.eval_step(
                    self.state,
                    shard_lm_batch(self.mesh, host_batch,
                                   layout=self.model_config.ring_layout),
                    acc
                )
        acc = jax.device_get(acc)
        tokens = float(acc["tokens"])
        if tokens == 0.0:
            raise ValueError(
                "validation saw zero tokens — the val dataset is smaller "
                "than one global batch on every host; shrink batch_size or "
                "grow the val split"
            )
        mean = float(acc["loss_sum"]) / tokens
        return {"loss": mean, "ppl": float(np.exp(min(mean, 30.0))),
                "tokens": tokens}

    def fit(self) -> dict:
        """Re-entrant epoch loop — see ``Trainer.fit``: RollbackRequested
        from the step guard restores the last good checkpoint and resumes
        from its epoch/step, identically on every rank."""
        from pytorch_distributed_tpu.resilience.stepguard import (
            RollbackRequested,
        )

        self.goodput.start()
        self.try_resume()
        self._run_warmup()  # AOT-compile the registry before step 1
        summary: dict = {}
        epoch = self.start_epoch
        while epoch < self.config.epochs:
            t0 = time.time()
            self.train_sampler.set_epoch(epoch)
            start_step = self.start_step if epoch == self.start_epoch else 0
            try:
                self.train_epoch(epoch, start_step)
            except RollbackRequested as err:
                self._rollback(err)  # restores state + start_epoch/step
                epoch = self.start_epoch
                continue
            # commit last epoch's pending best-save: its file write
            # overlapped this epoch's training; all ranks reach this point
            # together, so the commit barrier is safely ordered
            with self.goodput.timed("checkpoint"), \
                    self.tracer.span("ckpt_save", commit=True):
                self.ckpt.wait()
            summary = self.validate()
            rank0_print(
                f"epoch {epoch}: val loss {summary['loss']:.4f} "
                f"ppl {summary['ppl']:.3f}"
            )
            if summary["ppl"] < self.best_ppl:
                self.best_ppl = summary["ppl"]
                # sharded, non-blocking: only the device→host snapshot runs
                # here; the file write rides a thread and the commit
                # (barrier + manifest) lands at the next wait() — a point
                # every rank reaches in the same order because the psum'd
                # ppl gives all ranks the same improvement decision
                with self.goodput.timed("checkpoint"), \
                        self.tracer.span("ckpt_save", best=True):
                    self.ckpt.save_best_sharded(
                        self._payload_live(epoch + 1, 0), block=False
                    )
                rank0_print(f"new best ppl {self.best_ppl:.3f}, saved best.ckpt")
            self.metrics_log.log(kind="val", epoch=epoch,
                                 epoch_s=time.time() - t0, **summary)
            epoch += 1
        with self.goodput.timed("checkpoint"):
            self.ckpt.wait()  # commit any pending best-save before return
        if self.watchdog is not None:
            self.watchdog.stop()
        self._log_cost_cards()  # per-program MFU/roofline attribution
        self._log_goodput()
        self._save_traces()
        if self.exporter is not None:
            self.exporter.stop()
        self.start_step = 0
        summary["best_ppl"] = self.best_ppl
        return summary
