"""The trainer: one SPMD loop serving all four reference recipes.

The reference implements the same epoch loop four times (SURVEY.md §2a, R1-R4)
— the scripts differ only in how replicas communicate. Here the loop exists
once and the communication mode is the ``Mesh`` passed in:

    1-device mesh          ≙ resnet_single_gpu.py
    local 8-chip mesh      ≙ resnet_dp.py        (without D5's scatter cost)
    multi-host mesh        ≙ restnet_ddp.py      (rendezvous via parallel.init_process_group)
    + precision="bf16"     ≙ resnet_ddp_apex.py  (no scaler needed on TPU)

Reproduced behaviors (each is a cited shared behavior from SURVEY.md §2a):
epoch loop with ``set_epoch`` reshuffle (``restnet_ddp.py:135-137``),
mid-epoch step resume — seekable, not read-and-discard
(``restnet_ddp.py:22-23`` improved per §3.5), suspend poll → checkpoint →
yield (``restnet_ddp.py:36-47``), resume-load restoring
model/optimizer/scheduler/best_acc/epoch/step (``restnet_ddp.py:127-132``),
per-epoch validation with cross-replica reduction (``restnet_ddp.py:50-70``),
best-checkpoint tracking (``restnet_ddp.py:145-150``), epoch timing log
(``restnet_ddp.py:136-146``), step-progress log every 100 steps
(``resnet_single_gpu.py:23-24``).
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Optional

import jax
import numpy as np

from pytorch_distributed_tpu.compilecache.aot import attribute_compile
from pytorch_distributed_tpu.ops.metrics import ClassificationMetrics
from pytorch_distributed_tpu.ops.optim import sgd_with_weight_decay
from pytorch_distributed_tpu.ops.precision import DynamicLossScaler, NoOpLossScaler
from pytorch_distributed_tpu.ops.schedules import step_lr
from pytorch_distributed_tpu.parallel import mesh as mesh_lib
from pytorch_distributed_tpu.train.base import SuspendableTrainer
from pytorch_distributed_tpu.train.state import TrainState
from pytorch_distributed_tpu.train.step import make_eval_step, make_train_step
from pytorch_distributed_tpu.utils.checkpoint import Checkpointer
from pytorch_distributed_tpu.utils.logging import rank0_print
from pytorch_distributed_tpu.utils.profiling import MetricsLogger, trace
from pytorch_distributed_tpu.utils.suspend import NullSuspendWatcher, SuspendWatcher


@dataclasses.dataclass
class TrainerConfig:
    """Hyperparameters, defaulted to the reference's hardcoded values
    (``restnet_ddp.py:77-83``, ``resnet_single_gpu.py:107-109``)."""

    epochs: int = 100
    batch_size: int = 400  # per data-replica, like DDP's per-process bs
    lr: float = 0.1
    momentum: float = 0.9
    weight_decay: float = 1e-4
    lr_step_epochs: int = 30
    lr_gamma: float = 0.1
    precision: str = "fp32"  # fp32 | bf16 | fp16 (fp16 adds a dynamic scaler)
    label_smoothing: float = 0.0
    save_dir: str = "output"
    log_every: int = 100  # ref resnet_single_gpu.py:23
    num_workers: int = 8
    prefetch: int = 2
    seed: int = 0
    # multi-host suspend agreement: how often (steps) hosts agree on a
    # suspend landing on ANY of them. 1 (default) = every step — a SIGTERM
    # delivered to one host makes all hosts checkpoint and yield together
    # (one tiny host-level collective per step, only when process_count>1;
    # without it the survivors deadlock at their next collective).
    # 0 = primary-only polling, the reference's exact (unsafe) semantics.
    suspend_sync_every: int = 1
    # FSDP/ZeRO-3: shard params+optimizer over the data axis (~axis-size
    # less state memory; identical training math — parallel/fsdp.py).
    fsdp: bool = False
    # Global-norm gradient clipping (0 = off); sharding-correct under FSDP
    # (ops.optim.sharded_global_norm), applied after scaler unscale.
    grad_clip_norm: float = 0.0
    # Step-interval durability (0 = off, the reference's policy: saves only
    # on suspend and on val improvement). Every N steps a NON-BLOCKING
    # sharded save lands in step-<global_step>.ckpt; retention keeps the
    # newest keep_last_ckpts completed ones, and resume picks the newest
    # restorable checkpoint (train/base.py, utils/checkpoint.py round 5).
    save_every_n_steps: int = 0
    keep_last_ckpts: int = 3
    # Resilience guards (resilience/, ANALYSIS.md "Failure model"):
    # nan_guard compiles a finite gate into the train step — a non-finite
    # loss/grad step keeps the pre-step params on device (lax.cond, no
    # host sync) and reports step_good; after max_bad_steps consecutive
    # bad steps (0 = never) the trainer rolls back to the last good
    # checkpoint. watchdog_timeout_s > 0 arms a per-step deadline thread
    # that dumps all-thread stacks on stall and latches the suspend path.
    nan_guard: bool = False
    max_bad_steps: int = 0
    watchdog_timeout_s: float = 0.0
    # Telemetry (telemetry/, ANALYSIS.md "Observability & goodput"):
    # metrics_out overrides the JSONL stream path (default
    # <save_dir>/metrics.jsonl; rank-0 gating lives inside MetricsLogger);
    # flush_every sizes the on-device metrics ring — log-interval metric
    # scalars are pushed by a donated compiled program and drained with
    # ONE lagged host transfer per window, so logging never stalls the
    # dispatch pipeline (0 = the legacy blocking float() sync, kept for
    # bit-identity A/B); trace_dir writes the host span Chrome trace
    # (spans.trace.json — data_wait/step_dispatch/ckpt_save/...).
    metrics_out: Optional[str] = None
    trace_dir: Optional[str] = None
    flush_every: int = 32
    # Compile cache (compilecache/, ANALYSIS.md "Cold start & compile
    # cache"): compile_cache_dir points jax's persistent compilation
    # cache at a directory (env fallback PDT_COMPILE_CACHE_DIR);
    # warmup AOT-compiles the train/eval program registry before the
    # first step (ledger compile attribution + kind="warmup" manifest).
    compile_cache_dir: Optional[str] = None
    warmup: bool = False
    # Elastic resume (reshard/, ANALYSIS.md "Elastic topology & reshard"):
    # restore checkpoints written on a DIFFERENT mesh shape by resolving
    # target shardings from this run's spec tree and assembling each
    # device's slices from the manifest block table — preemption can hand
    # back any topology. False refuses topology-mismatched candidates
    # (they fall through to older same-topology checkpoints).
    elastic_resume: bool = True
    # Attribution & forensics (telemetry/, ANALYSIS.md "Performance
    # attribution & forensics"): anomaly_threshold is the sentinel's
    # robust z-score bound over the step-time/data-wait series (0 = off;
    # MAD-based, immune to the first-step compile); flightrec keeps a
    # bounded ring of recent events mirrored to <save_dir>/flightrec.jsonl
    # and dumped atomically on stall/rollback/suspend/exception;
    # cost_cards emits kind="program_cost" records at fit end (one extra
    # AOT compile per program — a cache hit when compile_cache_dir is
    # set); metrics_port serves live Prometheus-text /metrics.
    anomaly_threshold: float = 8.0
    anomaly_window: int = 64
    flightrec: bool = True
    cost_cards: bool = False
    metrics_port: Optional[int] = None
    # Host–device overlap profiling (round 15; telemetry/overlap.py,
    # ANALYSIS.md "Host–device overlap"): the dispatch ledger records
    # every train/eval step launch's host dispatch wall, bounds device
    # completion with lagged fences (metrics outputs, k steps behind —
    # never a sync on the hot path), and classifies inter-launch gaps
    # into attributed bubbles as kind="overlap" JSONL.
    overlap: bool = False


class Trainer(SuspendableTrainer):
    """Drives (model, datasets) over a mesh with the config's recipe."""

    def __init__(
        self,
        model,
        train_dataset,
        val_dataset,
        config: TrainerConfig,
        mesh: Optional[jax.sharding.Mesh] = None,
        suspend_watcher: Optional[SuspendWatcher] = None,
        input_shape=(1, 224, 224, 3),
    ):
        from pytorch_distributed_tpu.data import DataLoader, DistributedSampler

        self.config = config
        self.model = model
        self._init_compilecache()  # before any compile: init programs too
        self.mesh = mesh if mesh is not None else mesh_lib.make_mesh()
        self.watcher = suspend_watcher or NullSuspendWatcher()
        self.ckpt = Checkpointer(config.save_dir)

        # Each process loads the shard its local chips will consume: sampler
        # splits by host (D10 semantics), loader batches local_replicas × bs.
        n_local = mesh_lib.local_replica_count(self.mesh)
        local_batch = config.batch_size * n_local
        self.train_sampler = DistributedSampler(
            len(train_dataset),
            num_replicas=jax.process_count(),
            rank=jax.process_index(),
            shuffle=True,
            seed=config.seed,
        )
        self.val_sampler = DistributedSampler(
            len(val_dataset),
            num_replicas=jax.process_count(),
            rank=jax.process_index(),
            shuffle=False,
            seed=config.seed,
        )
        self.train_loader = DataLoader(
            train_dataset,
            batch_size=local_batch,
            sampler=self.train_sampler,
            num_workers=config.num_workers,
            drop_last=True,
            prefetch=config.prefetch,
            seed=config.seed,
        )
        self.val_loader = DataLoader(
            val_dataset,
            batch_size=local_batch,
            sampler=self.val_sampler,
            num_workers=config.num_workers,
            drop_last=False,
            prefetch=config.prefetch,
            seed=config.seed,
        )

        steps_per_epoch = len(self.train_loader)
        schedule = step_lr(
            config.lr,
            steps_per_epoch,
            step_size_epochs=config.lr_step_epochs,
            gamma=config.lr_gamma,
        )
        tx = sgd_with_weight_decay(
            schedule, momentum=config.momentum, weight_decay=config.weight_decay
        )
        scaler = (
            DynamicLossScaler.create()
            if config.precision == "fp16"
            else NoOpLossScaler.create()
        )
        state = TrainState.create(
            model, tx, jax.random.key(config.seed), input_shape, scaler=scaler
        )
        if config.fsdp:
            from pytorch_distributed_tpu.parallel.fsdp import shard_fsdp_state

            self.state, self.state_specs = shard_fsdp_state(self.mesh, state)
        else:
            # Replicated placement ≙ DDP's broadcast-from-rank-0
            # (restnet_ddp.py:99).
            self.state = jax.device_put(
                state, mesh_lib.replicated_sharding(self.mesh)
            )
            self.state_specs = None

        self.train_step = make_train_step(
            self.mesh,
            label_smoothing=config.label_smoothing,
            state_specs=self.state_specs,
            grad_clip_norm=config.grad_clip_norm,
            nan_guard=config.nan_guard,
        )
        self.eval_step = make_eval_step(self.mesh, state_specs=self.state_specs)
        # pre-fault the checkpoint snapshot arena while the first step
        # compiles — the first non-blocking best-save then stalls only for
        # its memcpy (see utils.checkpoint._Arena)
        self.ckpt.warm_for({"state": self.state})

        self.best_acc = 0.0
        self.start_epoch = 0
        self.start_step = 0
        self._init_resilience()  # stepguard + watchdog + telemetry
        self.ckpt.tracer = self.tracer  # ckpt snapshot/commit spans

        # Observability (SURVEY.md §5: the reference has only time.time()
        # prints; we keep those AND stream machine-readable metrics).
        # Rank-0 gating lives inside MetricsLogger now.
        self.metrics_log = MetricsLogger(
            config.metrics_out
            or os.path.join(config.save_dir, "metrics.jsonl")
        )
        self._bind_observability()  # sentinel JSONL + live exporter

    # ---- program registry (compilecache/): the programs this trainer
    # compiles, with the batch avals the loaders will actually produce ----

    def _registry_entries(self):
        from jax.sharding import PartitionSpec as P  # noqa: F401

        sample = self.train_loader.collate_fn([self.train_loader.dataset[0]])
        pc = jax.process_count()
        local_batch = self.train_loader.batch_size
        gb = local_batch * pc
        sharding = mesh_lib.batch_sharding(self.mesh)

        def aval_for(b):
            return {
                k: jax.ShapeDtypeStruct(
                    (b,) + np.asarray(v).shape[1:], np.asarray(v).dtype,
                    sharding=sharding,
                )
                for k, v in sample.items()
            }

        def train_avals():
            return [(self.state, aval_for(gb))]

        def eval_batch_sizes():
            # validate() pads a partial FINAL batch only up to replica
            # divisibility (duplicate-counting val semantics), so the
            # eval step holds one program per distinct global batch size:
            # the full batch, plus the padded remainder when the local
            # sample count doesn't divide evenly.
            n_local_samples = self.val_sampler.num_samples
            n_replicas = mesh_lib.local_replica_count(self.mesh)
            sizes = []
            if n_local_samples >= local_batch:
                sizes.append(gb)
            rem = n_local_samples % local_batch
            if rem:
                rem += (-rem) % n_replicas
                if rem * pc not in sizes:
                    sizes.append(rem * pc)
            return sizes

        def eval_avals():
            metrics = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(
                    x.shape, x.dtype,
                    sharding=mesh_lib.replicated_sharding(self.mesh),
                ),
                ClassificationMetrics.empty(),
            )
            return [(self.state, aval_for(b), metrics)
                    for b in eval_batch_sizes()]

        # train budget 2: steady-state entry + the donation/layout retrace
        # the first dispatch settles through — the same pair no_recompile's
        # warmup_steps=2 window forgives (analysis/guards.py)
        return [
            ("train_step", self.train_step, train_avals, 2),
            ("eval_step", self.eval_step, eval_avals,
             max(len(eval_batch_sizes()), 1)),
        ]

    # ---- checkpoint contract (SURVEY.md §3.5): shared machinery in
    # train/base.py (payload gather, resume placement, suspend agreement);
    # the payload reads the trainer's LIVE best_acc, fixing the reference's
    # stale-best_acc bug (SURVEY.md §2a defects). ----

    def _extra_payload(self) -> dict:
        return {"best_acc": self.best_acc}

    def _restore_extra(self, restored: dict) -> None:
        self.best_acc = float(restored["best_acc"])

    # ---- the loops ----

    def _emit_train_record(self, rec: dict) -> None:
        """Print + JSONL one train log event (``rec`` carries the metric
        floats plus epoch/step). Same arithmetic as the legacy blocking
        path, so the two paths' series are bit-identical."""
        acc1 = 100.0 * rec["correct1"] / max(rec["count"], 1)
        rank0_print(
            f"epoch {rec['epoch']} step {rec['step']}: "
            f"loss {rec['loss']:.4f} acc1 {acc1:.2f}"
        )
        self.metrics_log.log(
            kind="train", epoch=rec["epoch"], step=rec["step"],
            loss=rec["loss"], acc1=acc1,
        )

    def _drain_train_records(self, records) -> dict:
        last: dict = {}
        for rec in records:
            self._emit_train_record(rec)
            last = {
                k: v for k, v in rec.items() if k not in ("epoch", "step")
            }
        return last

    def train_epoch(self, epoch: int, start_step: int = 0) -> dict:
        """One training epoch (ref ``train``, ``restnet_ddp.py:19-47``)."""
        cfg = self.config
        last = {}
        global_bs = mesh_lib.global_batch_size(self.mesh, cfg.batch_size)
        t0 = time.perf_counter()
        steps_done = 0
        it = enumerate(
            self.train_loader.iter_batches(start_step), start=start_step
        )
        while True:
            t_wait = time.perf_counter()
            with self.goodput.timed("data_wait"), \
                    self.tracer.span("data_wait"):
                pair = next(it, None)
            self._observe_data_wait(time.perf_counter() - t_wait)
            if pair is None:
                break
            step, host_batch = pair
            host_batch = self._pre_step(host_batch)
            batch = mesh_lib.shard_batch(self.mesh, host_batch)
            # the run's first dispatch traces + compiles the step: split
            # its wall into compile (XLA backend / cache load) and trace
            # (Python lowering) so a warm start's ledger shows the cache
            # win; later recompiles are a guarded hazard, not steady state
            first = self._dispatched == 0
            with self.tracer.span("step_dispatch", step=step), \
                    attribute_compile(self.goodput if first else None), \
                    self.ledger.launch(0, "train_step") as launch:
                self.state, metrics = self.train_step(self.state, batch)
                # metrics are fresh (non-donated) outputs every step —
                # the lagged fence blocks on them k steps later, the
                # exact PR 4 ring idiom
                launch.handle = metrics
            self._dispatched += 1
            self._post_step(metrics)
            steps_done += 1
            if cfg.log_every and step % cfg.log_every == 0:
                if cfg.flush_every > 0:
                    # sync-free: push the device scalars into the ring;
                    # records surface lagged, one transfer per window
                    last = self._drain_train_records(
                        self._telemetry_append(
                            metrics, epoch=epoch, step=step
                        )
                    ) or last
                else:
                    # legacy blocking path (flush_every=0): float() syncs
                    # the dispatch pipeline at every log interval
                    last = {k: float(v) for k, v in metrics.items()}
                    self._emit_train_record(
                        dict(last, epoch=epoch, step=step)
                    )
            self._maybe_save_step(epoch, step)
            self._maybe_suspend(epoch, step)
        self._epoch_end_guard()  # drain the guard's lag window
        last = self._drain_train_records(self._telemetry_flush()) or last
        if steps_done:
            # Drain the async dispatch queue with a value fetch before
            # reading the clock — per-step host timestamps would measure
            # dispatch gaps, not device time (first epoch includes compile,
            # same caveat as the reference's epoch timing).
            float(self.state.step)
            elapsed = time.perf_counter() - t0
            # cost-card join: this epoch's synced wall attributed to the
            # train step program (telemetry/costmodel.py)
            self.prog_times.observe_total("train_step", elapsed, steps_done)
            self.metrics_log.log(
                kind="epoch_timing", epoch=epoch, steps=steps_done,
                mean_ms=1e3 * elapsed / steps_done,
                items_per_s=global_bs * steps_done / elapsed,
            )
        return last

    def validate(self) -> dict:
        """Validation epoch (ref ``validate``, ``restnet_ddp.py:50-72``):
        device-resident accumulators, one global psum'd result on every host."""
        metrics = jax.device_put(
            ClassificationMetrics.empty(), mesh_lib.replicated_sharding(self.mesh)
        )
        n_local = mesh_lib.local_replica_count(self.mesh)
        for host_batch in self.val_loader.iter_batches(0):
            # Wrap-pad a partial final batch to replica divisibility — the
            # same duplicate-counting semantics torch's non-drop_last
            # DistributedSampler gives the reference's val loop
            # (restnet_ddp.py:118, D10 padding).
            n = host_batch["image"].shape[0]
            pad = (-n) % n_local
            if pad:
                # np.resize tiles cyclically, so pad > n (tiny final batch,
                # many replicas) still fills correctly.
                host_batch = {
                    k: np.resize(v, (n + pad,) + v.shape[1:])
                    for k, v in host_batch.items()
                }
            batch = mesh_lib.shard_batch(self.mesh, host_batch)
            # no fence handle: the accumulator is donated into the next
            # eval call, so completion rides the t1 lower bound
            with self.ledger.launch(0, "eval_step"):
                metrics = self.eval_step(self.state, batch, metrics)
        return jax.device_get(metrics).summary()

    def fit(self) -> dict:
        """Full run: resume → epochs → validate → best tracking → timing
        (ref ``main`` of every recipe, e.g. ``restnet_ddp.py:135-150``).

        The epoch loop is re-entrant for rollback: when the step guard
        condemns the run (``RollbackRequested`` after ``max_bad_steps``
        consecutive non-finite steps), the last good checkpoint is
        restored and the loop continues from ITS epoch/step — which may
        rewind epochs. Every rank takes the same path (replicated guard
        metric), preserving collective ordering."""
        from pytorch_distributed_tpu.resilience.stepguard import (
            RollbackRequested,
        )

        self.goodput.start()
        self.try_resume()
        self._run_warmup()  # AOT-compile the registry before step 1
        summary: dict = {}
        first_epoch = self.start_epoch  # trace only the first epoch run
        epoch = self.start_epoch
        while epoch < self.config.epochs:
            t0 = time.time()
            self.train_sampler.set_epoch(epoch)  # ref restnet_ddp.py:137
            start_step = self.start_step if epoch == self.start_epoch else 0
            # jax.profiler capture when PDT_TRACE_DIR is set — first epoch of
            # this run only (tracing all epochs would buffer multi-GB of
            # events on the host).
            try:
                with trace(enabled=bool(os.environ.get("PDT_TRACE_DIR"))
                           and epoch == first_epoch):
                    self.train_epoch(epoch, start_step)
            except RollbackRequested as err:
                self._rollback(err)  # restores state + start_epoch/step
                epoch = self.start_epoch
                continue
            # commit last epoch's pending best-save: its file write
            # overlapped this epoch's training; all ranks reach this point
            # together, so the commit barrier is safely ordered
            with self.goodput.timed("checkpoint"), \
                    self.tracer.span("ckpt_save", commit=True):
                self.ckpt.wait()
            summary = self.validate()
            rank0_print(
                f"epoch {epoch}: val loss {summary['loss']:.4f} "
                f"acc1 {summary['acc1']:.2f} acc5 {summary['acc5']:.2f}"
            )
            if summary["acc1"] > self.best_acc:
                self.best_acc = summary["acc1"]
                # sharded, non-blocking: only the device→host snapshot runs
                # here; the file write rides a thread and the commit
                # (barrier + manifest) lands at the next wait() — a point
                # every rank reaches in the same order because the psum'd
                # acc gives all ranks the same improvement decision
                with self.goodput.timed("checkpoint"), \
                        self.tracer.span("ckpt_save", best=True):
                    self.ckpt.save_best_sharded(
                        self._payload_live(epoch + 1, 0), block=False
                    )
                rank0_print(f"new best acc1 {self.best_acc:.2f}, saved best.ckpt")
            epoch_s = time.time() - t0
            rank0_print(
                f"epoch {epoch} cost time: {epoch_s:.1f} s"
            )  # ref restnet_ddp.py:146
            self.metrics_log.log(
                kind="val", epoch=epoch, epoch_s=epoch_s, **summary
            )
            epoch += 1
        with self.goodput.timed("checkpoint"):
            self.ckpt.wait()  # commit any pending best-save before return
        if self.watchdog is not None:
            self.watchdog.stop()
        self._log_cost_cards()  # per-program MFU/roofline attribution
        self._log_goodput()
        self._save_traces()
        if self.exporter is not None:
            self.exporter.stop()
        self.start_step = 0
        summary["best_acc"] = self.best_acc
        return summary
