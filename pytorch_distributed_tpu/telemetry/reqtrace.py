"""Request-lifecycle causal tracing: one trace per request, across owners.

``SpanTracer`` (telemetry/spans.py) records wall-clock *phases of the
host loop* — but its spans carry no request identity, so the per-request
JSONL records (``kind="request"/"preempt"/"swap"``) cannot be joined
into a causal timeline: which replica served rid 17, how long it sat in
the queue, whether the handoff or the preemption ate its tail latency.
This module is that join layer. A request's whole lifecycle — SLOGate
admission decision, queue wait, chunked prefill, the disaggregated
prefill→decode handoff, decode windows, preempt→park→restore, retire —
becomes ONE trace:

- ``trace`` id = the fleet-wide rid (requests keep their rid across
  replicas and the handoff, so the trace follows them for free);
- ``span`` ids are process-monotone; every span names its ``parent``
  (the root "request" span has none), so the trace is a tree by
  construction;
- ``seq`` is a global logical clock bumped once per emitted record —
  the one-loop fleet simulation ticks replicas from a single host loop,
  so seq order IS causal step-domain order even where wall clocks of
  two spans are too close to distinguish;
- every record is one versioned ``kind="span"`` line on the caller's
  ``MetricsLogger`` sink — same rotation and SIGKILL-durability story as
  the flight-recorder mirror: a killed process leaves every *begin*
  already on disk, which is exactly how a post-mortem finds the phase a
  request died in.

Record shapes (all carry ``kind="span"``, ``v=1``, ``trace``, ``span``,
``seq``, ``t`` [monotone seconds]):

- ``ev="begin"``: ``name``, ``parent`` (absent on the root), optional
  ``replica``, plus free-form attributes;
- ``ev="end"``: closes ``span``; ``dur_s`` plus attributes measured at
  close (e.g. a swap's measured wall next to its predicted cost);
- ``ev="event"``: an instant — gate decisions, prefill chunks, KV block
  transitions, restores; parented like a span;
- ``ev="link"``: a causal arrow between two spans that is NOT a parent
  edge (the handoff span → the adopted decode window); rendered as a
  Chrome-trace flow arrow.

``validate_trace`` is the completeness checker behind
``scripts/explain_request.py --assert-complete``: every begin closed
exactly once, parent links resolving to earlier spans of the same trace
(acyclic by the seq order), exactly one root, no orphan events, links
landing on known spans. ``chrome_trace`` renders the records for
Perfetto/chrome://tracing — one process ("request <rid>") per trace,
one thread row per replica, flow arrows across the handoff.

What seq does and does not guarantee: records emitted by the one host
loop are totally ordered, and that order embeds every happens-before
the loop enforces (admit before prefill, export before adopt). It says
NOTHING about wall-clock overlap on real hardware — two replicas'
device work is concurrent even though their host-side records
interleave — which is why spans carry ``t`` too, and why the async
fleet host (ROADMAP item 3) gates on this layer: wall attribution per
request has to exist before the loop goes event-driven.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Dict, Iterable, Iterator, List, Optional

#: schema version stamped into every record (bump on breaking change)
SPAN_SCHEMA_VERSION = 1

#: record keys owned by the tracer — span attributes must not shadow them
RESERVED_KEYS = frozenset(
    {"kind", "v", "ev", "trace", "span", "parent", "name", "seq", "t",
     "dur_s", "replica", "ts"}
)


class ReqTracer:
    """Per-request span emitter over a ``MetricsLogger``-shaped sink.

    ``sink`` needs one method, ``log(**record)`` (``None`` keeps records
    in memory only — ``self.records``). A disabled tracer costs one
    truthiness check per call site (the ``NULL_TRACER`` pattern), so
    every lifecycle owner threads one through unconditionally.

    Thread-safe: id/seq allocation, open-span bookkeeping, and the sink
    write happen under one lock, so ``seq`` order on disk matches
    allocation order even if a worker thread (ROADMAP item 3) emits
    concurrently with the main loop.
    """

    def __init__(self, sink=None, enabled: bool = True,
                 keep: Optional[bool] = None):
        self.enabled = bool(enabled)
        self.sink = sink
        #: in-memory mirror of every record (tests, in-process export);
        #: defaults to on only when there is no sink to hold them
        self.keep = (sink is None) if keep is None else bool(keep)
        self.records: List[dict] = []
        self._lock = threading.Lock()
        self._seq = 0
        self._next_span = 1
        self._open: Dict[int, dict] = {}  # span_id -> begin record
        self._roots: Dict[int, int] = {}  # trace (rid) -> root span id

    # -- emission ----------------------------------------------------------

    def claim_seq(self) -> int:
        """Allocate one tick of the logical clock WITHOUT emitting a
        record — the round-15 dispatch ledger (``telemetry.overlap``)
        stamps its launch windows from the same clock as the span
        stream, which is what makes "what spans landed between launch N
        and N+1" a pure seq-range query. Claimed seqs appear as gaps in
        the span stream's numbering; ``validate_trace`` only requires
        monotonicity, so gaps are legal."""
        with self._lock:
            s = self._seq
            self._seq += 1
            return s

    def _emit(self, record: dict) -> None:
        # caller holds the lock: seq order and sink order must agree
        record["seq"] = self._seq
        self._seq += 1
        if self.keep:
            self.records.append(record)
        if self.sink is not None:
            self.sink.log(**record)

    @staticmethod
    def _clean(attrs: dict) -> dict:
        bad = RESERVED_KEYS.intersection(attrs)
        if bad:
            raise ValueError(
                f"span attributes {sorted(bad)} shadow reserved record "
                f"keys {sorted(RESERVED_KEYS)}"
            )
        return {k: v for k, v in attrs.items() if v is not None}

    # -- spans -------------------------------------------------------------

    def open_root(self, rid: int, **attrs) -> int:
        """Open (or return) the trace's root "request" span. Idempotent:
        the gate decision opens it in a fleet, ``Scheduler.submit``
        opens it standalone — whichever runs first wins and the other
        sees the existing root."""
        if not self.enabled:
            return 0
        with self._lock:
            root = self._roots.get(rid)
            if root is not None:
                return root
        return self.begin(rid, "request", parent=0, **attrs)

    def root(self, rid: int) -> int:
        """The trace's root span id (0 when none is open yet)."""
        if not self.enabled:
            return 0
        with self._lock:
            return self._roots.get(rid, 0)

    def begin(self, rid: int, name: str, *, parent: Optional[int] = None,
              replica: Optional[int] = None, t: Optional[float] = None,
              **attrs) -> int:
        """Open a span; returns its id (0 when disabled). ``parent=None``
        defaults to the trace's root; ``parent=0`` makes THIS span the
        root. ``t`` backdates the start (a caller that only commits a
        span once it succeeded — the handoff — passes the wall it
        captured up front)."""
        if not self.enabled:
            return 0
        attrs = self._clean(attrs)
        with self._lock:
            if parent is None:
                parent = self._roots.get(rid, 0)
            span = self._next_span
            self._next_span += 1
            rec = {
                "kind": "span", "v": SPAN_SCHEMA_VERSION, "ev": "begin",
                "trace": rid, "span": span, "name": name,
                "t": time.perf_counter() if t is None else t,
            }
            if parent:
                rec["parent"] = parent
            if replica is not None:
                rec["replica"] = replica
            rec.update(attrs)
            self._open[span] = rec
            if not parent:
                self._roots[rid] = span
            self._emit(rec)
            return span

    def end(self, span: int, **attrs) -> None:
        """Close a span (no-op for id 0 / unknown ids — a disabled
        tracer hands out 0s, and double-close must not corrupt the
        stream)."""
        if not self.enabled or not span:
            return
        attrs = self._clean(attrs)
        with self._lock:
            begin = self._open.pop(span, None)
            if begin is None:
                return
            now = time.perf_counter()
            rec = {
                "kind": "span", "v": SPAN_SCHEMA_VERSION, "ev": "end",
                "trace": begin["trace"], "span": span, "t": now,
                "dur_s": round(now - begin["t"], 9),
            }
            rec.update(attrs)
            # Closing the root retires the trace: drop the rid→root
            # entry so _roots stays O(open traces), not O(rids ever)
            # (round 21 census finding — 100k sessions held 100k ints
            # here). A later open_root for a *harvested* rid still
            # finds its entry because abandon() deliberately leaves
            # dead-replica roots open; only a closed root is purged.
            trace = begin["trace"]
            if self._roots.get(trace) == span:
                del self._roots[trace]
            self._emit(rec)

    @contextlib.contextmanager
    def span(self, rid: int, name: str, **kw) -> Iterator[int]:
        """``begin``/``end`` as a context manager; yields the span id."""
        span = self.begin(rid, name, **kw)
        try:
            yield span
        finally:
            self.end(span)

    def event(self, rid: int, name: str, *, parent: Optional[int] = None,
              replica: Optional[int] = None, **attrs) -> int:
        """An instant record (gate decision, chunk, KV transition,
        restore) — gets its own span id so links can target it, but
        needs no close."""
        if not self.enabled:
            return 0
        attrs = self._clean(attrs)
        with self._lock:
            if parent is None:
                parent = self._roots.get(rid, 0)
            span = self._next_span
            self._next_span += 1
            rec = {
                "kind": "span", "v": SPAN_SCHEMA_VERSION, "ev": "event",
                "trace": rid, "span": span, "name": name,
                "t": time.perf_counter(),
            }
            if parent:
                rec["parent"] = parent
            if replica is not None:
                rec["replica"] = replica
            rec.update(attrs)
            self._emit(rec)
            return span

    def link(self, rid: int, src: int, dst: int, name: str = "flow") -> None:
        """A causal arrow between two spans of ``rid``'s trace that is
        not a parent edge — the handoff span → the decode window it
        enabled on the other replica. Rendered as a Perfetto flow
        arrow."""
        if not self.enabled or not src or not dst:
            return
        with self._lock:
            self._emit({
                "kind": "span", "v": SPAN_SCHEMA_VERSION, "ev": "link",
                "trace": rid, "span": src, "dst": dst, "name": name,
                "t": time.perf_counter(),
            })

    # -- live introspection (pdt_top's in-process twin reads the JSONL) ----

    def open_spans(self) -> List[dict]:
        with self._lock:
            return [dict(r) for r in self._open.values()]

    def open_traces(self) -> List[int]:
        """Traces whose ROOT span is still open — the in-flight
        requests."""
        with self._lock:
            return sorted(
                rid for rid, span in self._roots.items()
                if span in self._open
            )

    def census_decls(self):
        from .census import Decl

        return [
            Decl("records", lambda t: "unbounded" if t.keep else "fixed",
                 cap=lambda t: None if t.keep else 0,
                 why="keep-mode retains every record for in-process "
                     "assertions (tests/forensics); streaming mode "
                     "(sink set, keep=False) holds none"),
            Decl("_open", "live", per_live=8,
                 why="open begin records; a live request holds at most a "
                     "handful of concurrently-open spans (root, queue, "
                     "prefill/decode window, swap, handoff)"),
            Decl("_roots", "live",
                 why="rid→root map, purged when the root closes "
                     "(round 21); harvested rids' roots stay open by "
                     "design until the router resolves them"),
        ]


#: Shared no-op tracer (the NULL_TRACER pattern): lifecycle owners thread
#: one through without caring whether anyone is listening.
NULL_REQTRACER = ReqTracer(enabled=False)


# ---------------------------------------------------------------------------
# stream-side analysis: completeness, trees, Perfetto export
# ---------------------------------------------------------------------------


def span_records(records: Iterable[dict],
                 rid: Optional[int] = None) -> List[dict]:
    """The ``kind="span"`` records (of one trace, when ``rid`` is
    given), in seq order — the stable causal order, independent of file
    interleaving."""
    out = [
        r for r in records
        if r.get("kind") == "span" and (rid is None or r.get("trace") == rid)
    ]
    out.sort(key=lambda r: r.get("seq", 0))
    return out


def trace_rids(records: Iterable[dict]) -> List[int]:
    return sorted({
        r["trace"] for r in records
        if r.get("kind") == "span" and "trace" in r
    })


def validate_trace(records: Iterable[dict],
                   rid: Optional[int] = None) -> List[str]:
    """Completeness/causality errors for one trace (or every trace when
    ``rid`` is None). Empty list == the stream is a closed, acyclic,
    fully-parented span forest — the ``--assert-complete`` CI gate."""
    errors: List[str] = []
    for r in (trace_rids(records) if rid is None else [rid]):
        errors.extend(_validate_one(span_records(records, r), r))
    return errors


def _validate_one(recs: List[dict], rid: int) -> List[str]:
    errors: List[str] = []
    if not recs:
        return [f"trace {rid}: no span records"]
    begun: Dict[int, dict] = {}
    ended: Dict[int, dict] = {}
    events: Dict[int, dict] = {}
    roots: List[int] = []
    last_seq = -1
    for r in recs:
        seq = r.get("seq", -1)
        if seq <= last_seq:
            errors.append(
                f"trace {rid}: seq not strictly increasing at span "
                f"{r.get('span')} ({seq} after {last_seq})"
            )
        last_seq = seq
        ev = r.get("ev")
        span = r.get("span")
        if ev == "begin":
            if span in begun:
                errors.append(f"trace {rid}: span {span} begun twice")
            begun[span] = r
            parent = r.get("parent")
            if not parent:
                roots.append(span)
            elif parent not in begun and parent not in events:
                errors.append(
                    f"trace {rid}: span {span} ({r.get('name')}) parent "
                    f"{parent} not opened earlier in this trace"
                )
        elif ev == "end":
            if span not in begun:
                errors.append(f"trace {rid}: end for unopened span {span}")
            if span in ended:
                errors.append(f"trace {rid}: span {span} ended twice")
            ended[span] = r
        elif ev == "event":
            events[span] = r
            parent = r.get("parent")
            if parent and parent not in begun and parent not in events:
                errors.append(
                    f"trace {rid}: event {span} ({r.get('name')}) parent "
                    f"{parent} not opened earlier in this trace"
                )
        elif ev == "link":
            known = set(begun) | set(events)
            for end_key in ("span", "dst"):
                if r.get(end_key) not in known:
                    errors.append(
                        f"trace {rid}: link endpoint {r.get(end_key)} "
                        f"unknown"
                    )
        else:
            errors.append(f"trace {rid}: unknown ev {ev!r}")
    for span, r in begun.items():
        if span not in ended:
            errors.append(
                f"trace {rid}: span {span} ({r.get('name')}) never closed"
            )
    if len(roots) != 1:
        errors.append(
            f"trace {rid}: expected exactly one root span, found "
            f"{len(roots)}"
        )
    return errors


class SpanNode:
    """One span (or instant event) with its children, for rendering."""

    __slots__ = ("record", "end", "children")

    def __init__(self, record: dict, end: Optional[dict] = None):
        self.record = record
        self.end = end
        self.children: List["SpanNode"] = []

    @property
    def name(self) -> str:
        return self.record.get("name", "?")

    @property
    def is_event(self) -> bool:
        return self.record.get("ev") == "event"

    @property
    def t0(self) -> float:
        return self.record.get("t", 0.0)

    @property
    def t1(self) -> Optional[float]:
        return self.end.get("t") if self.end is not None else None

    @property
    def dur_s(self) -> Optional[float]:
        return self.end.get("dur_s") if self.end is not None else None

    def attrs(self) -> dict:
        out = {
            k: v for k, v in self.record.items()
            if k not in RESERVED_KEYS and k != "dst"
        }
        if self.end is not None:
            out.update({
                k: v for k, v in self.end.items()
                if k not in RESERVED_KEYS and k != "dst"
            })
        return out


def build_tree(records: Iterable[dict], rid: int) -> Optional[SpanNode]:
    """The trace's span tree (children in seq order). Returns None when
    the trace has no root; tolerates incomplete traces — explain must
    render the trace of a crashed run too."""
    recs = span_records(records, rid)
    ends = {r["span"]: r for r in recs if r.get("ev") == "end"}
    nodes: Dict[int, SpanNode] = {}
    root: Optional[SpanNode] = None
    for r in recs:
        if r.get("ev") not in ("begin", "event"):
            continue
        node = SpanNode(r, ends.get(r["span"]))
        nodes[r["span"]] = node
        parent = nodes.get(r.get("parent"))
        if parent is not None:
            parent.children.append(node)
        elif r.get("ev") == "begin" and not r.get("parent"):
            root = node
    return root


def chrome_trace(records: Iterable[dict]) -> dict:
    """Render span records as Chrome-trace JSON (Perfetto-loadable).

    Each trace (request) is a *process* named ``request <rid>``; each
    replica that touched it is a thread row inside it, so the
    cross-replica handoff reads as the request's own timeline switching
    rows; ``ev="link"`` records become flow arrows between their
    endpoint spans. Instant events render as thread-scoped ``i``
    events. Spans still open at export time render to the stream's last
    timestamp with ``open: true`` — a crashed run's last phase stays
    visible instead of vanishing.

    When the stream also carries ``kind="overlap"`` launch records
    (round 15, ``telemetry.overlap``), each replica additionally gets a
    synthetic "device r<N>" process (pid ``DEVICE_PID_BASE + N``) with
    a **device** track of estimated busy slices and a **dispatch**
    track of host dispatch walls, joined by flow arrows — the
    host-vs-device overlap view next to the per-request span trees."""
    records = list(records)
    recs = span_records(records)
    from pytorch_distributed_tpu.telemetry.overlap import (
        DEVICE_PID_BASE,
        device_timeline,
    )

    timelines = device_timeline(records)
    launch_ts = [
        t for slices in timelines.values() for s in slices
        for t in (s.get("t0", 0.0), s["end"])
    ]
    if not recs and not launch_ts:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    span_ts = [r.get("t", 0.0) for r in recs]
    t0 = min(span_ts + launch_ts)
    t_last = max(span_ts + launch_ts)

    def us(t: float) -> float:
        return (t - t0) * 1e6

    ends = {
        (r["trace"], r["span"]): r for r in recs if r.get("ev") == "end"
    }
    begins = {(r["trace"], r["span"]): r
              for r in recs if r.get("ev") in ("begin", "event")}
    events: List[dict] = []
    seen_tracks = set()
    for r in recs:
        trace = r.get("trace")
        tid = r.get("replica", 0) or 0
        if r.get("ev") in ("begin", "event") and (trace, tid) not in seen_tracks:
            seen_tracks.add((trace, tid))
            events.append({
                "name": "process_name", "ph": "M", "pid": trace,
                "args": {"name": f"request {trace}"},
            })
            events.append({
                "name": "thread_name", "ph": "M", "pid": trace, "tid": tid,
                "args": {"name": f"replica {tid}"},
            })
        args = {k: v for k, v in r.items() if k not in RESERVED_KEYS}
        args["seq"] = r.get("seq")
        if r.get("ev") == "begin":
            end = ends.get((trace, r["span"]))
            if end is not None:
                dur = us(end["t"]) - us(r["t"])
                args.update({
                    k: v for k, v in end.items() if k not in RESERVED_KEYS
                })
            else:
                dur = us(t_last) - us(r["t"])
                args["open"] = True
            events.append({
                "name": r.get("name", "?"), "ph": "X", "ts": us(r["t"]),
                "dur": max(dur, 0.0), "pid": trace, "tid": tid,
                "args": args,
            })
        elif r.get("ev") == "event":
            events.append({
                "name": r.get("name", "?"), "ph": "i", "s": "t",
                "ts": us(r["t"]), "pid": trace, "tid": tid, "args": args,
            })
        elif r.get("ev") == "link":
            src = begins.get((trace, r.get("span")))
            dst = begins.get((trace, r.get("dst")))
            if src is None or dst is None:
                continue
            flow_id = int(r.get("seq", 0))
            events.append({
                "name": r.get("name", "flow"), "cat": "handoff",
                "ph": "s", "id": flow_id, "ts": us(src["t"]),
                "pid": trace, "tid": src.get("replica", 0) or 0,
            })
            events.append({
                "name": r.get("name", "flow"), "cat": "handoff",
                "ph": "f", "bp": "e", "id": flow_id, "ts": us(dst["t"]),
                "pid": trace, "tid": dst.get("replica", 0) or 0,
            })
    # device tracks (round 15): one synthetic process per replica with a
    # device row (estimated busy slices) and a dispatch row (host
    # dispatch walls), flow arrows dispatch → device slice per launch
    for rep, slices in sorted(timelines.items()):
        pid = DEVICE_PID_BASE + rep
        events.append({
            "name": "process_name", "ph": "M", "pid": pid,
            "args": {"name": f"device r{rep}"},
        })
        events.append({
            "name": "thread_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": "device"},
        })
        events.append({
            "name": "thread_name", "ph": "M", "pid": pid, "tid": 1,
            "args": {"name": "dispatch"},
        })
        for s in slices:
            prog = s.get("program", "?")
            args = {"seq0": s.get("seq0"), "seq1": s.get("seq1")}
            if "done" not in s:
                args["completion"] = "t1-lower-bound"
            events.append({
                "name": prog, "ph": "X", "pid": pid, "tid": 1,
                "ts": us(s.get("t0", 0.0)),
                "dur": max(us(s.get("t1", 0.0)) - us(s.get("t0", 0.0)),
                           0.0),
                "args": args,
            })
            events.append({
                "name": prog, "ph": "X", "pid": pid, "tid": 0,
                "ts": us(s["start"]),
                "dur": max(us(s["end"]) - us(s["start"]), 0.0),
                "args": args,
            })
            flow_id = DEVICE_PID_BASE + int(s.get("seq0", 0) or 0)
            events.append({
                "name": prog, "cat": "dispatch", "ph": "s",
                "id": flow_id, "ts": us(s.get("t0", 0.0)),
                "pid": pid, "tid": 1,
            })
            events.append({
                "name": prog, "cat": "dispatch", "ph": "f", "bp": "e",
                "id": flow_id, "ts": us(s["start"]),
                "pid": pid, "tid": 0,
            })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def save_chrome_trace(records: Iterable[dict], path: str) -> str:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        json.dump(chrome_trace(records), f)
    return path
