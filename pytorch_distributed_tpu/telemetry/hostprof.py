"""Host-resource monitor — RSS, gc population, tracemalloc top sites.

Round 21.  The serving stack measures program cost (PR 8), per-request
causality (PR 12), and device idleness (PR 13) — but nothing measures
the *host process itself*, and ROADMAP item 5's acceptance ("flat host
RSS and flat per-tick host wall at ≥100k sessions") is a host-memory
property.  ``ResourceMonitor`` samples on a tick-count cadence and
streams ``kind="resource"`` records through the same rotating
``MetricsLogger`` JSONL as every other telemetry kind, so a 100k-session
soak's resource history is itself memory-bounded (the log rotates; the
monitor keeps only a fixed ring of samples for slope fitting).

What a sample carries:

- ``rss_mib`` — resident set from ``/proc/self/status`` (``VmRSS``),
  falling back to ``resource.getrusage`` where /proc is absent
  (``ru_maxrss`` is a *peak*, not current — the record says which via
  ``rss_source`` so a slope fit over getrusage data is read as an
  upper bound).
- ``gc_objects`` — ``len(gc.get_objects())``; O(heap) to compute,
  which is why it rides the sample cadence, not the tick path.  Off
  by default via ``gc_objects=False`` for latency-sensitive runs.
- ``live`` / ``cumulative`` — the load axes the growth sentinel
  regresses against (live in-flight requests; sessions ever served).
- ``tick_wall_ms_mean`` — mean host wall per tick over the window
  since the previous sample, fed by ``tick(wall_s=...)``.  This is the
  per-tick host-wall series for the scaling fit without requiring the
  O(launches) dispatch ledger to be live during a soak.
- optional ``tracemalloc`` top allocation sites every
  ``tracemalloc_every`` samples (0 = never start tracemalloc).
"""

from __future__ import annotations

import gc
import time
from collections import deque
from typing import List, Optional, Tuple

from .census import Decl

__all__ = ["ResourceMonitor", "NULL_MONITOR", "rss_mib"]

_PAGE_KIB = 1024.0


def _rss_proc_kib() -> Optional[float]:
    try:
        with open("/proc/self/status", "r") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return float(line.split()[1])
    except (OSError, ValueError, IndexError):
        return None
    return None


def _rss_rusage_kib() -> Optional[float]:
    try:
        import resource

        # Linux reports ru_maxrss in KiB; macOS in bytes. Either way it
        # is a high-water mark, not the current RSS.
        val = float(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss)
        import sys

        return val / 1024.0 if sys.platform == "darwin" else val
    except Exception:
        return None


def rss_mib() -> Tuple[float, str]:
    """Current resident set in MiB, plus which source produced it."""
    kib = _rss_proc_kib()
    if kib is not None:
        return kib / _PAGE_KIB, "proc"
    kib = _rss_rusage_kib()
    if kib is not None:
        return kib / _PAGE_KIB, "rusage_peak"
    return 0.0, "none"


class ResourceMonitor:
    """Samples host resources every ``every_ticks`` ticks.

    Call ``tick(live=..., cumulative=..., wall_s=...)`` once per
    scheduler/router step; it returns the sample record on sampling
    ticks and ``None`` otherwise.  ``sample()`` forces one immediately
    (used at soak start/end so the fit has endpoints).
    """

    def __init__(self, metrics_log=None, *, every_ticks: int = 256,
                 gc_objects: bool = True, tracemalloc_every: int = 0,
                 top_sites: int = 5, history: int = 4096,
                 enabled: bool = True):
        self.metrics_log = metrics_log
        self.every_ticks = max(1, int(every_ticks))
        self.gc_objects = bool(gc_objects)
        self.tracemalloc_every = int(tracemalloc_every)
        self.top_sites = int(top_sites)
        self.enabled = bool(enabled)
        self.ticks = 0
        self.samples = 0
        # (cumulative, rss_mib, tick_wall_ms_mean) per sample — the
        # growth sentinel's input; ring-bounded so the monitor itself
        # passes its own census.
        self.history: deque = deque(maxlen=history)
        self._wall_sum = 0.0
        self._wall_n = 0
        self._tm_started = False

    # -- census ----------------------------------------------------------
    def census_decls(self) -> List[Decl]:
        return [
            Decl("history", "fixed", cap=lambda m: m.history.maxlen,
                 why="deque(maxlen): fixed ring of (cumulative, rss, wall) "
                     "samples for slope fitting"),
        ]

    # -- sampling --------------------------------------------------------
    def tick(self, *, live: int = 0, cumulative: int = 0,
             wall_s: Optional[float] = None) -> Optional[dict]:
        if not self.enabled:
            return None
        self.ticks += 1
        if wall_s is not None:
            self._wall_sum += float(wall_s)
            self._wall_n += 1
        if self.ticks % self.every_ticks:
            return None
        return self.sample(live=live, cumulative=cumulative)

    def sample(self, *, live: int = 0, cumulative: int = 0) -> dict:
        rss, source = rss_mib()
        wall_ms = (1000.0 * self._wall_sum / self._wall_n
                   if self._wall_n else None)
        self._wall_sum, self._wall_n = 0.0, 0
        rec = {
            "kind": "resource",
            "tick": self.ticks,
            "rss_mib": round(rss, 3),
            "rss_source": source,
            "live": int(live),
            "cumulative": int(cumulative),
        }
        if wall_ms is not None:
            rec["tick_wall_ms_mean"] = round(wall_ms, 4)
        if self.gc_objects:
            rec["gc_objects"] = len(gc.get_objects())
            rec["gc_counts"] = list(gc.get_count())
        self.samples += 1
        if self.tracemalloc_every > 0:
            rec.update(self._tracemalloc_sites())
        self.history.append((int(cumulative), rss, wall_ms))
        if self.metrics_log is not None:
            self.metrics_log.log(**rec)
        return rec

    def _tracemalloc_sites(self) -> dict:
        import tracemalloc

        if not self._tm_started:
            # Start lazily on the first sampling tick so the monitor's
            # construction cost is zero when tracemalloc is unwanted.
            tracemalloc.start(1)
            self._tm_started = True
            return {}
        if self.samples % self.tracemalloc_every:
            return {}
        t0 = time.perf_counter()
        snap = tracemalloc.take_snapshot()
        stats = snap.statistics("lineno")[: self.top_sites]
        sites = [{"site": str(s.traceback[0]), "kib": round(s.size / 1024, 1),
                  "count": s.count} for s in stats]
        return {"tracemalloc_top": sites,
                "tracemalloc_snapshot_ms":
                    round(1000 * (time.perf_counter() - t0), 2)}

    def close(self) -> None:
        if self._tm_started:
            import tracemalloc

            tracemalloc.stop()
            self._tm_started = False

    # Series accessors for the growth sentinel -----------------------
    def rss_series(self) -> Tuple[List[float], List[float]]:
        xs = [h[0] for h in self.history]
        ys = [h[1] for h in self.history]
        return xs, ys

    def wall_series(self) -> Tuple[List[float], List[float]]:
        pts = [(h[0], h[2]) for h in self.history if h[2] is not None]
        return [p[0] for p in pts], [p[1] for p in pts]


NULL_MONITOR = ResourceMonitor(enabled=False)
