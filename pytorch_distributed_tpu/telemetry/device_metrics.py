"""Sync-free device metrics: a donated on-device ring, drained lagged.

The problem (ISSUE 4): both trainers materialized their log-interval
metrics with ``float(v)`` — a blocking device→host sync that stalls the
async dispatch pipeline every ``log_every`` steps. Through a tunneled TPU
runtime one such round trip has measured ~95 ms (PERF_NOTES.md), which at
``log_every=100`` is real goodput lost to printing a loss.

The fix: the trainer pushes each log event's replicated metric scalars
into a fixed-shape ``[capacity, n_metrics]`` f32 device buffer via a tiny
compiled ``dynamic_update_slice`` program that DONATES the buffer and the
write index — pure device work, dispatched asynchronously, zero host
transfers, zero allocations after the first window. When a window fills,
the buffer is handed to an async host copy and a fresh one is minted
on-device; the *previous* window — whose copy has long since completed —
is read then, so the host never blocks on in-flight device work. The
values make exactly one f32 hop through the buffer, so the drained
series is bit-identical to what the blocking ``float()`` path logged.

``flush()`` (epoch end) force-drains both the pending window and the
partial current one; that read may wait on the last pushed step, which
is the same sync the epoch-timing record already pays.

The push is its own jitted program, *outside* the train step: wrapping
the step with ``analysis.no_recompile`` (jit-cache growth + implicit
transfer guard) stays green with telemetry enabled —
``tests/test_telemetry.py`` proves it.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence


class DeviceMetricsRing:
    """Fixed-shape on-device metrics ring with lagged, windowed drain.

    ``names``    ordered metric keys; every ``append`` must supply each.
    ``capacity`` window length: the drain interval (``flush_every``).
    ``sharding`` optional ``jax.sharding.Sharding`` for the buffer —
                 pass the mesh's replicated sharding when the pushed
                 scalars are replicated global arrays (mixing a
                 single-device buffer with mesh-replicated operands is a
                 jit device-mismatch error).

    ``append(metrics, **meta)`` pushes one row (device work only) and
    returns the drained records of the PREVIOUS window when the current
    one just filled — each record is ``{**meta, name: float, ...}`` in
    push order. ``flush()`` drains everything still buffered.
    """

    def __init__(
        self,
        names: Sequence[str],
        capacity: int = 32,
        sharding: Optional[Any] = None,
    ):
        import jax
        import jax.numpy as jnp

        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if not names:
            raise ValueError("names must be non-empty")
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate metric names: {list(names)}")
        self.names: List[str] = list(names)
        self.capacity = int(capacity)
        n = len(self.names)
        cap = self.capacity

        def _push(buf, idx, vals):
            row = jnp.stack(
                [jnp.asarray(v).astype(jnp.float32) for v in vals]
            )
            buf = jax.lax.dynamic_update_slice(
                buf, row[None, :], (idx % cap, jnp.zeros((), jnp.int32))
            )
            return buf, idx + 1

        def _fresh():
            return (
                jnp.zeros((cap, n), jnp.float32),
                jnp.zeros((), jnp.int32),
            )

        out_sh = (sharding, sharding) if sharding is not None else None
        # donation keeps the window buffer at one allocation for the
        # whole run; the index scalar rides along
        self._push = jax.jit(_push, donate_argnums=(0, 1))
        self._fresh = (
            jax.jit(_fresh, out_shardings=out_sh)
            if out_sh is not None
            else jax.jit(_fresh)
        )
        self._buf, self._idx = self._fresh()
        self._metas: List[dict] = []
        self._pending = None  # (buf, metas) awaiting its lagged host read
        self.pushed = 0
        self.drained = 0

    # ---- the hot path ----------------------------------------------------

    def append(self, metrics: Dict[str, Any], **meta) -> List[dict]:
        """Push one row of device scalars; never blocks on device work.

        Returns drained records (possibly empty): when this push fills
        the window, the previous window — already host-resident — is
        materialized and returned, and the filled one starts its async
        host copy.
        """
        vals = tuple(metrics[name] for name in self.names)
        self._buf, self._idx = self._push(self._buf, self._idx, vals)
        self._metas.append(dict(meta))
        self.pushed += 1
        if len(self._metas) >= self.capacity:
            return self._rotate()
        return []

    def _rotate(self) -> List[dict]:
        out = self._harvest()
        buf, metas = self._buf, self._metas
        try:
            buf.copy_to_host_async()  # overlap the D2H with training
        except AttributeError:  # non-jax.Array stand-ins in unit tests
            pass
        self._pending = (buf, metas)
        self._buf, self._idx = self._fresh()
        self._metas = []
        return out

    # ---- the (lagged) host reads -----------------------------------------

    def _rows(self, buf, metas: List[dict]) -> List[dict]:
        import jax
        import numpy as np

        arr = np.asarray(jax.device_get(buf))
        out = []
        for i, meta in enumerate(metas):
            rec = dict(meta)
            for j, name in enumerate(self.names):
                rec[name] = float(arr[i, j])
            out.append(rec)
        self.drained += len(out)
        return out

    def _harvest(self) -> List[dict]:
        if self._pending is None:
            return []
        buf, metas = self._pending
        self._pending = None
        return self._rows(buf, metas)

    def flush(self) -> List[dict]:
        """Force-drain the pending window AND the current partial one
        (epoch end / run end). May block on the last pushed step."""
        out = self._harvest()
        if self._metas:
            out.extend(self._rows(self._buf, self._metas))
            self._buf, self._idx = self._fresh()
            self._metas = []
        return out

    @property
    def buffered(self) -> int:
        """Rows pushed but not yet drained (pending + current window)."""
        pend = len(self._pending[1]) if self._pending is not None else 0
        return pend + len(self._metas)
