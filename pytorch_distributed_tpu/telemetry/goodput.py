"""Goodput ledger: classify a run's wall time, emit the goodput fraction.

The north-star metric every perf PR reports through: of the wall time a
run consumed, what fraction went to productive training steps versus the
overheads this repo has grown machinery for — XLA compilation, data
wait, checkpoint stalls, rollback replay after the step guard condemned
a run, and watchdog-detected stalls.

Accounting model (host-side, exact by construction):

- the trainers time each NON-productive phase as it happens
  (``timed(category)`` around the blocking call; the watchdog feeds
  ``stall`` from its heartbeat gap);
- productive time is the REMAINDER: ``wall - sum(classified)``. Under
  async dispatch the host is inside ``next(loader)`` or a drain sync
  while the device trains, so host-side "time not lost to a known
  overhead" is precisely the time the device had work to do;
- fractions are normalized by ``max(wall, classified_sum)`` so they sum
  to 1 even if overlapping attribution ever over-counts (categories are
  disjoint in the trainers, so normally ``denominator == wall``).

``report()`` is one flat dict — the ``kind="goodput"`` JSONL record
``scripts/telemetry_report.py`` renders.
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, Iterator, Optional

#: The non-productive wall-time classes the trainers attribute.
#: ``compile`` is XLA backend compilation (including persistent-cache
#: loads — the part ``compilecache/`` collapses on a warm start);
#: ``trace`` is the Python tracing/lowering half of a cold first call,
#: split out by ``compilecache.aot.attribute_compile`` because no disk
#: cache can remove it — lumping the two would understate a warm start's
#: win and overstate a cold start's compile time.
GOODPUT_CATEGORIES = (
    "compile",
    "trace",
    "data_wait",
    "checkpoint",
    "rollback",
    "stall",
)


class GoodputLedger:
    """Run-level wall-time classification.

    ``start()`` pins the run clock (idempotent; ``timed``/``add`` call it
    implicitly). ``add(category, s)`` attributes seconds; ``timed(cat)``
    is the context-manager form. ``report()`` returns per-category
    seconds + fractions + ``goodput_frac`` (the productive fraction).
    """

    def __init__(self):
        self._t0: Optional[float] = None
        self._acc: Dict[str, float] = {c: 0.0 for c in GOODPUT_CATEGORIES}

    def start(self) -> None:
        if self._t0 is None:
            self._t0 = time.perf_counter()

    def add(self, category: str, seconds: float) -> None:
        if category not in self._acc:
            raise ValueError(
                f"unknown goodput category {category!r}; "
                f"expected one of {GOODPUT_CATEGORIES}"
            )
        if seconds < 0:
            raise ValueError(f"negative duration {seconds!r}")
        self.start()
        self._acc[category] += float(seconds)

    @contextlib.contextmanager
    def timed(self, category: str) -> Iterator[None]:
        self.start()
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(category, time.perf_counter() - t0)

    def seconds(self, category: str) -> float:
        return self._acc[category]

    def report(self) -> dict:
        """Flat goodput record. ``productive_s`` is the unclassified
        remainder; ``*_frac`` values (productive + every category) sum
        to 1."""
        wall = (
            time.perf_counter() - self._t0 if self._t0 is not None else 0.0
        )
        classified = sum(self._acc.values())
        denom = max(wall, classified) or 1.0
        productive = max(wall - classified, 0.0)
        out: dict = {"wall_s": wall, "productive_s": productive}
        out["goodput_frac"] = productive / denom
        out["productive_frac"] = out["goodput_frac"]
        for cat in GOODPUT_CATEGORIES:
            out[f"{cat}_s"] = self._acc[cat]
            out[f"{cat}_frac"] = self._acc[cat] / denom
        return out
