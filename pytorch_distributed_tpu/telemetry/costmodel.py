"""Per-program cost cards: FLOP/byte accounting joined with measured time.

PR 4's telemetry answers *what* a run spent its wall on; this module
answers *why* a program takes the time it takes. For every program the
``compilecache.ProgramRegistry`` enumerates, a **cost card** records the
compiler's own static accounting — FLOPs and bytes accessed from
``Compiled.cost_analysis()``, argument/output/temp bytes from
``memory_analysis()`` — and, once the run has measured wall time for the
program (scheduler tick spans, trainer epoch timing), joins the two into
achieved FLOP/s, achieved HBM bandwidth, MFU against the device's peak,
and a compute-vs-bandwidth **roofline classification**: a program whose
arithmetic intensity (FLOP/B) sits below the device ridge point
(peak FLOP/s over peak B/s) cannot be compute-bound no matter how well it
is scheduled — exactly the analysis PERF_NOTES.md §4/§7 did by hand for
the ResNet step, now produced by the runtime for every program
(generalizing the one-off ``scripts/exp_resnet_roofline.py``).

Caveats, stated on the card rather than hidden:

- XLA's ``bytes accessed`` double-counts fused intermediates and
  aliased (donated) operands (PERF_NOTES §9 measured 40.6 GB reported
  vs 23.3 GB real HBM traffic). Round 20 subtracts the part the
  compiler itself reports — ``memory_analysis().alias_size_in_bytes``,
  the donated-operand overlap counted once as an argument and again as
  an output — into ``bytes_accessed_dedup``, which all derived rates
  (intensity, achieved GB/s, hbm_frac, the roofline bound) now use.
  The raw ``bytes_accessed`` stays on the card for comparability. The
  fusion share of the double-count is not separable from the analysis,
  so deduped GB/s is still an upper bound on real traffic — fine for
  *classification* (a program the metric calls bandwidth-bound is), a
  smaller overestimate for absolute bandwidth.
- Measured seconds are host wall around the dispatch (the spans the run
  already records). Programs whose results the caller materializes
  (decode tick, epoch-synced train steps) are honest; pure-dispatch
  spans under-report on async backends — the card carries ``calls`` so a
  reader can judge the join.

Ceilings come from ``device_ceilings()``: env overrides
``PDT_PEAK_FLOPS`` (FLOP/s) / ``PDT_PEAK_GBS`` (GB/s) first, then a
small builtin table of measured numbers (the v5e entries are this repo's
own measurements, PERF_NOTES §2/§7). Unknown device → no MFU/bound
columns, but the card (and achieved rates) still emit: attribution
degrades, never crashes.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Callable, Dict, List, Optional, Tuple

#: (peak FLOP/s, peak bytes/s) per jax device kind. v5e compute is the
#: bf16 datasheet peak (the MFU convention); bandwidth is the MEASURED
#: streaming ceiling (PERF_NOTES §7: 657 GB/s triad vs 819 datasheet) —
#: roofline fractions against what the chip actually streams.
DEVICE_CEILINGS: Dict[str, Tuple[float, float]] = {
    "TPU v5 lite": (197e12, 657e9),
    "TPU v5e": (197e12, 657e9),
    "TPU v4": (275e12, 1228e9),
}


def device_ceilings(device_kind: Optional[str] = None):
    """``(peak_flops, peak_bytes_s)`` for the active device, or
    ``(None, None)`` when unknown. Env ``PDT_PEAK_FLOPS`` /
    ``PDT_PEAK_GBS`` override both the table and the unknown case — the
    knob CI uses to render full roofline tables on the CPU backend."""
    flops = os.environ.get("PDT_PEAK_FLOPS")
    gbs = os.environ.get("PDT_PEAK_GBS")
    if flops or gbs:
        return (
            float(flops) if flops else None,
            float(gbs) * 1e9 if gbs else None,
        )
    if device_kind is None:
        try:
            import jax

            device_kind = jax.devices()[0].device_kind
        except Exception:
            return None, None
    return DEVICE_CEILINGS.get(device_kind, (None, None))


#: env overrides for the host↔device link (GB/s), the PDT_PEAK_* knob
#: family extended to the swap path: CI pins these to steer the
#: swap-vs-recompute decision deterministically on the CPU backend.
LINK_ENV_H2D = "PDT_PEAK_H2D_GBS"
LINK_ENV_D2H = "PDT_PEAK_D2H_GBS"

_link_cache: Optional[Tuple[float, float]] = None


def link_bandwidth(probe_mb: int = 4,
                   reps: int = 3) -> Tuple[Optional[float], Optional[float]]:
    """``(h2d_bytes_s, d2h_bytes_s)`` of the host↔device link.

    Env overrides ``PDT_PEAK_H2D_GBS``/``PDT_PEAK_D2H_GBS`` first
    (deterministic CI), else ONE measured probe per process — a
    ``probe_mb`` buffer put/get round (median of ``reps``), the in-tree
    twin of ``scripts/bench_serving.py``'s ``link_probe`` — cached
    module-global so the serve loop never re-pays it. A backend that
    cannot run the probe yields ``(None, None)``: the decision degrades
    to its stated default, never crashes."""
    global _link_cache
    h2d_env = os.environ.get(LINK_ENV_H2D)
    d2h_env = os.environ.get(LINK_ENV_D2H)
    if h2d_env and d2h_env:
        return float(h2d_env) * 1e9, float(d2h_env) * 1e9
    if _link_cache is None:
        try:
            import time

            import jax
            import numpy as np

            buf = np.ones(probe_mb << 20, np.uint8)

            def med(f):
                times = []
                for _ in range(reps):
                    t0 = time.perf_counter()
                    f()
                    times.append(time.perf_counter() - t0)
                return max(float(np.median(times)), 1e-9)

            dev = jax.block_until_ready(jax.device_put(buf))  # warm path
            h2d_s = med(
                lambda: jax.block_until_ready(jax.device_put(buf))
            )
            d2h_s = med(lambda: np.asarray(jax.device_get(dev)))
            _link_cache = (buf.nbytes / h2d_s, buf.nbytes / d2h_s)
        except Exception:
            _link_cache = (0.0, 0.0)  # probe failed: remembered as unknown
    h2d = float(h2d_env) * 1e9 if h2d_env else (_link_cache[0] or None)
    d2h = float(d2h_env) * 1e9 if d2h_env else (_link_cache[1] or None)
    return h2d, d2h


@dataclasses.dataclass(frozen=True)
class SwapDecision:
    """One preemption's swap-vs-recompute verdict, with the predicted
    costs that produced it — logged verbatim (``kind="preempt"``) so the
    crossover is auditable against measured walls after the fact."""

    choice: str  # "swap" | "recompute"
    swap_s: Optional[float]
    recompute_s: Optional[float]
    bytes_to_move: int
    chunks: int
    reason: str


def swap_vs_recompute(
    bytes_to_move: int,
    *,
    chunks: int = 0,
    chunk_wall_s: Optional[float] = None,
    h2d_bytes_s: Optional[float] = None,
    d2h_bytes_s: Optional[float] = None,
) -> SwapDecision:
    """The measured crossover (vLLM's preemption choice, with this
    repo's numbers in it): predicted swap cost is the chain's bytes
    through the MEASURED link both ways (d2h now + h2d at restore);
    predicted recompute cost is the resume-prefill's chunk count times
    the chunk program's MEASURED per-call wall (``ProgramTimes`` — the
    cost-card join, not a FLOP guess). Link rates default from
    ``link_bandwidth()`` (env-overridable). When one side is
    unmeasurable the other wins; when neither is, swap is the stated
    default (same-host d2h/h2d is cheap everywhere this repo runs;
    recompute burns accelerator FLOPs the pool is starved for)."""
    if h2d_bytes_s is None or d2h_bytes_s is None:
        h2d0, d2h0 = link_bandwidth()
        h2d_bytes_s = h2d_bytes_s if h2d_bytes_s is not None else h2d0
        d2h_bytes_s = d2h_bytes_s if d2h_bytes_s is not None else d2h0
    swap_s = (
        bytes_to_move * (1.0 / h2d_bytes_s + 1.0 / d2h_bytes_s)
        if h2d_bytes_s and d2h_bytes_s else None
    )
    recompute_s = (
        chunks * chunk_wall_s
        if chunk_wall_s is not None and chunks > 0 else None
    )
    if swap_s is None and recompute_s is None:
        choice, reason = "swap", "unmeasured-default"
    elif recompute_s is None:
        choice, reason = "swap", "recompute-unmeasured"
    elif swap_s is None:
        choice, reason = "recompute", "link-unmeasured"
    else:
        choice = "swap" if swap_s <= recompute_s else "recompute"
        reason = "measured-crossover"
    return SwapDecision(choice=choice, swap_s=swap_s,
                        recompute_s=recompute_s,
                        bytes_to_move=int(bytes_to_move), chunks=chunks,
                        reason=reason)


def extract_costs(compiled) -> dict:
    """Static cost fields from a ``jax.stages.Compiled`` (or ``Lowered``).

    ``cost_analysis()`` has returned both a bare dict and a per-device
    list of dicts across jax versions — both shapes are handled. Any
    backend that cannot produce an analysis yields an empty dict: a cost
    card with unknown FLOPs is still a card."""
    out: dict = {}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if ca:
            if ca.get("flops") is not None:
                out["flops"] = float(ca["flops"])
            if ca.get("bytes accessed") is not None:
                out["bytes_accessed"] = float(ca["bytes accessed"])
    except Exception:
        pass
    try:
        ma = compiled.memory_analysis()
        if ma is not None:
            arg = int(getattr(ma, "argument_size_in_bytes", 0))
            outb = int(getattr(ma, "output_size_in_bytes", 0))
            tmp = int(getattr(ma, "temp_size_in_bytes", 0))
            alias = int(getattr(ma, "alias_size_in_bytes", 0))
            out["argument_bytes"] = arg
            out["output_bytes"] = outb
            out["temp_bytes"] = tmp
            out["alias_bytes"] = alias
            # live working set while the program runs — the number that
            # decides whether two programs can overlap in HBM. Donated
            # operands (the pool, the logits buffer) appear in BOTH the
            # argument and output totals but occupy one allocation, so
            # the aliased overlap is subtracted once.
            out["peak_bytes"] = arg + outb + tmp - alias
    except Exception:
        pass
    return out


@dataclasses.dataclass
class CostCard:
    """One program's static cost accounting plus its measured join."""

    program: str
    flops: Optional[float] = None
    bytes_accessed: Optional[float] = None
    argument_bytes: Optional[int] = None
    output_bytes: Optional[int] = None
    temp_bytes: Optional[int] = None
    alias_bytes: Optional[int] = None
    peak_bytes: Optional[int] = None
    # measured join (ProgramTimes): host wall attributed to this program
    calls: int = 0
    total_s: float = 0.0

    @property
    def bytes_accessed_dedup(self) -> Optional[float]:
        """``bytes accessed`` minus the aliased (donated) operand bytes
        XLA counted twice — the traffic figure every derived rate uses
        (PERF_NOTES §9). Floored at zero: the analysis pair comes from
        two separate compiler queries and is not guaranteed coherent."""
        if self.bytes_accessed is None:
            return None
        return max(self.bytes_accessed - (self.alias_bytes or 0), 0.0)

    @property
    def intensity(self) -> Optional[float]:
        """Arithmetic intensity, FLOP per deduped byte accessed."""
        if not self.flops or not self.bytes_accessed_dedup:
            return None
        return self.flops / self.bytes_accessed_dedup

    def record(self, peak_flops: Optional[float] = None,
               peak_bytes_s: Optional[float] = None) -> dict:
        """The flat ``kind="program_cost"`` JSONL record: statics,
        measured join, and every derived rate the ceilings allow."""
        rec: dict = {"program": self.program, "calls": self.calls}
        for k in ("flops", "bytes_accessed", "argument_bytes",
                  "output_bytes", "temp_bytes", "alias_bytes",
                  "peak_bytes"):
            v = getattr(self, k)
            if v is not None:
                rec[k] = v
        if self.bytes_accessed_dedup is not None:
            rec["bytes_accessed_dedup"] = self.bytes_accessed_dedup
        if self.intensity is not None:
            rec["intensity_flop_b"] = round(self.intensity, 3)
        if self.calls and self.total_s > 0:
            mean_s = self.total_s / self.calls
            rec["total_s"] = round(self.total_s, 6)
            rec["mean_s"] = round(mean_s, 6)
            if self.flops:
                rec["achieved_flops_s"] = self.flops / mean_s
                if peak_flops:
                    rec["mfu"] = round(self.flops / mean_s / peak_flops, 5)
            if self.bytes_accessed_dedup:
                rec["achieved_bytes_s"] = self.bytes_accessed_dedup / mean_s
                if peak_bytes_s:
                    rec["hbm_frac"] = round(
                        self.bytes_accessed_dedup / mean_s / peak_bytes_s, 5
                    )
        if peak_flops and peak_bytes_s and self.intensity is not None:
            ridge = peak_flops / peak_bytes_s
            rec["ridge_flop_b"] = round(ridge, 3)
            rec["bound"] = (
                "compute" if self.intensity >= ridge else "bandwidth"
            )
        return rec


class ProgramTimes:
    """Per-program measured wall accumulator — the join side of a cost
    card. ``observe(name, seconds)`` adds one call;
    ``observe_total(name, seconds, calls)`` adds a pre-aggregated window
    (epoch timing). Thread-safe enough for the single-writer call sites
    (scheduler tick loop, trainer epoch end)."""

    def __init__(self):
        self._acc: Dict[str, Tuple[int, float]] = {}

    def observe(self, name: str, seconds: float) -> None:
        self.observe_total(name, seconds, 1)

    def observe_total(self, name: str, seconds: float, calls: int) -> None:
        if calls < 1 or seconds < 0:
            return
        n, s = self._acc.get(name, (0, 0.0))
        self._acc[name] = (n + calls, s + float(seconds))

    def __contains__(self, name: str) -> bool:
        return name in self._acc

    def get(self, name: str) -> Tuple[int, float]:
        return self._acc.get(name, (0, 0.0))

    def items(self):
        return self._acc.items()

    def census_decls(self):
        from pytorch_distributed_tpu.telemetry.census import Decl

        return [
            Decl("_acc", "fixed", cap=256,
                 why="(calls, total_s) aggregate per program name — "
                     "O(registered programs), not O(observations); the "
                     "ProgramRegistry is a small closed set"),
        ]


def build_cost_cards(registry, times: Optional[ProgramTimes] = None,
                     ) -> List[CostCard]:
    """One card per registry program, in registry order.

    Statics come from each spec's ``aot`` thunk (``lower(...).compile()``
    — a persistent-cache hit when ``enable_persistent_cache`` ran, a
    fresh XLA compile otherwise; that cost is why trainers gate card
    emission behind ``cost_cards=True`` and pay it once at fit end, off
    the training critical path). A spec without an ``aot`` thunk, or one
    whose compile/analysis fails, still yields a card — with the static
    fields unknown — so "every program in the registry has a cost card"
    holds unconditionally."""
    cards = []
    for spec in registry:
        card = CostCard(program=spec.name)
        aot = getattr(spec, "aot", None)
        if aot is not None:
            try:
                compiled = aot()
                if compiled is not None:
                    for k, v in extract_costs(compiled).items():
                        setattr(card, k, v)
            except Exception:
                pass  # unanalyzable program: card ships without statics
        if times is not None:
            card.calls, card.total_s = times.get(spec.name)
        cards.append(card)
    return cards


def log_cost_cards(registry, times, metrics_log, *,
                   fingerprint: Optional[str] = None,
                   annotate: Optional[dict] = None) -> List[dict]:
    """Build every card, join, and emit one ``kind="program_cost"``
    JSONL record per program. Returns the records (emitted or not — a
    ``metrics_log`` of None still returns them for callers that render
    directly). ``annotate`` merges extra keys into every record — the
    scheduler passes the engine's tuned-config provenance so forensics
    can tell which kernel variant actually served."""
    peak_flops, peak_bytes_s = device_ceilings()
    records = []
    for card in build_cost_cards(registry, times):
        rec = card.record(peak_flops, peak_bytes_s)
        rec["fingerprint"] = (
            fingerprint if fingerprint is not None else registry.fingerprint
        )
        if annotate:
            rec.update(annotate)
        records.append(rec)
        if metrics_log is not None:
            metrics_log.log(kind="program_cost", **rec)
    return records
