"""Bounded-structure census — declared bounds for long-lived containers.

Round 21.  ROADMAP item 5 demands host bookkeeping that stays O(live
batch), not O(sessions ever served) — the bug class only a scale
harness surfaces (the unbounded affinity table fixed in PR 15, the
``ReqTracer`` root map and redispatch-origin map fixed this round).
The census turns "we believe this dict is bounded" into a checked
invariant: every long-lived container on a swept object *declares* its
identity and bound class, a sweep audits actual ``len()`` against the
declared bound each sample, and an **undeclared** container on a swept
object is itself a loud finding — new code can't silently add
unbounded state.

Bound classes (``Decl.kind``):

``fixed``
    Capacity set at construction (slot tables, rings, LRU caps).  The
    declared ``cap`` is audited: ``len() > cap`` is a violation.
``live``
    O(live requests).  Audited against the ``live`` count the sweeper
    passes (``FleetRouter.live_requests()``): a structure that keeps
    entries for *retired* rids grows past ``live`` and flags.  This is
    the class whose violation means an O(sessions-ever) host leak.
``replicas``
    O(fleet size).  Audited against ``replicas`` when given.
``unbounded``
    Unbounded *by design* (the scheduler queue under admission
    backpressure, ``ReqTracer.records`` in keep-mode tests, the
    dispatch ledger's profiling log).  Never flags; the declaration
    exists so the ``why`` is written down and the meta-test knows the
    container was considered, not missed.

``kind`` and ``cap`` may be callables of the owner so a declaration
can depend on runtime mode — ``FleetRouter.results`` is
unbounded-by-design under the default drain() contract but proven
O(live) when the router runs with ``retain_results=False`` (the soak
configuration).
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

__all__ = [
    "Decl",
    "StructCensus",
    "audit_owner",
    "undeclared_containers",
]

# Container types the undeclared-sweep treats as "long-lived structure
# that could grow".  numpy arrays are fixed-shape buffers, not growth
# candidates, and are deliberately excluded.
_CONTAINER_TYPES = (dict, list, set, frozenset, deque)

_KINDS = ("fixed", "live", "replicas", "unbounded")


@dataclasses.dataclass(frozen=True)
class Decl:
    """One declared container: where it lives, how it's bounded, why."""

    attr: str  # attribute path on the owner; "." means the owner itself
    kind: Union[str, Callable[[Any], str]]
    cap: Union[None, int, Callable[[Any], Optional[int]]] = None
    why: str = ""
    # For kind="live": entries per live request (a request can hold
    # several open spans, a few queued tokens, ...). Audited bound is
    # per_live * live + live_slack.
    per_live: int = 1

    def kind_for(self, owner: Any) -> str:
        k = self.kind(owner) if callable(self.kind) else self.kind
        if k not in _KINDS:
            raise ValueError(f"unknown bound class {k!r} for {self.attr!r}")
        return k

    def cap_for(self, owner: Any) -> Optional[int]:
        c = self.cap(owner) if callable(self.cap) else self.cap
        return None if c is None else int(c)


def _resolve(owner: Any, attr: str) -> Any:
    if attr == ".":
        return owner
    obj = owner
    for part in attr.split("."):
        obj = getattr(obj, part, None)
        if obj is None:
            return None
    return obj


def undeclared_containers(obj: Any, decls: Optional[Sequence[Decl]] = None,
                          ) -> List[str]:
    """Direct container attributes of ``obj`` not covered by a declaration.

    Coverage is by first path component: ``Decl(attr="ttft.values")``
    does not cover a hypothetical ``self.ttft`` dict — only a dotted
    reach *through* a non-container attribute.  The meta-test asserts
    this returns ``[]`` for every swept class.
    """
    if decls is None:
        decls = obj.census_decls() if hasattr(obj, "census_decls") else []
    # A dotted decl ("ttft.values") reaches *through* a non-container
    # attribute; only undotted decls name a direct container attr.
    covered = {d.attr for d in decls if "." not in d.attr}
    out = []
    for name, val in vars(obj).items():
        if isinstance(val, _CONTAINER_TYPES) and name not in covered:
            out.append(name)
    return sorted(out)


def audit_owner(name: str, obj: Any, *, live: Optional[int] = None,
                replicas: Optional[int] = None, live_slack: int = 0,
                ) -> Tuple[Dict[str, int], List[dict], List[str]]:
    """Audit one owner: (sizes, violations, undeclared).

    ``sizes`` maps ``"{name}.{attr}"`` to current ``len()``.
    ``violations`` carry the declared bound that was exceeded.
    """
    decls = obj.census_decls() if hasattr(obj, "census_decls") else []
    sizes: Dict[str, int] = {}
    violations: List[dict] = []
    for d in decls:
        target = _resolve(obj, d.attr)
        if target is None:
            continue
        try:
            size = len(target)
        except TypeError:
            continue
        qname = f"{name}.{d.attr}" if d.attr != "." else name
        sizes[qname] = size
        kind = d.kind_for(obj)
        cap = d.cap_for(obj)
        bound: Optional[int] = None
        if kind == "fixed":
            bound = cap
        elif kind == "live":
            if live is not None:
                bound = d.per_live * live + live_slack
                if cap is not None and cap:
                    bound = min(bound, cap)
        elif kind == "replicas":
            bound = cap if cap is not None else replicas
        if bound is not None and size > bound:
            violations.append({"name": qname, "size": size, "kind": kind,
                               "bound": bound, "why": d.why})
    undeclared = [f"{name}.{a}" for a in undeclared_containers(obj, decls)]
    return sizes, violations, undeclared


class StructCensus:
    """Registry of swept owners + the periodic sweep.

    ``register`` objects (or a whole fleet via the owners list the
    router exposes), then call ``sweep(live=...)`` on a sample cadence.
    Each sweep emits one ``kind="census"`` record through
    ``metrics_log`` (same rotating JSONL as every other telemetry
    kind) and accumulates peak sizes + violation totals for the
    end-of-run verdict.
    """

    def __init__(self, metrics_log=None):
        self.metrics_log = metrics_log
        self._owners: List[Tuple[str, Any]] = []
        self.sweeps = 0
        self.total_violations = 0
        self.total_undeclared = 0
        self.peak: Dict[str, int] = {}

    def register(self, name: str, obj: Any) -> None:
        self._owners.append((name, obj))

    def register_many(self, owners: Sequence[Tuple[str, Any]]) -> None:
        for name, obj in owners:
            self.register(name, obj)

    def owners(self) -> List[Tuple[str, Any]]:
        return list(self._owners)

    def sweep(self, *, live: Optional[int] = None,
              replicas: Optional[int] = None, tick: Optional[int] = None,
              live_slack: int = 0) -> dict:
        structures: Dict[str, int] = {}
        violations: List[dict] = []
        undeclared: List[str] = []
        for name, obj in self._owners:
            sizes, viol, undecl = audit_owner(
                name, obj, live=live, replicas=replicas,
                live_slack=live_slack)
            structures.update(sizes)
            violations.extend(viol)
            undeclared.extend(undecl)
        worst_name, worst_ratio = "", 0.0
        for name, obj in self._owners:
            decls = (obj.census_decls()
                     if hasattr(obj, "census_decls") else [])
            for d in decls:
                qname = f"{name}.{d.attr}" if d.attr != "." else name
                if qname not in structures:
                    continue
                kind = d.kind_for(obj)
                if kind == "fixed":
                    denom = d.cap_for(obj)
                elif kind == "live":
                    denom = d.per_live * live if live else None
                elif kind == "replicas":
                    denom = d.cap_for(obj) or replicas
                else:
                    continue
                if not denom:
                    continue
                ratio = structures[qname] / denom
                if ratio > worst_ratio:
                    worst_name, worst_ratio = qname, ratio
        for qname, size in structures.items():
            if size > self.peak.get(qname, -1):
                self.peak[qname] = size
        self.sweeps += 1
        self.total_violations += len(violations)
        self.total_undeclared += len(set(undeclared))
        rec = {
            "kind": "census",
            "tick": tick,
            "live": live,
            "structures": structures,
            "violations": len(violations),
            "violation_details": violations,
            "undeclared": sorted(set(undeclared)),
            "worst_ratio": round(worst_ratio, 4),
            "worst_name": worst_name,
            "ok": not violations and not undeclared,
        }
        if self.metrics_log is not None:
            self.metrics_log.log(**rec)
        return rec

    def verdict(self) -> str:
        """"ok" iff no sweep ever saw a violation or undeclared container."""
        if self.total_violations:
            return f"violations:{self.total_violations}"
        if self.total_undeclared:
            return f"undeclared:{self.total_undeclared}"
        return "ok" if self.sweeps else "no-sweeps"
