"""Cost-card-keyed kernel autotuner: sweep, persist, reload by fingerprint.

Round 10 gave the serving stack two spellings of every KV-bound program
and round 20 multiplied the variant space again (pool dtype × block_len
× split-S × chunk bucket). Which point is fastest depends on the
backend, the device generation, and the model shape — exactly the things
``compilecache.run_fingerprint`` already encodes. This module closes the
loop the ISSUE names "the measurement loop":

- ``sweep`` times candidate ``(block_len, prefill_chunk, split_s)``
  configs with the same warm-decode-tick methodology as
  ``scripts/bench_serving.py --gather-ab`` (one untimed tick, then timed
  ticks on a warm program), joins each candidate with its decode
  program's cost-card roofline class (``costmodel.CostCard`` — so the
  tuned file records WHY the winner won, not just that it did), and
  picks the highest decode tok/s.
- ``save_tuned``/``load_tuned`` persist the winner as JSON keyed by
  ``autotune_fingerprint`` — the registry fingerprint with the TUNED
  knobs normalized out (``split_s=None``, no block_len/chunk extras).
  The tuned parameters must never appear in their own key: an engine
  about to choose block_len cannot know it yet.
- Staleness is structural: a tuned file whose recorded fingerprint does
  not match the requesting engine's key simply does not load (clean
  miss, never a crash, never a wrong config) — same contract as the
  AOT artifact cache.

``serving.engine.PagedEngine`` calls ``load_tuned`` at construction when
``autotune_dir=`` (or env ``PDT_AUTOTUNE_DIR``) is set; explicit caller
arguments always win over the tuned file.
"""

from __future__ import annotations

import dataclasses
import json
import os
import tempfile
import time
from typing import Dict, List, Optional, Sequence, Tuple

#: bump when the tuned-file schema or sweep methodology changes — rides
#: into the fingerprint so old files miss cleanly instead of misloading
AUTOTUNE_VERSION = "autotune=v1"


@dataclasses.dataclass(frozen=True)
class TunedConfig:
    """One sweep's winner plus the evidence that picked it."""

    block_len: int
    prefill_chunk: int
    split_s: Optional[int]
    #: the autotune fingerprint this config is valid for (load key)
    fingerprint: str
    #: backend the sweep MEASURED on — a CPU-interpret sweep is a
    #: plumbing exercise, not a TPU performance claim (honesty rule)
    backend: str
    decode_tok_s: float
    #: roofline class of the winning decode program ("compute" /
    #: "bandwidth" / None when ceilings are unknown)
    decode_bound: Optional[str] = None
    #: every candidate's row (knobs, tok/s, bound) for audit
    candidates: Tuple[Dict, ...] = ()


def autotune_fingerprint(config, n_slots: int, *, kv_dtype=None,
                         temperature: float = 0.0, top_k=None,
                         prefix_cache: bool = False, mesh=None) -> str:
    """The tuned-file key: ``run_fingerprint`` over everything that
    shapes the decode program EXCEPT the knobs being tuned.

    ``split_s`` is normalized to None in the config repr and block_len /
    prefill_chunk are deliberately absent from the extras (contrast
    ``serving_registry``, which includes all three — program artifacts
    must not cross tuned variants, but the tuned file must be findable
    BEFORE the variant is chosen)."""
    from pytorch_distributed_tpu.compilecache.registry import (
        run_fingerprint,
    )

    norm = dataclasses.replace(config, split_s=None)
    return run_fingerprint(mesh=mesh, extra=(
        norm,
        f"n_slots={n_slots}",
        f"temperature={temperature}",
        f"top_k={top_k}",
        f"kv_dtype={kv_dtype}",
        f"prefix_cache={prefix_cache}",
        AUTOTUNE_VERSION,
    ))


def tuned_path(out_dir: str, fingerprint: str) -> str:
    return os.path.join(out_dir, f"autotune_{fingerprint}.json")


def save_tuned(out_dir: str, tuned: TunedConfig) -> str:
    """Atomic JSON write (tmp + rename) so a reader never sees a torn
    file; returns the path."""
    os.makedirs(out_dir, exist_ok=True)
    path = tuned_path(out_dir, tuned.fingerprint)
    fd, tmp = tempfile.mkstemp(dir=out_dir, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(dataclasses.asdict(tuned), f, indent=2)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return path


def load_tuned(out_dir: str, fingerprint: str) -> Optional[TunedConfig]:
    """The tuned config for ``fingerprint``, or None.

    None covers EVERY miss mode — no directory, no file, unparseable
    JSON, missing fields, or a recorded fingerprint that does not match
    the requested one (a stale file from another environment). Loading
    must never crash engine construction: an untuned engine is correct,
    just default-configured."""
    try:
        with open(tuned_path(out_dir, fingerprint)) as f:
            rec = json.load(f)
        if rec.get("fingerprint") != fingerprint:
            return None
        return TunedConfig(
            block_len=int(rec["block_len"]),
            prefill_chunk=int(rec["prefill_chunk"]),
            split_s=(None if rec.get("split_s") is None
                     else int(rec["split_s"])),
            fingerprint=rec["fingerprint"],
            backend=str(rec.get("backend", "unknown")),
            decode_tok_s=float(rec.get("decode_tok_s", 0.0)),
            decode_bound=rec.get("decode_bound"),
            candidates=tuple(rec.get("candidates", ())),
        )
    except Exception:
        return None


def _time_candidate(config, params, n_slots, *, block_len, prefill_chunk,
                    split_s, kv_dtype, temperature, top_k, prefix_cache,
                    mesh, gather_impl, prompt, ticks) -> Dict:
    """One candidate's measured row: build a throwaway engine, prefill
    every slot with ``prompt``, warm the decode tick, then time ``ticks``
    ticks — the ``bench_serving.measure_gather_ab`` methodology. The
    roofline class comes from the decode program's cost card (the AOT
    thunk is a jit-cache hit here: decode just ran)."""
    import jax
    import numpy as np

    from pytorch_distributed_tpu.compilecache.registry import (
        serving_registry,
    )
    from pytorch_distributed_tpu.serving.engine import ChunkJob, PagedEngine
    from pytorch_distributed_tpu.telemetry.costmodel import (
        CostCard,
        device_ceilings,
        extract_costs,
    )

    prompt_len = len(prompt)
    eng = PagedEngine(
        config, params, n_slots, block_len=block_len,
        prefill_chunk=prefill_chunk, split_s=split_s,
        temperature=temperature, top_k=top_k, mesh=mesh,
        gather_impl=gather_impl, kv_dtype=kv_dtype,
        prefix_cache=prefix_cache,
    )
    for s in range(n_slots):
        if not eng.admit(s, prompt_len, ticks + 1):
            raise ValueError(
                f"candidate block_len={block_len} cannot admit "
                f"{n_slots} x (prompt {prompt_len} + {ticks + 1} ticks)"
            )
    # chunked prefill, the scheduler's spelling: every job carries
    # exactly prefill_chunk tokens, the last zero-padded with last_idx
    # marking the final real token
    for start in range(0, prompt_len, prefill_chunk):
        seg = prompt[start:start + prefill_chunk]
        tokens = np.zeros((prefill_chunk,), np.int32)
        tokens[:len(seg)] = seg
        is_last = start + prefill_chunk >= prompt_len
        eng.run_chunks([
            ChunkJob(slot=s, tokens=tokens, start=start, is_last=is_last,
                     last_idx=(prompt_len - 1 - start) if is_last else 0)
            for s in range(n_slots)
        ])
    positions = np.full(n_slots, prompt_len, np.int32)
    active = np.ones(n_slots, bool)
    key = jax.random.key(0)
    _tokens, positions = eng.decode(positions, active, key)  # warm
    times = []
    for _ in range(ticks):
        t0 = time.perf_counter()
        _tokens, positions = eng.decode(positions, active, key)
        times.append(time.perf_counter() - t0)
    total = sum(times)
    # roofline join for the decode program only (chunk programs are not
    # what the sweep optimizes) — a backend without analysis still rows
    bound = None
    try:
        reg = serving_registry(eng)
        spec = next(s for s in reg if s.name == eng.DECODE_PROGRAM)
        card = CostCard(program=spec.name)
        for k, v in extract_costs(spec.aot()).items():
            setattr(card, k, v)
        card.calls, card.total_s = ticks, total
        rec = card.record(*device_ceilings())
        bound = rec.get("bound")
    except Exception:
        pass
    return {
        "block_len": block_len,
        "prefill_chunk": prefill_chunk,
        "split_s": split_s,
        "decode_tok_s": round(n_slots * ticks / total, 1),
        "decode_tick_p95_ms": round(
            float(np.percentile(times, 95)) * 1e3, 3
        ),
        "decode_bound": bound,
    }


def sweep(config, params, n_slots: int, *,
          block_lens: Sequence[int] = (16,),
          prefill_chunks: Sequence[int] = (128,),
          split_ss: Sequence[Optional[int]] = (1, None),
          kv_dtype: Optional[str] = None, temperature: float = 0.0,
          top_k: Optional[int] = None, prefix_cache: bool = False,
          mesh=None, gather_impl: Optional[str] = None,
          prompt_len: int = 32, ticks: int = 8,
          out_dir: Optional[str] = None) -> TunedConfig:
    """Time every candidate in the cross product, pick the highest
    decode tok/s, and (when ``out_dir`` is given) persist the winner
    keyed by ``autotune_fingerprint``. Candidates that cannot serve the
    probe workload (admission fails — e.g. a block_len too coarse for
    the pool) are skipped, not fatal; at least one candidate must
    survive."""
    import jax
    import numpy as np

    # Fold gather_impl into the config EXACTLY like PagedEngine does, so
    # the fingerprint computed here equals the one a later engine (which
    # replaces before keying) will look up.
    if gather_impl is not None and gather_impl != config.gather_impl:
        config = dataclasses.replace(config, gather_impl=gather_impl)
    gather_impl = None
    rng = np.random.default_rng(0)
    prompt = rng.integers(1, config.vocab_size, prompt_len).astype(np.int32)
    rows: List[Dict] = []
    for bl in block_lens:
        for pc in prefill_chunks:
            for ss in split_ss:
                try:
                    rows.append(_time_candidate(
                        config, params, n_slots, block_len=bl,
                        prefill_chunk=pc, split_s=ss, kv_dtype=kv_dtype,
                        temperature=temperature, top_k=top_k,
                        prefix_cache=prefix_cache, mesh=mesh,
                        gather_impl=gather_impl, prompt=prompt,
                        ticks=ticks,
                    ))
                except ValueError:
                    continue  # unservable candidate: skipped, recorded not
    if not rows:
        raise ValueError("no autotune candidate could serve the probe "
                         "workload")
    best = max(rows, key=lambda r: r["decode_tok_s"])
    fp = autotune_fingerprint(
        config, n_slots, kv_dtype=kv_dtype, temperature=temperature,
        top_k=top_k, prefix_cache=prefix_cache, mesh=mesh,
    )
    tuned = TunedConfig(
        block_len=int(best["block_len"]),
        prefill_chunk=int(best["prefill_chunk"]),
        split_s=best["split_s"],
        fingerprint=fp,
        backend=jax.default_backend(),
        decode_tok_s=float(best["decode_tok_s"]),
        decode_bound=best.get("decode_bound"),
        candidates=tuple(rows),
    )
    if out_dir is not None:
        save_tuned(out_dir, tuned)
    return tuned
