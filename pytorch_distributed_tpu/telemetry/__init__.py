"""Unified observability runtime: device metrics, spans, goodput, latency.

The reference's entire observability story is rank-0 ``time.time()`` epoch
prints with GPU util measured externally by the cluster (PAPER.md §5
"tracing: ABSENT"); round 1 replaced the prints with a JSONL stream
(``utils.profiling.MetricsLogger``) but left three holes this package
closes:

- ``device_metrics`` — a fixed-shape, donated on-device ring buffer the
  trainers push each log-interval's metric scalars into, drained every
  ``flush_every`` windows with ONE lagged host transfer. Replaces the
  per-log-interval blocking ``float()`` sync that stalled the dispatch
  pipeline in both trainers; the logged series is bit-identical to the
  blocking path (same f32 scalars, one hop through the buffer).
- ``spans`` — nested host-side span tracing (data_wait, step_dispatch,
  ckpt_save, rollback_replay, admission, prefill_chunk, decode_tick)
  emitted as Chrome-trace JSON and mirrored into
  ``jax.profiler.TraceAnnotation`` so host phases line up with XLA op
  timelines in xprof.
- ``goodput`` — a run-level ledger classifying wall time into
  productive-step vs compile, data wait, checkpoint stall, rollback
  replay, and watchdog stall; fractions sum to 1 by construction.
- ``latency`` — exact host-side latency series with percentile
  summaries (TTFT, per-output-token, queue wait for the serving
  scheduler).

Round 11 adds the attribution-and-forensics layer (ANALYSIS.md
"Performance attribution & forensics"):

- ``costmodel`` — per-program cost cards: ``Compiled.cost_analysis()``
  FLOP/byte statics for every ``compilecache.ProgramRegistry`` program,
  joined with measured span/tick times into MFU, achieved bandwidth, and
  a compute-vs-bandwidth roofline classification (``kind="program_cost"``
  JSONL);
- ``anomaly`` — streaming median/MAD z-score detectors over step-time,
  data-wait, TTFT, and queue-depth series (``kind="anomaly"`` with a
  context window); a recently-anomalous serving replica reads as hot to
  the fleet ``SLOGate``;
- ``flightrec`` — a bounded ring of recent structured events, dumped
  atomically on watchdog stall, rollback, suspend, and unhandled
  exception, with a size-capped durable JSONL mirror the kill-matrix
  relaunch reads;
- ``export`` — a stdlib-HTTP Prometheus-text ``/metrics`` thread
  (``scripts/pdt_top.py`` is the JSONL-tailing terminal twin).

Round 14 adds the causal join layer (ANALYSIS.md "Request-lifecycle
tracing"):

- ``reqtrace`` — per-request lifecycle traces: rid-keyed span trees
  (gate decision → queue → prefill → handoff → decode windows →
  preempt/park/restore → retire) as a versioned ``kind="span"`` JSONL
  stream, with a completeness validator and a Perfetto/Chrome-trace
  exporter (``scripts/explain_request.py`` is the forensics CLI);
- ``schema`` — the JSONL record-kind registry: required keys per kind
  with a validator, so emitter drift breaks CI instead of the report.

Round 15 adds the host–device overlap layer (ANALYSIS.md "Host–device
overlap"):

- ``overlap`` — a dispatch ledger wrapping every compiled call site
  (engine chunk/decode/export/import/swap, trainer train/eval steps):
  host dispatch walls, lagged device-completion fences (never a sync on
  the hot path), a per-replica device timeline, and every inter-launch
  gap classified as a bubble attributed to its host cause by joining
  the span stream's logical clock (``kind="overlap"`` JSONL;
  ``scripts/bench_serving.py --wall-clock`` is the fleet bench ROADMAP
  item 3's async refactor gates against).

Round 21 adds the scale observatory (ANALYSIS.md "Scale observatory"):

- ``hostprof`` — a ``ResourceMonitor`` sampling host RSS
  (``/proc/self/status``, ``getrusage`` fallback), gc population, and
  optional tracemalloc top sites on a tick-count cadence
  (``kind="resource"`` JSONL);
- ``census`` — the bounded-structure census: every long-lived
  container on the swept serving classes declares its bound class
  (fixed / O(live) / O(replicas) / unbounded-by-design) and a sweep
  audits actual ``len()`` against it (``kind="census"``; an undeclared
  container is itself a finding);
- ``scaling`` — a growth sentinel regressing RSS, per-tick host wall,
  and structure sizes against session counts with MAD-floored
  flagging, so "flat host cost at 100k sessions" is a checked verdict
  (``bench_serving.py --soak``), not an impression.

Everything reports through the one JSONL schema of
``utils.profiling.MetricsLogger``; ``scripts/telemetry_report.py``
renders a run's JSONL into the summary table ``bench.py`` consumes.
ANALYSIS.md "Observability & goodput" documents the schema.
"""

from pytorch_distributed_tpu.telemetry.anomaly import (
    AnomalySentinel,
    StreamingDetector,
)
from pytorch_distributed_tpu.telemetry.census import (
    Decl,
    StructCensus,
    audit_owner,
    undeclared_containers,
)
from pytorch_distributed_tpu.telemetry.costmodel import (
    CostCard,
    ProgramTimes,
    SwapDecision,
    build_cost_cards,
    device_ceilings,
    link_bandwidth,
    log_cost_cards,
    swap_vs_recompute,
)
from pytorch_distributed_tpu.telemetry.device_metrics import DeviceMetricsRing
from pytorch_distributed_tpu.telemetry.export import (
    MetricsExporter,
    prometheus_text,
)
from pytorch_distributed_tpu.telemetry.flightrec import (
    NULL_RECORDER,
    FlightRecorder,
)
from pytorch_distributed_tpu.telemetry.goodput import (
    GOODPUT_CATEGORIES,
    GoodputLedger,
)
from pytorch_distributed_tpu.telemetry.hostprof import (
    NULL_MONITOR,
    ResourceMonitor,
    rss_mib,
)
from pytorch_distributed_tpu.telemetry.latency import LatencySeries, percentiles
from pytorch_distributed_tpu.telemetry.overlap import (
    NULL_LEDGER,
    DispatchLedger,
    busy_summary,
    busy_within,
    cause_histogram,
    classify_bubbles,
    device_timeline,
    fleet_busy_summary,
)
from pytorch_distributed_tpu.telemetry.reqtrace import (
    NULL_REQTRACER,
    SPAN_SCHEMA_VERSION,
    ReqTracer,
    build_tree,
    chrome_trace,
    save_chrome_trace,
    span_records,
    trace_rids,
    validate_trace,
)
from pytorch_distributed_tpu.telemetry.scaling import (
    GrowthSentinel,
    fit_growth,
    mad_scale,
)
from pytorch_distributed_tpu.telemetry.schema import (
    REQUIRED_KEYS,
    validate_record,
    validate_stream,
)
from pytorch_distributed_tpu.telemetry.spans import NULL_TRACER, SpanTracer

__all__ = [
    "AnomalySentinel",
    "StreamingDetector",
    "Decl",
    "StructCensus",
    "audit_owner",
    "undeclared_containers",
    "NULL_MONITOR",
    "ResourceMonitor",
    "rss_mib",
    "GrowthSentinel",
    "fit_growth",
    "mad_scale",
    "CostCard",
    "ProgramTimes",
    "SwapDecision",
    "build_cost_cards",
    "device_ceilings",
    "link_bandwidth",
    "log_cost_cards",
    "swap_vs_recompute",
    "DeviceMetricsRing",
    "MetricsExporter",
    "prometheus_text",
    "NULL_RECORDER",
    "FlightRecorder",
    "GOODPUT_CATEGORIES",
    "GoodputLedger",
    "LatencySeries",
    "percentiles",
    "NULL_LEDGER",
    "DispatchLedger",
    "busy_summary",
    "busy_within",
    "cause_histogram",
    "classify_bubbles",
    "device_timeline",
    "fleet_busy_summary",
    "NULL_REQTRACER",
    "SPAN_SCHEMA_VERSION",
    "ReqTracer",
    "build_tree",
    "chrome_trace",
    "save_chrome_trace",
    "span_records",
    "trace_rids",
    "validate_trace",
    "REQUIRED_KEYS",
    "validate_record",
    "validate_stream",
    "NULL_TRACER",
    "SpanTracer",
]
