"""Nested host-side span tracing → Chrome trace JSON + xprof annotations.

Wall-clock phases of a run (data_wait, step_dispatch, ckpt_save,
rollback_replay, admission, prefill_chunk, decode_tick) as nested spans:

- collected host-side with ``time.perf_counter`` (microsecond Chrome
  trace convention), one complete ("X") event per span, ``tid`` = the
  recording thread — ``chrome://tracing`` / Perfetto load the output
  directly;
- mirrored into ``jax.profiler.TraceAnnotation`` when a jax profiler
  trace is active, so the host phases line up with the XLA op/fusion
  timelines in xprof (the reference has no tracing story at all,
  PAPER.md §5).

A disabled tracer (``NULL_TRACER``, the default everywhere) costs one
truthiness check per span — components thread a tracer through without
caring whether anyone is listening.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Dict, Iterator, List, Optional


class SpanTracer:
    """Collects nested spans; ``save()`` writes Chrome-trace JSON.

    ``span(name, **args)`` is a context manager; spans may nest freely
    (the Chrome trace format reconstructs the stack from containment per
    ``tid``). Thread-safe: events append under a lock, ``tid`` is the
    recording thread's ident, and the open-span stack is PER-THREAD
    (keyed by ``threading.get_ident()``) — concurrent emitters (the
    background warmup compiler today; ROADMAP item 3's worker threads)
    each nest within their own stack, so one thread's open span can
    never become another thread's parent. Each event records its
    ``depth`` and ``parent`` from that stack.
    """

    def __init__(self, enabled: bool = True, mirror_jax: bool = True):
        self.enabled = bool(enabled)
        self.mirror_jax = bool(mirror_jax)
        self._events: List[dict] = []
        self._lock = threading.Lock()
        # thread ident -> stack of open span names. Mutated only by the
        # owning thread, but always under self._lock: the dict itself is
        # shared, and stack() may read another thread's entry.
        self._stacks: Dict[int, List[str]] = {}
        self._t0 = time.perf_counter()

    def _now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    def stack(self) -> List[str]:
        """The CALLING thread's open span names, outermost first."""
        with self._lock:
            return list(self._stacks.get(threading.get_ident(), ()))

    @contextlib.contextmanager
    def span(self, name: str, **args) -> Iterator[None]:
        if not self.enabled:
            yield
            return
        tid = threading.get_ident()
        with self._lock:
            stack = self._stacks.setdefault(tid, [])
            depth = len(stack)
            parent = stack[-1] if stack else None
            stack.append(name)
        ctx = contextlib.nullcontext()
        if self.mirror_jax:
            try:
                import jax

                ctx = jax.profiler.TraceAnnotation(name)
            except Exception:  # no jax / no profiler: host-only spans
                ctx = contextlib.nullcontext()
        t0 = self._now_us()
        try:
            with ctx:
                yield
        finally:
            dur = self._now_us() - t0
            ev = {
                "name": name,
                "ph": "X",
                "ts": t0,
                "dur": dur,
                "pid": os.getpid(),
                "tid": tid,
            }
            if depth:
                args = dict(args, depth=depth, parent=parent)
            if args:
                ev["args"] = args
            with self._lock:
                # this thread's innermost open span is necessarily ours:
                # spans are context managers, so per-thread exits are LIFO
                stack.pop()
                if not stack:
                    self._stacks.pop(tid, None)
                self._events.append(ev)

    # ---- output ----------------------------------------------------------

    def chrome_trace(self) -> dict:
        """The Chrome trace dict: metadata + every completed span."""
        with self._lock:
            events = list(self._events)
        meta = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": os.getpid(),
                "args": {"name": "pytorch_distributed_tpu host"},
            }
        ]
        return {"traceEvents": meta + events, "displayTimeUnit": "ms"}

    def save(self, path: str) -> str:
        """Write the Chrome trace JSON to ``path`` (dirs created)."""
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)
        return path

    def events(self, name: Optional[str] = None) -> List[dict]:
        with self._lock:
            evs = list(self._events)
        if name is not None:
            evs = [e for e in evs if e["name"] == name]
        return evs

    def clear(self) -> None:
        with self._lock:
            self._events.clear()


#: Shared no-op tracer: components default to it so span call sites never
#: need a None check.
NULL_TRACER = SpanTracer(enabled=False)
