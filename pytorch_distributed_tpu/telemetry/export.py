"""Live metrics exporter: a stdlib-HTTP Prometheus-text ``/metrics``.

The JSONL stream is a flight data recorder; operators also need a live
gauge. This is the smallest honest version: a daemon
``ThreadingHTTPServer`` whose ``/metrics`` renders a caller-supplied
``collect()`` dict (the shapes the run already has —
``Scheduler.metrics()``, ``FleetRouter.metrics()``, a trainer's goodput
report) in Prometheus text exposition format. No dependency, no push
gateway, no background sampling thread: ``collect()`` runs on the HTTP
thread at scrape time, so an unscraped exporter costs nothing.

Scrape-path discipline: ``collect`` callbacks must stay host-side (the
metric dicts this repo produces are exact host counters by design —
PR 4). Nothing here touches the device.

    exporter = MetricsExporter(scheduler.metrics, port=9100).start()
    # curl localhost:9100/metrics
    exporter.stop()

``port=0`` binds an ephemeral port (tests); ``.port`` reports the bound
one. ``/healthz`` answers 200 while the thread lives.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional


def _sanitize(name: str) -> str:
    out = "".join(c if (c.isalnum() or c == "_") else "_" for c in name)
    return out if not out[:1].isdigit() else f"_{out}"


def prometheus_text(metrics: dict, prefix: str = "pdt") -> str:
    """Flat metrics dict → Prometheus text exposition. Numbers emit as
    gauges (bools as 0/1); non-numeric values are skipped — the format
    has no string type and a label-less gauge is the honest mapping for
    the flat dicts this repo produces."""
    lines = []
    for key in sorted(metrics):
        value = metrics[key]
        if isinstance(value, bool):
            value = int(value)
        if not isinstance(value, (int, float)):
            continue
        if value != value or value in (float("inf"), float("-inf")):
            continue  # NaN/inf serialize poorly across scrapers
        name = f"{_sanitize(prefix)}_{_sanitize(key)}"
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {value}")
    return "\n".join(lines) + "\n"


class MetricsExporter:
    """Serve ``collect()`` as Prometheus text on ``/metrics``."""

    def __init__(self, collect: Callable[[], dict], port: int = 0,
                 host: str = "127.0.0.1", prefix: str = "pdt"):
        self.collect = collect
        self.prefix = prefix
        self._host = host
        self._requested_port = int(port)
        self.port: Optional[int] = None
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "MetricsExporter":
        if self._server is not None:
            return self
        exporter = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib API name)
                if self.path == "/healthz":
                    body = b"ok\n"
                    ctype = "text/plain"
                elif self.path in ("/metrics", "/"):
                    try:
                        body = prometheus_text(
                            exporter.collect(), exporter.prefix
                        ).encode()
                    except Exception as e:
                        self.send_error(500, f"collect failed: {e}")
                        return
                    ctype = "text/plain; version=0.0.4"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # silence per-scrape stderr spam
                pass

        self._server = ThreadingHTTPServer(
            (self._host, self._requested_port), Handler
        )
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="pdt-metrics-exporter",
            daemon=True,
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._server is None:
            return
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self._server = None
        self._thread = None

    def __enter__(self) -> "MetricsExporter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
