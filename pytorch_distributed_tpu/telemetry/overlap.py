"""Host–device overlap profiler: dispatch ledger + bubble attribution.

The observability stack so far explains what a request did (reqtrace),
what a program costs (costmodel), and where a run's wall went (goodput)
— but nothing measures where the **device sat idle**. The one-loop
``FleetRouter`` ticks replicas sequentially from a single host loop, so
replica B's decode waits on replica A's host work — ROADMAP item 3's
async refactor exists to remove exactly that serialization, and this
module is the measurement contract it will be verified against: a
per-replica device timeline whose every inter-launch gap is a **bubble**
attributed to its host cause.

``DispatchLedger`` wraps every compiled call site (the engine's
chunk/decode/export/import/swap programs, the trainers' train/eval
steps) and records, per launch:

- **host dispatch wall** ``[t0, t1]`` (``time.perf_counter``) — for an
  async dispatch this is enqueue time only; for a call that materializes
  its result (``sync=True``: the decode tick fetches its tokens) it is
  dispatch + device + sync, i.e. exact completion;
- **logical-clock window** ``[seq0, seq1]`` claimed from the SAME clock
  as the round-14 span stream (``ReqTracer.claim_seq``), so "what was
  the host doing between launch N and N+1" is answerable by selecting
  span records with ``seq`` in the gap — the causal join the bubble
  classifier rides;
- a **lagged fence** bound on device completion: when launch N is
  recorded, the ledger calls ``block_until_ready`` on launch N−k's
  registered handle (the PR 4 LAGGED ring idiom — by then the work is
  almost surely done, so the fence returns immediately and the hot path
  never stalls; ``hot_fences`` counts violations of the lag and is zero
  by construction, the no-sync guard tests assert it).

What the fences do and do not bound (ANALYSIS.md "Host–device
overlap"): a fence that RETURNS IMMEDIATELY (wait below
``FENCE_BLOCK_EPS_S``) only proves completion happened somewhere in
``[t1, fence_return]`` — the ledger then uses the ``t1`` lower bound,
so device-busy is a LOWER bound and bubbles an UPPER bound on an async
backend. A fence that actually BLOCKS pins completion exactly (the
device was still running; the fence return IS the completion). On the
CPU backend dispatch is effectively synchronous (``t1`` ≈ completion),
so CPU timelines are exact — the same honesty split as
``gather_ab_backend``. Launches whose outputs are donated into later
programs (chunk prefill, kv_import, kv_swap_in) register no handle —
their buffers are invalid by fence time — and their completion rides
the ``t1`` lower bound tightened by the next synchronous launch on the
same replica stream (the decode tick, every scheduler step).

Bubble classification (``classify_bubbles``): per replica, launches
sort by ``t0``; completion ``c_i = max(done_i or t1_i, c_{i-1})``
(in-order execution per stream); the busy slice is ``[max(t0_i,
c_{i-1}), c_i]`` and the gap to the next launch ``[c_i, t0_{i+1}]`` is
a bubble. Its cause is the overlapping host activity with the largest
share of the gap:

- another replica's dispatch wall     → ``other-replica-tick`` (the
  host loop serialized behind that replica's tick — what the async
  refactor removes; a sync launch's wall contains its execution, so
  the synchronous loop's attribution reads as before)
- another replica's busy slice beyond its dispatch wall
                                      → ``shared-device-wait`` (round
  16: the shared device executing someone else — unavoidable at N
  replicas per device, gone on real N-device hardware)
- a ledger host mark (``host(...)``)  → the mark's name, one of
  ``tokenize/detokenize``, ``admission/gate``, ``jsonl-emit``,
  ``handoff-pump``, ``swap-decision``, ``sampling-prep``,
  ``metrics-refresh`` — marks recorded on a worker thread (round 16:
  the async host runtime's ``HostWorkerPool``) carry the thread name
  and classify as ``<name>@<thread>``, so host work OVERLAPPED onto a
  worker stops being misattributed to ``idle-no-work``
- a ``kind="span"`` record whose ``seq`` falls inside the gap's logical
  window (the PR 12 join), mapped through ``_SPAN_CAUSES``
- nothing                             → ``idle-no-work``

Round 16 (async host runtime): ``launch`` tokens can be **collected** —
``DispatchLedger.complete(token)`` pins an async launch's completion at
its lagged materialization site (the dispatch-then-collect loop's
collect phase) exactly like a fence would, without waiting for the
lagged window. And because N single-process replicas on a CPU host
share ONE device, per-replica busy slices measured from dispatch
windows legitimately overlap each other (a launch waits behind the
other replica's program INSIDE its dispatch→completion window) —
summing per-replica busy would double-count the shared device.
``fleet_busy_summary`` reports the interval-UNION busy fraction next
to the per-replica ones (the ``gather_ab_backend`` honesty pattern:
per-replica fractions are scheduling health, the union is true device
utilization), and ``finalize`` emits it as a ``replica=-1`` summary
record.

Everything lands as ``kind="overlap"`` JSONL (schema-registered) on the
caller's ``MetricsLogger``: ``ev="launch"``/``ev="host"`` batched off
the hot path (buffered, emitted every ``emit_every`` records inside a
self-marked ``jsonl-emit`` window), ``ev="bubble"`` and ``ev="summary"``
at ``finalize()``. ``scripts/telemetry_report.py`` renders the section,
``scripts/pdt_top.py`` tails the live row, ``scripts/bench_serving.py
--wall-clock`` is the fleet bench that gates on it, and the Perfetto
exporter (``reqtrace.chrome_trace``) renders one device track per
replica with dispatch→device flow arrows.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

#: a lagged fence that waited less than this was a no-op (the work had
#: already finished): completion collapses to the dispatch-return lower
#: bound instead of the (much later) fence timestamp
FENCE_BLOCK_EPS_S = 2e-4

#: chrome-trace pid base for the synthetic per-replica device processes
#: (request traces use the rid as pid; this keeps the spaces disjoint)
DEVICE_PID_BASE = 1_000_000_000

#: the bubble-cause taxonomy (host marks use these names verbatim)
CAUSE_OTHER_REPLICA = "other-replica-tick"
#: round 16: the other replica's program EXECUTING on the shared device
#: while this replica's gap is open — distinct from other-replica-tick,
#: which is the other replica's host-side DISPATCH WALL occupying the
#: loop (the serialization the async refactor removes). On one shared
#: device a sync launch's dispatch wall contains its execution, so the
#: sync loop's attribution is unchanged; under async dispatch the walls
#: collapse to microseconds and the execution time shows up here — the
#: part that vanishes on real N-device hardware (backend honesty).
CAUSE_SHARED_DEVICE = "shared-device-wait"
CAUSE_IDLE = "idle-no-work"
HOST_CAUSES = (
    "tokenize/detokenize",
    "admission/gate",
    "jsonl-emit",
    "handoff-pump",
    "swap-decision",
    "sampling-prep",
    "metrics-refresh",
    "tick-collect",
)

#: span names (round-14 ``kind="span"`` stream) → bubble cause, for gaps
#: no ledger mark explains — the logical-clock join against PR 12
_SPAN_CAUSES = {
    "queued": "admission/gate",
    "gate": "admission/gate",
    "handoff": "handoff-pump",
    "handoff_wait": "handoff-pump",
    "preempt": "swap-decision",
    "swap_out": "swap-decision",
    "swap_in": "swap-decision",
    "parked": "swap-decision",
}


class _LaunchToken:
    """Yielded by ``DispatchLedger.launch``: the call site sets
    ``handle`` to a (non-donated) output array/pytree inside the with
    block so the lagged fence has something to block on later. The
    ledger fills ``rec``/``entry`` on exit so an async call site can
    hold the token and pin completion itself at its collect site
    (``DispatchLedger.complete`` — the round-16 dispatch-then-collect
    loop)."""

    __slots__ = ("handle", "rec", "entry")

    def __init__(self):
        self.handle = None
        self.rec = None
        self.entry = None


class DispatchLedger:
    """Per-launch dispatch ledger over a ``MetricsLogger``-shaped sink.

    ``sink`` needs one method, ``log(**record)`` (None keeps records in
    memory only). ``seq_source`` is any object with ``claim_seq()`` —
    pass the run's ``ReqTracer`` so launch windows and span records
    share one logical clock (the bubble classifier's join key); without
    one the ledger keeps a private counter. A disabled ledger
    (``NULL_LEDGER``) costs one truthiness check per call site.

    Thread-safe: record appends and seq claims happen under one lock
    (the background-warmup thread never launches through the ledger,
    but ROADMAP item 3's worker threads will).
    """

    def __init__(self, sink=None, seq_source=None, *, lag: int = 4,
                 emit_every: int = 64, enabled: bool = True):
        if lag < 1:
            raise ValueError(f"lag must be >= 1, got {lag}")
        self.enabled = bool(enabled)
        self.sink = sink
        self.seq_source = seq_source
        self.lag = lag
        self.emit_every = emit_every
        self._lock = threading.Lock()
        self._seq = 0
        #: every record in emission order (in-memory mirror; also the
        #: source ``finalize`` classifies from)
        self.records: List[dict] = []
        self._unemitted = 0
        # per-replica launch bookkeeping for the lagged fence: list of
        # (record, handle); handles dropped once fenced so the ledger
        # never pins more than ``lag`` launch outputs alive per replica
        self._streams: Dict[int, List[list]] = {}
        #: fences that targeted a launch NEWER than current−lag — a
        #: hot-path sync. Structurally impossible; the no-sync guard
        #: test asserts it stayed zero.
        self.hot_fences = 0
        #: fences whose target buffer was already donated away (no
        #: handle should have been registered — loud counter, not crash)
        self.dead_fences = 0
        self.fences = 0
        self._finalized = False

    # ---- logical clock ---------------------------------------------------

    def _claim(self) -> int:
        if self.seq_source is not None:
            return self.seq_source.claim_seq()
        with self._lock:
            s = self._seq
            self._seq += 1
            return s

    # ---- the hot path ----------------------------------------------------

    @contextlib.contextmanager
    def launch(self, replica: int, program: str, sync: bool = False):
        """Record one compiled-program launch. Wrap exactly the dispatch
        (plus the result fetch for ``sync=True`` call sites — their
        ``t1`` is then true completion). Set ``token.handle`` to a
        non-donated output for the lagged fence; leave it None for
        launches whose outputs later programs donate."""
        if not self.enabled:
            yield _LaunchToken()
            return
        token = _LaunchToken()
        seq0 = self._claim()
        t0 = time.perf_counter()
        try:
            yield token
        finally:
            t1 = time.perf_counter()
            seq1 = self._claim()
            rec = {
                "kind": "overlap", "ev": "launch", "replica": replica,
                "program": program, "t0": t0, "t1": t1,
                "seq0": seq0, "seq1": seq1,
            }
            if sync:
                rec["done"] = t1
            with self._lock:
                stream = self._streams.setdefault(replica, [])
                entry = [rec, None if sync else token.handle]
                stream.append(entry)
                token.rec = rec
                token.entry = entry
                self._append(rec)
                # the lagged fence target: exactly one candidate per
                # launch (indices fence consecutively as the stream
                # grows), so handles older than the window are already
                # dropped — the ledger pins at most ``lag`` outputs.
                # The handle is taken IN PLACE (entry mutated, not
                # replaced) so a token's ``entry`` ref stays live and
                # ``complete`` / the fence can never double-target one
                # launch.
                fence_target = None
                fence_handle = None
                idx = len(stream) - 1 - self.lag
                if idx >= 0 and stream[idx][1] is not None:
                    fence_target = stream[idx]
                    fence_handle = fence_target[1]
                    fence_target[1] = None
            if fence_target is not None:
                self._fence(fence_target[0], fence_handle)

    def _fence(self, rec: dict, handle) -> None:
        """Block on a LAGGED launch's handle: returns immediately when
        the work already finished (the normal case — no hot-path stall);
        a blocking fence pins the launch's completion exactly."""
        import jax

        f0 = time.perf_counter()
        try:
            jax.block_until_ready(handle)
        except Exception:
            with self._lock:
                self.dead_fences += 1
            return
        f1 = time.perf_counter()
        wait = f1 - f0
        with self._lock:
            self.fences += 1
            rec["fenced"] = True
            rec["fence_wait_s"] = round(wait, 9)
            if wait > FENCE_BLOCK_EPS_S:
                # the device was still running: the fence return IS the
                # completion time (exact, not a bound)
                rec["done"] = f1

    def complete(self, token) -> None:
        """Pin an async launch's completion at its collect site (the
        round-16 dispatch-then-collect loop): blocks on the launch's
        handle like a lagged fence — by collect time the work is
        usually done and the wait is a no-op; a wait that actually
        blocked pins ``done`` exactly. Takes the handle out of the
        lagged-fence window so one launch is never fenced twice.
        No-op for sync launches, disabled ledgers, and already-fenced
        entries."""
        import jax

        if not self.enabled or token is None or token.rec is None:
            return
        with self._lock:
            handle = token.entry[1] if token.entry is not None else None
            if handle is not None:
                token.entry[1] = None
        if handle is None:
            return
        f0 = time.perf_counter()
        try:
            jax.block_until_ready(handle)
        except Exception:
            with self._lock:
                self.dead_fences += 1
            return
        f1 = time.perf_counter()
        with self._lock:
            self.fences += 1
            token.rec["collected"] = True
            token.rec["fence_wait_s"] = round(f1 - f0, 9)
            if f1 - f0 > FENCE_BLOCK_EPS_S:
                # the device was still running at collect: the wait's
                # return IS the completion time (exact, not a bound)
                token.rec["done"] = f1

    @contextlib.contextmanager
    def host(self, name: str, replica: int = -1):
        """Mark a host-work interval (tokenize/detokenize,
        admission/gate, jsonl-emit, handoff-pump, swap-decision,
        sampling-prep, metrics-refresh) — the attribution targets
        bubbles resolve to. ``replica=-1`` marks router-level work any
        replica's gap may land in. Marks recorded off the main thread
        (the async host runtime's workers) carry the thread name, so
        ``classify_bubbles`` can attribute overlapped worker work
        instead of calling it ``idle-no-work``."""
        if not self.enabled:
            yield
            return
        seq0 = self._claim()
        t0 = time.perf_counter()
        try:
            yield
        finally:
            t1 = time.perf_counter()
            rec = {
                "kind": "overlap", "ev": "host", "replica": replica,
                "name": name, "t0": t0, "t1": t1, "seq0": seq0,
            }
            th = threading.current_thread()
            if th is not threading.main_thread():
                rec["thread"] = th.name
            with self._lock:
                rec["seq1"] = self._claim_locked()
                self._append(rec)

    def _claim_locked(self) -> int:
        # caller holds self._lock; claim without re-locking
        if self.seq_source is not None:
            return self.seq_source.claim_seq()
        s = self._seq
        self._seq += 1
        return s

    def _append(self, rec: dict) -> None:
        # caller holds the lock
        self.records.append(rec)
        self._unemitted += 1
        if self.sink is not None and self._unemitted >= self.emit_every:
            self._drain_locked()

    def _drain_locked(self) -> None:
        """Emit buffered records in one batch — amortized JSONL cost,
        itself recorded as a ``jsonl-emit`` host interval so the bytes
        the profiler writes show up in its own attribution."""
        if self.sink is None or self._unemitted == 0:
            return
        pending = self.records[len(self.records) - self._unemitted:]
        t0 = time.perf_counter()
        seq0 = self._claim_locked()
        for rec in pending:
            self.sink.log(**rec)
        mark = {
            "kind": "overlap", "ev": "host", "replica": -1,
            "name": "jsonl-emit", "t0": t0, "t1": time.perf_counter(),
            "seq0": seq0, "seq1": self._claim_locked(),
        }
        self.records.append(mark)
        self.sink.log(**mark)
        self._unemitted = 0

    # ---- finalization ----------------------------------------------------

    def finalize(self) -> List[dict]:
        """End of run: fence the tail of every stream (an end-of-run
        sync is allowed — the run is over), classify bubbles, emit
        everything still buffered plus one ``ev="bubble"`` record per
        gap and one ``ev="summary"`` per replica. Idempotent. Returns
        the bubble + summary records."""
        import jax

        with self._lock:
            if self._finalized:
                return []
            self._finalized = True
            tails = [
                (entry[0], entry[1])
                for stream in self._streams.values()
                for entry in stream if entry[1] is not None
            ]
        for rec, handle in tails:
            try:
                jax.block_until_ready(handle)
            except Exception:
                pass
        out: List[dict] = []
        with self._lock:
            bubbles = classify_bubbles(self.records)
            for b in bubbles:
                rec = {"kind": "overlap", "ev": "bubble", **b}
                self.records.append(rec)
                out.append(rec)
            summaries = busy_summary(self.records)
            for replica, summary in summaries.items():
                rec = {
                    "kind": "overlap", "ev": "summary",
                    "replica": replica, **summary,
                }
                self.records.append(rec)
                out.append(rec)
            if len(summaries) > 1:
                # shared-device honesty (round 16): the interval-UNION
                # busy fraction as a replica=-1 summary — per-replica
                # fractions overlap on a shared device and must not be
                # summed (module docstring)
                fleet = fleet_busy_summary(self.records)
                rec = {
                    "kind": "overlap", "ev": "summary", "replica": -1,
                    "union": True,
                    "launches": sum(s["launches"]
                                    for s in summaries.values()),
                    "busy_s": fleet["union_busy_s"],
                    "span_s": fleet["window_s"],
                    "window_s": fleet["window_s"],
                    "busy_frac": fleet["union_busy_frac"],
                }
                self.records.append(rec)
                out.append(rec)
            self._unemitted = (
                len(out) + self._unemitted if self.sink is not None else 0
            )
            # final drain writes bubbles + summaries + any buffered tail
            if self.sink is not None:
                pending = self.records[
                    len(self.records) - self._unemitted:
                ]
                for rec in pending:
                    self.sink.log(**rec)
                self._unemitted = 0
        return out

    def snapshot(self) -> List[dict]:
        """A consistent copy of the record list — worker threads append
        concurrently under the lock, so live readers (the fleet metrics
        rollup) must not iterate ``records`` bare."""
        with self._lock:
            return list(self.records)

    def census_decls(self):
        from pytorch_distributed_tpu.telemetry.census import Decl

        return [
            Decl("records", "unbounded",
                 why="O(launches) profiling log by design — the ledger "
                     "is enabled only for bounded bench windows; soaks "
                     "run NULL_LEDGER and take per-tick wall from "
                     "hostprof.ResourceMonitor instead"),
            Decl("_streams", "unbounded",
                 why="per-replica launch stream mirroring ``records`` "
                     "(same bound, same bench-window-only lifetime)"),
        ]


#: Shared no-op ledger (the NULL_TRACER pattern): call sites thread one
#: through unconditionally.
NULL_LEDGER = DispatchLedger(enabled=False)


# ---------------------------------------------------------------------------
# stream-side analysis: timelines, bubbles, summaries
# ---------------------------------------------------------------------------


def overlap_records(records: Iterable[dict],
                    ev: Optional[str] = None) -> List[dict]:
    return [
        r for r in records
        if r.get("kind") == "overlap" and (ev is None or r.get("ev") == ev)
    ]


def device_timeline(records: Iterable[dict],
                    replica: Optional[int] = None
                    ) -> Dict[int, List[dict]]:
    """Per-replica device timeline from launch records: each entry is
    the launch record plus ``start``/``end`` — the busy slice under the
    in-order-execution model (``end = max(done or t1, prev end)``,
    ``start = max(t0, prev end)``). Exact on a synchronous backend;
    a lower bound on busy under true async dispatch (module docstring).
    """
    launches = overlap_records(records, "launch")
    by_rep: Dict[int, List[dict]] = {}
    for r in launches:
        if replica is not None and r.get("replica") != replica:
            continue
        by_rep.setdefault(r.get("replica", 0), []).append(r)
    out: Dict[int, List[dict]] = {}
    for rep, recs in by_rep.items():
        recs.sort(key=lambda r: r.get("t0", 0.0))
        prev_end = None
        slices = []
        for r in recs:
            end = r.get("done", r.get("t1", 0.0))
            if prev_end is not None:
                end = max(end, prev_end)
            start = r.get("t0", 0.0)
            if prev_end is not None:
                start = max(start, prev_end)
            slices.append({**r, "start": start, "end": end})
            prev_end = end
        out[rep] = slices
    return out


def _overlap_s(a0: float, a1: float, b0: float, b1: float) -> float:
    return max(0.0, min(a1, b1) - max(a0, b0))


def classify_bubbles(records: Iterable[dict],
                     min_gap_s: float = 0.0) -> List[dict]:
    """Every inter-launch gap on every replica stream, attributed to
    its host cause (module docstring: other-replica busy slices first,
    then ledger host marks, then the span-stream seq join, else
    idle-no-work). Returns plain dicts (no ``kind``/``ev``) sorted by
    gap start; ``DispatchLedger.finalize`` wraps them into
    ``ev="bubble"`` records."""
    records = list(records)
    timelines = device_timeline(records)
    hosts = overlap_records(records, "host")
    spans = [r for r in records if r.get("kind") == "span"]
    window = _global_window(timelines)
    bubbles: List[dict] = []
    for rep, slices in timelines.items():
        others = [
            s for r, ss in timelines.items() if r != rep for s in ss
        ]
        # gaps between adjacent launches, PLUS the edge idle inside the
        # fleet-wide window: before this replica's first launch and
        # after its last (a drained decode replica idling out the run's
        # tail is real lost device time — edge gaps make busy + bubbles
        # tile the window exactly)
        gaps: List[Tuple[float, float, Optional[dict], Optional[dict]]] = []
        if window is not None and slices:
            if slices[0].get("t0", 0.0) > window[0]:
                gaps.append((window[0], slices[0]["t0"], None, slices[0]))
        for cur, nxt in zip(slices, slices[1:]):
            gaps.append((cur["end"], nxt.get("t0", cur["end"]), cur, nxt))
        if window is not None and slices:
            if window[1] > slices[-1]["end"]:
                gaps.append((slices[-1]["end"], window[1], slices[-1],
                             None))
        for g0, g1, cur, nxt in gaps:
            gap = g1 - g0
            if gap <= min_gap_s:
                continue
            causes: Dict[str, float] = {}
            for s in others:
                # the other replica's host-side dispatch wall occupying
                # the loop is SERIALIZATION (other-replica-tick); its
                # device execution beyond that wall is the shared
                # device working for someone else (shared-device-wait).
                # A sync launch's wall contains its execution, so
                # synchronous-loop attribution is unchanged; an async
                # launch's wall is thin and the split becomes visible.
                d = _overlap_s(g0, g1, s.get("t0", 0.0),
                               s.get("t1", 0.0))
                b = _overlap_s(g0, g1, s["start"], s["end"])
                both = max(
                    0.0,
                    min(g1, s.get("t1", 0.0), s["end"])
                    - max(g0, s.get("t0", 0.0), s["start"]),
                )
                if d > 0:
                    causes[CAUSE_OTHER_REPLICA] = (
                        causes.get(CAUSE_OTHER_REPLICA, 0.0) + d
                    )
                if b - both > 0:
                    causes[CAUSE_SHARED_DEVICE] = (
                        causes.get(CAUSE_SHARED_DEVICE, 0.0) + b - both
                    )
            for h in hosts:
                ov = _overlap_s(g0, g1, h.get("t0", 0.0), h.get("t1", 0.0))
                if ov <= 0:
                    continue
                h_rep = h.get("replica", -1)
                if h_rep not in (-1, rep) and not h.get("thread"):
                    # ANOTHER replica's host work on the shared loop:
                    # this gap exists because the loop was doing that
                    # replica's tick — the definition of
                    # other-replica-tick (worker-thread marks are
                    # overlapped work, not loop serialization, and keep
                    # their own @thread cause below)
                    causes[CAUSE_OTHER_REPLICA] = (
                        causes.get(CAUSE_OTHER_REPLICA, 0.0) + ov
                    )
                else:
                    name = h.get("name", "?")
                    # worker-thread marks (round 16) keep the thread
                    # name in the cause: "jsonl-emit@pdt-host-0" says
                    # the gap overlapped OFFLOADED host work — visible
                    # overlap, not idle-no-work, and distinguishable
                    # from the same work blocking the main loop
                    if h.get("thread"):
                        name = f"{name}@{h['thread']}"
                    causes[name] = causes.get(name, 0.0) + ov
            # apportioned shares (round 16): the winner-take-all cause
            # stays (back-compat; the "dominant cause" cell), but each
            # MEASURED candidate also gets its proportional seconds,
            # with the uncovered remainder booked as idle-no-work —
            # under the async loop a gap is typically a MIX (the other
            # replica's host work + this replica's own collect +
            # unmarked glue), and assigning the whole gap to whichever
            # candidate is largest overstated it (the r06 96% reading
            # was safe only because sync walls covered gaps entirely).
            shares: Optional[Dict[str, float]] = None
            if causes:
                cov = sum(causes.values())
                scale = min(1.0, gap / cov) if cov > 0 else 0.0
                shares = {c: round(v * scale, 9)
                          for c, v in causes.items()}
                rem = gap - sum(shares.values())
                if rem > 1e-12:
                    shares[CAUSE_IDLE] = round(
                        shares.get(CAUSE_IDLE, 0.0) + rem, 9
                    )
            if not causes:
                # the PR 12 join: span records whose logical-clock seq
                # falls inside the gap's window tell what the host loop
                # was doing even where no ledger mark ran (pseudo
                # weights — winner only, no shares: a span is an
                # ordering witness, not a measured duration)
                s0 = cur.get("seq1") if cur is not None else None
                s1 = nxt.get("seq0") if nxt is not None else None
                if s0 is not None and s1 is not None:
                    for sp in spans:
                        if s0 < sp.get("seq", -1) < s1:
                            cause = _SPAN_CAUSES.get(sp.get("name", ""))
                            if cause:
                                causes[cause] = causes.get(cause, 0.0) + 1e-9
            cause = (
                max(causes.items(), key=lambda kv: kv[1])[0]
                if causes else CAUSE_IDLE
            )
            rec = {
                "replica": rep, "cause": cause,
                "gap_s": round(gap, 9), "t0": g0, "t1": g1,
                "after": cur.get("program") if cur is not None else None,
                "before": nxt.get("program") if nxt is not None else None,
                "seq0": cur.get("seq1") if cur is not None else None,
                "seq1": nxt.get("seq0") if nxt is not None else None,
            }
            if shares is not None:
                rec["shares"] = shares
            bubbles.append(rec)
    bubbles.sort(key=lambda b: b["t0"])
    return bubbles


def _global_window(timelines: Dict[int, List[dict]]
                   ) -> Optional[Tuple[float, float]]:
    """The fleet-wide measurement window: first dispatch start to last
    completion across every replica stream."""
    starts = [s[0].get("t0", s[0]["start"]) for s in timelines.values()
              if s]
    ends = [s[-1]["end"] for s in timelines.values() if s]
    if not starts:
        return None
    return min(starts), max(ends)


def busy_summary(records: Iterable[dict]) -> Dict[int, dict]:
    """Per-replica rollup: launches, busy seconds, the replica stream's
    own span, the fleet-wide window, and the busy fraction (busy /
    WINDOW — a replica that drained early and idled out the run's tail
    is idle for it, which is what makes fractions comparable across
    replicas). ``busy + Σ bubbles == window`` per replica by
    construction, so the bubbles tile the idle time exactly."""
    out: Dict[int, dict] = {}
    timelines = device_timeline(records)
    window = _global_window(timelines)
    for rep, slices in timelines.items():
        if not slices:
            continue
        busy = sum(s["end"] - s["start"] for s in slices)
        span = slices[-1]["end"] - slices[0]["start"]
        w = (window[1] - window[0]) if window is not None else span
        out[rep] = {
            "launches": len(slices),
            "busy_s": round(busy, 9),
            "span_s": round(span, 9),
            "window_s": round(w, 9),
            "busy_frac": round(busy / w, 6) if w > 0 else 1.0,
        }
    return out


def fleet_busy_summary(records: Iterable[dict]) -> dict:
    """Shared-device-honest fleet rollup: the interval UNION of every
    replica's busy slices over the fleet-wide window, next to the
    per-replica fractions. On a host where N replicas share one device
    (the CPU simulation — and any oversubscribed placement), a launch's
    dispatch→completion window includes time spent queued behind the
    other replica's program, so per-replica "busy" slices overlap and
    their SUM double-counts the device. The union is true device
    utilization; the per-replica fractions are per-stream scheduling
    health. The ``gather_ab_backend`` pattern: report both, marked.

    Returns ``{"replicas": {rep: busy_frac}, "union_busy_s",
    "window_s", "union_busy_frac"}`` (zeros when no launches)."""
    records = list(records)
    timelines = device_timeline(records)
    window = _global_window(timelines)
    per = {rep: s["busy_frac"] for rep, s in busy_summary(records).items()}
    if window is None:
        return {"replicas": per, "union_busy_s": 0.0, "window_s": 0.0,
                "union_busy_frac": 0.0}
    intervals = sorted(
        (s["start"], s["end"])
        for slices in timelines.values() for s in slices
        if s["end"] > s["start"]
    )
    merged: List[List[float]] = []
    for a, b in intervals:
        if merged and a <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], b)
        else:
            merged.append([a, b])
    union = sum(b - a for a, b in merged)
    w = window[1] - window[0]
    return {
        "replicas": per,
        "union_busy_s": round(union, 9),
        "window_s": round(w, 9),
        "union_busy_frac": round(union / w, 6) if w > 0 else 0.0,
    }


def busy_within(records: Iterable[dict], replica: int,
                t0: float, t1: float) -> Tuple[float, float]:
    """``(busy_s, bubble_s)`` of ``replica``'s device inside the wall
    window ``[t0, t1]`` — the per-decode-window split
    ``scripts/explain_request.py`` annotates request phases with."""
    if t1 <= t0:
        return 0.0, 0.0
    slices = device_timeline(records, replica).get(replica, [])
    busy = sum(_overlap_s(t0, t1, s["start"], s["end"]) for s in slices)
    busy = min(busy, t1 - t0)
    return busy, (t1 - t0) - busy


def cause_histogram(records: Iterable[dict]) -> Dict[str, dict]:
    """``{cause: {count, gap_s}}`` from ``ev="bubble"`` records (the
    report's histogram; recompute with ``classify_bubbles`` when a
    stream carries launches but no finalize ran). Bubbles carrying
    apportioned ``shares`` (round 16) contribute their measured
    per-cause seconds; legacy/span-joined bubbles contribute their
    whole gap to the winning cause. ``count`` counts bubbles a cause
    appeared in, either way."""
    hist: Dict[str, dict] = {}
    bubbles = overlap_records(records, "bubble")
    if not bubbles:
        bubbles = classify_bubbles(records)
    for b in bubbles:
        shares = b.get("shares")
        if isinstance(shares, dict) and shares:
            for cause, sec in shares.items():
                h = hist.setdefault(cause, {"count": 0, "gap_s": 0.0})
                h["count"] += 1
                h["gap_s"] += sec
        else:
            h = hist.setdefault(b.get("cause", "?"),
                                {"count": 0, "gap_s": 0.0})
            h["count"] += 1
            h["gap_s"] += b.get("gap_s", 0.0)
    for h in hist.values():
        h["gap_s"] = round(h["gap_s"], 9)
    return hist
