"""Streaming anomaly sentinel: robust z-scores over the run's own series.

PR 4 made every latency and step-time series exact; nothing *watched*
them — a degrading run surfaced only when a human read the JSONL (or the
watchdog's hard deadline fired, minutes too late). The sentinel closes
that gap with a detector cheap enough to run on every observation:

- per-series rolling window of the last ``window`` values;
- robust center/scale: median and MAD (×1.4826, the normal-consistency
  constant), so the baseline itself is immune to the outliers it hunts
  and to the multi-second first-step compile that would wreck a
  mean/stddev baseline;
- a value is anomalous when ``|x - median| / scale > threshold`` once
  ``min_samples`` observations exist. An all-equal window has MAD 0; the
  scale floors at ``rel_floor·|median|`` (+ an absolute epsilon) so a
  constant series flags genuine departures without dividing by zero.

Anomalous values still ENTER the window: MAD tolerates <50% contamination,
and absorbing them means a genuine level shift (a slower disk, a new
steady state) stops alarming once it becomes the new normal — the
detector flags *transitions*, not states.

Each hit emits one ``kind="anomaly"`` JSONL record carrying the value,
the baseline it violated, and a context window of the observations that
preceded it — the forensic record ``scripts/telemetry_report.py`` and
``scripts/pdt_top.py`` surface. Determinism: no wall clock, no RNG — the
same series flags the same indices on every run, which is what lets
``resilience/faults.py`` hang injection prove the sentinel in a test.

The serving scheduler feeds it tick time, TTFT, and queue depth and
exposes ``anomaly_recent`` in ``metrics()``; the fleet ``SLOGate`` treats
a recently-anomalous replica as hot (spill-around), making the sentinel
an admission signal, not just a log line.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, Optional


class StreamingDetector:
    """One series' rolling median/MAD detector."""

    def __init__(self, window: int = 64, threshold: float = 8.0,
                 min_samples: int = 8, context: int = 8,
                 rel_floor: float = 0.05, abs_floor: float = 1e-9):
        if window < 4:
            raise ValueError(f"window must be >= 4, got {window}")
        if threshold <= 0:
            raise ValueError(f"threshold must be > 0, got {threshold}")
        self.window = window
        self.threshold = float(threshold)
        self.min_samples = max(int(min_samples), 2)
        self.context = int(context)
        self.rel_floor = float(rel_floor)
        self.abs_floor = float(abs_floor)
        self._buf: deque = deque(maxlen=window)
        self.seen = 0
        self.anomalies = 0

    def observe(self, value: float) -> Optional[dict]:
        """Test ``value`` against the CURRENT baseline (the spike must not
        contaminate the window it is judged by), then absorb it. Returns
        the anomaly record dict, or None."""
        import numpy as np

        value = float(value)
        self.seen += 1
        hit = None
        if len(self._buf) >= self.min_samples:
            buf = np.asarray(self._buf, dtype=np.float64)
            med = float(np.median(buf))
            mad = float(np.median(np.abs(buf - med)))
            scale = max(
                1.4826 * mad, self.rel_floor * abs(med), self.abs_floor
            )
            z = (value - med) / scale
            if abs(z) > self.threshold:
                self.anomalies += 1
                hit = {
                    "value": value,
                    "zscore": round(z, 2),
                    "median": med,
                    "mad": mad,
                    "n_baseline": int(len(buf)),
                    "index": self.seen - 1,
                    "context": [
                        round(v, 9) for v in list(self._buf)[-self.context:]
                    ],
                }
        self._buf.append(value)
        return hit


class AnomalySentinel:
    """Named-series front end over per-series detectors.

    ``observe(series, value, **meta)`` returns the anomaly record (meta
    merged in) or None, and streams it as ``kind="anomaly"`` JSONL when a
    ``metrics_log`` is attached (attachable after construction — the
    trainers build the sentinel before their logger exists). An optional
    ``flightrec`` gets one ring event per hit, so a post-mortem dump
    shows the anomalies that preceded death."""

    def __init__(self, threshold: float = 8.0, window: int = 64,
                 min_samples: int = 8, context: int = 8,
                 metrics_log=None, flightrec=None, source: str = ""):
        self.threshold = float(threshold)
        self.window = int(window)
        self.min_samples = int(min_samples)
        self.context = int(context)
        self.metrics_log = metrics_log
        self.flightrec = flightrec
        self.source = source
        self._detectors: Dict[str, StreamingDetector] = {}
        self.anomalies = 0
        # round 16: the async host runtime feeds tick series from its
        # worker pool, so detector windows and the hit counter mutate
        # under one lock (the median/MAD math runs inside it too —
        # observe() must judge and absorb atomically per series)
        self._lock = threading.Lock()

    def detector(self, series: str) -> StreamingDetector:
        det = self._detectors.get(series)
        if det is None:
            det = self._detectors[series] = StreamingDetector(
                window=self.window, threshold=self.threshold,
                min_samples=self.min_samples, context=self.context,
            )
        return det

    def observe(self, series: str, value: float, **meta) -> Optional[dict]:
        with self._lock:
            hit = self.detector(series).observe(value)
            if hit is None:
                return None
            self.anomalies += 1
        hit["series"] = series
        if self.source:
            hit["source"] = self.source
        hit.update(meta)
        if self.metrics_log is not None:
            self.metrics_log.log(kind="anomaly", **hit)
        if self.flightrec is not None:
            self.flightrec.record(
                "anomaly", series=series, value=hit["value"],
                zscore=hit["zscore"],
            )
        return hit

    def counts(self) -> Dict[str, int]:
        return {
            name: det.anomalies for name, det in self._detectors.items()
            if det.anomalies
        }

    def census_decls(self):
        from .census import Decl

        return [
            Decl("_detectors", "fixed", cap=64,
                 why="one detector per named series; call sites name a "
                     "closed set (tick_time, ttft, queue_depth, ...) and "
                     "each detector's window is a deque(maxlen)"),
        ]
