"""Exact host-side latency series with percentile summaries.

The serving scheduler holds every timestamp a latency SLO needs — submit,
admit, first token, per-token ticks — but round 6 reported only
throughput. This module is the missing aggregation: append raw seconds,
summarize with exact percentiles (``numpy.percentile``, linear
interpolation — no bucketing error at demo scale; the series are
host-side floats, never device work).

Used for TTFT (submit → first materialized token), per-output-token
latency (inter-token gap per stream), and queue wait (submit → admit).
"""

from __future__ import annotations

from typing import Dict, List, Sequence


def percentiles(
    values: Sequence[float], qs: Sequence[float] = (50, 95, 99)
) -> Dict[str, float]:
    """``{"p50": ..., "p95": ...}`` via numpy's linear interpolation;
    empty input → empty dict."""
    import numpy as np

    vals = np.asarray(list(values), dtype=np.float64)
    if vals.size == 0:
        return {}
    return {
        f"p{q:g}": float(np.percentile(vals, q)) for q in qs
    }


class LatencySeries:
    """Append-only series of seconds with a flat summary.

    ``summary(prefix)`` → ``{prefix_count, prefix_mean_s, prefix_p50_s,
    prefix_p95_s, prefix_p99_s, prefix_max_s}`` (empty series → counts
    only), ready to merge into a metrics dict / JSONL record.
    """

    def __init__(self, name: str = ""):
        self.name = name
        self.values: List[float] = []

    def observe(self, seconds: float) -> None:
        self.values.append(float(seconds))

    def __len__(self) -> int:
        return len(self.values)

    def summary(self, prefix: str = "") -> dict:
        import numpy as np

        p = f"{prefix}_" if prefix else ""
        out = {f"{p}count": len(self.values)}
        if not self.values:
            return out
        vals = np.asarray(self.values, dtype=np.float64)
        out[f"{p}mean_s"] = float(vals.mean())
        out[f"{p}max_s"] = float(vals.max())
        for q, v in percentiles(vals).items():
            out[f"{p}{q}_s"] = v
        return out
