"""Exact host-side latency series with percentile summaries.

The serving scheduler holds every timestamp a latency SLO needs — submit,
admit, first token, per-token ticks — but round 6 reported only
throughput. This module is the missing aggregation: append raw seconds,
summarize with exact percentiles (``numpy.percentile``, linear
interpolation — no bucketing error at demo scale; the series are
host-side floats, never device work).

Used for TTFT (submit → first materialized token), per-output-token
latency (inter-token gap per stream), and queue wait (submit → admit).
"""

from __future__ import annotations

from typing import Dict, List, Sequence


def percentiles(
    values: Sequence[float], qs: Sequence[float] = (50, 95, 99)
) -> Dict[str, float]:
    """``{"p50": ..., "p95": ...}`` via numpy's linear interpolation;
    empty input → empty dict."""
    import numpy as np

    vals = np.asarray(list(values), dtype=np.float64)
    if vals.size == 0:
        return {}
    return {
        f"p{q:g}": float(np.percentile(vals, q)) for q in qs
    }


class LatencySeries:
    """Windowed series of seconds with a cumulative flat summary.

    ``summary(prefix)`` → ``{prefix_count, prefix_mean_s, prefix_p50_s,
    prefix_p95_s, prefix_p99_s, prefix_max_s}`` (empty series → counts
    only), ready to merge into a metrics dict / JSONL record.

    Round 21 (scale observatory): the raw buffer is capped at
    ``window`` observations so a 100k-session soak doesn't hold every
    latency sample ever taken — the census declares this bound.
    ``count``/``mean_s``/``max_s`` stay *cumulative* (running count,
    sum, and max survive the window); percentiles are over the most
    recent ``window`` observations, which is also what an SLO gate
    wants to react to.  ``values`` remains a plain list (consumers
    concatenate and snapshot it) holding at most ``2 * window``
    entries — trimming is amortized by slicing half away only when the
    buffer doubles, keeping ``observe`` O(1) amortized.
    """

    def __init__(self, name: str = "", window: int = 4096):
        self.name = name
        self.window = int(window)
        self.values: List[float] = []
        self.count = 0
        self._sum = 0.0
        self._max = 0.0

    def observe(self, seconds: float) -> None:
        s = float(seconds)
        self.values.append(s)
        self.count += 1
        self._sum += s
        if s > self._max:
            self._max = s
        if len(self.values) >= 2 * self.window:
            del self.values[: len(self.values) - self.window]

    def __len__(self) -> int:
        return self.count

    def window_values(self) -> List[float]:
        return self.values[-self.window:]

    def census_decls(self):
        from .census import Decl

        return [
            Decl("values", "fixed", cap=lambda s: 2 * s.window,
                 why="percentile window; amortized trim keeps ≤ 2·window "
                     "entries, cumulative count/sum/max live in scalars"),
        ]

    def summary(self, prefix: str = "") -> dict:
        import numpy as np

        p = f"{prefix}_" if prefix else ""
        out = {f"{p}count": self.count}
        if not self.values:
            return out
        out[f"{p}mean_s"] = self._sum / self.count
        out[f"{p}max_s"] = self._max
        vals = np.asarray(self.window_values(), dtype=np.float64)
        for q, v in percentiles(vals).items():
            out[f"{p}{q}_s"] = v
        return out
