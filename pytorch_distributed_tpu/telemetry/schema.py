"""JSONL schema registry: the one place each record ``kind`` is declared.

Every telemetry producer in this repo writes through
``utils.profiling.MetricsLogger``, but until round 14 the record shapes
lived only in the emitters — ``telemetry_report.py`` and ``pdt_top.py``
discovered drift at render time (a silently absent key degrades a
section, never fails a build). This module makes the contract explicit:
``REQUIRED_KEYS`` names the keys every record of a kind must carry,
``validate_record`` checks one record, ``validate_stream`` a whole run.
``tests/test_reqtrace.py`` replays every emitter against it, so a
producer dropping or renaming a key breaks CI instead of the report.

The registry is deliberately a FLOOR, not a straitjacket: emitters may
add keys freely (reports use ``.get`` for optional ones); only removing
a required key — the ones consumers index unconditionally — is a
schema break. Unknown kinds pass by default (``strict=True`` flags
them), so an experiment can stream new record kinds without registering
first; promotion to the registry happens when a consumer starts
depending on them.
"""

from __future__ import annotations

from typing import Dict, FrozenSet, Iterable, List

#: required keys per record kind. ``ts`` is stamped by MetricsLogger
#: itself and therefore not listed. Span records are versioned
#: separately (``v``; reqtrace.SPAN_SCHEMA_VERSION) and their per-``ev``
#: shapes are refined by ``_SPAN_EV_KEYS`` below.
REQUIRED_KEYS: Dict[str, FrozenSet[str]] = {
    # serving/scheduler.py per-retirement + fleet shed records
    "request": frozenset(
        {"rid", "replica_id", "rejected", "prompt_len", "new_tokens"}
    ),
    # serving/scheduler.py preempt decision (round 13)
    "preempt": frozenset(
        {"rid", "replica_id", "reason", "decision", "decision_reason",
         "predicted_swap_s", "predicted_recompute_s"}
    ),
    # serving/scheduler.py swap-out/in outcomes
    "swap": frozenset({"rid", "replica_id", "direction", "ok"}),
    # serving/scheduler.py shared-prefix admissions (round 17)
    "prefix": frozenset(
        {"rid", "replica_id", "prompt_len", "covered", "shared_blocks",
         "cow"}
    ),
    # telemetry/reqtrace.py lifecycle spans (round 14)
    "span": frozenset({"v", "ev", "trace", "span", "seq", "t"}),
    # telemetry/overlap.py dispatch ledger (round 15); per-``ev`` shapes
    # refined by ``_OVERLAP_EV_KEYS`` below
    "overlap": frozenset({"ev", "replica"}),
    # telemetry/goodput.py ledger report
    "goodput": frozenset({"goodput_frac", "productive_s", "wall_s"}),
    # telemetry/anomaly.py sentinel hits
    "anomaly": frozenset({"series", "value", "median", "mad", "zscore"}),
    # telemetry/costmodel.py per-program cost cards
    "program_cost": frozenset({"program", "calls"}),
    # fleet/router.py run rollup
    "fleet_summary": frozenset(
        {"replicas", "submitted", "shed", "spilled", "handoffs",
         "preempts", "restores", "tokens_out"}
    ),
    # recipes/serve_lm.py single-scheduler rollup
    "serving_summary": frozenset({"tokens_out", "completed"}),
    # compilecache/warmup.py per-program manifest
    "warmup": frozenset({"program", "seconds", "cache_hit"}),
    # analysis/blocksan.py block-lifecycle sanitizer (round 18);
    # per-``ev`` shapes refined by ``_SANITIZER_EV_KEYS`` below
    "sanitizer": frozenset({"ev", "shadow", "replica_id"}),
    # fleet/router.py replica health transitions (round 19): one record
    # per state-machine edge (healthy/suspect/dead/draining/rejoining)
    "health": frozenset({"replica_id", "state", "prev", "reason", "tick"}),
    # telemetry/hostprof.py host-resource samples (round 21): RSS in MiB
    # plus the load axes the growth sentinel regresses against;
    # gc/tracemalloc/tick-wall fields are optional extras
    "resource": frozenset({"rss_mib", "rss_source", "live", "cumulative"}),
    # telemetry/census.py bounded-structure sweeps (round 21): per-sweep
    # verdict + per-structure sizes; violation_details/undeclared carry
    # the loud-finding payloads
    "census": frozenset({"ok", "violations", "structures", "worst_ratio"}),
    # gateway/server.py per-connection ingress records (round 22): one
    # per /v1/generate connection — rid (-1 when rejected before
    # admission), HTTP status, the X-Deadline-Ms budget (null when
    # absent), whether the client disconnected, SSE bytes written, and
    # TTFT measured over the wire (null when no token ever reached the
    # socket); outcome/tokens/reason/gap_max_ms/open/queued ride as
    # optional extras
    "http": frozenset(
        {"rid", "route", "status", "deadline", "disconnect", "bytes",
         "ttft_wire"}
    ),
}

#: additional required keys per span ``ev`` (see reqtrace module docs)
_SPAN_EV_KEYS: Dict[str, FrozenSet[str]] = {
    "begin": frozenset({"name"}),
    "end": frozenset({"dur_s"}),
    "event": frozenset({"name"}),
    "link": frozenset({"dst", "name"}),
}

#: additional required keys per overlap ``ev`` (see overlap module docs)
_OVERLAP_EV_KEYS: Dict[str, FrozenSet[str]] = {
    "launch": frozenset({"program", "t0", "t1", "seq0", "seq1"}),
    "host": frozenset({"name", "t0", "t1", "seq0", "seq1"}),
    "bubble": frozenset({"cause", "gap_s", "t0", "t1"}),
    "summary": frozenset({"launches", "busy_s", "span_s", "busy_frac"}),
}

#: additional required keys per sanitizer ``ev`` (analysis/blocksan.py)
_SANITIZER_EV_KEYS: Dict[str, FrozenSet[str]] = {
    "violation": frozenset({"class", "block", "owner", "site"}),
    "quiesce": frozenset({"ok", "live_blocks", "violations"}),
}


def validate_record(record: dict, strict: bool = False) -> List[str]:
    """Errors for one record (empty list == conformant). ``strict``
    additionally flags kinds the registry does not know."""
    kind = record.get("kind")
    if kind is None:
        return ["record has no 'kind' key"]
    required = REQUIRED_KEYS.get(kind)
    if required is None:
        return [f"unknown kind {kind!r}"] if strict else []
    errors = [
        f"kind={kind}: missing required key {k!r}"
        for k in sorted(required) if k not in record
    ]
    for refined, table in (("span", _SPAN_EV_KEYS),
                           ("overlap", _OVERLAP_EV_KEYS),
                           ("sanitizer", _SANITIZER_EV_KEYS)):
        if kind != refined:
            continue
        ev = record.get("ev")
        ev_keys = table.get(ev)
        if ev_keys is None:
            errors.append(f"kind={kind}: unknown ev {ev!r}")
        else:
            errors.extend(
                f"kind={kind} ev={ev}: missing required key {k!r}"
                for k in sorted(ev_keys) if k not in record
            )
    return errors


def validate_stream(records: Iterable[dict],
                    strict: bool = False) -> List[str]:
    """Errors across a record stream, each prefixed with its index —
    the CI conformance gate (and a debugging aid: the index is the JSONL
    line number for an unrotated stream)."""
    errors: List[str] = []
    for i, record in enumerate(records):
        errors.extend(f"record {i}: {e}"
                      for e in validate_record(record, strict=strict))
    return errors
