"""Flight recorder: a bounded ring of recent events, dumped on disaster.

A crashed or stalled run used to leave only ``watchdog_stall.log`` — a
stack dump with no history. The flight recorder keeps the last
``capacity`` structured events (step results, decode ticks, admissions,
spills/sheds, handoffs, checkpoint saves, rollbacks, watchdog beats,
anomalies) in memory, and writes them out two ways:

- ``dump(path, reason)`` — an ATOMIC snapshot (tmp + ``os.replace``) of
  the whole ring with a header, taken at the trigger sites: watchdog
  stall, StepGuard rollback, suspend, and unhandled exception (the
  chained ``sys.excepthook``). A half-written dump can never exist.
- an optional **mirror**: every event also appends one line to a
  size-capped JSONL (``MetricsLogger`` with rotation), durable the
  moment ``record`` returns. SIGKILL runs no handlers — the mirror is
  what lets the resilience kill-matrix relaunch read the last events
  *before* the kill site even though the process never got to dump.

Recording is cheap (one dict build + deque append + one buffered write
when mirrored), so per-step / per-tick recording is fine; ``seq`` is a
monotone event counter, so a reader can detect the ring's horizon and
order events without trusting wall clocks.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from collections import deque
from typing import List, Optional


class FlightRecorder:
    """Bounded in-memory event ring with atomic dumps and an optional
    durable JSONL mirror."""

    def __init__(self, capacity: int = 256, mirror_path: Optional[str] = None,
                 mirror_max_bytes: int = 1 << 20, enabled: bool = True):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.enabled = bool(enabled)
        self._ring: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._seq = 0
        self.dumps = 0
        self._mirror = None
        self._prev_excepthook = None
        self._excepthook_path: Optional[str] = None
        if mirror_path and self.enabled:
            from pytorch_distributed_tpu.utils.profiling import MetricsLogger

            # per-process stream (rank0_only=False): the crash child whose
            # death the mirror must survive is not always rank 0's twin
            self._mirror = MetricsLogger(
                mirror_path, rank0_only=False, max_bytes=mirror_max_bytes
            )

    # -- recording ---------------------------------------------------------

    def record(self, kind: str, **fields) -> None:
        if not self.enabled:
            return
        with self._lock:
            seq = self._seq
            self._seq += 1
            event = {"seq": seq, "ts": time.time(), "kind": kind, **fields}
            self._ring.append(event)
        if self._mirror is not None:
            # MetricsLogger is line-buffered: durable before return
            self._mirror.log(**event)

    def snapshot(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def census_decls(self):
        from pytorch_distributed_tpu.telemetry.census import Decl

        return [
            Decl("_ring", "fixed", cap=lambda r: r._ring.maxlen,
                 why="deque(maxlen=capacity): the bounded ring is the "
                     "module's whole design"),
        ]

    # -- dumping -----------------------------------------------------------

    def dump(self, path: str, reason: str) -> Optional[str]:
        """Atomic ring snapshot → ``path``. Never raises (a forensics
        write must not take down the run it is documenting); returns the
        path, or None on failure/disabled."""
        if not self.enabled:
            return None
        try:
            events = self.snapshot()
            payload = {
                "reason": reason,
                "pid": os.getpid(),
                "dumped_at": time.time(),
                "events": events,
                "first_seq": events[0]["seq"] if events else None,
                "last_seq": events[-1]["seq"] if events else None,
            }
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, path)
            self.dumps += 1
            self.record("dump", reason=reason, path=path)
            return path
        except Exception:
            return None

    # -- unhandled exceptions ----------------------------------------------

    def install_excepthook(self, path: str) -> None:
        """Chain onto ``sys.excepthook``: an unhandled exception dumps the
        ring (reason ``exception:<Type>``) before the previous hook runs.
        Idempotent; ``uninstall_excepthook`` restores the chain."""
        if self._prev_excepthook is not None or not self.enabled:
            self._excepthook_path = path
            return
        self._excepthook_path = path
        self._prev_excepthook = sys.excepthook

        def hook(exc_type, exc, tb):
            self.record("exception", type=exc_type.__name__, msg=str(exc))
            self.dump(self._excepthook_path, f"exception:{exc_type.__name__}")
            (self._prev_excepthook or sys.__excepthook__)(exc_type, exc, tb)

        self._hook = hook
        sys.excepthook = hook

    def uninstall_excepthook(self) -> None:
        if self._prev_excepthook is None:
            return
        if sys.excepthook is getattr(self, "_hook", None):
            sys.excepthook = self._prev_excepthook
        self._prev_excepthook = None

    def close(self) -> None:
        self.uninstall_excepthook()
        if self._mirror is not None:
            self._mirror.close()


def read_dump(path: str) -> dict:
    """Load a dump written by :meth:`FlightRecorder.dump`."""
    with open(path) as f:
        return json.load(f)


def read_mirror(path: str) -> List[dict]:
    """Events from a mirror JSONL (rotated generation first, so events
    come back in seq order even across a rotation boundary). Tolerates a
    torn final line — the one a SIGKILL can leave."""
    events: List[dict] = []
    for p in (f"{path}.1", path):
        if not os.path.exists(p):
            continue
        with open(p) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    events.append(json.loads(line))
                except json.JSONDecodeError:
                    continue  # torn tail: the kill mid-write
    return events


#: Shared no-op recorder (the NULL_TRACER pattern): call sites thread a
#: recorder through without caring whether anyone is listening.
NULL_RECORDER = FlightRecorder(enabled=False)
