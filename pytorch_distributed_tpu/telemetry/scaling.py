"""Growth sentinel — regress host cost against load, flag growth.

Round 21.  A 100k-session soak produces per-sample series of RSS and
mean per-tick host wall against live-and-cumulative session counts
(``hostprof.ResourceMonitor``) plus per-structure sizes
(``census.StructCensus``).  ROADMAP item 5's acceptance is that these
stay *flat*: host cost must be O(live batch), not O(sessions ever).
This module turns "looks flat" into a fit with a noise floor.

The flagging rule reuses the PR 8 anomaly-sentinel floor idea: a
series' natural jitter scale is ``max(1.4826·MAD, rel_floor·|median|,
abs_floor)`` — so a constant series (MAD 0) cannot flag off numeric
dust, and a noisy-but-flat series needs *total fitted growth across
the observed load range* to exceed ``threshold ×`` that scale before
it counts as growing.  Superlinearity is judged by refitting each half
of the load range: accelerating slope (second half ≫ first half) on a
growing series reads as superlinear — the O(N²) shape a per-tick scan
of an O(N) structure produces.

On a shared-CPU runner the *wall* series is noisy (neighbors steal the
core); the MAD floor absorbs that, but a wall verdict here is a smoke
alarm, not a proof — see ANALYSIS.md "Scale observatory" for what a
slope does and does not establish.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["fit_growth", "mad_scale", "GrowthSentinel"]


def _median(vals: Sequence[float]) -> float:
    s = sorted(vals)
    n = len(s)
    if not n:
        return 0.0
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def mad_scale(ys: Sequence[float], *, rel_floor: float = 0.05,
              abs_floor: float = 1e-9) -> float:
    """Robust jitter scale with the PR 8 sentinel floors applied."""
    med = _median(ys)
    mad = _median([abs(y - med) for y in ys])
    return max(1.4826 * mad, rel_floor * abs(med), abs_floor)


def _ols(xs: Sequence[float], ys: Sequence[float]) -> Tuple[float, float]:
    n = len(xs)
    mx = sum(xs) / n
    my = sum(ys) / n
    sxx = sum((x - mx) ** 2 for x in xs)
    if sxx <= 0.0:
        return 0.0, my
    sxy = sum((x - mx) * (y - my) for x, y in zip(xs, ys))
    slope = sxy / sxx
    return slope, my - slope * mx


def fit_growth(xs: Sequence[float], ys: Sequence[float], *,
               threshold: float = 4.0, rel_floor: float = 0.05,
               abs_floor: float = 1e-9, min_samples: int = 8) -> dict:
    """Fit ``y`` against load ``x``; classify flat / linear / superlinear.

    Returns a dict (JSON-ready): ``slope`` (y-units per x-unit),
    ``growth`` (fitted rise across the observed x span), ``scale``
    (the MAD-floored jitter scale), ``grows`` (growth exceeds
    ``threshold × scale``), ``accel`` (second-half slope over
    first-half slope, 0 when either half is degenerate), and
    ``verdict`` in {"insufficient", "flat", "linear", "superlinear"}.
    """
    n = min(len(xs), len(ys))
    xs, ys = list(xs[:n]), list(ys[:n])
    out = {"n": n, "slope": 0.0, "intercept": 0.0, "growth": 0.0,
           "scale": 0.0, "grows": False, "accel": 0.0,
           "verdict": "insufficient"}
    if n < min_samples:
        return out
    span = max(xs) - min(xs)
    if span <= 0:
        return out
    slope, intercept = _ols(xs, ys)
    # Jitter scale from the fit RESIDUALS — the raw series' MAD
    # contains the trend itself and would mask exactly the growth we
    # hunt; the floors still ride on the series' own level so a
    # constant series (zero residual) cannot flag numeric dust.
    resid = [y - (intercept + slope * x) for x, y in zip(xs, ys)]
    scale = max(mad_scale(resid, rel_floor=0.0, abs_floor=abs_floor),
                rel_floor * abs(_median(ys)), abs_floor)
    growth = slope * span
    grows = growth > threshold * scale
    # Half-range refits for acceleration. Split at the median x so
    # both halves carry data even under bursty sampling.
    pivot = _median(xs)
    lo = [(x, y) for x, y in zip(xs, ys) if x <= pivot]
    hi = [(x, y) for x, y in zip(xs, ys) if x > pivot]
    accel = 0.0
    s_lo = s_hi = 0.0
    if len(lo) >= max(2, min_samples // 2) and len(hi) >= max(
            2, min_samples // 2):
        s_lo, _ = _ols([p[0] for p in lo], [p[1] for p in lo])
        s_hi, _ = _ols([p[0] for p in hi], [p[1] for p in hi])
        floor = scale / max(span, 1e-12)
        if abs(s_lo) > floor:
            accel = s_hi / s_lo
    superlinear = bool(grows and s_hi > 0 and (
        accel > 2.0 or (s_lo <= 0 < s_hi and s_hi * span > threshold * scale)))
    verdict = ("superlinear" if superlinear
               else "linear" if grows else "flat")
    out.update(slope=slope, intercept=intercept, growth=growth,
               scale=scale, grows=bool(grows), accel=round(accel, 3),
               verdict=verdict)
    return out


class GrowthSentinel:
    """Named (load, value) series + end-of-run growth verdicts.

    ``observe(name, x, y)`` appends one point (ring-bounded);
    ``report()`` fits every series; ``flags()`` lists the series whose
    verdict is linear/superlinear.  Structure-size series from the
    census and resource series from the monitor share one sentinel so
    the soak summary has a single "what grew" answer.
    """

    def __init__(self, *, window: int = 4096, threshold: float = 4.0,
                 rel_floor: float = 0.05, abs_floor: float = 1e-9,
                 min_samples: int = 8):
        self.window = int(window)
        self.threshold = threshold
        self.rel_floor = rel_floor
        self.abs_floor = abs_floor
        self.min_samples = min_samples
        self._series: Dict[str, deque] = {}

    def census_decls(self):
        from .census import Decl

        return [
            Decl("_series", "fixed", cap=256,
                 why="one ring per named series; call sites name a closed "
                     "set (rss, tick_wall, census structures)"),
        ]

    def observe(self, name: str, x: float, y: Optional[float]) -> None:
        if y is None:
            return
        buf = self._series.get(name)
        if buf is None:
            buf = self._series[name] = deque(maxlen=self.window)
        buf.append((float(x), float(y)))

    def observe_sizes(self, x: float, sizes: Dict[str, int]) -> None:
        """Feed one census sweep's structure sizes at load ``x``."""
        for name, size in sizes.items():
            self.observe(f"size:{name}", x, float(size))

    def report(self) -> Dict[str, dict]:
        out = {}
        for name, buf in sorted(self._series.items()):
            xs = [p[0] for p in buf]
            ys = [p[1] for p in buf]
            out[name] = fit_growth(
                xs, ys, threshold=self.threshold, rel_floor=self.rel_floor,
                abs_floor=self.abs_floor, min_samples=self.min_samples)
        return out

    def flags(self) -> List[str]:
        return [name for name, fit in self.report().items()
                if fit["verdict"] in ("linear", "superlinear")]
