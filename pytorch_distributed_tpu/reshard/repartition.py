"""Offline checkpoint repartitioning: rewrite the block table for a
target topology, no devices required.

A sharded checkpoint is a manifest (leaf dtype/shape + block table) plus
raw block files; "which mesh it fits" is purely a property of the block
layout. This module recomputes that layout for a target ``{axis: size}``
mesh shape — PartitionSpecs resolved per leaf path from the partition
rule tables (``resolver.spec_for_path``) and turned into block bounds by
plain arithmetic (``block_layout``, the device-free twin of
``utils.checkpoint._canonical_blocks``) — then streams each target block
out of the source's overlapping blocks. Memory high-water is one target
block plus the mmap'd source regions it intersects: the full global
state never exists in this process.

Why pre-reshard at all, when ``load_elastic`` restores cross-topology on
the fly? Assembly cost moves offline: a restore whose target layout
matches the manifest exactly takes the zero-copy fast path on every
block (``ManifestReader.exact_blocks``), which matters when the same
checkpoint is restored many times (a serving fleet fanning one trainer
snapshot out to N replicas) or when restore happens inside a tight
preemption window.
"""

from __future__ import annotations

import json
import os
import zipfile
from typing import Any, Mapping, Optional, Sequence

import numpy as np

from pytorch_distributed_tpu.reshard import resolver
from pytorch_distributed_tpu.utils.checkpoint import (
    MANIFEST,
    ManifestReader,
    _shard_name,
)


def block_layout(shape: Sequence[int], spec,
                 mesh_shape: Mapping[str, int]) -> list:
    """Canonical block bounds ``[(start, stop), ...]`` for a leaf placed
    with ``spec`` on a mesh of ``{axis: size}`` — one block per DISTINCT
    index tuple, exactly what ``_canonical_blocks`` derives from a live
    array's sharding (replication across unnamed axes creates no extra
    blocks). Sorted like the save path sorts, so block numbering matches
    what a live save on that mesh would write."""
    shape = tuple(int(d) for d in shape)
    chunks = []
    for d, dim in enumerate(shape):
        names = spec[d] if d < len(spec) else None
        if names is None:
            parts = 1
        else:
            if not isinstance(names, tuple):
                names = (names,)
            parts = 1
            for a in names:
                parts *= int(mesh_shape.get(a, 1))
        if parts > 1 and dim % parts:
            raise ValueError(
                f"dim {d} of shape {shape} not divisible by {parts} "
                f"(spec {spec} over mesh {dict(mesh_shape)})"
            )
        chunks.append(parts)
    blocks = []
    for idx in np.ndindex(*chunks):
        start = tuple(i * (dim // c)
                      for i, dim, c in zip(idx, shape, chunks))
        stop = tuple(s + dim // c
                     for s, dim, c in zip(start, shape, chunks))
        blocks.append((start, stop))
    # the save path sorts blocks by their (start, stop) key tuple
    return sorted((tuple(zip(s, e)) for s, e in blocks))


class _LegacySource:
    """Adapter giving a legacy single-file msgpack checkpoint the same
    (paths, shape/dtype, read_region) surface as ``ManifestReader``."""

    def __init__(self, path: str):
        from flax import serialization

        with open(path, "rb") as f:
            sd = serialization.msgpack_restore(f.read())
        self._leaves: dict = {}
        self._flatten(sd, [])
        self.mesh_meta = None

    def _flatten(self, node, parts):
        if isinstance(node, Mapping):
            for k, v in node.items():
                self._flatten(v, parts + [str(k)])
        else:
            self._leaves["/".join(parts)] = np.asarray(node)

    def leaf_paths(self) -> list:
        return list(self._leaves)

    def leaf_meta(self, path: str) -> dict:
        arr = self._leaves[path]
        return {"dtype": str(arr.dtype), "shape": list(arr.shape)}

    def read_region(self, path: str, start, stop) -> np.ndarray:
        arr = self._leaves[path]
        if not start:
            return arr
        return arr[tuple(slice(s, e) for s, e in zip(start, stop))]


def repartition(
    src: str | os.PathLike,
    dst: str | os.PathLike,
    mesh_shape: Mapping[str, int],
    *,
    rules: Optional[Sequence] = None,
    config=None,
    fsdp: bool = False,
    mesh_axes: Optional[Sequence[str]] = None,
    overwrite: bool = False,
    verify: bool = False,
) -> dict:
    """Rewrite checkpoint ``src`` (sharded dir or legacy single file) as a
    sharded checkpoint at ``dst`` whose block layout matches a restore
    onto ``mesh_shape`` with the resolved specs. Single-process output
    (one shard file) with a fresh save token and the target topology in
    the manifest. Returns a stats dict (leaves, blocks, bytes,
    exact/assembled source reads, per-leaf spec strings).

    ``verify=True`` re-reads every leaf from both checkpoints afterwards
    and bit-compares — repartitioning must be a pure relayout.
    """
    src = os.fspath(src)
    dst = os.fspath(dst)
    source: Any = (
        ManifestReader(src) if os.path.isdir(src) else _LegacySource(src)
    )
    if os.path.exists(os.path.join(dst, MANIFEST)) and not overwrite:
        raise FileExistsError(
            f"{dst} already holds a checkpoint manifest; pass "
            "overwrite=True (--force) to replace it"
        )
    os.makedirs(dst, exist_ok=True)

    axes = list(mesh_axes) if mesh_axes is not None else list(mesh_shape)
    token = os.urandom(8).hex()
    fname = _shard_name(token, 0)
    manifest: dict = {
        "version": 2,
        "n_processes": 1,
        "token": token,
        "mesh": {"axes": axes,
                 "shape": [int(mesh_shape[a]) for a in axes]},
        "leaves": {},
    }
    stats = {"leaves": 0, "blocks": 0, "bytes": 0, "specs": {}}

    tmp = os.path.join(dst, f"{fname}.tmp.{os.getpid()}")
    with open(tmp, "wb") as raw, \
            zipfile.ZipFile(raw, "w", zipfile.ZIP_STORED) as zf:
        with zf.open("__token__.npy", "w") as f:
            np.lib.format.write_array(
                f, np.frombuffer(bytes.fromhex(token), np.uint8)
            )
        for path in source.leaf_paths():
            meta = source.leaf_meta(path)
            shape = tuple(int(d) for d in meta["shape"])
            dtype = np.dtype(meta["dtype"])
            spec = resolver.spec_for_path(
                path, shape,
                rules if rules is not None else resolver.lm_rules(config),
                mesh_shape, fsdp=fsdp,
            )
            stats["specs"][path] = str(spec)
            blocks = []
            for i, key in enumerate(block_layout(shape, spec, mesh_shape)):
                start = [s for s, _ in key]
                stop = [e for _, e in key]
                region = np.ascontiguousarray(
                    np.asarray(source.read_region(path, start, stop))
                )
                member = f"{path}#{i}"
                with zf.open(member + ".npy", "w",
                             force_zip64=True) as f:
                    np.lib.format.write_array(
                        f, region.reshape(-1).view(np.uint8)
                    )
                blocks.append({"file": fname, "key": member,
                               "start": start, "stop": stop})
                stats["blocks"] += 1
                stats["bytes"] += region.nbytes
            manifest["leaves"][path] = {
                "dtype": str(dtype), "shape": list(shape),
                "blocks": blocks,
            }
            stats["leaves"] += 1
        raw.flush()
        os.fsync(raw.fileno())
    os.replace(tmp, os.path.join(dst, fname))

    mtmp = os.path.join(dst, f"{MANIFEST}.tmp.{os.getpid()}")
    with open(mtmp, "w") as f:
        json.dump(manifest, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(mtmp, os.path.join(dst, MANIFEST))

    if isinstance(source, ManifestReader):
        stats["source_exact_blocks"] = source.exact_blocks
        stats["source_assembled_regions"] = source.assembled_regions

    if verify:
        out = ManifestReader(dst)
        for path in source.leaf_paths():
            shape = tuple(source.leaf_meta(path)["shape"])
            a = np.asarray(source.read_region(
                path, [0] * len(shape), list(shape)))
            b = np.asarray(out.read_region(
                path, [0] * len(shape), list(shape)))
            # compare raw bytes: dtype-agnostic (bf16 etc.) and exact
            if not np.array_equal(
                np.ascontiguousarray(a).reshape(-1).view(np.uint8),
                np.ascontiguousarray(b).reshape(-1).view(np.uint8),
            ):
                raise RuntimeError(
                    f"repartition verify failed: {path!r} differs "
                    f"between {src} and {dst}"
                )
        stats["verified"] = True
    return stats
