"""Elastic topology: restore ANY checkpoint onto ANY mesh shape.

The reference repo's premise is preemption — suspend, lose the slice,
resume on whatever the scheduler hands back. That only works if a
checkpoint written on mesh (4,2) restores onto (2,2) or (8,1) with
optimizer state, RNG, data cursor and global step intact. This package
makes restore mesh-shape-agnostic (ROADMAP item 4):

- ``resolver`` — target shardings derived from the partition-rule tables
  the trainers own (live-state and manifest-path modes), validated by
  ``analysis/partition_coverage.py``;
- ``reader`` — ``load_elastic``: sharded dirs, legacy single files, and
  torn-checkpoint fallbacks, placed slice-wise per addressable shard
  from the manifest's block table (no full-global materialization);
- ``repartition`` — offline relayout for a target topology
  (``scripts/reshard.py``), no devices needed;
- ``serving`` — trainer checkpoints loaded at any serving TP degree,
  reading only the params blocks.

Proof: the cross-topology kill matrix in ``tests/test_reshard.py``
(SIGKILL on one mesh, resume on others, loss series vs an unpreempted
control) and ANALYSIS.md "Elastic topology & reshard".
"""

from pytorch_distributed_tpu.reshard.reader import (
    ReshardRefused,
    RestoreInfo,
    checkpoint_mesh,
    load_elastic,
    mesh_desc,
    mesh_shape_of,
)
from pytorch_distributed_tpu.reshard.repartition import (
    block_layout,
    repartition,
)
from pytorch_distributed_tpu.reshard.resolver import (
    assert_rules_cover,
    lm_rules,
    manifest_specs,
    payload_shardings,
    resolve_lm_state_specs,
    spec_for_path,
)
from pytorch_distributed_tpu.reshard.serving import (
    load_trainer_params,
    params_template,
    serving_param_shardings,
)

__all__ = [
    "ReshardRefused",
    "RestoreInfo",
    "assert_rules_cover",
    "block_layout",
    "checkpoint_mesh",
    "lm_rules",
    "load_elastic",
    "load_trainer_params",
    "manifest_specs",
    "mesh_desc",
    "mesh_shape_of",
    "params_template",
    "payload_shardings",
    "repartition",
    "resolve_lm_state_specs",
    "serving_param_shardings",
    "spec_for_path",
]
