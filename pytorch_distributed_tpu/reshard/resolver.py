"""Sharding resolution from the partition-rule tables — mesh-agnostic.

The whole point of elastic resume is that target shardings are derived
from the RULES the trainers already own, never from the layout the
checkpoint writer happened to use (SNIPPETS.md [1], the EasyLM/levanter
``match_partition_rules`` pattern). Two resolution modes live here:

- **live-state**: ``resolve_lm_state_specs`` produces the TrainState-shaped
  spec tree exactly the way ``train.lm.lm_state_specs`` (+ the FSDP
  overlay) does — one delegation point, so resolver and trainer placement
  cannot drift;
- **path-based**: ``spec_for_path``/``manifest_specs`` resolve a
  PartitionSpec from a manifest leaf path + shape alone — no live model,
  no devices, no mesh object. This is what lets ``scripts/reshard.py``
  repartition a checkpoint offline for a target topology that may not
  even be attachable from this host: a "mesh" is just an
  ``{axis: size}`` mapping.

Path-based resolution leans on two structural facts: (1) the TP/EP/vocab
rules match with ``re.search``, and every optimizer-state copy of a
parameter carries the full parameter path as a suffix
(``state/opt_state/0/mu/block0/attn/qkv/kernel``), so one rule claims the
parameter AND its moments; (2) the FSDP overlay is pure shape arithmetic
(largest data-axis-divisible dim of big-enough unclaimed leaves —
``parallel.fsdp.fsdp_dim``). ``analysis/partition_coverage.py`` proves at
lint time that every shardable parameter is claimed by a rule, which is
what makes rule-derived resolution complete; ``assert_rules_cover`` runs
that same check on demand.
"""

from __future__ import annotations

import re
from typing import Any, Mapping, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, PartitionSpec as P

from pytorch_distributed_tpu.parallel.mesh import DATA_AXIS

# Only these payload subtrees hold rule-governed (and FSDP-shardable)
# arrays; everything else — batch_stats, scaler, step, host scalars —
# is replicated by design, exactly as lm_state_specs/fsdp_state_specs
# leave them.
RULE_SCOPES = ("state/params/", "state/opt_state/")


def lm_rules(config=None) -> Tuple:
    """The LM trainers' full rule list for ``config``: the Megatron TP
    table plus the conditional MoE/vocab-parallel placements — the same
    composition ``lm_state_specs`` performs."""
    from pytorch_distributed_tpu.train import lm as lm_mod

    rules = lm_mod.TRANSFORMER_TP_RULES
    if config is not None and getattr(config, "n_experts", 0):
        rules = rules + lm_mod._moe_rules(config)
    if lm_mod._uses_vocab_parallel(config):
        rules = rules + lm_mod._vocab_rules(config)
    return rules


def resolve_lm_state_specs(state, mesh: Mesh, config=None,
                           fsdp: bool = False):
    """TrainState-shaped PartitionSpec tree for ``state`` on ``mesh`` —
    the one the LM trainer would use: TP/EP/vocab rules, optimizer state
    following its parameters, optional ZeRO overlay."""
    from pytorch_distributed_tpu.train.lm import (
        _overlay_fsdp_specs,
        lm_state_specs,
    )

    specs = lm_state_specs(state, config=config)
    if fsdp:
        specs = _overlay_fsdp_specs(specs, state, mesh, config)
    return specs


def payload_shardings(mesh: Mesh, template: Any, state_specs=None) -> Any:
    """Template-shaped shardings tree for a trainer checkpoint payload:
    the ``state`` subtree gets NamedShardings (from ``state_specs``, or
    fully replicated when None — the non-FSDP image trainer), every other
    entry (epoch/step/best_* host scalars) gets False so the loader
    returns plain numpy for them."""
    from pytorch_distributed_tpu.parallel import mesh as mesh_lib

    if state_specs is not None:
        state_sh = mesh_lib.specs_to_shardings(mesh, state_specs)
    else:
        state_sh = jax.tree.map(
            lambda _: mesh_lib.replicated_sharding(mesh), template["state"]
        )
    shardings = {k: jax.tree.map(lambda _: False, v)
                 for k, v in template.items() if k != "state"}
    shardings["state"] = state_sh
    return shardings


def _spec_effective(spec: P, mesh_shape: Mapping[str, int]) -> bool:
    """A matched rule only CLAIMS a path when some named axis has size > 1
    (on tp=1 meshes the Megatron specs are vacuous and leaves correctly
    fall through to the FSDP overlay) — mirrors ``train.lm._rule_claimed``
    for ``{axis: size}`` mappings."""
    from pytorch_distributed_tpu.ops.optim import spec_axes

    return any(int(mesh_shape.get(a, 1)) > 1 for a in spec_axes(spec))


def spec_for_path(
    path: str,
    shape: Sequence[int],
    rules: Sequence[Tuple[str, P]],
    mesh_shape: Mapping[str, int],
    fsdp: bool = False,
    data_axis: str = DATA_AXIS,
) -> P:
    """PartitionSpec for one manifest leaf, from its path + shape alone.

    Resolution order mirrors the live spec builders exactly: scalar or
    out-of-scope (non-params/opt) paths are replicated; the first rule
    whose regex matches the path wins when it effectively shards
    something on this mesh shape; otherwise the FSDP overlay (when
    enabled) shards the largest data-axis-divisible dimension of
    big-enough leaves; everything else replicates.
    """
    from pytorch_distributed_tpu.parallel.fsdp import fsdp_dim

    shape = tuple(int(d) for d in shape)
    if not shape or not any(path.startswith(s) for s in RULE_SCOPES):
        return P()
    for pattern, spec in rules:
        if re.search(pattern, path):
            if _spec_effective(spec, mesh_shape):
                if len(spec) > len(shape):
                    raise ValueError(
                        f"rule {pattern!r} spec {spec} has more dims than "
                        f"leaf {path!r} {shape} — rule/table drift"
                    )
                return spec
            break  # matched but vacuous on this mesh: overlay may claim it
    if fsdp:
        d = fsdp_dim(shape, int(mesh_shape.get(data_axis, 1)))
        if d is not None and int(mesh_shape.get(data_axis, 1)) > 1:
            return P(*(data_axis if i == d else None
                       for i in range(len(shape))))
    return P()


def manifest_specs(
    manifest: Mapping[str, Any],
    mesh_shape: Mapping[str, int],
    rules: Optional[Sequence[Tuple[str, P]]] = None,
    config=None,
    fsdp: bool = False,
) -> dict:
    """``{leaf_path: PartitionSpec}`` for every leaf of a sharded
    checkpoint manifest, resolved for a target ``{axis: size}`` mesh
    shape (no devices needed). ``rules=None`` uses the LM tables for
    ``config`` (``lm_rules``); pass ``rules=()`` for rule-free models
    (the image trainer: FSDP overlay or plain replication)."""
    if rules is None:
        rules = lm_rules(config)
    return {
        path: spec_for_path(path, meta["shape"], rules, mesh_shape,
                            fsdp=fsdp)
        for path, meta in manifest["leaves"].items()
    }


def assert_rules_cover() -> None:
    """Run ``analysis.partition_coverage`` and raise if any shardable
    parameter falls through the rule tables (or a rule is dead) — the
    lint-time proof that rule-derived target shardings are complete,
    callable at reshard time (``scripts/reshard.py --check``)."""
    from pytorch_distributed_tpu.analysis.partition_coverage import (
        check_partition_coverage,
    )

    findings = check_partition_coverage()
    if findings:
        raise RuntimeError(
            "partition-rule coverage failed — rule-derived reshard "
            "targets would be incomplete:\n" + "\n".join(
                f.message for f in findings
            )
        )
