"""Serving-side elastic load: trainer checkpoints at ANY TP degree.

Training and serving rarely agree on topology — a dp4×tp2 trainer
checkpoint typically feeds tp1 single-chip replicas, or a tp4 serving
mesh sized for latency. Parameter shapes are GLOBAL in every layout, so
the only real work is (1) pulling the ``state/params`` subtree out of a
trainer checkpoint (sharded dir or legacy single file — the payload also
carries opt state, RNG-free by design, and host scalars serving never
needs) and (2) resolving placements from the serving rule table
(``models.generate._tp_rules`` — the same Megatron layout the trainer
rules express, remapped to the config's axis name) instead of from the
writer's layout. The block-table reader then feeds each serving shard
exactly its slices; the optimizer moments (usually 2/3 of the
checkpoint's bytes) are never read at all.
"""

from __future__ import annotations

import os
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_tpu.reshard.reader import RestoreInfo, mesh_shape_of


def params_template(config) -> Any:
    """ShapeDtypeStruct params tree for ``config`` — ``jax.eval_shape``
    through the same dense init twin ``create_lm_state`` uses (global
    shapes are identical across parallel layouts), so no FLOPs and no
    device memory."""
    import dataclasses

    from pytorch_distributed_tpu.models.transformer import TransformerLM

    init_cfg = dataclasses.replace(
        config, attention="dense", model_axis=None, tp_size=1,
        expert_axis=None, ep_size=1, ring_layout="contiguous",
    )
    model = TransformerLM(init_cfg)
    return jax.eval_shape(
        lambda: model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))
    )["params"]


def serving_param_shardings(config, mesh, params_like) -> Any:
    """NamedSharding tree for serving params on ``mesh``, resolved from
    the serving TP rule table at the CONFIG's degree — never from the
    checkpoint writer's layout."""
    from jax.sharding import NamedSharding

    from pytorch_distributed_tpu.models.generate import _tp_rules
    from pytorch_distributed_tpu.parallel.tensor import match_partition_rules

    specs = match_partition_rules(_tp_rules(config), params_like)
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs)


def load_trainer_params(
    path: str | os.PathLike,
    config,
    mesh=None,
) -> Tuple[Any, RestoreInfo]:
    """Load the parameter tree of a trainer checkpoint for serving under
    ``config``. Returns ``(params, RestoreInfo)``.

    ``mesh=None`` (replicated / single-chip serving, or letting the
    engine place): host numpy leaves. With a mesh (TP serving), each
    leaf is placed slice-wise per the serving rules at ``config``'s TP
    degree — whatever degree the trainer ran at.
    """
    path = os.fspath(path)
    template = params_template(config)
    shardings = (
        serving_param_shardings(config, mesh, template)
        if mesh is not None else None
    )

    if os.path.isdir(path):
        from pytorch_distributed_tpu.reshard.reader import load_elastic

        tree, info = load_elastic(
            # the template names ONLY state/params/* leaves, so the
            # reader never touches the optimizer-moment blocks
            path,
            {"state": {"params": template}},
            None if shardings is None else {"state": {"params": shardings}},
            mesh=mesh,
        )
        params = tree["state"]["params"]
    else:
        from flax import serialization

        with open(path, "rb") as f:
            sd = serialization.msgpack_restore(f.read())
        try:
            sub = sd["state"]["params"]
        except (KeyError, TypeError):
            raise KeyError(
                f"{path} has no state/params subtree — not a trainer "
                "checkpoint payload"
            )
        params = serialization.from_state_dict(template, sub)
        if shardings is not None:
            from pytorch_distributed_tpu.reshard.reader import (
                _place_from_host,
            )

            params = _place_from_host(params, shardings)
        info = RestoreInfo(
            path=path, format="legacy",
            target_mesh=mesh_shape_of(mesh) if mesh is not None else None,
        )

    _check_shapes(params, template, path)
    return params, info


def _check_shapes(params, template, path) -> None:
    """Config/checkpoint drift (wrong vocab size, layer count edits)
    surfaces as a shape mismatch here with the leaf named — not as an
    XLA error three calls later."""
    for (p, got), (_, want) in zip(
        jax.tree_util.tree_leaves_with_path(params),
        jax.tree_util.tree_leaves_with_path(template),
    ):
        got_shape = tuple(np.shape(got))
        if got_shape != tuple(want.shape):
            raise ValueError(
                f"checkpoint {path} leaf {jax.tree_util.keystr(p)} has "
                f"shape {got_shape}, serving config expects "
                f"{tuple(want.shape)} — config/checkpoint mismatch"
            )
