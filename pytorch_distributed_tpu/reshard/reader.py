"""Elastic checkpoint reader: any format, any target mesh.

``load_elastic`` is the one restore entry point that accepts every
checkpoint this framework can produce — sharded multi-shard directories,
legacy single-file msgpack blobs, and whatever ``restorable_paths`` falls
back to after a torn save — and places it onto whatever mesh the caller
is running on NOW:

- sharded directories go through ``utils.checkpoint.ManifestReader``:
  each addressable shard of each target leaf is assembled from exactly
  the manifest blocks that overlap it and ``device_put`` slice-wise via
  ``make_array_from_callback`` — no process ever materializes a full
  global copy of a sharded leaf, whether or not the writer's block
  layout matches the target sharding;
- legacy single files have no block table (one msgpack blob), so the
  full host array is unavoidable — but placement is still slice-wise:
  each device receives a zero-copy VIEW of its shard, not a second copy;
- the writer's topology (recorded in the manifest since round 9) is
  compared against the target mesh, and a mismatch is surfaced as
  ``RestoreInfo.resharded`` — logged by the trainers, gateable via
  ``allow_reshard=False`` (``ReshardRefused``) for operators who want
  same-topology-only restores.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Mapping, Optional

import jax
import numpy as np

from pytorch_distributed_tpu.utils.checkpoint import (
    ManifestReader,
    load_checkpoint,
)


class ReshardRefused(RuntimeError):
    """The checkpoint was written on a different mesh shape and the
    caller disabled elastic restore (``allow_reshard=False``)."""


@dataclasses.dataclass
class RestoreInfo:
    """What one elastic restore actually did."""

    path: str
    format: str  # "sharded" | "legacy"
    source_mesh: Optional[dict] = None  # writer topology, if recorded
    target_mesh: Optional[dict] = None
    resharded: bool = False  # writer and target topologies differ
    exact_blocks: int = 0  # regions served by the no-copy fast path
    assembled_regions: int = 0  # regions stitched from partial overlaps
    bytes_assembled: int = 0

    def describe(self) -> str:
        src = mesh_desc(self.source_mesh) if self.source_mesh else "unknown"
        tgt = mesh_desc(self.target_mesh) if self.target_mesh else "host"
        return (
            f"{self.format} checkpoint [{src}] -> [{tgt}]"
            + (f", resharded ({self.exact_blocks} exact blocks, "
               f"{self.assembled_regions} assembled regions)"
               if self.resharded else "")
        )


def mesh_shape_of(mesh) -> dict:
    """``{"axes": [...], "shape": [...]}`` of a live Mesh — the same
    metadata the sharded save records in its manifest."""
    return {
        "axes": [str(a) for a in mesh.axis_names],
        "shape": [int(mesh.shape[a]) for a in mesh.axis_names],
    }


def mesh_desc(meta) -> str:
    """Human form: ``data=4 seq=1 model=2`` (accepts a Mesh or the
    manifest's ``{"axes", "shape"}`` mapping)."""
    if hasattr(meta, "axis_names"):
        meta = mesh_shape_of(meta)
    return " ".join(
        f"{a}={s}" for a, s in zip(meta["axes"], meta["shape"])
    )


def _meshes_differ(src: Optional[Mapping], tgt: Optional[Mapping]) -> bool:
    if src is None or tgt is None:
        return False  # unknown writer topology: never claim a reshard
    return dict(zip(src["axes"], src["shape"])) != dict(
        zip(tgt["axes"], tgt["shape"])
    )


def checkpoint_mesh(path: str | os.PathLike) -> Optional[dict]:
    """Writer topology of a sharded checkpoint directory, or None
    (legacy single file / pre-round-9 manifest)."""
    if not os.path.isdir(os.fspath(path)):
        return None
    return ManifestReader(path).mesh_meta


def _place_from_host(tree: Any, shardings: Any) -> Any:
    """Slice-wise placement of a host-numpy tree: each addressable shard
    gets a zero-copy view of its slice of the host array (the legacy
    single-file analog of the block-table path — the full array already
    exists on host, but no second full-size copy is made)."""

    def place(leaf, sh):
        if not isinstance(sh, jax.sharding.Sharding):
            return leaf
        arr = np.asarray(leaf)
        if arr.ndim == 0:
            return jax.device_put(arr, sh)
        return jax.make_array_from_callback(
            arr.shape, sh, lambda idx, arr=arr: arr[idx]
        )

    return jax.tree.map(place, tree, shardings)


def load_elastic(
    path: str | os.PathLike,
    template: Any,
    shardings: Any = None,
    *,
    mesh=None,
    allow_reshard: bool = True,
):
    """Restore ``path`` (sharded dir or legacy file) into ``template``'s
    structure, placed per ``shardings``. Returns ``(tree, RestoreInfo)``.

    ``mesh`` (the target mesh, for topology comparison/logging) is
    optional; without it ``resharded`` is inferred only when shardings
    carry a NamedSharding. ``allow_reshard=False`` raises
    :class:`ReshardRefused` when the writer topology is known and
    differs — the caller (``try_resume``) treats that like any other
    unusable candidate and falls through.
    """
    path = os.fspath(path)
    target = mesh_shape_of(mesh) if mesh is not None else _infer_target(
        shardings
    )
    if os.path.isdir(path):
        reader = ManifestReader(path)
        info = RestoreInfo(
            path=path, format="sharded",
            source_mesh=reader.mesh_meta, target_mesh=target,
            resharded=_meshes_differ(reader.mesh_meta, target),
        )
        if info.resharded and not allow_reshard:
            raise ReshardRefused(
                f"{path} was written on mesh "
                f"[{mesh_desc(info.source_mesh)}] but the run targets "
                f"[{mesh_desc(target)}] and elastic_resume is disabled"
            )
        from pytorch_distributed_tpu.utils.checkpoint import load_sharded

        tree = load_sharded(path, template, shardings, reader=reader)
        info.exact_blocks = reader.exact_blocks
        info.assembled_regions = reader.assembled_regions
        info.bytes_assembled = reader.bytes_assembled
        return tree, info

    # Legacy single-file msgpack: structure-only template restore, then
    # slice-wise placement. No block table -> no writer topology either;
    # the restore is mesh-agnostic by construction (full global host
    # arrays), so it can never be refused as a reshard.
    tree = load_checkpoint(path, template)
    if shardings is not None:
        tree = _place_from_host(tree, shardings)
    return tree, RestoreInfo(
        path=path, format="legacy", target_mesh=target
    )


def _infer_target(shardings) -> Optional[dict]:
    if shardings is None:
        return None
    for leaf in jax.tree.leaves(shardings):
        mesh = getattr(leaf, "mesh", None)
        if getattr(mesh, "axis_names", None):
            return mesh_shape_of(mesh)
    return None
