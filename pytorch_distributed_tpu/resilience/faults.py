"""Deterministic fault injection: one plan, named sites, exact replay.

Chaos tooling that fires randomly cannot be asserted on; this plane is
deterministic end to end so every failure a test provokes is reproducible
bit-for-bit. A :class:`FaultPlan` is a list of :class:`FaultSpec` entries
keyed by ``(site, occurrence)``: each named hazard site counts its own
calls, and a spec fires on occurrences ``[at, at + times)`` of its site.
No wall clock, no RNG — the nth call to a site fails the same way on every
run.

Hook placement is the contract: ``fault_point(site)`` sits at the real
hazard sites of the framework, so the kill-matrix exercises exactly the
states a production crash can leave behind:

====================  =====================================================
site                  placed at
====================  =====================================================
``data.fetch``        ``data/loader.py`` ``_fetch`` — a batch read
``ckpt.shard_write``  ``utils/checkpoint.py`` shard write, after the tmp
                      file is written but BEFORE the atomic publish — a
                      kill here is the classic torn shard
``ckpt.pre_commit``   immediately before rank 0's atomic manifest replace
                      (the commit point) — data files landed, manifest not
``ckpt.post_commit``  immediately after the manifest replace — the new
                      checkpoint is live, stale-shard GC has not run
``train.step``        the trainer loop, once per step before dispatch
``kv.swap_out_d2h``   ``serving/engine.py`` ``swap_out_finish``, before
                      the gathered chain's device→host materialization —
                      a failure here leaves the chain resident, intact
``kv.host_write``     same method, after d2h but BEFORE the host-store
                      commit — the classic half-swapped hazard; the
                      chain is still resident until the commit lands
``kv.swap_in_h2d``    ``serving/engine.py`` ``swap_in_chain``, before
                      any device write of a restoring chain — a failure
                      frees the fresh blocks, host copy stays retryable
``serve.dispatch``    ``serving/scheduler.py`` ``dispatch_tick``, before
                      any admission or launch work of the tick — the
                      replica fails with its resident set untouched;
                      the router's health plane must harvest and
                      re-dispatch every stranded request
``serve.collect``     ``serving/scheduler.py`` ``collect_tick``, before
                      the pending tick's device results are drained —
                      tokens the device already produced are lost with
                      the replica; replay must regenerate them
``serve.handoff_export``
                      ``serving/engine.py`` ``export_chain``, before the
                      prefill replica's chain is read out — the decode
                      side sees the failure mid-adopt, the export pin
                      stays on the source until the router disposes of it
``serve.handoff_import``
                      ``serving/engine.py`` ``import_chain``, before any
                      fresh block is allocated on the decode replica — a
                      failure leaves the source chain intact and
                      re-exportable (the PR 16 failure-safe contract)
====================  =====================================================

The ``serve.*`` sites model *replica death*, not transient I/O: an
exception escaping a serve tick marks the replica suspect/dead in the
fleet health plane (``fleet/router.py``) rather than being retried in
place, and recovery is re-dispatch of the stranded requests to
surviving replicas. The ``hang`` kind at a serve site stands in for a
wedged device loop: the tick returns late and the router's tick
deadline, not an exception, is what condemns the replica.

Fault kinds:

- ``raise``   — raise :class:`InjectedFault` (an ``OSError``, so the
  bounded retry in ``resilience.retry`` treats it as transient);
- ``kill``    — ``SIGKILL`` the current process: no atexit, no finally
  blocks, exactly what preemption or an OOM kill delivers;
- ``hang``    — sleep ``seconds`` (synthetic stall for the watchdog);
- ``nan``     — returned to the caller as a directive: the trainer poisons
  the step's batch with NaNs (``poison_batch``) to provoke NaN gradients;
- ``suspend`` — returned to the caller: the trainer latches its
  ``SuspendWatcher``, exercising checkpoint-then-yield in-process.

Configuration: ``install_plan(plan)`` in-process (tests), or the
``PDT_FAULT_PLAN`` env var — inline JSON or ``@/path/to/plan.json`` — for
subprocess children (the kill-matrix). JSON shape::

    {"faults": [{"site": "ckpt.shard_write", "kind": "kill", "at": 2},
                {"site": "data.fetch", "kind": "raise", "at": 1, "times": 2}]}

``fault_point`` is a no-op returning None when no plan is installed — one
attribute check, nothing measurable in the hot loop.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import signal
import threading
import time
from typing import Any, Dict, List, Optional

logger = logging.getLogger("pytorch_distributed_tpu")

ENV_PLAN = "PDT_FAULT_PLAN"

_KINDS = ("raise", "kill", "hang", "nan", "suspend")
# kinds fault_point executes itself vs. returns for the caller to interpret
_DIRECTIVES = ("nan", "suspend")


class InjectedFault(OSError):
    """A fault raised by the injection plane. Subclasses ``OSError`` so the
    bounded retry path treats it exactly like the transient I/O error it
    stands in for."""


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    site: str
    kind: str
    at: int = 0          # first occurrence (0-based call count of the site)
    times: int = 1       # fire on occurrences [at, at + times)
    seconds: float = 0.0  # hang duration

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; known: {_KINDS}"
            )
        if self.at < 0 or self.times < 1:
            raise ValueError(
                f"need at >= 0 and times >= 1, got at={self.at} "
                f"times={self.times}"
            )

    def matches(self, occurrence: int) -> bool:
        return self.at <= occurrence < self.at + self.times


class FaultPlan:
    """An immutable set of specs plus the per-site occurrence counters and
    a log of every fault fired (``plan.fired``: ``(site, occurrence,
    kind)`` tuples — tests assert on it)."""

    def __init__(self, specs: List[FaultSpec]):
        self.specs = list(specs)
        self._counts: Dict[str, int] = {}
        self._lock = threading.Lock()
        self.fired: List[tuple] = []

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        data = json.loads(text)
        return cls([FaultSpec(**spec) for spec in data.get("faults", [])])

    @classmethod
    def from_env(cls, env: str = ENV_PLAN) -> Optional["FaultPlan"]:
        value = os.environ.get(env, "").strip()
        if not value:
            return None
        if value.startswith("@"):
            with open(value[1:]) as f:
                value = f.read()
        return cls.from_json(value)

    def to_json(self) -> str:
        return json.dumps(
            {"faults": [dataclasses.asdict(s) for s in self.specs]}
        )

    def tick(self, site: str) -> Optional[FaultSpec]:
        """Count one occurrence of ``site``; return the matching spec, if
        any. Thread-safe: shard writes run on background threads."""
        with self._lock:
            n = self._counts.get(site, 0)
            self._counts[site] = n + 1
            for spec in self.specs:
                if spec.site == site and spec.matches(n):
                    self.fired.append((site, n, spec.kind))
                    return spec
        return None


# Installed plan: module-global, one per process. ``None`` + env-checked
# means injection is off and fault_point is a single attribute test.
_plan: Optional[FaultPlan] = None
_env_checked = False


def install_plan(plan: Optional[FaultPlan]) -> Optional[FaultPlan]:
    """Install (or clear, with None) the process-wide plan. Returns it."""
    global _plan, _env_checked
    _plan = plan
    _env_checked = True  # an explicit install overrides the env
    return plan


def clear_plan() -> None:
    global _plan, _env_checked
    _plan = None
    _env_checked = False


def active_plan() -> Optional[FaultPlan]:
    """The installed plan, lazily loading ``PDT_FAULT_PLAN`` once."""
    global _plan, _env_checked
    if not _env_checked:
        _env_checked = True
        _plan = FaultPlan.from_env()
        if _plan is not None:
            logger.warning(
                "fault injection active from $%s: %d spec(s)",
                ENV_PLAN, len(_plan.specs),
            )
    return _plan


def fault_point(site: str) -> Optional[FaultSpec]:
    """The injection hook. Executes ``raise``/``kill``/``hang`` faults
    itself; returns directive specs (``nan``, ``suspend``) for the caller
    to interpret; returns None when nothing fires."""
    plan = active_plan()
    if plan is None:
        return None
    spec = plan.tick(site)
    if spec is None:
        return None
    if spec.kind == "raise":
        raise InjectedFault(f"injected fault at {site} (at={spec.at})")
    if spec.kind == "kill":
        logger.warning("injected SIGKILL at %s", site)
        # flush logs; SIGKILL runs no atexit/finally — that is the point
        logging.shutdown()
        os.kill(os.getpid(), signal.SIGKILL)
    if spec.kind == "hang":
        logger.warning("injected %.1fs hang at %s", spec.seconds, site)
        time.sleep(spec.seconds)
        return None
    if spec.kind in _DIRECTIVES:
        logger.warning("injected %s directive at %s", spec.kind, site)
        return spec
    return None


def poison_batch(batch: Any) -> Any:
    """NaN-fill every inexact-dtype array of a host batch — the ``nan``
    directive's payload. Poisoning the *input* (not the state) provokes
    NaN loss and NaN gradients through the real compiled step, which is
    what the stepguard must catch; integer arrays (tokens, labels) pass
    through untouched."""
    import numpy as np

    poisoned = False

    def leaf(x):
        nonlocal poisoned
        arr = np.asarray(x)
        if np.issubdtype(arr.dtype, np.floating):
            poisoned = True
            return np.full_like(arr, np.nan)
        return x

    import jax

    out = jax.tree.map(leaf, batch)
    if not poisoned:
        raise ValueError(
            "poison_batch found no float leaf to NaN-fill; the nan fault "
            "needs a float field in the batch (e.g. LM loss weights or "
            "float images)"
        )
    return out
