"""Resilience runtime: deterministic fault injection and the guards that
turn "crash-safe on paper" into recovery demonstrated under ``kill -9``.

The reference repo's one robustness capability is the hfai
suspend/checkpoint/yield protocol (``restnet_ddp.py:36-47``), reproduced in
``utils/suspend.py`` + ``utils/checkpoint.py`` — but nothing there ever
*exercises* a failure. This package adds the missing half of fault
tolerance:

- ``faults``    — a deterministic fault plan (env/JSON-configurable, keyed
  by named site x occurrence) with injection hooks placed at the real
  hazard sites: data fetch, checkpoint shard write, pre/post manifest
  commit, and the train step (NaN batch, synthetic hang, suspend, SIGKILL).
- ``stepguard`` — jit-compatible finite-check on loss / gradients that
  skips the optimizer update on a bad step (``lax.cond``, no host sync in
  the compiled step), plus the host-side policy that counts consecutive
  bad steps and requests rollback-to-last-good-checkpoint after K.
- ``watchdog``  — a per-step deadline watchdog thread that dumps all-thread
  stacks on stall and can checkpoint-and-exit via the existing
  ``SuspendWatcher`` path.
- ``retry``     — bounded exponential-backoff retry (deterministic seeded
  jitter) for data reads and checkpoint I/O.

The proof lives in ``tests/test_resilience.py``: injected NaNs are skipped
and rolled back, and a subprocess kill-matrix SIGKILLs a training run at
each checkpoint hazard site and asserts the relaunch resumes from a
complete checkpoint. See ANALYSIS.md "Failure model & recovery guarantees".
"""

from pytorch_distributed_tpu.resilience.faults import (
    FaultPlan,
    FaultSpec,
    InjectedFault,
    clear_plan,
    fault_point,
    install_plan,
    poison_batch,
)
from pytorch_distributed_tpu.resilience.retry import retry_call, retrying
from pytorch_distributed_tpu.resilience.stepguard import (
    RollbackRequested,
    StepGuard,
    finite_ok,
    guard_state,
)
from pytorch_distributed_tpu.resilience.watchdog import Watchdog

__all__ = [
    "FaultPlan",
    "FaultSpec",
    "InjectedFault",
    "clear_plan",
    "fault_point",
    "install_plan",
    "poison_batch",
    "retry_call",
    "retrying",
    "RollbackRequested",
    "StepGuard",
    "finite_ok",
    "guard_state",
    "Watchdog",
]
