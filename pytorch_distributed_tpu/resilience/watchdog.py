"""Per-step deadline watchdog: stacks on stall, then the suspend path.

A hung collective (one host dropped out of a psum), a deadlocked data
loader, or an NFS mount that stopped answering all present the same way:
the step loop simply stops, forever, with zero diagnostics — the failure
mode the multihost triage in ANALYSIS.md calls the worst to debug. The
watchdog converts that silence into evidence and (optionally) a clean
yield:

- the trainer calls ``beat()`` once per step; a dedicated daemon thread
  checks the deadline;
- on stall it dumps **every thread's stack** (``sys._current_frames``) to
  the log and an optional file — the post-mortem shows exactly which
  frame is stuck (a ``q.get``, a collective, a ``pread``);
- optionally latches the existing :class:`SuspendWatcher`, so a *soft*
  stall (data loader wedged, filesystem slow) flows into the proven
  checkpoint-then-yield path at the next step; a *hard* stall (the device
  program itself is hung) can't reach that poll again, so ``exit_code``
  forces ``os._exit`` after a grace period and the scheduler relaunches
  into crash recovery — which the kill-matrix proves restores correctly.

One stall fires one dump (re-armed by the next beat), so a long stall
doesn't spray logs.
"""

from __future__ import annotations

import logging
import os
import sys
import threading
import time
import traceback
from typing import Callable, Optional

logger = logging.getLogger("pytorch_distributed_tpu")


def dump_all_stacks() -> str:
    """Format every live thread's current stack (the stall post-mortem)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    parts = []
    for ident, frame in sys._current_frames().items():
        parts.append(
            f"--- thread {names.get(ident, '?')} ({ident}) ---\n"
            + "".join(traceback.format_stack(frame))
        )
    return "\n".join(parts)


class Watchdog:
    """Deadline watchdog over a heartbeat.

    ``timeout_s``   stall threshold between ``beat()`` calls.
    ``watcher``     optional ``SuspendWatcher``: on stall,
                    ``request_suspend()`` is latched so a recovered loop
                    checkpoints and yields at its next poll.
    ``dump_path``   also write the stack dump to this file (atomic-ish
                    append; the kill-matrix parent reads it).
    ``on_stall``    optional callback (tests; checkpoint-and-exit hooks).
    ``exit_code``   if not None, ``os._exit(exit_code)`` ``grace_s`` after
                    a stall that no beat cleared — the hard-hang escape
                    hatch; the scheduler's relaunch resumes from the last
                    complete checkpoint.
    ``ledger``      optional ``telemetry.GoodputLedger``: when a beat
                    clears a fired stall, the whole beat-to-beat gap is
                    classified as ``stall`` time (the step made no
                    progress while the watchdog was screaming).
    ``flightrec``   optional ``telemetry.FlightRecorder``: a stall
                    records one ring event and — with
                    ``flightrec_path`` set — atomically dumps the ring
                    next to the stack dump, so the post-mortem has the
                    run's recent HISTORY, not just its frozen stacks.
    """

    def __init__(
        self,
        timeout_s: float,
        *,
        watcher=None,
        dump_path: Optional[str] = None,
        on_stall: Optional[Callable[[str], None]] = None,
        exit_code: Optional[int] = None,
        grace_s: float = 10.0,
        poll_s: Optional[float] = None,
        ledger=None,
        flightrec=None,
        flightrec_path: Optional[str] = None,
    ):
        if timeout_s <= 0:
            raise ValueError(f"timeout_s must be > 0, got {timeout_s}")
        self.timeout_s = float(timeout_s)
        self.watcher = watcher
        self.dump_path = dump_path
        self.on_stall = on_stall
        self.exit_code = exit_code
        self.grace_s = float(grace_s)
        self.ledger = ledger
        self.flightrec = flightrec
        self.flightrec_path = flightrec_path
        self.poll_s = float(poll_s) if poll_s else min(
            1.0, self.timeout_s / 4.0
        )
        self.stalls = 0
        self._last = time.monotonic()
        self._armed = False  # becomes True at the first beat
        self._fired = False  # one dump per stall
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "Watchdog":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="pdt-watchdog", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self) -> "Watchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- the heartbeat -----------------------------------------------------

    def beat(self) -> None:
        """One step completed; re-arm the deadline. Cheap: one clock read
        and two attribute stores (plus a goodput attribution when this
        beat clears a fired stall)."""
        now = time.monotonic()
        if self._fired and self.ledger is not None:
            try:
                self.ledger.add("stall", max(now - self._last, 0.0))
            except Exception:
                logger.exception("watchdog: goodput ledger rejected stall")
        self._last = now
        self._armed = True
        self._fired = False

    # -- the watcher thread ------------------------------------------------

    def _run(self) -> None:
        stall_at: Optional[float] = None
        while not self._stop.wait(self.poll_s):
            if not self._armed:
                continue
            stalled = time.monotonic() - self._last
            if stalled < self.timeout_s:
                stall_at = None
                continue
            if not self._fired:
                self._fired = True  # jaxlint: disable=thread-unsynced-mutation -- deliberate lock-free monotonic flag: single GIL-atomic bool store; beat() clearing it concurrently at worst re-arms one extra dump
                self.stalls += 1
                stall_at = time.monotonic()
                self._handle_stall(stalled)
            elif (
                self.exit_code is not None
                and stall_at is not None
                and time.monotonic() - stall_at >= self.grace_s
            ):
                logger.error(
                    "watchdog: stall persisted %.1fs past the dump; "
                    "os._exit(%d) for scheduler relaunch",
                    self.grace_s, self.exit_code,
                )
                logging.shutdown()
                os._exit(self.exit_code)

    def _handle_stall(self, stalled_s: float) -> None:
        dump = dump_all_stacks()
        logger.error(
            "watchdog: no step heartbeat for %.1fs (deadline %.1fs); "
            "all-thread stacks:\n%s",
            stalled_s, self.timeout_s, dump,
        )
        if self.dump_path:
            try:
                with open(self.dump_path, "a") as f:
                    f.write(
                        f"=== watchdog stall #{self.stalls} "
                        f"({stalled_s:.1f}s) ===\n{dump}\n"
                    )
            except OSError as e:
                logger.error("watchdog: could not write dump: %s", e)
        if self.flightrec is not None:
            self.flightrec.record(
                "watchdog_stall", n=self.stalls,
                stalled_s=round(stalled_s, 3),
            )
            if self.flightrec_path:
                self.flightrec.dump(self.flightrec_path, "watchdog_stall")
        if self.watcher is not None:
            # soft-stall path: the next step's suspend poll checkpoints
            # and yields through the existing, tested machinery
            self.watcher.request_suspend()
        if self.on_stall is not None:
            try:
                self.on_stall(dump)
            except Exception:
                logger.exception("watchdog: on_stall callback failed")


class FleetWatchdog:
    """Many named heartbeats, one watcher thread — the serve-side
    generalization of :class:`Watchdog` for the fleet health plane.

    The trainer watchdog guards ONE loop; a serving fleet has one
    heartbeat per replica (``replica0`` … ``replicaN``) plus the router
    loop itself, and a single wedged replica must be *named*, not just
    noticed. ``watch(name)`` registers a heartbeat, ``beat(name)``
    re-arms it, and a heartbeat that goes quiet past ``timeout_s``
    fires ``on_stall(name, stalled_s, dump)`` ONCE (re-armed by the
    next beat of that name) with the all-thread stack dump — the
    router's callback marks the replica suspect/dead and the
    re-dispatch machinery takes it from there. ``unwatch(name)``
    retires a heartbeat (a dead replica must stop screaming).

    Deterministic tests drive :meth:`check` directly instead of
    starting the thread: it evaluates every armed heartbeat against
    the deadline NOW and returns the names that fired — same logic,
    no wall-clock race. The production path calls ``start()`` and the
    daemon thread polls exactly like the trainer watchdog."""

    def __init__(
        self,
        timeout_s: float,
        *,
        on_stall: Optional[Callable[[str, float, str], None]] = None,
        dump_path: Optional[str] = None,
        poll_s: Optional[float] = None,
        flightrec=None,
    ):
        if timeout_s <= 0:
            raise ValueError(f"timeout_s must be > 0, got {timeout_s}")
        self.timeout_s = float(timeout_s)
        self.on_stall = on_stall
        self.dump_path = dump_path
        self.flightrec = flightrec
        self.poll_s = float(poll_s) if poll_s else min(
            1.0, self.timeout_s / 4.0
        )
        self.stalls = 0
        self._lock = threading.Lock()
        # name -> (last beat monotonic, fired)
        self._beats: dict = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "FleetWatchdog":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="pdt-fleet-watchdog", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self) -> "FleetWatchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- heartbeats --------------------------------------------------------

    def watch(self, name: str) -> None:
        """Register (or re-register) heartbeat ``name``, armed now."""
        with self._lock:
            self._beats[name] = (time.monotonic(), False)

    def unwatch(self, name: str) -> None:
        """Retire heartbeat ``name`` (replica dead or drained)."""
        with self._lock:
            self._beats.pop(name, None)

    def beat(self, name: str) -> None:
        """Heartbeat ``name`` made progress; re-arm its deadline."""
        with self._lock:
            self._beats[name] = (time.monotonic(), False)

    def stalled(self) -> list:
        """Names currently past deadline (fired or not) — a health
        surface, no side effects."""
        now = time.monotonic()
        with self._lock:
            return sorted(
                n for n, (last, _f) in self._beats.items()
                if now - last >= self.timeout_s
            )

    # -- evaluation --------------------------------------------------------

    def check(self) -> list:
        """Evaluate every heartbeat against the deadline now; fire
        ``on_stall`` for each newly-stalled name and return those
        names. The watcher thread calls this each poll; deterministic
        tests call it directly."""
        now = time.monotonic()
        fired = []
        with self._lock:
            for name, (last, already) in list(self._beats.items()):
                if now - last >= self.timeout_s and not already:
                    self._beats[name] = (last, True)
                    fired.append((name, now - last))
        for name, stalled_s in fired:
            self.stalls += 1
            self._handle_stall(name, stalled_s)
        return [name for name, _ in fired]

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            self.check()

    def _handle_stall(self, name: str, stalled_s: float) -> None:
        dump = dump_all_stacks()
        logger.error(
            "fleet watchdog: no %s heartbeat for %.1fs (deadline "
            "%.1fs); all-thread stacks:\n%s",
            name, stalled_s, self.timeout_s, dump,
        )
        if self.dump_path:
            try:
                with open(self.dump_path, "a") as f:
                    f.write(
                        f"=== fleet watchdog stall #{self.stalls} "
                        f"[{name}] ({stalled_s:.1f}s) ===\n{dump}\n"
                    )
            except OSError as e:
                logger.error("fleet watchdog: could not write dump: %s",
                             e)
        if self.flightrec is not None:
            self.flightrec.record(
                "watchdog_stall", n=self.stalls, heartbeat=name,
                stalled_s=round(stalled_s, 3),
            )
        if self.on_stall is not None:
            try:
                self.on_stall(name, stalled_s, dump)
            except Exception:
                logger.exception(
                    "fleet watchdog: on_stall callback failed"
                )
