"""Step guard: skip non-finite updates in-jit, roll back after K in a row.

A single NaN step — a corrupt record, an overflowed bf16 activation, a
cosmic-ray flip — must not kill a production run, and it must not poison
the parameters either. Two layers, split by where they can afford to run:

**In the compiled step** (:func:`finite_ok` + :func:`guard_state`): the
step builders (``train/step.py``, ``train/lm.py``) compute a replicated
``good`` flag from the globally-reduced loss and the combined gradients
and select old-vs-new state with ``lax.cond`` — params, optimizer state
and BN stats keep their pre-step values on a bad step, while ``step``
still advances (mirroring torch GradScaler's skip semantics,
``resnet_ddp_apex.py:30-33``). Everything stays on device: no ``float()``,
no ``.item()``, no host round trip in the hot path — the flag is returned
as one more replicated metric (``step_good``).

**On the host** (:class:`StepGuard`): a lag-1 policy loop. The trainer
hands each step's ``step_good`` device scalar to ``observe``; the guard
reads the value from the *previous* step — already materialized, so the
read never stalls dispatch of the current one — counts consecutive bad
steps, and raises :class:`RollbackRequested` once ``max_bad_steps`` hit in
a row. The trainers catch it, restore the newest restorable checkpoint,
and re-enter the epoch loop. Skip handles a transient; rollback handles
the case where skipping isn't enough (the state itself, or the data
stream, has gone bad).

Multi-host safety: ``step_good`` is derived from psum'd loss and the
post-combine gradients (with an explicit ``pmin`` over every mesh axis
where shards can disagree), so every process observes the identical flag
sequence and raises RollbackRequested at the same step — no rank ever
rolls back alone into a mismatched-collective hang.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def finite_ok(loss, grads=None) -> jax.Array:
    """Scalar bool: the step's loss (and, if given, every float gradient
    leaf) is finite. Pure jnp — safe inside the compiled step."""
    good = jnp.isfinite(jnp.asarray(loss)).all()
    if grads is not None:
        from pytorch_distributed_tpu.ops.precision import all_finite

        good = jnp.logical_and(good, all_finite(grads))
    return good


def guard_state(good, new_state, old_state, keep=("step",)):
    """Select the whole post-update state on a good step, the pre-update
    state on a bad one — via ``lax.cond`` so the selection is a single
    branch in the compiled program. Fields named in ``keep`` always come
    from ``new_state``: ``step`` advances on skipped steps (a skip is a
    consumed batch, same as torch GradScaler), and callers running a
    dynamic loss scaler pass ``("step", "scaler")`` so backoff still
    happens on the skipped step."""
    selected = jax.lax.cond(
        good,
        lambda pair: pair[0],
        lambda pair: pair[1],
        (new_state, old_state),
    )
    kept = {k: getattr(new_state, k) for k in keep if hasattr(new_state, k)}
    return selected.replace(**kept) if kept else selected


class RollbackRequested(RuntimeError):
    """Raised by :class:`StepGuard` when ``max_bad_steps`` consecutive
    steps were skipped — the trainer restores the last good checkpoint."""

    def __init__(self, bad_steps: int):
        super().__init__(
            f"{bad_steps} consecutive non-finite train steps; rolling back "
            "to the last good checkpoint"
        )
        self.bad_steps = bad_steps


class StepGuard:
    """Host-side skip accounting and the rollback trigger.

    ``observe(step_good)`` enqueues the device scalar and reads the one
    ``lag`` steps old (materialized by then — reading it does not stall
    the pipeline). ``flush()`` drains the queue at epoch end. Counters:
    ``bad_total`` (skipped steps this run), ``bad_consecutive`` (current
    streak), ``rollbacks`` (times RollbackRequested fired).
    """

    def __init__(self, max_bad_steps: int = 0, lag: int = 1):
        if lag < 0:
            raise ValueError(f"lag must be >= 0, got {lag}")
        self.max_bad_steps = int(max_bad_steps)
        self.lag = int(lag)
        self._pending: list = []
        self.bad_total = 0
        self.bad_consecutive = 0
        self.rollbacks = 0

    def _ingest(self, value) -> None:
        if float(jax.device_get(value)) > 0.0:
            self.bad_consecutive = 0
            return
        self.bad_total += 1
        self.bad_consecutive += 1
        if self.max_bad_steps and self.bad_consecutive >= self.max_bad_steps:
            self.rollbacks += 1
            bad, self.bad_consecutive = self.bad_consecutive, 0
            self._pending.clear()  # stale flags die with the rolled-back run
            raise RollbackRequested(bad)

    def observe(self, step_good: Optional[jax.Array]) -> None:
        """Feed one step's replicated ``step_good`` metric. Raises
        :class:`RollbackRequested` when the streak limit is hit."""
        if step_good is None:
            return
        self._pending.append(step_good)
        while len(self._pending) > self.lag:
            self._ingest(self._pending.pop(0))

    def flush(self) -> None:
        """Drain the lag window (epoch end / before validation)."""
        while self._pending:
            self._ingest(self._pending.pop(0))

    def reset(self) -> None:
        """Forget the streak (after a rollback restored good state)."""
        self._pending.clear()
        self.bad_consecutive = 0
