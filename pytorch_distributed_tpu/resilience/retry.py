"""Bounded exponential-backoff retry with deterministic seeded jitter.

Cluster filesystems fail transiently — an NFS pread mid-failover, a
checkpoint fsync against a briefly-full volume — and the difference
between a lost job and a log line is a bounded retry. Two properties this
module insists on:

- **bounded**: ``retries`` attempts and a ``max_delay`` cap. Unbounded
  retry converts a hard failure into a silent hang, which is strictly
  worse (the watchdog would fire on it);
- **deterministic jitter**: backoff delays derive from
  ``random.Random((seed, attempt))``, never the global RNG or wall clock —
  two runs of the same plan retry on the same schedule, so fault-injection
  tests can assert the exact sleep sequence.

Used by the data read path (``data/packed_record.py``,
``data/raw.py``) and checkpoint I/O (``utils/checkpoint.py``). Injected
faults of kind ``raise`` are ``InjectedFault(OSError)``, so they exercise
exactly this machinery.
"""

from __future__ import annotations

import functools
import logging
import random
import time
from typing import Callable, Tuple, Type

logger = logging.getLogger("pytorch_distributed_tpu")

DEFAULT_RETRIES = 3
DEFAULT_BASE_DELAY = 0.05
DEFAULT_MAX_DELAY = 2.0


def backoff_delays(
    retries: int = DEFAULT_RETRIES,
    base_delay: float = DEFAULT_BASE_DELAY,
    max_delay: float = DEFAULT_MAX_DELAY,
    seed: int = 0,
) -> list:
    """The deterministic delay schedule: ``min(max, base * 2**k)`` scaled
    by a seeded jitter in [0.5, 1.0) — jitter desynchronizes a pod's
    retry herd; seeding keeps each process's schedule reproducible."""
    out = []
    for attempt in range(retries):
        cap = min(max_delay, base_delay * (2.0 ** attempt))
        jitter = 0.5 + random.Random(f"{seed}:{attempt}").random() / 2.0
        out.append(cap * jitter)
    return out


def retry_call(
    fn: Callable,
    *args,
    retries: int = DEFAULT_RETRIES,
    base_delay: float = DEFAULT_BASE_DELAY,
    max_delay: float = DEFAULT_MAX_DELAY,
    retry_on: Tuple[Type[BaseException], ...] = (OSError,),
    no_retry_on: Tuple[Type[BaseException], ...] = (),
    seed: int = 0,
    what: str = "",
    **kwargs,
):
    """Call ``fn(*args, **kwargs)``; on ``retry_on`` retry up to
    ``retries`` extra times with the :func:`backoff_delays` schedule. The
    last failure propagates unchanged (bounded — never a hang).
    ``no_retry_on`` carves permanent-failure subclasses out of a broad
    ``retry_on`` (e.g. a structural SizeMismatch under OSError)."""
    delays = backoff_delays(retries, base_delay, max_delay, seed)
    for attempt in range(retries + 1):
        try:
            return fn(*args, **kwargs)
        except retry_on as e:
            if no_retry_on and isinstance(e, no_retry_on):
                raise
            if attempt >= retries:
                raise
            delay = delays[attempt]
            logger.warning(
                "%s failed (%s: %s); retry %d/%d in %.3fs",
                what or getattr(fn, "__name__", "call"),
                type(e).__name__, e, attempt + 1, retries, delay,
            )
            time.sleep(delay)


def retrying(
    retries: int = DEFAULT_RETRIES,
    base_delay: float = DEFAULT_BASE_DELAY,
    max_delay: float = DEFAULT_MAX_DELAY,
    retry_on: Tuple[Type[BaseException], ...] = (OSError,),
    seed: int = 0,
):
    """Decorator form of :func:`retry_call` for whole functions."""

    def wrap(fn):
        @functools.wraps(fn)
        def inner(*args, **kwargs):
            return retry_call(
                fn, *args,
                retries=retries, base_delay=base_delay,
                max_delay=max_delay, retry_on=retry_on, seed=seed,
                what=fn.__qualname__, **kwargs,
            )

        return inner

    return wrap
