"""Multi-host rendezvous — TPU-native process-group bootstrap.

Replaces ``dist.init_process_group(backend='nccl',
init_method=f'tcp://{ip}:{port}', world_size=hosts*gpus,
rank=rank*gpus+local_rank)`` (``restnet_ddp.py:87-94``) with
``jax.distributed.initialize``: the JAX coordination service plays the role
of the TCPStore rendezvous, and there is no backend string — collectives are
chosen by XLA from the mesh (ICI within a pod, DCN across pods).

Env-var contract (kept compatible with the reference, ``restnet_ddp.py:87-90``,
including its quirk that WORLD_SIZE counts *nodes* and RANK is the *node
index* — on TPU one process per host is the native model, so node == process
and the reference's ``rank*gpus+local_rank`` arithmetic disappears, D11):

    MASTER_IP / MASTER_PORT   coordinator address   (ref restnet_ddp.py:87-88)
    WORLD_SIZE                number of hosts       (ref restnet_ddp.py:89)
    RANK                      this host's index     (ref restnet_ddp.py:90)

On TPU pods all three are auto-discoverable; ``init_process_group()`` with
no env set degrades to single-process, so every recipe runs unchanged from a
laptop CPU to a multi-pod slice.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

import jax

logger = logging.getLogger("pytorch_distributed_tpu")

_initialized = False


def init_process_group(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join the job's coordination service (idempotent).

    Arguments default from the reference's env contract (module docstring);
    with nothing set and nothing auto-detectable this is a no-op and the
    process runs single-host (≙ ``resnet_single_gpu.py`` / ``resnet_dp.py``,
    which never call ``init_process_group``).
    """
    global _initialized
    if _initialized:
        return

    ip = os.environ.get("MASTER_IP")
    port = os.environ.get("MASTER_PORT")
    if coordinator_address is None and ip and port:
        coordinator_address = f"{ip}:{port}"
    if num_processes is None and os.environ.get("WORLD_SIZE"):
        num_processes = int(os.environ["WORLD_SIZE"])
    if process_id is None and os.environ.get("RANK"):
        process_id = int(os.environ["RANK"])

    if coordinator_address is None and num_processes is None:
        # Single-host path, or a TPU pod where JAX auto-discovers topology
        # from the metadata server. Only call initialize on a genuinely
        # multi-worker runtime (single-worker setups — including tunneled
        # dev chips that advertise TPU_WORKER_HOSTNAMES=localhost — stay
        # single-process).
        workers = [
            h
            for h in os.environ.get("TPU_WORKER_HOSTNAMES", "").split(",")
            if h.strip()
        ]
        if len(workers) > 1 or os.environ.get("MEGASCALE_COORDINATOR_ADDRESS"):
            # Fail loudly: silently degrading a multi-worker job to N
            # independent single-process trainers would have every host
            # believe it is primary and clobber shared checkpoints.
            jax.distributed.initialize()
            _initialized = True
            logger.info(
                "auto-initialized: process %d/%d", jax.process_index(), jax.process_count()
            )
        return

    if num_processes is not None and num_processes <= 1:
        return

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    logger.info(
        "rendezvous complete at %s: process %d/%d, %d local / %d global devices",
        coordinator_address,
        jax.process_index(),
        jax.process_count(),
        jax.local_device_count(),
        jax.device_count(),
    )


def get_rank() -> int:
    """This host's process index (ref ``dist.get_rank()``, but per-host: one
    process drives all local chips, so there is no local_rank)."""
    return jax.process_index()


def get_world_size() -> int:
    """Number of processes (ref ``dist.get_world_size()`` counted GPUs; here
    hosts — chip count is ``jax.device_count()``)."""
    return jax.process_count()


def is_primary() -> bool:
    """Rank-0 gate for printing/checkpointing (ref ``rank == 0 and
    local_rank == 0``, ``restnet_ddp.py:36,66,145``)."""
    return jax.process_index() == 0


def barrier(name: str = "barrier") -> None:
    """Block until every process reaches this point (the reference has no
    explicit barrier; NCCL collectives gave it implicit sync)."""
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)
