"""Tensor parallelism primitives: the f/g collective pair and partition rules.

Megatron-style TP inside ``shard_map``: weights of "column-parallel" layers
are split on their output dimension (each device computes a slice of the
features), "row-parallel" layers on their input dimension (each device
computes a partial sum that one ``psum`` completes). Two custom-vjp
identities make autodiff correct by construction, independent of shard_map's
replication checking:

- ``tp_copy`` ("f"): forward identity on a replicated activation entering a
  column-parallel layer; backward psums the partial cotangents over the
  model axis, so everything upstream (embeddings, layernorms) receives full
  gradients and replicated params need no extra grad collective.
- ``tp_reduce`` ("g"): forward psum completing a row-parallel layer;
  backward identity (the cotangent is already replicated — a plain psum's
  transpose would multiply it by the axis size).

Row-parallel layers must not add a bias before ``tp_reduce`` (it would be
summed tp times); the transformer keeps those projections bias-free.
"""

from __future__ import annotations

import re
from typing import Any, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from pytorch_distributed_tpu.parallel.mesh import MODEL_AXIS


from functools import partial


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def _tp_copy(x, axis_name: str):
    return x


def _tp_copy_fwd(x, axis_name):
    return x, None


def _tp_copy_bwd(axis_name, _res, g):
    return (jax.lax.psum(g, axis_name),)


_tp_copy.defvjp(_tp_copy_fwd, _tp_copy_bwd)


def tp_copy(x, axis_name: str = MODEL_AXIS):
    """Identity forward, psum backward (enter a column-parallel region)."""
    return _tp_copy(x, axis_name)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def _tp_reduce(x, axis_name: str):
    return jax.lax.psum(x, axis_name)


def _tp_reduce_fwd(x, axis_name):
    return jax.lax.psum(x, axis_name), None


def _tp_reduce_bwd(axis_name, _res, g):
    return (g,)


_tp_reduce.defvjp(_tp_reduce_fwd, _tp_reduce_bwd)


def tp_reduce(x, axis_name: str = MODEL_AXIS):
    """Psum forward, identity backward (exit a row-parallel region)."""
    return _tp_reduce(x, axis_name)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _tp_all_gather(x, axis_name: str, dim: int):
    return jax.lax.all_gather(x, axis_name, axis=dim, tiled=True)


def _tp_all_gather_fwd(x, axis_name, dim):
    return _tp_all_gather(x, axis_name, dim), x.shape[dim]


def _tp_all_gather_bwd(axis_name, dim, local, g):
    r = jax.lax.axis_index(axis_name)
    return (jax.lax.dynamic_slice_in_dim(g, r * local, local, axis=dim),)


_tp_all_gather.defvjp(_tp_all_gather_fwd, _tp_all_gather_bwd)


def tp_all_gather(x, axis_name: str = MODEL_AXIS, dim: int = -1):
    """All-gather forward, slice backward — for REPLICATED downstream
    consumers (e.g. the vocab-parallel logits feeding a loss every model
    shard computes identically). The raw ``lax.all_gather`` transposes to
    psum_scatter, which SUMS the tp identical replicated cotangents and
    hands each shard tp× its true gradient; the slice backward takes
    exactly this shard's piece of the (replicated) cotangent instead —
    the same f/g bookkeeping as ``tp_copy``/``tp_reduce``."""
    if dim < 0:
        dim += x.ndim
    return _tp_all_gather(x, axis_name, dim)


# ---- partition rules (the standard path-regex → PartitionSpec mapping) ----


def path_str(path) -> str:
    """'block0/attn/qkv/kernel'-style string for a jax tree path."""
    parts = []
    for p in path:
        name = getattr(p, "key", None)
        if name is None:
            name = getattr(p, "name", None)
        if name is None:
            name = str(getattr(p, "idx", p))
        parts.append(str(name))
    return "/".join(parts)


def match_partition_rules(
    rules: Sequence[Tuple[str, P]], tree: Any, default: P = P()
) -> Any:
    """PartitionSpec pytree for ``tree``: first regex (re.search) that matches
    each leaf's path wins; scalars and unmatched leaves get ``default``."""

    def assign(path, leaf):
        name = path_str(path)
        shape = getattr(leaf, "shape", ())
        if len(shape) == 0:
            return P()
        for pattern, spec in rules:
            if re.search(pattern, name):
                return spec
        return default

    return jax.tree_util.tree_map_with_path(assign, tree)


def opt_state_specs(params: Any, param_specs: Any, tx) -> Any:
    """PartitionSpec tree for ``tx.init(params)``'s state.

    Optimizer state (momentum traces, second moments, …) embeds copies of
    the parameter tree; each such leaf must shard exactly like its
    parameter. Leaves are matched by their tree-path suffix (optax state
    paths end with the full parameter path); anything else (schedule counts,
    scalars) is replicated. Suffix matches are anchored at a path-component
    boundary so e.g. 'proj/kernel' can never claim 'out_proj/kernel'.
    """
    flat_param_specs = {
        path_str(path): spec
        for path, spec in jax.tree_util.tree_flatten_with_path(param_specs)[0]
    }
    opt_shapes = jax.eval_shape(tx.init, params)

    def assign(path, leaf):
        name = path_str(path)
        for param_path, spec in flat_param_specs.items():
            if name == param_path or name.endswith("/" + param_path):
                return spec
        return P()

    return jax.tree_util.tree_map_with_path(assign, opt_shapes)
