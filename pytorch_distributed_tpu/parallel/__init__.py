from pytorch_distributed_tpu.parallel.fsdp import (
    fsdp_param_specs,
    fsdp_state_specs,
    shard_fsdp_state,
)
from pytorch_distributed_tpu.parallel.mesh import (
    DATA_AXIS,
    MESH_AXES,
    MODEL_AXIS,
    SEQ_AXIS,
    batch_sharding,
    global_batch_size,
    local_mesh,
    local_replica_count,
    make_mesh,
    replicated_sharding,
    shard_batch,
    single_device_mesh,
)
from pytorch_distributed_tpu.parallel.distributed import (
    barrier,
    get_rank,
    get_world_size,
    init_process_group,
    is_primary,
)
from pytorch_distributed_tpu.parallel.pipeline import gpipe, last_stage_value
from pytorch_distributed_tpu.parallel.sequence import (
    ring_attention,
    ring_attention_sharded,
)
from pytorch_distributed_tpu.parallel.collectives import (
    all_reduce,
    broadcast_from_primary,
    pmean_tree,
    psum_tree,
)

__all__ = [
    "fsdp_param_specs",
    "fsdp_state_specs",
    "shard_fsdp_state",
    "DATA_AXIS",
    "MESH_AXES",
    "MODEL_AXIS",
    "SEQ_AXIS",
    "make_mesh",
    "single_device_mesh",
    "local_mesh",
    "batch_sharding",
    "replicated_sharding",
    "shard_batch",
    "global_batch_size",
    "local_replica_count",
    "init_process_group",
    "get_rank",
    "get_world_size",
    "is_primary",
    "barrier",
    "gpipe",
    "last_stage_value",
    "ring_attention",
    "ring_attention_sharded",
    "all_reduce",
    "broadcast_from_primary",
    "psum_tree",
    "pmean_tree",
]
