"""FSDP / ZeRO-3: parameters and optimizer state sharded over the data axis.

The reference replicates everything (SURVEY.md §2c "ZeRO/FSDP: absent; full
replication everywhere"); this fills that last parallelism row the TPU way.
Instead of a wrapper class with hooks (torch FSDP), sharding is a spec
change on the SAME SPMD train step (``train/step.py``):

- at rest, every parameter/momentum leaf is split along its largest
  axis-divisible dimension across the ``data`` axis — per-device state
  memory drops by ~the axis size (the ZeRO memory win);
- inside the step, ``lax.all_gather`` materializes full parameters just
  before use (XLA's latency-hiding scheduler overlaps the gathers with
  compute — what torch FSDP's prefetch hooks hand-implement);
- gradients come back via ``lax.psum_scatter`` (mean), so each device only
  ever holds the gradient shard it owns — the reduce-scatter half of ZeRO;
- the optimizer update runs on local shards (SGD/momentum are elementwise).

Training math is IDENTICAL to replicated DP: all_gather∘psum_scatter is
exactly the pmean the DP step performs, just materialized shard-wise; BN
stays per-replica. Parity is asserted in tests/test_fsdp.py down to
float tolerance over multiple steps.

Checkpoint compatibility: specs only change placement, never the pytree —
``utils.checkpoint.gather_global`` materializes the global value, so FSDP
checkpoints restore into replicated runs and vice versa (the reference's
one-canonical-layout contract, ``restnet_ddp.py:38``).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from pytorch_distributed_tpu.parallel.mesh import DATA_AXIS


def fsdp_dim(shape, axis_size: int, min_shard_elems: int = 1024) -> Optional[int]:
    """Pick the dimension to shard: the LARGEST axis-size-divisible dim.

    Returns None (replicate) for scalars, tiny leaves (sharding a 64-element
    bias saves nothing and costs a gather), and shapes with no divisible
    dim. Largest-dim choice keeps shards as square as possible, which keeps
    the all_gather payloads contiguous and large.
    """
    if int(np.prod(shape, initial=1)) < min_shard_elems:
        return None
    best = None
    for d, n in enumerate(shape):
        if n % axis_size == 0 and (best is None or n > shape[best]):
            best = d
    return best


def fsdp_param_specs(
    params: Any, mesh: Mesh, axis: str = DATA_AXIS, min_shard_elems: int = 1024
) -> Any:
    """PartitionSpec tree sharding each eligible leaf over ``axis``."""
    size = mesh.shape[axis]

    def spec(leaf):
        shape = getattr(leaf, "shape", ())
        d = fsdp_dim(shape, size, min_shard_elems)
        if d is None:
            return P()
        return P(*(axis if i == d else None for i in range(len(shape))))

    return jax.tree.map(spec, params)


def fsdp_state_specs(state, mesh: Mesh, axis: str = DATA_AXIS):
    """TrainState-shaped spec tree: params+opt sharded, the rest replicated.

    Mirrors ``train.lm.lm_state_specs``'s shape so the step builders can
    treat TP and FSDP specs uniformly.
    """
    from pytorch_distributed_tpu.parallel.tensor import opt_state_specs

    param_specs = fsdp_param_specs(state.params, mesh, axis)
    return state.replace(
        step=P(),
        params=param_specs,
        batch_stats=jax.tree.map(lambda _: P(), state.batch_stats),
        opt_state=opt_state_specs(state.params, param_specs, state.tx),
        scaler=jax.tree.map(lambda _: P(), state.scaler),
    )


def shard_fsdp_state(mesh: Mesh, state, axis: str = DATA_AXIS):
    """Place a state onto the mesh with FSDP sharding.

    Returns (placed_state, spec_state) — same contract as
    ``train.lm.shard_lm_state``.
    """
    from pytorch_distributed_tpu.parallel.mesh import specs_to_shardings

    specs = fsdp_state_specs(state, mesh, axis)
    return jax.device_put(state, specs_to_shardings(mesh, specs)), specs


def _sharded_dim(spec: P, axis: str) -> Optional[int]:
    for d, part in enumerate(spec):
        parts = part if isinstance(part, tuple) else (part,)
        if axis in parts:
            return d
    return None


def gather_params(params: Any, specs: Any, axis: str = DATA_AXIS,
                  mask: Any = None) -> Any:
    """all_gather each sharded leaf back to full size (inside shard_map).

    XLA schedules these independently, overlapping with the forward ops that
    consume them — torch FSDP's unshard-prefetch, for free.

    ``mask`` (optional boolean tree): gather only masked leaves — the LM
    step's mixed-placement case, where TP/EP compute shards also name
    mesh axes in their specs but must stay sharded.
    """

    def gather(leaf, spec, m=True):
        if not m:
            return leaf
        d = _sharded_dim(spec, axis)
        if d is None:
            return leaf
        return jax.lax.all_gather(leaf, axis, axis=d, tiled=True)

    if mask is None:
        return jax.tree.map(gather, params, specs)
    return jax.tree.map(gather, params, specs, mask)


def scatter_grads(grads: Any, specs: Any, axis: str = DATA_AXIS) -> Any:
    """Reduce full gradients to the shard each device owns (mean semantics).

    Sharded leaves: ``psum_scatter`` (the reduce-scatter half of ZeRO)
    divided by the axis size; replicated leaves: plain ``pmean`` — together
    exactly the DP gradient combine, split by ownership. The axis size is
    read from the axis itself so every leaf gets consistent mean scaling.
    """
    n = jax.lax.psum(1, axis)

    def scatter(g, spec):
        d = _sharded_dim(spec, axis)
        if d is None:
            return jax.lax.pmean(g, axis_name=axis)
        return (
            jax.lax.psum_scatter(g, axis, scatter_dimension=d, tiled=True) / n
        )

    return jax.tree.map(scatter, grads, specs)
