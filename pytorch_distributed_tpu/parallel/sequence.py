"""Ring attention: sequence/context parallelism over the ``seq`` mesh axis.

First-class long-context support (absent from the reference — SURVEY.md §5
"long-context: ABSENT" — but required of this framework): sequences are
sharded over the ``seq`` axis, each device holds Q/K/V for its L/S-token
shard, and K/V shards travel around the ring with ``lax.ppermute`` over ICI
while every device folds the visiting block into an online-softmax
accumulator (``ops.attention.attend_block`` — the same recurrence the
blockwise kernel scans locally). After S steps every query has attended to
every key, with O(L/S) memory per device and L² compute spread S ways.

TPU-first details:
- the next-step ``ppermute`` is independent of the current fold, so XLA's
  latency-hiding scheduler overlaps the ICI transfer with the block matmuls
  (the hand-written overlap the GPU ring-attention papers implement with
  separate comm streams);
- causal masking uses absolute position offsets derived from the ring step,
  so the math is identical to single-device causal attention (verified in
  tests/test_sequence.py);
- everything lives inside ``shard_map`` and differentiates through scan +
  ppermute, so the same code trains.

Causal runs skip fully-masked visiting shards entirely (a KV shard whose
every key is in the future of every local query contributes nothing — a
``lax.cond`` keeps the scan structure static while the branch's matmuls
never execute), recovering ~2x of the plain ring schedule's waste at no
change in results. That fixes FLOPs but not wall-clock: with contiguous
shards rank s-1 still folds s real shards while rank 0 folds one, so the
critical path is unimproved. ``layout="zigzag"`` fixes the balance: the
global sequence is cut into 2s chunks and rank r holds chunks
(r, 2s-1-r) — one early, one late. Of the four (q-chunk × kv-chunk)
pairs per visiting shard, one is ALWAYS fully visible, one NEVER
(statically omitted), and only the two chunk-diagonal pairs carry a
runtime cond — every rank folds exactly ~2 real chunk-blocks per step,
halving the causal critical path at sp >= 4 (counter-measured in
tests/test_sequence.py; use ``parallel.sequence.zigzag_shard`` to lay
global arrays out so contiguous sharding delivers each rank its chunks).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from pytorch_distributed_tpu.ops.attention import SoftmaxState, attend_block
from pytorch_distributed_tpu.parallel.mesh import DATA_AXIS, SEQ_AXIS, shard_map


def zigzag_shard(x, s: int, axis: int = 1):
    """Reorder a global array so CONTIGUOUS equal sharding over ``s``
    devices delivers the zigzag layout: shard r = chunks (r, 2s-1-r) of
    the 2s-chunk decomposition along ``axis``. Inverse: `zigzag_unshard`.
    Apply to every per-token array (tokens, labels, weights) so they stay
    aligned — tested with ``train.lm.shift_labels`` in test_sequence.py."""
    import numpy as np

    l = x.shape[axis]
    if l % (2 * s):
        raise ValueError(f"length {l} not divisible by 2*{s} chunks")
    order = np.concatenate([[r, 2 * s - 1 - r] for r in range(s)])
    # numpy stays numpy (host pipelines mutate in place; a silent device
    # round-trip here would also break them) — dispatch on jax.Array
    xp = jnp if isinstance(x, jax.Array) else np
    parts = xp.split(x, 2 * s, axis=axis)
    return xp.concatenate([parts[i] for i in order], axis=axis)


def zigzag_unshard(x, s: int, axis: int = 1):
    """Inverse of :func:`zigzag_shard`."""
    import numpy as np

    order = np.concatenate([[r, 2 * s - 1 - r] for r in range(s)])
    inv = np.argsort(order)
    xp = jnp if isinstance(x, jax.Array) else np
    parts = xp.split(x, 2 * s, axis=axis)
    return xp.concatenate([parts[i] for i in inv], axis=axis)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis: str = SEQ_AXIS,
    causal: bool = False,
    scale: Optional[float] = None,
    base_offset: jax.Array | int = 0,
    remat: bool = True,
    layout: str = "contiguous",
    with_schedule_counts: bool = False,
) -> jax.Array:
    """Attention over a sequence sharded on ``axis`` (call under shard_map).

    Args:
      q, k, v: this device's shards, ``[B, L_local, H, D]``; global length
        is ``L_local * axis_size``, shard i holding tokens
        ``[base_offset + i*L_local, base_offset + (i+1)*L_local)``
        (contiguous layout) or chunks ``(i, 2s-1-i)`` of the 2s-chunk
        decomposition (zigzag — see :func:`zigzag_shard`).
      causal: apply the global causal mask (offsets handled per ring step).
      base_offset: absolute position of the sharded sequence's first token
        (non-zero when attending over a chunk of a longer document).
      layout: "contiguous" or "zigzag" (causal only; balances the causal
        critical path across ranks — module docstring).
      with_schedule_counts: also return this rank's executed block area
        (q_len*k_len summed over attend calls that actually RAN — the
        counter lives inside the cond branches, so skipped shards don't
        count). Shape [1] f32; gather over the axis to see the per-rank
        causal balance. This is the compute that becomes per-rank
        wall-clock on a real ring — measured in tests/test_sequence.py.

    Returns: ``[B, L_local, H, D]`` — this device's rows of the exact
    softmax(QK^T)V over the full sequence (bit-comparable to dense
    attention on the gathered sequence, up to fp accumulation order).
    """
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    if layout == "zigzag":
        if not causal:
            raise ValueError(
                "zigzag layout only changes causal scheduling; use "
                "layout='contiguous' for non-causal attention"
            )
        return _ring_attention_zigzag(
            q, k, v, axis=axis, scale=scale, base_offset=base_offset,
            remat=remat, with_schedule_counts=with_schedule_counts,
        )
    if layout != "contiguous":
        raise ValueError(f"unknown layout {layout!r}")
    s = jax.lax.psum(1, axis)
    my = jax.lax.axis_index(axis)
    b, lq, h, d = q.shape
    lk = k.shape[1]
    q_offset = base_offset + my * lq
    perm = [(i, (i + 1) % s) for i in range(s)]

    def fold(state_counts, k_cur, v_cur, step):
        state, counts = state_counts
        # kv shard currently held originated on device (my - step) mod s
        src = jax.lax.rem(my - step + s, s)

        def attend(st_c):
            st, c_ = st_c
            st = attend_block(
                st, q, k_cur, v_cur,
                scale=scale, causal=causal,
                q_offset=q_offset, k_offset=base_offset + src * lk,
            )
            return st, c_ + float(lq * lk)

        if not causal:
            return attend((state, counts))
        # Shards are CONTIGUOUS position blocks, so a visiting shard from a
        # later ring position (src > my) is entirely in every local query's
        # future: fully masked, contributes nothing — skip its matmuls.
        # (Equal-length shards ⇒ the block test reduces to src > my.)
        if lk != lq:
            return attend((state, counts))  # unequal: no block shortcut
        return jax.lax.cond(src > my, lambda st_c: st_c, attend,
                            (state, counts))

    def body(carry, step):
        state, (k_cur, v_cur) = carry
        # Rotate for the next step first: independent of the fold below, so
        # the ICI transfer overlaps the matmuls.
        k_nxt, v_nxt = jax.lax.ppermute((k_cur, v_cur), axis, perm)
        state = fold(state, k_cur, v_cur, step)
        return (state, (k_nxt, v_nxt)), None

    if remat:
        body = jax.checkpoint(body)
        fold = jax.checkpoint(fold)

    init = ((SoftmaxState.zero(b, lq, h, d), jnp.zeros((1,), jnp.float32)),
            (k, v))
    # s-1 rotate+fold steps, then fold the last visiting shard with no
    # rotation — a full-s scan would ship K/V around the ring once more
    # only to discard them.
    if s > 1:
        (state_counts, (k_last, v_last)), _ = jax.lax.scan(
            body, init, jnp.arange(s - 1)
        )
    else:
        state_counts, (k_last, v_last) = init
    state, counts = fold(state_counts, k_last, v_last, s - 1)
    out = state.finalize(q.dtype)
    return (out, counts) if with_schedule_counts else out


def _ring_attention_zigzag(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis: str,
    scale: float,
    base_offset: jax.Array | int = 0,
    remat: bool = True,
    with_schedule_counts: bool = False,
) -> jax.Array:
    """Causal ring attention on the zigzag layout (module docstring).

    Rank r holds q/kv chunks (r, 2s-1-r), each of length c = L_local/2.
    Visiting shard from rank ``src`` carries kv chunks (src, 2s-1-src).
    Chunk-index algebra (all chunks are contiguous position ranges):
      (q_lo=r,      kv_lo=src):      diag if src==r, full if src<r, skip else
      (q_lo=r,      kv_hi=2s-1-src): 2s-1-src >= s > r — ALWAYS masked, omitted
      (q_hi=2s-1-r, kv_lo=src):      src <= s-1 < 2s-1-r — ALWAYS fully visible
      (q_hi=2s-1-r, kv_hi=2s-1-src): diag if src==r, full if src>r, skip else
    So every rank folds exactly two real chunk-blocks per step (plus the
    within-chunk diagonals on the src==r step): balanced critical path.
    """
    s = jax.lax.psum(1, axis)
    my = jax.lax.axis_index(axis)
    b, lq, h, d = q.shape
    if lq % 2 or k.shape[1] != lq:
        raise ValueError(
            f"zigzag needs equal, even-length shards; got q {lq}, k {k.shape[1]}"
        )
    c = lq // 2
    perm = [(i, (i + 1) % s) for i in range(s)]
    q_lo, q_hi = q[:, :c], q[:, c:]
    lo_off = base_offset + my * c
    hi_off = base_offset + (2 * s - 1 - my) * c

    def fold(states, k_cur, v_cur, step):
        st_lo, st_hi, counts = states
        src = jax.lax.rem(my - step + s, s)
        k_lo, k_hi = k_cur[:, :c], k_cur[:, c:]
        v_lo, v_hi = v_cur[:, :c], v_cur[:, c:]
        src_lo_off = base_offset + src * c
        src_hi_off = base_offset + (2 * s - 1 - src) * c

        def pair(st_c, qc, q_off, kc, vc, k_off):
            st, c_ = st_c
            st = attend_block(st, qc, kc, vc, scale=scale, causal=True,
                              q_offset=q_off, k_offset=k_off)
            return st, c_ + float(c * c)

        # (q_lo, kv_lo): runs unless src > my (attend_block's positional
        # mask handles both the src==my diagonal and src<my full case)
        st_lo, counts = jax.lax.cond(
            src > my,
            lambda st_c: st_c,
            lambda st_c: pair(st_c, q_lo, lo_off, k_lo, v_lo, src_lo_off),
            (st_lo, counts),
        )
        # (q_hi, kv_lo): always fully visible
        st_hi, counts = pair((st_hi, counts), q_hi, hi_off, k_lo, v_lo,
                             src_lo_off)
        # (q_hi, kv_hi): runs unless src < my
        st_hi, counts = jax.lax.cond(
            src < my,
            lambda st_c: st_c,
            lambda st_c: pair(st_c, q_hi, hi_off, k_hi, v_hi, src_hi_off),
            (st_hi, counts),
        )
        return (st_lo, st_hi, counts)

    def body(carry, step):
        states, (k_cur, v_cur) = carry
        k_nxt, v_nxt = jax.lax.ppermute((k_cur, v_cur), axis, perm)
        states = fold(states, k_cur, v_cur, step)
        return (states, (k_nxt, v_nxt)), None

    if remat:
        body = jax.checkpoint(body)
        fold = jax.checkpoint(fold)

    init = (
        (SoftmaxState.zero(b, c, h, d), SoftmaxState.zero(b, c, h, d),
         jnp.zeros((1,), jnp.float32)),
        (k, v),
    )
    if s > 1:
        (states, (k_last, v_last)), _ = jax.lax.scan(
            body, init, jnp.arange(s - 1)
        )
    else:
        states, (k_last, v_last) = init
    st_lo, st_hi, counts = fold(states, k_last, v_last, s - 1)
    out = jnp.concatenate(
        [st_lo.finalize(q.dtype), st_hi.finalize(q.dtype)], axis=1
    )
    return (out, counts) if with_schedule_counts else out


def ring_attention_sharded(
    mesh: Mesh,
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    layout: str = "contiguous",
) -> jax.Array:
    """Convenience wrapper: global ``[B, L, H, D]`` arrays, batch sharded on
    ``data`` and length on ``seq``; returns the globally-sharded output.
    With ``layout="zigzag"``, inputs must already be in zigzag order
    (:func:`zigzag_shard`), and the output comes back in that order.
    Inside a larger shard_map'd step, call ``ring_attention`` directly."""
    spec = P(DATA_AXIS, SEQ_AXIS)
    fn = shard_map(
        partial(ring_attention, causal=causal, scale=scale, layout=layout),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
