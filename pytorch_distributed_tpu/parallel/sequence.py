"""Ring attention: sequence/context parallelism over the ``seq`` mesh axis.

First-class long-context support (absent from the reference — SURVEY.md §5
"long-context: ABSENT" — but required of this framework): sequences are
sharded over the ``seq`` axis, each device holds Q/K/V for its L/S-token
shard, and K/V shards travel around the ring with ``lax.ppermute`` over ICI
while every device folds the visiting block into an online-softmax
accumulator (``ops.attention.attend_block`` — the same recurrence the
blockwise kernel scans locally). After S steps every query has attended to
every key, with O(L/S) memory per device and L² compute spread S ways.

TPU-first details:
- the next-step ``ppermute`` is independent of the current fold, so XLA's
  latency-hiding scheduler overlaps the ICI transfer with the block matmuls
  (the hand-written overlap the GPU ring-attention papers implement with
  separate comm streams);
- causal masking uses absolute position offsets derived from the ring step,
  so the math is identical to single-device causal attention (verified in
  tests/test_sequence.py);
- everything lives inside ``shard_map`` and differentiates through scan +
  ppermute, so the same code trains.

Causal runs skip fully-masked visiting shards entirely (a KV shard whose
every key is in the future of every local query contributes nothing — a
``lax.cond`` keeps the scan structure static while the branch's matmuls
never execute), recovering ~2x of the plain ring schedule's waste at no
change in results. The remaining imbalance (later ring positions fold more
real blocks than earlier ones) is what a zigzag/striped layout would fix;
noted so the cost model is honest.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from pytorch_distributed_tpu.ops.attention import SoftmaxState, attend_block
from pytorch_distributed_tpu.parallel.mesh import DATA_AXIS, SEQ_AXIS, shard_map


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis: str = SEQ_AXIS,
    causal: bool = False,
    scale: Optional[float] = None,
    base_offset: jax.Array | int = 0,
    remat: bool = True,
) -> jax.Array:
    """Attention over a sequence sharded on ``axis`` (call under shard_map).

    Args:
      q, k, v: this device's shards, ``[B, L_local, H, D]``; global length
        is ``L_local * axis_size``, shard i holding tokens
        ``[base_offset + i*L_local, base_offset + (i+1)*L_local)``.
      causal: apply the global causal mask (offsets handled per ring step).
      base_offset: absolute position of the sharded sequence's first token
        (non-zero when attending over a chunk of a longer document).

    Returns: ``[B, L_local, H, D]`` — this device's rows of the exact
    softmax(QK^T)V over the full sequence (bit-comparable to dense
    attention on the gathered sequence, up to fp accumulation order).
    """
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    s = jax.lax.psum(1, axis)
    my = jax.lax.axis_index(axis)
    b, lq, h, d = q.shape
    lk = k.shape[1]
    q_offset = base_offset + my * lq
    perm = [(i, (i + 1) % s) for i in range(s)]

    def fold(state, k_cur, v_cur, step):
        # kv shard currently held originated on device (my - step) mod s
        src = jax.lax.rem(my - step + s, s)

        def attend(st):
            return attend_block(
                st, q, k_cur, v_cur,
                scale=scale, causal=causal,
                q_offset=q_offset, k_offset=base_offset + src * lk,
            )

        if not causal:
            return attend(state)
        # Shards are CONTIGUOUS position blocks, so a visiting shard from a
        # later ring position (src > my) is entirely in every local query's
        # future: fully masked, contributes nothing — skip its matmuls.
        # (Equal-length shards ⇒ the block test reduces to src > my.)
        if lk != lq:
            return attend(state)  # unequal shards: no block-level shortcut
        return jax.lax.cond(src > my, lambda st: st, attend, state)

    def body(carry, step):
        state, (k_cur, v_cur) = carry
        # Rotate for the next step first: independent of the fold below, so
        # the ICI transfer overlaps the matmuls.
        k_nxt, v_nxt = jax.lax.ppermute((k_cur, v_cur), axis, perm)
        state = fold(state, k_cur, v_cur, step)
        return (state, (k_nxt, v_nxt)), None

    if remat:
        body = jax.checkpoint(body)
        fold = jax.checkpoint(fold)

    init = (SoftmaxState.zero(b, lq, h, d), (k, v))
    # s-1 rotate+fold steps, then fold the last visiting shard with no
    # rotation — a full-s scan would ship K/V around the ring once more
    # only to discard them.
    if s > 1:
        (state, (k_last, v_last)), _ = jax.lax.scan(body, init, jnp.arange(s - 1))
    else:
        state, (k_last, v_last) = init
    state = fold(state, k_last, v_last, s - 1)
    return state.finalize(q.dtype)


def ring_attention_sharded(
    mesh: Mesh,
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
) -> jax.Array:
    """Convenience wrapper: global ``[B, L, H, D]`` arrays, batch sharded on
    ``data`` and length on ``seq``; returns the globally-sharded output.
    Inside a larger shard_map'd step, call ``ring_attention`` directly."""
    spec = P(DATA_AXIS, SEQ_AXIS)
    fn = shard_map(
        partial(ring_attention, causal=causal, scale=scale),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
