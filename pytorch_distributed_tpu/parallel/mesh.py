"""Device mesh construction and sharding specs — the communication layer.

TPU-native replacement for the reference's NCCL process-group + DDP wrapper
stack (D6/D7/D13: ``dist.init_process_group('nccl', ...)``,
``restnet_ddp.py:94``; ``DistributedDataParallel(model.cuda())``,
``restnet_ddp.py:99``). There is no wrapper object here: parallelism is a
``jax.sharding.Mesh`` plus sharding specs on one SPMD step function. XLA
compiles the gradient all-reduce into the step program and routes it over
ICI (intra-pod) / DCN (cross-pod) automatically.

The mesh always carries three axes — ``data`` (the only one the reference's
capability surface uses: all three DP flavors map onto it), ``seq``
(sequence/context parallelism, ``parallel.sequence``), and ``model``
(tensor parallelism) — so adding a parallelism dimension is a sharding-spec
change, not a redesign (SURVEY.md §2c).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.8: top-level export; older: experimental module
    from jax import shard_map
except ImportError:  # pragma: no cover
    from functools import wraps

    from jax.experimental.shard_map import shard_map as _shard_map_legacy

    @wraps(_shard_map_legacy)
    def shard_map(f=None, /, **kwargs):
        # pre-0.8 signature spells check_vma as check_rep; every call site
        # here uses the modern keyword, so translate (pyproject pins
        # jax>=0.8 — this fallback only cushions older interpreters).
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map_legacy(f, **kwargs)

DATA_AXIS = "data"
SEQ_AXIS = "seq"
MODEL_AXIS = "model"

# The canonical axis order, in one place: jaxlint's collective-axis rule
# treats these constants as the declared axis set, so a collective naming
# anything else is a build error (ANALYSIS.md).
MESH_AXES = (DATA_AXIS, SEQ_AXIS, MODEL_AXIS)


def make_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    data_parallel: Optional[int] = None,
    model_parallel: int = 1,
    seq_parallel: int = 1,
    axis_names: Sequence[str] = MESH_AXES,
) -> Mesh:
    """Build a (data, seq, model) mesh over the given (default: all) devices.

    With ``model_parallel=seq_parallel=1`` (the reference's entire capability
    surface) this is a pure data-parallel mesh: one replica per chip, the
    exact topology ``DistributedDataParallel`` builds with one process per
    GPU (``restnet_ddp.py:154-155``) — minus the processes: a single program
    spans every chip on every host.

    The ``seq`` axis carries sequence/context parallelism (ring attention,
    ``parallel.sequence``) and the ``model`` axis tensor parallelism — both
    absent from the reference (SURVEY.md §2c) but first-class here. Axis
    order is (data, seq, model) so the innermost (fastest-varying, i.e.
    physically closest over ICI) devices carry the most latency-sensitive
    collectives.
    """
    if len(axis_names) != 3:
        raise ValueError(
            f"make_mesh builds a 3-axis (data, seq, model) grid; got "
            f"axis_names={tuple(axis_names)}"
        )
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n = len(devices)
    inner = model_parallel * seq_parallel
    if data_parallel is None:
        if n % inner:
            raise ValueError(
                f"{n} devices not divisible by seq_parallel*model_parallel={inner}"
            )
        data_parallel = n // inner
    if data_parallel * inner != n:
        raise ValueError(
            f"mesh {data_parallel}x{seq_parallel}x{model_parallel} != {n} devices"
        )
    grid = np.asarray(devices).reshape(data_parallel, seq_parallel, model_parallel)
    return Mesh(grid, axis_names=tuple(axis_names))


def single_device_mesh(device: Optional[jax.Device] = None) -> Mesh:
    """1-chip mesh: the ``resnet_single_gpu.py`` topology. The same SPMD
    step function runs unchanged; collectives over a size-1 axis are no-ops."""
    if device is None:
        device = jax.devices()[0]
    return make_mesh([device])


def local_mesh() -> Mesh:
    """All chips addressable by this process (the ``nn.DataParallel``
    topology, ``resnet_dp.py:82`` — 8 local devices, one process)."""
    return make_mesh(jax.local_devices())


def batch_sharding(mesh: Mesh, axis: str = DATA_AXIS) -> NamedSharding:
    """Leading (batch) dimension split across the data axis — how every
    input batch is laid out. Per-replica shard size ≙ the reference's
    per-process batch of 400 (``restnet_ddp.py:78``)."""
    return NamedSharding(mesh, P(axis))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully replicated — parameters and optimizer state in pure DP.

    ≙ DDP's broadcast-from-rank-0 at construction (``restnet_ddp.py:99``):
    placing the initial pytree with this sharding performs the broadcast.
    """
    return NamedSharding(mesh, P())


def specs_to_shardings(mesh: Mesh, specs):
    """PartitionSpec pytree → NamedSharding pytree over ``mesh``.

    The one place the spec→sharding mapping lives: initial placement
    (``fsdp.shard_fsdp_state``, ``lm.shard_lm_state``) and checkpoint
    restore (``Trainer.try_resume``) must place identically or resumed runs
    get a different layout than fresh ones.
    """
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_batch(mesh: Mesh, batch, axis: str = DATA_AXIS):
    """Place a host-local numpy batch onto the mesh as a global array.

    Each process passes its local shard (what its DataLoader produced for
    its ranks); together they form the global batch. Replaces the per-step
    H2D copy ``x.cuda(non_blocking=True)`` (``restnet_ddp.py:25``) — the
    transfer is async and the result is already laid out for the compiled
    step, so no scatter happens at step time (unlike ``nn.DataParallel``'s
    per-step scatter, D5).
    """
    sharding = batch_sharding(mesh, axis)
    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(sharding, np.asarray(x)),
        batch,
    )


def global_batch_size(mesh: Mesh, per_replica_batch: int, axis: str = DATA_AXIS) -> int:
    """per-replica bs × data-axis size (ref: 400 × world_size)."""
    return per_replica_batch * mesh.shape[axis]


def local_replica_count(mesh: Mesh, axis: str = DATA_AXIS) -> int:
    """How many data-axis replicas this process feeds (= local chips / model
    axis span). The loader produces ``local_replica_count × per_replica_bs``
    samples per step."""
    local = set(jax.local_devices())
    axis_index = mesh.axis_names.index(axis)
    coords = set()
    for idx in np.ndindex(mesh.devices.shape):
        if mesh.devices[idx] in local:
            coords.add(idx[axis_index])
    return max(len(coords), 1)
