"""Pipeline parallelism: a GPipe-style microbatch executor under shard_map.

Completes the framework's parallelism taxonomy (dp/sp/tp/ep in
``mesh``/``sequence``/``tensor``/``models.moe``; pp here — all absent from
the reference, SURVEY.md §2c). TPU-first shape discipline:

- stages live on the ``model`` mesh axis; stage s holds its own slice of
  the layer stack (placement-sharded params, like TP/EP);
- the schedule is one ``lax.scan`` over M + S - 1 ticks; each tick every
  stage computes its current microbatch and ``ppermute``s the activation to
  its successor — the classic GPipe pipeline with bubble fraction
  (S-1)/(M+S-1), all static shapes, no data-dependent control flow;
- warm-up/drain bubbles are computed-but-masked (XLA cannot skip them
  without dynamic shapes); outputs are collected at the LAST stage and are
  valid there — combine with an out_spec that reads the final stage's
  shard, or psum-mask as needed by the caller;
- the whole schedule differentiates through scan + ppermute, so the same
  executor trains (backward replays the ring in reverse).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

from pytorch_distributed_tpu.parallel.mesh import MODEL_AXIS


def gpipe(
    stage_fn: Callable[..., Any],
    stage_params: Any,
    microbatches: jax.Array,
    *,
    axis: str = MODEL_AXIS,
    remat: bool = True,
    has_aux: bool = False,
):
    """Run microbatches through the stage pipeline (call under shard_map).

    Args:
      stage_fn: ``(stage_params, x, mb_idx) -> y`` (or ``-> (y, aux)`` with
        ``has_aux``) — one stage's computation; every stage must map the
        same activation shape to itself (uniform-width pipeline, e.g. a
        slice of transformer blocks). ``mb_idx`` is the index of the
        microbatch this tick computes on THIS stage (clipped during
        warm-up/drain) — derive dropout rngs from it so the pipelined run
        reproduces the sequential reference's masks exactly. A 2-arg
        ``(stage_params, x)`` stage_fn (the pre-r3 contract) is also
        accepted and simply doesn't receive the index.
      stage_params: THIS stage's parameters (the local shard of a
        stage-stacked tree).
      microbatches: ``[M, ...]`` — the full input, identical on every stage
        (stage 0 consumes it; others ignore theirs).
      has_aux: ``stage_fn`` also returns a scalar auxiliary loss (e.g. MoE
        load balancing); contributions from warm-up/drain ticks — garbage
        activations — are masked OUT (their gradients too), and the summed
        real-tick aux is returned alongside the outputs.

    Returns: ``[M, ...]`` outputs (with ``has_aux``: ``(outputs, aux)``),
    VALID ON THE LAST STAGE (other stages hold garbage from their position
    in the ring; ``aux`` is valid on EVERY stage for its own real ticks) —
    select stage S-1's output copy via ``last_stage_value`` or a psum-mask.
    """
    # r2→r3 API compatibility: stage_fns written against the 2-arg contract
    # ``(stage_params, x)`` (before mb_idx existed for dropout parity) are
    # accepted and simply don't receive the index. Detected once at trace
    # time from the signature. CONTRACT for opaque signatures (ADVICE r4
    # #2): ``*args`` callables and C callables whose signature cannot be
    # inspected are assumed mb_idx-AWARE and receive the 3-arg call
    # ``(stage_params, x, mb_idx)`` — a legacy 2-arg wrapper written as
    # ``lambda *a: f(*a[:2])``-style must accept (and may ignore) the
    # third argument, or expose a real 2-positional signature to opt out.
    import inspect

    try:
        params = list(inspect.signature(stage_fn).parameters.values())
        pos = [p for p in params
               if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)]
        if any(p.kind == p.VAR_POSITIONAL for p in params):
            takes_mb_idx = True
        elif len(pos) < 3:
            takes_mb_idx = False
        elif pos[2].default is inspect.Parameter.empty:
            takes_mb_idx = True
        else:
            # A defaulted third positional is ambiguous: a pre-r3 fn like
            # ``(params, x, train=False)`` must NOT receive the traced
            # index in ``train``. Only a parameter literally named mb_idx
            # opts in.
            takes_mb_idx = pos[2].name == "mb_idx"
    except (TypeError, ValueError):  # builtins / C callables
        takes_mb_idx = True

    s = jax.lax.psum(1, axis)
    my = jax.lax.axis_index(axis)
    m = microbatches.shape[0]
    mb_shape = microbatches.shape[1:]

    # Send each stage's activation to its successor; the ring wraps only to
    # keep the permutation total (stage 0 ignores what it receives).
    perm = [(i, (i + 1) % s) for i in range(s)]

    def tick(carry, t):
        incoming, outputs, aux_acc = carry
        # Stage 0 feeds microbatch t while t < M; later stages consume what
        # arrived from their predecessor last tick. Stage ``my`` works on
        # microbatch t - my when my <= t < my + M (else a garbage tick).
        feed = microbatches[jnp.clip(t, 0, m - 1)]
        x = jnp.where(my == 0, feed, incoming)
        mb_idx = jnp.clip(t - my, 0, m - 1)
        call_args = (
            (stage_params, x, mb_idx) if takes_mb_idx else (stage_params, x)
        )
        if has_aux:
            y, aux = stage_fn(*call_args)
            real = ((t >= my) & (t < my + m)).astype(aux.dtype)
            aux_acc = aux_acc + real * aux
        else:
            y = stage_fn(*call_args)
        # The last stage banks its result at output slot t - (S-1) (valid
        # once the pipeline is full).
        slot = jnp.clip(t - (s - 1), 0, m - 1)
        valid = (t >= s - 1) & (jnp.asarray(my) == s - 1)
        current = jax.lax.dynamic_index_in_dim(outputs, slot, keepdims=False)
        banked = jnp.where(valid, y, current)
        outputs = jax.lax.dynamic_update_index_in_dim(outputs, banked, slot, 0)
        incoming = jax.lax.ppermute(y, axis, perm)
        return (incoming, outputs, aux_acc), None

    if remat:
        tick = jax.checkpoint(tick)

    init = (
        jnp.zeros(mb_shape, microbatches.dtype),
        jnp.zeros((m,) + mb_shape, microbatches.dtype),
        jnp.zeros((), jnp.float32),
    )
    (_, outputs, aux), _ = jax.lax.scan(tick, init, jnp.arange(m + s - 1))
    return (outputs, aux) if has_aux else outputs


def last_stage_value(x: jax.Array, axis: str = MODEL_AXIS) -> jax.Array:
    """Broadcast the LAST stage's copy of ``x`` to every stage (psum-mask —
    one collective), turning gpipe's stage-local outputs into a replicated
    value usable by loss code on any stage."""
    s = jax.lax.psum(1, axis)
    my = jax.lax.axis_index(axis)
    mask = (my == s - 1).astype(x.dtype)
    return jax.lax.psum(x * mask, axis)
