"""Collective operations, in-step and host-level.

The reference uses three collectives (SURVEY.md §5): bucketed gradient
**all-reduce** inside DDP backward (``restnet_ddp.py:29`` via the C++
Reducer), **reduce-to-rank-0** for validation metrics (``restnet_ddp.py:63-64``),
and parameter **broadcast** at DDP construction. Here the first two are
``lax.psum`` calls compiled *into* the step program (XLA's latency-hiding
scheduler overlaps the gradient psum with the remaining backward, which is
what DDP's bucketing hand-implements), and broadcast is just replicated
sharding at init. This module provides:

- in-step tree collectives (``psum_tree`` / ``pmean_tree``) for use under
  ``shard_map``;
- host-level helpers (``all_reduce``, ``broadcast_from_primary``) for the
  rare out-of-step reductions (cross-host metric readout, checkpoint
  agreement). These ride the same XLA collectives — no hand-managed
  communicator, no backend string (D13).
"""

from __future__ import annotations

from typing import Any

import numpy as np

from pytorch_distributed_tpu.parallel.mesh import DATA_AXIS


def psum_tree(tree: Any, axis: str = DATA_AXIS) -> Any:
    """Sum every leaf across a mesh axis. Inside a compiled step this is the
    gradient/metric all-reduce (ref: NCCL allreduce via D7's Reducer; metric
    ``dist.reduce``, ``restnet_ddp.py:63-64`` — every replica gets the
    result, a strict superset of reduce-to-dst)."""
    import jax

    return jax.lax.psum(tree, axis_name=axis)


def pmean_tree(tree: Any, axis: str = DATA_AXIS) -> Any:
    """Mean across a mesh axis — the DP gradient combine. DDP averages
    gradients over world size; ``pmean`` of per-replica mean-loss gradients
    reproduces exactly that."""
    import jax

    return jax.lax.pmean(tree, axis_name=axis)


def all_reduce(tree: Any, reduce: str = "sum") -> Any:
    """Host-level all-reduce of per-process pytrees of scalars/arrays.

    Every process calls it with its local contribution; every process
    receives the global reduction (numpy). Single-process: identity.
    Used for out-of-step reductions (e.g. cross-host epoch timing); in-step
    metrics are psum'd inside the compiled program instead.
    """
    import jax

    ops = {"sum": np.sum, "mean": np.mean, "max": np.max, "min": np.min}
    try:
        op = ops[reduce]
    except KeyError:
        raise ValueError(f"unknown reduction {reduce!r}; known: {sorted(ops)}")
    if jax.process_count() == 1:
        return jax.tree.map(np.asarray, tree)
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(tree)  # leading axis: process
    return jax.tree.map(lambda v: op(np.asarray(v), axis=0), gathered)


def broadcast_from_primary(tree: Any) -> Any:
    """Make every process see process 0's value (ref: DDP's param broadcast
    at construction, ``restnet_ddp.py:99``). For parameters this is implicit
    in replicated init; this helper covers host-side values (e.g. the
    restored ``start_epoch``). Single-process: identity."""
    import jax

    if jax.process_count() == 1:
        return tree
    from jax.experimental import multihost_utils

    return multihost_utils.broadcast_one_to_all(tree)
