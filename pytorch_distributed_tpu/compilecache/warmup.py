"""The warmup runtime: compile a registry's programs before they stall.

``WarmupRunner`` walks a ``ProgramRegistry`` in priority order and forces
each program compiled via its ``warm`` thunk:

- **priority 0** specs (decode tick, smallest prefill bucket, trainer
  steps) compile in the FOREGROUND, with ``execute=True`` — serving
  programs run once with inert inputs, so their jit call path is hot and
  the first real request pays nothing;
- remaining specs compile in a background thread (``background=True``)
  with ``execute=False`` — AOT lower+compile only, which is safe
  concurrently with live traffic (no donated-buffer execution) and
  populates the persistent compilation cache so the first real use of a
  large bucket pays a disk load, not an XLA compile. ``wait()`` joins.

Every compile emits a ``warmup_compile`` span through the shared
``SpanTracer``, adds its wall time to the goodput ledger's ``compile``
category (foreground only — background compiles don't stall the run), and
appends one ``kind="warmup"`` manifest record (program, seconds,
``cache_hit`` from jax's persistent-cache monitoring events, fingerprint,
priority, background) to a ``MetricsLogger`` JSONL —
``scripts/telemetry_report.py`` renders these, and
``scripts/ci_check.sh --warmup-smoke`` gates on a warm run reporting
hits.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional

from pytorch_distributed_tpu.compilecache.aot import (
    BackendCompileTimer,
    CacheHitCounter,
)
from pytorch_distributed_tpu.compilecache.registry import (
    ProgramRegistry,
    ProgramSpec,
)
from pytorch_distributed_tpu.telemetry import NULL_TRACER


class WarmupRunner:
    """Drives one registry through compilation; reusable stats object."""

    def __init__(self, registry: ProgramRegistry, *, tracer=None,
                 ledger=None, manifest=None):
        self.registry = registry
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self.ledger = ledger
        self.manifest = manifest  # a MetricsLogger (or None)
        self.records: List[dict] = []
        self._records_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None

    def run(self, background: bool = True) -> "WarmupRunner":
        """Compile everything: priority <= 0 foreground (executed inert
        where the spec allows), the rest on a daemon thread when
        ``background`` — call ``wait()`` to join, or just start serving:
        the background portion only ever touches programs traffic hasn't
        reached yet, and a bucket traffic reaches first simply compiles
        on demand (the registry still predicted it)."""
        specs = sorted(self.registry, key=lambda s: s.priority)
        if background:
            fg = [s for s in specs if s.priority <= 0]
            bg = [s for s in specs if s.priority > 0]
        else:
            fg, bg = specs, []
        for spec in fg:
            self._compile_one(spec, execute=True, foreground=True)
        if bg:
            self._thread = threading.Thread(
                target=self._compile_batch, args=(bg,),
                name="compilecache-warmup", daemon=True,
            )
            self._thread.start()
        return self

    def wait(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    def _compile_batch(self, specs: List[ProgramSpec]) -> None:
        for spec in specs:
            self._compile_one(spec, execute=False, foreground=False)

    def _compile_one(self, spec: ProgramSpec, *, execute: bool,
                     foreground: bool) -> None:
        t0 = time.perf_counter()
        with CacheHitCounter() as hits, BackendCompileTimer() as bc, \
                self.tracer.span("warmup_compile", program=spec.name):
            spec.warm(execute)
        seconds = time.perf_counter() - t0
        backend_s = min(bc.seconds, seconds)
        if foreground and self.ledger is not None:
            # split: "compile" is the XLA backend portion (collapses to a
            # disk load on a warm start), "trace" the Python residual
            self.ledger.add("compile", backend_s)
            self.ledger.add("trace", max(seconds - backend_s, 0.0))
        record = {
            "program": spec.name,
            "seconds": round(seconds, 6),
            "backend_compile_s": round(backend_s, 6),
            "cache_hit": hits.hits > 0,
            "fingerprint": self.registry.fingerprint,
            "priority": spec.priority,
            "background": not foreground,
        }
        with self._records_lock:
            self.records.append(record)
        if self.manifest is not None:
            self.manifest.log(kind="warmup", **record)

    def summary(self) -> dict:
        """Aggregate over the records emitted so far (call ``wait()``
        first for a complete background picture)."""
        with self._records_lock:
            records = list(self.records)
        return {
            "programs": len(records),
            "cache_hits": sum(1 for r in records if r["cache_hit"]),
            "fresh": sum(1 for r in records if not r["cache_hit"]),
            "total_s": round(sum(r["seconds"] for r in records), 6),
            "backend_compile_s": round(
                sum(r["backend_compile_s"] for r in records), 6
            ),
            "fingerprint": self.registry.fingerprint,
        }
