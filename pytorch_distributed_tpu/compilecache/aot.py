"""AOT compilation artifacts: the persistent XLA cache + ``jax.export``.

Two complementary layers, both keyed so stale entries are misses rather
than hazards:

- **persistent compilation cache** (``enable_persistent_cache``): jax's
  on-disk executable cache (``jax_compilation_cache_dir``), tuned so
  every program qualifies (the default 1 s minimum-compile-time floor
  would skip exactly the small programs our tests exercise). The cache
  key is XLA's — serialized HLO + compile options + backend — so a warm
  process re-running the same code path loads executables from disk
  instead of recompiling: the mechanism that collapses a resumed
  trainer's / relaunched server's compile fraction. ``CacheHitCounter``
  observes jax's own ``/jax/compilation_cache/cache_hits`` monitoring
  events (per-thread, so a background warmup thread can't cross-count a
  foreground compile) and is how the warmup manifest distinguishes
  ``cache`` from ``fresh``.
- **exported-program artifacts** (``save_exported``/``load_exported``):
  ``jax.export`` serializations of individual programs, stored under
  ``<cache_dir>/aot/<name>-<fingerprint>.jaxexport`` with an atomic
  tmp+rename write. Load is corruption-safe by contract: a truncated,
  garbage, or version-incompatible artifact logs a warning and returns
  ``None`` — the caller falls through to a fresh compile, never crashes
  (the same discipline as ``Checkpointer.restorable_paths`` scanning past
  torn checkpoints).
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
import time
from typing import Dict, Optional

logger = logging.getLogger("pytorch_distributed_tpu")

#: jax monitoring event recorded on every persistent-cache executable hit.
_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"
#: jax monitoring duration recorded around every XLA backend compile —
#: on a persistent-cache hit this wraps the (fast) disk load instead of
#: the compile, so it is THE honest "compile seconds" measure: it
#: collapses on a warm start while Python tracing/lowering time does not.
_BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_hit_counts: Dict[int, int] = {}
_compile_secs: Dict[int, float] = {}
_listener_lock = threading.Lock()
_listener_installed = False


def _reset_jax_cache_state() -> None:
    """Drop jax's lazily-initialized compilation-cache singleton so the
    NEXT compile re-reads ``jax_compilation_cache_dir``. jax binds the
    cache object on first use — without this, enabling (or re-pointing)
    the directory in a process that already compiled something is a
    silent no-op. Private jax API, so best-effort: on a jax that moved
    it, the worst case is the old behavior (first-compile binding)."""
    try:
        from jax._src.compilation_cache import reset_cache

        reset_cache()
    except Exception:
        pass


def enable_persistent_cache(cache_dir: str) -> str:
    """Point jax's persistent compilation cache at ``cache_dir``.

    Sets the three knobs that matter: the directory itself, and the two
    size/time floors dropped to "cache everything" (tiny CPU test
    programs compile in milliseconds and would otherwise never be
    written, making warm-start untestable off-TPU). Safe to call more
    than once; later calls re-point the directory (the cache singleton
    is reset so the change takes effect even after compiles have
    happened). Returns the dir.
    """
    import jax

    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    _reset_jax_cache_state()
    return cache_dir


def persistent_cache_dir() -> Optional[str]:
    """The active persistent-cache directory, or None when disabled."""
    import jax

    return getattr(jax.config, "jax_compilation_cache_dir", None)


def _install_listener() -> None:
    global _listener_installed
    with _listener_lock:
        if _listener_installed:
            return
        import jax.monitoring

        def _on_event(name: str, **kwargs) -> None:
            if name == _CACHE_HIT_EVENT:
                ident = threading.get_ident()
                with _listener_lock:
                    _hit_counts[ident] = _hit_counts.get(ident, 0) + 1

        def _on_duration(name: str, duration_secs: float, **kwargs) -> None:
            if name == _BACKEND_COMPILE_EVENT:
                ident = threading.get_ident()
                with _listener_lock:
                    _compile_secs[ident] = (
                        _compile_secs.get(ident, 0.0) + duration_secs
                    )

        # registered once per process and never cleared:
        # jax.monitoring.clear_event_listeners would nuke listeners we
        # don't own, so counters scope by thread + start offset instead
        jax.monitoring.register_event_listener(_on_event)
        jax.monitoring.register_event_duration_secs_listener(_on_duration)
        _listener_installed = True


class CacheHitCounter:
    """Context manager counting persistent-cache hits on THIS thread.

    ``with CacheHitCounter() as c: compile_something()`` then ``c.hits``.
    Per-thread scoping means a foreground warmup and a background warmup
    thread each see only their own compiles' hits.
    """

    def __enter__(self) -> "CacheHitCounter":
        _install_listener()
        self._ident = threading.get_ident()
        with _listener_lock:
            self._start = _hit_counts.get(self._ident, 0)
        self.hits = 0
        return self

    def __exit__(self, *exc) -> None:
        with _listener_lock:
            self.hits = _hit_counts.get(self._ident, 0) - self._start


class BackendCompileTimer:
    """Context manager accumulating XLA backend-compile seconds on THIS
    thread (``/jax/core/compile/backend_compile_duration`` events). On a
    persistent-cache hit the event wraps the disk load, so ``seconds``
    is exactly the quantity a warm start collapses."""

    def __enter__(self) -> "BackendCompileTimer":
        _install_listener()
        self._ident = threading.get_ident()
        with _listener_lock:
            self._start = _compile_secs.get(self._ident, 0.0)
        self.seconds = 0.0
        return self

    def __exit__(self, *exc) -> None:
        with _listener_lock:
            self.seconds = _compile_secs.get(self._ident, 0.0) - self._start


@contextlib.contextmanager
def attribute_compile(ledger):
    """Bracket a possibly-compiling call, splitting its wall time into
    the goodput ledger's ``compile`` (XLA backend compile / cache load —
    what a populated persistent cache eliminates) and ``trace`` (the
    Python tracing + lowering residual, which no disk cache can remove).
    ``ledger=None`` is a no-op bracket — call sites don't need a guard.
    """
    if ledger is None:
        yield
        return
    t0 = time.perf_counter()
    with BackendCompileTimer() as bc:
        yield
    wall = time.perf_counter() - t0
    compile_s = min(bc.seconds, wall)
    ledger.add("compile", compile_s)
    ledger.add("trace", max(wall - compile_s, 0.0))


# ---------------------------------------------------------------------------
# exported-program artifacts (jax.export)
# ---------------------------------------------------------------------------


def _safe_name(name: str) -> str:
    return "".join(c if (c.isalnum() or c in "._-") else "_" for c in name)


def artifact_path(cache_dir: str, name: str, fingerprint: str) -> str:
    """``<cache_dir>/aot/<name>-<fingerprint>.jaxexport`` — the
    fingerprint in the filename is the staleness gate: a different
    environment looks for a different file and simply misses."""
    return os.path.join(
        cache_dir, "aot", f"{_safe_name(name)}-{fingerprint}.jaxexport"
    )


def export_program(jit_fn, *avals):
    """Trace + lower ``jit_fn`` at ``avals`` into a serializable
    ``jax.export.Exported`` (no execution)."""
    from jax import export

    return export.export(jit_fn)(*avals)


def save_exported(cache_dir: str, name: str, fingerprint: str,
                  exported) -> str:
    """Serialize an ``Exported`` to its artifact path atomically
    (tmp + ``os.replace``: a concurrent reader sees the old file or the
    new one, never a torn write). Returns the path."""
    path = artifact_path(cache_dir, name, fingerprint)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    blob = exported.serialize()
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(blob)
    os.replace(tmp, path)
    return path


def load_exported(cache_dir: str, name: str, fingerprint: str):
    """Deserialize the artifact for (name, fingerprint), or ``None``.

    NEVER raises for a bad artifact: a missing file is a plain miss; a
    truncated/garbage/incompatible blob logs a warning naming the file
    and also returns ``None`` so the caller falls through to a fresh
    compile — a corrupt cache must cost a recompile, not a crash.
    """
    from jax import export

    path = artifact_path(cache_dir, name, fingerprint)
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except FileNotFoundError:
        return None
    except OSError as e:
        logger.warning("compilecache: unreadable artifact %s (%s); "
                       "falling through to fresh compile", path, e)
        return None
    try:
        return export.deserialize(blob)
    except Exception as e:  # any deserialize failure = corrupt/stale
        logger.warning("compilecache: corrupt/stale artifact %s (%s); "
                       "falling through to fresh compile", path, e)
        return None
