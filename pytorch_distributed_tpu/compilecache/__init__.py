"""Cold-start elimination: AOT program registry, persistent compile
cache, warmup runtime.

The telemetry runtime (telemetry/) classifies "compile" as a first-class
goodput loss; this package is the machinery that REDUCES it. A run
enumerates every compiled program it will need (``registry``), compiles
them ahead of traffic in priority order (``warmup``), and persists the
executables across process restarts (``aot``) — so a preempted-and-
resumed trainer or a freshly launched server reaches full speed with a
near-zero compile fraction, and the first request into each serving
bucket never eats a multi-second mid-traffic stall.

ANALYSIS.md "Cold start & compile cache" documents fingerprint keying,
corruption fall-through, and warmup ordering; ``scripts/warmup.py`` is
the CLI, ``scripts/bench_coldstart.py`` the cold-vs-warm proof.
"""

from pytorch_distributed_tpu.compilecache.aot import (
    CacheHitCounter,
    enable_persistent_cache,
    export_program,
    load_exported,
    persistent_cache_dir,
    save_exported,
)
from pytorch_distributed_tpu.compilecache.registry import (
    CoverageError,
    ProgramRegistry,
    ProgramSpec,
    aot_spec,
    jit_cache_size,
    run_fingerprint,
    serving_registry,
)
from pytorch_distributed_tpu.compilecache.warmup import WarmupRunner

__all__ = [
    "CacheHitCounter",
    "CoverageError",
    "ProgramRegistry",
    "ProgramSpec",
    "WarmupRunner",
    "aot_spec",
    "enable_persistent_cache",
    "export_program",
    "jit_cache_size",
    "load_exported",
    "persistent_cache_dir",
    "run_fingerprint",
    "save_exported",
    "serving_registry",
]
