"""Program registry: every compiled program a run needs, enumerated AHEAD
of execution.

The jit caches this repo guards (``analysis.guards.no_recompile``) answer
"did anything compile that shouldn't have?" *after* the fact. The registry
answers the dual question up front: given the configs a run already holds
(``TrainerConfig``/``LMTrainerConfig`` + model config + mesh, or a
``PagedEngine``'s slot/block/chunk geometry), list every program the run
will execute — train step, eval step(s), one chunk-prefill program per
(job-count, table-width) bucket, the decode tick — so that

- the **warmup runtime** (``compilecache.warmup``) can compile all of them
  before traffic / training starts, in priority order;
- the **coverage guard** (``ProgramRegistry.assert_covers``) can fail the
  run when a compiled program appears that no registry entry predicted —
  the registry provably covers what actually executes, the same
  build-real-trees-and-cross-check discipline as
  ``analysis/partition_coverage.py``;
- AOT artifacts (``compilecache.aot``) can be keyed by a stable
  **fingerprint** (jax/jaxlib version, backend, device kind, mesh shape,
  config extras) so a stale cache entry from a different environment is a
  miss, never a wrong program.

Specs carry a ``warm(execute)`` thunk — the strongest safe way to force
that program compiled. Serving programs can *execute* with inert inputs
(writes routed to the trash block; see ``PagedEngine.warm_chunk``), which
populates the jit call path itself: zero residual stall. Trainer steps
must not execute (a dummy step would corrupt training state), so their
thunks AOT-compile via ``jit(...).lower(...).compile()`` — which populates
the persistent compilation cache (``compilecache.aot``), making the real
first dispatch a disk hit instead of a fresh XLA compile.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Callable, Dict, Iterable, Iterator, List, Optional


class CoverageError(AssertionError):
    """A compiled program exists that no registry entry predicted."""


@dataclasses.dataclass(frozen=True)
class ProgramSpec:
    """One compiled program a run will need.

    ``warm(execute)`` forces the program compiled; ``execute=True`` permits
    running it with inert inputs (only safe before/outside traffic — the
    caller decides), ``execute=False`` restricts the thunk to AOT
    lower+compile (safe concurrently; populates the persistent cache but
    not the jit call path). Thunks that cannot execute safely ignore the
    flag and always AOT-compile.

    ``expect_entries`` is the number of live jit-cache entries this
    program may legitimately hold (1 for a steady-state step; the eval
    step of a non-drop_last loader may hold one per distinct batch shape);
    ``cache_probe`` returns the live count when the program is backed by a
    single jit callable (None when it is not observable that way).

    ``aot`` (ISSUE 8, cost cards) returns the program's ``jax.stages.
    Compiled`` — ``lower(...).compile()`` at the spec's real avals — so
    ``telemetry.costmodel`` can pull ``cost_analysis()`` /
    ``memory_analysis()`` for every enumerated program. Calling it pays
    a trace + compile (a disk hit under ``enable_persistent_cache``);
    card builders invoke it on demand, off the hot path.
    """

    name: str
    warm: Callable[[bool], None]
    priority: int = 1  # 0 = serve-critical: compiled first, foreground
    expect_entries: int = 1
    cache_probe: Optional[Callable[[], Optional[int]]] = None
    aot: Optional[Callable[[], object]] = None


class ProgramRegistry:
    """Ordered, name-unique collection of ``ProgramSpec`` entries plus the
    run fingerprint that keys their AOT artifacts."""

    def __init__(self, fingerprint: str = ""):
        self.fingerprint = fingerprint
        self._specs: Dict[str, ProgramSpec] = {}

    def add(self, spec: ProgramSpec) -> ProgramSpec:
        if spec.name in self._specs:
            raise ValueError(f"duplicate program spec {spec.name!r}")
        self._specs[spec.name] = spec
        return spec

    def __iter__(self) -> Iterator[ProgramSpec]:
        return iter(self._specs.values())

    def __len__(self) -> int:
        return len(self._specs)

    @property
    def names(self) -> List[str]:
        return list(self._specs)

    def predicts(self, name: str) -> bool:
        return name in self._specs

    # ---- the coverage guard ----

    def assert_covers(self, observed: Iterable[str]) -> None:
        """Fail if ``observed`` contains a program (or more live cache
        entries of one) that the registry did not predict.

        ``observed`` is the run's live program inventory — e.g.
        ``PagedEngine.compiled_program_names()`` or a trainer's
        ``compiled_program_names()`` — with one element per live jit-cache
        entry, so multiplicity is checked too: a predicted program that
        retraced past its ``expect_entries`` budget is a coverage failure
        (that's a recompile the registry's enumeration didn't account
        for), same spirit as ``no_recompile``'s cache-growth check.
        """
        counts: Dict[str, int] = {}
        for name in observed:
            counts[name] = counts.get(name, 0) + 1
        unpredicted = sorted(n for n in counts if n not in self._specs)
        if unpredicted:
            raise CoverageError(
                f"compiled program(s) outside the registry: {unpredicted} "
                f"— the registry enumerated {sorted(self._specs)}; either "
                "the enumeration is missing a bucket/config variant or "
                "the run compiled something it was never meant to"
            )
        over = sorted(
            f"{n} ({c} entries > {self._specs[n].expect_entries} expected)"
            for n, c in counts.items()
            if c > self._specs[n].expect_entries
        )
        if over:
            raise CoverageError(
                f"program(s) retraced past their registry budget: {over} "
                "— shape/dtype drift compiled extra variants the registry "
                "did not predict"
            )


# ---------------------------------------------------------------------------
# fingerprints
# ---------------------------------------------------------------------------


def run_fingerprint(mesh=None, extra: Iterable = ()) -> str:
    """Stable hex key for the environment a compiled artifact is valid in.

    Folds in: jax + jaxlib versions, backend platform and device kind,
    device count, mesh axis names/sizes, and any caller extras (config
    reprs, dtypes, flags). Two runs agree on the fingerprint iff their
    artifacts are interchangeable; everything else is a cache miss by
    construction — stale artifacts can never load as wrong programs.
    """
    import jax
    import jaxlib

    parts = [
        f"jax={jax.__version__}",
        f"jaxlib={jaxlib.__version__}",
    ]
    try:
        devices = jax.devices()
        parts.append(f"backend={jax.default_backend()}")
        parts.append(f"device_kind={devices[0].device_kind}")
        parts.append(f"n_devices={len(devices)}")
    except Exception:  # uninitialized backend: version-only fingerprint
        parts.append("backend=uninitialized")
    if mesh is not None:
        parts.append(f"mesh={tuple(sorted(dict(mesh.shape).items()))}")
    for item in extra:
        parts.append(repr(item))
    return hashlib.sha256("|".join(parts).encode()).hexdigest()[:16]


# ---------------------------------------------------------------------------
# builders
# ---------------------------------------------------------------------------


def jit_cache_size(fn) -> Optional[int]:
    """Live jit-cache entry count of a ``jax.jit`` callable (None when the
    object carries no probe) — the same probe ``no_recompile`` watches."""
    probe = getattr(fn, "_cache_size", None)
    if callable(probe):
        try:
            return int(probe())
        except Exception:
            return None
    return None


def aot_spec(
    name: str,
    jit_fn,
    avals_thunk: Callable[[], tuple],
    *,
    priority: int = 1,
    expect_entries: int = 1,
) -> ProgramSpec:
    """Spec for a program that must NOT execute during warmup (trainer
    steps): ``warm`` AOT-compiles via ``lower(*avals).compile()``, which
    feeds the persistent compilation cache so the real first call is a
    disk hit. ``avals_thunk`` is lazy — avals (ShapeDtypeStructs carrying
    the REAL shardings, or live arrays) are built only if warmup runs."""

    def warm(execute: bool) -> None:  # execute ignored: AOT only
        jit_fn.lower(*avals_thunk()).compile()

    return ProgramSpec(
        name=name,
        warm=warm,
        priority=priority,
        expect_entries=expect_entries,
        cache_probe=lambda: jit_cache_size(jit_fn),
        aot=lambda: jit_fn.lower(*avals_thunk()).compile(),
    )


def serving_registry(engine, extra: Iterable = ()) -> ProgramRegistry:
    """Enumerate every program a ``PagedEngine`` can compile: one
    chunk-prefill program per (padded job count, table-slice width)
    bucket — the same pow2 bucketing ``run_chunks`` applies, read from
    ``engine.chunk_buckets()`` so registry and engine cannot drift — plus
    the shared decode tick.

    Priority order: the decode tick and the smallest prefill bucket are
    priority 0 (serve-critical — with them compiled the scheduler can
    admit and stream its first request), every larger bucket priority 1
    so a warmup runner can finish them in the background while serving
    has already started.
    """
    reg = ProgramRegistry(
        run_fingerprint(
            mesh=engine.mesh,
            extra=(
                engine.config,
                f"n_slots={engine.n_slots}",
                f"block_len={engine.block_len}",
                f"chunk={engine.chunk}",
                f"temperature={engine.temperature}",
                f"top_k={engine.top_k}",
                # program-shape variants (ISSUE 10): the gather spelling
                # rides in via the config repr (gather_impl field); the
                # pool quantization changes every program's cache avals,
                # so artifacts must not be interchangeable across it
                f"kv_dtype={getattr(engine, 'kv_dtype', None)}",
                f"prefix_cache={getattr(engine, 'prefix_cache', False)}",
                *extra,
            ),
        )
    )
    reg.add(ProgramSpec(
        name=engine.DECODE_PROGRAM,
        warm=lambda execute: engine.warm_decode(execute=execute),
        priority=0,
        aot=lambda: engine.warm_decode(execute=False),
    ))
    buckets = engine.chunk_buckets()
    smallest = min(buckets) if buckets else None
    for k_pad, wp in buckets:
        reg.add(ProgramSpec(
            name=engine.chunk_program_name(k_pad, wp),
            warm=(lambda execute, k=k_pad, w=wp:
                  engine.warm_chunk(k, w, execute=execute)),
            priority=0 if (k_pad, wp) == smallest else 1,
            aot=(lambda k=k_pad, w=wp:
                 engine.warm_chunk(k, w, execute=False)),
        ))
    # fleet disaggregation handoff programs (empty unless the engine was
    # built with handoff=True — read from the engine for the same
    # no-drift reason as chunk_buckets)
    for n_pad in engine.handoff_buckets():
        reg.add(ProgramSpec(
            name=engine.export_program_name(n_pad),
            warm=(lambda execute, n=n_pad:
                  engine.warm_export(n, execute=execute)),
            aot=lambda n=n_pad: engine.warm_export(n, execute=False),
        ))
        reg.add(ProgramSpec(
            name=engine.import_program_name(n_pad),
            warm=(lambda execute, n=n_pad:
                  engine.warm_import(n, execute=execute)),
            aot=lambda n=n_pad: engine.warm_import(n, execute=False),
        ))
    # copy-on-write block duplication (round 17 prefix sharing; absent
    # unless the engine was built with prefix_cache=True — same gating
    # story as handoff/swap). ONE program: a block copy has no chain-
    # length bucketing, and only the full-cover hit path runs it.
    if getattr(engine, "prefix_cache", False):
        reg.add(ProgramSpec(
            name=engine.BLOCK_COPY_PROGRAM,
            warm=lambda execute: engine.warm_block_copy(execute=execute),
            aot=lambda: engine.warm_block_copy(execute=False),
        ))
    # host-offload swap programs (round 13 pressure tier; empty unless
    # the engine was built with swap=True — read from the engine so the
    # registry and the swap path's lazy bucketing cannot drift)
    for n_pad in engine.swap_buckets():
        reg.add(ProgramSpec(
            name=engine.swap_out_program_name(n_pad),
            warm=(lambda execute, n=n_pad:
                  engine.warm_swap_out(n, execute=execute)),
            aot=lambda n=n_pad: engine.warm_swap_out(n, execute=False),
        ))
        reg.add(ProgramSpec(
            name=engine.swap_in_program_name(n_pad),
            warm=(lambda execute, n=n_pad:
                  engine.warm_swap_in(n, execute=execute)),
            aot=lambda n=n_pad: engine.warm_swap_in(n, execute=False),
        ))
    return reg
