"""Serving fleet layer (round 10 tentpole): seeded traces, SLO gate
routing, session affinity + spill + shed, graceful drain (zero leaked
blocks), disaggregated prefill/decode token identity, KV handoff
exactness, fleet-wide registry coverage, and the telemetry fleet
section."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.fleet import (
    FleetRouter,
    SLOConfig,
    SLOGate,
    clamp_trace,
    generate_trace,
    load_trace,
    prompt_for,
    recommend_replicas,
    replay_trace,
    save_trace,
)
from pytorch_distributed_tpu.models.generate import generate
from pytorch_distributed_tpu.models.transformer import (
    TransformerLM,
    tiny_config,
)
from pytorch_distributed_tpu.serving import PagedEngine, Scheduler
from pytorch_distributed_tpu.serving.engine import ChunkJob


def setup(max_seq_len=64, **over):
    cfg = tiny_config(attention="dense", max_seq_len=max_seq_len, **over)
    params = TransformerLM(cfg).init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return cfg, params


SCHED_KW = dict(n_slots=3, block_len=8, prefill_chunk=16,
                admit_per_step=4)


# ---------------------------------------------------------------------------
# traffic traces (pure host logic — fast)
# ---------------------------------------------------------------------------


def test_trace_generator_seeded_bursty_heavy_tail():
    kw = dict(seed=3, duration_s=300.0, base_rate=1.0,
              burst_rate_mult=6.0, burst_every_s=30.0, burst_len_s=3.0,
              prompt_median=24, prompt_sigma=0.9, prompt_max=512,
              max_new_median=8)
    a = generate_trace(**kw)
    assert a == generate_trace(**kw)  # deterministic per seed
    assert a != generate_trace(**{**kw, "seed": 4})
    times = np.array([r.t for r in a])
    assert (np.diff(times) >= 0).all() and times[-1] < 300.0
    # bursts: arrival density inside burst windows well above outside
    in_burst = (times % 30.0) < 3.0
    rate_in = in_burst.sum() / (300 / 30 * 3)
    rate_out = (~in_burst).sum() / (300 - 300 / 30 * 3)
    assert rate_in > 2.5 * rate_out
    # heavy tail: p99 prompt length is a multiple of the median
    lens = np.array([r.prompt_len for r in a])
    assert np.percentile(lens, 99) > 3 * np.median(lens)
    # sessions repeat (affinity has something to bite on)
    sessions = [r.session for r in a]
    assert len(set(sessions)) < len(sessions)


def test_trace_jsonl_roundtrip_and_clamp(tmp_path):
    trace = generate_trace(seed=1, duration_s=20.0, base_rate=2.0,
                           prompt_max=None)
    path = str(tmp_path / "trace.jsonl")
    save_trace(path, trace, seed=1)
    loaded = load_trace(path)
    assert [(r.rid, r.session, r.prompt_len, r.max_new) for r in loaded] \
        == [(r.rid, r.session, r.prompt_len, r.max_new) for r in trace]
    np.testing.assert_allclose([r.t for r in loaded],
                               [r.t for r in trace], atol=1e-6)
    header = json.loads(open(path).readline())
    assert header["kind"] == "trace_header" and header["seed"] == 1
    # clamp fits any trace to a serving config's admission contract
    clamped = clamp_trace(trace, max_seq_len=64, chunk=16)
    for r in clamped:
        padded = -(-r.prompt_len // 16) * 16
        assert padded <= 64 and r.prompt_len + r.max_new <= 64
        assert r.prompt_len >= 1 and r.max_new >= 1
    # arrival times and sessions (the traffic shape) survive clamping
    assert [r.t for r in clamped] == [r.t for r in trace]


def test_replay_trace_step_mapping():
    from pytorch_distributed_tpu.fleet import TraceRequest

    trace = [TraceRequest(i, t, 0, 4, 2)
             for i, t in enumerate([0.0, 0.5, 1.0, 2.2])]
    submitted, ticks = [], []
    replay_trace(
        trace,
        lambda r: submitted.append((len(ticks), r.rid)),
        lambda: ticks.append(None),
        lambda: len(submitted) == 4,
        tick_s=1.0,
    )
    # arrival t maps to the first tick k with t <= k*tick_s
    assert submitted == [(0, 0), (1, 1), (1, 2), (3, 3)]


# ---------------------------------------------------------------------------
# SLO gate + autoscaler (pure policy — fast)
# ---------------------------------------------------------------------------


def _m(queue=0, occ=0.0, ttft_p95=0.0, qw_p95=0.0, draining=False,
       occ_mean=0.5, goodput=0.9):
    return {"queue_depth": queue, "occupancy": occ,
            "ttft_p95_s": ttft_p95, "queue_wait_p95_s": qw_p95,
            "draining": draining, "occupancy_mean": occ_mean,
            "goodput_frac": goodput}


def test_slo_gate_routing_decisions():
    gate = SLOGate(SLOConfig(ttft_p95_ms=100.0, spill_queue_depth=2,
                             shed_queue_depth=4))
    # affinity replica cool -> admit there, even if others are cooler
    d = gate.route({0: _m(queue=1), 1: _m(queue=0)}, preferred=0)
    assert d == ("admit", 0, "")
    # affinity replica hot (queue) -> spill to the cool one, reason kept
    d = gate.route({0: _m(queue=2), 1: _m(queue=0)}, preferred=0)
    assert d.action == "spill" and d.replica == 1
    assert d.reason == "queue_depth"
    # live TTFT p95 past the SLO is a hot signal too
    d = gate.route({0: _m(ttft_p95=0.2), 1: _m()}, preferred=0)
    assert d.action == "spill" and d.reason == "slo_ttft_p95"
    # no session: least-loaded cool replica, plain admit
    d = gate.route({0: _m(queue=1), 1: _m(queue=0)}, preferred=None)
    assert d == ("admit", 1, "")
    # every replica hot but none past the shed bound: queue (admit) on
    # the least-loaded — backpressure, not failure
    d = gate.route({0: _m(queue=3), 1: _m(queue=2)}, preferred=None)
    assert d.action == "admit" and d.replica == 1
    # every replica past the shed bound: explicit reject with reason
    d = gate.route({0: _m(queue=4), 1: _m(queue=5)}, preferred=0)
    assert d.action == "shed" and d.replica == -1
    assert d.reason == "queue_depth"
    # draining replicas are routed around
    d = gate.route({0: _m(draining=True), 1: _m()}, preferred=0)
    assert d.action == "spill" and d.replica == 1
    assert d.reason == "draining"


def test_autoscaler_recommendation():
    gate = SLOGate(SLOConfig(spill_queue_depth=2, shed_queue_depth=8))
    # every replica hot -> scale up
    assert recommend_replicas(2, [_m(queue=3), _m(queue=2)], gate) == 3
    # provably idle -> scale down (but never below 1)
    idle = _m(queue=0, occ_mean=0.05)
    assert recommend_replicas(2, [idle, idle], gate) == 1
    assert recommend_replicas(1, [idle], gate) == 1
    # compile-bound "idle" is warming up, not idle -> hold
    warming = _m(queue=0, occ_mean=0.05, goodput=0.2)
    assert recommend_replicas(2, [warming, warming], gate) == 2
    # mixed load -> hold
    assert recommend_replicas(2, [_m(queue=3), _m(queue=0)], gate) == 2


# ---------------------------------------------------------------------------
# router: affinity, spill, shed
# ---------------------------------------------------------------------------


def test_router_session_affinity():
    cfg, params = setup()
    r = FleetRouter(cfg, params, n_replicas=2, **SCHED_KW)
    prompt = np.arange(1, 10, dtype=np.int32)
    rids = []
    for _ in range(3):
        rids.append(r.submit(prompt, 2, session=7))
        r.drain()  # fully drain between submits: no load pressure
    home = r.placement[rids[0]]
    assert all(r.placement[rid] == home for rid in rids)
    # a different session lands by load, independent of session 7's home
    assert r._affinity == {7: home}


def test_router_spill_on_hot_replica():
    cfg, params = setup()
    r = FleetRouter(cfg, params, n_replicas=2,
                    slo=SLOConfig(spill_queue_depth=2,
                                  shed_queue_depth=64),
                    **SCHED_KW)
    prompt = np.arange(1, 10, dtype=np.int32)
    # no ticks between submits: session 5's home replica queues up to
    # the spill bound, then the gate routes around it
    rids = [r.submit(prompt, 2, session=5) for _ in range(6)]
    home = r.placement[rids[0]]
    placements = [r.placement[rid] for rid in rids]
    assert placements.count(home) >= 2  # queued up to the bound at home
    assert 1 - home in placements      # then spilled to the other
    assert r._spilled > 0
    assert r._affinity[5] == home      # affinity sticks through spills
    out = r.drain()
    assert len(out) == 6 and not r.rejected


def test_router_shed_under_burst_only_when_slo_violated():
    cfg, params = setup()
    slo = SLOConfig(spill_queue_depth=1, shed_queue_depth=2)
    prompt = np.arange(1, 14, dtype=np.int32)
    # gentle load: drain between submits -> zero rejects
    r = FleetRouter(cfg, params, n_replicas=1, slo=slo, **SCHED_KW)
    for _ in range(4):
        r.submit(prompt, 2, session=1)
        r.drain()
    assert not r.rejected
    # burst: everything at once -> queue passes the shed bound and the
    # overflow is explicitly rejected with a reason
    r = FleetRouter(cfg, params, n_replicas=1, slo=slo, **SCHED_KW)
    rids = [r.submit(prompt, 2, session=1) for _ in range(8)]
    assert r.rejected, "burst past the shed bound must shed"
    assert all(reason == "queue_depth" for reason in r.rejected.values())
    out = r.drain()
    served = [rid for rid in rids if rid not in r.rejected]
    assert set(out) == set(served)  # shed rids never stream tokens
    m = r.metrics()
    assert m["shed"] == len(r.rejected) and m["shed_rate"] > 0


# ---------------------------------------------------------------------------
# graceful drain (scale-down primitive)
# ---------------------------------------------------------------------------


def test_graceful_drain_zero_leaked_blocks():
    cfg, params = setup()
    s = Scheduler(cfg, params, **SCHED_KW)
    prompt = np.arange(1, 20, dtype=np.int32)
    rids = [s.submit(prompt, 3) for _ in range(6)]
    pre: dict = {}
    for _ in range(2):  # some in flight, some still queued
        for rid, tok in s.step():
            pre.setdefault(rid, []).append(tok)
    in_flight = {r.rid for r in s.resident.values()}
    assert in_flight and len(s.queue) > 0
    produced, requeued = s.drain_graceful()
    # in-flight requests ran to completion; queued ones came back intact
    assert set(produced) == in_flight
    assert all(
        len(pre.get(rid, [])) + len(toks) == 3
        for rid, toks in produced.items()
    )
    assert {r.rid for r in requeued} == set(rids) - in_flight
    # zero leaked pool blocks, and the replica refuses new work
    assert s.engine.allocator.in_use == 0
    assert not s.resident and s.draining
    with pytest.raises(RuntimeError, match="draining"):
        s.submit(prompt, 2)
    s.engine.release_all()  # teardown is a no-op by then
    assert s.engine.allocator.in_use == 0


# ---------------------------------------------------------------------------
# disaggregated prefill/decode
# ---------------------------------------------------------------------------


def test_handoff_export_import_blocks_exact():
    cfg, params = setup()
    src = PagedEngine(cfg, params, 2, block_len=8, prefill_chunk=16,
                      handoff=True)
    dst = PagedEngine(cfg, params, 3, block_len=8, prefill_chunk=16,
                      handoff=True)
    prompt = np.arange(1, 14, dtype=np.int32)  # 13 tokens, chunk 16
    assert src.admit(0, len(prompt), 3)
    tokens = np.zeros((16,), np.int32)
    tokens[:13] = prompt
    src.run_chunks([ChunkJob(slot=0, tokens=tokens, start=0,
                             is_last=True, last_idx=12)])
    export = src.export_chain(0)
    assert export.n_blocks == len(src.allocator.chain(0))
    # occupy dst slot 0 first so the imported chain lands elsewhere —
    # block ids must NOT need to agree between pools
    assert dst.admit(0, 8, 2)
    assert dst.import_chain(1, export)
    src_chain = src.allocator.chain(0)
    dst_chain = dst.allocator.chain(1)
    src_leaves = jax.tree.leaves(src.cache)
    dst_leaves = jax.tree.leaves(dst.cache)
    for s_leaf, d_leaf in zip(src_leaves, dst_leaves):
        np.testing.assert_array_equal(
            np.asarray(s_leaf[np.asarray(src_chain)]),
            np.asarray(d_leaf[np.asarray(dst_chain)]),
        )
    np.testing.assert_array_equal(np.asarray(src.logits[0]),
                                  np.asarray(dst.logits[1]))
    # table remap points the dst slot at its own chain
    assert list(dst.tables[1, :len(dst_chain)]) == dst_chain
    # a full pool is a deterministic False, state unchanged
    assert dst.admit(2, 60, 2) or True  # fill what's left
    before = dst.allocator.in_use
    third = PagedEngine(cfg, params, 1, n_blocks=2, block_len=8,
                        prefill_chunk=16, handoff=True)
    assert not third.import_chain(0, export)  # 1 free block < chain
    assert third.allocator.in_use == 0
    assert dst.allocator.in_use == before


def test_handoff_requires_flag():
    cfg, params = setup()
    eng = PagedEngine(cfg, params, 2, block_len=8, prefill_chunk=16)
    eng.admit(0, 9, 2)
    with pytest.raises(RuntimeError, match="handoff=True"):
        eng.export_chain(0)
    assert eng.handoff_buckets() == []  # registry predicts none


@pytest.mark.slow
def test_disagg_token_identical_to_colocated():
    cfg, params = setup()
    rng = np.random.default_rng(0)
    # lengths straddling chunk boundaries, incl. an exact multiple
    lens = [5, 16, 23, 31, 9, 17]
    prompts = [rng.integers(1, cfg.vocab_size, l).astype(np.int32)
               for l in lens]
    ref = Scheduler(cfg, params, **SCHED_KW)
    for p in prompts:
        ref.submit(p, 5)
    want = ref.drain()
    # disaggregated: 1 prefill + 1 decode replica, role-sized decode,
    # handoff budget exercised
    r = FleetRouter(cfg, params, n_replicas=2, disaggregate=True,
                    decode_slots=4, handoffs_per_tick=1, **SCHED_KW)
    for p in prompts:
        r.submit(p, 5)
    got = r.drain()
    assert set(got) == set(want)
    for rid in want:
        assert got[rid] == want[rid], f"stream {rid} diverged"
    m = r.metrics()
    assert m["handoffs"] == len(prompts)
    # every pool block freed once everything retired
    for s in r.replicas:
        assert s.engine.allocator.in_use == 0
    # greedy decode against the plain generate() reference too
    full = generate(
        cfg, params, jnp.asarray(prompts[0])[None, :], jax.random.key(1),
        max_new_tokens=5, temperature=0.0,
    )
    np.testing.assert_array_equal(
        np.asarray(full)[0, len(prompts[0]):], got[0]
    )


def test_fleet_registry_coverage_across_replicas():
    from pytorch_distributed_tpu.compilecache import CoverageError

    cfg, params = setup()
    r = FleetRouter(cfg, params, n_replicas=2, disaggregate=True,
                    **SCHED_KW)
    prompt = np.arange(1, 20, dtype=np.int32)
    for _ in range(3):
        r.submit(prompt, 3, session=2)
    r.drain()
    # every replica compiled something, incl. the handoff programs
    names = [n for s in r.replicas
             for n in s.engine.compiled_program_names()]
    assert any(n.startswith("kv_export") for n in names)
    assert any(n.startswith("kv_import") for n in names)
    r.assert_registry_covers()  # fleet-wide coverage guard green
    # the guard has teeth: a rogue program fails it
    regs = r.registries()
    with pytest.raises(CoverageError, match="rogue"):
        regs[0].assert_covers(["rogue"])


# ---------------------------------------------------------------------------
# JSONL schema + telemetry report fleet section
# ---------------------------------------------------------------------------


def test_fleet_jsonl_schema_and_report_section(tmp_path):
    import os
    import subprocess
    import sys

    from pytorch_distributed_tpu.utils.profiling import MetricsLogger

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    report = os.path.join(repo, "scripts", "telemetry_report.py")
    cfg, params = setup()
    path = str(tmp_path / "fleet.jsonl")
    with MetricsLogger(path) as mlog:
        r = FleetRouter(cfg, params, n_replicas=2,
                        slo=SLOConfig(spill_queue_depth=1,
                                      shed_queue_depth=2),
                        metrics_log=mlog, **SCHED_KW)
        prompt = np.arange(1, 18, dtype=np.int32)
        for i in range(8):
            r.submit(prompt, 2, session=i % 3)
        r.drain()
        r.log_summary()
    assert r.rejected and r._spilled  # the run exercised shed AND spill
    records = [json.loads(line) for line in open(path)]
    reqs = [rec for rec in records if rec.get("kind") == "request"]
    served = [rec for rec in reqs if not rec["rejected"]]
    shed = [rec for rec in reqs if rec["rejected"]]
    assert served and shed
    for rec in served:
        assert rec["replica_id"] in (0, 1)
        assert "ttft_steps" in rec and rec["ttft_steps"] >= 1
        assert "session" in rec and "spilled" in rec
    for rec in shed:
        assert rec["reject_reason"] == "queue_depth"
        assert rec["new_tokens"] == 0
    assert any(rec.get("kind") == "fleet_summary" for rec in records)
    # the report renders the fleet section and honors --require fleet
    proc = subprocess.run(
        [sys.executable, report, path, "--json", "--require", "fleet"],
        capture_output=True, text=True, cwd=repo,
    )
    assert proc.returncode == 0, proc.stderr
    assert "== fleet ==" in proc.stdout
    flat = json.loads(proc.stdout.strip().splitlines()[-1])
    assert flat["fleet_replicas"] == 2
    assert flat["fleet_shed_rate"] > 0
    assert flat["fleet_spill_rate"] > 0
    assert "fleet_r0_ttft_p95_ms" in flat
    # --require fleet fails on a fleet-less stream
    lonely = str(tmp_path / "lonely.jsonl")
    with open(lonely, "w") as f:
        f.write(json.dumps({"kind": "train", "step": 1}) + "\n")
    proc = subprocess.run(
        [sys.executable, report, lonely, "--require", "fleet"],
        capture_output=True, text=True, cwd=repo,
    )
    assert proc.returncode != 0
