"""GPipe pipeline executor: staged == sequential (values and grads)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from pytorch_distributed_tpu.parallel import make_mesh
from pytorch_distributed_tpu.parallel.mesh import shard_map
from pytorch_distributed_tpu.parallel.pipeline import gpipe, last_stage_value

D = 16
STAGES = 4


def stage_fn(p, x, mb_idx=0):
    return jax.nn.relu(x @ p["w"] + p["b"])


def make_params(rng):
    return {
        "w": jnp.asarray(rng.normal(size=(STAGES, D, D)) * 0.5, jnp.float32),
        "b": jnp.asarray(rng.normal(size=(STAGES, D)) * 0.1, jnp.float32),
    }


def sequential(params, x):
    for s in range(STAGES):
        x = stage_fn(jax.tree.map(lambda a: a[s], params), x)
    return x


def pipelined(mesh, n_micro):
    param_specs = {"w": P("model"), "b": P("model")}

    def fn(params, x):
        stage_params = jax.tree.map(lambda a: a[0], params)
        mb = x.reshape(n_micro, -1, D)
        out = gpipe(stage_fn, stage_params, mb, axis="model")
        return last_stage_value(out).reshape(x.shape)

    return jax.jit(
        shard_map(
            fn,
            mesh=mesh,
            in_specs=(param_specs, P("data")),
            out_specs=P("data"),
            check_vma=False,
        )
    )


@pytest.mark.parametrize("n_micro", [4, 8])
def test_gpipe_matches_sequential(devices8, n_micro):
    mesh = make_mesh(devices8, data_parallel=2, model_parallel=4)
    rng = np.random.default_rng(0)
    params = make_params(rng)
    x = jnp.asarray(rng.normal(size=(2 * n_micro * 4, D)), jnp.float32)

    ref = sequential(params, x)
    fn = pipelined(mesh, n_micro)
    out = fn(
        jax.device_put(params, jax.tree.map(lambda s: NamedSharding(mesh, s),
                                            {"w": P("model"), "b": P("model")})),
        jax.device_put(x, NamedSharding(mesh, P("data"))),
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-6)


def test_gpipe_grads_match_sequential(devices8):
    mesh = make_mesh(devices8, data_parallel=2, model_parallel=4)
    rng = np.random.default_rng(1)
    params = make_params(rng)
    x = jnp.asarray(rng.normal(size=(16, D)), jnp.float32)
    fn = pipelined(mesh, n_micro=4)

    def loss_pipe(params, x):
        return jnp.sum(fn(params, x) ** 2)

    def loss_seq(params, x):
        return jnp.sum(sequential(params, x) ** 2)

    g_pipe = jax.grad(loss_pipe)(
        jax.device_put(params, jax.tree.map(lambda s: NamedSharding(mesh, s),
                                            {"w": P("model"), "b": P("model")})),
        jax.device_put(x, NamedSharding(mesh, P("data"))),
    )
    g_seq = jax.grad(loss_seq)(params, x)
    for a, b in zip(jax.tree.leaves(g_pipe), jax.tree.leaves(g_seq)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)


def test_gpipe_accepts_two_arg_stage_fn(devices8):
    """ADVICE r3 low: the pre-r3 ``(stage_params, x)`` stage_fn contract
    still works — the executor detects the arity once at trace time and
    omits mb_idx."""
    mesh = make_mesh(devices8, data_parallel=2, model_parallel=4)
    rng = np.random.default_rng(2)
    params = make_params(rng)
    x = jnp.asarray(rng.normal(size=(16, D)), jnp.float32)

    def old_stage_fn(p, x):  # strictly 2-arg
        return jax.nn.relu(x @ p["w"] + p["b"])

    def fn(params, x):
        stage_params = jax.tree.map(lambda a: a[0], params)
        mb = x.reshape(4, -1, D)
        out = gpipe(old_stage_fn, stage_params, mb, axis="model")
        return last_stage_value(out).reshape(x.shape)

    jitted = jax.jit(shard_map(
        fn, mesh=mesh,
        in_specs=({"w": P("model"), "b": P("model")}, P("data")),
        out_specs=P("data"), check_vma=False,
    ))
    out = jitted(
        jax.device_put(params, jax.tree.map(
            lambda s: NamedSharding(mesh, s),
            {"w": P("model"), "b": P("model")})),
        jax.device_put(x, NamedSharding(mesh, P("data"))),
    )
    ref = sequential(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_gpipe_defaulted_third_arg_not_misbound(devices8):
    """A legacy stage_fn with an unrelated defaulted third parameter
    (``train=False``) must NOT receive the traced mb_idx in it."""
    mesh = make_mesh(devices8, data_parallel=2, model_parallel=4)
    rng = np.random.default_rng(3)
    params = make_params(rng)
    x = jnp.asarray(rng.normal(size=(16, D)), jnp.float32)

    def legacy_fn(p, x, train=False):
        assert train is False  # a tracer here would mean misbinding
        return jax.nn.relu(x @ p["w"] + p["b"])

    def fn(params, x):
        stage_params = jax.tree.map(lambda a: a[0], params)
        out = gpipe(legacy_fn, stage_params, x.reshape(4, -1, D), axis="model")
        return last_stage_value(out).reshape(x.shape)

    jitted = jax.jit(shard_map(
        fn, mesh=mesh,
        in_specs=({"w": P("model"), "b": P("model")}, P("data")),
        out_specs=P("data"), check_vma=False,
    ))
    out = jitted(
        jax.device_put(params, jax.tree.map(
            lambda s: NamedSharding(mesh, s),
            {"w": P("model"), "b": P("model")})),
        jax.device_put(x, NamedSharding(mesh, P("data"))),
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(sequential(params, x)),
                               rtol=1e-5, atol=1e-6)
