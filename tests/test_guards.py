"""analysis.guards: the runtime companion catches what the AST cannot —
recompiles and implicit host transfers after warmup — and the LM train
step runs 5 guarded steps clean (the acceptance demo)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from pytorch_distributed_tpu.analysis import GuardViolation, no_recompile


def test_rejects_unjitted_function():
    with pytest.raises(TypeError, match="jit-compiled"):
        no_recompile(lambda x: x + 1)


def test_steady_state_passes_and_counts():
    step = no_recompile(jax.jit(lambda x: x * 2), warmup_steps=2)
    x = jnp.ones((4,))
    for _ in range(5):
        x = step(x)
    assert step.stats.calls == 5
    assert step.stats.cache_size == 1
    assert step.stats.recompiles_after_warmup == 0


def test_recompile_after_warmup_raises():
    step = no_recompile(jax.jit(lambda x: x * 2), warmup_steps=2)
    step(jnp.ones((4,)))
    step(jnp.ones((4,)))
    with pytest.raises(GuardViolation, match="cache grew"):
        step(jnp.ones((5,)))  # new shape -> retrace after warmup


def test_shape_churn_during_warmup_is_forgiven():
    # warmup absorbs the first trace AND a second-shape trace (donation /
    # layout settling); only growth after the window trips
    step = no_recompile(jax.jit(lambda x: x + 1), warmup_steps=2)
    step(jnp.ones((4,)))
    step(jnp.ones((8,)))  # second compile, still warmup
    step(jnp.ones((8,)))
    assert step.stats.cache_size == 2


def test_implicit_host_transfer_after_warmup_raises():
    step = no_recompile(jax.jit(lambda x: x + 1), warmup_steps=1)
    step(jnp.ones((4,)))
    step(jnp.ones((4,)))
    with pytest.raises(GuardViolation, match="host transfer"):
        step(np.ones((4,), np.float32))  # numpy batch sneaks in H2D


def test_lm_train_step_5_guarded_steps(devices8):
    """Acceptance demo: the real LM train step, wrapped, 5 steps on CPU —
    no recompiles, no implicit transfers."""
    from pytorch_distributed_tpu.models.transformer import tiny_config
    from pytorch_distributed_tpu.ops.optim import sgd_with_weight_decay
    from pytorch_distributed_tpu.parallel import make_mesh, replicated_sharding
    from pytorch_distributed_tpu.train.lm import (
        create_lm_state,
        make_lm_train_step,
        shift_labels,
    )

    mesh = make_mesh(devices8[:4], data_parallel=4)
    cfg = tiny_config()
    state = create_lm_state(
        cfg, sgd_with_weight_decay(0.1, momentum=0.9, weight_decay=0.0),
        jax.random.key(0), init_len=8,
    )
    state = jax.device_put(state, replicated_sharding(mesh))
    step = no_recompile(make_lm_train_step(mesh, config=cfg), warmup_steps=2)

    sharding = NamedSharding(mesh, P("data", "seq"))
    rng = np.random.default_rng(0)
    losses = []
    for i in range(5):
        tokens = rng.integers(1, 128, (4, 32)).astype(np.int32)
        labels, weights = shift_labels(tokens)
        batch = {
            "tokens": jax.device_put(tokens, sharding),
            "labels": jax.device_put(labels, sharding),
            "weights": jax.device_put(weights, sharding),
        }
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert step.stats.calls == 5
    assert step.stats.recompiles_after_warmup == 0
    assert np.isfinite(losses).all()
    assert int(jax.device_get(state.step)) == 5
