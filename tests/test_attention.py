"""Attention kernel math: blockwise == dense, gradients included."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow

from pytorch_distributed_tpu.ops.attention import (
    blockwise_attention,
    dense_attention,
)


def qkv(b=2, l=32, h=3, d=8, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(b, l, h, d)), dtype)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("block_size", [8, 16, 32])
def test_blockwise_matches_dense(causal, block_size):
    q, k, v = qkv()
    ref = dense_attention(q, k, v, causal=causal)
    out = blockwise_attention(q, k, v, causal=causal, block_size=block_size)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_blockwise_grads_match_dense():
    q, k, v = qkv()

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

    def loss_block(q, k, v):
        return jnp.sum(blockwise_attention(q, k, v, causal=True, block_size=8) ** 2)

    g_ref = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    g_blk = jax.grad(loss_block, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_blk):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)


def test_causal_first_token_attends_self_only():
    q, k, v = qkv(b=1, l=4, h=1, d=4)
    out = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out[0, 0, 0]), np.asarray(v[0, 0, 0]), rtol=1e-5, atol=1e-6
    )


def test_offsets_reproduce_causal_tiling():
    """Computing causal attention row-block by row-block with explicit
    offsets equals the full causal result — the property ring attention
    relies on."""
    q, k, v = qkv(b=1, l=16, h=2, d=8)
    ref = dense_attention(q, k, v, causal=True)
    half = 8
    top = blockwise_attention(
        q[:, :half], k, v, causal=True, block_size=8, q_offset=0, k_offset=0
    )
    bot = blockwise_attention(
        q[:, half:], k, v, causal=True, block_size=8, q_offset=half, k_offset=0
    )
    out = jnp.concatenate([top, bot], axis=1)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_bf16_inputs_fp32_softmax():
    q, k, v = qkv(dtype=jnp.bfloat16)
    ref = dense_attention(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32),
        causal=True,
    )
    out = blockwise_attention(q, k, v, causal=True, block_size=8)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), rtol=0.05, atol=0.05
    )


def test_fully_masked_rows_are_zero():
    """A query block whose keys are all in the future must produce zeros
    (the documented finalize() contract), not uniform mean(V)."""
    q, k, v = qkv(b=1, l=8, h=1, d=4)
    out_blk = blockwise_attention(q, k, v, causal=True, block_size=8,
                                  q_offset=0, k_offset=100)
    out_dense = dense_attention(q, k, v, causal=True, q_offset=0, k_offset=100)
    np.testing.assert_array_equal(np.asarray(out_blk), 0.0)
    np.testing.assert_array_equal(np.asarray(out_dense), 0.0)


def test_indivisible_block_raises():
    q, k, v = qkv(l=30)
    with pytest.raises(ValueError):
        blockwise_attention(q, k, v, block_size=16)


# ---- Pallas flash attention (interpret mode: same kernel, CPU executed) ----


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_dense(causal):
    from pytorch_distributed_tpu.ops.flash_attention import flash_attention

    q, k, v = qkv(l=64, d=16)
    out = flash_attention(
        q, k, v, causal=causal, block_q=16, block_k=16, interpret=True
    )
    ref = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_flash_grads_match_dense():
    from pytorch_distributed_tpu.ops.flash_attention import flash_attention

    q, k, v = qkv(l=32, d=16)

    def loss_flash(q, k, v):
        out = flash_attention(
            q, k, v, causal=True, block_q=16, block_k=16, interpret=True
        )
        return jnp.sum(out**2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

    g_f = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_d = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_f, g_d):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_arbitrary_lengths_match_dense(causal):
    """r2: lengths that are NOT block multiples work via zero padding +
    in-kernel key masking (round 1 raised), values AND gradients."""
    from pytorch_distributed_tpu.ops.flash_attention import flash_attention

    q, k, v = qkv(l=30, d=16)
    out = flash_attention(
        q, k, v, causal=causal, block_q=16, block_k=16, interpret=True
    )
    ref = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5,
                               atol=1e-5)

    g_f = jax.grad(
        lambda q, k, v: jnp.sum(
            flash_attention(q, k, v, causal=causal, block_q=16, block_k=16,
                            interpret=True) ** 2
        ),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_d = jax.grad(
        lambda q, k, v: jnp.sum(dense_attention(q, k, v, causal=causal) ** 2),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(g_f, g_d):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4,
                                   atol=2e-5)


def test_flash_cross_attention_lengths():
    """Lq != Lk (cross/prefix shapes), non-causal, with key padding."""
    from pytorch_distributed_tpu.ops.flash_attention import flash_attention

    rng = np.random.default_rng(7)
    q = jnp.asarray(rng.normal(size=(2, 24, 2, 16)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(2, 50, 2, 16)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(2, 50, 2, 16)), jnp.float32)
    out = flash_attention(q, k, v, block_q=16, block_k=16, interpret=True)
    ref = dense_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5,
                               atol=1e-5)


def test_flash_lm_forward_matches_dense():
    from pytorch_distributed_tpu.models.transformer import TransformerLM, tiny_config

    # interpret-mode flash inside the full model on CPU
    import importlib

    fa = importlib.import_module("pytorch_distributed_tpu.ops.flash_attention")

    cfg_d = tiny_config(attention="dense")
    cfg_f = tiny_config(attention="flash")
    tokens = jnp.asarray(np.random.default_rng(0).integers(1, 128, (2, 32)), jnp.int32)
    model_d = TransformerLM(cfg_d)
    variables = model_d.init(jax.random.key(0), tokens)
    out_d = model_d.apply(variables, tokens)
    orig = fa.flash_attention
    try:
        fa.flash_attention = lambda *a, **kw: orig(*a, **{**kw, "interpret": True})
        out_f = TransformerLM(cfg_f).apply(variables, tokens)
    finally:
        fa.flash_attention = orig
    np.testing.assert_allclose(
        np.asarray(out_f), np.asarray(out_d), rtol=2e-4, atol=2e-5
    )


def test_flash_fused_backward_matches_split():
    """The single-pass backward (bwd_impl='fused') must produce the same
    gradients as the two-kernel split backward — including the causal
    skip-block zeroing of dQ partials and padded lengths."""
    import numpy as np

    from pytorch_distributed_tpu.ops.flash_attention import flash_attention

    r = np.random.RandomState(0)
    for (b, l, h, d) in [(2, 256, 2, 32), (1, 200, 2, 32)]:
        q = jnp.asarray(r.randn(b, l, h, d), jnp.float32)
        k = jnp.asarray(r.randn(b, l, h, d), jnp.float32)
        v = jnp.asarray(r.randn(b, l, h, d), jnp.float32)

        def loss(impl):
            return lambda q_, k_, v_: jnp.sum(
                flash_attention(q_, k_, v_, causal=True, block_q=64,
                                block_k=64, bwd_impl=impl) ** 2
            )

        g_split = jax.grad(loss("split"), argnums=(0, 1, 2))(q, k, v)
        g_fused = jax.grad(loss("fused"), argnums=(0, 1, 2))(q, k, v)
        for a, bb in zip(g_fused, g_split):
            np.testing.assert_allclose(a, bb, rtol=2e-4, atol=2e-5)


def test_flash_partials_f32_knob():
    """ADVICE r5 #2: ``partials_f32=True`` keeps the fused backward's dQ
    partials in fp32. For fp32 inputs the partials already ARE fp32, so
    the knob must be exactly inert; for bf16 inputs it removes the
    per-partial bf16 rounding, so the fused dQ must land at least as
    close to the split backward's pure-fp32 dQ accumulation as the
    default does."""
    import numpy as np

    from pytorch_distributed_tpu.ops.flash_attention import flash_attention

    r = np.random.RandomState(1)
    raw = [r.randn(2, 256, 2, 32) for _ in range(3)]

    def grads(dtype, impl, pf32):
        q, k, v = (jnp.asarray(x, dtype) for x in raw)
        loss = lambda q_, k_, v_: jnp.sum(
            flash_attention(q_, k_, v_, causal=True, block_q=64,
                            block_k=64, bwd_impl=impl,
                            partials_f32=pf32).astype(jnp.float32) ** 2
        )
        return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    # fp32: bit-inert (partials were fp32 either way)
    for a, b in zip(grads(jnp.float32, "fused", True),
                    grads(jnp.float32, "fused", False)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # bf16: fp32 partials must not be FARTHER from the split (pure-fp32
    # dQ accumulation) reference than the default bf16 partials
    dq_split = np.asarray(grads(jnp.bfloat16, "split", False)[0],
                          np.float32)
    dq_bf16 = np.asarray(grads(jnp.bfloat16, "fused", False)[0], np.float32)
    dq_f32 = np.asarray(grads(jnp.bfloat16, "fused", True)[0], np.float32)
    err = lambda x: np.abs(x - dq_split).max()
    assert err(dq_f32) <= err(dq_bf16) + 1e-6
    np.testing.assert_allclose(dq_f32, dq_split, rtol=2e-2, atol=2e-2)
