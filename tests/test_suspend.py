"""Direct unit tests for the suspend/checkpoint/yield protocol
(utils/suspend.py): the flag-file, signal, and programmatic paths, plus
the handler-chaining contract — none of which had dedicated tests before
(the trainer tests only exercise injected watchers)."""

import os
import signal

import pytest

from pytorch_distributed_tpu.utils.suspend import (
    NullSuspendWatcher,
    SuspendWatcher,
)


def test_request_suspend_is_sticky():
    w = SuspendWatcher(install_handlers=False)
    assert not w.receive_suspend_command()
    w.request_suspend()
    assert w.receive_suspend_command()
    assert w.receive_suspend_command()  # latched, stays set


def test_flag_file_polling(tmp_path):
    flag = tmp_path / "suspend.flag"
    w = SuspendWatcher(flag_file=str(flag), poll_interval=0.0,
                       install_handlers=False)
    assert not w.receive_suspend_command()
    flag.write_text("")
    assert w.receive_suspend_command()
    # sticky even after the flag file disappears
    flag.unlink()
    assert w.receive_suspend_command()


def test_flag_file_from_env(tmp_path, monkeypatch):
    flag = tmp_path / "env.flag"
    monkeypatch.setenv("SUSPEND_FLAG_FILE", str(flag))
    w = SuspendWatcher(poll_interval=0.0, install_handlers=False)
    assert w.flag_file == str(flag)
    flag.write_text("")
    assert w.receive_suspend_command()


def test_signal_delivery_latches():
    w = SuspendWatcher(signals=(signal.SIGUSR1,))
    try:
        assert not w.receive_suspend_command()
        os.kill(os.getpid(), signal.SIGUSR1)
        assert w.receive_suspend_command()
    finally:
        w.uninstall()


def test_signal_handler_chains_previous():
    """A previously-installed handler (a nested trainer, a framework
    SIGTERM hook) must still fire — the watcher chains, not clobbers."""
    calls = []

    def mine(s, f):
        calls.append(s)

    prev = signal.signal(signal.SIGUSR1, mine)
    try:
        w = SuspendWatcher(signals=(signal.SIGUSR1,))
        try:
            os.kill(os.getpid(), signal.SIGUSR1)
            assert w.receive_suspend_command()
            assert calls == [signal.SIGUSR1]  # the old handler ran too
        finally:
            w.uninstall()
        # uninstall restored the previous handler verbatim
        assert signal.getsignal(signal.SIGUSR1) is mine
        os.kill(os.getpid(), signal.SIGUSR1)
        assert len(calls) == 2
    finally:
        signal.signal(signal.SIGUSR1, prev)


def test_uninstall_leaves_foreign_handler():
    """uninstall() only unwinds signals still pointing at the watcher — a
    handler someone stacked on top stays installed."""
    base = signal.getsignal(signal.SIGUSR1)
    w = SuspendWatcher(signals=(signal.SIGUSR1,))
    top = lambda s, f: None  # noqa: E731
    signal.signal(signal.SIGUSR1, top)
    try:
        w.uninstall()
        assert signal.getsignal(signal.SIGUSR1) is top
    finally:
        signal.signal(signal.SIGUSR1, base)


def test_go_suspend_exits():
    w = SuspendWatcher(install_handlers=False)
    with pytest.raises(SystemExit) as e:
        w.go_suspend(3)
    assert e.value.code == 3


def test_null_watcher_never_fires():
    w = NullSuspendWatcher()
    w.request_suspend()  # even explicit injection is ignored
    assert not w.receive_suspend_command()
