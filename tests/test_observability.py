"""Performance attribution & forensics (ISSUE 8): cost cards, anomaly
sentinel, flight recorder, live exporter, and their wiring.

The load-bearing proofs:

- every program in a ``ProgramRegistry`` gets a cost card, and measured
  joins produce MFU/roofline numbers that match hand arithmetic;
- the anomaly sentinel flags a fault-injected hang DETERMINISTICALLY
  (seeded plan through the real trainer loop) and never before its
  warmup window;
- a SIGKILL'd kill-matrix child leaves a readable flight-recorder
  mirror whose last event precedes the kill site;
- the fleet SLOGate treats a recently-anomalous replica as hot.
"""

import functools
import json
import os
import subprocess
import sys
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.telemetry import (
    AnomalySentinel,
    CostCard,
    FlightRecorder,
    MetricsExporter,
    ProgramTimes,
    StreamingDetector,
    build_cost_cards,
    prometheus_text,
)
from pytorch_distributed_tpu.telemetry.costmodel import extract_costs
from pytorch_distributed_tpu.telemetry.flightrec import (
    read_dump,
    read_mirror,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---- anomaly sentinel ----------------------------------------------------


def test_detector_flags_spike_deterministically_after_warmup():
    det = StreamingDetector(window=16, threshold=8.0, min_samples=8,
                            context=4)
    # warmup: nothing can flag before min_samples observations exist
    base = [0.010, 0.011, 0.009, 0.010, 0.012, 0.010, 0.011, 0.010]
    hits = [det.observe(v) for v in base]
    assert hits == [None] * 8
    # the spike flags, with the right index and context window
    hit = det.observe(1.5)
    assert hit is not None
    assert hit["index"] == 8
    assert hit["value"] == 1.5
    assert hit["zscore"] > 8
    assert hit["median"] == pytest.approx(0.010, abs=1e-3)
    assert hit["context"] == [pytest.approx(v) for v in base[-4:]]
    # baseline values after the spike do NOT flag (the spike entered the
    # window but the median absorbed it)
    assert det.observe(0.010) is None
    assert det.anomalies == 1
    # replaying the same series flags the same index — determinism
    det2 = StreamingDetector(window=16, threshold=8.0, min_samples=8)
    replay = [det2.observe(v) for v in base + [1.5, 0.010]]
    assert [i for i, h in enumerate(replay) if h] == [8]


def test_detector_all_equal_series_uses_scale_floor():
    """MAD of a constant series is 0; the relative floor keeps z finite
    and only a genuine departure flags."""
    det = StreamingDetector(window=16, threshold=8.0, min_samples=4,
                            rel_floor=0.05)
    for _ in range(8):
        assert det.observe(2.0) is None
    # within threshold*rel_floor*|median| = 8*0.05*2 = 0.8 of the median
    assert det.observe(2.5) is None
    hit = det.observe(4.0)  # 2.0 above median > 0.8
    assert hit is not None and hit["zscore"] == pytest.approx(20.0, rel=0.1)


def test_sentinel_streams_jsonl_with_meta(tmp_path):
    from pytorch_distributed_tpu.utils.profiling import MetricsLogger

    path = os.fspath(tmp_path / "m.jsonl")
    with MetricsLogger(path) as mlog:
        s = AnomalySentinel(threshold=8.0, min_samples=4,
                            metrics_log=mlog, source="test")
        for _ in range(6):
            s.observe("lat", 0.01)
        assert s.observe("lat", 9.0, step=42) is not None
    assert s.anomalies == 1
    assert s.counts() == {"lat": 1}
    recs = [json.loads(l) for l in open(path)]
    assert len(recs) == 1
    r = recs[0]
    assert r["kind"] == "anomaly" and r["series"] == "lat"
    assert r["step"] == 42 and r["source"] == "test"
    assert r["value"] == 9.0 and len(r["context"]) > 0


def test_slo_gate_treats_recent_anomaly_as_hot():
    from pytorch_distributed_tpu.fleet import SLOGate

    gate = SLOGate()
    cool = {"queue_depth": 0, "occupancy": 0.1}
    hot = {"queue_depth": 0, "occupancy": 0.1, "anomaly_recent": True}
    assert gate.hot(cool) is None
    assert gate.hot(hot) == "anomaly"
    # routing: the anomalous affinity replica is spilled around
    d = gate.route({0: hot, 1: cool}, preferred=0)
    assert d.action == "spill" and d.replica == 1 and d.reason == "anomaly"


# ---- cost cards ----------------------------------------------------------


def test_extract_costs_from_real_compiled():
    comp = jax.jit(lambda x: (x @ x).sum()).lower(
        jnp.ones((64, 64), jnp.float32)
    ).compile()
    costs = extract_costs(comp)
    # 64^3 MACs * 2 flops minimum for the matmul alone
    assert costs["flops"] >= 2 * 64**3
    assert costs["bytes_accessed"] >= 64 * 64 * 4
    assert costs["argument_bytes"] == 64 * 64 * 4
    assert costs["peak_bytes"] > 0


def test_cost_card_join_arithmetic_and_roofline_class():
    # bandwidth-bound: intensity 2 F/B below ridge 10 F/B
    card = CostCard(program="p", flops=2e9, bytes_accessed=1e9,
                    calls=4, total_s=0.4)
    rec = card.record(peak_flops=1e12, peak_bytes_s=1e11)
    assert rec["mean_s"] == pytest.approx(0.1)
    assert rec["achieved_flops_s"] == pytest.approx(2e10)
    assert rec["mfu"] == pytest.approx(0.02)
    assert rec["hbm_frac"] == pytest.approx(0.1)
    assert rec["intensity_flop_b"] == pytest.approx(2.0)
    assert rec["ridge_flop_b"] == pytest.approx(10.0)
    assert rec["bound"] == "bandwidth"
    # compute-bound twin
    card2 = CostCard(program="q", flops=2e12, bytes_accessed=1e9,
                     calls=1, total_s=0.1)
    assert card2.record(1e12, 1e11)["bound"] == "compute"
    # no ceilings: achieved rates still emit, mfu/bound absent
    rec3 = card.record(None, None)
    assert "achieved_flops_s" in rec3
    assert "mfu" not in rec3 and "bound" not in rec3
    # unmeasured card: statics only, no rates
    rec4 = CostCard(program="r", flops=1.0).record(1e12, 1e11)
    assert rec4["calls"] == 0 and "mean_s" not in rec4


def test_extract_costs_dedupes_aliased_operand_bytes():
    """The round 20 double-count fix (PERF_NOTES §9): donated operands
    appear in BOTH argument and output totals, so peak_bytes subtracts
    the aliased overlap once and bytes_accessed_dedup removes it from
    the traffic number the roofline join divides by. Regression pinned
    against a fake compiled object with known analysis values."""

    class FakeMem:
        argument_size_in_bytes = 1000
        output_size_in_bytes = 700
        temp_size_in_bytes = 50
        alias_size_in_bytes = 600  # a donated pool counted twice above

    class FakeCompiled:
        def cost_analysis(self):
            return [{"flops": 4000.0, "bytes accessed": 2000.0}]

        def memory_analysis(self):
            return FakeMem()

    costs = extract_costs(FakeCompiled())
    assert costs["alias_bytes"] == 600
    assert costs["peak_bytes"] == 1000 + 700 + 50 - 600
    card = CostCard(program="fake", calls=2, total_s=0.2, **costs)
    assert card.bytes_accessed_dedup == pytest.approx(2000.0 - 600)
    # intensity and the roofline join use the DEDUPED traffic
    assert card.intensity == pytest.approx(4000.0 / 1400.0)
    rec = card.record(peak_flops=1e6, peak_bytes_s=1e5)
    assert rec["bytes_accessed"] == pytest.approx(2000.0)  # raw kept
    assert rec["bytes_accessed_dedup"] == pytest.approx(1400.0)
    assert rec["achieved_bytes_s"] == pytest.approx(1400.0 / 0.1)
    assert rec["hbm_frac"] == pytest.approx(1400.0 / 0.1 / 1e5)
    # no alias info → dedup degrades to the raw number, never negative
    plain = CostCard(program="p", flops=1.0, bytes_accessed=100.0)
    assert plain.bytes_accessed_dedup == pytest.approx(100.0)
    swamped = CostCard(program="s", bytes_accessed=100.0,
                       alias_bytes=1000)
    assert swamped.bytes_accessed_dedup == 0.0


def test_extract_costs_alias_on_real_donated_program():
    """A live donated buffer really shows up in alias_size_in_bytes and
    peak_bytes stays below the naive arg+out+temp sum (tolerant: if
    this jax build reports no aliasing, the dedup must be a no-op
    rather than wrong)."""

    @functools.partial(jax.jit, donate_argnums=0)
    def bump(x):
        return x + 1

    comp = bump.lower(jnp.ones((256, 256), jnp.float32)).compile()
    costs = extract_costs(comp)
    naive = (costs["argument_bytes"] + costs["output_bytes"]
             + costs["temp_bytes"])
    assert costs["peak_bytes"] == naive - costs["alias_bytes"]
    if costs["alias_bytes"]:
        assert costs["alias_bytes"] >= 256 * 256 * 4
        card = CostCard(program="bump", **costs)
        assert card.bytes_accessed_dedup < card.bytes_accessed


def _tiny_scheduler(**kw):
    from pytorch_distributed_tpu.models.transformer import (
        TransformerLM,
        tiny_config,
    )
    from pytorch_distributed_tpu.serving import Scheduler

    cfg = tiny_config(attention="dense", max_seq_len=64)
    params = TransformerLM(cfg).init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return cfg, Scheduler(cfg, params, n_slots=2, block_len=8,
                          prefill_chunk=8, **kw)


@pytest.mark.slow
def test_every_registry_program_has_a_cost_card(tmp_path):
    """The acceptance line: cards cover the registry exactly, and the
    measured decode tick joins into achieved rates."""
    from pytorch_distributed_tpu.compilecache import serving_registry
    from pytorch_distributed_tpu.utils.profiling import MetricsLogger

    path = os.fspath(tmp_path / "serve.jsonl")
    with MetricsLogger(path) as mlog:
        cfg, s = _tiny_scheduler(metrics_log=mlog)
        rng = np.random.default_rng(0)
        for l in (5, 9, 14, 7):
            s.submit(rng.integers(1, cfg.vocab_size, l).astype(np.int32), 4)
        s.drain()
        records = s.log_cost_cards()
    reg = serving_registry(s.engine)
    names = {r["program"] for r in records}
    assert names == set(reg.names)  # every program, nothing else
    by_name = {r["program"]: r for r in records}
    decode = by_name["decode_tick"]
    assert decode["calls"] > 0 and decode["flops"] > 0
    assert decode["achieved_flops_s"] > 0
    assert decode["bytes_accessed"] > 0 and decode["peak_bytes"] > 0
    # statics exist even for buckets traffic never touched
    unmeasured = [r for r in records if not r["calls"]]
    assert unmeasured and all(r.get("flops") for r in unmeasured)
    # the JSONL stream carries the same records
    jl = [json.loads(l) for l in open(path)
          if json.loads(l).get("kind") == "program_cost"]
    assert {r["program"] for r in jl} == names


def test_build_cost_cards_survives_aotless_and_failing_specs():
    from pytorch_distributed_tpu.compilecache import (
        ProgramRegistry,
        ProgramSpec,
    )

    def boom():
        raise RuntimeError("unanalyzable")

    reg = ProgramRegistry("fp")
    reg.add(ProgramSpec(name="no_aot", warm=lambda e: None))
    reg.add(ProgramSpec(name="bad_aot", warm=lambda e: None, aot=boom))
    times = ProgramTimes()
    times.observe("no_aot", 0.5)
    cards = build_cost_cards(reg, times)
    assert [c.program for c in cards] == ["no_aot", "bad_aot"]
    assert cards[0].flops is None and cards[0].calls == 1
    assert cards[1].flops is None  # failure -> card without statics


def test_program_times_accumulates():
    t = ProgramTimes()
    t.observe("a", 0.1)
    t.observe("a", 0.3)
    t.observe_total("b", 1.0, 10)
    t.observe("a", -1.0)  # rejected
    assert t.get("a") == (2, pytest.approx(0.4))
    assert t.get("b") == (10, 1.0)
    assert t.get("missing") == (0, 0.0)


# ---- flight recorder -----------------------------------------------------


def test_flightrec_ring_bound_dump_and_mirror(tmp_path):
    mirror = os.fspath(tmp_path / "fr.jsonl")
    fr = FlightRecorder(capacity=8, mirror_path=mirror)
    for i in range(20):
        fr.record("step", n=i)
    assert len(fr) == 8  # ring bounded
    snap = fr.snapshot()
    assert [e["n"] for e in snap] == list(range(12, 20))
    assert [e["seq"] for e in snap] == list(range(12, 20))
    # the mirror kept EVERYTHING (durable beyond the ring horizon)
    events = read_mirror(mirror)
    assert [e["n"] for e in events] == list(range(20))
    # atomic dump: header + the ring's events
    path = os.fspath(tmp_path / "dump.json")
    assert fr.dump(path, "test_reason") == path
    dump = read_dump(path)
    assert dump["reason"] == "test_reason"
    assert dump["first_seq"] == 12 and dump["last_seq"] == 19
    assert [e["n"] for e in dump["events"]] == list(range(12, 20))
    fr.close()


def test_flightrec_mirror_rotation_and_torn_tail(tmp_path):
    mirror = os.fspath(tmp_path / "fr.jsonl")
    fr = FlightRecorder(capacity=4, mirror_path=mirror,
                        mirror_max_bytes=1024)
    for i in range(100):
        fr.record("step", n=i, pad="z" * 32)
    fr.close()
    assert os.path.exists(f"{mirror}.1")
    # simulate the SIGKILL torn final line
    with open(mirror, "a") as f:
        f.write('{"seq": 9999, "kind": "to')
    events = read_mirror(mirror)
    ns = [e["n"] for e in events if "n" in e]
    assert ns == sorted(ns) and ns[-1] == 99  # ordered across rotation
    assert all(e.get("seq") != 9999 for e in events)  # torn line dropped


def test_flightrec_excepthook_dumps_then_chains(tmp_path):
    fr = FlightRecorder(capacity=4)
    fr.record("step", n=1)
    dump_path = os.fspath(tmp_path / "exc.json")
    seen = []
    prev = sys.excepthook
    sys.excepthook = lambda *a: seen.append(a)
    try:
        fr.install_excepthook(dump_path)
        try:
            raise ValueError("boom")
        except ValueError:
            sys.excepthook(*sys.exc_info())
        assert os.path.exists(dump_path)
        dump = read_dump(dump_path)
        assert dump["reason"] == "exception:ValueError"
        kinds = [e["kind"] for e in dump["events"]]
        assert "exception" in kinds and "step" in kinds
        assert len(seen) == 1  # previous hook still ran
    finally:
        fr.uninstall_excepthook()
        sys.excepthook = prev


def test_flightrec_disabled_is_free(tmp_path):
    from pytorch_distributed_tpu.telemetry import NULL_RECORDER

    NULL_RECORDER.record("step", n=1)
    assert len(NULL_RECORDER) == 0
    assert NULL_RECORDER.dump(os.fspath(tmp_path / "x.json"), "r") is None
    assert not os.path.exists(tmp_path / "x.json")


# ---- live exporter -------------------------------------------------------


def test_metrics_exporter_serves_prometheus_text():
    state = {"tokens_per_s": 123.5, "queue_depth": 4, "draining": False,
             "name": "skipme", "bad": float("nan")}
    with MetricsExporter(lambda: state, port=0) as ex:
        assert ex.port and ex.port > 0
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{ex.port}/metrics", timeout=5
        ).read().decode()
        assert "pdt_tokens_per_s 123.5" in body
        assert "pdt_queue_depth 4" in body
        assert "pdt_draining 0" in body
        assert "skipme" not in body and "pdt_bad" not in body
        health = urllib.request.urlopen(
            f"http://127.0.0.1:{ex.port}/healthz", timeout=5
        )
        assert health.status == 200
    # prometheus_text is the pure renderer the handler uses
    text = prometheus_text({"a_b": 1})
    assert "# TYPE pdt_a_b gauge" in text and "pdt_a_b 1" in text


# ---- scheduler integration ----------------------------------------------


def test_scheduler_metrics_expose_anomaly_signal():
    cfg, s = _tiny_scheduler()
    m = s.metrics()
    assert m["anomaly_count"] == 0 and m["anomaly_recent"] is False
    # inject recency directly: the signal is tick-windowed
    s._last_anomaly_step = 0
    s._step_count = 10
    assert s.metrics()["anomaly_recent"] is True
    s._step_count = s.anomaly_recent_ticks + 5
    assert s.metrics()["anomaly_recent"] is False


# ---- trainer integration: deterministic hang → anomaly + cost cards ------


def _lm_fit(tmp_path, monkeypatch, fault_plan=None, watcher=None,
            **cfg_over):
    from pytorch_distributed_tpu.data.tokens import SyntheticTokens
    from pytorch_distributed_tpu.models.transformer import tiny_config
    from pytorch_distributed_tpu.parallel import make_mesh
    from pytorch_distributed_tpu.resilience import faults
    from pytorch_distributed_tpu.train import LMTrainer, LMTrainerConfig

    if fault_plan is not None:
        monkeypatch.setattr(faults, "_plan", None)
        faults.install_plan(fault_plan)
    mesh = make_mesh(jax.devices()[:1], data_parallel=1, seq_parallel=1,
                     model_parallel=1)
    cfg = LMTrainerConfig(
        epochs=1, batch_size=2, lr=1e-2, save_dir=os.fspath(tmp_path),
        num_workers=0, log_every=1, warmup_steps=0, **cfg_over,
    )
    train = SyntheticTokens(size=24, seq_len=32, vocab_size=128)
    val = SyntheticTokens(size=8, seq_len=32, vocab_size=128, seed=9)
    t = LMTrainer(tiny_config(attention="dense"), train, val, cfg,
                  mesh=mesh, suspend_watcher=watcher)
    t.fit()
    t.metrics_log.close()
    t.flightrec.close()
    if fault_plan is not None:
        faults.install_plan(None)
    return t, [json.loads(l)
               for l in open(os.path.join(tmp_path, "metrics.jsonl"))]


@pytest.mark.slow
def test_trainer_hang_injection_flags_anomaly_and_cost_cards(
    tmp_path, monkeypatch
):
    """ISSUE 8 acceptance: a seeded ``train.step`` hang is flagged by
    the sentinel (kind="anomaly" with the hang's magnitude), the flight
    recorder mirror holds the step history, and fit-end cost cards
    carry a measured MFU join for the train step."""
    from pytorch_distributed_tpu.resilience.faults import (
        FaultPlan,
        FaultSpec,
    )

    monkeypatch.setenv("PDT_PEAK_FLOPS", "1e12")
    monkeypatch.setenv("PDT_PEAK_GBS", "100")
    # 12 steps; hang 1.0s at occurrence 10 — past the sentinel's
    # min_samples warmup, so the flag is guaranteed, not probabilistic
    plan = FaultPlan([FaultSpec(site="train.step", kind="hang", at=10,
                                seconds=1.0)])
    t, recs = _lm_fit(tmp_path, monkeypatch, fault_plan=plan,
                      cost_cards=True)
    anomalies = [r for r in recs if r.get("kind") == "anomaly"
                 and r.get("series") == "step_time"]
    assert anomalies, "injected hang was not flagged"
    assert any(r["value"] >= 1.0 for r in anomalies)
    # replaying the plan on a fresh run flags again — deterministic
    assert t.sentinel.anomalies >= 1
    # flight recorder: mirror holds the full step history
    events = read_mirror(os.path.join(tmp_path, "flightrec.jsonl"))
    steps = [e for e in events if e["kind"] == "step"]
    assert len(steps) == 12
    # cost cards: train step measured, eval step static-only
    cards = {r["program"]: r for r in recs
             if r.get("kind") == "program_cost"}
    assert set(cards) == {"lm_train_step", "lm_eval_step"}
    train_card = cards["lm_train_step"]
    assert train_card["calls"] == 12
    assert train_card["flops"] > 0 and train_card["mfu"] > 0
    assert train_card["bound"] in ("compute", "bandwidth")
    # the report renders + gates on both new sections
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts/telemetry_report.py"),
         os.path.join(tmp_path, "metrics.jsonl"), "--json",
         "--require", "cost,anomaly"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr
    assert "program cost / roofline" in proc.stdout
    assert "anomalies" in proc.stdout
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["cost_programs"] == 2
    assert out["cost_measured_programs"] >= 1
    assert out["anomalies"] >= 1


@pytest.mark.slow
def test_trainer_suspend_dumps_flight_recorder(tmp_path, monkeypatch):
    """The suspend trigger: a latched suspend leaves an atomic ring dump
    (reason=suspend) before the run yields."""
    from pytorch_distributed_tpu.resilience.faults import (
        FaultPlan,
        FaultSpec,
    )

    from pytorch_distributed_tpu.utils.suspend import SuspendWatcher

    class YieldlessWatcher(SuspendWatcher):
        """Real latch semantics, but yielding returns instead of
        sys.exit so the test can assert on the artifacts."""

        def __init__(self):
            super().__init__(install_handlers=False)

        def go_suspend(self, exit_code: int = 0) -> None:
            self._event.clear()  # un-latch so the run finishes

    plan = FaultPlan([FaultSpec(site="train.step", kind="suspend", at=3)])
    t, recs = _lm_fit(tmp_path, monkeypatch, fault_plan=plan,
                      watcher=YieldlessWatcher())
    dump_path = os.path.join(tmp_path, "flightrec_dump.json")
    assert os.path.exists(dump_path)
    dump = read_dump(dump_path)
    assert dump["reason"] == "suspend"
    kinds = [e["kind"] for e in dump["events"]]
    assert "suspend" in kinds and "step" in kinds


# ---- kill-matrix: the mirror survives SIGKILL ----------------------------


@pytest.mark.crash
@pytest.mark.slow
def test_kill_matrix_child_leaves_readable_flightrec_mirror(tmp_path):
    """ISSUE 8 acceptance: SIGKILL the crash child at a train.step fault
    point; the relaunch-visible mirror must parse, and its last step
    event must PRECEDE the kill site (no event from the step the kill
    interrupted)."""
    kill_at = 2
    plan = json.dumps({"faults": [
        {"site": "train.step", "kind": "kill", "at": kill_at}
    ]})
    env = dict(os.environ, PDT_FAULT_PLAN=plan, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tests/crash_child.py"),
         "--save-dir", os.fspath(tmp_path)],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=300,
    )
    assert proc.returncode == -9, proc.stderr  # SIGKILL'd, as planned
    events = read_mirror(os.path.join(tmp_path, "flightrec.jsonl"))
    assert events, "kill left no readable mirror"
    # seqs are monotone — the mirror is a valid prefix of the run
    seqs = [e["seq"] for e in events]
    assert seqs == sorted(seqs)
    steps = [e["n"] for e in events if e["kind"] == "step"]
    # the kill fired in _pre_step of occurrence `kill_at`, so exactly
    # the prior steps' events exist: n = 1..kill_at, nothing beyond
    assert steps and max(steps) == kill_at
    # checkpoint saves before the kill are on record too
    assert any(e["kind"] == "ckpt_save" for e in events)


# ---- bench_regression ----------------------------------------------------


def test_bench_regression_directions_and_bands():
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        from bench_regression import compare, direction
    finally:
        sys.path.pop(0)

    prev = {"lm_tok_s": 1000.0, "serving_ttft_p95_ms": 100.0,
            "ckpt_save_s": 10.0, "batch_size": 128, "platform": "tpu"}
    # throughput drop + latency rise outside band -> both regress
    res = compare(
        {"lm_tok_s": 800.0, "serving_ttft_p95_ms": 150.0,
         "ckpt_save_s": 11.0, "batch_size": 128, "platform": "tpu"},
        prev,
    )
    keys = {r["key"] for r in res["regressions"]}
    assert keys == {"lm_tok_s", "serving_ttft_p95_ms"}
    # ckpt keys ride the wide disk-weather band: +10% is NOT a regression
    assert res["within"] >= 1
    # improvements within direction semantics
    res2 = compare({"lm_tok_s": 1300.0, "serving_ttft_p95_ms": 80.0},
                   prev)
    assert not res2["regressions"]
    assert {r["key"] for r in res2["improvements"]} == {
        "lm_tok_s", "serving_ttft_p95_ms"
    }
    # per-key override narrows the band
    res3 = compare({"ckpt_save_s": 12.0}, prev,
                   overrides={"ckpt_save_s": 0.1})
    assert [r["key"] for r in res3["regressions"]] == ["ckpt_save_s"]
    # direction classification
    assert direction("lm_tok_s") == "up"
    assert direction("decode_p95_ms") == "down"
    assert direction("batch_size") is None
    assert direction("padding_waste_frac") is None


def test_bench_regression_cli_roundtrip(tmp_path):
    cur = tmp_path / "cur.json"
    prev = tmp_path / "prev.json"
    prev.write_text(json.dumps({"parsed": {"lm_tok_s": 1000.0}}))
    cur.write_text(json.dumps({"parsed": {"lm_tok_s": 500.0}}))
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts/bench_regression.py"),
         os.fspath(cur), os.fspath(prev), "--json"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 1  # regression -> the gate trips
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["regression_keys"] == ["lm_tok_s"]
    # same comparison inside the band passes
    cur.write_text(json.dumps({"parsed": {"lm_tok_s": 980.0}}))
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts/bench_regression.py"),
         os.fspath(cur), os.fspath(prev)],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ---- pdt_top -------------------------------------------------------------


def test_pdt_top_once_renders_all_sections(tmp_path):
    path = tmp_path / "run.jsonl"
    with open(path, "w") as f:
        f.write(json.dumps({"kind": "train", "epoch": 0, "step": 3,
                            "loss": 4.5}) + "\n")
        f.write(json.dumps({"kind": "goodput", "goodput_frac": 0.9,
                            "compile_frac": 0.05, "data_wait_frac": 0.03,
                            "stall_frac": 0.0}) + "\n")
        f.write(json.dumps({"kind": "request", "rid": 0, "new_tokens": 4,
                            "ttft_s": 0.12,
                            "token_gaps_s": [0.01, 0.02]}) + "\n")
        f.write(json.dumps({"kind": "anomaly", "series": "tick_time",
                            "zscore": 12.3, "value": 1.0}) + "\n")
        f.write(json.dumps({"kind": "program_cost", "program": "decode",
                            "calls": 8, "mean_s": 0.004, "total_s": 0.032,
                            "mfu": 0.12, "bound": "bandwidth"}) + "\n")
        f.write('{"torn tail')  # must not crash the tailer
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts/pdt_top.py"),
         os.fspath(path), "--once"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr
    out = proc.stdout
    assert "loss 4.5000" in out
    assert "goodput  0.900" in out
    assert "ttft" in out
    assert "tick_time=1" in out
    assert "decode" in out and "[bandwidth]" in out
