"""HTTP/SSE front door (round 22): the gateway over a live fleet.

The claims under test, each of which is a wire-level contract the
in-process serving stack never had to keep before:

1. FIDELITY — the SSE stream is token-identical to an in-process
   ``FleetRouter`` replay of the same prompts (greedy decode is
   deterministic; the gateway must add transport, not entropy), and
   the terminal ``done`` event carries the true outcome + usage.
2. CONTROL-PLANE MAPPING — ``X-Deadline-Ms`` becomes the PR 17
   admission deadline (a lapsed budget sheds as HTTP 429 with
   ``Retry-After`` and the gate's reason), ``/v1/health`` is the PR 19
   health plane verbatim, ``/metrics`` carries both fleet and gateway
   gauges.
3. DISCONNECT → CANCEL — closing the client socket mid-stream reaches
   ``FleetRouter.cancel``: blocks free (a disconnect STORM under
   ``PDT_BLOCKSAN=1`` quiesces clean), the span tree closes
   ``outcome=cancelled``, and the cancel-to-block-free latency is
   observed.
4. HARDENING — malformed ingress (bad JSON, non-numeric deadline,
   oversized prompt, bad types) is a 400 with a JSON error body; a
   stack trace never reaches the socket.
5. HYGIENE — every gateway container is census-declared and the
   ``kind="http"`` JSONL it emits validates against the schema
   registry.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from pytorch_distributed_tpu.telemetry import undeclared_containers
from pytorch_distributed_tpu.telemetry.census import audit_owner
from pytorch_distributed_tpu.telemetry.reqtrace import ReqTracer
from pytorch_distributed_tpu.telemetry.schema import validate_stream
from pytorch_distributed_tpu.utils.profiling import MetricsLogger


# ---------------------------------------------------------------------------
# fixtures: one shared gateway over a 2-replica fleet + the in-process
# reference transcript collected BEFORE the gateway takes the router
# ---------------------------------------------------------------------------

N_REF = 3  # reference prompts replayed over the wire


@pytest.fixture(scope="module")
def tiny_model():
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_tpu.models.transformer import (
        TransformerLM,
        tiny_config,
    )

    cfg = tiny_config(attention="dense", max_seq_len=96)
    params = TransformerLM(cfg).init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return cfg, params


def _build_router(cfg, params, **kw):
    from pytorch_distributed_tpu.fleet import FleetRouter

    kw.setdefault("n_replicas", 2)
    kw.setdefault("n_slots", 3)
    kw.setdefault("block_len", 8)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("async_host", True)
    kw.setdefault("retain_results", False)
    return FleetRouter(cfg, params, **kw)


def _prompts(cfg, n=N_REF, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, cfg.vocab_size, (9 + 3 * i,)).astype(np.int32)
            for i in range(n)]


@pytest.fixture(scope="module")
def gw_env(tiny_model, tmp_path_factory):
    from pytorch_distributed_tpu.gateway import Gateway

    cfg, params = tiny_model
    prompts = _prompts(cfg)

    # in-process reference: the SAME prompts through a plain router.
    # retain_results=False drops transcripts at retire, so collect from
    # step() directly — exactly what the gateway's driver does.
    # n_replicas=1: routing never changes a request's greedy stream, and
    # one engine init keeps the module fixture cheap in the fast tier.
    ref_router = _build_router(cfg, params, async_host=False, n_replicas=1)
    ref_rids = [ref_router.submit(p, 6) for p in prompts]
    reference = {rid: [] for rid in ref_rids}
    for _ in range(4000):
        if ref_router.idle:
            break
        for rid, tok in ref_router.step():
            reference[rid].append(int(tok))
    ref_router.drain(max_steps=100)
    ref_tokens = [reference[rid] for rid in ref_rids]
    assert all(len(t) == 6 for t in ref_tokens)

    path = str(tmp_path_factory.mktemp("gw") / "gw.jsonl")
    mlog = MetricsLogger(path)
    router = _build_router(cfg, params, metrics_log=mlog,
                           reqtrace=ReqTracer(mlog))
    gw = Gateway(router, port=0, metrics_log=mlog)
    gw.start()
    env = {
        "base": f"http://127.0.0.1:{gw.port}",
        "gw": gw,
        "router": router,
        "cfg": cfg,
        "prompts": prompts,
        "ref_tokens": ref_tokens,
        "jsonl": path,
    }
    yield env
    gw.stop()
    router.drain(max_steps=4000)
    mlog.close()


def _http_records(path):
    rows = [json.loads(l) for l in open(path) if l.strip()]
    return [r for r in rows if r.get("kind") == "http"]


def _wait(pred, timeout=20.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


# ---------------------------------------------------------------------------
# 1. fidelity: the wire adds transport, not entropy
# ---------------------------------------------------------------------------

def test_sse_stream_token_identical_to_inprocess(gw_env):
    from pytorch_distributed_tpu.gateway import generate

    for prompt, expect in zip(gw_env["prompts"], gw_env["ref_tokens"]):
        out = generate(gw_env["base"], prompt, 6)
        assert out["status"] == 200, out
        assert out["outcome"] == "complete", out
        assert out["tokens"] == expect, (
            "HTTP stream diverged from the in-process replay")
        assert out["usage"] == {"prompt_tokens": len(prompt),
                                "completion_tokens": 6}
        assert out["rid"] >= 0


def test_sse_events_ordered_and_indexed(gw_env):
    from pytorch_distributed_tpu.gateway import open_stream

    with open_stream(gw_env["base"], gw_env["prompts"][0], 5) as st:
        events = list(st.events())
    names = [n for n, _ in events]
    assert names == ["token"] * 5 + ["done"]
    assert [d["i"] for n, d in events if n == "token"] == list(range(5))
    done = events[-1][1]
    assert done["outcome"] == "complete"
    assert done["usage"]["completion_tokens"] == 5


# ---------------------------------------------------------------------------
# 2. control-plane mapping: deadline, shed ladder, health, metrics
# ---------------------------------------------------------------------------

def test_lapsed_deadline_sheds_as_429_with_retry_after(gw_env):
    from pytorch_distributed_tpu.gateway import generate

    out = generate(gw_env["base"], gw_env["prompts"][0], 5, deadline_ms=0)
    assert out["status"] == 429, out
    assert out["reason"] == "deadline-expired", out
    assert out["retry_after"] == "1"
    assert out["error"] == "shed"


def test_generous_deadline_admits(gw_env):
    from pytorch_distributed_tpu.gateway import generate

    out = generate(gw_env["base"], gw_env["prompts"][0], 4,
                   deadline_ms=60_000)
    assert out["status"] == 200 and out["outcome"] == "complete", out


def test_health_endpoint_is_the_health_plane(gw_env):
    from pytorch_distributed_tpu.gateway import health

    snap = health(gw_env["base"])
    assert len(snap["replicas"]) == 2
    for i, rec in enumerate(snap["replicas"]):
        assert rec["replica"] == i
        assert rec["state"] in ("healthy", "suspect", "dead",
                                "draining", "rejoining")
    assert snap["routable"] == 2  # nothing has been failed here
    # verbatim the router's plane, not a paraphrase
    assert [r["state"] for r in snap["replicas"]] == \
        [h["state"] for h in gw_env["router"].health]


def test_metrics_endpoint_carries_fleet_and_gateway_gauges(gw_env):
    from pytorch_distributed_tpu.gateway import metrics_text

    text = metrics_text(gw_env["base"])
    for key in ("pdt_gateway_open_streams", "pdt_gateway_connections",
                "pdt_gateway_http_429", "pdt_completed"):
        assert any(line.startswith(key + " ") for line
                   in text.splitlines()), f"{key} missing from /metrics"


# ---------------------------------------------------------------------------
# 3. disconnect → cancel
# ---------------------------------------------------------------------------

def test_mid_stream_disconnect_cancels_request(gw_env):
    from pytorch_distributed_tpu.gateway import open_stream

    gw, router = gw_env["gw"], gw_env["router"]
    cancelled0 = router.metrics()["cancelled"]
    gw_cancel0 = gw.metrics()["gateway_cancels"]

    st = open_stream(gw_env["base"], gw_env["prompts"][0], 40)
    it = st.events()
    name, data = next(it)          # stream is live past admission
    assert name == "token" and data["i"] == 0
    st.close()                     # hang up mid-stream

    assert _wait(lambda: gw.metrics()["gateway_cancels"] > gw_cancel0), \
        "disconnect never reached FleetRouter.cancel"
    assert _wait(lambda: router.metrics()["cancelled"] > cancelled0)
    # the stream table does not retain the hung-up rid
    assert _wait(lambda: gw.metrics()["gateway_open_streams"] == 0)
    # cancel-to-block-free latency was observed
    assert gw.metrics()["gateway_cancel_free_count"] >= 1


def test_disconnect_record_and_span_outcome_cancelled(gw_env):
    """The JSONL trail of the disconnect above: an ``http`` record with
    ``disconnect=true`` and a root span closed ``outcome=cancelled``."""
    recs = _http_records(gw_env["jsonl"])
    dis = [r for r in recs if r.get("disconnect")]
    assert dis, "no disconnect http record written"
    assert dis[-1]["status"] == 200 and dis[-1]["outcome"] == "cancelled"

    rows = [json.loads(l) for l in open(gw_env["jsonl"]) if l.strip()]
    ends = [r for r in rows if r.get("kind") == "span"
            and r.get("ev") == "end" and r.get("outcome") == "cancelled"]
    assert ends, "no span closed outcome=cancelled"


@pytest.mark.slow  # fast tier sits ~60 s under its cap; ci_check.sh
# --gateway-smoke runs this by node id (node-id selection ignores -m)
def test_disconnect_storm_leaks_zero_blocks(tiny_model, tmp_path,
                                            monkeypatch):
    """6 concurrent streams all hang up after the first token, under the
    block sanitizer: every cancel must free its blocks — quiesce clean."""
    from pytorch_distributed_tpu.gateway import Gateway, open_stream

    monkeypatch.setenv("PDT_BLOCKSAN", "1")
    cfg, params = tiny_model
    mlog = MetricsLogger(str(tmp_path / "storm.jsonl"))
    router = _build_router(cfg, params, metrics_log=mlog,
                           reqtrace=ReqTracer(mlog))
    assert router.blocksan is not None
    gw = Gateway(router, port=0, metrics_log=mlog)
    gw.start()
    base = f"http://127.0.0.1:{gw.port}"
    prompts = _prompts(cfg, n=6, seed=3)

    hung = []

    def _one(prompt):
        st = open_stream(base, prompt, 40, timeout=30.0)
        next(st.events())  # first token over the wire, then hang up
        st.close()
        hung.append(1)

    try:
        threads = [threading.Thread(target=_one, args=(p,), daemon=True)
                   for p in prompts]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60.0)
        assert len(hung) == 6
        assert _wait(lambda: gw.metrics()["gateway_cancels"] >= 6,
                     timeout=30.0), gw.metrics()
        assert _wait(lambda: gw.metrics()["gateway_open_streams"] == 0)
    finally:
        gw.stop()
        router.drain(max_steps=4000)
        mlog.close()
    # the storm's whole point: cancel freed every block, provably
    router.blocksan.assert_clean()
    assert router.metrics()["cancelled"] >= 6


# ---------------------------------------------------------------------------
# 4. malformed-input hardening: 400 + JSON body, never a stack trace
# ---------------------------------------------------------------------------

def _raw_post(base, body: bytes, headers=None):
    """POST raw bytes; return (status, parsed-json-body)."""
    req = urllib.request.Request(
        base + "/v1/generate", data=body,
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST")
    try:
        with urllib.request.urlopen(req, timeout=15.0) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        raw = e.read().decode("utf-8", "replace")
        assert "Traceback" not in raw, raw  # hardening: no stack traces
        return e.code, json.loads(raw)      # and ALWAYS a JSON body


def test_bad_json_is_400(gw_env):
    status, body = _raw_post(gw_env["base"], b'{"prompt": [1, 2')
    assert status == 400 and body["error"] == "bad-json", body


def test_non_numeric_deadline_is_400(gw_env):
    status, body = _raw_post(
        gw_env["base"],
        json.dumps({"prompt": [1, 2, 3], "max_new_tokens": 4}).encode(),
        headers={"X-Deadline-Ms": "soon"})
    assert status == 400 and body["error"] == "bad-deadline", body


def test_oversized_prompt_is_400_not_a_crash(gw_env):
    # 200 tokens > max_seq_len=96: the scheduler's admission validator
    # raises ValueError; the gateway must surface it as a 400
    big = list(range(1, 201))
    status, body = _raw_post(
        gw_env["base"],
        json.dumps({"prompt": big, "max_new_tokens": 4}).encode())
    assert status == 400 and body["error"] == "invalid-request", body
    assert "detail" in body


@pytest.mark.parametrize("payload,err", [
    ({"max_new_tokens": 4}, "bad-prompt"),                # missing
    ({"prompt": [], "max_new_tokens": 4}, "bad-prompt"),  # empty
    ({"prompt": [1, "a"], "max_new_tokens": 4}, "bad-prompt"),
    ({"prompt": [1, 2], "max_new_tokens": 0}, "bad-max-new-tokens"),
    ({"prompt": [1, 2], "max_new_tokens": 4, "session": "x"},
     "bad-session"),
])
def test_bad_payload_types_are_400(gw_env, payload, err):
    status, body = _raw_post(gw_env["base"],
                             json.dumps(payload).encode())
    assert status == 400 and body["error"] == err, body


def test_gateway_still_serves_after_the_abuse(gw_env):
    """Hardening is only real if the gateway SURVIVES it routable."""
    from pytorch_distributed_tpu.gateway import generate

    out = generate(gw_env["base"], gw_env["prompts"][1], 3)
    assert out["status"] == 200 and out["outcome"] == "complete", out


# ---------------------------------------------------------------------------
# 5. hygiene: census decls + JSONL schema conformance
# ---------------------------------------------------------------------------

def test_gateway_census_declared_and_bounded(gw_env):
    gw = gw_env["gw"]
    owners = gw.census_owners()
    assert owners, "gateway exposed no census owners"
    for name, obj in owners:
        assert undeclared_containers(obj) == []
        _, viol, undecl = audit_owner(name, obj, live=0, live_slack=4)
        assert viol == [] and undecl == [], (viol, undecl)


@pytest.mark.slow  # spins the whole serve_lm recipe; --gateway-smoke
# runs it by node id
def test_serve_lm_http_port_recipe(monkeypatch):
    """``recipes/serve_lm.py --http-port 0``: the recipe stands up the
    front door on an ephemeral port (exposed as ``serve_lm.GATEWAY``
    for in-process drivers), serves a real request, and shuts down
    clean when the duration lapses."""
    import importlib.util
    import os
    import sys

    from pytorch_distributed_tpu.gateway import generate

    recipes = os.path.join(os.path.dirname(__file__), os.pardir,
                           "recipes")
    monkeypatch.syspath_prepend(recipes)
    spec = importlib.util.spec_from_file_location(
        "serve_lm", os.path.join(recipes, "serve_lm.py"))
    serve_lm = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(serve_lm)
    monkeypatch.setattr(sys, "argv", [
        "serve_lm.py", "--tiny", "--replicas", "2",
        "--http-port", "0", "--http-duration", "6"])
    th = threading.Thread(target=serve_lm.main, daemon=True)
    th.start()
    try:
        assert _wait(lambda: serve_lm.GATEWAY is not None
                     and serve_lm.GATEWAY.port, timeout=90.0), \
            "recipe never brought the gateway up"
        base = f"http://127.0.0.1:{serve_lm.GATEWAY.port}"
        out = generate(base, [5, 6, 7, 8], 3)
        assert out["status"] == 200 and out["outcome"] == "complete", out
    finally:
        th.join(timeout=90.0)
    assert not th.is_alive(), "recipe did not shut down after duration"


def test_http_jsonl_validates_against_schema(gw_env):
    recs = _http_records(gw_env["jsonl"])
    assert len(recs) >= 5, "the module's traffic left too few records"
    assert validate_stream(recs) == [], validate_stream(recs)[:3]
    statuses = {r["status"] for r in recs}
    assert {200, 400, 429} <= statuses, statuses
    # rejected-before-admission records carry rid=-1 by contract
    assert all(r["rid"] == -1 for r in recs if r["status"] == 400)
