"""The multi-process/multi-host path, exercised for real on localhost.

Round-1 VERDICT missing #1: the TPU equivalent of the reference's core
artifact — multi-node DDP with env rendezvous, cross-host all-reduce,
rank-0 checkpointing, and the suspend agreement
(``restnet_ddp.py:87-99,154-155``) — had zero coverage. These tests spawn
TWO real ``jax.distributed`` processes on the CPU backend (4 virtual
devices each → an 8-device global mesh) and run the actual Trainer/DDP
code path end to end.

Slow (~2 min each: two CPU compiles per launch); marked ``multihost`` so
they can be deselected with ``-m 'not multihost'``.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import pytest

# jaxlint triage (ANALYSIS.md, "multihost triage"): every case below spawns
# a real 2-process jax.distributed run on the CPU backend, and this
# jaxlib's CPU client cannot compile cross-process programs at all — the
# first multihost-sharded device_put in the child dies with
# "XlaRuntimeError: INVALID_ARGUMENT: Multiprocess computations aren't
# implemented on the CPU backend" (see
# analysis.guards.backend_supports_multiprocess). The collective-axis and
# rendezvous lints come back clean on parallel/ and train/, so this is an
# environment capability gap, not a code defect: xfail (not skip) so a
# collectives-capable backend reports loudly via XPASS.
_MULTIPROCESS_XFAIL = pytest.mark.xfail(
    reason="jaxlint triage: jaxlib CPU backend lacks multiprocess "
    "collectives ('Multiprocess computations aren't implemented on the "
    "CPU backend'); rendezvous/collective-axis lints clean — see "
    "ANALYSIS.md",
    strict=False,
)

pytestmark = [pytest.mark.multihost, _MULTIPROCESS_XFAIL]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHILD = os.path.join(REPO, "tests", "multihost_child.py")


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def launch(rank: int, port: int, mode: str, save_dir: str,
           extra_env=None) -> subprocess.Popen:
    env = {
        k: v
        for k, v in os.environ.items()
        # A parent pytest env pins JAX to 8 devices / a platform; children
        # configure their own backend (multihost_child.py header).
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "PYTHONPATH")
    }
    env.update(
        MASTER_IP="127.0.0.1",
        MASTER_PORT=str(port),
        WORLD_SIZE="2",
        RANK=str(rank),
    )
    env.update(extra_env or {})
    return subprocess.Popen(
        [sys.executable, CHILD, mode, save_dir],
        env=env,
        cwd=REPO,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
    )


def communicate(procs, timeout=600):
    outs = []
    deadline = time.monotonic() + timeout
    for p in procs:
        try:
            out, err = p.communicate(timeout=max(deadline - time.monotonic(), 1))
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append((p.returncode, out, err))
    return outs


def result_line(stdout: str) -> dict:
    for line in reversed(stdout.strip().splitlines()):
        try:
            return json.loads(line)
        except json.JSONDecodeError:
            continue
    raise AssertionError(f"no JSON result in child stdout:\n{stdout}")


def test_two_process_rendezvous_and_agreement(tmp_path):
    """Env-contract rendezvous works; training state agrees bit-for-bit
    across hosts (the gradient psum really is global); rank-0-only
    checkpoint/metrics writes (``restnet_ddp.py:36,145``)."""
    port = free_port()
    save = os.fspath(tmp_path / "ddp")
    procs = [launch(r, port, "train", save) for r in (0, 1)]
    results = communicate(procs)
    for rc, out, err in results:
        assert rc == 0, f"child failed rc={rc}\nstdout:{out}\nstderr:{err}"
    r0, r1 = (result_line(out) for _, out, _ in results)
    assert r0["world"] == r1["world"] == 2
    # Replicated-state agreement: identical params and identical global
    # (psum'd) validation metrics on both hosts.
    assert r0["param_l1"] == r1["param_l1"]
    assert r0["val_loss"] == r1["val_loss"]
    assert r0["acc1"] == r1["acc1"]
    assert r0["final_step"] == r1["final_step"] > 0
    # rank-0-gated artifacts: exactly one process wrote them
    assert os.path.exists(os.path.join(save, "best.ckpt"))
    assert os.path.exists(os.path.join(save, "metrics.jsonl"))


def test_multihost_suspend_agreement_and_resume(tmp_path):
    """SIGTERM delivered to ONE (non-primary) host must make BOTH hosts
    checkpoint and yield together (suspend_sync_every=1 any-reduce,
    trainer._maybe_suspend), and a relaunch must resume mid-run
    (``restnet_ddp.py:127-132`` + SURVEY.md §3.5)."""
    port = free_port()
    save = os.fspath(tmp_path / "suspend")
    os.makedirs(save, exist_ok=True)
    procs = [launch(r, port, "suspend", save) for r in (0, 1)]

    # wait until both ranks have taken at least one optimizer step
    deadline = time.monotonic() + 420
    sentinels = [os.path.join(save, f"started.{r}") for r in (0, 1)]
    while time.monotonic() < deadline:
        if all(os.path.exists(s) for s in sentinels):
            break
        if any(p.poll() is not None for p in procs):
            results = communicate(procs, timeout=5)
            raise AssertionError(f"child exited before starting: {results}")
        time.sleep(0.5)
    else:
        for p in procs:
            p.kill()
        raise AssertionError("children never reached the training loop")

    procs[1].send_signal(signal.SIGTERM)  # the NON-primary host is preempted
    results = communicate(procs, timeout=300)
    for rc, out, err in results:
        # go_suspend exits 0 after the checkpoint is on disk
        assert rc == 0, f"suspend path failed rc={rc}\nstdout:{out}\nstderr:{err}"
        assert "suspend" in err.lower() or "suspend" in out.lower(), (out, err)
    assert os.path.exists(os.path.join(save, "latest.ckpt"))

    # relaunch: both hosts must resume from the checkpoint, not epoch 0 step 0
    port2 = free_port()
    procs = [launch(r, port2, "train", save) for r in (0, 1)]
    results = communicate(procs)
    for rc, out, err in results:
        assert rc == 0, f"resume failed rc={rc}\nstdout:{out}\nstderr:{err}"
    outs = [out for _, out, _ in results]
    assert any("resumed from" in o for o in outs), outs
    r0, r1 = (result_line(o) for o in outs)
    assert r0["param_l1"] == r1["param_l1"]


def test_lm_trainer_two_process_tp_sharded_checkpoint(tmp_path):
    """LMTrainer with ring attention + tensor parallelism spanning two
    processes: TP-sharded leaves are NOT locally addressable, so the
    checkpoint payload's gather_global must run its cross-process
    process_allgather on all ranks (the exact path that would deadlock if
    the gather were rank-0-gated). Asserts cross-host agreement of the
    gathered params and psum'd metrics, and that best.ckpt landed."""
    port = free_port()
    save = os.fspath(tmp_path / "lm")
    procs = [launch(r, port, "lm", save) for r in (0, 1)]
    results = communicate(procs)
    for rc, out, err in results:
        assert rc == 0, f"lm child failed rc={rc}\nstdout:{out}\nstderr:{err}"
    r0, r1 = (result_line(out) for _, out, _ in results)
    assert r0["world"] == r1["world"] == 2
    assert r0["param_l1"] == r1["param_l1"]
    assert r0["val_loss"] == r1["val_loss"]
    assert r0["final_step"] == r1["final_step"] > 0
    assert r0["sharded_ckpt_ok"] and r1["sharded_ckpt_ok"]
    assert os.path.isdir(os.path.join(save, "best.ckpt"))
    assert os.path.isdir(os.path.join(save, "latest.ckpt"))
    import glob

    for r in (0, 1):
        # r4 layout: token-named shard files (shard-<token>-NNNNN.npz)
        assert glob.glob(
            os.path.join(save, "latest.ckpt", f"shard-*-{r:05d}.npz")
        )


def test_suspend_sync_gt_one_defers_without_deadlock(tmp_path):
    """suspend_sync_every=3: a SIGTERM landing at a non-agreement step must
    be DEFERRED (latched) to the next agreement step, not acted on locally
    — acting locally sends one host into the collective checkpoint gather
    while the other runs the next train step (permanent hang). Regression
    for the r2 code-review finding."""
    port = free_port()
    save = os.fspath(tmp_path / "sync3")
    os.makedirs(save, exist_ok=True)
    procs = [
        launch(r, port, "suspend", save, extra_env={"SUSPEND_SYNC": "3"})
        for r in (0, 1)
    ]
    deadline = time.monotonic() + 420
    sentinels = [os.path.join(save, f"started.{r}") for r in (0, 1)]
    while time.monotonic() < deadline:
        if all(os.path.exists(s) for s in sentinels):
            break
        if any(p.poll() is not None for p in procs):
            raise AssertionError(f"child died early: {communicate(procs, 5)}")
        time.sleep(0.5)
    else:
        for p in procs:
            p.kill()
        raise AssertionError("children never reached the training loop")
    procs[1].send_signal(signal.SIGTERM)
    results = communicate(procs, timeout=300)  # would time out on deadlock
    for rc, out, err in results:
        assert rc == 0, f"rc={rc}\nstdout:{out}\nstderr:{err}"
    assert os.path.exists(os.path.join(save, "latest.ckpt"))


def test_multihost_crash_mid_save_keeps_previous_checkpoint(tmp_path):
    """VERDICT r3 #1 done-condition: a mid-save crash (data files written
    on both ranks, manifest never committed) must leave the PREVIOUS
    checkpoint restorable by a fresh 2-process job — the token-named file
    layout means an interrupted save never clobbers the committed one."""
    port = free_port()
    save = os.fspath(tmp_path / "crash")
    os.makedirs(save, exist_ok=True)
    procs = [launch(r, port, "lm_crash_save", save) for r in (0, 1)]
    results = communicate(procs)
    for rc, out, err in results:
        assert rc == 0, f"child failed rc={rc}\nstdout:{out}\nstderr:{err}"
    for _, out, _ in results:
        assert result_line(out)["crash_save_done"]

    # orphaned second-save data files exist next to the committed save
    import glob

    assert len(glob.glob(os.path.join(save, "latest.ckpt", "shard-*.npz"))) == 4

    port2 = free_port()
    procs = [launch(r, port2, "lm_crash_resume", save) for r in (0, 1)]
    results = communicate(procs)
    for rc, out, err in results:
        assert rc == 0, f"child failed rc={rc}\nstdout:{out}\nstderr:{err}"
    for _, out, _ in results:
        r = result_line(out)
        # the COMPLETE save (epoch 1, step 5) survives; the crashed one
        # (epoch 2, step 9) is invisible
        assert r["resumed"] and r["epoch"] == 1 and r["step"] == 5, r
