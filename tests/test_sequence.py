"""Ring attention == dense attention on the gathered sequence (value and
gradient), over real (data, seq) meshes on 8 virtual devices."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from pytorch_distributed_tpu.ops.attention import dense_attention
from pytorch_distributed_tpu.parallel import make_mesh
from pytorch_distributed_tpu.parallel.sequence import ring_attention_sharded


def qkv(b, l, h=2, d=8, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(b, l, h, d)), jnp.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("dp,sp", [(1, 8), (2, 4), (4, 2), (8, 1)])
@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_dense(devices8, dp, sp, causal):
    mesh = make_mesh(devices8, data_parallel=dp, seq_parallel=sp)
    q, k, v = qkv(b=dp, l=sp * 8)
    ref = dense_attention(q, k, v, causal=causal)

    sharding = NamedSharding(mesh, P("data", "seq"))
    qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))
    out = ring_attention_sharded(mesh, qs, ks, vs, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_ring_grads_match_dense(devices8):
    mesh = make_mesh(devices8, data_parallel=2, seq_parallel=4)
    q, k, v = qkv(b=2, l=32, seed=3)

    def loss_ref(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

    @jax.jit
    def loss_ring(q, k, v):
        return jnp.sum(ring_attention_sharded(mesh, q, k, v, causal=True) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    sharding = NamedSharding(mesh, P("data", "seq"))
    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(
        *(jax.device_put(x, sharding) for x in (q, k, v))
    )
    for a, b in zip(g_ref, g_ring):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)
