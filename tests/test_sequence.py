"""Ring attention == dense attention on the gathered sequence (value and
gradient), over real (data, seq) meshes on 8 virtual devices."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from pytorch_distributed_tpu.ops.attention import dense_attention
from pytorch_distributed_tpu.parallel import make_mesh
from pytorch_distributed_tpu.parallel.sequence import ring_attention_sharded


def qkv(b, l, h=2, d=8, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(b, l, h, d)), jnp.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("dp,sp", [(1, 8), (2, 4), (4, 2), (8, 1)])
@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_dense(devices8, dp, sp, causal):
    mesh = make_mesh(devices8, data_parallel=dp, seq_parallel=sp)
    q, k, v = qkv(b=dp, l=sp * 8)
    ref = dense_attention(q, k, v, causal=causal)

    sharding = NamedSharding(mesh, P("data", "seq"))
    qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))
    out = ring_attention_sharded(mesh, qs, ks, vs, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_ring_grads_match_dense(devices8):
    mesh = make_mesh(devices8, data_parallel=2, seq_parallel=4)
    q, k, v = qkv(b=2, l=32, seed=3)

    def loss_ref(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

    @jax.jit
    def loss_ring(q, k, v):
        return jnp.sum(ring_attention_sharded(mesh, q, k, v, causal=True) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    sharding = NamedSharding(mesh, P("data", "seq"))
    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(
        *(jax.device_put(x, sharding) for x in (q, k, v))
    )
    for a, b in zip(g_ref, g_ring):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)


# ---- zigzag layout ----

@pytest.mark.parametrize("dp,sp", [(2, 4), (1, 8)])
def test_zigzag_ring_matches_dense(devices8, dp, sp):
    from pytorch_distributed_tpu.parallel.sequence import (
        zigzag_shard,
        zigzag_unshard,
    )

    mesh = make_mesh(devices8[: dp * sp], data_parallel=dp, seq_parallel=sp)
    q, k, v = qkv(b=dp, l=sp * 8)
    ref = dense_attention(q, k, v, causal=True)
    sharding = NamedSharding(mesh, P("data", "seq"))
    qz, kz, vz = (
        jax.device_put(zigzag_shard(x, sp), sharding) for x in (q, k, v)
    )
    out = ring_attention_sharded(mesh, qz, kz, vz, causal=True,
                                 layout="zigzag")
    np.testing.assert_allclose(
        np.asarray(zigzag_unshard(out, sp)), np.asarray(ref),
        rtol=1e-5, atol=1e-5,
    )


def test_zigzag_ring_grads_match_dense(devices8):
    from pytorch_distributed_tpu.parallel.sequence import (
        zigzag_shard,
        zigzag_unshard,
    )

    sp = 4
    mesh = make_mesh(devices8, data_parallel=2, seq_parallel=sp)
    q, k, v = qkv(b=2, l=32, seed=7)

    def loss_ref(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

    @jax.jit
    def loss_zz(q, k, v):
        return jnp.sum(
            ring_attention_sharded(mesh, q, k, v, causal=True,
                                   layout="zigzag") ** 2
        )

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    sharding = NamedSharding(mesh, P("data", "seq"))
    g_zz = jax.grad(loss_zz, argnums=(0, 1, 2))(
        *(jax.device_put(zigzag_shard(x, sp), sharding) for x in (q, k, v))
    )
    for a, b in zip(g_ref, g_zz):
        np.testing.assert_allclose(
            np.asarray(zigzag_unshard(b, sp)), np.asarray(a),
            rtol=1e-4, atol=1e-5,
        )


def test_zigzag_shard_roundtrip_and_labels():
    """zigzag_shard/unshard invert, and shift_labels applied globally then
    zigzag-sharded keeps every (token -> next-token) pair aligned within
    each shard — the label mapping survives the permuted layout."""
    from pytorch_distributed_tpu.parallel.sequence import (
        zigzag_shard,
        zigzag_unshard,
    )
    from pytorch_distributed_tpu.train.lm import shift_labels

    s = 4
    tokens = np.arange(1, 33, dtype=np.int32)[None, :]  # [1, 32]
    labels, weights = shift_labels(tokens)
    tz = zigzag_shard(tokens, s)
    lz = zigzag_shard(labels, s)
    wz = zigzag_shard(weights, s)
    np.testing.assert_array_equal(zigzag_unshard(tz, s), tokens)
    flat_t, flat_l, flat_w = tz[0], lz[0], wz[0]
    # per-shard slices carry matching (token -> next global token) pairs
    # (tokens are arange, so the global next token is always token+1)
    for r in range(s):
        sl = slice(r * 8, (r + 1) * 8)
        assert (flat_l[sl][flat_w[sl] > 0] ==
                flat_t[sl][flat_w[sl] > 0] + 1).all()


def test_zigzag_balances_the_causal_critical_path(devices8):
    """The measured schedule: executed block area per rank, counted at
    runtime inside the cond branches. Contiguous causal ring: rank r folds
    r+1 shards, so the slowest rank does s*(L/s)^2 work while the mean is
    ~half that — the critical path (max) is what wall-clock follows on a
    real ring. Zigzag: every rank does the same ~(2s+1)*(L/2s)^2, cutting
    the max ~2x at sp=8 with identical totals."""
    import functools

    from pytorch_distributed_tpu.parallel.mesh import shard_map
    from pytorch_distributed_tpu.parallel.sequence import (
        ring_attention,
        zigzag_shard,
    )

    sp = 8
    mesh = make_mesh(devices8, data_parallel=1, seq_parallel=sp)
    q, k, v = qkv(b=1, l=sp * 16)

    def counts_for(layout, inputs):
        fn = shard_map(
            functools.partial(
                ring_attention, causal=True, layout=layout,
                with_schedule_counts=True,
            ),
            mesh=mesh,
            in_specs=(P("data", "seq"),) * 3,
            out_specs=(P("data", "seq"), P("seq")),
            check_vma=False,
        )
        _, counts = fn(*inputs)
        return np.asarray(counts)

    sharding = NamedSharding(mesh, P("data", "seq"))
    cont = counts_for(
        "contiguous", [jax.device_put(x, sharding) for x in (q, k, v)]
    )
    zz = counts_for(
        "zigzag",
        [jax.device_put(zigzag_shard(x, sp), sharding) for x in (q, k, v)],
    )
    assert cont.shape == zz.shape == (sp,)
    # contiguous: rank r folds r+1 shards of area (L/s)^2
    shard_area = (q.shape[1] // sp) ** 2
    np.testing.assert_allclose(cont, shard_area * np.arange(1, sp + 1))
    # zigzag: perfectly balanced, (2s+1) quarter-shard blocks per rank
    np.testing.assert_allclose(zz, zz[0])
    assert zz[0] == (2 * sp + 1) * shard_area / 4
    # the critical path (max over ranks) halves; totals stay comparable
    assert zz.max() <= 0.55 * cont.max()
    assert abs(zz.sum() - cont.sum()) / cont.sum() < 0.15
