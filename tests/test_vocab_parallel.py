"""Megatron vocab parallelism (VERDICT r4 next #4): wte + lm_head shard
their vocab dim over the model axis. Parity vs replicated at tp∈{2,4},
through the fused-CE loss tail (cross-shard logsumexp) AND the
materialized-logits path (masked-lookup psum embedding + all_gathered
head), decode parity via generate_tp, and checkpoint interchangeability
across tp degrees (global param shapes; placement does the sharding)."""

import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

pytestmark = pytest.mark.slow

from pytorch_distributed_tpu.models.transformer import tiny_config  # noqa: E402
from pytorch_distributed_tpu.parallel import make_mesh  # noqa: E402
from pytorch_distributed_tpu.train.lm import (  # noqa: E402
    create_lm_state,
    empty_lm_metrics,
    make_lm_eval_step,
    make_lm_train_step,
    shard_lm_state,
    shift_labels,
)
def _cfgs(tp):
    rep = tiny_config(vocab_size=96, num_layers=2, num_heads=4)
    vp = dataclasses.replace(
        rep, model_axis="model", tp_size=tp, vocab_parallel=True
    )
    return rep, vp


def _batch(cfg, b=4, l=32, seed=0):
    r = np.random.RandomState(seed)
    tokens = r.randint(0, cfg.vocab_size, (b, l)).astype(np.int32)
    labels, w = shift_labels(tokens)
    return {"tokens": jnp.asarray(tokens), "labels": jnp.asarray(labels),
            "weights": jnp.asarray(w)}


def _run_steps(cfg, mesh, batch, n=3, fused=True):
    state = create_lm_state(cfg, optax.sgd(0.1), jax.random.key(0),
                            init_len=32)
    state, specs = shard_lm_state(mesh, state, cfg)
    step = make_lm_train_step(mesh, state_specs=specs, config=cfg,
                              fused_ce=fused, fused_ce_block_n=16)
    losses = []
    for _ in range(n):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    return losses, jax.device_get(state.params), state, specs


@pytest.mark.parametrize("tp", [2, 4])
@pytest.mark.parametrize("fused", [True, False])
def test_train_parity_vs_replicated(tp, fused):
    rep, vp = _cfgs(tp)
    batch = _batch(rep)
    mesh_rep = make_mesh(jax.devices()[:2], data_parallel=2, seq_parallel=1,
                         model_parallel=1)
    mesh_vp = make_mesh(jax.devices()[:2 * tp], data_parallel=2,
                        seq_parallel=1, model_parallel=tp)
    l_rep, p_rep, *_ = _run_steps(rep, mesh_rep, batch, fused=fused)
    l_vp, p_vp, state_vp, _ = _run_steps(vp, mesh_vp, batch, fused=fused)
    np.testing.assert_allclose(l_vp, l_rep, rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6),
        p_vp, p_rep,
    )
    # the vocab dims really are sharded on the mesh
    wte = state_vp.params["wte"]["embedding"]
    assert next(iter(wte.addressable_shards)).data.shape[0] == \
        wte.shape[0] // tp
    head = state_vp.params["lm_head"]["kernel"]
    assert next(iter(head.addressable_shards)).data.shape[1] == \
        head.shape[1] // tp


def test_generate_tp_vocab_parallel_parity():
    from pytorch_distributed_tpu.models.generate import generate, generate_tp

    rep, vp = _cfgs(2)
    mesh = make_mesh(jax.devices()[:2], data_parallel=1, seq_parallel=1,
                     model_parallel=2)
    state = create_lm_state(rep, optax.sgd(0.1), jax.random.key(1),
                            init_len=32)
    prompt = jnp.asarray(
        np.random.RandomState(3).randint(1, 96, (2, 8)), jnp.int32
    )
    out_rep = generate(rep, state.params, prompt, jax.random.key(5),
                       max_new_tokens=8)
    out_vp = generate_tp(mesh, vp, state.params, prompt, jax.random.key(5),
                         max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(out_vp), np.asarray(out_rep))


def test_checkpoint_interchangeable_across_degrees(tmp_path):
    """Train 2 steps vocab-parallel at tp=2, save sharded, restore into
    the REPLICATED config — eval loss must match the vp run's eval
    (global param shapes make the checkpoint degree-free)."""
    from pytorch_distributed_tpu.parallel.mesh import specs_to_shardings
    from pytorch_distributed_tpu.utils.checkpoint import (
        load_sharded,
        save_sharded,
    )

    rep, vp = _cfgs(2)
    batch = _batch(rep)
    mesh_vp = make_mesh(jax.devices()[:4], data_parallel=2, seq_parallel=1,
                        model_parallel=2)
    _, _, state_vp, specs_vp = _run_steps(vp, mesh_vp, batch, n=2)
    ev_vp = make_lm_eval_step(mesh_vp, state_specs=specs_vp, config=vp)
    acc_vp = jax.device_get(ev_vp(state_vp, batch, empty_lm_metrics()))

    d = str(tmp_path / "vp.ckpt")
    save_sharded(d, {"state": state_vp})

    mesh_rep = make_mesh(jax.devices()[:2], data_parallel=2, seq_parallel=1,
                         model_parallel=1)
    state_rep = create_lm_state(rep, optax.sgd(0.1), jax.random.key(0),
                                init_len=32)
    state_rep, specs_rep = shard_lm_state(mesh_rep, state_rep, rep)
    restored = load_sharded(
        d, {"state": state_rep},
        {"state": specs_to_shardings(mesh_rep, specs_rep)},
    )
    state_rep = restored["state"]
    ev_rep = make_lm_eval_step(mesh_rep, state_specs=specs_rep, config=rep)
    acc_rep = jax.device_get(ev_rep(state_rep, batch, empty_lm_metrics()))
    np.testing.assert_allclose(
        float(acc_rep["loss_sum"]), float(acc_vp["loss_sum"]), rtol=1e-5
    )


def test_vocab_parallel_rejected_under_pp():
    from pytorch_distributed_tpu.train.pp import create_pp_lm_state

    _, vp = _cfgs(2)
    vp = dataclasses.replace(vp, num_layers=4)
    with pytest.raises(ValueError, match="vocab_parallel"):
        create_pp_lm_state(vp, 2, optax.sgd(0.1), jax.random.key(0))


def test_vocab_size_divisibility_checked():
    with pytest.raises(ValueError, match="not divisible"):
        tiny_config(vocab_size=97, model_axis="model", tp_size=2,
                    vocab_parallel=True)


def test_vocab_parallel_composes_with_fsdp():
    """The vp rules CLAIM wte/lm_head, so the FSDP overlay must leave
    them TP-sharded (not ZeRO-sharded) and the step must still match the
    plain replicated run."""
    from pytorch_distributed_tpu.ops.optim import spec_axes

    rep, vp = _cfgs(2)
    batch = _batch(rep)
    mesh_rep = make_mesh(jax.devices()[:2], data_parallel=2, seq_parallel=1,
                         model_parallel=1)
    mesh_vp = make_mesh(jax.devices()[:4], data_parallel=2, seq_parallel=1,
                        model_parallel=2)
    l_rep, p_rep, *_ = _run_steps(rep, mesh_rep, batch)

    state = create_lm_state(vp, optax.sgd(0.1), jax.random.key(0),
                            init_len=32)
    state, specs = shard_lm_state(mesh_vp, state, vp, fsdp=True)
    assert set(spec_axes(specs.params["lm_head"]["kernel"])) == {"model"}
    assert set(spec_axes(specs.params["wte"]["embedding"])) == {"model"}
    step = make_lm_train_step(mesh_vp, state_specs=specs, config=vp,
                              fsdp=True, fused_ce_block_n=16)
    losses = []
    for _ in range(3):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    np.testing.assert_allclose(losses, l_rep, rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6),
        jax.device_get(state.params), p_rep,
    )
