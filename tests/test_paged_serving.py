"""Paged-KV serving engine (round 6 tentpole): block allocator, paged
attention math, dense-vs-paged token parity (single device and TP=2),
chunked-prefill equivalence, scheduler policy + exact metrics, and the
admission-cost scaling micro-bench (cost-analysis bytes: paged flat in
pool size, dense growing with it)."""

import dataclasses
import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.models.generate import ContinuousBatcher, generate
from pytorch_distributed_tpu.models.transformer import (
    TransformerLM,
    tiny_config,
)
from pytorch_distributed_tpu.ops.attention import paged_attention
from pytorch_distributed_tpu.serving import (
    TRASH_BLOCK,
    BlockAllocator,
    PagedEngine,
    Scheduler,
    blocks_needed,
)
from pytorch_distributed_tpu.serving.engine import ChunkJob


def setup(max_seq_len=96, **over):
    cfg = tiny_config(attention="dense", max_seq_len=max_seq_len, **over)
    params = TransformerLM(cfg).init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return cfg, params


def greedy_reference(cfg, params, prompt, max_new):
    full = generate(
        cfg, params, jnp.asarray(prompt)[None, :], jax.random.key(1),
        max_new_tokens=max_new, temperature=0.0,
    )
    return np.asarray(full)[0, len(prompt):]


# ---------------------------------------------------------------------------
# block allocator (pure host logic — fast tier)
# ---------------------------------------------------------------------------


def test_allocator_alloc_free_reuse_oom():
    a = BlockAllocator(8)  # ids 1..7 usable, 0 is trash
    assert a.available == 7 and a.in_use == 0
    c0 = a.alloc(0, 3)
    assert c0 == [1, 2, 3]  # deterministic first-allocation order
    assert TRASH_BLOCK not in c0
    c1 = a.alloc(1, 3)
    assert c1 == [4, 5, 6]
    # OOM is a deterministic None with state UNCHANGED — the queue signal
    assert a.alloc(2, 2) is None
    assert a.available == 1 and a.chain(2) == []
    # free → LIFO reuse: the just-freed blocks come back first
    a.free(0)
    assert a.available == 4
    c2 = a.alloc(2, 2)
    assert c2 == [1, 2]
    # double-alloc for a live owner is a bug, not a silent leak
    with pytest.raises(ValueError, match="already holds"):
        a.alloc(1, 1)
    a.free(99)  # unknown owner: no-op
    with pytest.raises(ValueError, match="n_blocks"):
        BlockAllocator(1)


def test_blocks_needed_covers_padded_prefill_and_decode():
    # prompt 9 padded to chunk 16 → 1 block of 16; decode to 9+20=29 → 2
    assert blocks_needed(9, 20, block_len=16, chunk=16) == 2
    # chunk padding dominates: prompt 17 pads to 32 > 17+4
    assert blocks_needed(17, 4, block_len=16, chunk=16) == 2
    assert blocks_needed(1, 1, block_len=16, chunk=16) == 1


# ---------------------------------------------------------------------------
# paged attention math (pure op — fast tier)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("h_kv,c", [(4, 1), (4, 5), (2, 5)])
def test_paged_attention_matches_masked_reference(h_kv, c):
    """Gather-over-blocks attention == a straight masked softmax over the
    same logical sequences, including the GQA narrow-head layout."""
    b, h, d, bl, w = 2, 4, 8, 4, 3
    L = w * bl
    rng = np.random.default_rng(0)
    k_seq = rng.normal(size=(b, L, h_kv, d)).astype(np.float32)
    v_seq = rng.normal(size=(b, L, h_kv, d)).astype(np.float32)
    q = jnp.asarray(rng.normal(size=(b, c, h, d)).astype(np.float32))
    # per-request block chains laid out non-contiguously in the pool
    n_blocks = 1 + b * w
    pool_k = np.zeros((n_blocks, bl, h_kv, d), np.float32)
    pool_v = np.zeros((n_blocks, bl, h_kv, d), np.float32)
    tables = np.zeros((b, w), np.int32)
    order = rng.permutation(np.arange(1, n_blocks))
    for bi in range(b):
        for wi in range(w):
            blk = int(order[bi * w + wi])
            tables[bi, wi] = blk
            pool_k[blk] = k_seq[bi, wi * bl:(wi + 1) * bl]
            pool_v[blk] = v_seq[bi, wi * bl:(wi + 1) * bl]
    q_positions = np.stack([
        np.arange(L - c, L), np.arange(3, 3 + c)
    ])[:b].astype(np.int32)

    out = paged_attention(
        q, jnp.asarray(pool_k), jnp.asarray(pool_v), jnp.asarray(tables),
        jnp.asarray(q_positions),
    )

    group = h // h_kv
    kw = np.repeat(k_seq, group, axis=2)  # widen narrow heads
    vw = np.repeat(v_seq, group, axis=2)
    ref = np.zeros((b, c, h, d), np.float32)
    for bi in range(b):
        for ci in range(c):
            p = int(q_positions[bi, ci])
            logits = np.einsum(
                "hd,khd->hk", np.asarray(q[bi, ci]) * d ** -0.5,
                kw[bi, :p + 1],
            )
            probs = np.exp(logits - logits.max(-1, keepdims=True))
            probs /= probs.sum(-1, keepdims=True)
            ref[bi, ci] = np.einsum("hk,khd->hd", probs, vw[bi, :p + 1])
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-5)


def test_paged_attention_gather_impl_flag():
    z = jnp.zeros((1, 1, 2, 4))
    pool = jnp.zeros((2, 4, 2, 4))
    t = jnp.zeros((1, 1), jnp.int32)
    p = jnp.zeros((1, 1), jnp.int32)
    with pytest.raises(ValueError, match="gather_impl"):
        paged_attention(z, pool, pool, t, p, gather_impl="nope")
    # round 12: "pallas" is no longer reserved — it dispatches to the
    # fused kernel (ops/paged_flash.py; parity in tests/test_paged_
    # kernel.py) and must agree with the dense spelling even on this
    # degenerate all-zeros pool
    out = paged_attention(z, pool, pool, t, p, gather_impl="pallas")
    ref = paged_attention(z, pool, pool, t, p, gather_impl="dense")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref))


# ---------------------------------------------------------------------------
# admission cost scaling (compiled cost analysis — deterministic, fast tier)
# ---------------------------------------------------------------------------


def _total_bytes(compiled):
    ca = compiled.cost_analysis()
    ca = ca[0] if isinstance(ca, (list, tuple)) else ca
    return float(ca["bytes accessed"])


def test_admission_cost_paged_flat_dense_grows():
    """THE tentpole claim, asserted without wall-clock flakiness: grow
    the KV capacity 8x (max_seq_len 256 → 2048 at fixed slots — the
    dense layout's pool is n_slots × max_seq_len rows) and compare each
    layout's compiled admission program by XLA's bytes-accessed cost.
    Dense admission writes a full per-slot row → must grow; paged
    admission touches O(prompt) blocks → must stay flat. rope positions
    keep the param tree identical across capacities, so the cache is the
    only thing that scales."""

    def build(max_len):
        cfg = tiny_config(
            attention="dense", max_seq_len=max_len, pos_embedding="rope",
            num_heads=4, embed_dim=64,
        )
        params = TransformerLM(cfg).init(
            jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        return cfg, params

    prompt = np.arange(1, 10, dtype=np.int32)  # 9 tokens, bucket 16
    padded = np.zeros((1, 16), np.int32)
    padded[0, :len(prompt)] = prompt
    costs = {}
    for max_len in (256, 2048):
        cfg, params = build(max_len)
        dense = ContinuousBatcher(
            cfg, params, n_slots=8, prefill_bucket=16, cache_layout="dense"
        )
        dense_bytes = _total_bytes(dense._submit_one.lower(
            params, jnp.asarray(padded), jnp.asarray([9], jnp.int32),
            dense.cache, dense.logits, jnp.asarray(0),
        ).compile())
        eng = PagedEngine(cfg, params, n_slots=8, block_len=16,
                          prefill_chunk=16)
        assert eng.admit(0, len(prompt), 6)
        paged_bytes = _total_bytes(eng._chunk_fn(1, 1).lower(
            params, eng.cache, eng.logits, jnp.asarray(padded),
            jnp.asarray([0], jnp.int32), jnp.asarray(eng.tables[:1, :1]),
            jnp.asarray([0], jnp.int32), jnp.asarray([True]),
            jnp.asarray([len(prompt) - 1], jnp.int32),
        ).compile())
        costs[max_len] = (dense_bytes, paged_bytes)

    dense_ratio = costs[2048][0] / costs[256][0]
    paged_ratio = costs[2048][1] / costs[256][1]
    # measured ~3.2x vs 1.00x on jaxlib 0.4.37; thresholds leave slack
    # for compiler drift while keeping the asymptotic claim falsifiable
    assert dense_ratio > 1.5, (
        f"dense admission no longer scales with capacity ({dense_ratio:.2f}"
        "x) — if XLA learned to elide the row write, retire this bench "
        "and the paged engine's motivation section"
    )
    assert paged_ratio < 1.1, (
        f"paged admission grew {paged_ratio:.2f}x with pool capacity — "
        "an O(pool) term leaked into the chunk program"
    )


# ---------------------------------------------------------------------------
# smoke (fast tier — scripts/ci_check.sh --serving-smoke runs exactly this)
# ---------------------------------------------------------------------------


def test_serving_smoke():
    """One full paged cycle: submit → decode steps → drain; slots and
    blocks return to the pool."""
    cfg, params = setup(max_seq_len=64)
    b = ContinuousBatcher(cfg, params, n_slots=2, prefill_bucket=8)
    assert b.cache_layout == "paged"
    slot = b.submit(np.arange(1, 10, dtype=np.int32), 4)
    produced = []
    while any(b.remaining > 0):
        produced += b.step()
    assert len(produced) == 4 and all(s == slot for s, _t in produced)
    assert b.engine.allocator.in_use == 0  # chain returned
    assert (b.engine.tables[slot] == TRASH_BLOCK).all()
    assert b.free_slots() == [0, 1]


# ---------------------------------------------------------------------------
# scheduler policy + exact metrics (fast tier — tiny model)
# ---------------------------------------------------------------------------


def test_scheduler_oom_queues_fifo_and_drains():
    """A pool too small for everyone at once: admissions stop at the
    first request that cannot get its chain (strict FIFO), the rest wait
    in queue, and everything still completes as blocks free up."""
    cfg, params = setup(max_seq_len=64)
    # block_len 8, chunk 8: each request (l=9 → padded 16, +4 decode) needs
    # 2 blocks; pool of 5 usable blocks fits TWO resident requests
    s = Scheduler(cfg, params, n_slots=4, n_blocks=6, block_len=8,
                  prefill_chunk=8)
    prompt = np.arange(1, 10, dtype=np.int32)
    rids = [s.submit(prompt, 4) for _ in range(4)]
    s.step()
    m = s.metrics()
    assert m["admitted"] == 2  # 3rd request OOM'd → queued, 4th behind it
    assert m["queue_depth"] == 2
    assert m["pool_blocks_in_use"] == 4
    outs = s.drain()
    assert sorted(outs) == sorted(rids)
    assert all(len(v) == 4 for v in outs.values())
    ref = list(greedy_reference(cfg, params, prompt, 4))
    for r in rids:
        assert outs[r] == ref  # queueing never changes tokens
    m = s.metrics()
    assert m["completed"] == 4 and m["queue_depth"] == 0
    assert m["pool_blocks_in_use"] == 0 and m["occupancy"] == 0.0
    # later arrivals waited: admission latency in steps is exact
    assert m["admission_latency_steps_mean"] > 0


def test_scheduler_metrics_exact_accounting():
    cfg, params = setup(max_seq_len=64)
    s = Scheduler(cfg, params, n_slots=1, block_len=8, prefill_chunk=8)
    prompt = np.arange(1, 6, dtype=np.int32)
    r0 = s.submit(prompt, 3)
    r1 = s.submit(prompt, 2)
    outs = s.drain()
    m = s.metrics()
    assert m["tokens_out"] == 5 == len(outs[r0]) + len(outs[r1])
    assert m["admitted"] == m["completed"] == 2
    # one slot: r0 runs steps 0..3 (chunk step + 3 decode), r1 admitted
    # the step after r0 retires → latency is deterministic and positive
    assert s.resident == {} and not s.queue
    assert 0.0 <= m["occupancy_mean"] <= 1.0
    assert 0.0 <= m["padding_waste_frac"] <= 1.0
    assert m["tokens_per_s"] > 0
    # padding waste while resident: 5-token prompt in 8-token blocks
    s2 = Scheduler(cfg, params, n_slots=1, block_len=8, prefill_chunk=8)
    s2.submit(prompt, 2)
    s2.step()  # chunk runs; first token decoded
    w = s2.metrics()["padding_waste_frac"]
    # 1 block of 8 allocated (covers 5+2), 5+1 tokens written → 2/8 waste
    assert abs(w - 2 / 8) < 1e-9


def test_scheduler_eos_early_retirement_frees_blocks():
    cfg, params = setup(max_seq_len=64)
    prompt = np.arange(1, 10, dtype=np.int32)
    first = int(greedy_reference(cfg, params, prompt, 1)[0])
    s = Scheduler(cfg, params, n_slots=1, block_len=8, prefill_chunk=8,
                  eos_id=first)
    rid = s.submit(prompt, 10)
    outs = s.drain()
    assert outs[rid] == [first]  # retired after 1 of 10
    assert s.metrics()["pool_blocks_in_use"] == 0


def test_scheduler_submit_validation():
    cfg, params = setup(max_seq_len=32)
    s = Scheduler(cfg, params, n_slots=1, block_len=8, prefill_chunk=8)
    with pytest.raises(ValueError, match="at least one token"):
        s.submit(np.zeros((0,), np.int32), 2)
    with pytest.raises(ValueError, match="max_seq_len"):
        s.submit(np.arange(1, 30, dtype=np.int32), 8)


def test_engine_rejects_oversized_chunk_and_chain():
    cfg, params = setup(max_seq_len=32)
    eng = PagedEngine(cfg, params, n_slots=1, block_len=8, prefill_chunk=8)
    with pytest.raises(ValueError, match="chunk"):
        eng.run_chunks([ChunkJob(0, np.zeros(4, np.int32), 0, True, 0)])
    with pytest.raises(ValueError, match="table width"):
        eng.admit(0, 30, 30)  # needs > max_seq_len worth of blocks


# ---------------------------------------------------------------------------
# token parity + chunked prefill equivalence (slow tier, like test_serving)
# ---------------------------------------------------------------------------


def _drive_batcher(b, prompts, budgets):
    got, slot_of, pending = {}, {}, list(range(len(prompts)))
    while pending or any(b.remaining > 0):
        while pending and b.free_slots():
            i = pending.pop(0)
            slot_of[i] = b.submit(prompts[i], budgets[i])
            got[i] = []
        for slot, token in b.step():
            req = next(i for i, s in slot_of.items()
                       if s == slot and len(got[i]) < budgets[i])
            got[req].append(token)
    return got


@pytest.mark.slow
def test_paged_batcher_matches_dense_continuous():
    """Staggered admissions, slot reuse, mixed budgets: the paged engine
    must emit token-identical greedy streams to the dense layout."""
    cfg, params = setup()
    rng = np.random.default_rng(1)
    prompts = [
        rng.integers(1, cfg.vocab_size, (l,)).astype(np.int32)
        for l in (7, 13, 4, 21)
    ]
    budgets = [6, 10, 8, 5]
    dense = _drive_batcher(
        ContinuousBatcher(cfg, params, n_slots=2, prefill_bucket=8,
                          cache_layout="dense"),
        prompts, budgets,
    )
    paged = _drive_batcher(
        ContinuousBatcher(cfg, params, n_slots=2, prefill_bucket=8,
                          cache_layout="paged"),
        prompts, budgets,
    )
    assert dense == paged


@pytest.mark.slow
@pytest.mark.parametrize("kv_heads", [None, 2])
def test_paged_batcher_tp_matches_dense(kv_heads):
    """TP=2 CPU mesh: the paged TP batcher (head-sharded block pool,
    Megatron collectives inside the chunk/decode programs) matches the
    replicated DENSE batcher token-for-token — and really is sharded."""
    from pytorch_distributed_tpu.parallel import make_mesh

    rep = tiny_config(attention="dense", max_seq_len=96, num_heads=4,
                      num_kv_heads=kv_heads)
    tpcfg = dataclasses.replace(rep, model_axis="model", tp_size=2)
    params = TransformerLM(rep).init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    mesh = make_mesh(jax.devices()[:2], data_parallel=1, seq_parallel=1,
                     model_parallel=2)
    rng = np.random.default_rng(2)
    prompts = [
        rng.integers(1, rep.vocab_size, (l,)).astype(np.int32)
        for l in (5, 11, 7)
    ]
    budgets = [6, 6, 6]
    dense_rep = _drive_batcher(
        ContinuousBatcher(rep, params, n_slots=2, prefill_bucket=8,
                          cache_layout="dense"),
        prompts, budgets,
    )
    paged_tp = ContinuousBatcher(tpcfg, params, n_slots=2, prefill_bucket=8,
                                 mesh=mesh, cache_layout="paged")
    assert _drive_batcher(paged_tp, prompts, budgets) == dense_rep
    # the pool really is head-sharded at rest
    leaf = jax.tree.leaves(paged_tp.cache)[0]
    assert next(iter(leaf.addressable_shards)).data.shape[2] == \
        leaf.shape[2] // 2


@pytest.mark.slow
def test_chunked_prefill_matches_whole_prefill():
    """A long prompt prefilled in 8-token chunks produces the same
    first-token logits path (hence identical greedy tokens) as one-shot
    prefill — the chunk boundary cannot change the math."""
    cfg, params = setup()
    rng = np.random.default_rng(3)
    prompt = rng.integers(1, cfg.vocab_size, (29,)).astype(np.int32)
    ref = greedy_reference(cfg, params, prompt, 8)
    for bucket in (8, 16, 32):  # 4 chunks, 2 chunks, whole-prompt
        b = ContinuousBatcher(cfg, params, n_slots=1,
                              prefill_bucket=bucket)
        slot = b.submit(prompt, 8)
        got = []
        while any(b.remaining > 0):
            got += [t for _s, t in b.step()]
        np.testing.assert_array_equal(
            np.asarray(got, np.int32), ref, err_msg=f"bucket {bucket}"
        )


@pytest.mark.slow
def test_scheduler_interleaves_long_prefill_with_decode():
    """Chunked prefill is the point: while a LONG prompt prefills chunk
    by chunk, an already-resident request keeps decoding every step (the
    dense layout would have stalled it for the whole prefill)."""
    cfg, params = setup(max_seq_len=96)
    s = Scheduler(cfg, params, n_slots=2, block_len=8, prefill_chunk=8,
                  admit_per_step=1)
    short = np.arange(1, 6, dtype=np.int32)
    long = np.arange(1, 41, dtype=np.int32)  # 5 chunks of 8
    produced = {}

    def tick():
        events = s.step()
        for rid, tok in events:
            produced.setdefault(rid, []).append(tok)
        return dict(events)

    r_short = s.submit(short, 12)
    tick()  # short admitted + prefilled (1 chunk) + first token
    r_long = s.submit(long, 2)
    short_tokens_during_long_prefill = 0
    for _ in range(5):  # the long prompt's 5 prefill-chunk steps
        if r_short in tick():
            short_tokens_during_long_prefill += 1
    assert short_tokens_during_long_prefill == 5  # never stalled
    for rid, toks in s.drain().items():
        produced.setdefault(rid, []).extend(toks)
    assert produced[r_short] == list(greedy_reference(cfg, params, short, 12))
    assert produced[r_long] == list(greedy_reference(cfg, params, long, 2))
