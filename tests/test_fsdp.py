"""FSDP/ZeRO-3 over the data axis: parity with replicated DP, the memory
win, and checkpoint interchange (SURVEY.md §2c's last open row)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from pytorch_distributed_tpu.models.resnet import BasicBlock, ResNet
from pytorch_distributed_tpu.ops.optim import sgd_with_weight_decay
from pytorch_distributed_tpu.parallel import (
    make_mesh,
    replicated_sharding,
    shard_batch,
    shard_fsdp_state,
)
from pytorch_distributed_tpu.parallel.fsdp import fsdp_dim, fsdp_param_specs
from pytorch_distributed_tpu.train.state import TrainState
from pytorch_distributed_tpu.train.step import make_eval_step, make_train_step


def tiny_model():
    return ResNet(stage_sizes=(1, 1), block_cls=BasicBlock, num_classes=10,
                  num_filters=16)


def make_state(mesh):
    tx = sgd_with_weight_decay(0.1, momentum=0.9, weight_decay=1e-4)
    return TrainState.create(tiny_model(), tx, jax.random.key(0), (1, 16, 16, 3))


def batch_for(mesh, n=16, seed=0):
    rng = np.random.default_rng(seed)
    return shard_batch(mesh, {
        "image": rng.normal(size=(n, 16, 16, 3)).astype(np.float32),
        "label": rng.integers(0, 10, n).astype(np.int32),
    })


def test_fsdp_dim_selection():
    assert fsdp_dim((4096, 128), 8) == 0        # largest divisible dim
    assert fsdp_dim((127, 4096), 8) == 1        # only dim 1 divisible
    assert fsdp_dim((63,), 8) is None           # tiny -> replicate
    assert fsdp_dim((1031, 1031), 8) is None    # nothing divisible
    assert fsdp_dim((), 8) is None              # scalar


def test_fsdp_specs_and_memory_win(devices8):
    mesh = make_mesh(devices8)
    state = make_state(mesh)
    sharded, specs = shard_fsdp_state(mesh, state)
    param_specs = fsdp_param_specs(state.params, mesh)
    # at least the conv kernels and fc weights must actually shard
    sharded_leaves = [s for s in jax.tree.leaves(
        param_specs, is_leaf=lambda x: isinstance(x, P)) if s != P()]
    assert len(sharded_leaves) >= 4
    # tiny leaves (fc kernel here is 32x10) stay replicated by threshold
    assert param_specs["fc"]["kernel"] == P()
    # exact memory win on the largest leaf: its sharded dim is 1/8 per device
    flat = dict(
        (str(p), (v, s))
        for (p, v), (_, s) in zip(
            jax.tree_util.tree_leaves_with_path(sharded.params),
            jax.tree_util.tree_leaves_with_path(
                param_specs, is_leaf=lambda x: isinstance(x, P)
            ),
        )
    )
    path, (leaf, spec) = max(flat.items(), key=lambda kv: kv[1][0].size)
    d = next(i for i, part in enumerate(spec) if part is not None)
    expect = tuple(
        n // 8 if i == d else n for i, n in enumerate(leaf.shape)
    )
    assert {s.data.shape for s in leaf.addressable_shards} == {expect}, path
    # the total addressable state is ~1/8 of a replicated run's per-device
    # copy for sharded leaves (each device holds exactly one shard)
    for s in leaf.addressable_shards:
        assert s.data.size == leaf.size // 8
    # momentum trace shards identically to its param
    mom_match = [
        m for m in jax.tree.leaves(sharded.opt_state)
        if isinstance(m, jax.Array) and m.shape == leaf.shape
        and {s.data.shape for s in m.addressable_shards} == {expect}
    ]
    assert mom_match


def test_fsdp_training_matches_replicated_dp(devices8):
    mesh = make_mesh(devices8)

    def run(fsdp, steps=4):
        state = make_state(mesh)
        if fsdp:
            state, specs = shard_fsdp_state(mesh, state)
        else:
            state = jax.device_put(state, replicated_sharding(mesh))
            specs = None
        step = make_train_step(mesh, state_specs=specs)
        losses = []
        for i in range(steps):
            state, metrics = step(state, batch_for(mesh, seed=i))
            losses.append(float(metrics["loss"]))
        return state, losses

    state_f, losses_f = run(True)
    state_r, losses_r = run(False)
    np.testing.assert_allclose(losses_f, losses_r, rtol=1e-5)
    flat_r = {str(p): v for p, v in
              jax.tree_util.tree_leaves_with_path(state_r.params)}
    for path, leaf in jax.tree_util.tree_leaves_with_path(state_f.params):
        np.testing.assert_allclose(
            np.asarray(leaf), np.asarray(flat_r[str(path)]),
            rtol=1e-4, atol=1e-6, err_msg=str(path),
        )


def test_fsdp_eval_matches_replicated(devices8):
    from pytorch_distributed_tpu.ops.metrics import ClassificationMetrics

    mesh = make_mesh(devices8)
    state = make_state(mesh)
    state_r = jax.device_put(state, replicated_sharding(mesh))
    state_f, specs = shard_fsdp_state(mesh, state)
    batch = batch_for(mesh, seed=3)
    empty = lambda: jax.device_put(ClassificationMetrics.empty(),
                                   replicated_sharding(mesh))
    m_r = make_eval_step(mesh)(state_r, batch, empty())
    m_f = make_eval_step(mesh, state_specs=specs)(state_f, batch, empty())
    r, f = jax.device_get(m_r).summary(), jax.device_get(m_f).summary()
    assert r["acc1"] == f["acc1"] and r["loss"] == pytest.approx(f["loss"], rel=1e-6)


def test_fsdp_trainer_end_to_end_with_resume(tmp_path, devices8):
    """Trainer(fsdp=True): trains, checkpoints (canonical global layout),
    and a REPLICATED run restores the FSDP checkpoint — the one-canonical-
    layout contract across parallelism modes."""
    from pytorch_distributed_tpu.data.synthetic import SyntheticImageClassification
    from pytorch_distributed_tpu.train import Trainer, TrainerConfig

    mesh = make_mesh(devices8)
    save = os.fspath(tmp_path / "fsdp_out")
    cfg = TrainerConfig(epochs=1, batch_size=2, lr=0.05, save_dir=save,
                        num_workers=0, fsdp=True)
    train_ds = SyntheticImageClassification(size=64, image_size=16, num_classes=10)
    val_ds = SyntheticImageClassification(size=16, image_size=16, num_classes=10,
                                          seed=1)
    tr = Trainer(tiny_model(), train_ds, val_ds, cfg, mesh=mesh,
                 input_shape=(1, 16, 16, 3))
    res = tr.fit()
    assert os.path.exists(os.path.join(save, "best.ckpt"))

    # restore the FSDP-written best checkpoint into a replicated trainer
    cfg2 = TrainerConfig(epochs=1, batch_size=2, save_dir=save, num_workers=0,
                         fsdp=False)
    tr2 = Trainer(tiny_model(), train_ds, val_ds, cfg2, mesh=mesh,
                  input_shape=(1, 16, 16, 3))
    restored = tr2.ckpt.load_best(tr2._payload(0, 0))
    flat_f = {str(p): v for p, v in
              jax.tree_util.tree_leaves_with_path(tr.state.params)}
    for path, leaf in jax.tree_util.tree_leaves_with_path(
        restored["state"].params
    ):
        np.testing.assert_allclose(
            np.asarray(leaf), np.asarray(flat_f[str(path)]), rtol=1e-6,
            err_msg=str(path),
        )


def test_fsdp_fp16_scaler_parity_with_replicated(devices8):
    """The GradScaler finite gate must be GLOBAL under FSDP (a local inf in
    one device's shard must skip the step on every device): fp16 FSDP
    training tracks fp16 replicated training exactly, scaler state
    included."""
    from pytorch_distributed_tpu.ops.precision import DynamicLossScaler

    mesh = make_mesh(devices8)
    tx = sgd_with_weight_decay(0.1, momentum=0.9)

    def run(fsdp, steps=3):
        state = TrainState.create(
            tiny_model(), tx, jax.random.key(0), (1, 16, 16, 3),
            scaler=DynamicLossScaler.create(init_scale=2.0**8),
        )
        if fsdp:
            state, specs = shard_fsdp_state(mesh, state)
        else:
            state = jax.device_put(state, replicated_sharding(mesh))
            specs = None
        step = make_train_step(mesh, state_specs=specs)
        out = []
        for i in range(steps):
            state, metrics = step(state, batch_for(mesh, seed=i))
            out.append((float(metrics["loss"]), float(metrics["grads_finite"])))
        return state, out

    state_f, hist_f = run(True)
    state_r, hist_r = run(False)
    np.testing.assert_allclose(hist_f, hist_r, rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(jax.device_get(state_f.scaler.scale)),
        np.asarray(jax.device_get(state_r.scaler.scale)),
    )
