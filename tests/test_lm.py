"""LM training over (data, seq) meshes: ring-parallel step == single-device
dense step, and learning works on a toy task."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow

from pytorch_distributed_tpu.models.transformer import TransformerLM, tiny_config
from pytorch_distributed_tpu.ops.optim import sgd_with_weight_decay
from pytorch_distributed_tpu.parallel import make_mesh, replicated_sharding
from pytorch_distributed_tpu.train.lm import (
    create_lm_state,
    make_lm_train_step,
    shift_labels,
)
from jax.sharding import NamedSharding, PartitionSpec as P


def batch_for(mesh, b=4, l=32, seed=0):
    rng = np.random.default_rng(seed)
    tokens = rng.integers(1, 128, (b, l)).astype(np.int32)
    labels, weights = shift_labels(tokens)
    sharding = NamedSharding(mesh, P("data", "seq"))
    put = lambda x: jax.device_put(x, sharding)
    return {"tokens": put(tokens), "labels": put(labels), "weights": put(weights)}


def run_steps(mesh, attention, steps=3, lr=0.1):
    cfg = tiny_config(attention=attention)
    tx = sgd_with_weight_decay(lr, momentum=0.9, weight_decay=0.0)
    state = create_lm_state(cfg, tx, jax.random.key(0), init_len=8)
    state = jax.device_put(state, replicated_sharding(mesh))
    step_fn = make_lm_train_step(mesh)
    losses = []
    for i in range(steps):
        state, metrics = step_fn(state, batch_for(mesh, seed=i))
        losses.append(float(metrics["loss"]))
    return state, losses


@pytest.mark.parametrize("dp,sp", [(2, 4), (1, 8)])
def test_ring_lm_matches_single_device_dense(devices8, dp, sp):
    mesh_sp = make_mesh(devices8, data_parallel=dp, seq_parallel=sp)
    mesh_one = make_mesh(devices8[:1])
    state_sp, losses_sp = run_steps(mesh_sp, "ring")
    state_one, losses_one = run_steps(mesh_one, "dense")
    np.testing.assert_allclose(losses_sp, losses_one, rtol=2e-4)
    for a, b in zip(
        jax.tree.leaves(state_sp.params), jax.tree.leaves(state_one.params)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-5
        )


def test_lm_loss_decreases(devices8):
    mesh = make_mesh(devices8, data_parallel=2, seq_parallel=4)
    cfg = tiny_config(attention="ring")
    tx = sgd_with_weight_decay(0.3, momentum=0.9, weight_decay=0.0)
    state = create_lm_state(cfg, tx, jax.random.key(0), init_len=8)
    state = jax.device_put(state, replicated_sharding(mesh))
    step_fn = make_lm_train_step(mesh)
    batch = batch_for(mesh, seed=42)  # fixed batch: memorization test
    first = last = None
    for i in range(12):
        state, metrics = step_fn(state, batch)
        loss = float(metrics["loss"])
        first = first if first is not None else loss
        last = loss
    assert last < first * 0.7, (first, last)


def test_blockwise_lm_forward_matches_dense():
    cfg_d = tiny_config(attention="dense")
    cfg_b = tiny_config(attention="blockwise", block_size=8)
    model_d, model_b = TransformerLM(cfg_d), TransformerLM(cfg_b)
    tokens = jnp.asarray(np.random.default_rng(0).integers(1, 128, (2, 32)), jnp.int32)
    variables = model_d.init(jax.random.key(0), tokens)
    out_d = model_d.apply(variables, tokens)
    out_b = model_b.apply(variables, tokens)
    np.testing.assert_allclose(np.asarray(out_b), np.asarray(out_d), rtol=2e-4, atol=2e-5)


def test_seq_sharded_mesh_rejects_non_ring_attention(devices8):
    """ADVICE r1 (medium): dense/blockwise/flash under a seq-sharded
    shard_map silently computes shard-local attention; the step builders
    must refuse instead."""
    from pytorch_distributed_tpu.train.lm import shard_lm_state

    mesh = make_mesh(devices8, data_parallel=4, seq_parallel=2)
    cfg = tiny_config(attention="dense")
    tx = sgd_with_weight_decay(0.1)
    state = create_lm_state(cfg, tx, jax.random.key(0), init_len=8)
    with pytest.raises(ValueError, match="ring"):
        shard_lm_state(mesh, state, cfg)
    with pytest.raises(ValueError, match="ring"):
        make_lm_train_step(mesh, config=cfg)
    # ring on the same mesh is accepted
    make_lm_train_step(mesh, config=tiny_config(attention="ring"))


def test_opt_state_specs_suffix_match_is_component_anchored():
    """ADVICE r1 (low): 'proj/kernel' must never claim 'out_proj/kernel'."""
    import optax

    from pytorch_distributed_tpu.parallel.tensor import opt_state_specs

    params = {
        "proj": {"kernel": jnp.zeros((4, 4))},
        "out_proj": {"kernel": jnp.zeros((4, 4))},
    }
    param_specs = {
        "proj": {"kernel": P("model", None)},
        "out_proj": {"kernel": P(None, "model")},
    }
    tx = sgd_with_weight_decay(0.1, momentum=0.9)
    specs = opt_state_specs(params, param_specs, tx)
    momenta = [
        (path, leaf)
        for path, leaf in jax.tree_util.tree_leaves_with_path(
            specs, is_leaf=lambda x: isinstance(x, P)
        )
    ]
    by_path = {
        "".join(str(k) for k in path): leaf for path, leaf in momenta
    }
    proj = [s for p, s in by_path.items() if "proj" in p and "out_proj" not in p]
    out_proj = [s for p, s in by_path.items() if "out_proj" in p]
    assert proj and all(s == P("model", None) for s in proj), by_path
    assert out_proj and all(s == P(None, "model") for s in out_proj), by_path
