"""Correctness of the Pallas one-pass reduction kernels
(ops/bottleneck_tail.py). These are a *documented negative perf result*
(PERF_NOTES.md §6: the custom-call boundary costs XLA more in layout
copies/fusions than the one-pass reads save), kept correct so the
measurement is reproducible and the kernels are available if the
boundary economics change (e.g. a whole-block Pallas path)."""

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_tpu.ops import bottleneck_tail as bt


def _data(dtype=jnp.float32, b=3, h=6, w=6, f=8, e=16, seed=0):
    r = np.random.default_rng(seed)
    z = jnp.asarray(r.standard_normal((b, h, w, f)), dtype)
    g = jnp.asarray(r.standard_normal((b, h, w, e)), dtype)
    out = jnp.asarray(r.standard_normal((b, h, w, e)), dtype)
    return z, g, out


def test_moments_matches_xla():
    z, _, _ = _data()
    s, m2 = bt.moments(z)
    np.testing.assert_allclose(
        np.asarray(s), np.asarray(jnp.sum(z, axis=(0, 1, 2))), rtol=1e-5
    )
    ref = jax.lax.dot_general(z, z, (((0, 1, 2), (0, 1, 2)), ((), ())))
    np.testing.assert_allclose(np.asarray(m2), np.asarray(ref), rtol=1e-5)


def test_bwd_reduce_matches_xla():
    z, g, out = _data(seed=1)
    gp, p, sb = bt.tail_bwd_reduce(z, g, out)
    gp_ref = jnp.where(out > 0, g, 0)
    np.testing.assert_array_equal(np.asarray(gp), np.asarray(gp_ref))
    p_ref = jax.lax.dot_general(
        z, gp_ref, (((0, 1, 2), (0, 1, 2)), ((), ()))
    )
    np.testing.assert_allclose(np.asarray(p), np.asarray(p_ref), rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(sb), np.asarray(jnp.sum(gp_ref, axis=(0, 1, 2))),
        rtol=1e-5, atol=1e-5,
    )


def test_bwd_dz_matches_xla():
    z, g, out = _data(seed=2)
    f, e = z.shape[-1], g.shape[-1]
    r = np.random.default_rng(3)
    gp = jnp.where(out > 0, g, 0)
    wa = jnp.asarray(r.standard_normal((e, f)), jnp.float32)
    c = jnp.asarray(r.standard_normal((f, f)), jnp.float32)
    dmn = jnp.asarray(r.standard_normal((1, f)), jnp.float32)
    dz = bt.tail_bwd_dz(gp, z, wa, c, dmn)
    ref = (
        gp.reshape(-1, e) @ wa + z.reshape(-1, f) @ c + dmn
    ).reshape(z.shape)
    np.testing.assert_allclose(np.asarray(dz), np.asarray(ref), rtol=1e-4,
                               atol=1e-4)
