"""Seeded host-concurrency violations with EXPECT markers.
Never imported, only parsed."""

import signal
import threading
import time


class Worker:
    """Thread-target method mutating shared attrs with no lock held."""

    def __init__(self):
        self._lock = threading.Lock()
        self.results = []
        self.count = 0
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        self.results.append(1)  # EXPECT: thread-unsynced-mutation
        self.count += 1  # EXPECT: thread-unsynced-mutation
        self._locked_push()
        self._acquire_push()

    def _locked_push(self):
        # reachable from the thread, but correctly guarded: no finding
        with self._lock:
            self.results.append(2)

    def _acquire_push(self):
        # bare acquire()/release() around try/finally is credited too
        self._lock.acquire()
        try:
            self.results.append(3)  # CLEAN: thread-unsynced-mutation
        finally:
            self._lock.release()

    def summary(self):
        return len(self.results), self.count


def _blocking_handler(signum, frame):
    with open("/tmp/dump.json", "w") as f:  # EXPECT: thread-blocking-signal
        f.write("{}")
    time.sleep(0.5)  # EXPECT: thread-blocking-signal


signal.signal(signal.SIGTERM, _blocking_handler)
