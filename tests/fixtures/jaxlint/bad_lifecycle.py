"""Seeded block-lifecycle violations with EXPECT markers. Never
imported, only parsed: the allocator/tables attributes are props for
the AST pass, not live objects."""


class LeakyEngine:
    def admit_leak(self, slot, need):
        chain = self.allocator.alloc(slot, need)
        if need > self.width:
            raise ValueError("too wide")  # EXPECT: lifecycle-alloc-leak
        self.tables[slot] = chain

    def admit_early_return(self, slot, need):
        chain = self.allocator.alloc(slot, need)
        if self.busy:
            return False  # EXPECT: lifecycle-alloc-leak
        self.tables[slot] = chain
        return True

    def admit_oom_guard_clean(self, slot, need):
        chain = self.allocator.alloc(slot, need)  # CLEAN: lifecycle-alloc-leak
        if chain is None:
            return False  # the OOM idiom: nothing was allocated
        self.tables[slot] = chain
        return True

    def admit_except_release_clean(self, slot, need):
        chain = self.allocator.alloc_mixed(slot, [], need)
        try:
            self.transfer(chain)
        except Exception:
            self.allocator.free(slot)
            raise  # CLEAN: lifecycle-alloc-leak (freed just above)
        self.tables[slot] = chain
        return True

    def alloc_handoff_clean(self, slot, need):
        chain = self.allocator.alloc(slot, need)
        return chain  # CLEAN: lifecycle-alloc-leak (caller owns it)


class RefTamper:
    def poke_books(self, allocator, b):
        allocator._refs[b] = 2  # EXPECT: lifecycle-refcount-outside-allocator
        allocator._free.append(b)  # EXPECT: lifecycle-refcount-outside-allocator
        allocator.incref(b)  # EXPECT: lifecycle-refcount-outside-allocator
        allocator.decref(b)  # EXPECT: lifecycle-refcount-outside-allocator

    def census_clean(self, allocator):
        # reads are fine: only mutations bypass the allocator's checks
        return len(allocator._refs)  # CLEAN: lifecycle-refcount-outside-allocator


class SwapWindow:
    def open_never_closed(self, slot):
        self.allocator.set_state(slot, "swapping-out")  # EXPECT: lifecycle-span-imbalance
        return self.gather(slot)

    def open_escaping_raise(self, slot):
        self.allocator.set_state(slot, "swapping-out")
        blocks = self.gather(slot)
        if blocks is None:
            raise OSError("gather failed")  # EXPECT: lifecycle-span-imbalance
        self.allocator.clear_state(slot)
        return blocks

    def open_close_balanced_clean(self, slot):
        self.allocator.set_state(slot, "swapping-out")  # CLEAN: lifecycle-span-imbalance
        try:
            blocks = self.gather(slot)
        finally:
            self.allocator.clear_state(slot)
        return blocks


class ChaoslessServer:
    # this fixture tree carries no tests/test_chaos_matrix.py, so any
    # serve-side site here is by definition unexercised by the grid
    def dispatch_tick(self):
        fault_point("serve.reorder_buffer")  # EXPECT: lifecycle-fault-site-untested
        return self.work()

    def swap_in(self, slot):
        # non-serve sites are the kill matrix's jurisdiction, not the
        # chaos matrix's: only serve.* requires a chaos entry
        fault_point("kv.swap_in_h2d")  # CLEAN: lifecycle-fault-site-untested
        return self.h2d(slot)
