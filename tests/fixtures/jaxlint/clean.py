"""Clean fixture: every pattern the rules police, done right.

The fixture test asserts jaxlint reports ZERO findings here — guarding
against false positives as the rules evolve. Never imported, only parsed.
"""

from functools import partial

import jax
import jax.numpy as jnp

DATA_AXIS = "data"
SEQ_AXIS = "seq"


def psum_tree(tree, axis=DATA_AXIS):
    # axis via shared constant, resolvable through the parameter default
    return jax.lax.psum(tree, axis_name=axis)  # CLEAN: collective-axis, collective-axis-literal


def combined(tree):
    # tuple of constants is fine
    return jax.lax.pmean(tree, (DATA_AXIS, SEQ_AXIS))


def consistent(grads, metrics):
    # same operand, same axis at both sites
    grads = jax.lax.pmean(grads, DATA_AXIS)
    grads = jax.lax.pmean(grads, DATA_AXIS)  # CLEAN: collective-axis-inconsistent
    metrics = jax.lax.psum(metrics, (DATA_AXIS, SEQ_AXIS))
    return grads, metrics


def make_step(label_smoothing=0.0):
    # the builder idiom: closures may drive Python control flow freely
    def _local_step(state, batch):
        if label_smoothing:  # closure, not a traced argument  # CLEAN: recompile-traced-branch
            pass
        loss = jnp.mean(batch)
        return jax.lax.pmean(loss, DATA_AXIS), state

    return jax.jit(_local_step, donate_argnums=(0,))


@partial(jax.jit, static_argnums=(1,))  # CLEAN: recompile-static-argnums
def scaled(x, factor=2):
    # static argument legitimately branches: it is a Python value
    if factor > 1:
        return x * factor
    return x


_COMPILED = jax.jit(lambda x: x + 1)


def hot_loop(xs):
    # jit built once at module scope, reused per call: no rebuild cost
    return [_COMPILED(x) for x in xs]  # CLEAN: recompile-jit-call
