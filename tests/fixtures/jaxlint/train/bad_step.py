"""Seeded host-transfer violations in a step-builder module.

Lives under a ``train/`` directory so the rule treats ``_local_step`` /
``make_*`` inner defs as hot roots; the cross-module leak goes through
``hot_helpers`` to prove call-graph reachability, not just direct scans.
Parsed by tests, never imported.
"""

import jax
import numpy as np

from hot_helpers import leaky_norm

DATA_AXIS = "data"


def make_train_step(mesh):
    def _local_step(state, batch):
        loss = batch["x"].sum()
        host_loss = float(loss)  # EXPECT: host-transfer
        arr = np.asarray(loss)  # EXPECT: host-transfer
        scalar = loss.item()  # EXPECT: host-transfer
        pulled = jax.device_get(loss)  # EXPECT: host-transfer
        norm = leaky_norm(state)
        del host_loss, arr, scalar, pulled
        return jax.lax.psum(loss, DATA_AXIS), norm

    return jax.jit(_local_step)


def host_side_summary(metrics):
    # NOT reachable from a hot root: float() here is fine
    return {k: float(v) for k, v in metrics.items()}
