"""Clean train/ fixture: a compiled step body with no host syncs — the
host-transfer call-graph walk must stay silent. Never imported, only
parsed."""

import jax
import jax.numpy as jnp

DATA_AXIS = "data"


def _pure_helper(batch):
    # device-side math only: reachable from the step, nothing to flag
    return jnp.mean(batch)  # CLEAN: host-transfer


def make_train_step():
    def _local_step(state, batch):
        loss = _pure_helper(batch)
        return state, jax.lax.pmean(loss, DATA_AXIS)

    return jax.jit(_local_step, donate_argnums=(0,))
