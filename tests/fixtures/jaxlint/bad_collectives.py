"""Seeded collective-axis violations. Parsed by tests, never imported.

Lines carrying a violation end with ``# EXPECT: <rule>``; the fixture
test asserts each rule fires exactly there and nowhere else.
"""

import jax

DATA_AXIS = "data"
SEQ_AXIS = "seq"


def wrong_axis(grads):
    return jax.lax.psum(grads, "dta")  # EXPECT: collective-axis


def wrong_axis_via_constant(grads):
    return jax.lax.pmean(grads, BOGUS_NAME)  # EXPECT: collective-axis


BOGUS_NAME = "batch_dim"


def wrong_axis_in_tuple(grads):
    return jax.lax.psum(grads, (DATA_AXIS, "modle"))  # EXPECT: collective-axis


def literal_spelling(grads):
    # 'data' has a shared constant; spelling it inline drifts call sites
    return jax.lax.psum(grads, "data")  # EXPECT: collective-axis-literal


def inconsistent(grads):
    grads = jax.lax.pmean(grads, DATA_AXIS)
    return jax.lax.pmean(grads, SEQ_AXIS)  # EXPECT: collective-axis-inconsistent


def wrong_axis_index():
    return jax.lax.axis_index("sequence")  # EXPECT: collective-axis
