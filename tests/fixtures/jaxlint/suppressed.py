"""Every violation here carries a suppression comment; the fixture test
asserts jaxlint reports ZERO findings — proving the suppression syntax
works for each rule (including every v2 family). Parsed by tests, never
imported."""

import signal
import threading
import time

import jax
from jax.sharding import PartitionSpec as P

from jax.experimental.shard_map import shard_map

DATA_AXIS = "data"


def reviewed_axis(grads):
    # e.g. linting a tree that talks to an external mesh
    return jax.lax.psum(grads, "replica")  # jaxlint: disable=collective-axis -- external mesh declares this axis


def reviewed_literal(grads):
    return jax.lax.psum(grads, "data")  # jaxlint: disable=collective-axis-literal -- doc example keeps the literal


@jax.jit
def reviewed_branch(x, n):
    if n > 0:  # jaxlint: disable=recompile-traced-branch -- n is static at every call site; one compile per n is intended
        return x * n
    return x


# ---- v2 families -----------------------------------------------------------


def reviewed_use_after_donate(state, batch):
    step = jax.jit(lambda s, b: s + b, donate_argnums=(0,))
    out = step(state, batch)
    return out, state.sum()  # jaxlint: disable=donation-use-after-donate -- CPU-only diagnostic helper; the backend copies donated buffers


def reviewed_alias(buf, row):
    combine = jax.jit(lambda a, b, r: a + b + r, donate_argnums=(0,))
    return combine(buf, buf, row)  # jaxlint: disable=donation-alias -- doc example demonstrating the hazard


def reviewed_undonated_loop(state, batches):
    step = jax.jit(lambda s, b: s + b)
    for b in batches:
        state = step(state, b)  # jaxlint: disable=donation-none-hot-loop -- toy carry in a test helper; donation churn is noise at this size
    return state


def reviewed_external_axis_spec():
    return P("replica")  # jaxlint: disable=sharding-unknown-axis -- external launcher mesh declares this axis


def make_reviewed_arity(mesh):
    def _local(xs, batch):
        return xs, batch

    return shard_map(  # jaxlint: disable=sharding-spec-arity -- doc example; the extra spec is the point being illustrated
        _local,
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS), P()),
        out_specs=(P(), P(DATA_AXIS)),
    )


def make_reviewed_replicated(mesh):
    def _fwd(params, batch):
        return batch

    return shard_map(
        _fwd,
        mesh=mesh,
        in_specs=(
            P(),  # jaxlint: disable=sharding-replicated -- tiny eval head; replication is cheaper than the gather
            P(DATA_AXIS),
        ),
        out_specs=P(DATA_AXIS),
    )


class ReviewedLatch:
    def __init__(self):
        self.flag = False
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        self.flag = True  # jaxlint: disable=thread-unsynced-mutation -- monotonic bool latch: single GIL-atomic store, readers only poll

    def poll(self):
        return self.flag


def _reviewed_handler(signum, frame):
    time.sleep(0.01)  # jaxlint: disable=thread-blocking-signal -- test-only handler on a dedicated diagnostic signal


signal.signal(signal.SIGUSR2, _reviewed_handler)
