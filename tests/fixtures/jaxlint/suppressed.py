"""Every violation here carries a suppression comment; the fixture test
asserts jaxlint reports ZERO findings — proving the suppression syntax
works for each rule. Parsed by tests, never imported."""

import jax

DATA_AXIS = "data"


def reviewed_axis(grads):
    # e.g. linting a tree that talks to an external mesh
    return jax.lax.psum(grads, "replica")  # jaxlint: disable=collective-axis -- external mesh declares this axis


def reviewed_literal(grads):
    return jax.lax.psum(grads, "data")  # jaxlint: disable=collective-axis-literal -- doc example keeps the literal


@jax.jit
def reviewed_branch(x, n):
    if n > 0:  # jaxlint: disable=recompile-traced-branch -- n is static at every call site; one compile per n is intended
        return x * n
    return x
