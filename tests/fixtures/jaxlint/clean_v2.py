"""Clean fixture for the v2 rule families: every donation/sharding/
threading pattern the rules police, done right. The fixture test asserts
jaxlint reports ZERO findings here — guarding against false positives —
and the meta-test requires every rule id to appear on a CLEAN marker
somewhere, proving a correct-usage example exists for each rule.
Never imported, only parsed."""

import signal
import threading

import jax
from jax.sharding import PartitionSpec as P

from jax.experimental.shard_map import shard_map

DATA_AXIS = "data"
MODEL_AXIS = "model"

_TABLE = [1, 2, 3]  # module-level container, never mutated: safe to close over


@jax.jit
def lookup(x):
    return x + _TABLE[0]  # CLEAN: recompile-mutable-closure


# ---- donation: the rebind-from-result idiom --------------------------------


def good_rebind(state, batch):
    step = jax.jit(lambda s, b: s + b, donate_argnums=(0,))
    state = step(state, batch)  # CLEAN: donation-use-after-donate
    return state.sum()


def good_loop_carry(state, batches):
    step = jax.jit(lambda s, b: s + b, donate_argnums=(0,))
    for b in batches:
        state = step(state, b)  # CLEAN: donation-none-hot-loop
    return state


def good_distinct_buffers(buf_a, buf_b, row):
    combine = jax.jit(lambda a, b, r: a + b + r, donate_argnums=(0,))
    return combine(buf_a, buf_b, row)  # CLEAN: donation-alias


class GoodEngine:
    def __init__(self, cache, logits):
        self.cache = cache
        self.logits = logits
        self._tick = jax.jit(lambda c, lg: (c * 2, lg), donate_argnums=(0, 1))

    def tick(self):
        # donated attrs rebound from the result in the same statement
        self.cache, self.logits = self._tick(self.cache, self.logits)
        return self.logits


# ---- sharding: specs that match the mesh and the signature -----------------


def make_good_specs(mesh):
    def _fwd(params, batch):
        return params, batch

    sharded = shard_map(  # CLEAN: sharding-spec-arity
        _fwd,
        mesh=mesh,
        in_specs=(P(MODEL_AXIS), P(DATA_AXIS)),  # CLEAN: sharding-unknown-axis, sharding-replicated
        out_specs=(P(MODEL_AXIS), P(DATA_AXIS)),
    )
    return sharded


def make_replicated_tokens(mesh):
    # P() on small host-built operands (token ids) is the design, not a bug
    def _fwd(params, tokens):
        return tokens

    return shard_map(
        _fwd,
        mesh=mesh,
        in_specs=(P(MODEL_AXIS), P()),
        out_specs=P(),
    )


# ---- threads: lock discipline and latch-only signal handlers ---------------


class GoodWorker:
    def __init__(self):
        self._lock = threading.Lock()
        self.results = []
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        with self._lock:
            self.results.append(1)  # CLEAN: thread-unsynced-mutation

    def summary(self):
        with self._lock:
            return list(self.results)


_SUSPEND = threading.Event()


def _latch_handler(signum, frame):
    _SUSPEND.set()  # CLEAN: thread-blocking-signal


signal.signal(signal.SIGTERM, _latch_handler)
