"""Seeded precision-cast violations (module lives under an ops/ dir).

Parsed by tests, never imported.
"""

import jax.numpy as jnp


def sloppy_upcast(x):
    return x.astype(jnp.float32)  # EXPECT: precision-cast


def sloppy_downcast(x):
    return x.astype(jnp.bfloat16)  # EXPECT: precision-cast


def sloppy_string_cast(x):
    return x.astype("float32")  # EXPECT: precision-cast


def sloppy_asarray(x):
    return jnp.asarray(x, jnp.bfloat16)  # EXPECT: precision-cast


def policy_driven(x, policy):
    # the blessed pattern: dtype flows from the policy object
    return x.astype(policy.compute_dtype)


def peer_driven(x, ref):
    return x.astype(ref.dtype)
