"""Clean ops/ fixture: dtype decisions routed through the policy — the
precision-cast rule must stay silent. Never imported, only parsed."""


def policy_cast(x, policy):
    # the policy owns the dtype: no literal cast, nothing to flag
    return x.astype(policy.compute_dtype)  # CLEAN: precision-cast


def peer_cast(q, k):
    return k.astype(q.dtype)  # CLEAN: precision-cast
