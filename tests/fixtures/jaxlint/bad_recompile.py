"""Seeded recompile-hazard violations. Parsed by tests, never imported."""

from functools import partial

import jax
import jax.numpy as jnp

_SCALE_TABLE = {"warm": 1.0}


@jax.jit
def branch_on_traced(x, threshold):
    if threshold > 0:  # EXPECT: recompile-traced-branch
        x = x * 2
    while x:  # EXPECT: recompile-traced-branch
        x = x - 1
    return x


@jax.jit
def reads_mutated_global(x):
    return x * _SCALE_TABLE["warm"]  # EXPECT: recompile-mutable-closure


def set_scale(v):
    _SCALE_TABLE["warm"] = v


def per_call_compile(xs):
    out = []
    for x in xs:
        out.append(jax.jit(lambda v: v + 1)(x))  # EXPECT: recompile-jit-call
    return out


def bad_static(fn_input):
    def inner(a, b, opts=[1, 2]):
        return a + b + len(opts)

    return jax.jit(inner, static_argnums=(5,))  # EXPECT: recompile-static-argnums


def static_donate_overlap():
    def inner(state, batch):
        return state

    return jax.jit(  # EXPECT: recompile-static-argnums
        inner, static_argnums=(0,), donate_argnums=(0,)
    )


def static_unhashable_default():
    def inner(x, opts=[1, 2]):
        return x * len(opts)

    return jax.jit(inner, static_argnums=(1,))  # EXPECT: recompile-static-argnums
