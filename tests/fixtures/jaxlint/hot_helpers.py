"""Helper reachable from the bad_step fixture's compiled step body."""

import jax.numpy as jnp
import numpy as np


def leaky_norm(tree):
    # host sync buried one call away from the step body
    total = jnp.zeros(())
    for leaf in tree.values():
        total = total + jnp.sum(leaf * leaf)
    return np.asarray(total)  # EXPECT: host-transfer


def honest_norm(tree):
    total = jnp.zeros(())
    for leaf in tree.values():
        total = total + jnp.sum(leaf * leaf)
    return jnp.sqrt(total)
