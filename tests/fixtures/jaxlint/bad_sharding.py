"""Seeded shard_map/PartitionSpec violations with EXPECT markers.
Never imported, only parsed."""

from jax.sharding import PartitionSpec as P

from jax.experimental.shard_map import shard_map

DATA_AXIS = "data"
MODEL_AXIS = "model"


def axis_typo():
    return P("modle")  # EXPECT: sharding-unknown-axis


def axis_typo_nested():
    return P(("data", "sq"), None)  # EXPECT: sharding-unknown-axis


def make_bad_in_arity(mesh):
    def _local(xs, batch):
        return xs, batch

    sharded = shard_map(  # EXPECT: sharding-spec-arity
        _local,
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS), P()),
        out_specs=(P(), P(DATA_AXIS)),
    )
    return sharded


def make_bad_out_arity(mesh):
    def _local(xs, batch):
        return xs, batch

    sharded = shard_map(  # EXPECT: sharding-spec-arity
        _local,
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS)),
        out_specs=(P(), P(DATA_AXIS), P()),
    )
    return sharded


def make_replicated_params(mesh):
    def _fwd(params, batch):
        return batch

    sharded = shard_map(
        _fwd,
        mesh=mesh,
        in_specs=(
            P(),  # EXPECT: sharding-replicated
            P(DATA_AXIS),
        ),
        out_specs=P(DATA_AXIS),
    )
    return sharded
