"""Seeded donation violations with EXPECT markers — the dataflow pass's
ground truth. Never imported, only parsed."""

import jax


def make_push():
    def _push(buf, idx, row):
        return buf.at[idx].set(row), idx + 1

    return jax.jit(_push, donate_argnums=(0, 1))


def use_after_donate(buf, idx, row):
    push = make_push()
    out, nidx = push(buf, idx, row)
    total = buf.sum()  # EXPECT: donation-use-after-donate
    return out, nidx, total


def use_after_donate_branchless(buf, idx, row):
    push = make_push()
    if idx is None:
        out, nidx = push(buf, idx, row)
        return out, nidx
    # different branch: reading buf here is fine (no donate on this path)
    return buf.sum(), idx


def double_donation(buf, row):
    combine = jax.jit(lambda a, b, r: a + b + r, donate_argnums=(0,))
    return combine(buf, buf, row)  # EXPECT: donation-alias


def loop_never_rebinds(state, batches):
    step = jax.jit(lambda s, b: s + b, donate_argnums=(0,))
    out = None
    for b in batches:
        out = step(state, b)  # EXPECT: donation-use-after-donate
    return out


def hot_loop_no_donation(state, batches):
    step = jax.jit(lambda s, b: s + b)
    for b in batches:
        state = step(state, b)  # EXPECT: donation-none-hot-loop
    return state


class Engine:
    """The builder/attr idioms the serving engine uses, done wrong."""

    def __init__(self, cache, logits):
        self.cache = cache
        self.logits = logits
        self._tick = jax.jit(lambda c: c * 2, donate_argnums=(0,))

    def _decode_fn(self):
        fn = jax.jit(lambda c, lg: (c, lg), donate_argnums=(0, 1))
        return fn

    def tick_then_read(self):
        new = self._tick(self.cache)
        stale = self.cache.sum()  # EXPECT: donation-use-after-donate
        self.cache = new
        return stale

    def chained_builder_wrong(self):
        out_c, out_l = self._decode_fn()(self.cache, self.logits)
        self.cache = out_c
        return self.logits  # EXPECT: donation-use-after-donate

    def tick_right(self):
        # the correct idiom: rebind from the result in the same statement
        self.cache = self._tick(self.cache)
        return self.cache
