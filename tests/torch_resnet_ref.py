"""Minimal torch ResNet with torchvision-compatible state_dict naming.

torchvision is not installed in this environment (zero egress), but the
parity tests need a live torch model whose ``state_dict`` uses the exact
naming contract ``models.torch_import`` translates (conv1 / bn1 /
layerL.B.convN / downsample.0/1 / fc). This is the ResNet v1.5
architecture written from the paper + the reference's usage
(``/root/reference/restnet_ddp.py:98`` uses ``torchvision.models.resnet50``):
7x7/2 stem, 3x3/2 maxpool, four stages, stride on the 3x3 conv of the
bottleneck (the v1.5 torchvision ships), adaptive average pool, linear
head. Kaiming fan-out init like torchvision. Not a copy of torchvision
source — only the public module-naming contract is reproduced, because
that contract is what the importer under test must understand.
"""

from __future__ import annotations

import torch
from torch import nn


class BasicBlock(nn.Module):
    expansion = 1

    def __init__(self, cin, filters, stride=1, downsample=None):
        super().__init__()
        self.conv1 = nn.Conv2d(cin, filters, 3, stride, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(filters)
        self.relu = nn.ReLU(inplace=True)
        self.conv2 = nn.Conv2d(filters, filters, 3, 1, 1, bias=False)
        self.bn2 = nn.BatchNorm2d(filters)
        self.downsample = downsample

    def forward(self, x):
        identity = x
        y = self.relu(self.bn1(self.conv1(x)))
        y = self.bn2(self.conv2(y))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(y + identity)


class Bottleneck(nn.Module):
    expansion = 4

    def __init__(self, cin, filters, stride=1, downsample=None):
        super().__init__()
        self.conv1 = nn.Conv2d(cin, filters, 1, 1, 0, bias=False)
        self.bn1 = nn.BatchNorm2d(filters)
        self.conv2 = nn.Conv2d(filters, filters, 3, stride, 1, bias=False)
        self.bn2 = nn.BatchNorm2d(filters)
        self.conv3 = nn.Conv2d(filters, filters * 4, 1, 1, 0, bias=False)
        self.bn3 = nn.BatchNorm2d(filters * 4)
        self.relu = nn.ReLU(inplace=True)
        self.downsample = downsample

    def forward(self, x):
        identity = x
        y = self.relu(self.bn1(self.conv1(x)))
        y = self.relu(self.bn2(self.conv2(y)))
        y = self.bn3(self.conv3(y))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(y + identity)


class ResNet(nn.Module):
    def __init__(self, block, stage_sizes, num_classes=1000):
        super().__init__()
        self.conv1 = nn.Conv2d(3, 64, 7, 2, 3, bias=False)
        self.bn1 = nn.BatchNorm2d(64)
        self.relu = nn.ReLU(inplace=True)
        self.maxpool = nn.MaxPool2d(3, 2, 1)
        cin = 64
        for i, n in enumerate(stage_sizes):
            filters, stride = 64 * 2**i, (1 if i == 0 else 2)
            blocks = []
            for j in range(n):
                s = stride if j == 0 else 1
                down = None
                if s != 1 or cin != filters * block.expansion:
                    down = nn.Sequential(
                        nn.Conv2d(cin, filters * block.expansion, 1, s,
                                  bias=False),
                        nn.BatchNorm2d(filters * block.expansion),
                    )
                blocks.append(block(cin, filters, s, down))
                cin = filters * block.expansion
            setattr(self, f"layer{i + 1}", nn.Sequential(*blocks))
        self.avgpool = nn.AdaptiveAvgPool2d(1)
        self.fc = nn.Linear(cin, num_classes)
        for m in self.modules():
            if isinstance(m, nn.Conv2d):
                nn.init.kaiming_normal_(m.weight, mode="fan_out",
                                        nonlinearity="relu")

    def forward(self, x):
        x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        for i in range(1, 5):
            layer = getattr(self, f"layer{i}", None)
            if layer is None:
                break
            x = layer(x)
        x = torch.flatten(self.avgpool(x), 1)
        return self.fc(x)


def resnet18(num_classes=1000):
    return ResNet(BasicBlock, (2, 2, 2, 2), num_classes)


def resnet50(num_classes=1000):
    return ResNet(Bottleneck, (3, 4, 6, 3), num_classes)
