"""Model parity tests vs torchvision (structure-level, CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu import models


def _param_count(params):
    return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))


def _init(model, image_size=32, batch=2):
    x = jnp.zeros((batch, image_size, image_size, 3), jnp.float32)
    variables = model.init(jax.random.key(0), x, train=False)
    return variables, x


def test_resnet50_param_count_matches_torchvision():
    # torchvision.models.resnet50() has 25,557,032 parameters
    # (ref model: resnet_single_gpu.py:83).
    model = models.resnet50()
    variables, _ = _init(model, image_size=32)
    assert _param_count(variables["params"]) == 25_557_032


def test_resnet18_param_count_matches_torchvision():
    model = models.resnet18()
    variables, _ = _init(model, image_size=32)
    assert _param_count(variables["params"]) == 11_689_512


def test_forward_shapes_and_finite():
    model = models.resnet50(num_classes=10)
    variables, x = _init(model, image_size=32, batch=2)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 10)
    assert logits.dtype == jnp.float32
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_train_mode_updates_batch_stats():
    model = models.resnet18(num_classes=4, num_filters=8)
    variables, x = _init(model, image_size=16, batch=4)
    x = jax.random.normal(jax.random.key(1), x.shape)
    logits, mutated = model.apply(variables, x, train=True, mutable=["batch_stats"])
    old = jax.tree.leaves(variables["batch_stats"])
    new = jax.tree.leaves(mutated["batch_stats"])
    changed = any(not np.allclose(a, b) for a, b in zip(old, new))
    assert changed, "train=True must update running BN statistics"


def test_bf16_compute_keeps_fp32_params_and_logits():
    model = models.resnet18(num_classes=4, num_filters=8, dtype=jnp.bfloat16)
    variables, x = _init(model, image_size=16, batch=2)
    for leaf in jax.tree.leaves(variables["params"]):
        assert leaf.dtype == jnp.float32
    logits = model.apply(variables, x, train=False)
    assert logits.dtype == jnp.float32


@pytest.mark.parametrize(
    "builder,expected_blocks",
    [(models.resnet34, (3, 4, 6, 3)), (models.resnet101, (3, 4, 23, 3))],
)
def test_family_stage_sizes(builder, expected_blocks):
    assert tuple(builder().stage_sizes) == expected_blocks


def test_space_to_depth_stem_is_exact():
    """SpaceToDepthStem computes the IDENTICAL function to the 7x7/2 stem
    from the same canonical [7,7,3,F] weights (values and grads) — the
    MLPerf input transform as a checkpoint-compatible model option."""
    import numpy as np

    from pytorch_distributed_tpu.models.resnet import BottleneckBlock, ResNet

    m_std = ResNet(stage_sizes=(1, 1), block_cls=BottleneckBlock, num_classes=10)
    m_s2d = ResNet(stage_sizes=(1, 1), block_cls=BottleneckBlock,
                   num_classes=10, space_to_depth_stem=True)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 64, 64, 3)),
                    jnp.float32)
    v = m_std.init(jax.random.key(0), x, train=False)
    assert jax.tree.structure(v) == jax.tree.structure(
        m_s2d.init(jax.random.key(0), x, train=False)
    )
    y1 = m_std.apply(v, x, train=False)
    y2 = m_s2d.apply(v, x, train=False)  # SAME weights
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2), rtol=1e-5,
                               atol=1e-5)
    g1 = jax.grad(lambda v: jnp.sum(m_std.apply(v, x, train=False) ** 2))(v)
    g2 = jax.grad(lambda v: jnp.sum(m_s2d.apply(v, x, train=False) ** 2))(v)
    from conftest import assert_trees_equal

    assert_trees_equal(g1["params"], g2["params"], rtol=2e-4, atol=2e-5)
