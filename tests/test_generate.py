"""KV-cache decode and generation (models/generate.py): the cached
single-token path must reproduce the full causal forward position by
position, and sampling must behave."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.models.generate import generate, init_cache
from pytorch_distributed_tpu.models.transformer import TransformerLM, tiny_config


def setup(seed=0, b=2, l=12):
    cfg = tiny_config(max_seq_len=32)
    model = TransformerLM(cfg)
    tokens = jnp.asarray(
        np.random.default_rng(seed).integers(1, 128, (b, l)), jnp.int32
    )
    params = model.init(jax.random.key(0), tokens)["params"]
    return cfg, model, params, tokens


def test_decode_matches_full_forward():
    """Feeding tokens one at a time through the cache produces the same
    logits as the full causal forward at every position."""
    cfg, model, params, tokens = setup()
    full = model.apply({"params": params}, tokens, train=False)

    cache = init_cache(cfg, params, tokens.shape[0])
    outs = []
    for t in range(tokens.shape[1]):
        logits, variables = model.apply(
            {"params": params, "cache": cache},
            tokens[:, t : t + 1],
            position_offset=t,
            decode=True,
            mutable=["cache"],
        )
        cache = variables["cache"]
        outs.append(logits[:, 0])
    stepped = jnp.stack(outs, axis=1)
    np.testing.assert_allclose(
        np.asarray(stepped), np.asarray(full), rtol=2e-4, atol=2e-5
    )


def test_greedy_generation_is_deterministic_and_extends_prompt():
    cfg, model, params, tokens = setup(l=6)
    out1 = generate(cfg, params, tokens, jax.random.key(1), max_new_tokens=8)
    out2 = generate(cfg, params, tokens, jax.random.key(2), max_new_tokens=8)
    assert out1.shape == (2, 14)
    np.testing.assert_array_equal(np.asarray(out1[:, :6]), np.asarray(tokens))
    # greedy ignores the rng
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    # and matches argmax over the full forward, token by token
    seq = tokens
    for _ in range(8):
        logits = model.apply({"params": params}, seq, train=False)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(seq))


def test_sampling_uses_rng_and_top_k():
    cfg, model, params, tokens = setup(l=4)
    a = generate(cfg, params, tokens, jax.random.key(1), max_new_tokens=16,
                 temperature=1.0)
    b = generate(cfg, params, tokens, jax.random.key(3), max_new_tokens=16,
                 temperature=1.0)
    assert not np.array_equal(np.asarray(a), np.asarray(b))
    # top_k=1 at any temperature is greedy
    g = generate(cfg, params, tokens, jax.random.key(1), max_new_tokens=8)
    k1 = generate(cfg, params, tokens, jax.random.key(5), max_new_tokens=8,
                  temperature=1.0, top_k=1)
    np.testing.assert_array_equal(np.asarray(g), np.asarray(k1))


def test_generate_length_validation():
    cfg, model, params, tokens = setup(l=12)  # max_seq_len 32
    with pytest.raises(ValueError, match="max_seq_len"):
        generate(cfg, params, tokens, jax.random.key(0), max_new_tokens=32)


def test_empty_prompt_raises():
    cfg, model, params, _ = setup()
    with pytest.raises(ValueError, match="at least one"):
        generate(cfg, params, jnp.zeros((2, 0), jnp.int32), jax.random.key(0))


def test_invalid_sampling_params_raise():
    """top_k out of [1, vocab_size] and negative temperature fail up front
    with clear messages, not opaque trace-time errors."""
    cfg, model, params, tokens = setup()
    key = jax.random.key(0)
    with pytest.raises(ValueError, match="top_k"):
        generate(cfg, params, tokens, key, max_new_tokens=4,
                 temperature=1.0, top_k=cfg.vocab_size + 1)
    with pytest.raises(ValueError, match="top_k"):
        generate(cfg, params, tokens, key, max_new_tokens=4,
                 temperature=1.0, top_k=0)
    with pytest.raises(ValueError, match="temperature"):
        generate(cfg, params, tokens, key, max_new_tokens=4,
                 temperature=-0.5)


def test_parallel_configs_rejected_up_front():
    """Ring attention and TP configs are documented unsupported in
    generate(); they must fail immediately, not with an unbound-axis error
    deep inside apply."""
    import dataclasses

    cfg, model, params, tokens = setup()
    ring = dataclasses.replace(cfg, attention="ring")
    with pytest.raises(ValueError, match="dense-attention only"):
        generate(ring, params, tokens, jax.random.key(0), max_new_tokens=4)
    tp = dataclasses.replace(cfg, model_axis="model", tp_size=2)
    with pytest.raises(ValueError, match="replicated"):
        generate(tp, params, tokens, jax.random.key(0), max_new_tokens=4)


def test_generate_tp_matches_replicated(devices8):
    """TP decoding (params + KV cache sharded over the model axis) emits
    exactly the tokens the replicated path does, greedy and sampled."""
    import dataclasses

    from pytorch_distributed_tpu.models.generate import generate_tp
    from pytorch_distributed_tpu.parallel import make_mesh

    cfg, model, params, tokens = setup()
    tp_cfg = dataclasses.replace(cfg, model_axis="model", tp_size=2)
    mesh = make_mesh(devices8, data_parallel=4, model_parallel=2)

    for kwargs in ({"temperature": 0.0},
                   {"temperature": 0.8, "top_k": 20}):
        ref = generate(cfg, params, tokens, jax.random.key(5),
                       max_new_tokens=8, **kwargs)
        got = generate_tp(mesh, tp_cfg, params, tokens, jax.random.key(5),
                          max_new_tokens=8, **kwargs)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_generate_tp_validations(devices8):
    import dataclasses

    from pytorch_distributed_tpu.models.generate import generate_tp
    from pytorch_distributed_tpu.parallel import make_mesh

    cfg, model, params, tokens = setup()
    mesh = make_mesh(devices8, data_parallel=4, model_parallel=2)
    with pytest.raises(ValueError, match="TP config"):
        generate_tp(mesh, cfg, params, tokens, jax.random.key(0))
    mesh1 = make_mesh(devices8, data_parallel=8, model_parallel=1)
    bad = dataclasses.replace(cfg, model_axis="model", tp_size=2)
    with pytest.raises(ValueError, match="tp_size"):
        generate_tp(mesh1, bad, params, tokens, jax.random.key(0))


def test_generate_tp_with_gqa_and_rope(devices8):
    """TP decoding with the round-4 model features together: GQA (kv
    heads Megatron-sharded, narrow sharded cache) + RoPE (rotation on the
    sharded q/k) emit exactly the replicated path's tokens."""
    import dataclasses

    from pytorch_distributed_tpu.models.generate import generate_tp
    from pytorch_distributed_tpu.models.transformer import (
        TransformerLM,
        tiny_config,
    )
    from pytorch_distributed_tpu.parallel import make_mesh

    cfg = tiny_config(num_heads=4, embed_dim=32, num_kv_heads=2,
                      pos_embedding="rope", max_seq_len=64,
                      attention="dense")
    params = TransformerLM(cfg).init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    tokens = jnp.asarray(
        np.random.default_rng(4).integers(1, 128, (2, 7)), jnp.int32
    )
    tp_cfg = dataclasses.replace(cfg, model_axis="model", tp_size=2)
    mesh = make_mesh(devices8, data_parallel=4, model_parallel=2)
    ref = generate(cfg, params, tokens, jax.random.key(5),
                   max_new_tokens=8, temperature=0.0)
    got = generate_tp(mesh, tp_cfg, params, tokens, jax.random.key(5),
                      max_new_tokens=8, temperature=0.0)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
