"""Tensor parallelism: dp×sp×tp LM training matches the single-device run.

TP is placement + the f/g collective pair; parameters keep global shapes, so
the same init serves every layout and parity can be asserted leaf-by-leaf.
"""

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from pytorch_distributed_tpu.models.transformer import tiny_config
from pytorch_distributed_tpu.ops.optim import sgd_with_weight_decay
from pytorch_distributed_tpu.parallel import make_mesh
from pytorch_distributed_tpu.parallel.tensor import match_partition_rules
from pytorch_distributed_tpu.train.lm import (
    TRANSFORMER_TP_RULES,
    create_lm_state,
    lm_state_specs,
    make_lm_train_step,
    shard_lm_state,
    shift_labels,
)


def run(mesh, attention, model_axis, steps=3, lr=0.1):
    tp = mesh.shape["model"] if model_axis else 1
    # 4 heads so the model axis can split them up to tp=4
    cfg = tiny_config(
        attention=attention, model_axis=model_axis, num_heads=4, tp_size=tp
    )
    tx = sgd_with_weight_decay(lr, momentum=0.9, weight_decay=1e-4)
    state = create_lm_state(cfg, tx, jax.random.key(0), init_len=8)
    state, specs = shard_lm_state(mesh, state)
    step_fn = make_lm_train_step(mesh, state_specs=specs)
    rng = np.random.default_rng(0)
    tokens = rng.integers(1, 128, (4, 32)).astype(np.int32)
    labels, weights = shift_labels(tokens)
    sh = NamedSharding(mesh, P("data", "seq"))
    batch = {
        "tokens": jax.device_put(tokens, sh),
        "labels": jax.device_put(labels, sh),
        "weights": jax.device_put(weights, sh),
    }
    losses = []
    for _ in range(steps):
        state, metrics = step_fn(state, batch)
        losses.append(float(metrics["loss"]))
    return state, losses


@pytest.mark.parametrize(
    "dp,sp,tp,attention",
    [(2, 1, 4, "dense"), (1, 4, 2, "ring"), (2, 2, 2, "ring")],
)
def test_tp_matches_single_device(devices8, dp, sp, tp, attention):
    mesh = make_mesh(devices8, data_parallel=dp, seq_parallel=sp, model_parallel=tp)
    mesh1 = make_mesh(devices8[:1])
    state_tp, losses_tp = run(mesh, attention, "model")
    state_1, losses_1 = run(mesh1, "dense", None)
    np.testing.assert_allclose(losses_tp, losses_1, rtol=5e-4)
    flat_tp = jax.tree_util.tree_leaves_with_path(state_tp.params)
    flat_1 = dict(
        (str(p), v) for p, v in jax.tree_util.tree_leaves_with_path(state_1.params)
    )
    for path, leaf in flat_tp:
        ref = flat_1[str(path)]
        np.testing.assert_allclose(
            np.asarray(leaf), np.asarray(ref), rtol=2e-3, atol=3e-5,
            err_msg=str(path),
        )


def test_partition_rules_shard_expected_leaves(devices8):
    cfg = tiny_config()
    tx = sgd_with_weight_decay(0.1)
    state = create_lm_state(cfg, tx, jax.random.key(0), init_len=8)
    specs = match_partition_rules(TRANSFORMER_TP_RULES, state.params)
    assert specs["block0"]["attn"]["qkv"]["kernel"] == P(None, None, "model", None)
    assert specs["block0"]["mlp_up"]["kernel"] == P(None, "model")
    assert specs["block0"]["ln1"]["scale"] == P()
    assert specs["wte"]["embedding"] == P()

    # optimizer state (momentum trace) follows its parameters
    full = lm_state_specs(state)
    trace_specs = full.opt_state[1].trace  # chain: (wd, trace, lr)
    assert trace_specs["block0"]["attn"]["qkv"]["kernel"] == P(None, None, "model", None)
    assert trace_specs["block0"]["ln1"]["scale"] == P()


def test_tp_param_placement_is_real_sharding(devices8):
    mesh = make_mesh(devices8, data_parallel=2, seq_parallel=2, model_parallel=2)
    cfg = tiny_config(model_axis="model")
    tx = sgd_with_weight_decay(0.1)
    state = create_lm_state(cfg, tx, jax.random.key(0), init_len=8)
    state, _ = shard_lm_state(mesh, state)
    kernel = state.params["block0"]["attn"]["qkv"]["kernel"]  # [E,3,H,D]
    shard_shapes = {s.data.shape for s in kernel.addressable_shards}
    h = cfg.num_heads
    assert shard_shapes == {(cfg.embed_dim, 3, h // 2, cfg.embed_dim // h)}
