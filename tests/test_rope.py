"""Rotary position embeddings (round 4): rotation on q/k inside
attention, absolute positions baked in before any attention path runs —
so dense/flash/ring/zigzag/decode/PP all inherit it unchanged, and the
KV cache stores rotated keys. No wpe table (unbounded-length friendly).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow

from pytorch_distributed_tpu.models.generate import generate
from pytorch_distributed_tpu.models.transformer import (
    TransformerLM,
    tiny_config,
)
from pytorch_distributed_tpu.ops.optim import sgd_with_weight_decay
from pytorch_distributed_tpu.parallel import make_mesh
from pytorch_distributed_tpu.train.lm import (
    create_lm_state,
    make_lm_train_step,
    shard_lm_state,
    shift_labels,
)
from pytorch_distributed_tpu.train.lm_trainer import shard_lm_batch


def test_rope_config_validation():
    with pytest.raises(ValueError, match="pos_embedding"):
        tiny_config(pos_embedding="alibi")
    with pytest.raises(ValueError, match="even head_dim"):
        tiny_config(num_heads=2, embed_dim=6, pos_embedding="rope")
    with pytest.raises(ValueError, match="rope_theta"):
        tiny_config(pos_embedding="rope", rope_theta=0.0)
    tiny_config(pos_embedding="rope")  # fine


def test_rope_has_no_wpe_param():
    cfg = tiny_config(pos_embedding="rope")
    params = TransformerLM(cfg).init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    assert "wpe" not in params
    assert "wte" in params


def test_rope_is_shift_invariant():
    """RoPE attends by RELATIVE position: the same tokens at a different
    absolute offset produce identical logits (the learned-wpe model
    cannot do this) — a direct probe that the rotation algebra is right."""
    cfg = tiny_config(pos_embedding="rope", max_seq_len=128)
    model = TransformerLM(cfg)
    params = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))[
        "params"]
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(1, 128, (2, 16)), jnp.int32
    )
    out0 = model.apply({"params": params}, tokens, position_offset=0,
                       train=False)
    out9 = model.apply({"params": params}, tokens, position_offset=9,
                       train=False)
    np.testing.assert_allclose(np.asarray(out9), np.asarray(out0),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("kv_heads", [None, 2])
def test_rope_decode_matches_full_forward(kv_heads):
    """Cached decode (rotated keys in the cache, per-step rotation of the
    new token) == full-forward greedy rollout — with and without GQA."""
    cfg = tiny_config(num_heads=4, embed_dim=32, pos_embedding="rope",
                      num_kv_heads=kv_heads, max_seq_len=64)
    model = TransformerLM(cfg)
    params = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))[
        "params"]
    prompt = jnp.asarray(
        np.random.default_rng(1).integers(1, 128, (2, 7)), jnp.int32
    )
    got = np.asarray(generate(cfg, params, prompt, jax.random.key(2),
                              max_new_tokens=8, temperature=0.0))
    toks = np.asarray(prompt)
    for _ in range(8):
        logits = model.apply({"params": params}, jnp.asarray(toks),
                             train=False)
        nxt = np.argmax(np.asarray(logits)[:, -1], axis=-1).astype(np.int32)
        toks = np.concatenate([toks, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(got, toks)


@pytest.mark.parametrize("layout", ["contiguous", "zigzag"])
def test_rope_ring_matches_dense(devices8, layout):
    """RoPE under the seq-sharded ring (both layouts): the per-shard
    rotation positions (offset+arange / the zigzag chunk map) must agree
    with the single-device absolute positions — trajectories match."""
    tx = sgd_with_weight_decay(0.1, momentum=0.9)

    def run(mesh, cfg, layout, steps=3):
        state = create_lm_state(cfg, tx, jax.random.key(0), init_len=8)
        state, specs = shard_lm_state(mesh, state, cfg)
        step = make_lm_train_step(mesh, state_specs=specs, config=cfg)
        rng = np.random.default_rng(0)
        losses = []
        for i in range(steps):
            tokens = rng.integers(1, 128, (4, 32)).astype(np.int32)
            labels, weights = shift_labels(tokens)
            batch = shard_lm_batch(
                mesh, {"tokens": tokens, "labels": labels,
                       "weights": weights},
                layout=layout,
            )
            state, m = step(state, batch)
            losses.append(float(m["loss"]))
        return state, losses

    mesh_sp = make_mesh(devices8, data_parallel=2, seq_parallel=4)
    cfg_sp = tiny_config(pos_embedding="rope", attention="ring",
                         ring_layout=layout, max_seq_len=64)
    mesh_1 = make_mesh(devices8[:1])
    cfg_1 = tiny_config(pos_embedding="rope", attention="dense",
                        max_seq_len=64)
    state_sp, losses_sp = run(mesh_sp, cfg_sp, layout)
    state_1, losses_1 = run(mesh_1, cfg_1, "contiguous")
    np.testing.assert_allclose(losses_sp, losses_1, rtol=5e-4)
    for a, b in zip(jax.tree.leaves(jax.device_get(state_sp.params)),
                    jax.tree.leaves(jax.device_get(state_1.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=3e-5)


def test_rope_under_pp_matches_reference(devices8):
    from pytorch_distributed_tpu.train.pp import (
        create_pp_lm_state,
        make_pp_lm_train_step,
        make_pp_reference_step,
        shard_pp_state,
    )

    cfg = tiny_config(num_layers=4, pos_embedding="rope", max_seq_len=64)
    tx = sgd_with_weight_decay(0.1, momentum=0.9)
    mesh = make_mesh(devices8, data_parallel=2, seq_parallel=1,
                     model_parallel=4)
    state0 = create_pp_lm_state(cfg, 4, tx, jax.random.key(0), init_len=32)
    state_ref = create_pp_lm_state(cfg, 4, tx, jax.random.key(0),
                                   init_len=32)
    state_pp, specs = shard_pp_state(mesh, state0)
    step_pp = make_pp_lm_train_step(mesh, cfg, specs, n_microbatches=2)
    step_ref = make_pp_reference_step(cfg, 4, tx, n_microbatches=2)
    from jax.sharding import NamedSharding, PartitionSpec as P

    sh = NamedSharding(mesh, P("data"))
    rng = np.random.default_rng(7)
    for i in range(2):
        tokens = rng.integers(1, 128, (4, 32)).astype(np.int32)
        labels, weights = shift_labels(tokens)
        b = {"tokens": tokens, "labels": labels, "weights": weights}
        state_pp, m_pp = step_pp(
            state_pp, {k: jax.device_put(v, sh) for k, v in b.items()}
        )
        state_ref, m_ref = step_ref(state_ref, b)
        np.testing.assert_allclose(float(m_pp["loss"]), float(m_ref["loss"]),
                                   rtol=1e-4)
