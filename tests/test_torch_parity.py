"""Numerical parity with torchvision ResNets — the reference's correctness
bar is torchvision resnet50 top-1/top-5 on ImageNet (restnet_ddp.py:58-70);
the honest proxy available without an ImageNet run is that torchvision
weights imported into models/resnet.py produce the same logits, the same
train-mode batch statistics, and the same SGD loss trajectory as torch on
identical data.

Torch models are randomly initialized (zero-egress environment: pretrained
downloads are unavailable) — the mapping under test is purely structural,
so random weights prove it just as well.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from pytorch_distributed_tpu.models.resnet import (  # noqa: E402
    resnet18,
    resnet50,
)
from pytorch_distributed_tpu.models.torch_import import (  # noqa: E402
    export_resnet_state,
    import_resnet_state,
)
import torch_resnet_ref  # noqa: E402


def _batch(rng, b=2, hw=64):
    x = rng.standard_normal((b, 3, hw, hw)).astype(np.float32)
    return torch.from_numpy(x), jnp.asarray(x.transpose(0, 2, 3, 1))


def _import(tmodel, stage_sizes, bottleneck):
    return import_resnet_state(tmodel.state_dict(), stage_sizes, bottleneck)


@pytest.mark.parametrize(
    "tv_name,builder,stages,bottleneck",
    [
        ("resnet18", resnet18, (2, 2, 2, 2), False),
        ("resnet50", resnet50, (3, 4, 6, 3), True),
    ],
)
def test_eval_logits_match_torch(tv_name, builder, stages, bottleneck):
    """Same weights + same input ⇒ same logits (running-stats eval mode)."""
    torch.manual_seed(0)
    tmodel = getattr(torch_resnet_ref, tv_name)().eval()
    variables = _import(tmodel, stages, bottleneck)
    xt, xj = _batch(np.random.default_rng(1))

    with torch.no_grad():
        ref = tmodel(xt).numpy()
    got = np.asarray(builder().apply(variables, xj, train=False))
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_train_mode_batch_stats_match_torch():
    """Train-mode forward uses batch statistics; logits AND the updated
    running mean/var must match torch's momentum-0.1 update."""
    torch.manual_seed(1)
    tmodel = torch_resnet_ref.resnet18().train()
    variables = _import(tmodel, (2, 2, 2, 2), False)
    xt, xj = _batch(np.random.default_rng(2))

    with torch.no_grad():
        ref = tmodel(xt).numpy()  # also updates torch running stats
    got, mutated = resnet18().apply(
        variables, xj, train=True, mutable=["batch_stats"]
    )
    np.testing.assert_allclose(np.asarray(got), ref, rtol=2e-4, atol=2e-4)

    # bn1 running stats after one train-mode forward
    np.testing.assert_allclose(
        np.asarray(mutated["batch_stats"]["bn_init"]["mean"]),
        tmodel.bn1.running_mean.numpy(),
        rtol=1e-4,
        atol=1e-5,
    )
    np.testing.assert_allclose(
        np.asarray(mutated["batch_stats"]["bn_init"]["var"]),
        tmodel.bn1.running_var.numpy(),
        rtol=1e-3,
        atol=1e-5,
    )


def test_export_roundtrip_bit_exact():
    torch.manual_seed(2)
    tmodel = torch_resnet_ref.resnet18()
    variables = _import(tmodel, (2, 2, 2, 2), False)
    sd = export_resnet_state(variables, bottleneck=False)
    again = import_resnet_state(sd, (2, 2, 2, 2), bottleneck=False)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        variables,
        again,
    )
    # and the exported dict loads cleanly into torch (strict: all keys map,
    # num_batches_tracked excepted — flax has no equivalent counter)
    missing, unexpected = tmodel.load_state_dict(
        {k: torch.from_numpy(v) for k, v in sd.items()}, strict=False
    )
    assert not unexpected
    assert all(k.endswith("num_batches_tracked") for k in missing)


@pytest.mark.slow
def test_sgd_loss_trajectory_matches_torch():
    """Identical init + identical batches + the same SGD(momentum, wd) rule
    ⇒ the same loss trajectory, through batch-norm train mode and all."""
    from pytorch_distributed_tpu.ops.losses import cross_entropy_loss
    from pytorch_distributed_tpu.ops.optim import sgd_with_weight_decay

    torch.manual_seed(3)
    tmodel = torch_resnet_ref.resnet18(num_classes=10).train()
    variables = _import(tmodel, (2, 2, 2, 2), False)
    params, stats = variables["params"], variables["batch_stats"]

    lr, mom, wd = 0.001, 0.9, 1e-4
    opt = torch.optim.SGD(tmodel.parameters(), lr=lr, momentum=mom,
                          weight_decay=wd)
    crit = torch.nn.CrossEntropyLoss()

    tx = sgd_with_weight_decay(lr, momentum=mom, weight_decay=wd)
    opt_state = tx.init(params)
    model = resnet18(num_classes=10)

    @jax.jit
    def step(params, stats, opt_state, x, y):
        def loss_fn(p):
            logits, mut = model.apply(
                {"params": p, "batch_stats": stats}, x, train=True,
                mutable=["batch_stats"],
            )
            return cross_entropy_loss(logits, y), mut["batch_stats"]

        (loss, new_stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params
        )
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax_apply(params, updates), new_stats, opt_state, loss

    import optax

    def optax_apply(p, u):
        return optax.apply_updates(p, u)

    # batch 16, not smaller: BatchNorm over a tiny batch amplifies fp32
    # backend noise ~40x per step (measured at batch 4), swamping the
    # comparison; at 16 the trajectories stay locked to ~1e-3.
    rng = np.random.default_rng(4)
    torch_losses, jax_losses = [], []
    for _ in range(4):
        x = rng.standard_normal((16, 3, 32, 32)).astype(np.float32)
        y = rng.integers(0, 10, 16)

        opt.zero_grad()
        out = tmodel(torch.from_numpy(x))
        tl = crit(out, torch.from_numpy(y))
        tl.backward()
        opt.step()
        torch_losses.append(float(tl.detach()))

        params, stats, opt_state, jl = step(
            params, stats, opt_state,
            jnp.asarray(x.transpose(0, 2, 3, 1)), jnp.asarray(y),
        )
        jax_losses.append(float(jl))

    # Step 0 is the parity proof proper: identical weights and data, one
    # forward+backward through BN train mode — fp32 backend noise only.
    assert abs(jax_losses[0] - torch_losses[0]) < 1e-5
    # The remaining steps compound conv-backward fp noise through the
    # optimizer (different fp32 conv kernels on each side); the math being
    # identical keeps the trajectories within a few 1e-3.
    np.testing.assert_allclose(jax_losses, torch_losses, rtol=5e-3, atol=5e-3)
