"""Mixture-of-Experts: routing math, single-device correctness, and
expert-parallel (data-axis all_to_all) training parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow
from jax.sharding import NamedSharding, PartitionSpec as P

from pytorch_distributed_tpu.models.moe import MoEMLP, top1_dispatch
from pytorch_distributed_tpu.models.transformer import tiny_config
from pytorch_distributed_tpu.ops.optim import sgd_with_weight_decay
from pytorch_distributed_tpu.parallel import make_mesh
from pytorch_distributed_tpu.train.lm import (
    create_lm_state,
    lm_state_specs,
    make_lm_train_step,
    shard_lm_state,
    shift_labels,
)


def test_top1_dispatch_capacity_and_positions():
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(16, 4)), jnp.float32)
    dispatch, combine, aux = top1_dispatch(logits, capacity=3)
    d = np.asarray(dispatch)
    # every expert buffer slot holds at most one token
    assert (d.sum(axis=0) <= 1.0 + 1e-6).all()
    # every kept token occupies exactly one (expert, slot); dropped are zero
    per_tok = d.sum(axis=(1, 2))
    assert set(np.round(per_tok).astype(int)) <= {0, 1}
    # expert load never exceeds capacity
    assert (d.sum(axis=(0, 2)) <= 3 + 1e-6).all()
    # combine carries the router prob on the same slots
    c = np.asarray(combine)
    probs = np.asarray(jax.nn.softmax(logits, axis=-1))
    gate = probs.max(axis=-1)
    np.testing.assert_allclose(c.sum(axis=(1, 2)), gate * per_tok, rtol=1e-5)
    assert float(aux) > 0


def test_top1_dispatch_drops_over_capacity():
    # all tokens pick expert 0; capacity 2 keeps exactly the first 2
    logits = jnp.asarray(np.tile([5.0, 0.0], (6, 1)), jnp.float32)
    dispatch, _, _ = top1_dispatch(logits, capacity=2)
    d = np.asarray(dispatch)
    assert d[:, 0].sum() == 2.0 and d[:2, 0].sum() == 2.0
    assert d[2:].sum() == 0.0


def test_moe_mlp_matches_manual_expert_computation():
    m = MoEMLP(n_experts=4, mlp_dim=16, capacity_factor=4.0, aux_loss_weight=0.0)
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(2, 8, 8)), jnp.float32)
    variables = m.init(jax.random.key(0), x)
    out, _ = m.apply(variables, x, mutable=["aux_loss"])

    p = variables["params"]
    logits = np.asarray(x.reshape(16, 8) @ np.asarray(p["router"]["kernel"]))
    probs = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))
    chosen = probs.argmax(-1)
    w_up, w_down = np.asarray(p["w_up"]), np.asarray(p["w_down"])
    xf = np.asarray(x.reshape(16, 8))
    expect = np.zeros_like(xf)
    for t in range(16):
        e = chosen[t]
        h = np.asarray(jax.nn.gelu(jnp.asarray(xf[t] @ w_up[e])))
        expect[t] = probs[t, e] * (h @ w_down[e])
    np.testing.assert_allclose(
        np.asarray(out).reshape(16, 8), expect, rtol=1e-4, atol=1e-5
    )


def lm_run(mesh, ep, steps=3):
    dp = mesh.shape["data"]
    cfg = tiny_config(
        attention="ring" if mesh.shape["seq"] > 1 else "dense",
        n_experts=4,
        moe_every=2,
        # no drops on any layout (capacity >= local tokens) and no aux loss:
        # per-shard aux means differ from the global mean, breaking parity
        capacity_factor=float(4 * 8),
        moe_aux_weight=0.0,
        expert_axis="data" if ep > 1 else None,
        ep_size=ep,
    )
    tx = sgd_with_weight_decay(0.1, momentum=0.9)
    state = create_lm_state(cfg, tx, jax.random.key(0), init_len=8)
    state, specs = shard_lm_state(mesh, state, cfg)
    step_fn = make_lm_train_step(mesh, state_specs=specs)
    rng = np.random.default_rng(0)
    tokens = rng.integers(1, 128, (4, 32)).astype(np.int32)
    labels, weights = shift_labels(tokens)
    sh = NamedSharding(mesh, P("data", "seq"))
    batch = {
        "tokens": jax.device_put(tokens, sh),
        "labels": jax.device_put(labels, sh),
        "weights": jax.device_put(weights, sh),
    }
    losses = []
    for _ in range(steps):
        state, metrics = step_fn(state, batch)
        losses.append(float(metrics["loss"]))
    return state, losses


def test_expert_parallel_matches_single_device(devices8):
    mesh_ep = make_mesh(devices8, data_parallel=4, seq_parallel=2)
    mesh_1 = make_mesh(devices8[:1])
    state_ep, losses_ep = lm_run(mesh_ep, ep=4)
    state_1, losses_1 = lm_run(mesh_1, ep=1)
    np.testing.assert_allclose(losses_ep, losses_1, rtol=5e-4)
    flat_1 = {
        str(p): v for p, v in jax.tree_util.tree_leaves_with_path(state_1.params)
    }
    for path, leaf in jax.tree_util.tree_leaves_with_path(state_ep.params):
        np.testing.assert_allclose(
            np.asarray(leaf), np.asarray(flat_1[str(path)]),
            rtol=2e-3, atol=3e-5, err_msg=str(path),
        )


def test_expert_weights_sharded_over_data(devices8):
    mesh = make_mesh(devices8, data_parallel=4, seq_parallel=2)
    cfg = tiny_config(attention="ring", n_experts=4, expert_axis="data", ep_size=4)
    tx = sgd_with_weight_decay(0.1)
    state = create_lm_state(cfg, tx, jax.random.key(0), init_len=8)
    state, specs = shard_lm_state(mesh, state, cfg)
    w_up = state.params["block1"]["moe"]["w_up"]  # block1 is the MoE block
    shapes = {s.data.shape for s in w_up.addressable_shards}
    assert shapes == {(1, 32, 128)}  # 4 experts / 4 data ranks
    assert specs.params["block1"]["moe"]["w_up"] == P("data", None, None)


def test_moe_replicated_experts_on_dp_mesh(devices8):
    """ep_size=1 on a dp>1 mesh: experts stay REPLICATED (no EP rule) and
    training still matches single-device — regression for the rule that
    used to shard experts over the full data axis unconditionally."""
    mesh_dp = make_mesh(devices8, data_parallel=4, seq_parallel=2)
    mesh_1 = make_mesh(devices8[:1])
    state_dp, losses_dp = lm_run(mesh_dp, ep=1)
    state_1, losses_1 = lm_run(mesh_1, ep=1)
    np.testing.assert_allclose(losses_dp, losses_1, rtol=5e-4)
    w_up = state_dp.params["block1"]["moe"]["w_up"]
    assert {s.data.shape for s in w_up.addressable_shards} == {(4, 32, 128)}


def test_shard_lm_state_validates_ep(devices8):
    mesh = make_mesh(devices8, data_parallel=4, seq_parallel=2)
    cfg = tiny_config(
        attention="ring", n_experts=4, expert_axis="data", ep_size=2
    )  # ep_size != dp
    tx = sgd_with_weight_decay(0.1)
    state = create_lm_state(cfg, tx, jax.random.key(0), init_len=8)
    with pytest.raises(ValueError, match="ep_size"):
        shard_lm_state(mesh, state, cfg)
    with pytest.raises(ValueError, match="MoE"):
        lm_state_specs(state)  # config required for MoE params


def test_moe_aux_loss_trains(devices8):
    mesh = make_mesh(devices8[:1])
    cfg = tiny_config(n_experts=4, moe_aux_weight=0.01)
    tx = sgd_with_weight_decay(0.2, momentum=0.9)
    state = create_lm_state(cfg, tx, jax.random.key(0), init_len=8)
    state, specs = shard_lm_state(mesh, state, cfg)
    step_fn = make_lm_train_step(mesh, state_specs=specs)
    rng = np.random.default_rng(3)
    tokens = rng.integers(1, 128, (2, 16)).astype(np.int32)
    labels, weights = shift_labels(tokens)
    sh = NamedSharding(mesh, P("data", "seq"))
    batch = {
        "tokens": jax.device_put(tokens, sh),
        "labels": jax.device_put(labels, sh),
        "weights": jax.device_put(weights, sh),
    }
    first = last = None
    for _ in range(8):
        state, metrics = step_fn(state, batch)
        loss = float(metrics["loss"])
        first = first if first is not None else loss
        last = loss
    assert np.isfinite(last) and last < first

def test_top2_dispatch_math():
    from pytorch_distributed_tpu.models.moe import topk_dispatch

    rng = np.random.default_rng(5)
    logits = jnp.asarray(rng.normal(size=(16, 4)), jnp.float32)
    dispatch, combine, aux, stats = topk_dispatch(logits, capacity=16, k=2)
    d = np.asarray(dispatch)
    # ample capacity: every token gets exactly 2 routes
    np.testing.assert_allclose(d.sum(axis=(1, 2)), 2.0)
    assert float(stats["dropped_frac"]) == 0.0
    # combine weights are the top-2 probs normalized to sum 1 per token
    c = np.asarray(combine)
    np.testing.assert_allclose(c.sum(axis=(1, 2)), 1.0, rtol=1e-5)
    probs = np.asarray(jax.nn.softmax(logits, axis=-1))
    top2 = np.sort(probs, axis=-1)[:, -2:]
    np.testing.assert_allclose(
        c.max(axis=(1, 2)), top2.max(-1) / top2.sum(-1), rtol=1e-5
    )
    assert float(aux) > 0
    # per-slot exclusivity and capacity still hold
    assert (d.sum(axis=0) <= 1.0 + 1e-6).all()


def test_top2_rank_priority_under_capacity():
    from pytorch_distributed_tpu.models.moe import topk_dispatch

    # 3 tokens all prefer expert 0 then expert 1; capacity 2: first choices
    # fill expert 0 with tokens 0,1; second choices fill expert 1 with
    # tokens 0,1 (rank priority + arrival order); token 2 gets NOTHING and
    # is the dropped fraction the new metric reports.
    logits = jnp.asarray(np.tile([4.0, 2.0, -4.0], (3, 1)), jnp.float32)
    dispatch, _, _, stats = topk_dispatch(logits, capacity=2, k=2)
    d = np.asarray(dispatch)
    assert d[:2, 0].sum() == 2.0  # expert 0 at capacity, first choices win
    assert d[:2, 1].sum() == 2.0  # their second choices fill expert 1
    assert d[2].sum() == 0.0  # token 2 fully dropped
    np.testing.assert_allclose(float(stats["dropped_frac"]), 1.0 / 3.0,
                               rtol=1e-6)


def test_moe_top2_ep_parity_and_dropped_metric(devices8):
    """top-2 routing under expert parallelism matches single-device, and
    the step reports moe_dropped_frac."""
    mesh_ep = make_mesh(devices8, data_parallel=4, seq_parallel=2)
    mesh_1 = make_mesh(devices8[:1])

    def run(mesh, ep):
        cfg = tiny_config(
            attention="ring" if mesh.shape["seq"] > 1 else "dense",
            n_experts=4, moe_every=2, moe_top_k=2,
            capacity_factor=float(4 * 8), moe_aux_weight=0.0,
            expert_axis="data" if ep > 1 else None, ep_size=ep,
        )
        tx = sgd_with_weight_decay(0.1, momentum=0.9)
        state = create_lm_state(cfg, tx, jax.random.key(0), init_len=8)
        state, specs = shard_lm_state(mesh, state, cfg)
        step_fn = make_lm_train_step(mesh, state_specs=specs, config=cfg)
        rng = np.random.default_rng(0)
        tokens = rng.integers(1, 128, (4, 32)).astype(np.int32)
        labels, weights = shift_labels(tokens)
        sh = NamedSharding(mesh, P("data", "seq"))
        batch = {"tokens": jax.device_put(tokens, sh),
                 "labels": jax.device_put(labels, sh),
                 "weights": jax.device_put(weights, sh)}
        losses, dropped = [], []
        for _ in range(3):
            state, m = step_fn(state, batch)
            losses.append(float(m["loss"]))
            dropped.append(float(m["moe_dropped_frac"]))
        return losses, dropped

    losses_ep, dropped_ep = run(mesh_ep, ep=4)
    losses_1, dropped_1 = run(mesh_1, ep=1)
    np.testing.assert_allclose(losses_ep, losses_1, rtol=5e-4)
    # huge capacity factor -> nothing dropped, metric present and zero
    assert dropped_ep == dropped_1 == [0.0, 0.0, 0.0]


def test_moe_dropped_frac_nonzero_when_capacity_tight(devices8):
    mesh = make_mesh(devices8[:1])
    cfg = tiny_config(n_experts=4, moe_every=2, capacity_factor=0.3,
                      moe_aux_weight=0.0)
    tx = sgd_with_weight_decay(0.1)
    state = create_lm_state(cfg, tx, jax.random.key(0), init_len=8)
    state, specs = shard_lm_state(mesh, state, cfg)
    step_fn = make_lm_train_step(mesh, state_specs=specs, config=cfg)
    rng = np.random.default_rng(1)
    tokens = rng.integers(1, 128, (4, 32)).astype(np.int32)
    labels, weights = shift_labels(tokens)
    sh = NamedSharding(mesh, P("data", "seq"))
    batch = {"tokens": jax.device_put(tokens, sh),
             "labels": jax.device_put(labels, sh),
             "weights": jax.device_put(weights, sh)}
    _, m = step_fn(state, batch)
    assert 0.0 < float(m["moe_dropped_frac"]) < 1.0


def test_moe_tp_hidden_dim_sharding_matches_single_device(devices8):
    """MoE hidden dim partitioned over the model axis (Megatron split
    inside each expert, composed with EP over data and ring attention over
    seq): a dp2 x sp2 x tp2 MoE LM matches single-device training, and the
    expert weights really shard on BOTH axes."""
    mesh_3d = make_mesh(devices8, data_parallel=2, seq_parallel=2,
                        model_parallel=2)
    mesh_1 = make_mesh(devices8[:1])

    def run(mesh, tp, ep):
        cfg = tiny_config(
            attention="ring" if mesh.shape["seq"] > 1 else "dense",
            model_axis="model" if tp > 1 else None, tp_size=tp,
            n_experts=4, moe_every=2,
            capacity_factor=float(4 * 8), moe_aux_weight=0.0,
            expert_axis="data" if ep > 1 else None, ep_size=ep,
        )
        tx = sgd_with_weight_decay(0.1, momentum=0.9)
        state = create_lm_state(cfg, tx, jax.random.key(0), init_len=8)
        state, specs = shard_lm_state(mesh, state, cfg)
        step_fn = make_lm_train_step(mesh, state_specs=specs, config=cfg)
        rng = np.random.default_rng(0)
        tokens = rng.integers(1, 128, (4, 32)).astype(np.int32)
        labels, weights = shift_labels(tokens)
        sh = NamedSharding(mesh, P("data", "seq"))
        batch = {"tokens": jax.device_put(tokens, sh),
                 "labels": jax.device_put(labels, sh),
                 "weights": jax.device_put(weights, sh)}
        losses = []
        for _ in range(3):
            state, m = step_fn(state, batch)
            losses.append(float(m["loss"]))
        return state, specs, losses

    state_3d, specs, losses_3d = run(mesh_3d, tp=2, ep=2)
    _, _, losses_1 = run(mesh_1, tp=1, ep=1)
    np.testing.assert_allclose(losses_3d, losses_1, rtol=5e-4)
    # both axes really shard: [E=4, D=32, F=128] -> local (2, 32, 64)
    w_up = state_3d.params["block1"]["moe"]["w_up"]
    assert specs.params["block1"]["moe"]["w_up"] == P("data", None, "model")
    assert {s.data.shape for s in w_up.addressable_shards} == {(2, 32, 64)}
    w_down = state_3d.params["block1"]["moe"]["w_down"]
    assert specs.params["block1"]["moe"]["w_down"] == P("data", "model", None)
    assert {s.data.shape for s in w_down.addressable_shards} == {(2, 64, 32)}
