"""Block-lifecycle sanitizer (round 18 tentpole): the shadow ledger
proves the KV pool leak-free across the full serving lifecycle — admit,
prefix-share, COW, preempt/swap, restore, disagg handoff, retire, and a
cancellation storm — stays clean through every kill-matrix swap fault,
detects each seeded violation class, costs nothing when detached, and
streams schema-valid kind="sanitizer" JSONL."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.analysis.blocksan import (
    BlockSanError,
    BlockSanitizer,
    VIOLATION_KINDS,
    Violation,
    maybe_sanitizer,
)
from pytorch_distributed_tpu.models.transformer import (
    TransformerLM,
    tiny_config,
)
from pytorch_distributed_tpu.resilience import faults
from pytorch_distributed_tpu.resilience.faults import FaultPlan, FaultSpec
from pytorch_distributed_tpu.serving import BlockAllocator, Scheduler


@pytest.fixture(scope="module")
def model():
    cfg = tiny_config(attention="dense", max_seq_len=96)
    params = TransformerLM(cfg).init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return cfg, params


def _shared_prompts(cfg, prefix_len=24, tails=(8, 9, 3), seed=0):
    shared = np.arange(1, prefix_len + 1, dtype=np.int32)
    rng = np.random.default_rng(seed)
    return [
        np.concatenate([
            shared,
            rng.integers(1, cfg.vocab_size, (t,)).astype(np.int32),
        ])
        for t in tails
    ]


def _san_scheduler(cfg, params, **over):
    """A Scheduler with an explicitly-armed sanitizer (no env needed)."""
    kw = dict(n_slots=3, block_len=8, prefill_chunk=8, prefix_cache=True,
              offload=True, swap_policy="swap", protect_ticks=0)
    kw.update(over)
    return Scheduler(cfg, params, blocksan=BlockSanitizer(), **kw)


# ---------------------------------------------------------------------------
# the acceptance trace: every lifecycle edge, one run, zero violations
# ---------------------------------------------------------------------------


def test_acceptance_trace_admit_share_cow_swap_restore_retire(model):
    """THE tentpole gate: a serving trace covering admit →
    prefix-share → COW → preempt/swap → restore → retire ends with
    zero leaked blocks, zero refcount violations, and a shadow ledger
    identical to the allocator's books."""
    cfg, params = model
    prompts = _shared_prompts(cfg)
    twin = prompts[0].copy()  # block-aligned twin → the COW path
    s = _san_scheduler(cfg, params)
    outs = {}
    ra = s.submit(prompts[0], 4)
    for _ in range(8):  # a retires (4 prefill chunks, then 4 tokens)
        for rid, tok in s.step():
            outs.setdefault(rid, []).append(tok)
    assert len(outs.get(ra, [])) == 4  # retired; its prefix is indexed
    rb = s.submit(prompts[1], 8)
    for _ in range(5):  # b rides the shared prefix, starts decoding
        for rid, tok in s.step():
            outs.setdefault(rid, []).append(tok)
    assert s.preempt(rb, reason="test").choice == "swap"
    rc = s.submit(prompts[2], 4)
    rd = s.submit(twin, 4)
    for rid, toks in s.drain().items():
        outs.setdefault(rid, []).extend(toks)
    m = s.metrics()
    assert m["prefix_hits"] >= 3 and m["prefix_cow_copies"] >= 1
    assert m["preempts"] == 1 and m["restores"] == 1
    assert [len(outs[r]) for r in (ra, rb, rc, rd)] == [4, 8, 4, 4]
    # zero violations, and the ledger agrees with the allocator exactly
    assert s._san.verify_quiesce() == []
    s.blocksan.assert_clean()
    assert m["blocksan_violations"] == 0 and m["blocksan_by_kind"] == {}
    assert s.blocksan.events_total > 0
    # the ledger's live view IS the allocator's: index-retained blocks
    assert set(s._san.refs) == set(s.engine.allocator._refs)
    assert s.engine.allocator.in_use == m["prefix_index_blocks"]


def test_cancellation_storm_leaves_clean_ledger(model):
    """Cancel requests in every state — queued, mid-prefill, decoding,
    parked after a swap preemption — and the ledger must still equal
    the allocator at quiesce (the leak class cancellation historically
    invites)."""
    cfg, params = model
    prompts = _shared_prompts(cfg, tails=(5, 9, 3, 7, 4, 6))
    s = _san_scheduler(cfg, params, n_slots=2)
    rids = [s.submit(p, 8) for p in prompts]
    for _ in range(5):
        s.step()  # slot 0 decoding, slot 1 mid-prefill, rest queued
    s.preempt(rids[0], reason="test")  # parked via the swap path
    for rid in rids:
        s.cancel(rid, reason="storm")
    assert s.metrics()["cancelled"] > 0
    s.drain()
    assert s._san.verify_quiesce() == []
    s.blocksan.assert_clean()
    # cancel is idempotent and unknown rids are refused quietly
    assert s.cancel(rids[0]) is False and s.cancel(10_000) is False


def test_disagg_fleet_handoff_quiesce(model, monkeypatch):
    """The fleet rung: a disaggregated prefill→decode fleet under
    PDT_BLOCKSAN=1 (the env gate, end to end) hands chains across
    pools and drains with every replica's ledger clean — including the
    handoff pin windows, which only the sanitizer can see."""
    from pytorch_distributed_tpu.fleet import (
        FleetRouter,
        generate_trace,
        replay_trace,
        shared_prefix_prompt_for,
    )

    monkeypatch.setenv("PDT_BLOCKSAN", "1")
    cfg, params = model
    trace = generate_trace(
        seed=3, duration_s=40.0, base_rate=0.25, burst_rate_mult=2.0,
        burst_every_s=10.0, burst_len_s=2.0, sessions=4,
        prompt_median=10, prompt_sigma=0.6, prompt_min=4, prompt_max=24,
        max_new_median=5, max_new_sigma=0.4, max_new_min=2, max_new_max=8,
    )
    router = FleetRouter(cfg, params, n_replicas=2, disaggregate=True,
                         prefix_cache=True, n_slots=3, block_len=8,
                         prefill_chunk=16, admit_per_step=4)
    assert router.blocksan is not None  # armed from the env
    replay_trace(
        trace,
        lambda r: router.submit(
            shared_prefix_prompt_for(r, cfg.vocab_size, 24),
            r.max_new, session=r.session,
        ),
        router.step,
        lambda: router.idle,
    )
    router.drain()  # runs the fleet-wide ledger quiesce
    m = router.metrics()
    assert m["handoffs"] > 0
    assert m["blocksan_violations"] == 0
    router.blocksan.assert_clean()


def test_fleet_cancel_routes_to_owning_replica(model):
    cfg, params = model
    from pytorch_distributed_tpu.fleet import FleetRouter

    router = FleetRouter(cfg, params, n_replicas=2, n_slots=3,
                         block_len=8, prefill_chunk=16)
    prompt = np.arange(1, 9, dtype=np.int32)
    rid = router.submit(prompt, 4, session=0)
    assert router.cancel(rid) is True
    assert router.cancel(rid) is False  # idempotent
    router.drain()
    assert router.metrics()["cancelled"] == 1


# ---------------------------------------------------------------------------
# kill matrix × blocksan: every fault site leaves a clean ledger
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "site", ["kv.swap_out_d2h", "kv.host_write", "kv.swap_in_h2d"],
    ids=lambda s: s.split(".")[1],
)
def test_fault_at_swap_hazard_ledger_stays_clean(model, site):
    """An injected failure at each swap hazard site: whichever way the
    engine recovers (revert the preemption, retry from the host copy),
    the shadow ledger must end identical to the allocator with no open
    windows — the fault-injection half of the tentpole gate."""
    cfg, params = model
    prompt = np.arange(1, 10, dtype=np.int32)
    faults.install_plan(FaultPlan([
        FaultSpec(site=site, kind="raise", at=0)
    ]))
    try:
        s = _san_scheduler(cfg, params, n_slots=2, prefix_cache=False)
        a = s.submit(prompt, 6)
        got = []
        for _ in range(3):
            got += [t for rid, t in s.step() if rid == a]
        s.preempt(a, reason="test")
        got += s.drain().get(a, [])
        assert len(got) == 6
        assert s.metrics()["swap_aborts"] == 1
        assert faults.active_plan().fired == [(site, 0, "raise")]
    finally:
        faults.clear_plan()
    assert s._san.verify_quiesce() == []
    s.blocksan.assert_clean()
    assert s.engine.allocator.in_use == 0 and not s._san.refs


# ---------------------------------------------------------------------------
# seeded negatives: each violation class must be provably detectable
# ---------------------------------------------------------------------------


def _armed_pool(n_blocks=12):
    san = BlockSanitizer()
    alloc = BlockAllocator(n_blocks)
    shadow = san.attach(alloc, name="seeded")
    return san, alloc, shadow


def test_seeded_leak_at_retire():
    san, alloc, shadow = _armed_pool()
    alloc.alloc(3, 2)
    shadow.check_retire(3, rid=77)  # retired without freeing the chain
    with pytest.raises(BlockSanError, match="leak-at-retire"):
        san.assert_clean()
    v = san.violations[0]
    assert v.kind == "leak-at-retire" and v.owner == 3 and v.rid == 77


def test_seeded_double_free():
    san, alloc, shadow = _armed_pool()
    chain = alloc.alloc(0, 2)
    alloc.free(0)
    with pytest.raises(RuntimeError, match="double free"):
        alloc.decref(chain[0])  # the hook records BEFORE the raise
    with pytest.raises(BlockSanError, match="double-free"):
        san.assert_clean()


def test_seeded_refcount_underflow():
    san, alloc, shadow = _armed_pool()
    chain = alloc.alloc(0, 2)
    alloc._refs[chain[0]] = -1  # out-of-API tampering (the lint's beat)
    found = shadow.verify(site="seeded")
    assert any(v.kind == "refcount-underflow" for v in found)
    with pytest.raises(BlockSanError, match="refcount-underflow"):
        san.assert_clean()


def test_seeded_use_after_free_table_row():
    san, alloc, shadow = _armed_pool()
    chain = alloc.alloc(0, 2)
    alloc.free(0)
    tables = np.zeros((2, 4), np.int32)
    tables[1, 0] = chain[1]  # a retired chain's id left in the table
    shadow.check_tables(tables, trash_block=0)
    with pytest.raises(BlockSanError, match="use-after-free"):
        san.assert_clean()
    assert san.violations[0].block == chain[1]


def test_seeded_use_after_free_free_list_hands_out_live_block():
    san, alloc, shadow = _armed_pool()
    chain = alloc.alloc(0, 1)
    alloc._free.append(chain[0])  # free list corrupted with a live id
    alloc.alloc(1, 1)  # hands the live block out again
    assert any(v.kind == "use-after-free" for v in san.violations)


def test_seeded_pinned_block_handoff_free():
    san, alloc, shadow = _armed_pool()
    alloc.alloc(2, 2)
    shadow.pin(2, "handoff")
    alloc.free(2)  # the allocator allows this; the exported peer doesn't
    with pytest.raises(BlockSanError, match="pinned-block"):
        san.assert_clean()
    shadow.unpin(2)


def test_seeded_quiesce_mismatch():
    san, alloc, shadow = _armed_pool()
    chain = alloc.alloc(0, 2)
    alloc._refs[chain[0]] += 1  # books drift out of agreement
    found = shadow.verify_quiesce()
    assert any(v.kind == "quiesce-mismatch" for v in found)
    # the open chain is also reported: quiesce means EVERYTHING retired
    assert any(v.kind == "leak-at-retire" for v in found)
    with pytest.raises(BlockSanError, match="quiesce-mismatch"):
        san.assert_clean()


def test_violation_kind_is_validated():
    with pytest.raises(ValueError, match="unknown violation kind"):
        Violation(kind="nonsense", block=1, owner=0, rid=None,
                  site="x", detail="")
    assert len(VIOLATION_KINDS) == 6


# ---------------------------------------------------------------------------
# enablement + overhead: detached means DETACHED
# ---------------------------------------------------------------------------


def test_blocksan_off_by_default(model, monkeypatch):
    monkeypatch.delenv("PDT_BLOCKSAN", raising=False)
    assert maybe_sanitizer() is None
    cfg, params = model
    s = Scheduler(cfg, params, n_slots=2, block_len=8, prefill_chunk=8)
    assert s.blocksan is None and s._san is None
    assert s.engine.allocator.sanitizer is None
    s.submit(np.arange(1, 9, dtype=np.int32), 2)
    s.drain()
    assert "blocksan_violations" not in s.metrics()


def test_blocksan_env_gate_arms(monkeypatch):
    monkeypatch.setenv("PDT_BLOCKSAN", "1")
    assert maybe_sanitizer() is not None
    monkeypatch.setenv("PDT_BLOCKSAN", "off")
    assert maybe_sanitizer() is None


def test_attach_is_idempotent_per_allocator():
    san = BlockSanitizer()
    alloc = BlockAllocator(8)
    first = san.attach(alloc, name="a")
    second = san.attach(alloc, name="b")  # replaces, never duplicates
    assert alloc.sanitizer is second and first is not second
    assert [s.name for s in san.shadows] == ["b"]


# ---------------------------------------------------------------------------
# telemetry: kind="sanitizer" records validate against the registry
# ---------------------------------------------------------------------------


def test_sanitizer_jsonl_schema(tmp_path):
    from pytorch_distributed_tpu.telemetry.schema import validate_stream
    from pytorch_distributed_tpu.utils.profiling import MetricsLogger

    path = tmp_path / "san.jsonl"
    mlog = MetricsLogger(str(path))
    san = BlockSanitizer(metrics_log=mlog, replica_id=1)
    alloc = BlockAllocator(8)
    shadow = san.attach(alloc, name="replica1")
    chain = alloc.alloc(0, 2)
    alloc.free(0)
    with pytest.raises(RuntimeError, match="double free"):
        alloc.decref(chain[0])  # → one ev="violation" record
    shadow.verify_quiesce()  # → one ev="quiesce" record
    mlog.close()
    records = [json.loads(l) for l in path.read_text().splitlines() if l]
    assert not validate_stream(records)
    by_ev = {r["ev"]: r for r in records if r.get("kind") == "sanitizer"}
    assert by_ev["violation"]["class"] == "double-free"
    assert by_ev["violation"]["replica_id"] == 1
    # the quiesce pass reports drift found AT quiesce: the allocator
    # refused the double free, so the books still agree — ok, while
    # the recorded violation keeps assert_clean loud
    assert by_ev["quiesce"]["ok"] is True
    with pytest.raises(BlockSanError, match="double-free"):
        san.assert_clean()
