"""jaxlint: every rule fires exactly where the fixtures say, stays silent
on clean/suppressed code, the baseline machinery works, and the CLI's
exit codes hold — including exit 0 on the shipped package tree."""

import json
import os
import re
import subprocess
import sys

import pytest

from pytorch_distributed_tpu.analysis import (
    load_baseline,
    run_lint,
    split_baselined,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "jaxlint")
CLI = os.path.join(REPO, "scripts", "jaxlint.py")

_EXPECT_RE = re.compile(r"#\s*EXPECT:\s*([a-z\-]+(?:\s*,\s*[a-z\-]+)*)")


def expected_findings():
    """{(relpath, line, rule)} parsed from the fixtures' EXPECT comments."""
    out = set()
    for dirpath, _dirs, files in os.walk(FIXTURES):
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, FIXTURES).replace(os.sep, "/")
            with open(path) as f:
                for i, line in enumerate(f, start=1):
                    m = _EXPECT_RE.search(line)
                    if m:
                        for rule in m.group(1).split(","):
                            out.add((rel, i, rule.strip()))
    return out


def test_every_rule_fires_exactly_where_expected():
    findings = run_lint([FIXTURES], rel_root=FIXTURES)
    got = {(f.path, f.line, f.rule) for f in findings}
    want = expected_findings()
    assert want, "fixtures lost their EXPECT markers"
    missing = want - got
    spurious = got - want
    assert not missing, f"rules failed to fire: {sorted(missing)}"
    assert not spurious, f"false positives: {sorted(spurious)}"


def test_clean_and_suppressed_fixtures_stay_silent():
    for name in ("clean.py", "suppressed.py"):
        findings = run_lint(
            [os.path.join(FIXTURES, name)], rel_root=FIXTURES
        )
        assert findings == [], [f.render() for f in findings]


def test_severities_and_rendering():
    findings = run_lint([FIXTURES], rel_root=FIXTURES)
    by_rule = {f.rule: f for f in findings}
    assert by_rule["collective-axis"].severity == "error"
    assert by_rule["host-transfer"].severity == "error"
    assert by_rule["precision-cast"].severity == "warning"
    r = by_rule["collective-axis"].render()
    assert re.match(r"^bad_collectives\.py:\d+: collective-axis error: ", r)


def test_baseline_split(tmp_path):
    target = os.path.join(FIXTURES, "ops", "bad_precision.py")
    findings = run_lint([target], rel_root=FIXTURES)
    assert len(findings) == 4
    with open(target) as f:
        lines = f.read().splitlines()
    entries = [
        {
            "rule": f.rule,
            "file": f.path,
            "line_content": lines[f.line - 1].strip(),
            "reason": "reviewed in test",
        }
        for f in findings[:2]
    ]
    sources = {"ops/bad_precision.py": lines}
    new, old = split_baselined(findings, entries, sources)
    assert len(old) == 2 and len(new) == 2
    # content-based matching: a drifted line no longer matches
    entries[0]["line_content"] = "something.else()"
    new, old = split_baselined(findings, entries, sources)
    assert len(old) == 1 and len(new) == 3


def test_shipped_baseline_entries_all_carry_reasons():
    entries = load_baseline(os.path.join(REPO, "scripts", "jaxlint_baseline.json"))
    assert entries, "shipped baseline unexpectedly empty"
    for e in entries:
        assert e["reason"].strip(), e


def _cli(*args):
    return subprocess.run(
        [sys.executable, CLI, *args],
        capture_output=True, text=True, cwd=REPO,
    )


def test_cli_exit_1_on_fixture_violations():
    res = _cli("--no-baseline", "--no-partition-coverage", FIXTURES)
    assert res.returncode == 1, res.stdout + res.stderr
    assert "collective-axis" in res.stdout


def test_cli_json_format():
    res = _cli("--no-baseline", "--no-partition-coverage", "--format", "json",
               FIXTURES)
    data = json.loads(res.stdout)
    assert data["baselined"] == []
    assert any(f["rule"] == "recompile-traced-branch" for f in data["new"])


def test_cli_list_rules():
    res = _cli("--list-rules")
    assert res.returncode == 0
    for rule in ("collective-axis", "recompile-traced-branch",
                 "host-transfer", "partition-coverage", "precision-cast"):
        assert rule in res.stdout


def test_cli_exit_0_on_shipped_tree():
    """The acceptance gate: the package lints clean (fixed, suppressed
    with reasons, or baselined) including the partition-coverage check."""
    res = _cli(os.path.join(REPO, "pytorch_distributed_tpu"))
    assert res.returncode == 0, res.stdout + res.stderr
    assert "0 error(s), 0 warning(s)" in res.stdout


# ---- partition coverage (runtime check against real param trees) ----


def test_partition_coverage_clean_on_shipped_rules():
    from pytorch_distributed_tpu.analysis.partition_coverage import (
        check_partition_coverage,
    )

    findings = check_partition_coverage()
    assert findings == [], [f.render() for f in findings]


def test_partition_coverage_catches_fallthrough_and_dead_rules():
    from jax.sharding import PartitionSpec as P

    from pytorch_distributed_tpu.analysis.partition_coverage import (
        check_partition_coverage,
    )

    crippled = (
        (r"attn/qkv/kernel", P(None, None, "model", None)),
        (r"renamed_module/never_matches", P("model")),
    )
    findings = check_partition_coverage(rules=crippled)
    messages = "\n".join(f.message for f in findings)
    # the MLP kernels fell through to replicated...
    assert "mlp_up/kernel" in messages and "mlp_down/kernel" in messages
    # ...and the drifted pattern is called out as dead
    assert "renamed_module/never_matches" in messages
    assert all(f.rule == "partition-coverage" for f in findings)
