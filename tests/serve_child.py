"""Kill-matrix child for the KV pressure tier: a tiny real serve cycle.

Launched as a subprocess by tests/test_pressure.py. Run 1 carries a
``PDT_FAULT_PLAN`` that SIGKILLs the process at a swap hazard site
(``kv.swap_out_d2h`` / ``kv.host_write`` / ``kv.swap_in_h2d``) mid-cycle;
run 2 relaunches with no plan and must serve the same workload to
completion with token streams identical to an unpreempted reference —
the "fleet host restarts clean" proof: a swap interrupted by SIGKILL
leaves nothing durable to corrupt (the host store dies with the
process), so a relaunch simply serves.

The child streams flight-recorder events to a durable mirror
(``flightrec.jsonl``) so the parent can see the preempt/swap events that
preceded the kill, and writes ``result.json`` with every request's token
stream on a clean finish.

Round 16 (``--fleet-async``): the same seeded workload through a
2-replica ``FleetRouter(async_host=True)`` — the dispatch-then-collect
loop with worker threads — so the kill matrix gains an async-loop cell:
SIGKILL inside a swap window while ticks are in flight and workers hold
queued JSONL must still leave nothing durable to corrupt, and the
relaunch must serve token streams identical to the synchronous
reference.

Not a pytest module (no ``test_`` prefix) — invoke as
``python tests/serve_child.py --save-dir DIR [--fleet-async]``.
"""

import argparse
import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def workload(cfg):
    """The fixed, seeded workload both runs (and the parent's reference
    scheduler) serve — determinism is what makes the token-identity
    assertion meaningful across processes."""
    rng = np.random.default_rng(7)
    lens = [9, 17, 5, 13, 21, 7, 11, 15]
    return [rng.integers(1, cfg.vocab_size, l).astype(np.int32)
            for l in lens]


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--save-dir", required=True)
    ap.add_argument("--max-new", type=int, default=6)
    ap.add_argument("--fleet-async", action="store_true",
                    help="serve through a 2-replica async-host fleet "
                         "(dispatch-then-collect + worker threads) "
                         "instead of the single synchronous scheduler")
    args = ap.parse_args()

    from pytorch_distributed_tpu.models.transformer import (
        TransformerLM,
        tiny_config,
    )
    from pytorch_distributed_tpu.serving import Scheduler
    from pytorch_distributed_tpu.telemetry import FlightRecorder

    cfg = tiny_config(attention="dense", max_seq_len=64)
    params = TransformerLM(cfg).init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    flightrec = FlightRecorder(
        mirror_path=os.path.join(args.save_dir, "flightrec.jsonl")
    )
    if args.fleet_async:
        from pytorch_distributed_tpu.fleet import FleetRouter, SLOConfig

        # same over-commit per replica; the async loop keeps ticks in
        # flight and worker threads hold queued telemetry when the
        # fault plan SIGKILLs inside the swap window
        r = FleetRouter(
            cfg, params, n_replicas=2, async_host=True,
            slo=SLOConfig(spill_queue_depth=2, shed_queue_depth=10**6),
            flightrec=flightrec, n_slots=4, n_blocks=10, block_len=8,
            prefill_chunk=16, offload=True, preempt_on_oom=True,
            swap_policy="swap", protect_ticks=0,
        )
        rids = [r.submit(p, args.max_new) for p in workload(cfg)]
        streams = r.drain()
        m = r.metrics()
        assert m["preempts"] >= 1, "workload never preempted"
    else:
        # over-committed on purpose: the pool holds ~3 chains for 4
        # lanes + queue, so admission pressure preempts (forced swap
        # path — the hazard sites under test are the swap's)
        s = Scheduler(
            cfg, params, n_slots=4, n_blocks=10, block_len=8,
            prefill_chunk=16, offload=True, preempt_on_oom=True,
            swap_policy="swap", protect_ticks=0, flightrec=flightrec,
        )
        rids = [s.submit(p, args.max_new) for p in workload(cfg)]
        streams = s.drain()
        m = s.metrics()
        assert m["preempts"] >= 1, "workload never preempted"
    with open(os.path.join(args.save_dir, "result.json"), "w") as f:
        json.dump({
            "streams": {str(rid): streams[rid] for rid in rids},
            "preempts": m["preempts"],
            "swap_aborts": m["swap_aborts"],
        }, f)
    return 0


if __name__ == "__main__":
    sys.exit(main())
