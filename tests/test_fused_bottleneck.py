"""FusedBottleneckBlock == BottleneckBlock: same math, same checkpoint
tree, same batch-stat semantics — only the stats *computation path*
differs (input moments instead of a pass over the raw expand-conv
output; models/resnet.py `_expand_bn_stats`). Block-level comparisons are
tight (~1e-5); whole-model comparisons get looser tolerances because BN
amplifies fp reordering noise multiplicatively across 16 stacked blocks.
"""

from functools import partial

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow

from pytorch_distributed_tpu.models.resnet import (
    BottleneckBlock,
    FusedBottleneckBlock,
    conv_kernel_init,
    resnet50,
)


def _modules(train=True):
    conv = partial(
        nn.Conv, use_bias=False, padding="SAME", dtype=jnp.float32,
        kernel_init=conv_kernel_init,
    )
    norm = partial(
        nn.BatchNorm, use_running_average=not train, momentum=0.9,
        epsilon=1e-5, dtype=jnp.float32, axis_name=None,
    )
    return conv, norm


def _pair(strides, train=True, filters=8):
    conv, norm = _modules(train)
    plain = BottleneckBlock(filters=filters, conv=conv, norm=norm,
                            strides=strides)
    fused = FusedBottleneckBlock(filters=filters, conv=conv, norm=norm,
                                 strides=strides)
    return plain, fused


@pytest.mark.parametrize("strides", [1, 2])
def test_block_train_parity(strides):
    """Identical params ⇒ identical output, batch-stat updates, and grads
    (1e-5 fp32: the two formulations differ only in reduction order)."""
    x = jnp.asarray(
        np.random.default_rng(0).standard_normal((4, 8, 8, 16)), jnp.float32
    )
    plain, fused = _pair(strides)
    v = plain.init(jax.random.key(0), x)
    assert jax.tree.structure(v) == jax.tree.structure(
        fused.init(jax.random.key(1), x, True)
    )

    op, mp_ = plain.apply(v, x, mutable=["batch_stats"])
    of, mf = fused.apply(v, x, True, mutable=["batch_stats"])
    np.testing.assert_allclose(np.asarray(of), np.asarray(op), atol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        ),
        mf,
        mp_,
    )

    def loss(apply_args, model):
        out, _ = model.apply(*apply_args, mutable=["batch_stats"])
        return jnp.sum(out**2)

    gp = jax.grad(
        lambda p: loss(({"params": p, "batch_stats": v["batch_stats"]}, x),
                       plain)
    )(v["params"])
    gf = jax.grad(
        lambda p: loss(
            ({"params": p, "batch_stats": v["batch_stats"]}, x, True), fused
        )
    )(v["params"])
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b),
            rtol=1e-4, atol=1e-4 * float(jnp.abs(a).max()),
        ),
        gp,
        gf,
    )


def test_block_eval_parity():
    """Eval mode uses running stats on both paths — near bit-identical."""
    x = jnp.asarray(
        np.random.default_rng(1).standard_normal((2, 8, 8, 16)), jnp.float32
    )
    plain, fused = _pair(2, train=False)
    v = plain.init(jax.random.key(0), x)
    op = plain.apply(v, x)
    of = fused.apply(v, x, False)
    np.testing.assert_allclose(np.asarray(of), np.asarray(op), atol=1e-6)


def test_resnet50_fused_flag_same_tree_and_output():
    """The flag swaps every bottleneck in resnet50 without changing the
    variable tree; outputs agree within stacked-BN fp amplification."""
    x = jnp.asarray(
        np.random.default_rng(2).standard_normal((2, 64, 64, 3)), jnp.float32
    )
    plain, fused = resnet50(), resnet50(fused_bottleneck=True)
    v = plain.init(jax.random.key(0), x)
    assert jax.tree.structure(v) == jax.tree.structure(
        fused.init(jax.random.key(0), x)
    )

    op, _ = plain.apply(v, x, train=True, mutable=["batch_stats"])
    of, _ = fused.apply(v, x, train=True, mutable=["batch_stats"])
    np.testing.assert_allclose(np.asarray(of), np.asarray(op), atol=5e-3)
    # eval path stays tight end to end
    np.testing.assert_allclose(
        np.asarray(fused.apply(v, x, train=False)),
        np.asarray(plain.apply(v, x, train=False)),
        atol=1e-4,
    )


def test_bf16_fused_as_accurate_as_plain():
    """bf16 compute dtype: two bf16 roundings of 16 stacked BN blocks land
    far apart from EACH OTHER (untrained BN amplifies rounding noise
    multiplicatively), so closeness-to-each-other is the wrong bar. The
    right one: the fused path's deviation from the fp32 ground truth must
    be no worse than the plain bf16 path's (measured: both ~1.2 mean abs
    on this config)."""
    x = jnp.asarray(
        np.random.default_rng(3).standard_normal((2, 32, 32, 3)), jnp.float32
    )
    v = resnet50().init(jax.random.key(0), x)
    truth, _ = resnet50().apply(v, x, train=True, mutable=["batch_stats"])
    truth = np.asarray(truth)

    def dev(model):
        out, _ = model.apply(v, x, train=True, mutable=["batch_stats"])
        assert np.isfinite(np.asarray(out, np.float32)).all()
        return np.abs(np.asarray(out, np.float32) - truth).mean()

    d_plain = dev(resnet50(dtype=jnp.bfloat16))
    d_fused = dev(resnet50(dtype=jnp.bfloat16, fused_bottleneck=True))
    assert d_fused <= 1.25 * d_plain, (d_fused, d_plain)


def test_fused_torch_import_parity():
    """torchvision-layout weights load into the fused model unchanged and
    produce torch's logits (eval) — checkpoint interchange at the proof
    level of tests/test_torch_parity.py."""
    torch = pytest.importorskip("torch")
    import torch_resnet_ref

    from pytorch_distributed_tpu.models.torch_import import import_resnet_state

    torch.manual_seed(0)
    tmodel = torch_resnet_ref.resnet50().eval()
    variables = import_resnet_state(tmodel.state_dict(), (3, 4, 6, 3), True)
    x = np.random.default_rng(4).standard_normal((2, 3, 64, 64)).astype(
        np.float32
    )
    with torch.no_grad():
        ref = tmodel(torch.from_numpy(x)).numpy()
    got = np.asarray(
        resnet50(fused_bottleneck=True).apply(
            variables, jnp.asarray(x.transpose(0, 2, 3, 1)), train=False
        )
    )
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("strides", [1, 2])
def test_fused_sync_bn_matches_plain_sync_bn(devices8, strides):
    """Sync-BN × fused bottleneck (VERDICT r3 #5): with the moment psum
    across the data axis, the fused block's outputs, global batch stats,
    and pmean'd grads match flax's own sync-BN on the plain block — the
    hand-written vjp must reproduce autodiff-through-psum exactly."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pytorch_distributed_tpu.parallel import make_mesh
    from pytorch_distributed_tpu.parallel.mesh import shard_map

    mesh = make_mesh(devices8)  # 8-way data axis
    conv = partial(
        nn.Conv, use_bias=False, padding="SAME", dtype=jnp.float32,
        kernel_init=conv_kernel_init,
    )
    norm = partial(
        nn.BatchNorm, use_running_average=False, momentum=0.9,
        epsilon=1e-5, dtype=jnp.float32, axis_name="data",
    )
    plain = BottleneckBlock(filters=8, conv=conv, norm=norm, strides=strides)
    fused = FusedBottleneckBlock(filters=8, conv=conv, norm=norm,
                                 strides=strides,
                                 bn_cross_replica_axis="data")

    x_np = np.random.default_rng(5).standard_normal((16, 8, 8, 16)).astype(
        np.float32
    )
    x = jax.device_put(jnp.asarray(x_np), NamedSharding(mesh, P("data")))

    # init needs the axis bound too — run it inside a shard_map
    def init_fn(x):
        return plain.init(jax.random.key(0), x)

    v = jax.jit(shard_map(init_fn, mesh=mesh, in_specs=(P("data"),),
                          out_specs=P(), check_vma=False))(x)

    def run(model, *extra):
        def f(v, x):
            out, mut = model.apply(v, x, *extra, mutable=["batch_stats"])
            g = jax.grad(
                lambda p: jnp.sum(
                    model.apply(
                        {"params": p, "batch_stats": v["batch_stats"]},
                        x, *extra, mutable=["batch_stats"],
                    )[0] ** 2
                )
            )(v["params"])
            # local direct terms differ per replica; the trainer's pmean
            # is what makes them comparable
            return out, mut, jax.lax.pmean(g, "data")

        return jax.jit(shard_map(
            f, mesh=mesh, in_specs=(P(), P("data")),
            out_specs=(P("data"), P(), P()), check_vma=False,
        ))(v, x)

    op, mp_, gp_ = run(plain)
    of, mf, gf = run(fused, True)

    np.testing.assert_allclose(np.asarray(of), np.asarray(op), atol=2e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5
        ),
        mf, mp_,
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b),
            rtol=1e-4, atol=1e-4 * max(float(jnp.abs(a).max()), 1e-3),
        ),
        gf, gp_,
    )
    # and the synced stats really are GLOBAL: they match a single-device
    # stats pass over the full batch (plain non-sync path, whole x)
    _, m_full = BottleneckBlock(
        filters=8, conv=conv,
        norm=partial(nn.BatchNorm, use_running_average=False, momentum=0.9,
                     epsilon=1e-5, dtype=jnp.float32, axis_name=None),
        strides=strides,
    ).apply(v, jnp.asarray(x_np), mutable=["batch_stats"])
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5
        ),
        mf, m_full,
    )


def test_resnet_fused_sync_bn_initializes_and_runs(devices8):
    """The r3 guard is gone: fused_bottleneck composes with sync-BN at the
    model level (a pod run no longer chooses between the fused perf path
    and cross-replica BN statistics)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pytorch_distributed_tpu.models.resnet import ResNet
    from pytorch_distributed_tpu.parallel import make_mesh
    from pytorch_distributed_tpu.parallel.mesh import shard_map

    mesh = make_mesh(devices8)
    model = ResNet(stage_sizes=(1, 1), block_cls=BottleneckBlock,
                   num_classes=10, num_filters=8, fused_bottleneck=True,
                   bn_cross_replica_axis="data")
    x = jax.device_put(
        jnp.asarray(np.random.default_rng(6).standard_normal(
            (8, 16, 16, 3)), jnp.float32),
        NamedSharding(mesh, P("data")),
    )

    def f(x):
        v = model.init(jax.random.key(0), x)
        out, _ = model.apply(v, x, train=True, mutable=["batch_stats"])
        return out

    out = jax.jit(shard_map(f, mesh=mesh, in_specs=(P("data"),),
                            out_specs=P("data"), check_vma=False))(x)
    assert np.isfinite(np.asarray(out)).all()
