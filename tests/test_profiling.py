"""Profiling/observability utilities (SURVEY.md §5: must exceed the
reference's time.time()-print-only story)."""

import json
import math
import os

import jax
import jax.numpy as jnp

from pytorch_distributed_tpu.utils.profiling import (
    MetricsLogger,
    StepTimer,
    device_duty_cycle,
    trace,
)


def test_step_timer_summary():
    t = StepTimer(warmup_steps=1)
    import time

    for _ in range(5):
        t.tick()
        time.sleep(0.01)
    s = t.summary(items_per_step=100)
    assert s["steps"] == 3
    assert 5 < s["mean_ms"] < 100
    assert s["items_per_s"] > 0


def test_metrics_logger_jsonl(tmp_path):
    path = os.fspath(tmp_path / "m.jsonl")
    log = MetricsLogger(path)
    log.log(kind="train", step=1, loss=2.5)
    log.log(kind="val", epoch=0, acc1=11.0)
    log.close()
    lines = [json.loads(x) for x in open(path)]
    assert lines[0]["loss"] == 2.5 and lines[1]["kind"] == "val"
    MetricsLogger(None).log(anything=1)  # disabled: no-op


def test_trace_noop_and_capture(tmp_path, monkeypatch):
    monkeypatch.delenv("PDT_TRACE_DIR", raising=False)
    with trace():  # disabled — must not create anything
        pass
    target = os.fspath(tmp_path / "tr")
    with trace(log_dir=target):
        jnp.zeros(4).block_until_ready()
    assert os.path.isdir(target) and os.listdir(target)


def test_device_duty_cycle_chains_donated_state():
    @jax.jit
    def step(carry, x):
        new = carry + jnp.sum(x)
        return new, {"loss": new}

    duty = device_duty_cycle(step, jnp.zeros(()), jnp.ones(128), iters=5)
    # Trace-based measurement: on backends with no device track in the
    # profiler trace (CPU), the documented result is NaN.
    assert math.isnan(duty) or 0.0 < duty <= 1.0
