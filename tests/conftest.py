"""Test harness: 8 virtual CPU devices.

Multi-device behavior (pjit sharding, psum reductions, sampler shard logic)
is exercised without TPUs via XLA's host-platform device-count override —
the strategy SURVEY.md §4 prescribes. Must run before jax initializes a
backend, hence module-level in conftest.

Tiers (the full suite takes >10 min on one contended core):
  fast   pytest -m "not slow and not multihost"   (~5 min, 124 tests)
  full   pytest -m "not multihost"                 (everything local)
  all    pytest                                    (+ real 2-process runs)
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# A site-installed TPU plugin may have forced its own platform list into the
# jax config at interpreter start (overriding JAX_PLATFORMS); force CPU back
# before any backend is initialized so tests never touch real accelerators.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices8():
    import jax

    devices = jax.devices()
    assert len(devices) >= 8, f"expected 8 virtual devices, got {len(devices)}"
    return devices[:8]


def assert_trees_equal(a, b, rtol=0, atol=0):
    """Leaf-wise comparison of two pytrees by path (shared test helper)."""
    import numpy as np

    flat_b = {str(p): v for p, v in jax.tree_util.tree_leaves_with_path(b)}
    for path, leaf in jax.tree_util.tree_leaves_with_path(a):
        np.testing.assert_allclose(
            np.asarray(jax.device_get(leaf)),
            np.asarray(jax.device_get(flat_b[str(path)])),
            rtol=rtol, atol=atol, err_msg=str(path),
        )


from pytorch_distributed_tpu.utils.suspend import SuspendWatcher  # noqa: E402


class FireAtStep(SuspendWatcher):
    """Deterministic suspend injection shared by the trainer tests:
    fires once the poll count reaches n."""

    def __init__(self, n):
        super().__init__(install_handlers=False)
        self.n = n
        self.calls = 0

    def receive_suspend_command(self) -> bool:
        self.calls += 1
        return self.calls >= self.n or self._event.is_set()
