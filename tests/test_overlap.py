"""Host–device overlap profiler (round 15 tentpole): the dispatch
ledger's lagged-fence no-hot-sync contract, bubble classification on a
synthetic two-replica trace, schema-registry replay for
``kind="overlap"``, Perfetto device tracks + dispatch→device flow
arrows, the report/--require overlap gate, the explain busy/bubble
split, trainer step-loop wiring, and rules_threads cleanliness."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.analysis import no_recompile
from pytorch_distributed_tpu.analysis.core import LintContext, parse_file
from pytorch_distributed_tpu.analysis.rules_threads import check_threads
from pytorch_distributed_tpu.models.transformer import (
    TransformerLM,
    tiny_config,
)
from pytorch_distributed_tpu.serving import Scheduler
from pytorch_distributed_tpu.telemetry import (
    DispatchLedger,
    NULL_LEDGER,
    ReqTracer,
    busy_summary,
    busy_within,
    cause_histogram,
    chrome_trace,
    classify_bubbles,
    device_timeline,
    validate_stream,
)
from pytorch_distributed_tpu.telemetry.overlap import (
    CAUSE_IDLE,
    CAUSE_OTHER_REPLICA,
    DEVICE_PID_BASE,
)
from pytorch_distributed_tpu.utils.profiling import MetricsLogger

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _import_script(name):
    import importlib
    import sys

    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        return importlib.import_module(name)
    finally:
        sys.path.pop(0)


@pytest.fixture(scope="module")
def model():
    cfg = tiny_config(attention="dense", max_seq_len=64)
    params = TransformerLM(cfg).init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return cfg, params


def _prompts(lens, cfg, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, cfg.vocab_size, size=l).astype(np.int32)
            for l in lens]


# ---------------------------------------------------------------------------
# ledger mechanics: lagged fences, no hot-path sync
# ---------------------------------------------------------------------------


def test_ledger_lagged_fence_targets_only_old_launches(monkeypatch):
    """The PR 4 LAGGED idiom: launch N's record-keeping may fence ONLY
    launch N-lag (whose work is long done) — never anything newer. The
    fence targets are observable through which records got ``fenced``."""
    led = DispatchLedger(lag=3)
    f = jax.jit(lambda x: x * 2 + 1)
    x = jnp.ones((8,))
    outs = []
    for i in range(8):
        with led.launch(0, f"p{i}") as lt:
            y = f(x)
            lt.handle = y
        outs.append(y)
    launches = [r for r in led.records if r["ev"] == "launch"]
    assert len(launches) == 8
    # with lag 3, launches 0..4 were fenced by launches 3..7; the last
    # ``lag`` launches stay unfenced until finalize
    fenced = [r["program"] for r in launches if r.get("fenced")]
    assert fenced == [f"p{i}" for i in range(5)]
    assert led.hot_fences == 0
    assert led.dead_fences == 0
    # fences of long-finished work must not have blocked: no fence may
    # claim a completion (that only happens when the wait exceeded the
    # blocking epsilon — impossible here, the next dispatch is ms later)
    for r in launches[:5]:
        assert "fence_wait_s" in r


def test_ledger_fence_on_donated_buffer_is_loud_not_fatal():
    """A handle registered by mistake on a donated-away buffer must not
    crash the serve loop — it counts as a dead fence."""
    led = DispatchLedger(lag=1)
    f = jax.jit(lambda x: x + 1, donate_argnums=(0,))
    x = jnp.ones((8,))
    for i in range(3):
        with led.launch(0, "donating") as lt:
            x = f(x)
            lt.handle = x  # donated into the NEXT call: dead by fence time
    assert led.dead_fences >= 1
    assert led.hot_fences == 0


def test_ledger_adds_no_programs_and_decode_stays_guarded(model):
    """Arming the ledger is pure host bookkeeping: the decode program's
    jit cache must not grow and no implicit transfer may appear — the
    ``no_recompile``-style no-sync guard with the ledger armed."""
    cfg, params = model
    led = DispatchLedger(lag=2)
    s = Scheduler(cfg, params, n_slots=2, block_len=8, prefill_chunk=8,
                  ledger=led)
    for p in _prompts([12, 9], cfg):
        s.submit(p, 4)
    # warm: first chunk + decode compile here
    for _ in range(4):
        s.step()
    # arm the guard on the live decode program, ledger still attached
    s.engine._decode_fn = no_recompile(s.engine._decode(), warmup_steps=1)
    for p in _prompts([10, 11], cfg, seed=1):
        s.submit(p, 4)
    s.drain()
    stats = s.engine._decode_fn.stats
    assert stats.recompiles_after_warmup == 0
    assert led.hot_fences == 0
    assert [r for r in led.records if r["ev"] == "launch"]


def test_finalize_idempotent_and_emits_bubbles_summaries(model):
    cfg, params = model
    led = DispatchLedger(lag=2)
    s = Scheduler(cfg, params, n_slots=2, block_len=8, prefill_chunk=8,
                  ledger=led)
    for p in _prompts([12, 9, 15], cfg):
        s.submit(p, 4)
    s.drain()
    out = led.finalize()
    assert any(r["ev"] == "bubble" for r in out)
    assert any(r["ev"] == "summary" for r in out)
    assert led.finalize() == []  # idempotent
    summary = busy_summary(led.records)
    assert 0 < summary[0]["busy_frac"] <= 1.0
    # bubbles + busy tile the window exactly (accounting closes)
    bubble_s = sum(r["gap_s"] for r in led.records
                   if r.get("ev") == "bubble")
    assert summary[0]["busy_s"] + bubble_s == pytest.approx(
        summary[0]["window_s"], rel=1e-6
    )


# ---------------------------------------------------------------------------
# bubble classification on a synthetic two-replica trace
# ---------------------------------------------------------------------------


def _launch(rep, prog, t0, t1, seq0, seq1, done=None):
    r = {"kind": "overlap", "ev": "launch", "replica": rep,
         "program": prog, "t0": t0, "t1": t1, "seq0": seq0, "seq1": seq1}
    if done is not None:
        r["done"] = done
    return r


def test_synthetic_two_replica_bubble_classification():
    """Known gaps, known causes: replica 0 idles [1, 2.5] while replica
    1 runs [1, 2] (other-replica-tick wins by overlap share), then a
    host mark owns [2.0, 2.5]; an unexplained gap is idle-no-work; edge
    idle inside the fleet window is attributed too."""
    recs = [
        _launch(0, "decode_tick", 0.0, 1.0, 0, 1, done=1.0),
        _launch(1, "decode_tick", 1.0, 2.0, 2, 3, done=2.0),
        _launch(0, "decode_tick", 2.5, 3.0, 6, 7, done=3.0),
        _launch(0, "decode_tick", 4.0, 5.0, 8, 9, done=5.0),
        {"kind": "overlap", "ev": "host", "replica": 0,
         "name": "admission/gate", "t0": 2.0, "t1": 2.45,
         "seq0": 4, "seq1": 5},
    ]
    bubbles = classify_bubbles(recs)
    by_rep = {}
    for b in bubbles:
        by_rep.setdefault(b["replica"], []).append(b)
    r0 = by_rep[0]
    # gap 1: [1.0, 2.5] — replica 1's tick covers 1.0s of it, the
    # admission mark 0.45s: other-replica-tick wins
    assert r0[0]["cause"] == CAUSE_OTHER_REPLICA
    assert r0[0]["gap_s"] == pytest.approx(1.5)
    # gap 2: [3.0, 4.0] — nothing overlaps: idle-no-work
    assert r0[1]["cause"] == CAUSE_IDLE
    assert r0[1]["gap_s"] == pytest.approx(1.0)
    # replica 1 has edge bubbles inside the fleet window [0, 5]:
    # [0, 1] (r0 busy -> other-replica-tick) and [2, 5]
    r1 = by_rep[1]
    assert r1[0]["t0"] == pytest.approx(0.0)
    assert r1[0]["cause"] == CAUSE_OTHER_REPLICA
    assert sum(b["gap_s"] for b in r1) == pytest.approx(4.0)
    hist = cause_histogram(recs)
    assert set(hist) <= {CAUSE_OTHER_REPLICA, CAUSE_IDLE,
                         "admission/gate"}


def test_span_seq_join_attributes_unmarked_gap():
    """A gap no ledger mark explains joins the round-14 span stream via
    the shared logical clock: a ``handoff`` span with seq inside the
    gap's window attributes it to the handoff pump."""
    recs = [
        _launch(0, "decode_tick", 0.0, 1.0, 0, 1, done=1.0),
        _launch(0, "decode_tick", 2.0, 3.0, 8, 9, done=3.0),
        {"kind": "span", "v": 1, "ev": "begin", "trace": 7, "span": 3,
         "name": "handoff", "seq": 4, "t": 1.2},
    ]
    bubbles = classify_bubbles(recs)
    assert len(bubbles) == 1
    assert bubbles[0]["cause"] == "handoff-pump"


def test_busy_within_window_split():
    recs = [
        _launch(0, "decode_tick", 0.0, 1.0, 0, 1, done=1.0),
        _launch(0, "decode_tick", 2.0, 3.0, 2, 3, done=3.0),
    ]
    busy, bubble = busy_within(recs, 0, 0.5, 2.5)
    assert busy == pytest.approx(1.0)   # [0.5,1.0] + [2.0,2.5]
    assert bubble == pytest.approx(1.0)  # [1.0,2.0]


def test_device_timeline_monotone_under_lower_bounds():
    """Async launches without ``done`` collapse to the t1 lower bound,
    clamped monotone per stream (in-order execution)."""
    recs = [
        _launch(0, "chunk", 0.0, 1.0, 0, 1),
        _launch(0, "chunk", 1.1, 1.2, 2, 3),
        _launch(0, "decode_tick", 1.3, 5.0, 4, 5, done=5.0),
    ]
    slices = device_timeline(recs)[0]
    ends = [s["end"] for s in slices]
    assert ends == sorted(ends)
    assert slices[1]["start"] >= slices[0]["end"]


# ---------------------------------------------------------------------------
# end-to-end: fleet run -> JSONL -> schema/report/perfetto/explain/top
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def fleet_run(model, tmp_path_factory):
    """A 2-replica fleet served with the ledger + reqtrace sharing one
    MetricsLogger and one logical clock — the full overlap JSONL."""
    from pytorch_distributed_tpu.fleet import FleetRouter

    cfg, params = model
    path = os.fspath(tmp_path_factory.mktemp("overlap") / "run.jsonl")
    mlog = MetricsLogger(path)
    reqtrace = ReqTracer(mlog)
    ledger = DispatchLedger(mlog, seq_source=reqtrace, emit_every=16)
    router = FleetRouter(
        cfg, params, n_replicas=2, metrics_log=mlog, reqtrace=reqtrace,
        ledger=ledger, n_slots=2, block_len=8, prefill_chunk=8,
        admit_per_step=2,
    )
    for i, p in enumerate(_prompts([12, 9, 15, 10, 8, 14], cfg)):
        router.submit(p, 4, session=i % 3)
    router.drain()
    router.log_summary()
    ledger.finalize()
    mlog.close()
    records = [json.loads(l) for l in open(path) if l.strip()]
    return path, records, ledger


def test_overlap_schema_replay(fleet_run):
    """Every emitted record — spans, requests, overlap launches/hosts/
    bubbles/summaries — validates against the schema registry."""
    _path, records, _led = fleet_run
    assert [r for r in records if r.get("kind") == "overlap"]
    assert validate_stream(records) == []


def test_overlap_jsonl_batched_emission_marked(fleet_run):
    """The ledger's own JSONL writes are batched off the hot path and
    self-marked as jsonl-emit host intervals."""
    _path, records, _led = fleet_run
    hosts = [r for r in records if r.get("kind") == "overlap"
             and r.get("ev") == "host"]
    assert any(r.get("name") == "jsonl-emit" for r in hosts)
    assert any(r.get("name") == "admission/gate" for r in hosts)


def test_perfetto_device_tracks_and_flow_arrows(fleet_run):
    """The Chrome trace gains one device process per replica (device +
    dispatch rows) with dispatch→device flow arrows, alongside the
    per-request span processes."""
    _path, records, _led = fleet_run
    trace = chrome_trace(records)
    events = trace["traceEvents"]
    dev_pids = {e["pid"] for e in events if e.get("pid", 0)
                and e["pid"] >= DEVICE_PID_BASE}
    assert dev_pids == {DEVICE_PID_BASE, DEVICE_PID_BASE + 1}
    names = {
        (e["pid"], e.get("args", {}).get("name"))
        for e in events if e.get("ph") == "M"
        and e.get("name") == "thread_name"
    }
    for pid in dev_pids:
        assert (pid, "device") in names
        assert (pid, "dispatch") in names
    # busy slices on the device row, dispatch walls on the dispatch row
    for pid in dev_pids:
        assert any(e.get("ph") == "X" and e["pid"] == pid
                   and e["tid"] == 0 for e in events)
        assert any(e.get("ph") == "X" and e["pid"] == pid
                   and e["tid"] == 1 for e in events)
    flows = [e for e in events if e.get("cat") == "dispatch"]
    assert any(e["ph"] == "s" for e in flows)
    assert any(e["ph"] == "f" for e in flows)
    json.dumps(trace)  # serializable == Perfetto-loadable shape


def test_report_overlap_section_and_require_gate(fleet_run, capsys):
    report = _import_script("telemetry_report")
    path, _records, _led = fleet_run
    assert report.main([path, "--json", "--require", "overlap"]) == 0
    out = capsys.readouterr().out
    assert "overlap & bubbles" in out
    row = json.loads(out.strip().splitlines()[-1])
    assert row["overlap_replicas"] == 2
    assert row["overlap_launches"] > 0
    assert row["overlap_bubble_s_total"] > 0
    assert "overlap_busy_frac_r0" in row
    assert "overlap_d2c_p95_ms_decode_tick" in row


def test_report_require_overlap_fails_without_records(tmp_path, capsys):
    report = _import_script("telemetry_report")
    path = os.fspath(tmp_path / "plain.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"kind": "goodput", "goodput_frac": 1.0,
                            "productive_s": 1.0, "wall_s": 1.0}) + "\n")
    assert report.main([path, "--require", "overlap"]) == 2
    capsys.readouterr()


def test_explain_decode_window_busy_bubble_split(fleet_run, capsys):
    explain = _import_script("explain_request")
    path, records, _led = fleet_run
    rid = next(r["trace"] for r in records if r.get("kind") == "span")
    assert explain.main([path, "--rid", str(rid)]) == 0
    out = capsys.readouterr().out
    assert "busy /" in out and "bubble]" in out
    assert "decode device split:" in out


def test_pdt_top_overlap_row(fleet_run):
    top = _import_script("pdt_top")
    _path, records, _led = fleet_run
    view = top.View()
    view.feed(records)
    lines = view.lines()
    row = next(l for l in lines if l.startswith("overlap"))
    assert "busy" in row and "launches" in row


# ---------------------------------------------------------------------------
# trainer wiring + lint cleanliness
# ---------------------------------------------------------------------------


def test_lm_trainer_overlap_ledger(tmp_path):
    """``LMTrainerConfig.overlap`` arms the ledger over the trainer's
    JSONL: lm_train_step launches land with lagged fences on the step's
    metrics outputs, eval launches ride the t1 bound, and finalize's
    bubbles/summaries reach the stream."""
    from pytorch_distributed_tpu.data.tokens import SyntheticTokens
    from pytorch_distributed_tpu.parallel import make_mesh
    from pytorch_distributed_tpu.train import LMTrainer, LMTrainerConfig

    mesh = make_mesh(jax.devices()[:1], data_parallel=1, seq_parallel=1,
                     model_parallel=1)
    cfg = LMTrainerConfig(
        epochs=1, batch_size=2, lr=1e-2,
        save_dir=os.fspath(tmp_path / "lm"), num_workers=0, log_every=1,
        warmup_steps=0, overlap=True,
    )
    train = SyntheticTokens(size=12, seq_len=32, vocab_size=128)
    val = SyntheticTokens(size=8, seq_len=32, vocab_size=128, seed=9)
    t = LMTrainer(tiny_config(attention="dense"), train, val, cfg,
                  mesh=mesh)
    t.fit()
    t.metrics_log.close()
    records = [json.loads(l)
               for l in open(os.path.join(cfg.save_dir, "metrics.jsonl"))]
    launches = [r for r in records if r.get("kind") == "overlap"
                and r.get("ev") == "launch"]
    assert sum(r["program"] == "lm_train_step" for r in launches) == 6
    assert any(r["program"] == "lm_eval_step" for r in launches)
    assert any(r.get("fenced") for r in launches)
    assert any(r.get("ev") == "summary" for r in records
               if r.get("kind") == "overlap")
    assert t.ledger.hot_fences == 0
    assert t.ledger.dead_fences == 0
    assert validate_stream(records) == []


def test_null_ledger_is_inert(model):
    """Schedulers default to NULL_LEDGER: no records, no fences, and
    the with-block token still accepts a handle."""
    with NULL_LEDGER.launch(0, "p") as lt:
        lt.handle = jnp.ones(())
    assert NULL_LEDGER.records == []
    cfg, params = model
    s = Scheduler(cfg, params, n_slots=2, block_len=8, prefill_chunk=8)
    assert s.ledger is NULL_LEDGER
    assert s.engine.ledger is NULL_LEDGER


def test_bench_regression_wallclock_bands_and_direction():
    """Round-15 satellite: wall-clock keys carry the wide machine-wall
    band, device-busy fraction is direction-aware (a halved busy frac
    flags; fractions are otherwise skipped), and the accounted-gap
    fraction is tightly banded."""
    br = _import_script("bench_regression")
    assert br.direction("serving_wallclock_device_busy_frac_r0") == "up"
    assert br.direction("serving_wallclock_efficiency_frac") is None
    assert br.band_for("serving_wallclock_tok_s_1r", {}) == 1.5
    flagged = br.compare(
        {"serving_wallclock_device_busy_frac_r0": 0.1},
        {"serving_wallclock_device_busy_frac_r0": 0.3},
    )
    assert [r["key"] for r in flagged["regressions"]] == [
        "serving_wallclock_device_busy_frac_r0"
    ]
    # machine-wall weather inside the wide band does not page anyone
    calm = br.compare(
        {"serving_wallclock_tok_s_1r": 1500.0},
        {"serving_wallclock_tok_s_1r": 2600.0},
    )
    assert not calm["regressions"]


def test_rules_threads_passes_overlap_module_clean():
    ctx = LintContext(modules=[], mesh_axes=set(), axis_constants={})
    mod = parse_file(
        os.path.join(REPO, "pytorch_distributed_tpu/telemetry/overlap.py"),
        REPO,
    )
    findings = check_threads(mod, ctx)
    assert findings == [], [f.render() for f in findings]
