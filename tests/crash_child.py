"""Kill-matrix child: a tiny real training run for crash-recovery tests.

Launched as a subprocess by tests/test_resilience.py (and by
``scripts/ci_check.sh --resilience-smoke``). Run 1 carries a
``PDT_FAULT_PLAN`` that SIGKILLs the process at an injected checkpoint
hazard site; run 2 relaunches with no plan and must resume from a
complete checkpoint. The child logs every step to ``progress.jsonl`` and
writes ``result.json`` on a clean finish, so the parent can assert
resume-point and step-monotonicity without parsing stdout.

Not a pytest module (no ``test_`` prefix) — invoke as
``python tests/crash_child.py --save-dir DIR``.
"""

import argparse
import json
import os
import sys

# 8 virtual CPU devices, pinned BEFORE jax import (same as conftest.py)
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--save-dir", required=True)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--train-size", type=int, default=32)  # 2 steps/epoch
    # elastic resume (round 9): mesh shape "data,seq,model" and a
    # per-replica batch size, so a relaunch can resume the SAME save dir
    # on a DIFFERENT topology at a fixed global batch (reshard/)
    ap.add_argument("--mesh", default="8,1,1",
                    help="data,seq,model axis sizes (devices used = "
                    "their product)")
    ap.add_argument("--batch-size", type=int, default=2,
                    help="per-data-replica batch (global = bs x data)")
    args = ap.parse_args()
    dp, sp, mp = (int(x) for x in args.mesh.split(","))

    from pytorch_distributed_tpu.data import SyntheticImageClassification
    from pytorch_distributed_tpu.models.resnet import BasicBlock, ResNet
    from pytorch_distributed_tpu.parallel import make_mesh
    from pytorch_distributed_tpu.train import Trainer, TrainerConfig

    progress_path = os.path.join(args.save_dir, "progress.jsonl")

    class LoggingTrainer(Trainer):
        """Appends (run pid, global step, loss) after every train step so
        the parent can assert monotonic step progress across the crash."""

        def _post_step(self, metrics):
            super()._post_step(metrics)
            with open(progress_path, "a") as f:
                f.write(json.dumps({
                    "pid": os.getpid(),
                    "gstep": int(np.asarray(jax.device_get(self.state.step))),
                    "loss": float(metrics["loss"]),
                }) + "\n")

    cfg = TrainerConfig(
        epochs=args.epochs,
        batch_size=args.batch_size,  # default ×8 replicas = global 16
        lr=0.05,
        save_dir=args.save_dir,
        log_every=0,
        num_workers=0,
        prefetch=1,
        save_every_n_steps=1,  # every step is a durability point
        keep_last_ckpts=3,
    )
    model = ResNet(stage_sizes=(1, 1), block_cls=BasicBlock,
                   num_classes=10, num_filters=8)
    trainer = LoggingTrainer(
        model,
        SyntheticImageClassification(size=args.train_size, image_size=16,
                                     num_classes=10),
        SyntheticImageClassification(size=16, image_size=16, num_classes=10,
                                     seed=1),
        cfg,
        mesh=make_mesh(jax.devices()[: dp * sp * mp], data_parallel=dp,
                       seq_parallel=sp, model_parallel=mp),
        input_shape=(1, 16, 16, 3),
    )
    resumed = trainer.try_resume()  # fit() re-runs this; it's idempotent
    start_epoch, start_step = trainer.start_epoch, trainer.start_step
    summary = trainer.fit()
    with open(os.path.join(args.save_dir, "result.json"), "w") as f:
        json.dump({
            "resumed": bool(resumed),
            "start_epoch": start_epoch,
            "start_step": start_step,
            "final_step": int(np.asarray(jax.device_get(trainer.state.step))),
            "val_loss": float(summary["loss"]),
        }, f)
    return 0


if __name__ == "__main__":
    sys.exit(main())
