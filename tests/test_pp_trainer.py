"""Pipeline parallelism through the TRAINER (round 4): LMTrainer with
pipeline_stages > 0 runs the GPipe step + the PP eval step inside the
standard epoch/val/suspend loop — PP becomes reachable from a recipe
(`lm_pretrain.py --pipeline-stages N`), not only from the train.pp API."""

import jax
import numpy as np
import pytest

pytestmark = pytest.mark.slow

from pytorch_distributed_tpu.data.tokens import SyntheticTokens
from pytorch_distributed_tpu.models.transformer import tiny_config
from pytorch_distributed_tpu.parallel import make_mesh
from pytorch_distributed_tpu.train import LMTrainer, LMTrainerConfig
from conftest import FireAtStep  # noqa: E402


def make_trainer(save_dir, devices8, stages=0, watcher=None, dropout=0.0,
                 batch_size=4):
    if stages:
        mesh = make_mesh(devices8, data_parallel=len(devices8) // stages,
                         seq_parallel=1, model_parallel=stages)
    else:
        mesh = make_mesh(devices8, data_parallel=len(devices8),
                         seq_parallel=1, model_parallel=1)
    cfg = LMTrainerConfig(
        epochs=2, batch_size=batch_size, lr=1e-2, save_dir=str(save_dir),
        num_workers=0, log_every=1, pipeline_stages=stages,
        pp_microbatches=2,
    )
    model_cfg = tiny_config(attention="dense", num_layers=4,
                            dropout=dropout)
    train = SyntheticTokens(size=16, seq_len=32, vocab_size=128)
    val = SyntheticTokens(size=8, seq_len=32, vocab_size=128, seed=9)
    return LMTrainer(model_cfg, train, val, cfg, mesh=mesh,
                     suspend_watcher=watcher)


def test_pp_trainer_fits_and_is_deterministic(tmp_path, devices8):
    """The pipelined trainer trains (finite improving ppl through the PP
    eval step) and is run-to-run deterministic — the trainer-level
    integration contract. (Math parity of the PP step itself vs the
    sequential reference is pinned at step level in tests/test_pp_lm.py;
    cross-layout trainer parity is not meaningful because
    create_pp_lm_state's per-stage init necessarily differs from the
    flat model's init.)"""
    t_a = make_trainer(tmp_path / "a", devices8, stages=4)
    s_a = t_a.fit()
    assert np.isfinite(s_a["best_ppl"])
    assert s_a["best_ppl"] < 2 * 128  # better than ~1.5x uniform over vocab
    # params moved from init
    init = make_trainer(tmp_path / "init", devices8, stages=4)
    moved = [
        float(np.abs(np.asarray(a) - np.asarray(b)).max())
        for a, b in zip(jax.tree.leaves(jax.device_get(t_a.state.params)),
                        jax.tree.leaves(jax.device_get(init.state.params)))
    ]
    assert max(moved) > 1e-3
    # determinism: an identical second run lands bit-identically
    t_b = make_trainer(tmp_path / "b", devices8, stages=4)
    s_b = t_b.fit()
    assert s_b["best_ppl"] == s_a["best_ppl"]
    for a, b in zip(jax.tree.leaves(jax.device_get(t_a.state.params)),
                    jax.tree.leaves(jax.device_get(t_b.state.params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_pp_trainer_suspend_resume_bit_parity(tmp_path, devices8):
    """Interrupted + resumed pipelined training (dropout ON — the
    per-(step, stage, microbatch) keys must survive the checkpoint)
    equals the uninterrupted run bit for bit."""
    t_ref = make_trainer(tmp_path / "ref", devices8, stages=4, dropout=0.1)
    t_ref.fit()

    t_int = make_trainer(tmp_path / "int", devices8, stages=4, dropout=0.1,
                         watcher=FireAtStep(3))
    with pytest.raises(SystemExit):
        t_int.fit()
    assert t_int.ckpt.has_latest()

    t_res = make_trainer(tmp_path / "int", devices8, stages=4, dropout=0.1)
    t_res.fit()
    for a, b in zip(jax.tree.leaves(jax.device_get(t_ref.state.params)),
                    jax.tree.leaves(jax.device_get(t_res.state.params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_pp_trainer_rejects_bad_combos(tmp_path, devices8):
    mesh = make_mesh(devices8, data_parallel=2, seq_parallel=1,
                     model_parallel=4)
    cfg_mismatch = LMTrainerConfig(epochs=1, batch_size=4,
                                   save_dir=str(tmp_path), num_workers=0,
                                   pipeline_stages=2)
    train0 = SyntheticTokens(size=8, seq_len=32, vocab_size=128)
    with pytest.raises(ValueError, match="axis to carry the stages"):
        LMTrainer(tiny_config(attention="dense", num_layers=4), train0,
                  train0, cfg_mismatch, mesh=mesh)
    cfg = LMTrainerConfig(epochs=1, batch_size=4, save_dir=str(tmp_path),
                          num_workers=0, pipeline_stages=4, fsdp=True)
    train = SyntheticTokens(size=8, seq_len=32, vocab_size=128)
    with pytest.raises(ValueError, match="fsdp does not compose"):
        LMTrainer(tiny_config(attention="dense", num_layers=4), train,
                  train, cfg, mesh=mesh)
    cfg2 = LMTrainerConfig(epochs=1, batch_size=4, save_dir=str(tmp_path),
                           num_workers=0, pipeline_stages=4)
    with pytest.raises(ValueError, match="dedicated stage axis"):
        LMTrainer(tiny_config(attention="dense", num_layers=4,
                              model_axis="model", tp_size=2),
                  train, train, cfg2, mesh=mesh)


def test_pp_trainer_with_tp_inside_stages(tmp_path, devices8):
    """TP-within-PP through the trainer: a (data, stage, model) mesh runs
    Megatron collectives inside each stage while the trainer's loop,
    eval, and sharded checkpointing drive the pipeline. Fit + bit-exact
    suspend/resume."""
    def trainer(save_dir, watcher=None):
        mesh = make_mesh(devices8, data_parallel=2, seq_parallel=2,
                         model_parallel=2,
                         axis_names=("data", "stage", "model"))
        cfg = LMTrainerConfig(epochs=2, batch_size=4, lr=1e-2,
                              save_dir=str(save_dir), num_workers=0,
                              log_every=1, pipeline_stages=2,
                              pp_microbatches=2)
        model_cfg = tiny_config(attention="dense", num_layers=4,
                                dropout=0.1, model_axis="model", tp_size=2)
        train = SyntheticTokens(size=16, seq_len=32, vocab_size=128)
        val = SyntheticTokens(size=8, seq_len=32, vocab_size=128, seed=9)
        return LMTrainer(model_cfg, train, val, cfg, mesh=mesh,
                         suspend_watcher=watcher)

    t_ref = trainer(tmp_path / "ref")
    s = t_ref.fit()
    assert np.isfinite(s["best_ppl"])
    # the stage stack AND the Megatron dims really shard
    qkv_spec = t_ref.state_specs.params["stages"]["layer0"]["attn"][
        "qkv"]["kernel"]
    assert str(qkv_spec) == str(
        jax.sharding.PartitionSpec("stage", None, None, "model", None)
    )

    t_int = trainer(tmp_path / "int", watcher=FireAtStep(3))
    with pytest.raises(SystemExit):
        t_int.fit()
    t_res = trainer(tmp_path / "int")
    t_res.fit()
    for a, b in zip(jax.tree.leaves(jax.device_get(t_ref.state.params)),
                    jax.tree.leaves(jax.device_get(t_res.state.params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
