"""Fused blockwise linear+CE vs the materialized-logits reference.

The fused op must be a drop-in numeric replacement for
``lm_head Dense → fp32 logits → ops.losses.cross_entropy_loss`` — value
AND gradients (x, kernel, weights) — including under vocab-dim sharding.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from pytorch_distributed_tpu.ops.fused_ce import fused_linear_cross_entropy
from pytorch_distributed_tpu.ops.losses import cross_entropy_loss


def _ref_loss_sum(x, kernel, labels, weights):
    logits = (x @ kernel).astype(jnp.float32)
    per_tok = cross_entropy_loss(logits, labels, reduction="none")
    return jnp.sum(per_tok * weights)


def _rand(n=37, e=16, v=50, seed=0):
    r = np.random.RandomState(seed)
    x = jnp.asarray(r.randn(n, e), jnp.float32)
    k = jnp.asarray(0.3 * r.randn(e, v), jnp.float32)
    labels = jnp.asarray(r.randint(0, v, n), jnp.int32)
    w = jnp.asarray((r.rand(n) > 0.2).astype(np.float32))
    return x, k, labels, w


def test_forward_parity_fp32():
    x, k, labels, w = _rand()
    ref = _ref_loss_sum(x, k, labels, w)
    # block_n=8 with n=37 forces the zero-weight padding path
    got = fused_linear_cross_entropy(
        x, k, labels, w, block_n=8, compute_dtype=jnp.float32
    )
    np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-5)


def test_forward_parity_single_block():
    x, k, labels, w = _rand(n=12)
    ref = _ref_loss_sum(x, k, labels, w)
    got = fused_linear_cross_entropy(
        x, k, labels, w, block_n=1024, compute_dtype=jnp.float32
    )
    np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-5)


def test_grad_parity_fp32():
    x, k, labels, w = _rand()

    ref_g = jax.grad(
        lambda x_, k_, w_: _ref_loss_sum(x_, k_, labels, w_),
        argnums=(0, 1, 2),
    )(x, k, w)
    got_g = jax.grad(
        lambda x_, k_, w_: fused_linear_cross_entropy(
            x_, k_, labels, w_, block_n=8, compute_dtype=jnp.float32
        ),
        argnums=(0, 1, 2),
    )(x, k, w)
    for r, g in zip(ref_g, got_g):
        np.testing.assert_allclose(g, r, rtol=1e-5, atol=1e-5)


def test_grad_scaled_cotangent():
    # the step divides the sum by a global count — the vjp must scale
    x, k, labels, w = _rand(n=16)
    scale = 0.125
    ref = jax.grad(
        lambda x_: _ref_loss_sum(x_, k, labels, w) * scale
    )(x)
    got = jax.grad(
        lambda x_: fused_linear_cross_entropy(
            x_, k, labels, w, block_n=8, compute_dtype=jnp.float32
        ) * scale
    )(x)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_threed_input_and_bf16_smoke():
    x, k, labels, w = _rand(n=32, e=8, v=24)
    got = fused_linear_cross_entropy(
        x.reshape(4, 8, 8), k, labels.reshape(4, 8), w.reshape(4, 8),
        block_n=16, compute_dtype=jnp.bfloat16,
    )
    ref = _ref_loss_sum(x, k, labels, w)
    assert jnp.isfinite(got)
    # bf16 matmul with fp32 accumulation: loose tolerance
    np.testing.assert_allclose(got, ref, rtol=2e-2)


@pytest.mark.parametrize("tp", [2, 4])
def test_vocab_parallel_parity(tp):
    """Sharded kernel [E, V/tp] + vocab_axis must reproduce the replicated
    loss and grads exactly (fp32): streamed max/sum combine + masked
    label gather + psum'd dx."""
    from pytorch_distributed_tpu.parallel.mesh import shard_map

    x, k, labels, w = _rand(n=24, e=8, v=48, seed=3)
    mesh = Mesh(np.array(jax.devices()[:tp]), ("model",))

    def local(x_, k_local, labels_, w_):
        loss = fused_linear_cross_entropy(
            x_, k_local, labels_, w_, block_n=8,
            compute_dtype=jnp.float32, vocab_axis="model",
        )
        return loss

    def sharded_val_and_grad(x_, k_, labels_, w_):
        def f(x__, k_local, labels__, w__):
            g = jax.value_and_grad(local, argnums=(0, 1))(
                x__, k_local, labels__, w__
            )
            return g

        return shard_map(
            f,
            mesh=mesh,
            in_specs=(P(), P(None, "model"), P(), P()),
            out_specs=(P(), (P(), P(None, "model"))),
            check_vma=False,
        )(x_, k_, labels_, w_)

    (loss, (dx, dk)) = jax.jit(sharded_val_and_grad)(x, k, labels, w)
    ref = _ref_loss_sum(x, k, labels, w)
    ref_dx, ref_dk = jax.grad(
        lambda x_, k_: _ref_loss_sum(x_, k_, labels, w), argnums=(0, 1)
    )(x, k)
    np.testing.assert_allclose(loss, ref, rtol=1e-6, atol=1e-5)
    np.testing.assert_allclose(dx, ref_dx, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(dk, ref_dk, rtol=1e-5, atol=1e-6)


def test_lm_step_fused_vs_unfused():
    """The full train step with fused_ce must track the materialized-logits
    step: same loss and same params after 3 steps (fp32 tiny config —
    differences are reassociation-level only)."""
    import optax

    from pytorch_distributed_tpu.models.transformer import tiny_config
    from pytorch_distributed_tpu.train.lm import (
        create_lm_state,
        make_lm_train_step,
        shift_labels,
    )

    cfg = tiny_config()
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(4, 1), ("data", "seq"))
    r = np.random.RandomState(0)
    tokens = r.randint(0, cfg.vocab_size, (4, 32)).astype(np.int32)
    labels, w = shift_labels(tokens)
    batch = {
        "tokens": jnp.asarray(tokens),
        "labels": jnp.asarray(labels),
        "weights": jnp.asarray(w),
    }

    def run(fused):
        state = create_lm_state(
            cfg, optax.sgd(0.1), jax.random.key(0), init_len=32
        )
        step = make_lm_train_step(mesh, config=cfg, fused_ce=fused,
                                  fused_ce_block_n=16)
        losses = []
        for _ in range(3):
            state, m = step(state, batch)
            losses.append(float(m["loss"]))
        return losses, state.params

    l_fused, p_fused = run(True)
    l_ref, p_ref = run(False)
    np.testing.assert_allclose(l_fused, l_ref, rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6),
        p_fused, p_ref,
    )


def test_pp_step_fused_vs_unfused():
    """The pipelined PP step with fused_ce must track the
    materialized-logits PP step (both compared to themselves the existing
    test_pp_lm parity would cancel a shared head-wiring bug)."""
    import optax

    from pytorch_distributed_tpu.models.transformer import tiny_config
    from pytorch_distributed_tpu.train.lm import shift_labels
    from pytorch_distributed_tpu.train.pp import (
        create_pp_lm_state,
        make_pp_lm_train_step,
        shard_pp_state,
    )

    cfg = tiny_config(num_layers=4, vocab_size=96)
    mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("data", "model"))
    r = np.random.RandomState(1)
    tokens = r.randint(0, cfg.vocab_size, (4, 32)).astype(np.int32)
    labels, w = shift_labels(tokens)
    batch = {
        "tokens": jnp.asarray(tokens),
        "labels": jnp.asarray(labels),
        "weights": jnp.asarray(w),
    }

    def run(fused):
        state = create_pp_lm_state(
            cfg, 4, optax.sgd(0.1), jax.random.key(0), init_len=32
        )
        state, specs = shard_pp_state(mesh, state)
        step = make_pp_lm_train_step(
            mesh, cfg, specs, n_microbatches=2, fused_ce=fused,
            fused_ce_block_n=16,
        )
        losses = []
        for _ in range(3):
            state, m = step(state, batch)
            losses.append(float(m["loss"]))
        return losses, jax.device_get(state.params)

    l_fused, p_fused = run(True)
    l_ref, p_ref = run(False)
    np.testing.assert_allclose(l_fused, l_ref, rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6),
        p_fused, p_ref,
    )


def test_bf16_weights_cotangent_dtype():
    """ADVICE r5 #4: the weights cotangent must come back at the PRIMAL
    weights dtype. The backward used to hardcode fp32, which failed deep
    inside the vjp trace for bf16 weights; now grad wrt bf16 weights
    works and lands at bf16 (per-token loss stays fp32 until the final
    cast)."""
    x, k, labels, _ = _rand()
    w = jnp.ones(x.shape[0], jnp.bfloat16)

    ref = jax.grad(
        lambda w_: _ref_loss_sum(x, k, labels, w_.astype(jnp.float32))
    )(w.astype(jnp.float32))
    got = jax.grad(
        lambda w_: fused_linear_cross_entropy(
            x, k, labels, w_, block_n=8, compute_dtype=jnp.float32
        )
    )(w)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(ref), rtol=1e-2, atol=1e-2
    )
