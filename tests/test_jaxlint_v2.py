"""jaxlint v2: the dataflow rule families (donation/sharding/threads),
the rule catalogue + --explain single-sourcing, stable fingerprints,
SARIF emission, the incremental content-hash cache, --fix-baseline, and
the CI timing budget — plus the shipped-tree regression gates (the
donation pass must keep resolving the engine/generate donation sites and
keep finding them clean)."""

import json
import os
import re
import shutil
import subprocess
import sys

import pytest

from pytorch_distributed_tpu.analysis import (
    explain_rule,
    load_baseline,
    regenerate_baseline,
    rule_catalog,
    run_lint,
    run_lint_incremental,
    to_sarif,
)
from pytorch_distributed_tpu.analysis.rules_threads import thread_inventory
from pytorch_distributed_tpu.analysis.core import parse_file

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "pytorch_distributed_tpu")
FIXTURES = os.path.join(REPO, "tests", "fixtures", "jaxlint")
CLI = os.path.join(REPO, "scripts", "jaxlint.py")

_EXPECT_RE = re.compile(r"#\s*EXPECT:\s*([a-z\-]+(?:\s*,\s*[a-z\-]+)*)")
_CLEAN_RE = re.compile(r"#\s*CLEAN:\s*([a-z\-]+(?:\s*,\s*[a-z\-]+)*)")

#: runtime-only rule: proven by tests/test_jaxlint.py's partition
#: coverage tests against live param trees, not by parsed fixtures
_RUNTIME_RULES = {"partition-coverage"}


def _marker_rules(regex):
    out = set()
    for dirpath, _dirs, files in os.walk(FIXTURES):
        for fname in files:
            if not fname.endswith(".py"):
                continue
            with open(os.path.join(dirpath, fname)) as f:
                for line in f:
                    m = regex.search(line)
                    if m:
                        out.update(r.strip() for r in m.group(1).split(","))
    return out


def _cli(*args):
    return subprocess.run(
        [sys.executable, CLI, *args], capture_output=True, text=True,
        cwd=REPO,
    )


# ---- meta-test: fixture coverage of the whole catalogue --------------------


def test_every_rule_has_a_firing_fixture_and_a_clean_fixture():
    """Every shipped AST rule id must be proven twice over: at least one
    EXPECT marker (the rule fires) and at least one CLEAN marker (a
    correct-usage example stays silent — the exactness test in
    test_jaxlint.py fails if any CLEAN line produces a finding)."""
    catalog_ids = {r.rule for r in rule_catalog()} - _RUNTIME_RULES
    expects = _marker_rules(_EXPECT_RE)
    cleans = _marker_rules(_CLEAN_RE)
    assert catalog_ids - expects == set(), (
        f"rules with no firing fixture: {sorted(catalog_ids - expects)}"
    )
    assert catalog_ids - cleans == set(), (
        f"rules with no clean-pass fixture: {sorted(catalog_ids - cleans)}"
    )
    # and no marker names a rule that does not exist (typo guard)
    assert expects - catalog_ids == set(), sorted(expects - catalog_ids)
    assert cleans - catalog_ids == set(), sorted(cleans - catalog_ids)


def test_v2_severities():
    findings = run_lint([FIXTURES], rel_root=FIXTURES)
    by_rule = {f.rule: f for f in findings}
    assert by_rule["donation-use-after-donate"].severity == "error"
    assert by_rule["donation-alias"].severity == "error"
    assert by_rule["donation-none-hot-loop"].severity == "warning"
    assert by_rule["sharding-unknown-axis"].severity == "error"
    assert by_rule["sharding-spec-arity"].severity == "error"
    assert by_rule["sharding-replicated"].severity == "warning"
    assert by_rule["thread-unsynced-mutation"].severity == "warning"
    assert by_rule["thread-blocking-signal"].severity == "error"
    assert by_rule["lifecycle-alloc-leak"].severity == "error"
    assert by_rule["lifecycle-refcount-outside-allocator"].severity == "error"
    assert by_rule["lifecycle-span-imbalance"].severity == "warning"
    assert by_rule["lifecycle-fault-site-untested"].severity == "error"


# ---- fingerprints ----------------------------------------------------------


def test_fingerprints_stable_under_line_shift(tmp_path):
    src = os.path.join(FIXTURES, "bad_donation.py")
    a = tmp_path / "a"
    b = tmp_path / "b"
    a.mkdir(), b.mkdir()
    shutil.copy(src, a / "mod.py")
    with open(src) as f:
        content = f.read()
    # prepend comments: every finding moves down three lines
    (b / "mod.py").write_text("# shifted\n# shifted\n# shifted\n" + content)
    fa = run_lint([str(a)], rel_root=str(a))
    fb = run_lint([str(b)], rel_root=str(b))
    assert fa and len(fa) == len(fb)
    assert [f.fingerprint for f in fa] == [f.fingerprint for f in fb]
    assert all(f.fingerprint for f in fa)
    # and distinct findings get distinct fingerprints
    assert len({f.fingerprint for f in fa}) == len(fa)


# ---- catalogue / --explain -------------------------------------------------


def test_explain_covers_every_rule_and_matches_catalog():
    for info in rule_catalog():
        text = explain_rule(info.rule)
        assert text is not None
        assert info.rule in text and info.short in text
        # the long-form text is the module-sourced explain, verbatim
        assert info.explain in text
    assert explain_rule("no-such-rule") is None


def test_cli_explain_and_unknown_rule():
    res = _cli("--explain", "donation-use-after-donate")
    assert res.returncode == 0
    assert "use-after" in res.stdout and "donate_argnums" in res.stdout
    res = _cli("--explain", "bogus-rule")
    assert res.returncode == 2
    assert "known rules" in res.stderr


def test_cli_list_rules_includes_v2_families():
    res = _cli("--list-rules")
    assert res.returncode == 0
    for rule in ("donation-use-after-donate", "donation-alias",
                 "donation-none-hot-loop", "sharding-unknown-axis",
                 "sharding-spec-arity", "sharding-replicated",
                 "thread-unsynced-mutation", "thread-blocking-signal",
                 "lifecycle-alloc-leak",
                 "lifecycle-refcount-outside-allocator",
                 "lifecycle-span-imbalance",
                 "lifecycle-fault-site-untested"):
        assert rule in res.stdout, rule


# ---- SARIF -----------------------------------------------------------------


def test_sarif_structure_and_fingerprints():
    findings = run_lint([FIXTURES], rel_root=FIXTURES)
    doc = to_sarif(findings)
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert {r.rule for r in rule_catalog()} <= rule_ids
    results = run["results"]
    assert len(results) == len(findings)
    for res, f in zip(results, findings):
        assert res["ruleId"] == f.rule
        assert res["level"] in ("error", "warning")
        loc = res["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"] == f.path
        assert loc["region"]["startLine"] == f.line
        assert res["partialFingerprints"]["jaxlintFingerprint/v1"] == f.fingerprint


def test_cli_sarif_artifact(tmp_path):
    out = tmp_path / "lint.sarif"
    res = _cli("--no-baseline", "--no-partition-coverage",
               "--sarif-out", str(out), FIXTURES)
    assert res.returncode == 1  # fixtures do violate
    doc = json.loads(out.read_text())
    assert doc["runs"][0]["results"], "SARIF artifact carries no results"
    res = _cli("--no-baseline", "--no-partition-coverage",
               "--format", "sarif", FIXTURES)
    doc = json.loads(res.stdout)
    assert doc["version"] == "2.1.0"


def test_sarif_baselined_results_marked_unchanged():
    findings = run_lint([FIXTURES], rel_root=FIXTURES)
    doc = to_sarif(findings[:1], baselined=findings[1:3])
    results = doc["runs"][0]["results"]
    assert "baselineState" not in results[0]
    assert all(r["baselineState"] == "unchanged" for r in results[1:])


# ---- incremental cache -----------------------------------------------------


@pytest.fixture()
def small_tree(tmp_path):
    tree = tmp_path / "tree"
    tree.mkdir()
    for name in ("bad_donation.py", "bad_sharding.py", "clean_v2.py"):
        shutil.copy(os.path.join(FIXTURES, name), tree / name)
    return tree


def test_incremental_cache_roundtrip(small_tree, tmp_path):
    cache = str(tmp_path / "cache.json")
    full = run_lint([str(small_tree)], rel_root=str(small_tree))
    r1 = run_lint_incremental([str(small_tree)], cache,
                              rel_root=str(small_tree))
    assert r1.linted == 3 and r1.cached == 0
    r2 = run_lint_incremental([str(small_tree)], cache,
                              rel_root=str(small_tree))
    assert r2.linted == 0 and r2.cached == 3
    want = [(f.rule, f.path, f.line, f.fingerprint) for f in full]
    for r in (r1, r2):
        got = [(f.rule, f.path, f.line, f.fingerprint) for f in r.findings]
        assert got == want


def test_incremental_relints_only_changed_file(small_tree, tmp_path):
    cache = str(tmp_path / "cache.json")
    run_lint_incremental([str(small_tree)], cache, rel_root=str(small_tree))
    target = small_tree / "bad_donation.py"
    target.write_text(
        target.read_text().replace(
            "total = buf.sum()  # EXPECT: donation-use-after-donate",
            "total = 0",
        )
    )
    r = run_lint_incremental([str(small_tree)], cache,
                             rel_root=str(small_tree))
    assert r.linted == 1 and r.cached == 2 and not r.full_run
    assert not any(
        f.path == "bad_donation.py" and f.line == 17 for f in r.findings
    )
    # the edit's result must equal a from-scratch run (no stale findings)
    fresh = run_lint([str(small_tree)], rel_root=str(small_tree))
    assert (
        [(f.rule, f.path, f.line) for f in r.findings]
        == [(f.rule, f.path, f.line) for f in fresh]
    )


def test_incremental_context_change_forces_full_pass(small_tree, tmp_path):
    cache = str(tmp_path / "cache.json")
    run_lint_incremental([str(small_tree)], cache, rel_root=str(small_tree))
    # a new *_AXIS constant anywhere changes every file's axis context
    extra = small_tree / "axes.py"
    extra.write_text('EXPERT_AXIS = "expert"\n')
    r = run_lint_incremental([str(small_tree)], cache,
                             rel_root=str(small_tree))
    assert r.full_run and r.linted == 4
    # deleting it must invalidate again, not serve stale axis context
    extra.unlink()
    r = run_lint_incremental([str(small_tree)], cache,
                             rel_root=str(small_tree))
    assert r.full_run and r.cached == 0


def test_incremental_corrupt_cache_degrades_to_full_run(small_tree, tmp_path):
    cache = tmp_path / "cache.json"
    cache.write_text("{not json")
    r = run_lint_incremental([str(small_tree)], str(cache),
                             rel_root=str(small_tree))
    assert r.linted == 3
    fresh = run_lint([str(small_tree)], rel_root=str(small_tree))
    assert len(r.findings) == len(fresh)


def test_cli_incremental_smoke(tmp_path):
    cache = str(tmp_path / "cli_cache.json")
    res1 = _cli("--incremental", "--cache", cache, "--no-baseline",
                "--no-partition-coverage", FIXTURES)
    res2 = _cli("--incremental", "--cache", cache, "--no-baseline",
                "--no-partition-coverage", FIXTURES)
    assert res1.returncode == 1 and res2.returncode == 1
    assert "0 file(s) linted" in res2.stderr
    assert res1.stdout.splitlines()[:-1] == res2.stdout.splitlines()[:-1]


# ---- --fix-baseline --------------------------------------------------------


def test_regenerate_baseline_deterministic_and_reason_preserving():
    findings = run_lint([FIXTURES], rel_root=FIXTURES)
    sources = {}
    for f in findings:
        p = os.path.join(FIXTURES, f.path)
        with open(p) as fh:
            sources[f.path] = fh.read().splitlines()
    doc1 = regenerate_baseline(findings, [], sources)
    doc2 = regenerate_baseline(list(reversed(findings)), [], sources)
    assert doc1["findings"] == doc2["findings"], "order must be deterministic"
    assert all(
        e["reason"].startswith("UNREVIEWED") for e in doc1["findings"]
    )
    # reasons survive regeneration by (rule, file, content) identity
    reviewed = [dict(doc1["findings"][0], reason="reviewed: fp32 on purpose")]
    doc3 = regenerate_baseline(findings, reviewed, sources)
    assert doc3["findings"][0]["reason"] == "reviewed: fp32 on purpose"
    assert all(
        e["reason"].startswith("UNREVIEWED") for e in doc3["findings"][1:]
    )


def test_cli_fix_baseline_roundtrip(tmp_path):
    bl = tmp_path / "baseline.json"
    res = _cli("--no-partition-coverage", "--baseline", str(bl),
               "--fix-baseline", FIXTURES)
    assert res.returncode == 0, res.stdout + res.stderr
    entries = load_baseline(str(bl))
    assert entries
    # with the regenerated baseline, the same tree lints clean
    res = _cli("--no-partition-coverage", "--baseline", str(bl), FIXTURES)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "0 error(s), 0 warning(s)" in res.stdout


def test_shipped_baseline_shrank_below_nineteen():
    """ISSUE 9 burn-down gate: the reviewed baseline must be strictly
    smaller than the 19 entries it started with, every entry reasoned."""
    entries = load_baseline(
        os.path.join(REPO, "scripts", "jaxlint_baseline.json")
    )
    assert 0 < len(entries) < 19, len(entries)
    for e in entries:
        assert e["reason"].strip() and not e["reason"].startswith(
            "UNREVIEWED"
        ), e


# ---- timing budget ---------------------------------------------------------


def test_full_tree_lint_within_ci_budget():
    """The ci_check.sh gate: a full-tree lint (all rule families, no
    cache) must finish inside the 30 s CI CPU budget; --max-seconds
    exits 3 when it does not."""
    res = _cli("--no-partition-coverage", "--max-seconds", "30", PKG)
    assert res.returncode == 0, res.stdout + res.stderr


def test_cli_max_seconds_exceeded_exit_code():
    res = _cli("--no-partition-coverage", "--max-seconds", "0.000001", PKG)
    assert res.returncode == 3
    assert "exceeded" in res.stderr


# ---- lifecycle-fault-site-untested (round 19) ------------------------------


def test_fault_site_untested_tracks_chaos_matrix(tmp_path):
    """A serve fault site flags until the chaos matrix names it; adding
    the site string to tests/test_chaos_matrix.py silences the rule —
    the lint edge of the 'every fault site has a chaos entry' contract.
    Each repo root is probed independently (cached per chaos file)."""
    repo = tmp_path / "repo"
    (repo / "pkg").mkdir(parents=True)
    mod = repo / "pkg" / "loop.py"
    mod.write_text(
        "def tick(self):\n"
        "    fault_point(\"serve.reorder\")\n"
        "    return self.work()\n"
    )
    # no chaos file at all: the site flags
    findings = run_lint([str(repo)], rel_root=str(repo))
    mine = [f for f in findings
            if f.rule == "lifecycle-fault-site-untested"]
    assert len(mine) == 1 and mine[0].line == 2
    assert "serve.reorder" in mine[0].message
    # a chaos file that names OTHER sites still flags this one
    (repo / "tests").mkdir()
    chaos = repo / "tests" / "test_chaos_matrix.py"
    chaos.write_text("SITES = ['serve.dispatch']\n")
    findings = run_lint([str(repo)], rel_root=str(repo))
    assert any(f.rule == "lifecycle-fault-site-untested"
               for f in findings)
    # naming the site satisfies the contract
    chaos.write_text("SITES = ['serve.dispatch', 'serve.reorder']\n")
    findings = run_lint([str(repo)], rel_root=str(repo))
    assert not any(f.rule == "lifecycle-fault-site-untested"
                   for f in findings), [f.render() for f in findings]


def test_shipped_serve_sites_all_have_chaos_entries():
    """The live contract on the real tree: every serve fault_point in
    the scheduler/engine is named by the shipped chaos matrix."""
    from pytorch_distributed_tpu.analysis import rules_lifecycle as rl

    for rel in ("serving/scheduler.py", "serving/engine.py"):
        mod = parse_file(os.path.join(PKG, rel), REPO)
        findings = rl.check_lifecycle(mod, None)
        assert not any(f.rule == "lifecycle-fault-site-untested"
                       for f in findings), [f.render() for f in findings]


# ---- shipped-tree regression gates -----------------------------------------


def test_donation_pass_resolves_and_clears_shipped_donation_sites():
    """The PR 9 triage result, locked in: the pass RESOLVES the real
    donating call sites (so silence means 'analyzed and clean', not
    'failed to see them') and reports zero donation findings on the
    serving engine, generators and metrics ring."""
    from pytorch_distributed_tpu.analysis import rules_donation as rd

    suspects = {
        "serving/engine.py": 4,        # warm_import/chunk/decode + import_chain + run_chunks/decode
        "models/generate.py": 2,       # _submit_one + _step_fn
        "telemetry/device_metrics.py": 1,  # the donated ring push
    }
    resolved = {}
    orig = rd._DonationScope._check_call

    def spy(self, call, sig, ev, events, class_name):
        if sig != (((), ())) and sig[0]:
            resolved[self.mod.path] = resolved.get(self.mod.path, 0) + 1
        return orig(self, call, sig, ev, events, class_name)

    rd._DonationScope._check_call = spy
    try:
        findings = []
        for rel in suspects:
            mod = parse_file(os.path.join(PKG, rel), REPO)
            findings += rd.check_donation(mod, None)
    finally:
        rd._DonationScope._check_call = orig
    assert findings == [], [f.render() for f in findings]
    for rel, minimum in suspects.items():
        path = f"pytorch_distributed_tpu/{rel}"
        assert resolved.get(path, 0) >= minimum, (
            f"{rel}: donation pass no longer resolves its donating call "
            f"sites ({resolved.get(path, 0)} < {minimum}) — silence would "
            f"be blindness, not cleanliness"
        )


def test_thread_inventory_sees_shipped_entry_points():
    cases = {
        "compilecache/warmup.py": ("threads", "self._compile_batch"),
        "resilience/watchdog.py": ("threads", "self._run"),
        "telemetry/export.py": ("threads", None),  # serve_forever is opaque
        "utils/suspend.py": ("signal_handlers", "self._on_signal"),
        "telemetry/flightrec.py": ("excepthooks", None),
    }
    for rel, (kind, expected) in cases.items():
        mod = parse_file(os.path.join(PKG, rel), REPO)
        inv = thread_inventory(mod)
        assert inv[kind], f"{rel}: no {kind} found"
        if expected is not None:
            assert any(e.get("target") == expected
                       or e.get("handler") == expected
                       for e in inv[kind]), (rel, inv[kind])


def test_shipped_tree_clean_with_all_v2_families(tmp_path):
    """The acceptance gate restated for v2: the package lints clean with
    every rule family enabled — donation included — against the live
    baseline, and the SARIF artifact materializes alongside."""
    sarif = tmp_path / "jaxlint.sarif"
    res = _cli("--sarif-out", str(sarif), PKG)
    assert res.returncode == 0, res.stdout + res.stderr
    doc = json.loads(sarif.read_text())
    # new findings: none; baselined precision casts ride along as
    # 'unchanged' so CI viewers render the full picture
    new = [r for r in doc["runs"][0]["results"]
           if r.get("baselineState") != "unchanged"]
    assert new == []
