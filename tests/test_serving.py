"""Ragged serving (VERDICT r3 #10): per-request prompt lengths in one
prefill, per-slot decode, continuous batching — all pinned against the
uniform-batch ``generate`` path, which is itself parity-tested against
the training forward."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow

from pytorch_distributed_tpu.models.generate import (
    ContinuousBatcher,
    generate,
    generate_ragged,
)
from pytorch_distributed_tpu.models.transformer import (
    TransformerLM,
    tiny_config,
)


def setup(max_seq_len=96):
    cfg = tiny_config(attention="dense", max_seq_len=max_seq_len)
    params = TransformerLM(cfg).init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return cfg, params


def per_request_reference(cfg, params, prompts_list, max_new):
    """Greedy generate() one request at a time — the known-good path."""
    outs = []
    for p in prompts_list:
        full = generate(
            cfg, params, jnp.asarray(p)[None, :], jax.random.key(1),
            max_new_tokens=max_new, temperature=0.0,
        )
        outs.append(np.asarray(full)[0, len(p):])
    return outs


def test_generate_ragged_matches_per_request():
    cfg, params = setup()
    rng = np.random.default_rng(0)
    lengths = [5, 17, 32, 9]
    prompts_list = [
        rng.integers(1, cfg.vocab_size, (l,)).astype(np.int32)
        for l in lengths
    ]
    l_max = max(lengths)
    padded = np.zeros((len(lengths), l_max), np.int32)
    for i, p in enumerate(prompts_list):
        padded[i, : len(p)] = p

    got = np.asarray(generate_ragged(
        cfg, params, jnp.asarray(padded),
        jnp.asarray(lengths, jnp.int32), jax.random.key(1),
        max_new_tokens=12, temperature=0.0,
    ))
    ref = per_request_reference(cfg, params, prompts_list, 12)
    for i in range(len(lengths)):
        np.testing.assert_array_equal(got[i], ref[i], err_msg=f"req {i}")


def test_continuous_batcher_matches_per_request():
    """Requests admitted at DIFFERENT ticks (true continuous batching —
    request 2 joins while 0 and 1 are mid-decode; a slot is reused after
    its request retires) still reproduce the per-request greedy tokens."""
    cfg, params = setup()
    rng = np.random.default_rng(1)
    prompts = [
        rng.integers(1, cfg.vocab_size, (l,)).astype(np.int32)
        for l in (7, 13, 4, 21)
    ]
    budgets = [6, 10, 8, 5]
    ref = [
        per_request_reference(cfg, params, [p], b)[0]
        for p, b in zip(prompts, budgets)
    ]

    batcher = ContinuousBatcher(cfg, params, n_slots=2, prefill_bucket=8)
    got = {}
    slot_of = {}
    pending = list(range(len(prompts)))
    # admit the first two; the rest join as slots free up
    while pending or any(batcher.remaining > 0):
        while pending and batcher.free_slots():
            i = pending.pop(0)
            slot_of[i] = batcher.submit(prompts[i], budgets[i])
            got[i] = []
        for slot, token in batcher.step():
            req = next(i for i, s in slot_of.items()
                       if s == slot and len(got[i]) < budgets[i])
            got[req].append(token)

    for i in range(len(prompts)):
        np.testing.assert_array_equal(
            np.asarray(got[i], np.int32), ref[i], err_msg=f"req {i}"
        )


def test_ragged_validations():
    cfg, params = setup(max_seq_len=32)
    prompts = jnp.ones((2, 28), jnp.int32)
    lengths = jnp.asarray([28, 4], jnp.int32)
    with pytest.raises(ValueError, match="max_seq_len"):
        generate_ragged(cfg, params, prompts, lengths, jax.random.key(0),
                        max_new_tokens=8)
    cfg_ring = tiny_config(attention="ring")
    with pytest.raises(ValueError, match="dense-attention only"):
        generate_ragged(cfg_ring, params, prompts, lengths,
                        jax.random.key(0), max_new_tokens=2)


def test_continuous_batcher_eos_early_retirement():
    """With eos_id set, a slot retires the moment it emits EOS — the
    remaining budget is abandoned and the slot frees for the next
    request. Forced by picking the greedy argmax of the first step as
    the eos_id."""
    cfg, params = setup()
    rng = np.random.default_rng(3)
    prompt = rng.integers(1, cfg.vocab_size, (9,)).astype(np.int32)
    # find what greedy emits first, then declare THAT token the EOS
    first = int(per_request_reference(cfg, params, [prompt], 1)[0][0])
    batcher = ContinuousBatcher(cfg, params, n_slots=1, prefill_bucket=8,
                                eos_id=first)
    slot = batcher.submit(prompt, max_new_tokens=10)
    events = batcher.step()
    assert events == [(slot, first)]
    assert batcher.remaining[slot] == 0  # retired after 1 of 10 tokens
    assert batcher.free_slots() == [slot]
    assert batcher.step() == []  # nothing active
    # the freed slot admits a new request immediately
    slot2 = batcher.submit(prompt, max_new_tokens=2)
    assert slot2 == slot


def test_batcher_eos_validation():
    cfg, params = setup()
    with pytest.raises(ValueError, match="eos_id"):
        ContinuousBatcher(cfg, params, n_slots=1, eos_id=cfg.vocab_size)
