"""KV pressure tier (round 13 tentpole): allocator swap-state machine,
host block store, measured swap-vs-recompute decision, preempt-and-
restore token identity (swap AND recompute paths), fault injection at
every swap hazard site, the drain-while-swapping race, the SLO gate's
preempt rung, registry coverage of the swap programs, the over-committed
zero-shed scenario, and the SIGKILL-mid-swap kill-matrix cell."""

import json
import os
import signal
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.fleet import (
    PREEMPT,
    FleetRouter,
    SLOConfig,
    SLOGate,
    generate_trace,
    prompt_for,
    replay_trace,
)
from pytorch_distributed_tpu.models.transformer import (
    TransformerLM,
    tiny_config,
)
from pytorch_distributed_tpu.resilience import faults
from pytorch_distributed_tpu.resilience.faults import FaultPlan, FaultSpec
from pytorch_distributed_tpu.serving import (
    BlockAllocator,
    HostBlockStore,
    HostChain,
    PagedEngine,
    Scheduler,
)
from pytorch_distributed_tpu.telemetry.costmodel import (
    LINK_ENV_D2H,
    LINK_ENV_H2D,
    swap_vs_recompute,
)


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    """Every test starts and ends without an installed fault plan."""
    faults.clear_plan()
    yield
    faults.clear_plan()


@pytest.fixture(scope="module")
def model():
    cfg = tiny_config(attention="dense", max_seq_len=64)
    params = TransformerLM(cfg).init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return cfg, params


def greedy_streams(cfg, params, prompts, max_new):
    """Reference streams from an unpreempted scheduler with an ample
    pool — what every preempted/restored run must match token-for-
    token."""
    s = Scheduler(cfg, params, n_slots=max(2, len(prompts)), block_len=8,
                  prefill_chunk=8)
    rids = [s.submit(p, max_new) for p in prompts]
    out = s.drain()
    return [out[r] for r in rids]


# ---------------------------------------------------------------------------
# allocator swap-state machine + host store (pure host logic — fast)
# ---------------------------------------------------------------------------


def test_allocator_swap_state_machine():
    a = BlockAllocator(8)
    a.alloc(0, 3)
    assert a.state(0) == "resident"
    a.set_state(0, "swapping-out")
    assert a.state(0) == "swapping-out" and a.swapping() == [0]
    # THE satellite assertion: a mid-swap chain cannot be freed
    with pytest.raises(RuntimeError, match="swapping-out"):
        a.free(0)
    a.clear_state(0)
    a.free(0)  # resident again: frees fine
    assert a.available == 7
    # swapping-in protects the same way
    a.alloc(1, 2)
    a.set_state(1, "swapping-in")
    with pytest.raises(RuntimeError, match="swapping-in"):
        a.free(1)
    a.clear_state(1)
    a.free(1)
    # states only exist on live chains; bogus states are rejected
    with pytest.raises(ValueError, match="no chain"):
        a.set_state(5, "swapping-out")
    a.alloc(2, 1)
    with pytest.raises(ValueError, match="must be one of"):
        a.set_state(2, "teleporting")
    a.clear_state(99)  # idempotent no-op


def test_release_all_refuses_mid_swap(model):
    """``release_all`` (teardown) walks ``free`` — a mid-swap chain
    makes it raise instead of silently recycling blocks under an open
    d2h window."""
    cfg, params = model
    eng = PagedEngine(cfg, params, 2, block_len=8, prefill_chunk=8,
                      swap=True)
    assert eng.admit(0, 9, 4)
    eng.allocator.set_state(0, "swapping-out")
    with pytest.raises(RuntimeError, match="swapping-out"):
        eng.release_all()
    eng.allocator.clear_state(0)
    eng.release_all()
    assert eng.allocator.in_use == 0


def test_host_block_store_accounting_and_budget():
    def chain(nbytes):
        return HostChain(blocks=None, logits_row=None, n_blocks=1,
                         block_len=8, nbytes=nbytes)

    store = HostBlockStore(max_bytes=100)
    assert store.has_room(100) and not store.has_room(101)
    assert store.put(1, chain(60))
    assert 1 in store and store.bytes_used == 60 and len(store) == 1
    assert not store.put(2, chain(50))  # over budget: refused, unchanged
    assert store.bytes_used == 60 and 2 not in store
    with pytest.raises(ValueError, match="already has"):
        store.put(1, chain(10))
    assert store.put(3, chain(40))
    assert store.rids() == [1, 3]
    popped = store.pop(1)
    assert popped.nbytes == 60 and store.bytes_used == 40
    assert HostBlockStore().has_room(10**15)  # unbounded default


# ---------------------------------------------------------------------------
# the swap-vs-recompute decision (pure policy — fast)
# ---------------------------------------------------------------------------


def test_swap_decision_crossover(monkeypatch):
    """Seeded cost inputs on both sides of the crossover pick the
    cheaper path; PDT_PEAK_H2D/D2H_GBS env overrides steer it
    deterministically (the CPU-CI knob)."""
    # explicit rates: 1 MiB chain, 1 GiB/s each way -> ~2 ms swap
    fast_link = dict(h2d_bytes_s=2**30, d2h_bytes_s=2**30)
    d = swap_vs_recompute(2**20, chunks=4, chunk_wall_s=0.010,
                          **fast_link)
    assert d.choice == "swap" and d.reason == "measured-crossover"
    assert d.swap_s < d.recompute_s
    d = swap_vs_recompute(2**20, chunks=4, chunk_wall_s=0.0001,
                          **fast_link)
    assert d.choice == "recompute" and d.swap_s > d.recompute_s
    # unmeasured sides degrade to the stated defaults
    assert swap_vs_recompute(
        2**20, chunks=0, **fast_link
    ).choice == "swap"
    assert swap_vs_recompute(
        2**20, chunks=4, chunk_wall_s=0.01,
        h2d_bytes_s=None, d2h_bytes_s=0.0,
    ).reason in ("link-unmeasured", "measured-crossover")
    # env overrides beat the measured probe: an absurdly slow link
    # forces recompute, an absurdly fast one forces swap — this is how
    # CPU CI pins the decision without wall-clock flakiness
    monkeypatch.setenv(LINK_ENV_H2D, "1e-9")
    monkeypatch.setenv(LINK_ENV_D2H, "1e-9")
    assert swap_vs_recompute(
        2**20, chunks=2, chunk_wall_s=0.01
    ).choice == "recompute"
    monkeypatch.setenv(LINK_ENV_H2D, "1e9")
    monkeypatch.setenv(LINK_ENV_D2H, "1e9")
    assert swap_vs_recompute(
        2**20, chunks=2, chunk_wall_s=0.01
    ).choice == "swap"


def test_scheduler_decision_steered_by_env(model, monkeypatch):
    """Scheduler-level decision boundary: with a measured chunk wall in
    the cost-card join, the env-pinned link rate alone flips the
    preemption between swap and recompute."""
    cfg, params = model
    prompt = np.arange(1, 10, dtype=np.int32)

    def preempt_one(h2d_gbs):
        monkeypatch.setenv(LINK_ENV_H2D, h2d_gbs)
        monkeypatch.setenv(LINK_ENV_D2H, h2d_gbs)
        s = Scheduler(cfg, params, n_slots=2, block_len=8,
                      prefill_chunk=8, offload=True, protect_ticks=0)
        s.submit(prompt, 2)
        s.drain()  # compiles the buckets (cold walls book as compile)
        rid = s.submit(prompt, 6)
        for _ in range(3):
            s.step()  # warm chunk dispatches -> measured program wall
        assert any(
            p.startswith("chunk_prefill") for p, _ in s.prog_times.items()
        )
        d = s.preempt(rid)
        s.drain()
        return d

    d = preempt_one("1e9")  # ~instant link: swap wins
    assert d.choice == "swap" and d.reason == "measured-crossover"
    d = preempt_one("1e-9")  # ~dead link: recompute wins
    assert d.choice == "recompute" and d.reason == "measured-crossover"


def test_gate_preempt_rung_between_queue_and_shed():
    gate = SLOGate(SLOConfig(spill_queue_depth=1, shed_queue_depth=2))
    hot = {"queue_depth": 3, "occupancy": 1.0}
    # overloaded + preemptible -> preempt on the least-loaded candidate
    d = gate.route({
        0: {**hot, "preemptible": 2, "offload": True},
        1: {**hot, "queue_depth": 4, "preemptible": 1, "offload": True},
    }, preferred=1)
    assert d.action == PREEMPT and d.replica == 0
    # overloaded, nothing preemptible RIGHT NOW, but the pressure tier
    # is on -> queue (backpressure), not shed
    d = gate.route({0: {**hot, "preemptible": 0, "offload": True}},
                   preferred=None)
    assert d.action == "admit" and d.reason == "pressure-queue"
    # the pressure queue bound restores the shed as a true last resort
    gate2 = SLOGate(SLOConfig(spill_queue_depth=1, shed_queue_depth=2,
                              pressure_queue_depth=3))
    d = gate2.route({0: {**hot, "queue_depth": 3, "preemptible": 0,
                         "offload": True}}, preferred=None)
    assert d.action == "shed"
    # no pressure tier at all: the pre-round-13 ladder is unchanged
    d = gate.route({0: hot}, preferred=None)
    assert d.action == "shed"
    with pytest.raises(ValueError, match="pressure_queue_depth"):
        SLOConfig(shed_queue_depth=8, pressure_queue_depth=4)


# ---------------------------------------------------------------------------
# preempt-and-restore: token identity, faults, drains (tiny model — fast)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("policy", ["swap", "recompute"])
def test_preempt_restore_token_identical(model, policy):
    """A request preempted mid-decode and restored (either path) must
    stream exactly the tokens of an unpreempted control, and every
    block/host byte must be back home at the end."""
    cfg, params = model
    prompts = [np.arange(1, 10, dtype=np.int32),
               np.arange(1, 6, dtype=np.int32)]
    want = greedy_streams(cfg, params, prompts, 6)
    s = Scheduler(cfg, params, n_slots=2, block_len=8, prefill_chunk=8,
                  offload=True, swap_policy=policy, protect_ticks=0)
    a, b = (s.submit(p, 6) for p in prompts)
    got = {a: [], b: []}
    for _ in range(3):
        for rid, tok in s.step():
            got[rid].append(tok)
    d = s.preempt(a, reason="test")
    assert d is not None and d.choice == policy
    assert a not in {r.rid for r in s.resident.values()}
    for rid, toks in s.drain().items():
        got[rid].extend(toks)
    assert got[a] == want[0] and got[b] == want[1]
    m = s.metrics()
    assert m["preempts"] == 1 and m["restores"] == 1
    assert (m["decision_swap"], m["decision_recompute"]) == (
        (1, 0) if policy == "swap" else (0, 1)
    )
    assert s.engine.allocator.in_use == 0
    assert len(s.host_store) == 0 and s.host_store.bytes_used == 0
    assert not s.parked and not s._swapping


def test_preempt_validation(model):
    cfg, params = model
    s = Scheduler(cfg, params, n_slots=2, block_len=8, prefill_chunk=8,
                  offload=True)
    with pytest.raises(ValueError, match="not resident"):
        s.preempt(99)
    with pytest.raises(ValueError, match="preempt_on_oom"):
        Scheduler(cfg, params, n_slots=2, preempt_on_oom=True)
    with pytest.raises(ValueError, match="swap_policy"):
        Scheduler(cfg, params, n_slots=2, offload=True,
                  swap_policy="maybe")
    # engines without the flag predict (and refuse) swap programs
    eng = PagedEngine(cfg, params, 2, block_len=8, prefill_chunk=8)
    assert eng.swap_buckets() == []
    eng.admit(0, 9, 2)
    with pytest.raises(RuntimeError, match="swap=True"):
        eng.swap_out_begin(0)


@pytest.mark.parametrize(
    "site", ["kv.swap_out_d2h", "kv.host_write", "kv.swap_in_h2d"],
    ids=lambda s: s.split(".")[1],
)
def test_fault_at_swap_hazard_never_corrupts(model, site):
    """An injected failure at each swap hazard site: the chain either
    stays resident (swap-out faults revert the preemption) or restores
    bit-exact on retry (swap-in faults keep the host copy) — proven by
    token-identical greedy streams vs the unpreempted control."""
    cfg, params = model
    prompt = np.arange(1, 10, dtype=np.int32)
    want = greedy_streams(cfg, params, [prompt], 6)[0]
    faults.install_plan(FaultPlan([
        FaultSpec(site=site, kind="raise", at=0)
    ]))
    s = Scheduler(cfg, params, n_slots=2, block_len=8, prefill_chunk=8,
                  offload=True, swap_policy="swap", protect_ticks=0)
    a = s.submit(prompt, 6)
    got = []
    for _ in range(3):
        got += [t for rid, t in s.step() if rid == a]
    s.preempt(a, reason="test")
    got += s.drain().get(a, [])
    assert got == want, f"stream corrupted by fault at {site}"
    m = s.metrics()
    assert m["swap_aborts"] == 1
    assert faults.active_plan().fired == [(site, 0, "raise")]
    # a swap-out fault reverts (no restore); a swap-in fault retries
    # from the intact host copy (exactly one restore)
    assert m["restores"] == (1 if site == "kv.swap_in_h2d" else 0)
    assert s.engine.allocator.in_use == 0 and len(s.host_store) == 0


def test_drain_while_swapping_waits_for_inflight_swap(model):
    """THE regression for the drain-while-swapping race: begin_drain
    must close the open swap window (commit or revert) before any
    teardown path can free blocks — and the graceful drain then runs
    the parked request to completion too."""
    cfg, params = model
    prompt = np.arange(1, 10, dtype=np.int32)
    want = greedy_streams(cfg, params, [prompt], 6)[0]
    s = Scheduler(cfg, params, n_slots=2, block_len=8, prefill_chunk=8,
                  offload=True, swap_policy="swap", protect_ticks=0)
    a = s.submit(prompt, 6)
    got = []
    for _ in range(3):
        got += [t for rid, t in s.step() if rid == a]
    s.preempt(a, reason="test")
    # the d2h window is OPEN: chain mid-swap, slot quarantined
    assert s._swapping and s.engine.allocator.swapping()
    slot = s._swapping[0][2].slot
    with pytest.raises(RuntimeError, match="swapping-out"):
        s.engine.allocator.free(slot)
    s.begin_drain()  # must finalize the in-flight swap first
    assert not s._swapping and not s.engine.allocator.swapping()
    produced, requeued = s.drain_graceful()
    got += produced.get(a, [])
    assert requeued == [] and got == want
    assert s.engine.allocator.in_use == 0 and len(s.host_store) == 0
    s.engine.release_all()  # teardown after drain stays a no-op


def test_swap_registry_coverage_and_warm_inert(model):
    """Every swap program registers under the coverage guard with inert
    warm thunks: warming mutates nothing, serving after a full warmup
    compiles nothing the registry did not predict."""
    from pytorch_distributed_tpu.compilecache import (
        CoverageError,
        serving_registry,
    )

    cfg, params = model
    s = Scheduler(cfg, params, n_slots=2, block_len=8, prefill_chunk=8,
                  offload=True, swap_policy="swap", protect_ticks=0)
    reg = serving_registry(s.engine)
    assert any(n.startswith("kv_swap_out") for n in reg.names)
    assert any(n.startswith("kv_swap_in") for n in reg.names)
    # inert warm: live pool untouched (it is all zeros pre-traffic)
    for n in s.engine.swap_buckets():
        s.engine.warm_swap_out(n, execute=True)
        s.engine.warm_swap_in(n, execute=True)
    assert all(
        not np.asarray(leaf).any() for leaf in jax.tree.leaves(s.engine.cache)
    )
    # a full preempt/restore cycle stays inside the prediction
    a = s.submit(np.arange(1, 10, dtype=np.int32), 6)
    for _ in range(3):
        s.step()
    s.preempt(a, reason="test")
    s.drain()
    reg.assert_covers(s.engine.compiled_program_names())
    with pytest.raises(CoverageError):
        reg.assert_covers(["kv_swap_out[n=999]"])


def test_preempt_jsonl_schema_and_pressure_report(model, tmp_path):
    """kind="preempt"/"swap" records carry the decision and predicted-
    vs-measured walls, and telemetry_report renders the pressure section
    (--require pressure has teeth both ways)."""
    from pytorch_distributed_tpu.utils.profiling import MetricsLogger

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    report = os.path.join(repo, "scripts", "telemetry_report.py")
    cfg, params = model
    path = str(tmp_path / "pressure.jsonl")
    with MetricsLogger(path) as mlog:
        s = Scheduler(cfg, params, n_slots=2, block_len=8,
                      prefill_chunk=8, offload=True, swap_policy="swap",
                      protect_ticks=0, metrics_log=mlog)
        a = s.submit(np.arange(1, 10, dtype=np.int32), 6)
        for _ in range(3):
            s.step()
        s.preempt(a, reason="test")
        s.drain()
    records = [json.loads(line) for line in open(path)]
    pre = [r for r in records if r.get("kind") == "preempt"]
    swaps = [r for r in records if r.get("kind") == "swap"]
    assert len(pre) == 1 and pre[0]["decision"] == "swap"
    assert pre[0]["rid"] == a and "predicted_swap_s" in pre[0]
    assert {r["direction"] for r in swaps} == {"out", "in"}
    for r in swaps:
        assert r["ok"] and r["bytes"] > 0 and r["wall_s"] >= 0
    reqs = [r for r in records if r.get("kind") == "request"]
    assert reqs and reqs[0]["preempts"] == 1
    proc = subprocess.run(
        [sys.executable, report, path, "--json", "--require", "pressure"],
        capture_output=True, text=True, cwd=repo,
    )
    assert proc.returncode == 0, proc.stderr
    assert "== kv pressure ==" in proc.stdout
    flat = json.loads(proc.stdout.strip().splitlines()[-1])
    assert flat["pressure_preempts"] == 1
    assert flat["pressure_decision_swap"] == 1
    assert "pressure_swap_out_p95_ms" in flat
    # --require pressure fails on a pressure-less stream
    lonely = str(tmp_path / "lonely.jsonl")
    with open(lonely, "w") as f:
        f.write(json.dumps({"kind": "train", "step": 1}) + "\n")
    proc = subprocess.run(
        [sys.executable, report, lonely, "--require", "pressure"],
        capture_output=True, text=True, cwd=repo,
    )
    assert proc.returncode != 0


# ---------------------------------------------------------------------------
# the over-committed scenario (slow tier): sessions >> pool, zero sheds
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_overcommitted_trace_zero_sheds_token_identical(model):
    """The headline scenario scaled to CI: a seeded bursty trace whose
    session count dwarfs the pool (the 100k-sessions-on-200-chains
    regime — here 10k sessions on a pool holding ~3 chains per replica,
    with a shed bound the load provably crosses) completes with ZERO
    sheds, >=1 real preemption, every restored stream token-identical
    to an unpreempted control, and every compiled swap program covered
    by the registry guard."""
    cfg, params = model
    trace = generate_trace(
        seed=3, duration_s=40.0, base_rate=0.8, burst_rate_mult=4.0,
        burst_every_s=15.0, burst_len_s=4.0, sessions=10_000,
        prompt_median=16, prompt_sigma=0.7, prompt_min=4, prompt_max=40,
        max_new_median=6, max_new_sigma=0.5, max_new_min=2,
        max_new_max=10,
    )
    slo = SLOConfig(spill_queue_depth=2, shed_queue_depth=6)
    KW = dict(n_slots=4, n_blocks=13, block_len=8, prefill_chunk=16,
              admit_per_step=4)
    # baseline: the same trace through the shed-only ladder must shed —
    # otherwise this scenario proves nothing about the preempt rung
    base = FleetRouter(cfg, params, n_replicas=2, slo=slo, **KW)
    replay_trace(
        trace,
        lambda r: base.submit(prompt_for(r, cfg.vocab_size), r.max_new,
                              session=r.session),
        base.step, lambda: base.idle,
    )
    assert base.metrics()["shed"] > 0, "trace does not pressure the pool"
    # pressure tier on: zero sheds, preemptions instead
    r = FleetRouter(cfg, params, n_replicas=2, slo=slo, offload=True,
                    preempt_on_oom=True, protect_ticks=0, **KW)
    submitted = {}
    replay_trace(
        trace,
        lambda t: submitted.__setitem__(
            r.submit(prompt_for(t, cfg.vocab_size), t.max_new,
                     session=t.session),
            t,
        ),
        r.step, lambda: r.idle,
    )
    got = r.drain()
    m = r.metrics()
    assert m["shed"] == 0, f"pressure tier shed {m['shed']}"
    assert m["preempts"] >= 1 and m["restores"] == m["preempts"]
    assert set(got) == set(submitted)
    # token identity for EVERY stream (preempted or not) vs a control
    # scheduler with an ample pool serving the same prompts
    ctrl = Scheduler(cfg, params, n_slots=4, block_len=8,
                     prefill_chunk=16)
    ref_cache = {}
    for rid, t in submitted.items():
        key = (t.rid, t.prompt_len, t.max_new)
        if key not in ref_cache:
            cr = ctrl.submit(prompt_for(t, cfg.vocab_size), t.max_new)
            ref_cache[key] = ctrl.drain()[cr]
        assert got[rid] == ref_cache[key], f"stream {rid} diverged"
    for s in r.replicas:
        assert s.engine.allocator.in_use == 0
        assert len(s.host_store) == 0
    r.assert_registry_covers()
    # the run really exercised the swap programs
    names = [n for s in r.replicas
             for n in s.engine.compiled_program_names()]
    assert any(n.startswith("kv_swap_out") for n in names)


# ---------------------------------------------------------------------------
# kill matrix (slow, crash): SIGKILL mid-swap, relaunch clean
# ---------------------------------------------------------------------------


def _run_serve_child(save_dir, env_extra=None, timeout=300):
    env = dict(os.environ)
    env.pop(faults.ENV_PLAN, None)
    env.update(env_extra or {})
    return subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), "serve_child.py"),
         "--save-dir", str(save_dir)],
        env=env, capture_output=True, text=True, timeout=timeout,
    )


@pytest.mark.slow
@pytest.mark.crash
@pytest.mark.parametrize("site", ["kv.swap_out_d2h", "kv.host_write"],
                         ids=lambda s: s.split(".")[1])
def test_kill_matrix_sigkill_mid_swap_restarts_clean(tmp_path, site,
                                                     model):
    """Run 1 is SIGKILLed inside the swap window; nothing durable can be
    corrupt (the host store dies with the process), the flight-recorder
    mirror shows the preemption that preceded death, and run 2 serves
    the identical workload to completion with token streams equal to an
    unpreempted reference."""
    from tests.serve_child import workload

    plan = FaultPlan([FaultSpec(site=site, kind="kill", at=0)])
    r1 = _run_serve_child(tmp_path, {faults.ENV_PLAN: plan.to_json()})
    assert r1.returncode == -signal.SIGKILL, (
        f"child should die by SIGKILL at {site}; rc={r1.returncode}\n"
        f"stdout:{r1.stdout}\nstderr:{r1.stderr}"
    )
    assert not os.path.exists(os.path.join(str(tmp_path), "result.json"))
    # the durable mirror shows the preempt that opened the fatal window
    from pytorch_distributed_tpu.telemetry.flightrec import read_mirror

    events = read_mirror(os.path.join(str(tmp_path), "flightrec.jsonl"))
    assert any(e.get("kind") == "preempt" for e in events)

    r2 = _run_serve_child(tmp_path)
    assert r2.returncode == 0, (
        f"relaunch failed\nstdout:{r2.stdout}\nstderr:{r2.stderr}"
    )
    with open(os.path.join(str(tmp_path), "result.json")) as f:
        result = json.load(f)
    assert result["preempts"] >= 1 and result["swap_aborts"] == 0
    cfg, params = model
    prompts = workload(cfg)
    want = greedy_streams(cfg, params, prompts, 6)
    for i in range(len(prompts)):
        assert result["streams"][str(i)] == want[i], f"stream {i} diverged"
