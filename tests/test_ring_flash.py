"""Ring attention over the Pallas flash kernels (ops.ring_flash): values
AND gradients must match dense attention on the gathered sequence — the
custom_vjp's two-ring-pass backward is the risky part."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow
from jax.sharding import NamedSharding, PartitionSpec as P

from pytorch_distributed_tpu.ops.attention import dense_attention
from pytorch_distributed_tpu.ops.ring_flash import ring_flash_attention
from pytorch_distributed_tpu.parallel import make_mesh
from pytorch_distributed_tpu.parallel.mesh import DATA_AXIS, SEQ_AXIS, shard_map


def qkv(b=2, l=64, h=2, d=16, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(b, l, h, d)), jnp.float32)
    return mk(), mk(), mk()


def ring_fn(mesh, causal, block=16):
    fn = shard_map(
        functools.partial(ring_flash_attention, causal=causal,
                          block_q=block, block_k=block, interpret=True),
        mesh=mesh,
        in_specs=(P(DATA_AXIS, SEQ_AXIS),) * 3,
        out_specs=P(DATA_AXIS, SEQ_AXIS),
        check_vma=False,
    )
    return fn


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("sp", [2, 4])
def test_ring_flash_matches_dense(devices8, causal, sp):
    mesh = make_mesh(devices8[: 2 * sp], data_parallel=2, seq_parallel=sp)
    q, k, v = qkv()
    sh = NamedSharding(mesh, P(DATA_AXIS, SEQ_AXIS))
    qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))
    out = ring_fn(mesh, causal)(qs, ks, vs)
    ref = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5,
                               atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_grads_match_dense(devices8, causal):
    mesh = make_mesh(devices8, data_parallel=2, seq_parallel=4)
    q, k, v = qkv()
    sh = NamedSharding(mesh, P(DATA_AXIS, SEQ_AXIS))
    fn = ring_fn(mesh, causal)

    def loss_ring(q, k, v):
        return jnp.sum(fn(q, k, v) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=causal) ** 2)

    g_r = jax.grad(loss_ring, argnums=(0, 1, 2))(
        *(jax.device_put(x, sh) for x in (q, k, v))
    )
    g_d = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g_r, g_d):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5,
            err_msg=f"d{name}",
        )


def test_ring_flash_single_shard(devices8):
    """seq axis of size 1: degenerates to plain (causal) flash."""
    mesh = make_mesh(devices8[:2], data_parallel=2, seq_parallel=1)
    q, k, v = qkv(l=32)
    sh = NamedSharding(mesh, P(DATA_AXIS, SEQ_AXIS))
    out = ring_fn(mesh, True)(*(jax.device_put(x, sh) for x in (q, k, v)))
    ref = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5,
                               atol=2e-5)


def test_ring_flash_validations():
    from pytorch_distributed_tpu.ops.ring_flash import _fit_block

    # Irregular shard lengths now ADAPT the block to the largest divisor
    # (raising the tuned defaults must never break a previously-valid
    # call) instead of raising; unequal q/kv lengths still error.
    assert _fit_block(512, 768) == 384  # largest 128-multiple divisor
    assert _fit_block(16, 30) == 15  # any divisor when no 128-multiple
    assert _fit_block(1024, 1024) == 1024
    assert _fit_block(512, 509) == 509  # prime: single block
    q2, _, _ = qkv(l=32)
    _, k, v = qkv(l=30)
    with pytest.raises(ValueError, match="equal"):
        ring_flash_attention(q2, k, v, interpret=True)


def test_lm_ring_flash_matches_ring(devices8):
    """The full TransformerLM with attention='ring_flash' matches the XLA
    ring path over a dp x sp mesh (interpret-mode kernels on CPU)."""
    import pytorch_distributed_tpu.ops.ring_flash as rf
    from pytorch_distributed_tpu.models.transformer import tiny_config
    from pytorch_distributed_tpu.ops.optim import sgd_with_weight_decay
    from pytorch_distributed_tpu.train.lm import (
        create_lm_state,
        make_lm_train_step,
        shard_lm_state,
        shift_labels,
    )

    def run(attention):
        mesh = make_mesh(devices8, data_parallel=4, seq_parallel=2)
        cfg = tiny_config(attention=attention)
        tx = sgd_with_weight_decay(0.1, momentum=0.9)
        state = create_lm_state(cfg, tx, jax.random.key(0), init_len=8)
        state, specs = shard_lm_state(mesh, state, cfg)
        step = make_lm_train_step(mesh, state_specs=specs, config=cfg)
        rng = np.random.default_rng(0)
        tokens = rng.integers(1, 128, (4, 32)).astype(np.int32)
        labels, weights = shift_labels(tokens)
        sh = NamedSharding(mesh, P("data", "seq"))
        batch = {"tokens": jax.device_put(tokens, sh),
                 "labels": jax.device_put(labels, sh),
                 "weights": jax.device_put(weights, sh)}
        losses = []
        for _ in range(3):
            state, m = step(state, batch)
            losses.append(float(m["loss"]))
        return losses

    orig = rf.ring_flash_attention
    try:
        rf.ring_flash_attention = functools.partial(orig, interpret=True)
        losses_rf = run("ring_flash")
    finally:
        rf.ring_flash_attention = orig
    losses_ring = run("ring")
    np.testing.assert_allclose(losses_rf, losses_ring, rtol=2e-4)


# ---- zigzag layout ----

def zz_ring_fn(mesh, block=16):
    fn = shard_map(
        functools.partial(ring_flash_attention, causal=True,
                          block_q=block, block_k=block, interpret=True,
                          layout="zigzag"),
        mesh=mesh,
        in_specs=(P(DATA_AXIS, SEQ_AXIS),) * 3,
        out_specs=P(DATA_AXIS, SEQ_AXIS),
        check_vma=False,
    )
    return fn


@pytest.mark.parametrize("sp", [2, 4])
def test_zigzag_ring_flash_matches_dense(devices8, sp):
    """Zigzag-laid-out inputs through the zigzag ring == dense attention
    on the original order, after unshuffling."""
    from pytorch_distributed_tpu.parallel.sequence import (
        zigzag_shard,
        zigzag_unshard,
    )

    mesh = make_mesh(devices8[: 2 * sp], data_parallel=2, seq_parallel=sp)
    q, k, v = qkv()
    ref = dense_attention(q, k, v, causal=True)
    sh = NamedSharding(mesh, P(DATA_AXIS, SEQ_AXIS))
    qz, kz, vz = (
        jax.device_put(zigzag_shard(x, sp), sh) for x in (q, k, v)
    )
    out = zigzag_unshard(zz_ring_fn(mesh)(qz, kz, vz), sp)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5,
                               atol=2e-5)


def test_zigzag_ring_flash_grads_match_dense(devices8):
    from pytorch_distributed_tpu.parallel.sequence import zigzag_shard

    sp = 4
    mesh = make_mesh(devices8, data_parallel=2, seq_parallel=sp)
    q, k, v = qkv(seed=5)
    sh = NamedSharding(mesh, P(DATA_AXIS, SEQ_AXIS))
    fn = zz_ring_fn(mesh)

    def loss_zz(q, k, v):
        return jnp.sum(fn(q, k, v).astype(jnp.float32) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

    g_ref = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    g_zz = jax.grad(loss_zz, argnums=(0, 1, 2))(
        *(jax.device_put(zigzag_shard(x, sp), sh) for x in (q, k, v))
    )
    from pytorch_distributed_tpu.parallel.sequence import zigzag_unshard

    for a, b in zip(g_ref, g_zz):
        np.testing.assert_allclose(
            np.asarray(zigzag_unshard(b, sp)), np.asarray(a),
            rtol=1e-4, atol=1e-4,
        )


def test_zigzag_validations():
    q, k, v = qkv(l=32)
    with pytest.raises(ValueError, match="non-causal"):
        ring_flash_attention(q, k, v, causal=False, layout="zigzag",
                             interpret=True)
    with pytest.raises(ValueError, match="unknown layout"):
        ring_flash_attention(q, k, v, causal=True, layout="striped",
                             interpret=True)


@pytest.mark.parametrize("layout", ["contiguous", "zigzag"])
def test_ring_flash_split_backward_escape_hatch(devices8, layout):
    """bwd_impl='split' (the documented fallback) must produce the same
    gradients as the fused default — the split argument threading through
    _visit_bwd is otherwise exercised by no test."""
    mesh = make_mesh(devices8, data_parallel=2, seq_parallel=4)
    q, k, v = qkv()
    sh = NamedSharding(mesh, P(DATA_AXIS, SEQ_AXIS))
    if layout == "zigzag":
        from pytorch_distributed_tpu.parallel.sequence import zigzag_shard

        q, k, v = (
            jnp.asarray(zigzag_shard(np.asarray(x), 4, axis=1))
            for x in (q, k, v)
        )

    def fn(impl):
        f = shard_map(
            functools.partial(ring_flash_attention, causal=True,
                              block_q=16, block_k=16, interpret=True,
                              layout=layout, bwd_impl=impl),
            mesh=mesh,
            in_specs=(P(DATA_AXIS, SEQ_AXIS),) * 3,
            out_specs=P(DATA_AXIS, SEQ_AXIS),
            check_vma=False,
        )
        return lambda q_, k_, v_: jnp.sum(f(q_, k_, v_) ** 2)

    args = tuple(jax.device_put(x, sh) for x in (q, k, v))
    g_f = jax.grad(fn("fused"), argnums=(0, 1, 2))(*args)
    g_s = jax.grad(fn("split"), argnums=(0, 1, 2))(*args)
    for name, a, b in zip("qkv", g_f, g_s):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-5, atol=5e-6,
            err_msg=f"d{name}",
        )
    with pytest.raises(ValueError, match="bwd_impl"):
        ring_flash_attention(q, k, v, causal=True, bwd_impl="nope")
