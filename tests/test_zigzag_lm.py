"""Zigzag ring layout wired end-to-end into the LM path (VERDICT r3
weak #5/#7: the balanced layout existed only at the ops level — nothing
reachable used it). These pin the full-trainer-path pieces:

- ``shard_lm_batch(layout="zigzag")`` places chunk pair (r, 2s-1-r) on
  seq-shard r, tokens/labels/weights aligned;
- the LM train step under ``ring_layout="zigzag"`` (XLA ring and
  ring_flash variants) reproduces the CONTIGUOUS layout's loss and
  parameter trajectory on the same data — the wpe position vector, the
  host permutation, and the zigzag attention math all have to agree for
  this to hold;
- eval matches too (position plumbing in the eval step).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

pytestmark = pytest.mark.slow

from pytorch_distributed_tpu.models.transformer import tiny_config
from pytorch_distributed_tpu.ops.optim import sgd_with_weight_decay
from pytorch_distributed_tpu.parallel import make_mesh
from pytorch_distributed_tpu.train.lm import (
    create_lm_state,
    empty_lm_metrics,
    make_lm_eval_step,
    make_lm_train_step,
    shard_lm_state,
    shift_labels,
)
from pytorch_distributed_tpu.train.lm_trainer import shard_lm_batch


def host_batch(seed=0, b=2, l=64):
    rng = np.random.default_rng(seed)
    tokens = rng.integers(1, 128, (b, l)).astype(np.int32)
    labels, weights = shift_labels(tokens)
    return {"tokens": tokens, "labels": labels, "weights": weights}


def test_shard_lm_batch_zigzag_places_chunk_pairs(devices8):
    mesh = make_mesh(devices8, data_parallel=2, seq_parallel=4)
    b = host_batch(b=2, l=32)
    out = shard_lm_batch(mesh, b, layout="zigzag")
    s, c = 4, 32 // 8  # 2s chunks of length 4
    tok = np.asarray(jax.device_get(out["tokens"]))
    # undo the permutation shard-wise: shard r columns = chunks (r, 2s-1-r)
    for r in range(s):
        local = tok[:, r * 8:(r + 1) * 8]
        np.testing.assert_array_equal(
            local[:, :c], b["tokens"][:, r * c:(r + 1) * c]
        )
        np.testing.assert_array_equal(
            local[:, c:], b["tokens"][:, (2 * s - 1 - r) * c:(2 * s - r) * c]
        )


@pytest.mark.parametrize("attention", ["ring", "ring_flash"])
def test_zigzag_lm_step_matches_contiguous(devices8, attention):
    mesh = make_mesh(devices8, data_parallel=2, seq_parallel=4)
    tx = sgd_with_weight_decay(0.1, momentum=0.9)

    def run(layout, steps=3):
        cfg = tiny_config(attention=attention, ring_layout=layout,
                          max_seq_len=64)
        state = create_lm_state(cfg, tx, jax.random.key(0), init_len=8)
        state, specs = shard_lm_state(mesh, state, cfg)
        step = make_lm_train_step(mesh, state_specs=specs, config=cfg)
        losses = []
        for i in range(steps):
            batch = shard_lm_batch(mesh, host_batch(seed=i), layout=layout)
            state, m = step(state, batch)
            losses.append(float(m["loss"]))
        return state, losses

    state_z, losses_z = run("zigzag")
    state_c, losses_c = run("contiguous")
    np.testing.assert_allclose(losses_z, losses_c, rtol=2e-4)
    for a, b in zip(jax.tree.leaves(jax.device_get(state_z.params)),
                    jax.tree.leaves(jax.device_get(state_c.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-5)


def test_zigzag_eval_matches_contiguous(devices8):
    mesh = make_mesh(devices8, data_parallel=2, seq_parallel=4)
    tx = sgd_with_weight_decay(0.1)

    def evaluate(layout):
        cfg = tiny_config(attention="ring", ring_layout=layout,
                          max_seq_len=64)
        state = create_lm_state(cfg, tx, jax.random.key(0), init_len=8)
        state, specs = shard_lm_state(mesh, state, cfg)
        ev = make_lm_eval_step(mesh, state_specs=specs, config=cfg)
        acc = jax.device_put(
            empty_lm_metrics(), NamedSharding(mesh, P())
        )
        acc = ev(state, shard_lm_batch(mesh, host_batch(seed=9),
                                       layout=layout), acc)
        acc = jax.device_get(acc)
        return float(acc["loss_sum"]) / float(acc["tokens"])

    np.testing.assert_allclose(evaluate("zigzag"), evaluate("contiguous"),
                               rtol=1e-5)


def test_zigzag_config_validation():
    with pytest.raises(ValueError, match="zigzag.*only applies to ring"):
        tiny_config(attention="dense", ring_layout="zigzag")
    with pytest.raises(ValueError, match="ring_layout"):
        tiny_config(attention="ring", ring_layout="diagonal")
